"""Head-to-head: the reference's OWN training loop vs trlx_tpu, CPU, identical data.

Ends three rounds of `vs_baseline: null`: runs `/root/reference`'s ILQL
randomwalks exactly as its example ships it (reference: examples/randomwalks.py:87-109,
trlx/trlx.py:61-93) through the real Accelerate CPU path, then trlx_tpu's ILQL
on the IDENTICAL dataset (same walks, same rewards, same graph, seed 1000) with
the REFERENCE's own optimality metric applied to both sides' eval samples.

Scope: CPU smoke (this container exposes one CPU core and one tunneled TPU chip;
the v4-32 ≥2x gate needs hardware that is not here). Both sides run on the same
single core: torch eager for the reference, XLA-CPU for trlx_tpu — the same
"whatever your stack compiles to on this machine" rules the reference's own
README applies to its GPU numbers. JAX compile time is INCLUDED in trlx_tpu's
wallclock (reported separately too).

The reference is never edited: import-time stubs for deps absent from this image
(wandb, deepspeed, torchtyping) and no-op'd Accelerator tracker methods are the
same shim technique as tests/test_reference_parity.py. Everything the reference
executes is its shipped code.

Usage:
  python bench_reference.py            # run both sides, write HEADTOHEAD.json
  python bench_reference.py --side ref # (internal) reference side only
  python bench_reference.py --side ours# (internal) trlx_tpu side only

bench.py picks up HEADTOHEAD.json to fill `vs_baseline` in the bench JSON.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))
REFERENCE_ROOT = "/root/reference"
RESULT_PATH = os.path.join(REPO, "HEADTOHEAD.json")

THRESHOLDS = [0.5, 0.7, 0.8, 0.9]


# ---------------------------------------------------------------------------
# Reference side


def _install_reference_stubs():
    """Import-time stubs for modules the reference imports but this image
    lacks. Mirrors tests/test_reference_parity.py:58-99; here they stay
    installed for the process lifetime (this subprocess runs nothing else)."""
    import importlib.machinery
    import types

    for name in ("deepspeed", "wandb", "torchtyping"):
        if name in sys.modules:
            continue
        m = types.ModuleType(name)
        m.__spec__ = importlib.machinery.ModuleSpec(name, None)
        sys.modules[name] = m
    ds = sys.modules["deepspeed"]
    ds.comm = types.SimpleNamespace(get_rank=lambda: 0)
    ds.zero = types.SimpleNamespace()

    wb = sys.modules["wandb"]

    class _Blob:
        def __init__(self, *a, **k):
            pass

    wb.Histogram = _Blob
    wb.Table = _Blob

    class _TensorType:
        def __class_getitem__(cls, item):
            return cls

    sys.modules["torchtyping"].TensorType = _TensorType


def run_reference_side(dataset_path: str, workdir: str) -> dict:
    """Run the reference's ILQL randomwalks example end-to-end via its real
    trlx.train → AccelerateILQLModel → Accelerate CPU path, and save the
    generated dataset for the trlx_tpu side."""
    _install_reference_stubs()
    sys.path.insert(0, REFERENCE_ROOT)

    import importlib.util

    import numpy as np
    import torch

    # The reference's own dataset generator (networkx graph, torch walks).
    spec = importlib.util.spec_from_file_location(
        "ref_randomwalks", os.path.join(REFERENCE_ROOT, "examples", "randomwalks.py")
    )
    ref_rw = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ref_rw)

    walks, logit_mask, metric_fn = ref_rw.generate_random_walks(seed=1000)
    eval_prompts = torch.arange(1, logit_mask.shape[0]).view(-1, 1)
    lengths = metric_fn(walks)["lengths"]

    # Extract the metric closure's constants so the trlx_tpu side can apply
    # the IDENTICAL optimality formula (best_lengths is not returned).
    free = dict(zip(metric_fn.__code__.co_freevars, (c.cell_contents for c in metric_fn.__closure__)))
    best_lengths = free["best_lengths"].numpy()
    worstlen = int(free["worstlen"])

    np.savez(
        dataset_path,
        walks=np.array([w.numpy() for w in walks], dtype=object),
        rewards=lengths.numpy(),
        logit_mask=logit_mask.numpy(),
        best_lengths=best_lengths,
        worstlen=worstlen,
    )

    # --- shim layer (harness-side; the reference itself is untouched) -----
    from accelerate import Accelerator

    logged = []
    t0 = time.time()
    Accelerator.init_trackers = lambda self, *a, **k: None
    Accelerator.log = lambda self, stats, **k: logged.append((time.time(), dict(stats)))

    # Full-step steady-state: timestamp every optimizer step; the median
    # inter-step delta is robust to the eval-step outliers (50 of 800) and
    # includes loss+backward+opt+scheduler+tqdm — the same definition as the
    # trlx_tpu side's per-step step_time.
    step_stamps = []
    orig_opt_step = torch.optim.AdamW.step

    def timed_opt_step(self, *a, **k):
        r = orig_opt_step(self, *a, **k)
        step_stamps.append(time.time())
        return r

    torch.optim.AdamW.step = timed_opt_step

    from trlx.model.accelerate_base_model import AccelerateRLModel

    eval_seconds = [0.0]
    orig_evaluate = AccelerateRLModel.evaluate

    def timed_evaluate(self):
        t = time.time()
        out = orig_evaluate(self)
        eval_seconds[0] += time.time() - t
        return out

    AccelerateRLModel.evaluate = timed_evaluate

    # --- the reference example's own __main__, verbatim semantics ---------
    import trlx
    from trlx.data.configs import TRLConfig
    from transformers import GPT2Config

    config = TRLConfig.load_yaml(os.path.join(REFERENCE_ROOT, "configs", "ilql_config.yml"))
    config.train.gen_size = 10
    config.train.epochs = 100
    config.train.learning_rate_init = 1e-3
    config.method.alpha = 0.1
    config.model.tokenizer_path = ""
    config.model.model_path = GPT2Config(n_layer=2, n_embd=144, vocab_size=logit_mask.shape[0])
    config.train.checkpoint_dir = os.path.join(workdir, "ref_ckpts")

    os.chdir(workdir)
    t0 = time.time()
    model = trlx.train(
        dataset=(walks, lengths),
        eval_prompts=eval_prompts,
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    wall = time.time() - t0

    steps = model.iter_count
    batch = config.train.batch_size
    trajectory = [
        {"t": round(t - t0, 2), "optimality": float(torch.as_tensor(s["metrics/optimality"]).mean())}
        for (t, s) in logged
        if "metrics/optimality" in s
    ]
    final_opt = trajectory[-1]["optimality"] if trajectory else float("nan")
    train_s = wall - eval_seconds[0]
    deltas = np.diff(step_stamps)
    steady = batch / float(np.median(deltas)) if len(deltas) else None
    return {
        "impl": "reference (trlx v0.2.0, torch eager, Accelerate CPU)",
        "steps": int(steps),
        "batch_size": int(batch),
        "wallclock_s": round(wall, 2),
        "eval_s": round(eval_seconds[0], 2),
        "train_s": round(train_s, 2),
        "samples_per_s": round(steps * batch / train_s, 2),
        "steady_state_samples_per_s": round(steady, 1) if steady else None,
        "final_optimality": round(final_opt, 4),
        "trajectory": trajectory,
    }


# ---------------------------------------------------------------------------
# trlx_tpu side


def run_ours_side(dataset_path: str, workdir: str) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")  # sitecustomize sets axon,cpu; override
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir:
        # Persistent compile cache: the "warm" pass quantifies how much of the
        # cold wallclock is one-time XLA compilation (any long-lived deployment
        # runs warm; the cold number stays the headline).
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

    import numpy as np

    sys.path.insert(0, REPO)
    from examples.randomwalks import base_config
    import trlx_tpu

    data = np.load(dataset_path, allow_pickle=True)
    walks = [w.astype(np.int32) for w in data["walks"]]
    rewards = data["rewards"].astype(np.float32)
    logit_mask = data["logit_mask"].astype(bool)
    best_lengths = data["best_lengths"].astype(np.float32)
    worstlen = int(data["worstlen"])
    n_nodes = logit_mask.shape[0]

    def metric_fn(samples):
        """The REFERENCE's optimality formula (reference:
        examples/randomwalks.py:62-81) on this side's eval samples, with its
        exact best_lengths; modulo indexing covers fixed-shape eval batches
        that wrap past the 20 unique prompts."""
        lengths = []
        for s in samples:
            s = np.asarray(s).reshape(-1)
            hits = np.nonzero(s == 0)[0]
            if s[-1] == 0 and len(hits):
                lengths.append(-(int(hits[0]) + 1))
            else:
                lengths.append(-100)
        lengths = np.asarray(lengths, np.float32)
        bound = np.where(lengths == -100, worstlen, np.abs(lengths))
        denom = worstlen - best_lengths[np.arange(len(lengths)) % len(best_lengths)]
        opt = (worstlen - bound) / np.maximum(denom, 1e-9)
        return {"lengths": lengths, "optimality": opt}

    config = base_config("ilql", n_nodes, worstlen)
    # Matched protocol: the reference example's effective hyperparameters
    # (reference: configs/ilql_config.yml + examples/randomwalks.py:92-96) so
    # both sides see the same batch size, step count, LR schedule, and ILQL
    # method constants — the comparison is implementation vs implementation.
    config.train.batch_size = 128
    # The reference's DataLoader keeps the last partial batch (8 steps/epoch
    # from 1000 walks); this side's fixed-shape loader drops it (7). 115
    # epochs × 7 = 805, capped at total_steps — both sides run exactly 800
    # optimizer steps at batch 128.
    config.train.epochs = 115
    config.train.total_steps = 800
    config.train.eval_interval = 16
    config.train.learning_rate_init = 1e-3
    config.train.learning_rate_target = 1e-4
    config.method.alpha = 0.1
    config.method.steps_for_target_q_sync = 1
    config.method.betas = [16]
    config.train.checkpoint_dir = os.path.join(workdir, "ours_ckpts")
    eval_prompts = [[i] for i in range(1, n_nodes)]

    t0 = time.time()
    model = trlx_tpu.train(
        dataset=(walks, rewards),
        eval_prompts=eval_prompts,
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    wall = time.time() - t0

    # Trajectory + eval cost + per-step times from the tracker's JSONL.
    trajectory, eval_s, step_times = [], 0.0, []
    with open(os.path.join(config.train.checkpoint_dir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if "metrics/optimality" in rec:
                trajectory.append({"t": round(rec["t"] - t0, 2), "optimality": rec["metrics/optimality"]})
            eval_s += rec.get("generate_time", 0.0) + rec.get("metric_time", 0.0)
            if "step_time" in rec:
                step_times.append(rec["step_time"])
    final_opt = trajectory[-1]["optimality"] if trajectory else float("nan")
    steps = model.iter_count
    batch = config.train.batch_size
    train_s = wall - eval_s
    # steady-state excludes one-time XLA compilation (in-train_s otherwise)
    steady = batch / float(np.median(step_times)) if step_times else None
    return {
        "impl": "trlx_tpu (JAX/XLA CPU, jit train step)",
        "steps": int(steps),
        "batch_size": int(batch),
        "wallclock_s": round(wall, 2),
        "eval_s": round(eval_s, 2),
        "train_s": round(train_s, 2),
        "samples_per_s": round(steps * batch / train_s, 2),
        "steady_state_samples_per_s": round(steady, 1) if steady else None,
        "final_optimality": round(float(final_opt), 4),
        "trajectory": trajectory,
    }


# ---------------------------------------------------------------------------
# Orchestrator


def time_to(trajectory, thr):
    for p in trajectory:
        if p["optimality"] >= thr:
            return p["t"]
    return None


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--side", choices=["ref", "ours"])
    parser.add_argument("--dataset", default=None)
    parser.add_argument("--workdir", default=None)
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    if args.side:
        fn = run_reference_side if args.side == "ref" else run_ours_side
        result = fn(args.dataset, args.workdir)
        with open(args.out, "w") as f:
            json.dump(result, f)
        return

    workdir = tempfile.mkdtemp(prefix="headtohead_")
    dataset = os.path.join(workdir, "dataset.npz")
    sides = {}
    for side, label in (("ref", "ref"), ("ours", "ours"), ("ours", "ours_warm")):
        out = os.path.join(workdir, f"{label}.json")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # each side pins its own platform
        if side == "ours":
            env["JAX_PLATFORMS"] = "cpu"
            env["TRLX_TPU_NO_PROGRESS"] = "1"
            env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(workdir, "xla_cache")
        os.makedirs(os.path.join(workdir, label), exist_ok=True)
        print(f"[bench_reference] running {label} side ...", flush=True)
        t = time.time()
        subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--side", side,
             "--dataset", dataset, "--workdir", os.path.join(workdir, label), "--out", out],
            env=env, check=True, cwd=REPO,
        )
        with open(out) as f:
            sides[label] = json.load(f)
        print(f"[bench_reference] {label} done in {time.time()-t:.1f}s: "
              f"{sides[label]['samples_per_s']} samples/s, "
              f"final optimality {sides[label]['final_optimality']}", flush=True)

    ref, ours, warm = sides["ref"], sides["ours"], sides["ours_warm"]
    t2o = {}
    for thr in THRESHOLDS:
        tr, to = time_to(ref["trajectory"], thr), time_to(ours["trajectory"], thr)
        tw = time_to(warm["trajectory"], thr)
        t2o[str(thr)] = {
            "ref_s": tr,
            "ours_s": to,
            "ours_warm_s": tw,
            "speedup": round(tr / to, 2) if (tr and to) else None,
        }
    result = {
        "task": "randomwalks ILQL (reference: examples/randomwalks.py, seed 1000)",
        "scope": ("cpu-smoke: both sides on this container's single CPU core, identical "
                  "dataset, matched protocol (batch/steps/LR/method constants), and the "
                  "reference's own optimality metric; NOT the v4-32 gate"),
        "reference": ref,
        "ours": ours,
        "ours_warm_cache": warm,
        "vs_baseline_samples_per_s": round(ours["samples_per_s"] / ref["samples_per_s"], 3),
        "vs_baseline_warm_cache": round(warm["samples_per_s"] / ref["samples_per_s"], 3),
        "vs_baseline_steady_state": (
            round(ours["steady_state_samples_per_s"] / ref["steady_state_samples_per_s"], 3)
            if ours.get("steady_state_samples_per_s") and ref.get("steady_state_samples_per_s")
            else None
        ),
        "time_to_optimality": t2o,
        "recorded_at": time.strftime("%Y-%m-%d %H:%M:%S"),
    }
    with open(RESULT_PATH, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({
        "metric": "headtohead_cpu_ilql_randomwalks_speedup",
        "value": result["vs_baseline_samples_per_s"],
        "unit": "x reference samples/s (CPU)",
        "ref_final_optimality": ref["final_optimality"],
        "ours_final_optimality": ours["final_optimality"],
    }))


if __name__ == "__main__":
    main()
