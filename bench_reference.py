"""Head-to-head: the reference's OWN training loop vs trlx_tpu, CPU, identical data.

Ends three rounds of `vs_baseline: null`. Two acceptance tasks, BOTH methods:

- ILQL (`--method ilql`): the reference's randomwalks example exactly as it
  ships (reference: examples/randomwalks.py:87-109, trlx/trlx.py:61-93)
  through the real Accelerate CPU path, vs trlx_tpu's ILQL on the IDENTICAL
  dataset (same walks/rewards/graph, seed 1000), judged by the reference's
  own optimality metric.
- PPO (`--method ppo`): the reference's flagship method (AcceleratePPOModel +
  hydra frozen branch, reference: trlx/model/accelerate_ppo_model.py) on a
  synthetic char task — reward = fraction of 'a' characters in the response —
  with BOTH sides starting from the IDENTICAL saved init checkpoint and a
  local char-level tokenizer (no network), matched protocol.

Scope: CPU smoke (this container exposes one CPU core and one tunneled TPU
chip; the v4-32 ≥2x gate needs hardware that is not here). Both sides run on
the same single core: torch eager for the reference, XLA-CPU for trlx_tpu.
JAX compile time is INCLUDED in trlx_tpu's cold wallclock (warm-cache pass
reported separately).

The reference is never edited: import-time stubs for deps absent from this
image (wandb, deepspeed, torchtyping), no-op'd Accelerator tracker methods,
and a `use_cache=False` patch on ModelBranch.forward (transformers>=4.38
removed tuple `presents` from GPT2Block outputs; cache collection has no
effect on logits) — the same shim technique as tests/test_reference_parity.py.
Everything the reference executes is its shipped code.

Usage:
  python bench_reference.py                 # both methods -> HEADTOHEAD.json
  python bench_reference.py --method ilql   # one method only
  python bench_reference.py --side ref ...  # (internal) one side subprocess

bench.py picks up HEADTOHEAD.json to fill `vs_baseline` in the bench JSON.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))
REFERENCE_ROOT = "/root/reference"
RESULT_PATH = os.path.join(REPO, "HEADTOHEAD.json")

THRESHOLDS = {"ilql": [0.5, 0.7, 0.8, 0.9], "ppo": [0.05, 0.1, 0.2, 0.3]}
TRAJECTORY_KEY = {"ilql": "optimality", "ppo": "reward"}

# PPO char task: both sides start from the IDENTICAL saved init checkpoint.
# d144/L4 keeps per-step work comparable to the ILQL task's (d144 reference
# example model) — large enough that neither stack is dominated by per-call
# dispatch overhead on this single core.
PPO_CHARS = "abcdefghijklmnopqrstuvwxyz0123456789 .,!?"
PPO_PROTOCOL = dict(
    n_layer=4, d_model=144, n_head=4, vocab=42, seq_length=32,
    batch_size=64, total_steps=300, num_rollouts=128, chunk_size=64,
    ppo_epochs=4, lr_init=1e-3, lr_target=1e-4, init_kl_coef=0.05,
    eval_interval=25, num_layers_unfrozen=2, response_tokens=24,
)


def _ppo_reward_fn(texts):
    r = PPO_PROTOCOL["response_tokens"]
    return [sum(c == "a" for c in t) / float(r) for t in texts]


def _ppo_prompts():
    import numpy as np

    rng = np.random.default_rng(0)
    return ["".join(rng.choice(list("bcdefgh"), size=6)) for _ in range(64)]


def _parse_ours_metrics(ckpt_dir, key, t0):
    """Shared trlx_tpu-side accounting from the tracker's metrics.jsonl:
    (trajectory of `key`, eval seconds, per-step times, phase sums). Eval cost
    counts generate + reward + metric time — the same components the reference
    side's timed evaluate() wrapper excludes from train_s. Phases mirror the
    reference-side wrappers: rollout total / generate-blocked / host reward /
    device scoring / store push, plus optimizer-step and batch-transfer sums."""
    trajectory, eval_components, eval_wall, step_times = [], 0.0, 0.0, []
    makeexp_starts, eval_calls = [], []
    phases = {"rollout": 0.0, "generate": 0.0, "reward": 0.0, "score": 0.0,
              "push": 0.0, "train_steps": 0.0, "data": 0.0, "save": 0.0}
    with open(os.path.join(ckpt_dir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if key in rec:
                trajectory.append({"t": round(rec["t"] - t0, 2), "value": round(rec[key], 4)})
            eval_components += (
                rec.get("generate_time", 0.0)
                + rec.get("reward_time", 0.0)
                + rec.get("metric_time", 0.0)
            )
            if "eval_wall_time" in rec:
                eval_wall += rec["eval_wall_time"]
                # "t" is the log stamp right after eval finished
                eval_calls.append((rec["t"] - rec["eval_wall_time"], rec["eval_wall_time"]))
            if "step_time" in rec:
                step_times.append(rec["step_time"])
                phases["train_steps"] += rec["step_time"]
                phases["data"] += rec.get("data_time", 0.0)
            if "exp_time" in rec:
                makeexp_starts.append(rec["t"] - rec["exp_time"])
            phases["rollout"] += rec.get("exp_time", 0.0)
            phases["generate"] += rec.get("exp_gen_s", 0.0)
            phases["reward"] += rec.get("exp_reward_s", 0.0)
            phases["score"] += rec.get("exp_score_s", 0.0)
            phases["push"] += rec.get("exp_push_s", 0.0)
            phases["save"] += rec.get("save_time", 0.0)
    # eval_wall_time (whole-call wall, matching the reference side's timed
    # evaluate() wrapper) supersedes the legacy component sum when present.
    eval_s = eval_wall if eval_wall > 0 else eval_components
    phases = {k: round(v, 2) for k, v in phases.items()}
    return trajectory, eval_s, step_times, phases, makeexp_starts, eval_calls


def build_ppo_assets(assets_dir):
    """Identical starting point for both sides: a tiny GPT-2 checkpoint
    (fixed torch seed) + a char-level byte-BPE tokenizer, saved as ordinary
    HF files. The reference loads them with from_pretrained; trlx_tpu streams
    the same safetensors through models/hf_import — so the two frameworks
    train the SAME initial weights."""
    import json as _json

    import torch
    import transformers
    from transformers.models.gpt2.tokenization_gpt2 import bytes_to_unicode

    p = PPO_PROTOCOL
    if os.path.exists(os.path.join(assets_dir, "model.safetensors")):
        return assets_dir
    os.makedirs(assets_dir, exist_ok=True)
    cfg = transformers.GPT2Config(
        n_layer=p["n_layer"], n_embd=p["d_model"], n_head=p["n_head"],
        vocab_size=p["vocab"], n_positions=128,
        bos_token_id=p["vocab"] - 1, eos_token_id=p["vocab"] - 1,
    )
    torch.manual_seed(7)
    transformers.GPT2LMHeadModel(cfg).save_pretrained(assets_dir, safe_serialization=True)
    b2u = bytes_to_unicode()
    vocab = {}
    for ch in PPO_CHARS:
        rep = "".join(b2u[b] for b in ch.encode("utf-8"))
        vocab.setdefault(rep, len(vocab))
    vocab["<|endoftext|>"] = len(vocab)
    assert len(vocab) == p["vocab"], len(vocab)
    with open(os.path.join(assets_dir, "vocab.json"), "w") as f:
        _json.dump(vocab, f)
    with open(os.path.join(assets_dir, "merges.txt"), "w") as f:
        f.write("#version: 0.2\n")
    return assets_dir


# ---------------------------------------------------------------------------
# Reference side


def _install_reference_stubs():
    """Import-time stubs for modules the reference imports but this image
    lacks. Mirrors tests/test_reference_parity.py:58-99; here they stay
    installed for the process lifetime (this subprocess runs nothing else)."""
    import importlib.machinery
    import types

    for name in ("deepspeed", "wandb", "torchtyping"):
        if name in sys.modules:
            continue
        m = types.ModuleType(name)
        m.__spec__ = importlib.machinery.ModuleSpec(name, None)
        sys.modules[name] = m
    ds = sys.modules["deepspeed"]
    ds.comm = types.SimpleNamespace(get_rank=lambda: 0)
    ds.zero = types.SimpleNamespace()

    wb = sys.modules["wandb"]

    class _Blob:
        def __init__(self, *a, **k):
            pass

    wb.Histogram = _Blob
    wb.Table = _Blob

    class _TensorType:
        def __class_getitem__(cls, item):
            return cls

    sys.modules["torchtyping"].TensorType = _TensorType


def _instrument_reference():
    """Harness-side shims (the reference itself is untouched): no-op'd
    Accelerator trackers with a log recorder, AdamW step timestamps
    (full-step steady-state = median inter-step delta, robust to eval-step
    outliers and the same definition as trlx_tpu's per-step step_time), and
    a timed evaluate() wrapper so eval cost is excluded from train_s.
    Returns (logged, eval_seconds, step_stamps). Call after
    _install_reference_stubs()."""
    import torch
    from accelerate import Accelerator

    from trlx.model.accelerate_base_model import AccelerateRLModel

    logged = []
    Accelerator.init_trackers = lambda self, *a, **k: None
    Accelerator.log = lambda self, stats, **k: logged.append((time.time(), dict(stats)))

    step_stamps = []
    orig_opt_step = torch.optim.AdamW.step

    def timed_opt_step(self, *a, **k):
        r = orig_opt_step(self, *a, **k)
        step_stamps.append(time.time())
        return r

    torch.optim.AdamW.step = timed_opt_step

    eval_seconds = [0.0]
    eval_calls = []  # (start, duration) — lets cycle timing subtract evals
    orig_evaluate = AccelerateRLModel.evaluate

    def timed_evaluate(self):
        t = time.time()
        out = orig_evaluate(self)
        eval_calls.append((t, time.time() - t))
        eval_seconds[0] += time.time() - t
        return out

    AccelerateRLModel.evaluate = timed_evaluate
    return logged, eval_seconds, step_stamps, eval_calls


def _cycle_sps(makeexp_starts, eval_calls, samples_per_cycle):
    """Steady-state throughput of the FULL recurring PPO cycle
    (rollout + its train steps + logging), from consecutive make_experience
    start stamps with any eval wall falling inside a cycle subtracted.
    The per-step steady state ignores the rollout phase entirely; this metric
    measures everything that recurs — one-time costs (imports, init, compile)
    fall out because they precede the first stamp or inflate only one cycle
    (the median discards it)."""
    import numpy as np

    if len(makeexp_starts) < 3:
        return None
    starts = list(makeexp_starts)
    cycles = []
    for a, b in zip(starts[:-1], starts[1:]):
        dur = b - a
        dur -= sum(d for (t, d) in eval_calls if a <= t < b)
        cycles.append(dur)
    return round(samples_per_cycle / float(np.median(cycles)), 1)


def _side_result(impl, steps, batch, wall, eval_s, trajectory, final_key, step_seconds,
                 phases=None, cycle_sps=None):
    """Shared result assembly — both sides, both methods, measured under the
    same rules (train_s = wall − eval cost; steady-state = batch / median
    full-step seconds). `phases` carries the matched per-phase attribution:
    rollout total, generate, host reward, device/score forwards — with
    `train_other` derived as train_s − rollout (optimizer steps + data +
    logging) so both sides decompose identically."""
    import numpy as np

    train_s = wall - eval_s
    steady = batch / float(np.median(step_seconds)) if len(step_seconds) else None
    out = {
        "impl": impl,
        "steps": int(steps),
        "batch_size": int(batch),
        "wallclock_s": round(wall, 2),
        "eval_s": round(eval_s, 2),
        "train_s": round(train_s, 2),
        "samples_per_s": round(steps * batch / train_s, 2),
        "steady_state_samples_per_s": round(steady, 1) if steady else None,
        "steady_state_cycle_samples_per_s": cycle_sps,
        final_key: (trajectory[-1]["value"] if trajectory else None),
        "trajectory": trajectory,
    }
    if phases:
        phases = dict(phases)
        if "rollout" in phases:
            phases["score"] = round(
                phases.get("score", max(phases["rollout"] - phases.get("generate", 0.0)
                                        - phases.get("reward", 0.0), 0.0)), 2)
            phases["train_other"] = round(train_s - phases["rollout"], 2)
        out["phase_seconds"] = phases
    return out


def run_reference_side(dataset_path: str, workdir: str) -> dict:
    """Run the reference's ILQL randomwalks example end-to-end via its real
    trlx.train → AccelerateILQLModel → Accelerate CPU path, and save the
    generated dataset for the trlx_tpu side."""
    _install_reference_stubs()
    sys.path.insert(0, REFERENCE_ROOT)

    import importlib.util

    import numpy as np
    import torch

    # The reference's own dataset generator (networkx graph, torch walks).
    spec = importlib.util.spec_from_file_location(
        "ref_randomwalks", os.path.join(REFERENCE_ROOT, "examples", "randomwalks.py")
    )
    ref_rw = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ref_rw)

    walks, logit_mask, metric_fn = ref_rw.generate_random_walks(seed=1000)
    eval_prompts = torch.arange(1, logit_mask.shape[0]).view(-1, 1)
    lengths = metric_fn(walks)["lengths"]

    # Extract the metric closure's constants so the trlx_tpu side can apply
    # the IDENTICAL optimality formula (best_lengths is not returned).
    free = dict(zip(metric_fn.__code__.co_freevars, (c.cell_contents for c in metric_fn.__closure__)))
    best_lengths = free["best_lengths"].numpy()
    worstlen = int(free["worstlen"])

    np.savez(
        dataset_path,
        walks=np.array([w.numpy() for w in walks], dtype=object),
        rewards=lengths.numpy(),
        logit_mask=logit_mask.numpy(),
        best_lengths=best_lengths,
        worstlen=worstlen,
    )

    logged, eval_seconds, step_stamps, _eval_calls = _instrument_reference()

    # --- the reference example's own __main__, verbatim semantics ---------
    import trlx
    from trlx.data.configs import TRLConfig
    from transformers import GPT2Config

    config = TRLConfig.load_yaml(os.path.join(REFERENCE_ROOT, "configs", "ilql_config.yml"))
    config.train.gen_size = 10
    config.train.epochs = 100
    config.train.learning_rate_init = 1e-3
    config.method.alpha = 0.1
    config.model.tokenizer_path = ""
    config.model.model_path = GPT2Config(n_layer=2, n_embd=144, vocab_size=logit_mask.shape[0])
    config.train.checkpoint_dir = os.path.join(workdir, "ref_ckpts")

    os.chdir(workdir)
    t0 = time.time()
    model = trlx.train(
        dataset=(walks, lengths),
        eval_prompts=eval_prompts,
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    wall = time.time() - t0

    trajectory = [
        {"t": round(t - t0, 2), "value": round(float(torch.as_tensor(s["metrics/optimality"]).mean()), 4)}
        for (t, s) in logged
        if "metrics/optimality" in s
    ]
    return _side_result(
        "reference (trlx v0.2.0, torch eager, Accelerate CPU)",
        model.iter_count, config.train.batch_size, wall, eval_seconds[0],
        trajectory, "final_optimality", np.diff(step_stamps),
    )


# ---------------------------------------------------------------------------
# trlx_tpu side


def run_ours_side(dataset_path: str, workdir: str) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")  # sitecustomize sets axon,cpu; override
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir:
        # Persistent compile cache: the "warm" pass quantifies how much of the
        # cold wallclock is one-time XLA compilation (any long-lived deployment
        # runs warm; the cold number stays the headline).
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

    import numpy as np

    sys.path.insert(0, REPO)
    from examples.randomwalks import base_config
    import trlx_tpu

    data = np.load(dataset_path, allow_pickle=True)
    walks = [w.astype(np.int32) for w in data["walks"]]
    rewards = data["rewards"].astype(np.float32)
    logit_mask = data["logit_mask"].astype(bool)
    best_lengths = data["best_lengths"].astype(np.float32)
    worstlen = int(data["worstlen"])
    n_nodes = logit_mask.shape[0]

    def metric_fn(samples):
        """The REFERENCE's optimality formula (reference:
        examples/randomwalks.py:62-81) on this side's eval samples, with its
        exact best_lengths; modulo indexing covers fixed-shape eval batches
        that wrap past the 20 unique prompts."""
        lengths = []
        for s in samples:
            s = np.asarray(s).reshape(-1)
            hits = np.nonzero(s == 0)[0]
            if s[-1] == 0 and len(hits):
                lengths.append(-(int(hits[0]) + 1))
            else:
                lengths.append(-100)
        lengths = np.asarray(lengths, np.float32)
        bound = np.where(lengths == -100, worstlen, np.abs(lengths))
        denom = worstlen - best_lengths[np.arange(len(lengths)) % len(best_lengths)]
        opt = (worstlen - bound) / np.maximum(denom, 1e-9)
        return {"lengths": lengths, "optimality": opt}

    config = base_config("ilql", n_nodes, worstlen)
    # Matched protocol: the reference example's effective hyperparameters
    # (reference: configs/ilql_config.yml + examples/randomwalks.py:92-96) so
    # both sides see the same batch size, step count, LR schedule, and ILQL
    # method constants — the comparison is implementation vs implementation.
    config.train.batch_size = 128
    # The reference's DataLoader keeps the last partial batch (8 steps/epoch
    # from 1000 walks); this side's fixed-shape loader drops it (7). 115
    # epochs × 7 = 805, capped at total_steps — both sides run exactly 800
    # optimizer steps at batch 128.
    config.train.epochs = 115
    config.train.total_steps = 800
    config.train.eval_interval = 16
    config.train.learning_rate_init = 1e-3
    config.train.learning_rate_target = 1e-4
    config.method.alpha = 0.1
    config.method.steps_for_target_q_sync = 1
    config.method.betas = [16]
    config.train.checkpoint_dir = os.path.join(workdir, "ours_ckpts")
    eval_prompts = [[i] for i in range(1, n_nodes)]

    t0 = time.time()
    model = trlx_tpu.train(
        dataset=(walks, rewards),
        eval_prompts=eval_prompts,
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    wall = time.time() - t0

    trajectory, eval_s, step_times, phases, _, _ = _parse_ours_metrics(
        config.train.checkpoint_dir, "metrics/optimality", t0
    )
    return _side_result(
        "trlx_tpu (JAX/XLA CPU, jit train step)",
        model.iter_count, config.train.batch_size, wall, eval_s,
        trajectory, "final_optimality", step_times, phases,
    )


# ---------------------------------------------------------------------------
# PPO sides


def run_reference_side_ppo(assets_dir: str, workdir: str) -> dict:
    """The reference's flagship PPO (hydra frozen branch, adaptive KL,
    alternating rollout/optimize) through its real trlx.train, on the char
    task, from the shared init checkpoint."""
    _install_reference_stubs()
    sys.path.insert(0, REFERENCE_ROOT)

    import torch

    build_ppo_assets(assets_dir)
    logged, eval_seconds, step_stamps, eval_calls = _instrument_reference()

    # Matched phase attribution (harness-side wrappers; the reference code is
    # untouched): rollout = make_experience total, generate = model.generate
    # inside make_experience only (evaluate() also calls generate — that time
    # belongs to eval_s), reward = orchestrator.score.
    from trlx.model.accelerate_base_model import AccelerateRLModel
    from trlx.orchestrator.ppo_orchestrator import PPOOrchestrator as RefPPOOrch

    ph = {"rollout": 0.0, "generate": 0.0, "reward": 0.0, "in_makeexp": False}
    makeexp_stamps = []

    def _timed(orig, key, flag_only_inside=False):
        def wrapper(self, *a, **k):
            if flag_only_inside and not ph["in_makeexp"]:
                return orig(self, *a, **k)
            t = time.time()
            out = orig(self, *a, **k)
            ph[key] += time.time() - t
            return out
        return wrapper

    orig_makeexp = RefPPOOrch.make_experience

    def timed_makeexp(self, *a, **k):
        ph["in_makeexp"] = True
        t = time.time()
        makeexp_stamps.append(t)
        out = orig_makeexp(self, *a, **k)
        ph["rollout"] += time.time() - t
        ph["in_makeexp"] = False
        return out

    RefPPOOrch.make_experience = timed_makeexp
    AccelerateRLModel.generate = _timed(AccelerateRLModel.generate, "generate", True)
    RefPPOOrch.score = _timed(RefPPOOrch.score, "reward", True)

    from trlx.model.nn.ppo_models import ModelBranch

    orig_mb = ModelBranch.forward

    def mb_no_cache(self, *a, **k):
        # transformers>=4.38 removed tuple `presents` from GPT2Block outputs
        # (the reference indexes outputs[1] when use_cache). Cache collection
        # has no effect on the frozen branch's logits — force it off.
        k["use_cache"] = False
        return orig_mb(self, *a, **k)

    ModelBranch.forward = mb_no_cache

    import numpy as np
    import trlx
    from trlx.data.configs import TRLConfig

    p = PPO_PROTOCOL
    prompts = _ppo_prompts()
    config = TRLConfig.load_yaml(os.path.join(REFERENCE_ROOT, "configs", "ppo_config.yml"))
    config.model.model_path = assets_dir
    config.model.tokenizer_path = assets_dir
    config.model.num_layers_unfrozen = p["num_layers_unfrozen"]
    config.train.seq_length = p["seq_length"]
    config.train.batch_size = p["batch_size"]
    config.train.total_steps = p["total_steps"]
    config.train.epochs = 10**6
    config.train.eval_interval = p["eval_interval"]
    config.train.checkpoint_interval = 10**9
    config.train.checkpoint_dir = os.path.join(workdir, "ref_ckpts")
    config.train.learning_rate_init = p["lr_init"]
    config.train.learning_rate_target = p["lr_target"]
    config.method.init_kl_coef = p["init_kl_coef"]
    config.method.num_rollouts = p["num_rollouts"]
    config.method.chunk_size = p["chunk_size"]
    # Prompts tokenize to exactly 6 char-tokens and HF max_length counts
    # prompt+response, so 6+24 pins the response at response_tokens — the
    # same 24 tokens the trlx_tpu side decodes (matched protocol, matched
    # reward denominator).
    ref_total_len = 6 + p["response_tokens"]
    config.method.gen_kwargs = {
        "max_length": ref_total_len,
        "min_length": ref_total_len,
        "top_k": 0.0,
        "top_p": 1.0,
        "do_sample": True,
    }

    os.chdir(workdir)
    t0 = time.time()
    model = trlx.train(
        reward_fn=_ppo_reward_fn,
        prompts=prompts,
        eval_prompts=prompts[: p["batch_size"] // 2],
        config=config,
    )
    wall = time.time() - t0

    trajectory = [
        {"t": round(t - t0, 2), "value": round(float(torch.as_tensor(s["mean_reward"])), 4)}
        for (t, s) in logged
        if "mean_reward" in s
    ]
    return _side_result(
        "reference (trlx v0.2.0, torch eager, Accelerate CPU, hydra PPO)",
        model.iter_count, p["batch_size"], wall, eval_seconds[0],
        trajectory, "final_reward", np.diff(step_stamps),
        {k: round(v, 2) for k, v in ph.items() if k != "in_makeexp"},
        _cycle_sps(makeexp_stamps, eval_calls, p["ppo_epochs"] * p["num_rollouts"]),
    )


def run_ours_side_ppo(assets_dir: str, workdir: str) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

    import numpy as np

    sys.path.insert(0, REPO)
    import trlx_tpu
    from trlx_tpu.data.configs import TRLConfig

    p = PPO_PROTOCOL
    prompts = _ppo_prompts()
    ckpt_dir = os.path.join(workdir, "ours_ckpts")
    config = TRLConfig.from_dict(
        {
            "model": {
                "model_path": assets_dir,
                "tokenizer_path": assets_dir,
                "model_type": "ppo",
                "num_layers_unfrozen": p["num_layers_unfrozen"],
                "dtype": "float32",
                "param_dtype": "float32",
            },
            "train": {
                "seq_length": p["seq_length"],
                "epochs": 10**6,
                "total_steps": p["total_steps"],
                "batch_size": p["batch_size"],
                "lr_ramp_steps": 10,
                "lr_decay_steps": p["total_steps"],
                "weight_decay": 1.0e-6,
                "learning_rate_init": p["lr_init"],
                "learning_rate_target": p["lr_target"],
                "opt_betas": [0.9, 0.95],
                "checkpoint_interval": 10**9,
                "eval_interval": p["eval_interval"],
                "orchestrator": "PPOOrchestrator",
                "mesh": [-1, 1, 1, 1],
                "seed": 1000,
                "checkpoint_dir": ckpt_dir,
            },
            "method": {
                "name": "ppoconfig",
                "num_rollouts": p["num_rollouts"],
                "chunk_size": p["chunk_size"],
                "ppo_epochs": p["ppo_epochs"],
                "init_kl_coef": p["init_kl_coef"],
                "target": 6,
                "horizon": 10000,
                "gamma": 1.0,
                "lam": 0.95,
                "cliprange": 0.2,
                "cliprange_value": 0.2,
                "vf_coef": 1.0,
                "gen_kwargs": {
                    "prompt_length": p["seq_length"] - p["response_tokens"],
                    "max_new_tokens": p["response_tokens"],
                    "min_new_tokens": p["response_tokens"],
                    "top_k": 0,
                    "top_p": 1.0,
                    "do_sample": True,
                    "temperature": 1.0,
                },
            },
        }
    )

    if os.environ.get("TRLX_TPU_TIMELINE"):
        # Diagnostic mode: stderr stamps around the coarse startup stages so
        # wall-clock gaps in this side are attributable without a profiler.
        from trlx_tpu.trainer.ppo import PPOTrainer
        from trlx_tpu.orchestrator.ppo_orchestrator import PPOOrchestrator as _O

        _t = time.time()

        def _stamp(name):
            print(f"[timeline] +{time.time() - _t:7.2f}s {name}", file=sys.stderr, flush=True)

        for cls, meth in ((PPOTrainer, "__init__"), (_O, "make_experience"),
                          (PPOTrainer, "learn"), (PPOTrainer, "evaluate")):
            orig = getattr(cls, meth)

            def wrap(o=orig, m=meth):
                def inner(self, *a, **k):
                    _stamp(f"{m} enter")
                    r = o(self, *a, **k)
                    _stamp(f"{m} exit")
                    return r
                return inner

            setattr(cls, meth, wrap())

    t0 = time.time()
    model = trlx_tpu.train(
        reward_fn=_ppo_reward_fn,
        prompts=prompts,
        eval_prompts=prompts[: p["batch_size"] // 2],
        config=config,
    )
    wall = time.time() - t0

    trajectory, eval_s, step_times, phases, makeexp_starts, eval_calls = _parse_ours_metrics(
        ckpt_dir, "mean_reward", t0
    )
    return _side_result(
        "trlx_tpu (JAX/XLA CPU, jit train step, hydra PPO)",
        model.iter_count, p["batch_size"], wall, eval_s,
        trajectory, "final_reward", step_times, phases,
        _cycle_sps(makeexp_starts, eval_calls, p["ppo_epochs"] * p["num_rollouts"]),
    )


# ---------------------------------------------------------------------------
# Orchestrator


def time_to(trajectory, thr):
    for p in trajectory:
        if p["value"] >= thr:
            return p["t"]
    return None


_SIDE_FNS = {
    ("ref", "ilql"): run_reference_side,
    ("ours", "ilql"): run_ours_side,
    ("ref", "ppo"): run_reference_side_ppo,
    ("ours", "ppo"): run_ours_side_ppo,
}

_TASK_META = {
    "ilql": {
        "task": "randomwalks ILQL (reference: examples/randomwalks.py, seed 1000)",
        "final_key": "final_optimality",
    },
    "ppo": {
        "task": "char-task PPO, reward = frac('a') in response (hydra frozen branch, "
                "identical init checkpoint both sides)",
        "final_key": "final_reward",
    },
}

_SCOPE = (
    "cpu-smoke: both sides on this container's single CPU core, identical "
    "dataset/init, matched protocol (batch/steps/LR/method constants), and the "
    "same metric applied to both; NOT the v4-32 gate"
)


def run_method(method: str, reps: int = 1) -> dict:
    workdir = tempfile.mkdtemp(prefix=f"headtohead_{method}_")
    # For ILQL the shared artifact is the dataset the reference side
    # generates; for PPO it is the init checkpoint + tokenizer dir.
    shared = os.path.join(workdir, "dataset.npz" if method == "ilql" else "assets")
    key = TRAJECTORY_KEY[method]
    final_key = _TASK_META[method]["final_key"]

    # This machine's single core drifts ±10% on the minutes scale (measured:
    # identical step microbenches spread 204-319 ms across runs). One rep
    # cannot resolve a 10-15% ratio; with reps > 1 each (ref, ours, warm)
    # triple runs back-to-back per rep and each label's MEDIAN-throughput rep
    # is reported, so a slow patch of machine hits whole reps, not one side.
    runs = {label: [] for label in ("ref", "ours", "ours_warm")}
    for rep in range(reps):
        for side, label in (("ref", "ref"), ("ours", "ours"), ("ours", "ours_warm")):
            out = os.path.join(workdir, f"{label}_{rep}.json")
            env = dict(os.environ)
            env.pop("JAX_PLATFORMS", None)  # each side pins its own platform
            if side == "ours":
                env["JAX_PLATFORMS"] = "cpu"
                env["TRLX_TPU_NO_PROGRESS"] = "1"
                # cold uses THIS rep's fresh cache dir (populating it); the
                # warm pass reuses the same rep's now-populated cache
                env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(workdir, f"xla_cache_{rep}")
            rundir = os.path.join(workdir, f"{label}_{rep}")
            os.makedirs(rundir, exist_ok=True)
            print(f"[bench_reference] running {method}/{label} (rep {rep + 1}/{reps}) ...", flush=True)
            t = time.time()
            subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--side", side, "--method", method,
                 "--dataset", shared, "--workdir", rundir, "--out", out],
                env=env, check=True, cwd=REPO,
            )
            with open(out) as f:
                runs[label].append(json.load(f))
            print(f"[bench_reference] {method}/{label} done in {time.time()-t:.1f}s: "
                  f"{runs[label][-1]['samples_per_s']} samples/s, "
                  f"final {key} {runs[label][-1][final_key]}", flush=True)

    def median_rep(rs):
        """The rep whose samples_per_s is the median — one self-consistent
        run's full record (trajectory, phases, steady-states together)."""
        ranked = sorted(rs, key=lambda r: r["samples_per_s"])
        return ranked[len(ranked) // 2]

    def paired_ratio(metric):
        """Median over reps of the PER-REP ours/ref ratio. Pairing within a
        rep (runs minutes apart) is what actually cancels machine drift —
        independent per-label medians can select different speed regimes."""
        import statistics

        vals = []
        for o, r in zip(runs["ours"], runs["ref"]):
            if o.get(metric) and r.get(metric):
                vals.append(o[metric] / r[metric])
        return round(statistics.median(vals), 3) if vals else None

    def paired_ratio_warm(metric):
        import statistics

        vals = []
        for w, r in zip(runs["ours_warm"], runs["ref"]):
            if w.get(metric) and r.get(metric):
                vals.append(w[metric] / r[metric])
        return round(statistics.median(vals), 3) if vals else None

    sides = {label: median_rep(rs) for label, rs in runs.items()}
    if reps > 1:
        for label in sides:
            sides[label]["rep_samples_per_s"] = [r["samples_per_s"] for r in runs[label]]
    ref, ours, warm = sides["ref"], sides["ours"], sides["ours_warm"]
    t2o = {}
    for thr in THRESHOLDS[method]:
        tr, to = time_to(ref["trajectory"], thr), time_to(ours["trajectory"], thr)
        tw = time_to(warm["trajectory"], thr)
        t2o[str(thr)] = {
            "ref_s": tr,
            "ours_s": to,
            "ours_warm_s": tw,
            "speedup": round(tr / to, 2) if (tr and to) else None,
        }
    return {
        "task": _TASK_META[method]["task"],
        "scope": _SCOPE,
        "reference": ref,
        "ours": ours,
        "ours_warm_cache": warm,
        # All ratios are medians of PER-REP pairings (see paired_ratio).
        "vs_baseline_samples_per_s": paired_ratio("samples_per_s"),
        "vs_baseline_warm_cache": paired_ratio_warm("samples_per_s"),
        "vs_baseline_steady_state": paired_ratio("steady_state_samples_per_s"),
        # Full recurring cycle (rollout + train + logging; one-time costs
        # excluded) — the production-cadence steady state. The per-step
        # steady state above ignores the rollout phase, where the two
        # implementations differ most.
        "vs_baseline_steady_cycle": paired_ratio("steady_state_cycle_samples_per_s"),
        "vs_baseline_steady_cycle_warm": paired_ratio_warm("steady_state_cycle_samples_per_s"),
        "per_rep_ratios": {
            "cold": [
                round(o["samples_per_s"] / r["samples_per_s"], 3)
                for o, r in zip(runs["ours"], runs["ref"])
            ],
            "steady_cycle": [
                round(o["steady_state_cycle_samples_per_s"] / r["steady_state_cycle_samples_per_s"], 3)
                for o, r in zip(runs["ours"], runs["ref"])
                if o.get("steady_state_cycle_samples_per_s") and r.get("steady_state_cycle_samples_per_s")
            ],
        },
        f"time_to_{key}": t2o,
    }


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--side", choices=["ref", "ours"])
    parser.add_argument("--method", choices=["ilql", "ppo", "both"], default="both")
    parser.add_argument("--reps", type=int, default=3,
                        help="repetitions per side; the median-throughput rep is "
                             "reported (this machine's core drifts ±10%%)")
    parser.add_argument("--dataset", default=None)
    parser.add_argument("--workdir", default=None)
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    if args.side:
        if args.method == "both":
            parser.error("--side requires an explicit --method (ilql or ppo)")
        result = _SIDE_FNS[(args.side, args.method)](args.dataset, args.workdir)
        with open(args.out, "w") as f:
            json.dump(result, f)
        return

    # Merge into the existing HEADTOHEAD.json so the two methods can be
    # (re)run independently; migrate the legacy single-task layout.
    existing = {}
    if os.path.exists(RESULT_PATH):
        with open(RESULT_PATH) as f:
            existing = json.load(f)
        if "reference" in existing:
            existing = {"ilql": existing}

    methods = ["ilql", "ppo"] if args.method == "both" else [args.method]
    for method in methods:
        existing[method] = run_method(method, reps=args.reps)
    existing["recorded_at"] = time.strftime("%Y-%m-%d %H:%M:%S")
    with open(RESULT_PATH, "w") as f:
        json.dump(existing, f, indent=1)

    summary = {"metric": "headtohead_cpu_speedup_vs_reference", "unit": "x reference samples/s (CPU)"}
    for method in ("ilql", "ppo"):
        if method in existing:
            r = existing[method]
            summary[f"{method}_cold"] = r["vs_baseline_samples_per_s"]
            summary[f"{method}_warm_cache"] = r["vs_baseline_warm_cache"]
            summary[f"{method}_steady_state"] = r["vs_baseline_steady_state"]
            if r.get("vs_baseline_steady_cycle") is not None:
                summary[f"{method}_steady_cycle"] = r["vs_baseline_steady_cycle"]
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
