"""Architext: PPO-tune a layout model to draw fewer rooms.

Counterpart of the reference (reference: examples/architext.py): the reward
is simply the negative count of ":" in each generated layout string — a toy
host-side reward demonstrating arbitrary-Python reward functions over
decoded text.

Requires network access for: architext/gptj-162M.

Run:  python examples/architext.py
"""

import trlx_tpu


def reward_fn(samples):
    """Negative room count (rooms are ':'-delimited in architext layouts)."""
    return [-float(sample.count(":")) for sample in samples]


PROMPTS = [
    "[prompt] the bedroom is adjacent to the living room [layout]",
    "[prompt] a bedroom is adjacent to the living room [layout]",
    "[prompt] the bedroom is adjacent to the kitchen [layout]",
    "[prompt] a bedroom is adjacent to the kitchen [layout]",
    "[prompt] the bedroom is adjacent to the kitchen [layout]",
    "[prompt] the kitchen is adjacent to the bathroom [layout]",
    "[prompt] a bathroom is adjacent to the living room [layout]",
    "[prompt] the bathroom is adjacent to the living room [layout]",
    "[prompt] the bedroom is not adjacent to the living room [layout]",
    "[prompt] a bedroom is not adjacent to the living room [layout]",
    "[prompt] the bedroom is not adjacent to the kitchen [layout]",
    "[prompt] a bedroom is not adjacent to the kitchen [layout]",
    "[prompt] the bedroom is not adjacent to the kitchen [layout]",
    "[prompt] the kitchen is not adjacent to the bathroom [layout]",
]


def main():
    return trlx_tpu.train("architext/gptj-162M", reward_fn=reward_fn, prompts=PROMPTS)


if __name__ == "__main__":
    main()
