"""Soft-prompt PPO sentiments: tune ONLY a learned prefix, LM frozen.

Counterpart of the daia99 fork's addition
(reference: examples/ppo_softprompt_sentiments.py +
trlx/model/accelerate_ppo_softprompt_model.py). The fork's example is
bitrotted against its own refactored base (SURVEY.md §2a); this one
reproduces the CAPABILITY — parameter-efficient prompt tuning under PPO —
through the working `train()` path: optimizer updates are optax-masked to
the soft prefix + value head only, so Adam state exists for a few thousand
parameters instead of the whole LM.

Requires network access for: lvwerra/gpt2-imdb, lvwerra/distilbert-imdb, imdb.

Run:  python examples/ppo_softprompt_sentiments.py
"""

import trlx_tpu
from trlx_tpu.trainer.api import default_config

from ppo_sentiments import build_reward_fn


def main():
    from datasets import load_dataset

    config = default_config("ppo_softprompt")

    imdb = load_dataset("imdb", split="train+test")
    prompts = [" ".join(review.split()[:4]) for review in imdb["text"]]

    return trlx_tpu.train(
        reward_fn=build_reward_fn(),
        prompts=prompts,
        eval_prompts=["I don't know much about Hungarian underground"] * 64,
        config=config,
    )


if __name__ == "__main__":
    main()
