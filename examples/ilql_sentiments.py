"""Offline ILQL on sentiment-labeled IMDB: learn positivity from labels alone.

Counterpart of the reference (reference: examples/ilql_sentiments.py): the
dataset is (review text, 0/1 sentiment label); ILQL learns Q/V heads over the
frozen-ish LM and decodes with advantage-steered sampling. The sentiment
classifier is only a METRIC here, not a reward signal.

Requires network access for: gpt2, lvwerra/distilbert-imdb, imdb.

Run:  python examples/ilql_sentiments.py
"""

import trlx_tpu


def build_metric_fn():
    from transformers import pipeline

    sentiment_fn = pipeline(
        "sentiment-analysis", "lvwerra/distilbert-imdb", device=-1, top_k=2, truncation=True
    )

    def metric_fn(samples):
        from trlx_tpu.utils import sentiment_score

        return {"sentiments": sentiment_score(sentiment_fn(samples))}

    return metric_fn


def main():
    from datasets import load_dataset

    imdb = load_dataset("imdb", split="train+test")

    return trlx_tpu.train(
        "gpt2",
        dataset=(imdb["text"], imdb["label"]),
        eval_prompts=["I don't know much about Hungarian underground"] * 64,
        metric_fn=build_metric_fn(),
    )


if __name__ == "__main__":
    main()
