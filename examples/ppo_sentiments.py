"""PPO sentiment tuning: make gpt2-imdb write positive movie reviews.

Counterpart of the reference's flagship example
(reference: examples/ppo_sentiments.py): a distilbert-imdb sentiment
classifier is the reward function; prompts are the first few words of IMDB
reviews. The reward model runs on HOST (torch-cpu) while rollouts and PPO
updates run as compiled XLA programs on the TPU mesh — the host/device
overlap the orchestrator manages (SURVEY.md §7 hard part 2).

Requires network access for the HF checkpoints/datasets:
    lvwerra/gpt2-imdb, lvwerra/distilbert-imdb, imdb

Run:  python examples/ppo_sentiments.py
"""

import trlx_tpu


def build_reward_fn():
    from transformers import pipeline

    sentiment_fn = pipeline(
        "sentiment-analysis", "lvwerra/distilbert-imdb", device=-1, top_k=2, truncation=True
    )

    def reward_fn(samples):
        # score of the POSITIVE class, order-stable regardless of ranking
        from trlx_tpu.utils import sentiment_score

        return sentiment_score(sentiment_fn(samples))

    return reward_fn


def main():
    from datasets import load_dataset

    imdb = load_dataset("imdb", split="train+test")
    prompts = [" ".join(review.split()[:4]) for review in imdb["text"]]

    return trlx_tpu.train(
        "lvwerra/gpt2-imdb",
        reward_fn=build_reward_fn(),
        prompts=prompts,
        eval_prompts=["I don't know much about Hungarian underground"] * 64,
    )


if __name__ == "__main__":
    main()
