"""Shortest-path toy task (Decision-Transformer random walks).

Counterpart of the reference's CPU smoke example
(reference: examples/randomwalks.py): a random directed graph whose node ids
are the vocabulary; models are trained to walk from a start node to node 0 in
as few steps as possible. No tokenizer, no downloads — from-scratch tiny GPT-2
config; runs on CPU JAX. Implemented independently: BFS instead of networkx,
explicit reward_fn for the PPO variant (the reference only exercises ILQL).

Run:  python examples/randomwalks.py [ppo|ilql]
"""

import sys
from collections import deque

import numpy as np

import trlx_tpu
from trlx_tpu.data.configs import TRLConfig


def generate_random_walks(n_nodes=21, max_length=10, n_walks=1000, p_edge=0.1, seed=1000):
    rng = np.random.default_rng(seed)

    # random digraph; every node needs an outgoing edge
    while True:
        adj = rng.random((n_nodes, n_nodes)) > (1 - p_edge)
        np.fill_diagonal(adj, False)
        if adj.sum(1).all():
            break

    # node 0 is the absorbing goal
    adj[0, :] = False
    adj[0, 0] = True
    goal = 0

    # sample random walks (the offline dataset)
    walks = []
    for _ in range(n_walks):
        node = int(rng.integers(1, n_nodes))
        walk = [node]
        for _ in range(max_length - 1):
            node = int(rng.choice(np.nonzero(adj[node])[0]))
            walk.append(node)
            if node == goal:
                break
        walks.append(np.asarray(walk, dtype=np.int32))

    # BFS shortest-path length from every node to the goal (walk edges backwards)
    radj = adj.T
    dist = np.full(n_nodes, -1, dtype=np.int64)
    dist[goal] = 0
    queue = deque([goal])
    while queue:
        u = queue.popleft()
        for v in np.nonzero(radj[u])[0]:
            if dist[v] < 0:
                dist[v] = dist[u] + 1
                queue.append(v)

    worstlen = max_length
    # path node-count from each non-goal start, capped at max_length
    best_lengths = np.asarray(
        [min(dist[s] + 1, max_length) if dist[s] >= 0 else max_length for s in range(1, n_nodes)],
        dtype=np.float32,
    )

    def walk_length(s):
        """Node count up to and including the first goal visit; None if never."""
        s = np.asarray(s).reshape(-1)
        hits = np.nonzero(s == goal)[0]
        return int(hits[0]) + 1 if len(hits) else None

    def metric_fn(samples):
        lengths, opt = [], []
        for i, s in enumerate(samples):
            L = walk_length(s)
            lengths.append(-float(L) if L else -100.0)
            bound = float(L) if L else worstlen
            denom = max(worstlen - best_lengths[i % len(best_lengths)], 1.0)
            opt.append(min((worstlen - bound) / denom, 1.0))
        return {"lengths": np.asarray(lengths), "optimality": np.asarray(opt)}

    def reward_fn(samples):
        """PPO reward: negative normalized path length, penalties for invalid
        edges / never reaching the goal."""
        rewards = []
        for s in samples:
            s = np.asarray(s).reshape(-1)
            invalid = sum(1 for a, b in zip(s[:-1], s[1:]) if not adj[a, b])
            L = walk_length(s)
            r = -(L if L else 2 * worstlen) / worstlen - invalid
            rewards.append(r)
        return np.asarray(rewards, dtype=np.float32)

    logit_mask = ~adj
    return walks, logit_mask, metric_fn, reward_fn


def base_config(method: str, n_nodes: int, max_length: int) -> TRLConfig:
    return TRLConfig.from_dict(
        {
            "model": {
                "model_path": "",
                "tokenizer_path": "",
                "model_type": method,
                "num_layers_unfrozen": -1,
                "dtype": "float32",
                "model_arch": {
                    "n_layer": 2,
                    "n_head": 4,
                    "d_model": 144,
                    "vocab_size": n_nodes,
                    "max_position": 2 * max_length,
                    "eos_token_id": 0,
                },
            },
            "train": {
                "seq_length": max_length,
                "epochs": 10 if method == "ppo" else 30,
                "total_steps": 150,
                "batch_size": 50,
                "lr_ramp_steps": 10,
                "lr_decay_steps": 200,
                "weight_decay": 1.0e-6,
                "learning_rate_init": 2.0e-3,
                "learning_rate_target": 2.0e-4,
                "opt_betas": [0.9, 0.95],
                "checkpoint_interval": 1000000,
                "eval_interval": 20,
                "orchestrator": "PPOOrchestrator" if method == "ppo" else "OfflineOrchestrator",
                "mesh": [-1, 1, 1, 1],
                "seed": 1000,
            },
            "method": {
                "name": "ppoconfig",
                "num_rollouts": 100,
                "chunk_size": 50,
                "ppo_epochs": 4,
                "init_kl_coef": 0.05,
                "target": 6,
                "horizon": 10000,
                "gamma": 1.0,
                "lam": 0.95,
                "cliprange": 0.2,
                "cliprange_value": 0.2,
                "vf_coef": 1.2,
                "gen_kwargs": {
                    "prompt_length": 1,
                    "max_new_tokens": max_length - 1,
                    "top_k": 0,
                    "top_p": 1.0,
                    "do_sample": True,
                    "temperature": 1.0,
                },
            }
            if method == "ppo"
            else {
                "name": "ilqlconfig",
                "tau": 0.7,
                "gamma": 0.99,
                "cql_scale": 0.1,
                "awac_scale": 1.0,
                "alpha": 0.1,
                "steps_for_target_q_sync": 5,
                "betas": [100],
                "two_qs": True,
            },
        }
    )


def main(method: str = "ppo"):
    n_nodes, max_length = 21, 10
    walks, logit_mask, metric_fn, reward_fn = generate_random_walks(n_nodes=n_nodes, max_length=max_length)
    eval_prompts = [[i] for i in range(1, n_nodes)]
    config = base_config(method, n_nodes, max_length)

    if method == "ppo":
        prompts = [[int(np.random.default_rng(i).integers(1, n_nodes))] for i in range(200)]
        model = trlx_tpu.train(
            reward_fn=reward_fn,
            prompts=prompts,
            eval_prompts=eval_prompts,
            metric_fn=metric_fn,
            config=config,
            logit_mask=logit_mask,
        )
    else:
        lengths = metric_fn(walks)["lengths"]
        model = trlx_tpu.train(
            dataset=(walks, lengths),
            eval_prompts=eval_prompts,
            metric_fn=metric_fn,
            config=config,
            logit_mask=logit_mask,
        )
    return model


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "ppo")
