"""Simulacra: offline ILQL on image-prompt/aesthetic-rating pairs.

Counterpart of the reference (reference: examples/simulacra.py): trains gpt2
to produce higher-rated image prompts from the Simulacra Aesthetic Captions
SQLite database (https://github.com/JD-P/simulacra-aesthetic-captions).

Requires network access for: gpt2 and the sqlite dataset download.

Run:  python examples/simulacra.py
"""

import os
import sqlite3
from urllib.request import urlretrieve

import trlx_tpu

URL = (
    "https://raw.githubusercontent.com/JD-P/simulacra-aesthetic-captions/main/"
    "sac_public_2022_06_29.sqlite"
)
DBPATH = "sac_public_2022_06_29.sqlite"


def load_ratings(dbpath: str = DBPATH):
    if not os.path.exists(dbpath):
        print(f"fetching {dbpath}")
        urlretrieve(URL, dbpath)
    conn = sqlite3.connect(dbpath)
    rows = conn.execute(
        "SELECT prompt, rating FROM ratings "
        "JOIN images ON images.id=ratings.iid "
        "JOIN generations ON images.gid=generations.id "
        "WHERE rating IS NOT NULL;"
    ).fetchall()
    conn.close()
    prompts, ratings = map(list, zip(*rows))
    return prompts, ratings


def main():
    prompts, ratings = load_ratings()
    return trlx_tpu.train(
        "gpt2",
        dataset=(prompts, ratings),
        eval_prompts=["Hatsune Miku, Red Dress"] * 64,
    )


if __name__ == "__main__":
    main()
