"""CPU smoke of the decode hot path: minutes, no TPU, CI-safe.

Probes covering exactly what BENCH_r05 showed CPU CI was blind to:

1. kernel — the flash-decode Pallas kernel runs in INTERPRET mode at the
   flagship head layout (h=16, d=256) over an int8 KV cache with a ragged
   cache length, and must match the model layer's dequantize+einsum fallback.
   Plus the static tile-legality check at the full bench shape (B=32, T=832),
   which is the part of the Mosaic lowering that CAN be enforced off-TPU.

2. rollout — a tiny bucketed rollout: PromptPipeline with bucket widths
   feeding make_generate_fn, asserting the compiled-program count stays
   <= n_buckets (the trace-count hook) and the decode metrics helper returns
   sane numbers.

3. overlap — a tiny bucketed PPO run with the rollout/train pipeline on
   (method.max_staleness=1): the phase windows in metrics.jsonl must carry
   time/overlap_fraction, the stored samples must carry the staleness
   column, and the producer/score-worker threads must be joined by the time
   train() returns.

4. fused_loss — the streaming logprob head: static tile legality at the
   FULL bench head shape (N=6656, d=4096, V=50400), interpret-mode parity
   vs the materialized log_softmax chain at the flagship head/vocab layout
   (d=4096, V=50400, N scaled down), gradient parity at a reduced width,
   and a tiny PPO train run with method.pack_train_batch=true whose
   metrics must carry train_tokens_per_s / train_batch_fill.

5. decode_engine — the continuous-batching rollout engine (trlx_tpu/engine)
   on a mixed-response-length CPU workload where every static chunk carries
   one full-budget straggler: slot decode must match the whole-batch decode
   token for token, keep slot occupancy > 85%, and deliver HIGHER decode
   tokens/s than the static-batch path (the straggler steps the slot refill
   reclaims). Both rates land in BENCH_SMOKE.json.

6. paged_kv — the paged KV cache + prefix caching path (trlx_tpu/engine,
   RUNBOOK §20) on a mixed-length workload whose prompts all open with the
   same 64-token template: the paged engine must match the fixed-slot
   engine token for token (int8 KV on and off), run >= 1.5x the slot count
   in the SAME cache bytes (pool blocks x block size <= fixed slots x
   cache_len), and skip the template's prefill on every admission after
   the first (prefix hits + tokens-saved land in BENCH_SMOKE.json).

7. fleet_elastic — elastic N-worker fleet transport throughput
   (trlx_tpu/fleet, RUNBOOK §18): threaded workers with a fixed synthetic
   produce cost drive the real lease ledger + per-worker stream indexes +
   exactly-once intake at 1 worker then 2. Intake must stay exactly-once
   (every unit chosen once, zero duplicates, no reclaims) and the 2-worker
   run must beat the 1-worker rate by > 1.3x — the claim/append/consume
   transports must overlap workers, not serialize them. Episodes/s for
   both fleet sizes land in BENCH_SMOKE.json.

Writes BENCH_SMOKE.json and prints one JSON summary line; exits 1 on any
failure. Wall time ~1-2 min on a laptop CPU.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(REPO, "BENCH_SMOKE.json")


def kernel_probe():
    import numpy as np
    import jax
    import jax.numpy as jnp

    from trlx_tpu.models.lm import quantize_kv
    from trlx_tpu.ops.decode_attention import decode_attention
    from trlx_tpu.ops.tiling import check_layout, decode_block_layout

    # Static legality at the REAL flagship decode shape (the lowering rule
    # that used to only fire on device).
    check_layout(decode_block_layout(32, 832, 16, 256, True))
    check_layout(decode_block_layout(32, 832, 16, 256, False))

    # Interpret-mode parity at the flagship head layout, batch scaled down
    # (interpret mode is a Python loop; B=32 would take minutes for no
    # additional coverage).
    B, T, h, d = 2, 300, 16, 256  # ragged: T % 128 != 0
    rng = np.random.default_rng(0)
    q = rng.normal(size=(B, h, d)).astype(np.float32)
    k = rng.normal(size=(B, T, h, d)).astype(np.float32)
    v = rng.normal(size=(B, T, h, d)).astype(np.float32)
    valid = np.ones((B, T), dtype=bool)
    valid[0, :7] = False  # left padding
    bias = np.where(valid, 0.0, -1e9).astype(np.float32)

    kq, ks = quantize_kv(jnp.asarray(k))
    vq, vs = quantize_kv(jnp.asarray(v))
    t0 = time.time()
    out = decode_attention(
        jnp.asarray(q), kq, vq, ks, vs, jnp.asarray(bias), scale=d ** -0.5, interpret=True
    )
    kernel_s = time.time() - t0

    k_dq = kq.astype(jnp.float32) * ks[..., None].astype(jnp.float32)
    v_dq = vq.astype(jnp.float32) * vs[..., None].astype(jnp.float32)
    scores = jnp.einsum("bhd,bkhd->bhk", jnp.asarray(q), k_dq) * d ** -0.5 + bias[:, None, :]
    ref = jnp.einsum("bhk,bkhd->bhd", jax.nn.softmax(scores, axis=-1), v_dq)
    err = float(jnp.max(jnp.abs(out[:, 0] - ref)))
    assert err < 2e-4, f"kernel parity failed: maxerr={err}"
    return {"shape": [B, T, h, d], "maxerr": err, "seconds": round(kernel_s, 2)}


def rollout_probe():
    import numpy as np
    import jax
    import jax.numpy as jnp

    from trlx_tpu.models import LMConfig, LMWithValueHead
    from trlx_tpu.ops.generate import make_generate_fn
    from trlx_tpu.ops.sampling import GenerateConfig
    from trlx_tpu.pipeline.prompt_pipeline import PromptPipeline
    from trlx_tpu.trainer.base import JaxBaseTrainer

    cfg = LMConfig(vocab_size=29, n_layer=1, n_head=2, d_model=16, max_position=32, dtype="float32")
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    ids0 = jnp.ones((2, 4), jnp.int32)
    params = {"params": model.init(rng, ids0, jnp.ones_like(ids0))["params"]}
    gcfg = GenerateConfig(max_new_tokens=4, do_sample=False, eos_token_id=None, pad_token_id=0)
    gen = make_generate_fn(model, gcfg)

    prng = np.random.default_rng(1)
    prompts = [list(prng.integers(2, 28, size=n)) for n in (2, 3, 5, 7, 8, 4, 6, 3)]
    pipe = PromptPipeline(prompts, tokenizer=None, max_prompt_length=8, bucket_widths=(4, 8))
    loader = pipe.create_loader(batch_size=2, shuffle=True, drop_last=False, seed=2)

    gen_tokens = 0
    t0 = time.time()
    for i, batch in enumerate(loader):
        toks, mask = gen(
            params,
            jnp.asarray(batch["input_ids"]),
            jnp.asarray(batch["attention_mask"]),
            jax.random.PRNGKey(i),
        )
        P = batch["input_ids"].shape[1]
        stats = JaxBaseTrainer.rollout_decode_stats(np.asarray(mask), P)
        assert 0 < stats["decode_steps"] <= stats["decode_step_budget"]
        gen_tokens += stats["gen_tokens"]
    gen_s = time.time() - t0

    n_buckets = len(pipe.bucket_widths)
    assert gen.num_traces <= n_buckets, (
        f"bucketing leak: {gen.num_traces} generate traces for {n_buckets} "
        f"buckets (shapes: {gen.traced_shapes})"
    )
    return {
        "buckets": list(pipe.bucket_widths),
        "generate_traces": gen.num_traces,
        "gen_tokens": gen_tokens,
        "tokens_per_s": round(gen_tokens / max(gen_s, 1e-9), 1),
        "seconds": round(gen_s, 2),
    }


def overlap_probe():
    import tempfile
    import threading

    import numpy as np

    sys.path.insert(0, os.path.join(REPO, "examples"))
    import trlx_tpu
    from randomwalks import base_config, generate_random_walks

    _, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=15, max_length=8, n_walks=60, seed=1000
    )
    config = base_config("ppo", 15, 8)
    config.train.total_steps = 16
    config.train.epochs = 8
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.method.num_rollouts = 32
    config.method.chunk_size = 16
    config.method.max_staleness = 1
    config.method.gen_kwargs["prompt_buckets"] = [1]
    d = tempfile.mkdtemp(prefix="overlap_smoke_")
    config.train.checkpoint_dir = d
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    t0 = time.time()
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[1]],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    wall_s = time.time() - t0

    with open(os.path.join(d, "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    fractions = [r["time/overlap_fraction"] for r in records if "time/overlap_fraction" in r]
    assert fractions, "no phase windows reached metrics.jsonl"
    stale = [r["staleness/mean"] for r in records if "staleness/mean" in r]
    assert stale and stale[-1] == 1.0, f"staleness stats missing/wrong: {stale}"
    # the producer joined cleanly: no pipeline thread outlives train()
    leaked = [t.name for t in threading.enumerate() if t.name.startswith("trlx-")]
    assert not leaked, f"pipeline threads leaked: {leaked}"
    assert model._rollout_producer is None
    return {
        "steps": model.iter_count,
        "overlap_fraction_max": round(max(fractions), 3),
        "windows": len(fractions),
        "staleness_last": stale[-1],
        "seconds": round(wall_s, 2),
    }


def fused_loss_probe():
    import tempfile

    import numpy as np
    import jax
    import jax.numpy as jnp

    from trlx_tpu.ops.fused_logprob import fused_logprob, naive_logprob
    from trlx_tpu.ops.tiling import check_layout, fused_logprob_block_layout

    # Static legality at the REAL bench head shape: 8 rows x T=832 states
    # flattened (N=6656), GPT-J head d=4096 over the ragged 50400 vocab.
    N, D, V = 8 * 832, 4096, 50400
    for tied, bias in ((True, False), (False, False), (False, True)):
        check_layout(fused_logprob_block_layout(N, D, V, 128, 512, tied, bias))

    # Interpret-mode parity at the flagship head/vocab layout, N scaled
    # down (one 128-row block; the 99-tile vocab stream incl. the masked
    # 224-wide tail is the coverage that matters).
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 8, D)), jnp.float32) * 0.2
    w = jnp.asarray(rng.normal(size=(D, V)), jnp.float32) * 0.05
    y = jnp.asarray(rng.integers(0, V, size=(2, 8)), jnp.int32)
    t0 = time.time()
    lp, lse, ent = jax.jit(
        lambda x, w: fused_logprob(x, w, y, tied=False, interpret=True)
    )(x, w)
    kernel_s = time.time() - t0
    lp_n, lse_n, ent_n = naive_logprob(x, w, y, tied=False)
    err = max(
        float(jnp.max(jnp.abs(a - b)))
        for a, b in ((lp, lp_n), (lse, lse_n), (ent, ent_n))
    )
    assert err < 1e-4, f"fused-logprob parity failed: maxerr={err}"

    # Gradient parity through the custom VJP at a reduced width (full-D
    # backward in interpret mode is minutes of CPU for no extra coverage).
    Dg, Vg = 256, 1000
    xg = jnp.asarray(rng.normal(size=(2, 8, Dg)), jnp.float32) * 0.2
    wg = jnp.asarray(rng.normal(size=(Dg, Vg)), jnp.float32) * 0.1
    yg = jnp.asarray(rng.integers(0, Vg, size=(2, 8)), jnp.int32)

    def scal(fn):
        return lambda x, w: sum(
            jnp.sum(o) for o in fn(x, w, yg, tied=False)
        )

    gk = jax.grad(scal(lambda *a, **k: fused_logprob(*a, interpret=True, **k)), argnums=(0, 1))(xg, wg)
    gn = jax.grad(scal(naive_logprob), argnums=(0, 1))(xg, wg)
    gerr = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(gk, gn))
    assert gerr < 1e-4, f"fused-logprob grad parity failed: maxerr={gerr}"

    # Tiny packed PPO train step end-to-end (pack_train_batch routes the
    # loader through pack_ppo_batch and the segment-aware loss).
    sys.path.insert(0, os.path.join(REPO, "examples"))
    import trlx_tpu
    from randomwalks import base_config, generate_random_walks

    _, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=15, max_length=8, n_walks=60, seed=1000
    )
    config = base_config("ppo", 15, 8)
    # must cross at least one rollout boundary: phase windows (and the
    # train_tokens_per_s / fill stats) flush there
    config.train.total_steps = 8
    config.train.epochs = 4
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    config.method.pack_train_batch = True
    d = tempfile.mkdtemp(prefix="packed_smoke_")
    config.train.checkpoint_dir = d
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    t0 = time.time()
    model = trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
        metric_fn=metric_fn, config=config, logit_mask=logit_mask,
    )
    packed_s = time.time() - t0
    assert model.iter_count >= 8
    with open(os.path.join(d, "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    toks = [r["train_tokens_per_s"] for r in records if "train_tokens_per_s" in r]
    fill = [r["train_batch_fill"] for r in records if "train_batch_fill" in r]
    assert toks and toks[-1] > 0, f"train_tokens_per_s missing: {toks}"
    assert fill and 0 < fill[-1] <= 1, f"train_batch_fill missing/bad: {fill}"
    return {
        "head_shape": [N, D, V],
        "maxerr": err,
        "grad_maxerr": gerr,
        "kernel_seconds": round(kernel_s, 2),
        "packed_steps": model.iter_count,
        "packed_fill": round(fill[-1], 3),
        "tokens_per_s": round(toks[-1], 1),
        "packed_seconds": round(packed_s, 2),
    }


def decode_engine_probe():
    import numpy as np
    import jax
    import jax.numpy as jnp

    from trlx_tpu.parallel import mesh as mesh_mod

    # The earlier probes (overlap/fused-loss train runs) leave the
    # process-global mesh installed; the engine pins its decode state to
    # that mesh, which would shard 8 slots one-per-fake-device and turn
    # every decode step into cross-device traffic. This probe measures the
    # single-host engine, so it runs mesh-free and restores the global.
    prev_mesh = mesh_mod.peek_mesh()
    mesh_mod.set_mesh(None)
    try:
        return _decode_engine_probe_meshless()
    finally:
        mesh_mod.set_mesh(prev_mesh)


def _decode_engine_probe_meshless():
    import numpy as np
    import jax
    import jax.numpy as jnp

    from trlx_tpu.engine import RolloutEngine
    from trlx_tpu.models import LMConfig, LMWithValueHead
    from trlx_tpu.ops.generate import make_generate_fn
    from trlx_tpu.ops.sampling import (
        GenerateConfig,
        make_bigram_mask_processor,
        process_logits_default,
    )

    # Forced-chain decode (the bigram-mask trick from tests/test_generate):
    # greedy can only emit (last_token + 1) % V, so a prompt ending at token
    # t runs for EXACTLY eos - t steps — response lengths are engineered,
    # not sampled, and both paths must agree token for token.
    V, R, W = 64, 16, 4
    eos, pad = V - 1, 0
    cfg = LMConfig(vocab_size=V, n_layer=4, n_head=2, d_model=256, max_position=64, dtype="float32")
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    params = {"params": model.init(rng, jnp.ones((2, W), jnp.int32), jnp.ones((2, W), jnp.int32))["params"]}
    gcfg = GenerateConfig(max_new_tokens=R, do_sample=False, eos_token_id=eos, pad_token_id=pad)
    forbidden = np.ones((V, V), dtype=bool)
    for i in range(V):
        forbidden[i, (i + 1) % V] = False
    bigram = make_bigram_mask_processor(jnp.asarray(forbidden))

    def proc(logits, state):
        return process_logits_default(bigram(logits, state), gcfg, state["step"])

    # 5 chunks of 8: each chunk = 1 straggler (full 16-step budget) + 7
    # short rows (5 steps) — the static while_loop pays 16 steps per chunk,
    # the engine refills the short rows' slots and pays ~mean steps.
    prng = np.random.default_rng(2)
    chunks = []
    for c in range(5):
        ids = prng.integers(1, 40, size=(8, W)).astype(np.int32)
        ids[0, -1] = eos - R  # straggler: 16 steps
        ids[1:, -1] = eos - 5  # short: 5 steps
        chunks.append((ids, np.ones((8, W), np.int32)))
    total_tokens = 5 * (R + 7 * 5)

    # Static-batch reference: whole-batch decode per chunk (warm chunk 0
    # first so both paths time EXECUTION, not compilation).
    gen = make_generate_fn(model, gcfg, processor=proc)
    ref = {}
    gen(params, jnp.asarray(chunks[0][0]), jnp.asarray(chunks[0][1]), jax.random.PRNGKey(1))
    t0 = time.time()
    for i, (ids, msk) in enumerate(chunks):
        toks, m = gen(params, jnp.asarray(ids), jnp.asarray(msk), jax.random.PRNGKey(i))
        toks, m = np.asarray(toks), np.asarray(m)
        for b in range(ids.shape[0]):
            ref[tuple(ids[b].tolist())] = (toks[b, W:], m[b, W:])
    static_s = time.time() - t0
    static_rate = total_tokens / max(static_s, 1e-9)

    engine = RolloutEngine(
        model, gcfg, n_slots=8, prompt_width=W, processor=proc,
        prefill_batch=1, steps_per_sync=1, rng=jax.random.PRNGKey(3),
    )
    engine.update_weights(params, version=0)
    # warm the compiled prefill/decode programs off the clock
    engine.submit(chunks[0][0][:1], chunks[0][1][:1])
    while not engine.idle:
        engine.step()
    engine.stats(reset=True)

    # Stragglers first: a 16-step row admitted near the end of the queue
    # would drain with mostly-empty slots and depress occupancy for no
    # reason the engine controls — admission order is the host's call.
    all_ids = np.concatenate([c[0] for c in chunks])
    all_msk = np.concatenate([c[1] for c in chunks])
    order = np.argsort(all_ids[:, -1], kind="stable")  # eos-R rows sort first
    engine.submit(all_ids[order], all_msk[order])
    episodes = []
    t0 = time.time()
    while not engine.idle:
        episodes.extend(engine.step())
    engine_s = time.time() - t0
    engine_rate = total_tokens / max(engine_s, 1e-9)
    stats = engine.stats(reset=False)
    engine.shutdown()

    assert len(episodes) == 40
    for ep in episodes:
        rtoks, rmask = ref[tuple(ep.prompt_ids.tolist())]
        assert np.array_equal(ep.response_ids, rtoks), "engine/static token mismatch"
        assert np.array_equal(ep.response_mask, rmask), "engine/static mask mismatch"
    assert engine.num_decode_traces == 1, f"decode retraced: {engine.num_decode_traces}"
    occ = stats["engine/slot_occupancy"]
    assert occ > 0.85, f"slot occupancy {occ:.3f} <= 0.85"
    assert stats["engine/gen_tokens"] == total_tokens
    assert engine_rate > static_rate, (
        f"engine decode {engine_rate:.1f} tok/s did not beat static batch "
        f"{static_rate:.1f} tok/s on the mixed-length workload"
    )
    return {
        "episodes": len(episodes),
        "slot_occupancy": round(occ, 3),
        "refills": stats["engine/refills"],
        "decode_tokens_per_s": round(engine_rate, 1),
        "static_decode_tokens_per_s": round(static_rate, 1),
        "speedup": round(engine_rate / max(static_rate, 1e-9), 2),
        "seconds": round(engine_s + static_s, 2),
    }


def spec_decode_probe():
    import numpy as np  # noqa: F401

    from trlx_tpu.parallel import mesh as mesh_mod

    # Meshless for the same reason as decode_engine_probe: the engine pins
    # its slot state to the process-global mesh left by earlier probes.
    prev_mesh = mesh_mod.peek_mesh()
    mesh_mod.set_mesh(None)
    try:
        return _spec_decode_probe_meshless()
    finally:
        mesh_mod.set_mesh(prev_mesh)


def _spec_decode_probe_meshless():
    import numpy as np
    import jax
    import jax.numpy as jnp

    from trlx_tpu.engine import NgramDrafter, RolloutEngine
    from trlx_tpu.models import LMConfig, LMWithValueHead
    from trlx_tpu.ops.sampling import (
        GenerateConfig,
        make_bigram_mask_processor,
        process_logits_default,
    )

    # Perfect-draft case (ISSUE 19 acceptance): the forced-bigram chain makes
    # greedy decode emit exactly (t+1) % V, and the drafter is seeded with
    # THAT transition — every in-budget draft position matches the model, so
    # the verify path's ceiling is measured: ~spec_k fewer dispatches for the
    # same token stream. The non-spec engine on the same workload is the
    # baseline; both must agree with each other token for token. Short rows
    # run 24 tokens = exactly 3 draft windows (eos lands on a window edge),
    # so the perfect drafter's accept rate is exactly 1.0. The model is kept
    # tiny on purpose: CPU decode is FLOP-bound, so speculation's win here is
    # dispatch-overhead amortization — the gauge the probe gates on is the
    # engine's own decode rate (tokens over decode wall), where the 8x
    # dispatch reduction shows as >= 2x even before accelerator memory
    # bandwidth enters the picture.
    V, R, W = 64, 48, 4
    K = 8
    eos, pad = V - 1, 0
    cfg = LMConfig(vocab_size=V, n_layer=2, n_head=2, d_model=64, max_position=64, dtype="float32")
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    params = {"params": model.init(rng, jnp.ones((2, W), jnp.int32), jnp.ones((2, W), jnp.int32))["params"]}
    gcfg = GenerateConfig(max_new_tokens=R, do_sample=False, eos_token_id=eos, pad_token_id=pad)
    forbidden = np.ones((V, V), dtype=bool)
    for i in range(V):
        forbidden[i, (i + 1) % V] = False
    bigram = make_bigram_mask_processor(jnp.asarray(forbidden))

    def proc(logits, state):
        return process_logits_default(bigram(logits, state), gcfg, state["step"])

    # Mixed lengths like decode_engine_probe, scaled so decode dominates
    # prefill: 2 chunks of 8, one straggler (48 steps) + 7 short rows (24
    # steps) per chunk.
    prng = np.random.default_rng(2)
    chunks = []
    for c in range(2):
        ids = prng.integers(1, 40, size=(8, W)).astype(np.int32)
        ids[0, -1] = eos - R
        ids[1:, -1] = eos - 24
        chunks.append((ids, np.ones((8, W), np.int32)))
    total_tokens = 2 * (R + 7 * 24)
    all_ids = np.concatenate([c[0] for c in chunks])
    all_msk = np.concatenate([c[1] for c in chunks])
    order = np.argsort(all_ids[:, -1], kind="stable")

    def run(spec):
        kw = {}
        if spec:
            kw = dict(
                spec_decode="ngram",
                spec_k=K,
                drafter=NgramDrafter(pad, transition=lambda t: (t + 1) % V),
            )
        engine = RolloutEngine(
            model, gcfg, n_slots=8, prompt_width=W, processor=proc,
            prefill_batch=1, steps_per_sync=1, rng=jax.random.PRNGKey(3), **kw,
        )
        engine.update_weights(params, version=0)
        # warm the compiled programs off the clock
        engine.submit(chunks[0][0][:1], chunks[0][1][:1])
        while not engine.idle:
            engine.step()
        # two timed passes, best decode wall kept — jitter in the host loop
        # must not decide a regression gate
        best = None
        for _ in range(2):
            engine.stats(reset=True)
            engine.submit(all_ids[order], all_msk[order])
            episodes = []
            t0 = time.time()
            while not engine.idle:
                episodes.extend(engine.step())
            wall = time.time() - t0
            stats = engine.stats(reset=False)
            if best is None or stats["engine/decode_wall_s"] < best[1]["engine/decode_wall_s"]:
                best = (episodes, stats, wall)
        traces = engine.num_verify_traces if spec else engine.num_decode_traces
        engine.shutdown()
        return best + (traces,)

    base_eps, base_stats, base_s, base_traces = run(spec=False)
    spec_eps, spec_stats, spec_s, spec_traces = run(spec=True)
    base_rate = base_stats["engine/decode_tokens_per_s"]
    spec_rate = spec_stats["engine/decode_tokens_per_s"]

    assert len(base_eps) == len(spec_eps) == 16
    ref = {tuple(e.prompt_ids.tolist()): e for e in base_eps}
    for ep in spec_eps:
        r = ref[tuple(ep.prompt_ids.tolist())]
        assert np.array_equal(ep.response_ids, r.response_ids), "spec/non-spec token mismatch"
        assert np.array_equal(ep.response_mask, r.response_mask), "spec/non-spec mask mismatch"
    assert base_traces == 1 and spec_traces == 1, "decode/verify retraced"
    assert spec_stats["engine/decode_tokens"] == total_tokens
    # the whole point: far fewer device round-trips for the same tokens
    assert spec_stats["engine/decode_dispatches"] < base_stats["engine/decode_dispatches"]
    accept = spec_stats["engine/spec_accept_rate"]
    assert accept == 1.0, f"perfect-draft accept rate {accept:.3f} != 1.0"
    speedup = spec_rate / max(base_rate, 1e-9)
    assert speedup >= 2.0, (
        f"speculative decode {spec_rate:.1f} tok/s is only {speedup:.2f}x the "
        f"non-spec engine {base_rate:.1f} tok/s on the perfect-draft workload"
    )
    return {
        "episodes": len(spec_eps),
        "spec_k": K,
        "accept_rate": round(accept, 3),
        "decode_dispatches": spec_stats["engine/decode_dispatches"],
        "decode_tokens": spec_stats["engine/decode_tokens"],
        "nonspec_decode_dispatches": base_stats["engine/decode_dispatches"],
        "decode_tokens_per_s": round(spec_rate, 1),
        "nonspec_decode_tokens_per_s": round(base_rate, 1),
        "speedup_vs_nonspec": round(speedup, 2),
        "wall_speedup": round(base_s / max(spec_s, 1e-9), 2),
        "seconds": round(base_s + spec_s, 2),
    }


def paged_kv_probe():
    from trlx_tpu.parallel import mesh as mesh_mod

    # Meshless for the same reason as decode_engine_probe: the engine pins
    # its slot state to the process-global mesh left by earlier probes.
    prev_mesh = mesh_mod.peek_mesh()
    mesh_mod.set_mesh(None)
    try:
        return _paged_kv_probe_meshless()
    finally:
        mesh_mod.set_mesh(prev_mesh)


def _paged_kv_probe_meshless():
    import numpy as np
    import jax
    import jax.numpy as jnp

    from trlx_tpu.engine import RolloutEngine
    from trlx_tpu.models import LMConfig, LMWithValueHead
    from trlx_tpu.ops.sampling import (
        GenerateConfig,
        make_bigram_mask_processor,
        process_logits_default,
    )

    # Paged KV + prefix caching (ISSUE 20): a mixed-length workload where
    # every prompt opens with the SAME 64-token template (the RLHF shape:
    # one system/task preamble, per-episode suffix). Three claims, each
    # gated here:
    #   1. parity — the paged engine with prefix caching ON returns
    #      token-for-token the fixed-slot engine's episodes (quant on/off);
    #   2. capacity — the paged pool runs MORE concurrent slots in the SAME
    #      cache bytes: S_paged >= 1.5 x S_fixed with
    #      n_blocks*block_size <= S_fixed*cache_len (same per-token layout,
    #      so token-slots ARE bytes);
    #   3. prefix savings — template blocks prefill ONCE per weight version;
    #      every later admission pins them and dispatches a suffix-only
    #      prefill (64 of 72 prompt tokens skipped per hit).
    # Forced-bigram chain (as in decode_engine_probe) engineers response
    # lengths: one straggler (16 steps) per wave, short rows run 5.
    V, R, W, TPL, BS = 64, 16, 72, 64, 16
    eos, pad = V - 1, 0
    S_FIXED, S_PAGED = 4, 6
    cache_len = W + R  # 88 -> 6 blocks of 16 per slot (kv_len 96)
    POOL_BLOCKS = (S_FIXED * cache_len) // BS  # 22: byte-parity with fixed
    gcfg = GenerateConfig(max_new_tokens=R, do_sample=False, eos_token_id=eos, pad_token_id=pad)
    forbidden = np.ones((V, V), dtype=bool)
    for i in range(V):
        forbidden[i, (i + 1) % V] = False
    bigram = make_bigram_mask_processor(jnp.asarray(forbidden))

    def proc(logits, state):
        return process_logits_default(bigram(logits, state), gcfg, state["step"])

    # 12 rows = 2 waves of 6: shared template, unique 8-token suffixes, the
    # suffix's last token engineering the response length.
    prng = np.random.default_rng(5)
    template = prng.integers(1, 40, size=TPL).astype(np.int32)
    ids = np.tile(template, (12, 1))
    suffix = prng.integers(1, 40, size=(12, W - TPL)).astype(np.int32)
    suffix[:, -1] = eos - 5  # short rows: 5 steps
    suffix[0, -1] = eos - R  # wave stragglers: full 16-step budget
    suffix[6, -1] = eos - R
    ids = np.concatenate([ids, suffix], axis=1)
    msk = np.ones_like(ids)

    def run(quant, paged):
        cfg = LMConfig(
            vocab_size=V, n_layer=2, n_head=2, d_model=64, max_position=128,
            dtype="float32", kv_cache_quant=quant,
        )
        model = LMWithValueHead(cfg)
        params = {"params": model.init(
            jax.random.PRNGKey(0), jnp.ones((2, W), jnp.int32), jnp.ones((2, W), jnp.int32)
        )["params"]}
        kw = dict(paged_kv=True, kv_block_size=BS, kv_pool_blocks=POOL_BLOCKS) if paged else {}
        engine = RolloutEngine(
            model, gcfg, n_slots=S_PAGED if paged else S_FIXED, prompt_width=W,
            processor=proc, prefill_batch=1, steps_per_sync=1,
            rng=jax.random.PRNGKey(3), **kw,
        )
        engine.update_weights(params, version=0)
        # warm the compiled programs off the clock (full-width prefill, the
        # suffix-only prefill shape, and decode)
        engine.submit(ids[:2], msk[:2])
        while not engine.idle:
            engine.step()
        engine.stats(reset=True)
        # pool hit counters are lifetime totals by contract — diff across
        # the timed window so the warm-up's hit does not inflate the claim
        base = {k: v for k, v in engine.stats(reset=False).items()
                if k.endswith("_total")} if paged else {}
        episodes, peak = [], 0
        t0 = time.time()
        engine.submit(ids, msk)
        while not engine.idle:
            episodes.extend(engine.step())
            if paged:
                peak = max(peak, engine.pool.used_blocks())
        wall = time.time() - t0
        stats = engine.stats(reset=False)
        for k, v in base.items():
            stats[k] = stats[k] - v
        if paged:
            engine.abort()  # leak_audit: every pool block accounted for
        engine.shutdown()
        return episodes, stats, peak, wall

    result = {
        "slots_fixed": S_FIXED,
        "slots_paged": S_PAGED,
        "slot_capacity_ratio": round(S_PAGED / S_FIXED, 2),
        "cache_tokens_fixed": S_FIXED * cache_len,
        "cache_tokens_paged": POOL_BLOCKS * BS,
        "block_size": BS,
        "pool_blocks": POOL_BLOCKS,
        "template_tokens": TPL,
    }
    # claim 2 is pure arithmetic — pin it before paying for any run
    assert POOL_BLOCKS * BS <= S_FIXED * cache_len
    assert S_PAGED >= 1.5 * S_FIXED
    t_all = time.time()
    for quant in (False, True):
        fixed_eps, _, _, _ = run(quant, paged=False)
        paged_eps, stats, peak, wall = run(quant, paged=True)
        assert len(fixed_eps) == len(paged_eps) == 12
        ref = {tuple(e.prompt_ids.tolist()): e for e in fixed_eps}
        for ep in paged_eps:
            r = ref[tuple(ep.prompt_ids.tolist())]
            assert np.array_equal(ep.response_ids, r.response_ids), (
                f"paged/fixed token mismatch (quant={quant})"
            )
            assert np.array_equal(ep.response_mask, r.response_mask), (
                f"paged/fixed mask mismatch (quant={quant})"
            )
        # claim 3: the warm-up registered the template at this weight
        # version, so ALL 12 timed admissions hit and skip TPL tokens of
        # prefill each (prefill_batch=1 admits one row per call — even on a
        # cold registry the second admission would see the first's entry).
        hits = stats["engine/prefix_hits_total"]
        saved = stats["engine/prefill_tokens_saved_total"]
        assert hits >= 12, f"prefix hits {hits} < 12 (quant={quant})"
        assert saved >= 12 * TPL, f"prefill tokens saved {saved} < {12 * TPL}"
        assert peak <= POOL_BLOCKS - 1, f"pool peak {peak} blocks overflows"
        frag = stats["engine/pool_frag_frac"]
        assert 0.0 <= frag <= 1.0
        key = "int8" if quant else "fp"
        result[key] = {
            "prefix_hits": int(hits),
            "prefill_tokens_saved": int(saved),
            "prefill_token_reduction": round(saved / float(12 * W), 3),
            "peak_pool_blocks": int(peak),
            "evictions": int(stats["engine/pool_evictions_total"]),
            "decode_tokens_per_s": round(stats["engine/decode_tokens_per_s"], 1),
            "wall_s": round(wall, 2),
        }
    # headline fields for the trajectory fold: worst case over quant modes
    result["prefix_hits_total"] = min(result["fp"]["prefix_hits"], result["int8"]["prefix_hits"])
    result["prefill_token_reduction"] = min(
        result["fp"]["prefill_token_reduction"], result["int8"]["prefill_token_reduction"]
    )
    result["seconds"] = round(time.time() - t_all, 2)
    return result


def fleet_elastic_probe():
    """Elastic fleet transport throughput: episode batches/s through the
    REAL lease ledger + per-worker stream indexes + exactly-once intake
    (trlx_tpu/fleet, RUNBOOK §18), at 1 worker vs 2. Workers are threads
    with a fixed synthetic produce cost standing in for generation — no
    model, no mesh — so the number isolates what the probe is for: the
    claim/append/consume transports must let N workers overlap, not
    serialize them. Intake must stay exactly-once either way."""
    import tempfile
    import threading

    import numpy as np

    from trlx_tpu.fleet import (
        ElasticStreamReader,
        EpisodeStreamWriter,
        FleetPaths,
        LeaseLedger,
        WorkerRegistry,
    )

    UNITS, S, BATCH = 24, 4, 16
    PRODUCE_S = 0.02  # modeled per-unit generation cost (dominates transport)
    cols = {
        "query_tensors": np.ones((BATCH, 8), np.int32),
        "query_mask": np.ones((BATCH, 8), np.int32),
        "response_tensors": np.ones((BATCH, 8), np.int32),
        "response_mask": np.ones((BATCH, 8), np.int32),
    }

    def run_fleet(n_workers: int, root: str) -> float:
        paths = FleetPaths(root=root).ensure_elastic()
        ledger = LeaseLedger(paths.leases_dir, ttl=60.0)
        registry = WorkerRegistry(paths.workers_dir)
        cursor = {"consumed": 0}
        lock = threading.Lock()

        def worker(wid: int):
            registry.register(wid)
            writer = EpisodeStreamWriter(paths, worker=wid)
            while True:
                with lock:
                    consumed = cursor["consumed"]
                if consumed >= UNITS:
                    return
                lease = None
                for unit in range(consumed, min(UNITS, consumed + S + 1)):
                    got = ledger.try_claim(unit, wid)
                    if got is not None:
                        lease = got
                        break
                if lease is None:
                    time.sleep(0.002)
                    continue
                time.sleep(PRODUCE_S)
                writer.append(cols, weight_version=0, unit=lease.unit)
                ledger.complete(lease)

        reader = ElasticStreamReader(paths)
        threads = [
            threading.Thread(target=worker, args=(k,), name=f"smoke-fleet-w{k}", daemon=True)
            for k in range(n_workers)
        ]
        t0 = time.time()
        for t in threads:
            t.start()
        for unit in range(UNITS):
            rec = reader.wait(unit, timeout=30.0, retries=1, backoff=0.1)
            loaded = reader.load(rec)
            assert int(next(iter(loaded.values())).shape[0]) == BATCH
            with lock:
                cursor["consumed"] = unit + 1
        wall = time.time() - t0
        for t in threads:
            t.join(timeout=10)
        assert not any(t.is_alive() for t in threads), "fleet worker thread leaked"
        # Exactly-once: every unit chosen once, zero duplicates (nothing
        # died, so the O_EXCL ledger must have prevented every double claim).
        assert sorted(reader.chosen()) == list(range(UNITS))
        assert reader.duplicates() == 0, f"{reader.duplicates()} duplicate records"
        assert ledger.reclaimed_units() == []
        assert sorted(registry.active()) == list(range(n_workers))
        return wall

    with tempfile.TemporaryDirectory() as tmp:
        wall_1 = run_fleet(1, os.path.join(tmp, "fleet1"))
        wall_2 = run_fleet(2, os.path.join(tmp, "fleet2"))
    rate_1 = UNITS / max(wall_1, 1e-9)
    rate_2 = UNITS / max(wall_2, 1e-9)
    speedup = rate_2 / max(rate_1, 1e-9)
    # 2 workers over a 20ms produce cost should approach 2x; 1.3x is the
    # "transports do not serialize the fleet" floor with CI noise headroom.
    assert speedup > 1.3, (
        f"2-worker elastic fleet {rate_2:.1f} units/s is not ahead of "
        f"1-worker {rate_1:.1f} units/s (speedup {speedup:.2f})"
    )
    return {
        "units": UNITS,
        "episodes_per_batch": BATCH,
        "units_per_s_1worker": round(rate_1, 1),
        "units_per_s_2workers": round(rate_2, 1),
        "episodes_per_s_1worker": round(rate_1 * BATCH, 1),
        "episodes_per_s_2workers": round(rate_2 * BATCH, 1),
        "speedup": round(speedup, 2),
        "seconds": round(wall_1 + wall_2, 2),
    }


def main():
    from trlx_tpu.observability.graftscope import RunManifest

    t0 = time.time()
    # Same crash contract as bench.py: a killed smoke run leaves a
    # line-atomic journal saying which probe it died in.
    manifest = RunManifest(
        os.path.join(REPO, "BENCH_SMOKE_MANIFEST.jsonl"), cmd=" ".join(sys.argv)
    )
    result = {}
    for name, probe in (
        ("kernel", kernel_probe),
        ("rollout", rollout_probe),
        ("overlap", overlap_probe),
        ("fused_loss", fused_loss_probe),
        ("decode_engine", decode_engine_probe),
        ("spec_decode", spec_decode_probe),
        ("paged_kv", paged_kv_probe),
        ("fleet_elastic", fleet_elastic_probe),
    ):
        manifest.heartbeat("probe", candidate=name)
        result[name] = probe()
        manifest.partial(result)
    # An engine speedup number is only meaningful NEXT TO the occupancy it
    # was measured at (a low-occupancy run can "beat" a static batch that
    # padding starved) — the recorded artifact must keep the pair together.
    eng = result["decode_engine"]
    assert {"speedup", "slot_occupancy"} <= set(eng), (
        f"decode_engine record must pair speedup with slot_occupancy: {eng}"
    )
    # Same pairing rule for speculation: a speedup without the accept rate
    # and the dispatch/token split it was achieved at is unreadable.
    spec = result["spec_decode"]
    assert {"speedup_vs_nonspec", "accept_rate", "decode_dispatches", "decode_tokens"} <= set(spec), (
        f"spec_decode record must pair speedup with accept rate + dispatch split: {spec}"
    )
    # A slot-capacity ratio is only meaningful next to the byte budget it
    # was achieved in and the prefix savings that funded it.
    paged = result["paged_kv"]
    assert {"slot_capacity_ratio", "cache_tokens_fixed", "cache_tokens_paged",
            "prefix_hits_total", "prefill_token_reduction"} <= set(paged), (
        f"paged_kv record must pair capacity ratio with bytes + prefix savings: {paged}"
    )
    result["wall_s"] = round(time.time() - t0, 1)
    with open(OUT, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"smoke": "ok", **result}))
    manifest.finish(rc=0)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — CI needs the one-line verdict
        print(json.dumps({"smoke": "FAIL", "error": f"{type(e).__name__}: {e}"}))
        sys.exit(1)
