"""Continuous-batching rollout engine (trlx_tpu/engine).

Unit tier: the width-grouped admission queue, the model's vector
``cache_index`` path (per-slot scatter writes + per-row causal frontier), and
the engine's straggler accounting. Parity tier (the acceptance criterion):
greedy slot decode is token-for-token identical to whole-batch
``make_generate_fn`` decode — mixed bucket widths, mixed response lengths,
slot refill mid-run, ONE compiled decode program. Integration tier (still
fast, CPU): a full PPO run with ``method.rollout_engine`` on trains and tears
down cleanly, and the reward_hang / slow_step fault drills hold through the
engine path (the PR 5 drill, re-run against the new generation machinery).
"""

import json
import os
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402
from trlx_tpu.engine import Episode, RolloutEngine  # noqa: E402
from trlx_tpu.models import LMConfig, LMWithValueHead  # noqa: E402
from trlx_tpu.ops.generate import make_generate_fn  # noqa: E402
from trlx_tpu.ops.sampling import GenerateConfig  # noqa: E402
from trlx_tpu.pipeline.prompt_pipeline import PromptSlotQueue  # noqa: E402


# ------------------------------------------------------------ admission queue


def test_prompt_slot_queue_groups_by_width_fifo():
    q = PromptSlotQueue()
    q.push_rows(np.arange(8).reshape(2, 4), np.ones((2, 4), np.int32))
    q.push_rows(np.arange(18).reshape(3, 6), np.ones((3, 6), np.int32))
    assert len(q) == 5
    # fullest width first
    width, ids, msk = q.pop_group(2)
    assert width == 6 and ids.shape == (2, 6)
    np.testing.assert_array_equal(ids[0], np.arange(6))  # FIFO within width
    # widths tie at 1 vs 2 → width-4 group still drains
    width, ids, _ = q.pop_group(10)
    assert width in (4, 6)
    assert len(q) + ids.shape[0] == 3
    while q.pop_group(10) is not None:
        pass
    assert len(q) == 0 and q.pop_group(1) is None


# ------------------------------------------------------- vector cache_index


def _tiny_model(**overrides):
    cfg = LMConfig(
        vocab_size=23, n_layer=2, n_head=2, d_model=32, max_position=64,
        dtype="float32", **overrides,
    )
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (3, 6), 2, cfg.vocab_size)
    ids = ids.at[0, :2].set(0)
    mask = jnp.ones((3, 6), jnp.int32).at[0, :2].set(0)
    params = {"params": model.init(rng, ids, mask)["params"]}
    return model, params, ids, mask


@pytest.mark.parametrize("quant", [False, True])
def test_vector_cache_index_matches_scalar_per_row(quant):
    """One decode step with a [b] vector cache_index at DIFFERENT per-row
    offsets must equal running each row alone through the scalar path — the
    scatter write, position derivation, and per-row causal frontier all have
    to agree."""
    from trlx_tpu.models.lm import init_cache

    model, params, ids, mask = _tiny_model(kv_cache_quant=quant)
    B, P = ids.shape
    T = P + 4
    # Stagger the rows: row b's sequence ends b positions early, so each row
    # appends its next token at a DIFFERENT offset P - b.
    row_mask = np.array(mask)
    for b in range(B):
        row_mask[b, P - b :] = 0
    grid_mask = jnp.asarray(row_mask)
    cache = init_cache(model.cfg, B, T)
    pre = model.apply(
        params, ids, grid_mask, cache=cache, cache_index=0,
        cache_mask=jnp.zeros((B, T), jnp.int32).at[:, :P].set(grid_mask),
    )
    vec = jnp.asarray([P - b for b in range(B)], jnp.int32)
    tok = jnp.asarray([[5], [7], [9]], jnp.int32)
    step_mask = jnp.ones((B, 1), jnp.int32)

    def cache_mask_for(off):
        cm = np.zeros((B, T), np.int32)
        cm[:, :P] = row_mask
        for b in range(B):
            cm[b, int(off[b])] = 1
        return jnp.asarray(cm)

    out_vec = model.apply(
        params, tok, step_mask, cache=pre["cache"], cache_index=vec,
        cache_mask=cache_mask_for(np.asarray(vec)),
    )
    # Scalar reference: run each row on its own with its scalar offset.
    for b in range(B):
        cache_b = init_cache(model.cfg, 1, T)
        pre_b = model.apply(
            params, ids[b : b + 1], grid_mask[b : b + 1], cache=cache_b,
            cache_index=0,
            cache_mask=jnp.zeros((1, T), jnp.int32).at[:, :P].set(grid_mask[b : b + 1]),
        )
        cm = np.zeros((1, T), np.int32)
        cm[0, :P] = row_mask[b]
        cm[0, int(vec[b])] = 1
        out_b = model.apply(
            params, tok[b : b + 1], step_mask[b : b + 1], cache=pre_b["cache"],
            cache_index=int(vec[b]), cache_mask=jnp.asarray(cm),
        )
        np.testing.assert_allclose(
            np.asarray(out_vec["logits"][b]), np.asarray(out_b["logits"][0]),
            rtol=1e-5, atol=1e-5,
        )
    # and the scatter landed where the scalar path would have put it
    leaf_vec = out_vec["cache"][0][0]
    leaf_pre = pre["cache"][0][0]
    for b in range(B):
        w = int(vec[b])
        assert not np.allclose(
            np.asarray(leaf_vec[b, w]), np.asarray(leaf_pre[b, w])
        ), f"row {b}: no KV written at its offset {w}"
        # untouched past the write offset
        np.testing.assert_array_equal(
            np.asarray(leaf_vec[b, w + 1 :]), np.asarray(leaf_pre[b, w + 1 :])
        )


@pytest.mark.parametrize("quant", [False, True])
def test_vector_cache_index_multi_token_window_matches_sequential(quant):
    """Spec-verify substrate: a K-token query with a [b] vector cache_index at
    STAGGERED per-row offsets must equal feeding the same K tokens one at a
    time through the single-token vector path — logits at every window
    position and every KV write bit-for-bit."""
    from trlx_tpu.models.lm import init_cache

    model, params, ids, mask = _tiny_model(kv_cache_quant="int8" if quant else None)
    B, P = ids.shape
    K = 3
    T = P + K + 2
    row_mask = np.array(mask)
    for b in range(B):
        row_mask[b, P - b :] = 0
    grid_mask = jnp.asarray(row_mask)

    def prefilled():
        cache = init_cache(model.cfg, B, T)
        return model.apply(
            params, ids, grid_mask, cache=cache, cache_index=0,
            cache_mask=jnp.zeros((B, T), jnp.int32).at[:, :P].set(grid_mask),
        )["cache"]

    wp = np.array([P - b for b in range(B)], np.int64)
    window = np.array([[5, 7, 9], [9, 5, 7], [7, 9, 5]], np.int32)

    def cm_for(extent):
        cm = np.zeros((B, T), np.int32)
        cm[:, :P] = row_mask
        for b in range(B):
            cm[b, int(wp[b]) : int(wp[b]) + int(extent[b])] = 1
        return jnp.asarray(cm)

    # one K-wide dispatch: cache_mask covers the whole window up front, as the
    # engine's verify program does before it knows the accepted length
    out_w = model.apply(
        params, jnp.asarray(window), jnp.ones((B, K), jnp.int32),
        cache=prefilled(), cache_index=jnp.asarray(wp, jnp.int32),
        cache_mask=cm_for(np.full(B, K)),
    )
    # sequential reference: same tokens one at a time through the proven path
    cache = prefilled()
    seq_logits = []
    for j in range(K):
        out_j = model.apply(
            params, jnp.asarray(window[:, j : j + 1]), jnp.ones((B, 1), jnp.int32),
            cache=cache, cache_index=jnp.asarray(wp + j, jnp.int32),
            cache_mask=cm_for(np.full(B, j + 1)),
        )
        cache = out_j["cache"]
        seq_logits.append(np.asarray(out_j["logits"][:, 0]))

    for j in range(K):
        np.testing.assert_allclose(
            np.asarray(out_w["logits"][:, j]), seq_logits[j], rtol=1e-5, atol=1e-5
        )
    # Layer-1 KVs carry reduction-order noise (3-query vs 1-query einsum), and
    # int8 codes may flip one ulp when a scale wobbles — tolerance, not equal.
    for leaf_w, leaf_s in zip(jax.tree.leaves(out_w["cache"]), jax.tree.leaves(cache)):
        lw, ls = np.asarray(leaf_w), np.asarray(leaf_s)
        if np.issubdtype(lw.dtype, np.integer):
            assert np.abs(lw.astype(np.int32) - ls.astype(np.int32)).max() <= 1
        else:
            np.testing.assert_allclose(lw, ls, rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------- greedy parity


def _mixed_prompts(vocab=23, seed=3):
    """Unique prompts at two bucket widths, one row left-padded."""
    rng = np.random.default_rng(seed)
    w6 = rng.integers(2, vocab, size=(3, 6)).astype(np.int32)
    m6 = np.ones((3, 6), np.int32)
    w6[0, :2] = 0
    m6[0, :2] = 0
    w4 = rng.integers(2, vocab, size=(3, 4)).astype(np.int32)
    m4 = np.ones((3, 4), np.int32)
    return (w6, m6), (w4, m4)


def _reference_episodes(model, params, gcfg, groups):
    """Whole-batch greedy decode per width group → prompt-keyed episodes."""
    ref = {}
    for ids, msk in groups:
        gen = make_generate_fn(model, gcfg)
        toks, m = gen(params, jnp.asarray(ids), jnp.asarray(msk), jax.random.PRNGKey(1))
        toks, m = np.asarray(toks), np.asarray(m)
        P = ids.shape[1]
        for b in range(ids.shape[0]):
            key = (tuple(ids[b].tolist()), tuple(msk[b].tolist()))
            ref[key] = (toks[b, P:], m[b, P:])
    return ref


@pytest.mark.parametrize("quant", [False, True])
def test_engine_greedy_parity_token_for_token(quant):
    """THE acceptance test: per-slot decode == whole-batch decode, token for
    token and mask bit for mask bit, across mixed bucket widths and natural
    mixed response lengths — with fewer slots than prompts, so refill
    mid-run is exercised, and with exactly ONE compiled decode program."""
    model, params, _, _ = _tiny_model(kv_cache_quant=quant)
    (w6, m6), (w4, m4) = _mixed_prompts()
    # Pick an eos the greedy decode emits at DIFFERENT depths across rows, so
    # response lengths are naturally mixed (per-row first occurrence decides
    # where each row stops once it becomes the eos).
    free = GenerateConfig(max_new_tokens=8, do_sample=False, eos_token_id=None, pad_token_id=0)
    first_at = {}
    for ids, msk in [(w6, m6), (w4, m4)]:
        toks, _ = make_generate_fn(model, free)(
            params, jnp.asarray(ids), jnp.asarray(msk), jax.random.PRNGKey(1)
        )
        for row in np.asarray(toks)[:, ids.shape[1] :]:
            seen = {}
            for step, t in enumerate(row.tolist()):
                seen.setdefault(int(t), step)
            for t, step in seen.items():
                first_at.setdefault(t, set()).add(step)
    eos = max(first_at, key=lambda t: len(first_at[t]))
    assert len(first_at[eos]) >= 2, "tiny model emitted no repeat token — reseed"
    gcfg = GenerateConfig(max_new_tokens=8, do_sample=False, eos_token_id=eos, pad_token_id=0)
    ref = _reference_episodes(model, params, gcfg, [(w6, m6), (w4, m4)])

    engine = RolloutEngine(
        model, gcfg, n_slots=4, prompt_width=6,
        prefill_batch=2, steps_per_sync=3, rng=jax.random.PRNGKey(2),
    )
    engine.update_weights(params, version=7)
    engine.submit(w6, m6)
    engine.submit(w4, m4)
    assert engine.pending == 6

    episodes = []
    for _ in range(200):
        episodes.extend(engine.step())
        if engine.idle:
            break
    assert len(episodes) == 6
    assert engine.num_decode_traces == 1, "decode retraced: slot lengths leaked into shapes"

    for ep in episodes:
        assert isinstance(ep, Episode) and ep.weight_version == 7
        key = (tuple(ep.prompt_ids.tolist()), tuple(ep.prompt_mask.tolist()))
        rtoks, rmask = ref[key]
        np.testing.assert_array_equal(ep.response_ids, rtoks)
        np.testing.assert_array_equal(ep.response_mask, rmask)
        assert ep.decode_steps == int(rmask.sum())

    # mixed lengths actually happened (otherwise this test proves nothing)
    lens = sorted(ep.decode_steps for ep in episodes)
    assert lens[0] < lens[-1]

    stats = engine.stats(reset=False)
    assert 0.0 < stats["engine/slot_occupancy"] <= 1.0
    assert stats["engine/refills"] == 6
    assert stats["engine/completed"] == 6
    assert stats["engine/gen_tokens"] == sum(lens)
    assert stats["engine/decode_tokens_per_s"] > 0
    # stats window resets on read
    engine.stats(reset=True)
    assert engine.stats(reset=False)["engine/completed"] == 0
    engine.shutdown()
    assert engine.idle


def test_engine_straggler_accounting_under_early_exit():
    """Satellite: per-episode decode_steps must SUM to the engine's generated
    tokens, and the chunked-path helper's per-episode view must reconcile
    with its whole-batch step count (max row) — the straggler gap both paths
    report."""
    from trlx_tpu.trainer.base import JaxBaseTrainer

    # chunked helper on an early-exited mask: rows used 2/4/1 of a 6 budget
    mask_h = np.zeros((3, 5 + 6), np.int32)
    mask_h[:, :5] = 1
    mask_h[0, 5:7] = 1
    mask_h[1, 5:9] = 1
    mask_h[2, 5:6] = 1
    ds = JaxBaseTrainer.rollout_decode_stats(mask_h, 5)
    assert ds["episode_steps"].tolist() == [2, 4, 1]
    assert int(ds["episode_steps"].sum()) == ds["gen_tokens"] == 7
    assert ds["decode_steps"] == 4  # whole batch PAID the longest row
    assert ds["decode_step_budget"] == 6

    # engine side: same identity from the slot lengths
    model, params, _, _ = _tiny_model()
    gcfg = GenerateConfig(max_new_tokens=6, do_sample=False, eos_token_id=None, pad_token_id=0)
    engine = RolloutEngine(model, gcfg, n_slots=2, prompt_width=4, prefill_batch=2)
    engine.update_weights(params)
    rng = np.random.default_rng(0)
    engine.submit(rng.integers(2, 23, size=(4, 4)).astype(np.int32), np.ones((4, 4), np.int32))
    eps = []
    while not engine.idle:
        eps.extend(engine.step())
    assert sum(e.decode_steps for e in eps) == engine.stats()["engine/gen_tokens"]
    engine.shutdown()


# ----------------------------------------------- in-flight weight updates


def _drain(engine):
    episodes = []
    for _ in range(200):
        episodes.extend(engine.step())
        if engine.idle:
            break
    return episodes


def test_mid_decode_update_splits_episodes_at_the_sync_boundary():
    """THE in-flight acceptance test: update_weights between sync points —
    slots mid-decode, no drain, no abort — is adopted at the NEXT
    steps_per_sync boundary, and every harvested Episode carries the exact
    per-token split. steps_per_sync=3 and max_new_tokens=6 with no eos pin
    the arithmetic: one step() generates exactly 3 tokens, so a push after
    the first step must split every episode [(v1, 3), (v2, 3)]. Pushing the
    SAME params under a new version number also proves the swap itself is
    token-neutral: the decode output is unchanged vs an uninterrupted run."""
    model, params, _, _ = _tiny_model()
    gcfg = GenerateConfig(max_new_tokens=6, do_sample=False, eos_token_id=None, pad_token_id=0)
    prompts = np.random.default_rng(5).integers(2, 23, size=(2, 4)).astype(np.int32)
    pmask = np.ones((2, 4), np.int32)

    ref_engine = RolloutEngine(
        model, gcfg, n_slots=2, prompt_width=4, prefill_batch=2,
        steps_per_sync=3, rng=jax.random.PRNGKey(2),
    )
    ref_engine.update_weights(params, version=1)
    ref_engine.submit(prompts, pmask)
    ref = {tuple(e.prompt_ids.tolist()): e for e in _drain(ref_engine)}
    ref_engine.shutdown()

    engine = RolloutEngine(
        model, gcfg, n_slots=2, prompt_width=4, prefill_batch=2,
        steps_per_sync=3, rng=jax.random.PRNGKey(2),
    )
    engine.update_weights(params, version=1)
    engine.submit(prompts, pmask)
    eps = engine.step()
    assert eps == []  # 3 of 6 tokens decoded: nothing finished yet
    # slots are mid-decode RIGHT NOW — push without draining or aborting
    engine.update_weights(params, version=2)
    states = engine.slot_states()
    assert [s["n_gen"] for s in states] == [3, 3]  # positions from the sync
    episodes = _drain(engine)
    assert len(episodes) == 2
    for ep in episodes:
        assert ep.version_spans == [(1, 3), (2, 3)]
        assert ep.weight_version == 2  # tagged with the LAST version
        assert ep.decode_steps == 6
        r = ref[tuple(ep.prompt_ids.tolist())]
        np.testing.assert_array_equal(ep.response_ids, r.response_ids)
        np.testing.assert_array_equal(ep.response_mask, r.response_mask)
    stats = engine.stats(reset=False)
    assert stats["engine/weight_switches"] == 1
    assert stats["engine/switches_coalesced"] == 0
    engine.shutdown()


def test_push_storm_coalesces_to_latest_and_same_version_is_a_noop():
    """version_switch_storm contract: N pushes between two sync points adopt
    ONCE, at the latest version — the queue never forms. And re-pushing the
    version the engine already holds records no switch at all (the
    phase-boundary handoff path stays span-free and byte-identical)."""
    model, params, _, _ = _tiny_model()
    gcfg = GenerateConfig(max_new_tokens=6, do_sample=False, eos_token_id=None, pad_token_id=0)
    engine = RolloutEngine(
        model, gcfg, n_slots=2, prompt_width=4, prefill_batch=2,
        steps_per_sync=3, rng=jax.random.PRNGKey(2),
    )
    engine.update_weights(params, version=1)
    engine.submit(np.full((2, 4), 3, np.int32), np.ones((2, 4), np.int32))
    engine.step()
    # the storm: three pushes before the next sync boundary
    engine.update_weights(params, version=2)
    engine.update_weights(params, version=3)
    engine.update_weights(params, version=4)
    episodes = _drain(engine)
    assert all(ep.version_spans == [(1, 3), (4, 3)] for ep in episodes)
    stats = engine.stats(reset=False)
    assert stats["engine/weight_switches"] == 1  # one adoption, not three
    assert stats["engine/switches_coalesced"] == 2  # v2 and v3 never ran

    # same-version re-push mid-decode: staged, adopted, but NO switch
    engine.submit(np.full((2, 4), 5, np.int32), np.ones((2, 4), np.int32))
    engine.step()
    engine.update_weights(params, version=4)
    episodes = _drain(engine)
    assert all(ep.version_spans == [(4, 6)] for ep in episodes)
    assert engine.stats(reset=False)["engine/weight_switches"] == 1
    engine.shutdown()


def test_schedule_fingerprint_is_deterministic_and_order_sensitive():
    """The slot-schedule crc: identical configs + identical submissions make
    identical fingerprints (the multi-host lockstep invariant
    verify_engine_schedule checks by allgather), and a reordered admission
    stream makes a DIFFERENT one (so a desynced host cannot collide)."""
    model, params, _, _ = _tiny_model()
    (w6, m6), (w4, m4) = _mixed_prompts()
    gcfg = GenerateConfig(max_new_tokens=4, do_sample=False, eos_token_id=None, pad_token_id=0)

    def run(order):
        engine = RolloutEngine(
            model, gcfg, n_slots=2, prompt_width=6, prefill_batch=2,
            steps_per_sync=2, rng=jax.random.PRNGKey(2),
        )
        engine.update_weights(params, version=1)
        for ids, msk in order:
            engine.submit(ids, msk)
        _drain(engine)
        crc = engine.schedule_fingerprint()
        engine.shutdown()
        return crc

    a = run([(w6, m6), (w4, m4)])
    b = run([(w6, m6), (w4, m4)])
    c = run([(w4, m4), (w6, m6)])
    assert a == b
    assert a != c
    assert 0 <= a <= 0xFFFFFFFF


@pytest.mark.parametrize("kv_quant", [False, True])
def test_engine_int8_decode_parity(kv_quant):
    """Satellite: the engine decodes with the int8 weight copies (the qw
    collection riding in the update_weights variables) token-for-token
    identically to whole-batch make_generate_fn decode with the SAME
    variables — the engine adds no numeric skew on top of W8A16 itself."""
    from trlx_tpu.models.lm import quantize_weights

    model, params, _, _ = _tiny_model(kv_cache_quant=kv_quant)
    variables = {"params": params["params"], "qw": quantize_weights(params["params"])}
    (w6, m6), (w4, m4) = _mixed_prompts()
    gcfg = GenerateConfig(max_new_tokens=6, do_sample=False, eos_token_id=None, pad_token_id=0)
    ref = _reference_episodes(model, variables, gcfg, [(w6, m6), (w4, m4)])

    engine = RolloutEngine(
        model, gcfg, n_slots=3, prompt_width=6, prefill_batch=3,
        steps_per_sync=2, rng=jax.random.PRNGKey(2),
    )
    engine.update_weights(variables, version=1)
    engine.submit(w6, m6)
    engine.submit(w4, m4)
    episodes = _drain(engine)
    assert len(episodes) == 6
    for ep in episodes:
        key = (tuple(ep.prompt_ids.tolist()), tuple(ep.prompt_mask.tolist()))
        rtoks, rmask = ref[key]
        np.testing.assert_array_equal(ep.response_ids, rtoks)
        np.testing.assert_array_equal(ep.response_mask, rmask)
    engine.shutdown()


def test_engine_requires_weight_handoff_and_bounds_prompt_width():
    model, params, _, _ = _tiny_model()
    gcfg = GenerateConfig(max_new_tokens=4, do_sample=False, pad_token_id=0)
    engine = RolloutEngine(model, gcfg, n_slots=2, prompt_width=4)
    with pytest.raises(RuntimeError, match="update_weights"):
        engine.step()
    with pytest.raises(ValueError, match="prompt width"):
        engine.submit(np.ones((1, 9), np.int32), np.ones((1, 9), np.int32))
    engine.update_weights(params)
    assert engine.step() == []  # empty queue: a no-op, not an error
    engine.shutdown()


def test_sanitizer_catches_unlocked_engine_dispatch(monkeypatch):
    """TRLX_TPU_SANITIZE=dispatch acceptance: an intentionally unlocked
    decode dispatch from a trlx-* worker thread raises DispatchLockViolation
    naming the program, while the engine's own locked dispatches still run."""
    from trlx_tpu.utils import sanitize

    monkeypatch.setenv(sanitize.ENV_VAR, "dispatch")
    try:
        lock = sanitize.make_dispatch_lock()
        assert isinstance(lock, sanitize.SanitizedDispatchLock)
        model, params, _, _ = _tiny_model()
        gcfg = GenerateConfig(max_new_tokens=3, do_sample=False, pad_token_id=0)
        engine = RolloutEngine(
            model, gcfg, n_slots=2, prompt_width=4, dispatch_lock=lock
        )
        engine.update_weights(params)
        engine.submit(np.ones((1, 4), np.int32), np.ones((1, 4), np.int32))
        assert engine.step() is not None  # locked path works under the sanitizer

        errors = []

        def rogue():
            try:
                # the PR 5 bug, replayed on purpose: dispatch without the lock
                engine._decode(engine._variables, engine._state)
            except sanitize.DispatchLockViolation as e:
                errors.append(e)

        t = threading.Thread(target=rogue, name="trlx-rogue-dispatcher")
        t.start()
        t.join()
        assert len(errors) == 1 and "engine/decode" in str(errors[0])
        engine.shutdown()
    finally:
        monkeypatch.delenv(sanitize.ENV_VAR, raising=False)
        sanitize.refresh()


def test_sanitizer_catches_donated_weight_handoff(monkeypatch):
    """TRLX_TPU_SANITIZE=donation acceptance: handing the engine a tree that
    was donated to a jitted program fails at update_weights with the donation
    site, instead of a deleted-array error mid-decode."""
    from trlx_tpu.utils import sanitize

    monkeypatch.setenv(sanitize.ENV_VAR, "donation")
    try:
        sanitize.refresh()
        model, params, _, _ = _tiny_model()
        gcfg = GenerateConfig(max_new_tokens=3, do_sample=False, pad_token_id=0)
        engine = RolloutEngine(model, gcfg, n_slots=2, prompt_width=4)
        sanitize.mark_donated(params, "train_step(state) [drill]")
        with pytest.raises(sanitize.DonatedBufferRead, match="train_step"):
            engine.update_weights(params)
        engine.shutdown()
    finally:
        monkeypatch.delenv(sanitize.ENV_VAR, raising=False)
        sanitize.refresh()
        sanitize.clear_donated()


# ------------------------------------------------------------ e2e acceptance


@pytest.fixture(scope="module")
def task():
    return generate_random_walks(n_nodes=15, max_length=8, n_walks=60, seed=1000)


def _run_ppo(task, ckpt_dir, **method_overrides):
    _, logit_mask, metric_fn, reward_fn = task
    config = base_config("ppo", 15, 8)
    config.train.total_steps = 8
    config.train.epochs = 4
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.checkpoint_dir = str(ckpt_dir)
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    for k, v in method_overrides.items():
        setattr(config.method, k, v)
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[1]],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    with open(os.path.join(str(ckpt_dir), "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    return model, records


def test_ppo_with_rollout_engine_trains_and_tears_down(task, tmp_path, monkeypatch):
    # Fully-armed sanitizer: the engine e2e doubles as the dispatch-lock,
    # donation, AND race (lockset) acceptance run — the engine migrates
    # between the producer thread (per-phase) and the main thread (teardown),
    # so every update_weights/shutdown handoff must keep the tracker clean.
    from trlx_tpu.utils import sanitize

    monkeypatch.setenv(sanitize.ENV_VAR, "dispatch,donation,race")
    try:
        model, records = _run_ppo(
            task, tmp_path / "eng", rollout_engine=True, engine_slots=8,
            prefill_batch=4, engine_steps_per_sync=4,
        )
    finally:
        monkeypatch.delenv(sanitize.ENV_VAR, raising=False)
        sanitize.refresh()
        sanitize.clear_donated()
        sanitize.clear_races()
    losses = [r["loss"] for r in records if "loss" in r]
    assert len(losses) == 8 and all(np.isfinite(losses))
    # engine gauges flowed to the tracker
    occ = [r["engine/slot_occupancy"] for r in records if "engine/slot_occupancy" in r]
    assert occ and all(0.0 < o <= 1.0 for o in occ)
    assert any("engine/refills" in r for r in records)
    assert any("exp_decode_steps_per_episode" in r for r in records)
    # learn()'s finally tore the engine down; no threads leaked
    assert model._rollout_engine is None
    assert not any(t.name.startswith("trlx-") for t in threading.enumerate())


def test_rollout_engine_config_validation(task):
    """The engine+decode_weight_quant guard is LIFTED (the unfused scoring
    delta is bounded by test_engine_int8_decode_parity): construction
    succeeds and both the engine and the int8 copies are armed. Without the
    engine, int8 decode still demands the fused-stats path."""
    from trlx_tpu.trainer.ppo import PPOTrainer

    _, logit_mask, _, _ = task
    config = base_config("ppo", 15, 8)
    config.train.batch_size = 16
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    config.method.rollout_engine = True
    config.model.decode_weight_quant = True
    trainer = PPOTrainer(config, logit_mask=logit_mask)
    assert trainer.rollout_engine_enabled and trainer._qw is not None
    # the engine's versioned handoff payload carries the int8 copies too
    assert "qw" in trainer.rollout_engine_variables()
    trainer._shutdown_experience_pipeline()

    config = base_config("ppo", 15, 8)
    config.train.batch_size = 16
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    config.model.decode_weight_quant = True
    config.method.fused_rollout_stats = False  # no fused path, no engine
    with pytest.raises(ValueError, match="decode_weight_quant"):
        PPOTrainer(config, logit_mask=logit_mask)


# ---------------------------------------------------------------- fault drill


def test_reward_hang_through_engine_path_drains_cleanly(task, tmp_path, monkeypatch):
    """TRLX_TPU_FAULTS=reward_hang against _make_experience_engine: the hang
    watchdog fires, the error surfaces from make_experience, and nothing
    leaks — then with retries restored the SAME injected hang is absorbed
    and the store fills completely (mirror of the PR 5 drill)."""
    from trlx_tpu.orchestrator.ppo_orchestrator import PPOOrchestrator
    from trlx_tpu.pipeline.ppo_pipeline import PPORolloutStorage
    from trlx_tpu.pipeline.prompt_pipeline import PromptPipeline
    from trlx_tpu.trainer.ppo import PPOTrainer

    monkeypatch.setenv("TRLX_TPU_FAULTS", "reward_hang@1")
    _, logit_mask, metric_fn, reward_fn = task
    config = base_config("ppo", 15, 8)
    config.train.checkpoint_dir = str(tmp_path / "ck")
    config.train.batch_size = 16
    config.train.reward_fn_timeout = 0.2
    config.train.reward_fn_retries = 0
    config.train.reward_fn_backoff = 0.0
    config.method.num_rollouts = 32
    config.method.chunk_size = 16
    config.method.rollout_engine = True
    config.method.engine_slots = 8
    trainer = PPOTrainer(config, reward_fn=reward_fn, metric_fn=metric_fn, logit_mask=logit_mask)
    assert trainer.rollout_engine_enabled

    pipeline = PromptPipeline([[1]] * 32, tokenizer=None, max_prompt_length=1)
    orch = PPOOrchestrator(trainer, pipeline, reward_fn, chunk_size=16)
    with pytest.raises(TimeoutError, match="still running"):
        orch.make_experience(num_rollouts=32)
    assert not any(t.name.startswith("trlx-") for t in threading.enumerate())

    # with retries restored the SAME injected hang is absorbed
    monkeypatch.setenv("TRLX_TPU_FAULTS", "reward_hang@3")
    from trlx_tpu.resilience import FaultPlan

    trainer.fault_plan = FaultPlan.from_env_or_config("")
    trainer.config.train.reward_fn_retries = 2
    store = PPORolloutStorage(pad_token_id=trainer.pad_token_id, record_staleness=True)
    orch.make_experience(num_rollouts=32, store=store, staleness=1)
    assert len(store) == 32
    assert all(f.fired for f in trainer.fault_plan.faults)
    g = store._buffer.gather(np.arange(32))
    assert np.all(g["staleness"] == 1.0)
    # the engine drained: nothing queued, nothing live, ready for next phase
    assert trainer.rollout_engine().idle
    trainer._shutdown_experience_pipeline()
    assert trainer._rollout_engine is None


def test_slow_step_with_engine_completes_and_captures(task, tmp_path, monkeypatch):
    """TRLX_TPU_FAULTS=slow_step through a full engine-path run: the anomaly
    detector's CPU drill must not interact badly with the engine (the stall
    sits between train dispatch and the log sync) — the run completes and
    shutdown is clean."""
    monkeypatch.setenv("TRLX_TPU_FAULTS", "slow_step@4")
    monkeypatch.setenv("TRLX_TPU_SLOW_STEP_SECONDS", "0.2")
    model, records = _run_ppo(task, tmp_path / "slow", rollout_engine=True, engine_slots=8)
    losses = [r["loss"] for r in records if "loss" in r]
    assert len(losses) == 8
    assert model._rollout_engine is None
    assert not any(t.name.startswith("trlx-") for t in threading.enumerate())


# ----------------------------------------------------- slot attention (kernel)


@pytest.mark.slow
def test_slot_decode_attention_interpret_matches_einsum():
    """slot_decode_attention: the slot-mask → bias-row shim over the
    flash-decode kernel handles per-slot ragged lengths (interpret mode)."""
    from trlx_tpu.ops.decode_attention import slot_decode_attention

    rng = np.random.default_rng(0)
    B, T, h, d = 2, 64, 2, 128
    q = rng.normal(size=(B, h, d)).astype(np.float32)
    k = rng.normal(size=(B, T, h, d)).astype(np.float32)
    v = rng.normal(size=(B, T, h, d)).astype(np.float32)
    slot_mask = np.zeros((B, T), np.int32)
    slot_mask[0, :10] = 1  # slot 0: 10 valid positions
    slot_mask[1, :37] = 1  # slot 1: 37 — ragged vs any block size
    out = slot_decode_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), None, None,
        jnp.asarray(slot_mask), scale=0.125, interpret=True,
    )
    bias = np.where(slot_mask.astype(bool), 0.0, -1e9).astype(np.float32)
    scores = np.einsum("bhd,bkhd->bhk", q, k) * 0.125 + bias[:, None, :]
    probs = jax.nn.softmax(jnp.asarray(scores), axis=-1)
    ref = np.einsum("bhk,bkhd->bhd", np.asarray(probs), v)
    np.testing.assert_allclose(np.asarray(out[:, 0]), ref, rtol=2e-5, atol=2e-5)


# ------------------------------------------------- slot timeline (graftscope)


def test_engine_slot_timeline_events_ordered_and_rolled_up(tmp_path):
    """PR 12: with graftscope + spans armed, every slot episode leaves an
    admit instant, a harvest instant, and an ``engine/slot`` span — strictly
    alternating admit/harvest per slot — and the scope rolls refill waits and
    per-slot occupancy up for /metrics and graftscope.json."""
    from trlx_tpu.observability import graftscope as obs_graftscope
    from trlx_tpu.observability import spans as obs_spans

    scope = obs_graftscope.configure()
    spans_path = str(tmp_path / "spans.jsonl")
    obs_spans.configure(spans_path)
    try:
        model, params, _, _ = _tiny_model()
        (w6, m6), (w4, m4) = _mixed_prompts()
        gcfg = GenerateConfig(
            max_new_tokens=6, do_sample=False, eos_token_id=None, pad_token_id=0
        )
        engine = RolloutEngine(
            model, gcfg, n_slots=2, prompt_width=6, prefill_batch=2, steps_per_sync=2
        )
        engine.update_weights(params)
        engine.submit(w6, m6)
        engine.submit(w4, m4)
        episodes = []
        while not engine.idle:
            episodes.extend(engine.step())
        engine.shutdown()
        assert len(episodes) == 6

        gauges = scope.window()
        samples = scope.drain_samples()
        snap = scope.snapshot()
    finally:
        obs_spans.shutdown()
        obs_graftscope.shutdown()

    events = obs_spans.read_spans(spans_path)
    slot_spans = [e for e in events if e["ph"] == "X" and e["name"] == "engine/slot"]
    admits = [e for e in events if e["ph"] == "i" and e["name"] == "engine/slot/admit"]
    harvests = [
        e for e in events if e["ph"] == "i" and e["name"] == "engine/slot/harvest"
    ]
    assert len(slot_spans) == 6 and len(admits) == 6 and len(harvests) == 6

    # per-slot lifecycle ordering: admit and harvest strictly alternate
    slots = {e["args"]["slot"] for e in admits}
    assert slots == {0, 1}
    for slot in slots:
        timeline = sorted(
            [(e["ts"], "admit") for e in admits if e["args"]["slot"] == slot]
            + [(e["ts"], "harvest") for e in harvests if e["args"]["slot"] == slot]
        )
        kinds = [k for _, k in timeline]
        assert kinds == ["admit", "harvest"] * (len(kinds) // 2), (slot, kinds)
    for e in slot_spans:
        assert e["dur"] >= 0
        assert e["args"]["steps"] >= 1 and e["args"]["width"] in (4, 6)

    # rollups: 2 first admissions wait for nothing, the 4 refills are timed
    assert len(samples["refill_wait_ms"]) == 4
    assert all(w >= 0.0 for w in samples["refill_wait_ms"])
    assert "engine/refill_wait_ms_p50" in gauges
    assert set(samples["straggler_steps"]) <= {4, 6}
    assert sum(row["episodes"] for row in snap["slots"]) == 6
    assert all(row["busy_s"] >= 0.0 for row in snap["slots"])
    assert {row["slot"] for row in snap["slots"]} == {0, 1}


# --------------------------------------------------------- paged KV cache


def _paged_prompts():
    """7 width-6 rows, two of which duplicate row 0 exactly (ids AND mask)
    — the prefix-cache hit candidates at kv_block_size=4."""
    rng = np.random.default_rng(0)
    prompts = rng.integers(2, 23, size=(7, 6)).astype(np.int32)
    pmask = np.ones((7, 6), np.int32)
    prompts[1, :2] = 0
    pmask[1, :2] = 0
    prompts[5] = prompts[0]
    prompts[6] = prompts[0]
    return prompts, pmask


def _paged_pair(quant, *, spec="", paged_kwargs=None):
    """A (fixed, paged) engine pair over the same tiny model/weights."""
    cfg = LMConfig(
        vocab_size=23, n_layer=2, n_head=2, d_model=32, max_position=96,
        dtype="float32", kv_cache_quant=quant,
    )
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (3, 6), 2, cfg.vocab_size)
    params = {"params": model.init(rng, ids, jnp.ones((3, 6), jnp.int32))["params"]}
    gcfg = GenerateConfig(
        max_new_tokens=7, do_sample=False, pad_token_id=0, eos_token_id=1
    )
    kw = dict(n_slots=3, prompt_width=6, prefill_batch=2, steps_per_sync=3)
    if spec:
        kw.update(spec_decode=spec, spec_k=3)
    # fresh rng arrays per engine: decode donates the slot state, and the
    # key rides in it — a shared array would be deleted under the 2nd engine
    fixed = RolloutEngine(model, gcfg, **kw, rng=jax.random.PRNGKey(2))
    paged = RolloutEngine(
        model, gcfg, **kw, rng=jax.random.PRNGKey(2), paged_kv=True,
        **(paged_kwargs or {"kv_block_size": 4}),
    )
    for e in (fixed, paged):
        e.update_weights(params, version=1)
    return fixed, paged


@pytest.mark.parametrize("quant", [False, True])
def test_paged_engine_token_parity_with_prefix_hits(quant):
    """The tentpole acceptance: the paged engine with prefix caching ON is
    token-for-token identical to the fixed-slot engine on a mixed workload
    with duplicate prompts, int8 KV on and off — and actually HITS (the dup
    rows skip their shared prefix's prefill), with a clean pool at the end."""
    prompts, pmask = _paged_prompts()
    fixed, paged = _paged_pair(quant)
    for e in (fixed, paged):
        e.submit(prompts, pmask)
    ref = {tuple(x.prompt_ids.tolist()): x for x in _drain(fixed)}
    got = {tuple(x.prompt_ids.tolist()): x for x in _drain(paged)}
    assert set(ref) == set(got)
    for k in ref:
        np.testing.assert_array_equal(ref[k].response_ids, got[k].response_ids)
        np.testing.assert_array_equal(ref[k].response_mask, got[k].response_mask)
    assert paged.num_decode_traces == 1, "paged decode retraced"
    st = paged.stats()
    assert st["engine/prefix_hits_total"] >= 1
    assert st["engine/prefill_tokens_saved_total"] >= 4
    assert 0.0 <= st["engine/pool_frag_frac"] <= 1.0
    paged.pool.leak_audit(expect_idle=True)
    fixed.shutdown()
    paged.shutdown()


def test_paged_engine_spec_decode_parity():
    """Satellite: paged_kv composes with spec_decode — the verify windows
    write through the block table (scratch tail in the slot's last block)
    and greedy output stays token-for-token equal to the non-paged spec
    engine."""
    prompts, pmask = _paged_prompts()
    fixed, paged = _paged_pair(False, spec="ngram")
    for e in (fixed, paged):
        e.submit(prompts, pmask)
    ref = {tuple(x.prompt_ids.tolist()): x for x in _drain(fixed)}
    got = {tuple(x.prompt_ids.tolist()): x for x in _drain(paged)}
    assert set(ref) == set(got)
    for k in ref:
        np.testing.assert_array_equal(ref[k].response_ids, got[k].response_ids)
    assert paged.num_verify_traces == 1, "paged verify retraced"
    # the scratch tail rounded INTO the last block: kv_len covers cache_len
    assert paged.kv_len >= paged.cache_len
    assert paged.stats()["engine/prefix_hits_total"] >= 1
    paged.pool.leak_audit(expect_idle=True)
    fixed.shutdown()
    paged.shutdown()


def test_paged_engine_undersized_pool_requeues_and_drains():
    """A pool too small for all slots at once (2 spans for 3 slots) must
    requeue pool-bound admissions and still drain the whole workload —
    transactional admission, no deadlock, no leak."""
    prompts, pmask = _paged_prompts()
    _, paged = _paged_pair(
        False, paged_kwargs={"kv_block_size": 4, "kv_pool_blocks": 1 + 2 * 4}
    )
    paged.submit(prompts, pmask)
    eps = _drain(paged)
    assert len(eps) == 7
    paged.pool.leak_audit(expect_idle=True)
    paged.shutdown()


def test_paged_engine_abort_releases_all_blocks():
    """Satellite: abort() mid-decode releases every pinned/private block
    (leak_audit inside abort raises otherwise) and repoints the device
    tables at the trash block."""
    prompts, pmask = _paged_prompts()
    _, paged = _paged_pair(False)
    paged.submit(prompts, pmask)
    paged.step()  # slots mid-decode: blocks pinned and referenced
    assert paged.pool.used_blocks() > 0
    paged.abort()
    assert paged.pool.used_blocks() == 0
    assert not np.asarray(jax.device_get(paged._state["block_tables"])).any()
    paged.shutdown()


def test_paged_kv_off_leaves_engine_byte_identical():
    """The default-off contract: an engine with paged_kv=False is the SAME
    engine as one built before the paged knobs existed — no block tables in
    the slot state, no pool, kv_len == cache_len, and a bit-identical
    decode jaxpr (the gather-indirection must vanish at trace time, not
    just at runtime)."""
    cfg = LMConfig(
        vocab_size=23, n_layer=2, n_head=2, d_model=32, max_position=96,
        dtype="float32",
    )
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (3, 6), 2, cfg.vocab_size)
    params = {"params": model.init(rng, ids, jnp.ones((3, 6), jnp.int32))["params"]}
    gcfg = GenerateConfig(max_new_tokens=7, do_sample=False, pad_token_id=0, eos_token_id=1)
    kw = dict(n_slots=3, prompt_width=6, prefill_batch=2, steps_per_sync=3)
    default = RolloutEngine(model, gcfg, **kw, rng=jax.random.PRNGKey(2))
    off = RolloutEngine(model, gcfg, **kw, rng=jax.random.PRNGKey(2),
                        paged_kv=False, kv_block_size=4,
                        kv_pool_blocks=99)  # knobs present but off
    assert off.pool is None and off.kv_len == off.cache_len
    for e in (default, off):
        e.update_weights(params, version=1)
        e._adopt_staged()  # weights are staged until the next step() top
        e._ensure_state()
    assert "block_tables" not in off._state
    assert jax.tree_util.tree_structure(default._state) == jax.tree_util.tree_structure(off._state)
    j_default = jax.make_jaxpr(default._decode_fn)(default._variables, default._state)
    j_off = jax.make_jaxpr(off._decode_fn)(off._variables, off._state)
    # identical programs modulo the memory addresses of callables embedded
    # in eqn params (two engine instances -> two bound-method objects)
    import re

    strip = lambda s: re.sub(r"0x[0-9a-f]+", "0x", str(s))  # noqa: E731
    assert strip(j_default) == strip(j_off)
    default.shutdown()
    off.shutdown()
