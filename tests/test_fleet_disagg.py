"""Disaggregated rollout/learner fleet (trlx_tpu/fleet): parity + drills.

Fast tier (in-process): the acceptance identity — a COLOCATED fleet run at
max_staleness=0 pushes every episode through the real transports (episode
stream npz + versioned weight broadcast) yet produces the bitwise-identical
loss trajectory to the serial schedule, re-proving the PR 5 contract
THROUGH the stream rather than around it. Runs fully sanitized
(dispatch/donation/race).

Slow tier (2-process CPU drills): the robustness story. Unlike
tests/test_fleet_drill.py these spawn NO jax.distributed world — the
rollout job and the learner job are SEPARATE single-controller processes
coupled only through train.fleet_dir, which is the whole point of the
disaggregation (topology.py). Drills:

- ``rollout_host_kill@N``: worker dies mid-phase → learner drains in-flight
  episodes at elevated staleness, reports ``fleet/degraded`` on a LIVE
  /healthz scrape, exits cleanly (no hang, no leaked trlx-* threads). The
  same drill carries a coordinated-save preemption (sigterm on the learner)
  plus a resume leg first — abort.json must NOT land on preemption, and the
  surviving worker keeps serving the resumed learner.
- ``broadcast_timeout@N``: the learner skips a publish → the staleness-0
  worker starves under collective_guard and aborts with exit 117.
- ``episode_stream_stall@N``: the worker stalls WITH a live heartbeat →
  triage says STALLED (not dead).
- 2-process staleness-0 parity: the distributed form of the acceptance
  identity, learner losses bitwise equal to a serial run.

When ``TRLX_TPU_DRILL_ARTIFACTS`` is set (the CI fleet-drill job does),
each drill copies the episode-stream index, broadcast log, fleet event log
and both role logs there for upload.
"""

import json
import os
import shutil
import subprocess
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402
from trlx_tpu.fleet.topology import read_jsonl_or_empty  # noqa: E402
from trlx_tpu.resilience.distributed import EXIT_COLLECTIVE_TIMEOUT  # noqa: E402

SANITIZE = "dispatch,donation,race"


@pytest.fixture(scope="module")
def task():
    return generate_random_walks(n_nodes=15, max_length=8, n_walks=60, seed=1000)


# ----------------------------------------------------- colocated parity (fast)


def _run_ppo(task, ckpt_dir, fleet=False, steps=8, **overrides):
    _, logit_mask, metric_fn, reward_fn = task
    config = base_config("ppo", 15, 8)
    config.train.total_steps = steps
    config.train.epochs = 4
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.checkpoint_dir = str(ckpt_dir)
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    if fleet:
        config.method.fleet_disaggregate = True
        config.train.fleet_dir = str(ckpt_dir) + "_fleet"
    for k, v in overrides.items():
        setattr(config.method, k, v)
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[1]],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    with open(os.path.join(str(ckpt_dir), "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    return model, records


def test_colocated_staleness0_matches_serial_bitwise(task, tmp_path, monkeypatch):
    """Staleness-0 disaggregated (colocated both-roles-one-process) run:
    every batch round-trips episodes/batch_*.npz and every weight hand-off
    round-trips weights_*.npz, and the loss trajectory is still bitwise
    equal to the serial path. Fully sanitized: the fleet snapshot path
    dispatches under the lock, donation and race trackers armed."""
    from trlx_tpu.utils import sanitize

    _, serial = _run_ppo(task, tmp_path / "serial")

    monkeypatch.setenv(sanitize.ENV_VAR, SANITIZE)
    try:
        model, fleet = _run_ppo(task, tmp_path / "colo", fleet=True, max_staleness=0)
    finally:
        monkeypatch.delenv(sanitize.ENV_VAR, raising=False)
        sanitize.refresh()
        sanitize.clear_donated()
        sanitize.clear_races()

    losses_serial = [r["loss"] for r in serial if "loss" in r]
    losses_fleet = [r["loss"] for r in fleet if "loss" in r]
    assert len(losses_serial) == 8
    assert losses_fleet == losses_serial

    # The run really went through the transports, all on-policy.
    fleet_dir = str(tmp_path / "colo") + "_fleet"
    stream = read_jsonl_or_empty(os.path.join(fleet_dir, "stream.jsonl"))
    broadcast = read_jsonl_or_empty(os.path.join(fleet_dir, "broadcast.jsonl"))
    assert len(stream) >= 2
    published = {r["version"] for r in broadcast if r["status"] == "published"}
    assert {r["weight_version"] for r in stream} <= published
    stale = [r["staleness/mean"] for r in fleet if "staleness/mean" in r]
    assert stale and all(s == 0.0 for s in stale)
    # Clean teardown: feed shut down and detached, no leaked threads.
    assert model._fleet_feed is None
    assert not any(t.name.startswith("trlx-") for t in threading.enumerate())
    # Coordinated completion landed for any (absent) worker to observe.
    with open(os.path.join(fleet_dir, "abort.json")) as f:
        assert json.load(f)["reason"] == "complete"


def test_colocated_inflight_knob_staleness0_is_bitwise_with_span_records(
    task, tmp_path, monkeypatch
):
    """In-flight weight updates, acceptance identity (PR 17): at staleness 0
    the learner only publishes AFTER consuming a batch and the worker cannot
    start the next one until that publish — so no push can ever land
    mid-phase, and flipping method.fleet_inflight_weights must change the
    loss trajectory by NOTHING (bitwise). What the knob DOES change is the
    stream index: knob-off records are the PR 16 shape (no spans key),
    knob-on records carry exactly one span naming their own version, with
    zero mixed-version tokens at consume time."""
    from trlx_tpu.utils import sanitize

    monkeypatch.setenv(sanitize.ENV_VAR, SANITIZE)
    engine = dict(max_staleness=0, rollout_engine=True, engine_steps_per_sync=2)
    try:
        _, off = _run_ppo(task, tmp_path / "off", fleet=True, steps=4, **engine)
        _, on = _run_ppo(
            task, tmp_path / "on", fleet=True, steps=4,
            fleet_inflight_weights=True, **engine,
        )
    finally:
        monkeypatch.delenv(sanitize.ENV_VAR, raising=False)
        sanitize.refresh()
        sanitize.clear_donated()
        sanitize.clear_races()

    losses_off = [r["loss"] for r in off if "loss" in r]
    losses_on = [r["loss"] for r in on if "loss" in r]
    assert len(losses_off) == 4
    assert losses_on == losses_off

    stream_off = read_jsonl_or_empty(os.path.join(str(tmp_path / "off") + "_fleet", "stream.jsonl"))
    stream_on = read_jsonl_or_empty(os.path.join(str(tmp_path / "on") + "_fleet", "stream.jsonl"))
    assert stream_off and all("version_spans" not in r for r in stream_off)
    assert stream_on
    for r in stream_on:
        assert r["version_spans"] == [[r["weight_version"], r["version_spans"][0][1]]]
        assert r["version_spans"][0][1] > 0
    # Token-granularity staleness: every consumed batch was single-version,
    # so the mixed-token count is identically zero.
    events = read_jsonl_or_empty(os.path.join(str(tmp_path / "on") + "_fleet", "fleet_events.jsonl"))
    consumed = [e for e in events if e["event"] == "episode_consumed"]
    assert consumed and all(e["mixed_version_tokens"] == 0 for e in consumed)
    assert all(e["staleness"] == 0 for e in consumed)


# ------------------------------------------------------- 2-process drills

pytest_slow = pytest.mark.slow

_ROLE_WORKER = r"""
import json, os, sys, threading, time
import urllib.request
import numpy as np

role = sys.argv[1]            # "serial" | "rollout" | "learner"
ckpt = sys.argv[2]
fleet_dir = sys.argv[3]
S = int(sys.argv[4])

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["TRLX_TPU_NO_PROGRESS"] = "1"

sys.path.insert(0, os.path.join(os.environ["TRLX_REPO"], "examples"))
import trlx_tpu
from randomwalks import base_config, generate_random_walks

_, logit_mask, metric_fn, reward_fn = generate_random_walks(
    n_nodes=15, max_length=8, n_walks=60, seed=1000
)

config = base_config("ppo", 15, 8)
config.train.total_steps = int(os.environ.get("TOTAL", "8"))
config.train.epochs = int(os.environ.get("EPOCHS", "4"))
config.train.batch_size = 16
config.train.eval_interval = 100
config.train.checkpoint_dir = ckpt
config.train.resume_from_checkpoint = bool(int(os.environ.get("RESUME", "0")))
config.method.num_rollouts = 16
config.method.chunk_size = 16
# Continuous-batching engine + in-flight weight adoption (PR 17 drills).
if int(os.environ.get("ENGINE", "0")):
    config.method.rollout_engine = True
    config.method.engine_steps_per_sync = int(os.environ.get("ENGINE_SYNC", "2"))
if role != "serial":
    config.method.fleet_disaggregate = True
    config.method.max_staleness = S
    config.method.fleet_inflight_weights = bool(int(os.environ.get("INFLIGHT", "0")))
    config.train.fleet_dir = fleet_dir
    # Drill-scale timing: seconds, not the production minutes.
    config.train.heartbeat_interval = 0.2
    config.train.fleet_episode_timeout = 2.0
    config.train.fleet_stream_retries = 1
    config.train.fleet_stream_backoff = 0.2
    config.train.fleet_heartbeat_timeout = 3.0
    config.train.fleet_broadcast_deadline = float(os.environ.get("BDEADLINE", "60"))

scrapes_stop = threading.Event()

def scrape_loop():
    # Live witness for the degraded window: the drill must observe
    # fleet/degraded on /healthz WHILE the learner drains, not post-hoc.
    mport = int(os.environ.get("TRLX_TPU_METRICS_PORT", "0"))
    while not scrapes_stop.is_set():
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/healthz", timeout=2
            ) as r:
                payload = json.loads(r.read().decode())
            block = payload.get("fleet", {}).get("disaggregated")
            if block:
                with open(os.path.join(ckpt, "scrape_last.json"), "w") as f:
                    json.dump(block, f)
                if block.get("state") == "degraded":
                    with open(os.path.join(ckpt, "scrape_degraded.json"), "w") as f:
                        json.dump(payload, f)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/metrics", timeout=2
            ) as r:
                body = r.read().decode()
            if "trlx_tpu_fleet_degraded 1" in body:
                with open(os.path.join(ckpt, "scrape_metrics.txt"), "w") as f:
                    f.write(body)
        except Exception:
            pass  # exporter not up yet / mid-teardown
        scrapes_stop.wait(0.05)

scraper = None
if role == "learner" and os.environ.get("TRLX_TPU_METRICS_PORT"):
    os.makedirs(ckpt, exist_ok=True)
    scraper = threading.Thread(target=scrape_loop, daemon=True)
    scraper.start()

prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
try:
    trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
        metric_fn=metric_fn, config=config, logit_mask=logit_mask,
    )
finally:
    scrapes_stop.set()
    if scraper is not None:
        scraper.join(timeout=5)

if role in ("serial", "learner"):
    with open(os.path.join(ckpt, "metrics.jsonl")) as f:
        losses = [json.loads(l).get("loss") for l in f]
    print("LOSSES", json.dumps([l for l in losses if l is not None]))
print("THREADS", json.dumps([t.name for t in threading.enumerate() if t.name.startswith("trlx-")]))
print(f"fleet role {role} DONE")
"""


def _script(tmp_path):
    script = tmp_path / "fleet_role_worker.py"
    script.write_text(_ROLE_WORKER)
    return str(script)


def _launch_role(tmp_path, role, ckpt, fleet_dir, staleness, extra_env=None):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("TRLX_TPU_FAULTS", None)
    env.pop("TRLX_TPU_METRICS_PORT", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo
    env["TRLX_REPO"] = repo
    env["TRLX_TPU_SANITIZE"] = SANITIZE
    if role != "serial":
        env["TRLX_TPU_FLEET_ROLE"] = role
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, _script(tmp_path), role, str(ckpt), str(fleet_dir), str(staleness)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )


def _communicate(proc, timeout=900):
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        pytest.skip("2-process fleet drill did not complete in this environment")
    return out.decode(errors="replace")


def _events(fleet_dir):
    return read_jsonl_or_empty(os.path.join(str(fleet_dir), "fleet_events.jsonl"))


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _export_artifacts(fleet_dir, logs, extra=None):
    dest = os.environ.get("TRLX_TPU_DRILL_ARTIFACTS")
    if not dest:
        return
    os.makedirs(dest, exist_ok=True)
    for name in ("stream.jsonl", "broadcast.jsonl", "fleet_events.jsonl", "weights_latest.json", "abort.json"):
        src = os.path.join(str(fleet_dir), name)
        if os.path.exists(src):
            shutil.copy(src, os.path.join(dest, name))
    # Span/lineage artifacts (in-flight weight updates): per-role lineage
    # and metrics files named by their source dir so uploads don't collide.
    for src in extra or []:
        if os.path.exists(src):
            tag = os.path.basename(os.path.dirname(src))
            shutil.copy(src, os.path.join(dest, f"{tag}_{os.path.basename(src)}"))
    for name, text in logs.items():
        with open(os.path.join(dest, name), "w") as f:
            f.write(text)


def _assert_clean_threads(out, who):
    lines = [l for l in out.splitlines() if l.startswith("THREADS ")]
    assert lines, f"{who} never reported its thread census:\n{out[-2000:]}"
    assert json.loads(lines[-1][len("THREADS "):]) == [], f"{who} leaked threads: {lines[-1]}"


@pytest.mark.slow
def test_fleet_drill_rollout_host_kill_with_preemption_and_resume(tmp_path):
    """The flagship drill, three legs against ONE persistent worker:

    1. learner leg 1 is preempted (sigterm@12) at a save boundary →
       exits 0, writes NO abort marker, worker keeps serving;
    2. learner leg 2 resumes from the checkpoint, republishes its restored
       version, and keeps consuming from the cursor;
    3. the worker is killed mid-phase (rollout_host_kill@6) → leg 2 drains
       the in-flight episodes at staleness ≤ cap, reports fleet/degraded on
       a live /healthz scrape, triages the peer as DEAD, and exits cleanly.
    """
    fleet_dir = tmp_path / "fleet"
    env = {"TOTAL": "100", "EPOCHS": "100"}
    worker = _launch_role(
        tmp_path, "rollout", tmp_path / "ckpt_w", fleet_dir, 2,
        {**env, "TRLX_TPU_FAULTS": "rollout_host_kill@6"},
    )
    logs = {}
    try:
        mport = _free_port()
        learner_env = {**env, "TRLX_TPU_METRICS_PORT": str(mport)}
        leg1 = _launch_role(
            tmp_path, "learner", tmp_path / "ckpt_l", fleet_dir, 2,
            {**learner_env, "TRLX_TPU_FAULTS": "sigterm@12"},
        )
        out1 = logs["learner_leg1.log"] = _communicate(leg1)
        assert leg1.returncode == 0, f"preempted learner leg failed:\n{out1[-4000:]}"
        # Preemption is NOT a shutdown: no abort marker, worker survives.
        assert not os.path.exists(os.path.join(str(fleet_dir), "abort.json"))
        assert worker.poll() is None, "worker died during learner preemption"
        _assert_clean_threads(out1, "learner leg 1")

        leg2 = _launch_role(
            tmp_path, "learner", tmp_path / "ckpt_l", fleet_dir, 2,
            {**learner_env, "RESUME": "1"},
        )
        out2 = logs["learner_leg2.log"] = _communicate(leg2)
        logs["worker.log"] = _communicate(worker, timeout=60)
        assert leg2.returncode == 0, f"resumed learner leg failed:\n{out2[-4000:]}"
        assert worker.returncode == 1  # rollout_host_kill is os._exit(1)
        assert "[fleet] learner stopped cleanly" in out2
        _assert_clean_threads(out2, "learner leg 2")

        events = _events(fleet_dir)
        exits = [e for e in events if e["event"] == "learner_exit"]
        assert [e["reason"] for e in exits] == ["preempted", "degraded"]
        degraded = [e for e in events if e["event"] == "degraded"]
        assert degraded and degraded[0]["triage"] == "dead"
        # The resumed leg restored its step and republished that version as
        # a fresh DENSE ordinal before consuming anything (broadcast.py's
        # resume contract: ordinals never fork, versions may repeat).
        assert "resumed from step" in out2
        starts = [i for i, e in enumerate(events) if e["event"] == "learner_start"]
        assert len(starts) == 2
        pre = [e["version"] for i, e in enumerate(events) if i < starts[1] and e["event"] == "weights_published"]
        post = [e["version"] for i, e in enumerate(events) if i > starts[1] and e["event"] == "weights_published"]
        assert pre and post and post[0] >= max(pre)
        ordinals = [e["ordinal"] for e in events if e["event"] == "weights_published"]
        assert ordinals == list(range(len(ordinals)))
        # In-flight drain at elevated-but-capped staleness, hitting the cap.
        consumed = [e for e in events if e["event"] == "episode_consumed"]
        staleness = [e["staleness"] for e in consumed]
        assert all(s <= 2 for s in staleness)
        assert staleness[-1] == 2
        assert [e["seq"] for e in consumed] == list(range(len(consumed)))

        # Coordinated degraded shutdown marker (vs NO marker on preemption).
        with open(os.path.join(str(fleet_dir), "abort.json")) as f:
            abort = json.load(f)
        assert abort["reason"] == "degraded" and abort["triage"] == "dead"

        # Every streamed episode's weight_version is a published version.
        stream = read_jsonl_or_empty(os.path.join(str(fleet_dir), "stream.jsonl"))
        broadcast = read_jsonl_or_empty(os.path.join(str(fleet_dir), "broadcast.jsonl"))
        published = {r["version"] for r in broadcast if r["status"] == "published"}
        assert stream and {r["weight_version"] for r in stream} <= published

        # Live /healthz witness: fleet/degraded observed DURING the drain.
        with open(os.path.join(str(tmp_path / "ckpt_l"), "scrape_degraded.json")) as f:
            scrape = json.load(f)
        block = scrape["fleet"]["disaggregated"]
        assert block["state"] == "degraded"
        assert block["triage"] == "dead"
        assert block["role"] == "learner"
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.communicate()
        _export_artifacts(fleet_dir, logs)


@pytest.mark.slow
def test_fleet_drill_broadcast_timeout_aborts_starved_worker(tmp_path):
    """broadcast_timeout@2 on the learner: ordinal 2 is skipped, so the
    staleness-0 worker's gate can never open for the next batch — its
    collective_guard deadline converts the starvation into exit 117."""
    fleet_dir = tmp_path / "fleet"
    # The deadline must COVER the learner's first-batch compile+train (so a
    # merely-slow publish is not an abort) while converting the injected
    # never-published ordinal into one within the test budget.
    env = {"TOTAL": "100", "EPOCHS": "100", "BDEADLINE": "30"}
    worker = _launch_role(tmp_path, "rollout", tmp_path / "ckpt_w", fleet_dir, 0, env)
    logs = {}
    try:
        learner = _launch_role(
            tmp_path, "learner", tmp_path / "ckpt_l", fleet_dir, 0,
            {**env, "TRLX_TPU_FAULTS": "broadcast_timeout@2"},
        )
        out_l = logs["learner.log"] = _communicate(learner)
        out_w = logs["worker.log"] = _communicate(worker)
        assert worker.returncode == EXIT_COLLECTIVE_TIMEOUT, (
            f"expected worker exit {EXIT_COLLECTIVE_TIMEOUT}, got "
            f"{worker.returncode}:\n{out_w[-4000:]}"
        )
        # The starved worker's own guard names the broadcast site.
        assert "fleet/weight_broadcast" in out_w
        # The learner outlives it: stream dries up, peer triaged dead,
        # degraded exit — never a hang on either side.
        assert learner.returncode == 0, f"learner failed:\n{out_l[-4000:]}"
        assert "[fleet] learner stopped cleanly" in out_l
        events = _events(fleet_dir)
        degraded = [e for e in events if e["event"] == "degraded"]
        assert degraded and degraded[0]["triage"] == "dead"
        broadcast = read_jsonl_or_empty(os.path.join(str(fleet_dir), "broadcast.jsonl"))
        assert any(r["status"] == "injected_timeout" and r["ordinal"] == 2 for r in broadcast)
        # The worker survived the slow-but-published ordinal 1 and streamed
        # against it — only the never-published ordinal starved it.
        stream = read_jsonl_or_empty(os.path.join(str(fleet_dir), "stream.jsonl"))
        assert len(stream) >= 2
        _assert_clean_threads(out_l, "learner")
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.communicate()
        _export_artifacts(fleet_dir, logs)


@pytest.mark.slow
def test_fleet_drill_episode_stream_stall_triages_stalled_not_dead(tmp_path):
    """episode_stream_stall@2 on the worker: batch 2 never lands but the
    worker's heartbeat thread keeps beating — fresh written_t, frozen
    progress_t — so the learner's triage must say STALLED, not dead. The
    stall is finite (30s) so the woken worker observes the abort marker and
    exits 0 on its own."""
    fleet_dir = tmp_path / "fleet"
    env = {"TOTAL": "100", "EPOCHS": "100"}
    worker = _launch_role(
        tmp_path, "rollout", tmp_path / "ckpt_w", fleet_dir, 1,
        {**env, "TRLX_TPU_FAULTS": "episode_stream_stall@2",
         "TRLX_TPU_STREAM_STALL_SECONDS": "30"},
    )
    logs = {}
    try:
        learner = _launch_role(tmp_path, "learner", tmp_path / "ckpt_l", fleet_dir, 1, env)
        out_l = logs["learner.log"] = _communicate(learner)
        out_w = logs["worker.log"] = _communicate(worker, timeout=120)
        assert learner.returncode == 0, f"learner failed:\n{out_l[-4000:]}"
        assert worker.returncode == 0, f"worker failed:\n{out_w[-4000:]}"
        events = _events(fleet_dir)
        degraded = [e for e in events if e["event"] == "degraded"]
        assert degraded and degraded[0]["triage"] == "stalled"
        with open(os.path.join(str(fleet_dir), "abort.json")) as f:
            assert json.load(f)["triage"] == "stalled"
        # The per-episode retry wrapper fired before triage escalated.
        assert "episode stream wait" in out_l
        _assert_clean_threads(out_l, "learner")
        _assert_clean_threads(out_w, "worker")
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.communicate()
        _export_artifacts(fleet_dir, logs)


@pytest.mark.slow
def test_two_process_staleness0_matches_serial_bitwise(tmp_path):
    """The distributed acceptance identity: a REAL 2-process disaggregated
    run at max_staleness=0 — episodes crossing process boundaries through
    npz files, weights crossing back as byte-leaf snapshots, the adaptive
    KL coefficient riding the pointer — reproduces the serial loss
    trajectory bitwise."""
    serial = _launch_role(tmp_path, "serial", tmp_path / "ckpt_s", tmp_path / "unused", 0)
    out_s = _communicate(serial)
    assert serial.returncode == 0, f"serial run failed:\n{out_s[-4000:]}"

    fleet_dir = tmp_path / "fleet"
    worker = _launch_role(tmp_path, "rollout", tmp_path / "ckpt_w", fleet_dir, 0)
    logs = {}
    try:
        learner = _launch_role(tmp_path, "learner", tmp_path / "ckpt_l", fleet_dir, 0)
        out_l = logs["learner.log"] = _communicate(learner)
        out_w = logs["worker.log"] = _communicate(worker, timeout=120)
        assert learner.returncode == 0, f"learner failed:\n{out_l[-4000:]}"
        assert worker.returncode == 0, f"worker failed:\n{out_w[-4000:]}"

        def losses(out):
            line = next(l for l in out.splitlines() if l.startswith("LOSSES "))
            return json.loads(line[len("LOSSES "):])

        assert losses(out_s) == losses(out_l)
        assert len(losses(out_s)) == 8

        # On-policy throughout, lineage intact, coordinated completion.
        consumed = [e for e in _events(fleet_dir) if e["event"] == "episode_consumed"]
        assert consumed and all(e["staleness"] == 0 for e in consumed)
        stream = read_jsonl_or_empty(os.path.join(str(fleet_dir), "stream.jsonl"))
        broadcast = read_jsonl_or_empty(os.path.join(str(fleet_dir), "broadcast.jsonl"))
        published = {r["version"] for r in broadcast if r["status"] == "published"}
        assert {r["weight_version"] for r in stream} <= published
        with open(os.path.join(str(fleet_dir), "abort.json")) as f:
            assert json.load(f)["reason"] == "complete"
        _assert_clean_threads(out_l, "learner")
        _assert_clean_threads(out_w, "worker")
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.communicate()
        _export_artifacts(fleet_dir, logs)


# ------------------------------------- in-flight weight update drills (PR 17)

_ENGINE_ENV = {"ENGINE": "1", "ENGINE_SYNC": "2", "INFLIGHT": "1"}


def _worker_metrics(ckpt):
    path = os.path.join(str(ckpt), "metrics.jsonl")
    return read_jsonl_or_empty(path)


@pytest.mark.slow
def test_fleet_drill_weight_push_torn_rejects_and_holds_old_version(tmp_path):
    """weight_push_torn@2 on the learner: the pointer flips to ordinal 2 but
    the snapshot file is truncated. The in-flight poller (and the boundary
    path) must REJECT the torn load — weights_torn event naming the ordinal,
    decoding continues on the held version — and pick up the next intact
    ordinal. Nobody crashes, nobody hangs; every streamed version is a
    published one."""
    fleet_dir = tmp_path / "fleet"
    env = {"TOTAL": "8", "EPOCHS": "4", **_ENGINE_ENV}
    worker = _launch_role(tmp_path, "rollout", tmp_path / "ckpt_w", fleet_dir, 2, env)
    logs = {}
    try:
        learner = _launch_role(
            tmp_path, "learner", tmp_path / "ckpt_l", fleet_dir, 2,
            {**env, "TRLX_TPU_FAULTS": "weight_push_torn@2"},
        )
        out_l = logs["learner.log"] = _communicate(learner)
        out_w = logs["worker.log"] = _communicate(worker, timeout=120)
        assert learner.returncode == 0, f"learner failed:\n{out_l[-4000:]}"
        assert worker.returncode == 0, f"worker failed:\n{out_w[-4000:]}"

        broadcast = read_jsonl_or_empty(os.path.join(str(fleet_dir), "broadcast.jsonl"))
        torn = [r for r in broadcast if r["status"] == "injected_torn"]
        assert [r["ordinal"] for r in torn] == [2]

        events = _events(fleet_dir)
        rejected = [e for e in events if e["event"] == "weights_torn"]
        assert rejected, "torn snapshot was never observed/rejected by the worker"
        assert all(e["ordinal"] == 2 for e in rejected)
        assert all(e["held"] < 2 for e in rejected)
        # The worker moved PAST the torn ordinal onto a later intact one.
        adopted = [
            e["ordinal"] for e in events
            if e["event"] in ("weights_adopted_inflight", "weights_fetched")
        ]
        assert adopted and max(adopted) >= 3

        # Lineage stayed intact: the torn version never decoded a token.
        stream = read_jsonl_or_empty(os.path.join(str(fleet_dir), "stream.jsonl"))
        published = {r["version"] for r in broadcast if r["status"] == "published"}
        assert stream and {r["weight_version"] for r in stream} <= published
        for r in stream:
            for v, k in r.get("version_spans") or []:
                assert v in published and k > 0
        _assert_clean_threads(out_l, "learner")
        _assert_clean_threads(out_w, "worker")
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.communicate()
        _export_artifacts(fleet_dir, logs, extra=[
            os.path.join(str(tmp_path / "ckpt_w"), "metrics.jsonl"),
            os.path.join(str(tmp_path / "ckpt_l"), "lineage.jsonl"),
        ])


@pytest.mark.slow
def test_fleet_drill_version_switch_storm_coalesces_never_queues(tmp_path):
    """version_switch_storm@3 on the worker: for a window of syncs the
    poller re-pushes its held latest every sync. The engine must coalesce —
    same-version re-pushes record NO switch, a burst between two syncs keeps
    only the newest — so the switch count stays bounded by the number of
    distinct versions actually adopted, spans stay strictly
    version-increasing, and the run completes."""
    fleet_dir = tmp_path / "fleet"
    env = {"TOTAL": "8", "EPOCHS": "4", **_ENGINE_ENV}
    worker = _launch_role(
        tmp_path, "rollout", tmp_path / "ckpt_w", fleet_dir, 2,
        {**env, "TRLX_TPU_FAULTS": "version_switch_storm@3",
         "TRLX_TPU_SWITCH_STORM_PUSHES": "6"},
    )
    logs = {}
    try:
        learner = _launch_role(tmp_path, "learner", tmp_path / "ckpt_l", fleet_dir, 2, env)
        out_l = logs["learner.log"] = _communicate(learner)
        out_w = logs["worker.log"] = _communicate(worker, timeout=120)
        assert learner.returncode == 0, f"learner failed:\n{out_l[-4000:]}"
        assert worker.returncode == 0, f"worker failed:\n{out_w[-4000:]}"

        # Switches bounded by distinct mid-phase adoptions: the 6 storm
        # re-pushes of the held version must not have recorded any.
        events = _events(fleet_dir)
        adoptions = [e for e in events if e["event"] == "weights_adopted_inflight"]
        metrics = _worker_metrics(tmp_path / "ckpt_w")
        switches = sum(int(r.get("engine/weight_switches", 0)) for r in metrics)
        assert any("engine/weight_switches" in r for r in metrics)
        assert switches <= len(adoptions)

        # Per-record spans stay minimal: strictly increasing versions, no
        # same-version split from the storm.
        stream = read_jsonl_or_empty(os.path.join(str(fleet_dir), "stream.jsonl"))
        assert stream
        for r in stream:
            spans = r.get("version_spans") or []
            versions = [v for v, _ in spans]
            assert versions == sorted(set(versions)), f"span thrash in {r}"
        _assert_clean_threads(out_l, "learner")
        _assert_clean_threads(out_w, "worker")
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.communicate()
        _export_artifacts(fleet_dir, logs, extra=[
            os.path.join(str(tmp_path / "ckpt_w"), "metrics.jsonl"),
            os.path.join(str(tmp_path / "ckpt_l"), "lineage.jsonl"),
        ])


@pytest.mark.slow
def test_two_process_inflight_knob_staleness0_matches_knob_off_bitwise(tmp_path):
    """The 2-process form of the in-flight acceptance identity: with real
    role processes at max_staleness=0, the publish-before-advance schedule
    means no weight push can land mid-phase — so the engine run with
    method.fleet_inflight_weights ON reproduces the knob-OFF learner loss
    trajectory bitwise, while its stream records carry single-version
    spans."""
    def leg(tag, inflight):
        fleet_dir = tmp_path / f"fleet_{tag}"
        env = {"TOTAL": "8", "EPOCHS": "4", "ENGINE": "1", "ENGINE_SYNC": "2",
               "INFLIGHT": "1" if inflight else "0"}
        worker = _launch_role(
            tmp_path, "rollout", tmp_path / f"ckpt_w_{tag}", fleet_dir, 0, env
        )
        logs = {}
        try:
            learner = _launch_role(
                tmp_path, "learner", tmp_path / f"ckpt_l_{tag}", fleet_dir, 0, env
            )
            out_l = logs["learner.log"] = _communicate(learner)
            out_w = logs["worker.log"] = _communicate(worker, timeout=120)
            assert learner.returncode == 0, f"{tag} learner failed:\n{out_l[-4000:]}"
            assert worker.returncode == 0, f"{tag} worker failed:\n{out_w[-4000:]}"
            _assert_clean_threads(out_l, f"{tag} learner")
            _assert_clean_threads(out_w, f"{tag} worker")
            line = next(l for l in out_l.splitlines() if l.startswith("LOSSES "))
            return json.loads(line[len("LOSSES "):]), fleet_dir
        finally:
            if worker.poll() is None:
                worker.kill()
                worker.communicate()
            _export_artifacts(fleet_dir, logs, extra=[
                os.path.join(str(tmp_path / f"ckpt_l_{tag}"), "lineage.jsonl"),
            ])

    losses_off, dir_off = leg("off", inflight=False)
    losses_on, dir_on = leg("on", inflight=True)
    assert len(losses_off) == 8
    assert losses_on == losses_off

    stream_off = read_jsonl_or_empty(os.path.join(str(dir_off), "stream.jsonl"))
    stream_on = read_jsonl_or_empty(os.path.join(str(dir_on), "stream.jsonl"))
    assert stream_off and all("version_spans" not in r for r in stream_off)
    assert stream_on and all(
        len(r["version_spans"]) == 1
        and r["version_spans"][0][0] == r["weight_version"]
        for r in stream_on
    )
    consumed = [e for e in _events(dir_on) if e["event"] == "episode_consumed"]
    assert consumed and all(e["staleness"] == 0 for e in consumed)
    assert all(e["mixed_version_tokens"] == 0 for e in consumed)
