"""Flash-decode kernel vs the einsum reference math (interpret mode).

The kernel's contract: bit-comparable attention output to the model layer's
einsum decode path — including the dequant-folding identity
(ks·dot(K_int8, q) == dot(K_int8·ks, q) up to fp32 reassociation) and the
additive bias masking — for int8 AND bf16 caches, tile-aligned AND ragged
cache lengths (the masked tail block), and fully-masked rows. CPU CI runs
the same kernel code via pallas interpret mode (the on-TPU routing gate and
lowering probe are tested separately)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trlx_tpu.models.lm import quantize_kv
from trlx_tpu.ops.decode_attention import (
    BLOCK_T,
    decode_attn_eligible,
    decode_attn_supported,
    decode_attention,
    pick_t_block,
)

pytestmark = pytest.mark.slow


def _reference_einsum(q, k, v, bias_row, scale):
    """The model layer's decode einsum path, verbatim math."""
    scores = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32), k.astype(jnp.float32))
    scores = scores * scale + bias_row[:, None, :]
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", probs, v.astype(jnp.float32))


def _setup(B=2, T=64, h=2, d=128, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(B, h, d)).astype(dtype)
    k = rng.normal(size=(B, T, h, d)).astype(dtype)
    v = rng.normal(size=(B, T, h, d)).astype(dtype)
    # validity mask with left padding + causal tail invalid
    valid = np.ones((B, T), dtype=bool)
    valid[0, : min(5, T - 1)] = False
    valid[1, T - min(8, T - 1) :] = False
    bias = np.where(valid, 0.0, -1e9).astype(np.float32)
    return q, k, v, bias


# T sweep: single full (unaligned) block, exactly one block, a ragged
# multi-block tail, and an aligned multi-block cache.
RAGGED_AND_ALIGNED_T = (64, BLOCK_T, BLOCK_T + 72, 3 * BLOCK_T)


@pytest.mark.parametrize("T", RAGGED_AND_ALIGNED_T)
def test_plain_matches_einsum(T):
    q, k, v, bias = _setup(T=T)
    out = decode_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), None, None,
        jnp.asarray(bias), scale=0.125, interpret=True,
    )
    ref = _reference_einsum(q, k, v, bias, 0.125)
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("T", RAGGED_AND_ALIGNED_T)
def test_quant_matches_dequantized_einsum(T):
    q, k, v, bias = _setup(T=T, seed=1)
    kq, ks = quantize_kv(jnp.asarray(k))
    vq, vs = quantize_kv(jnp.asarray(v))
    out = decode_attention(
        jnp.asarray(q), kq, vq, ks, vs, jnp.asarray(bias), scale=0.125, interpret=True,
    )
    # reference: dequantize then einsum — the exact model-layer fallback
    k_dq = kq.astype(jnp.float32) * ks[..., None].astype(jnp.float32)
    v_dq = vq.astype(jnp.float32) * vs[..., None].astype(jnp.float32)
    ref = _reference_einsum(q, k_dq, v_dq, bias, 0.125)
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_bf16_cache_matches_einsum():
    """Non-quantized caches are the compute dtype (bf16 in production)."""
    q, k, v, bias = _setup(T=BLOCK_T + 40, seed=3)
    qb = jnp.asarray(q, jnp.bfloat16)
    kb = jnp.asarray(k, jnp.bfloat16)
    vb = jnp.asarray(v, jnp.bfloat16)
    out = decode_attention(qb, kb, vb, None, None, jnp.asarray(bias), scale=0.125, interpret=True)
    ref = _reference_einsum(qb, kb, vb, bias, 0.125)
    np.testing.assert_allclose(
        np.asarray(out[:, 0], np.float32), np.asarray(ref, np.float32), rtol=2e-2, atol=2e-2
    )


@pytest.mark.parametrize("T", (64, BLOCK_T + 72))
def test_fully_masked_rows_match_einsum(T):
    """A fully-masked row degrades to softmax over the raw scores (the
    additive -1e9 bias cancels in the softmax shift) — same as einsum, and
    always finite."""
    q, k, v, bias = _setup(T=T, seed=2)
    bias[0, :] = -1e9  # every key invalid for row 0
    out = decode_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), None, None,
        jnp.asarray(bias), scale=0.125, interpret=True,
    )
    assert np.isfinite(np.asarray(out)).all()
    ref = _reference_einsum(q, k, v, bias, 0.125)
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_bench_head_layout_ragged():
    """The flagship bench head layout [h=16, d=256] at a ragged cache length
    — the exact shape class BENCH_r05 crashed on (there with B=32)."""
    q, k, v, bias = _setup(B=2, T=832, h=16, d=256, seed=4)
    kq, ks = quantize_kv(jnp.asarray(k))
    vq, vs = quantize_kv(jnp.asarray(v))
    out = decode_attention(
        jnp.asarray(q), kq, vq, ks, vs, jnp.asarray(bias), scale=0.0625, interpret=True,
    )
    k_dq = kq.astype(jnp.float32) * ks[..., None].astype(jnp.float32)
    v_dq = vq.astype(jnp.float32) * vs[..., None].astype(jnp.float32)
    ref = _reference_einsum(q, k_dq, v_dq, bias, 0.0625)
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_pick_t_block():
    assert pick_t_block(64) == 64          # short cache: one full block
    assert pick_t_block(100) == 100        # unaligned short cache is legal as-is
    assert pick_t_block(BLOCK_T) == BLOCK_T
    assert pick_t_block(BLOCK_T + 1) == BLOCK_T  # long cache streams in blocks
    assert pick_t_block(832) == BLOCK_T


def test_eligibility_gate():
    # off-TPU the gate must refuse (einsum path stands in CI)
    assert not decode_attn_eligible(16, 256, 1024, True) or jax.default_backend() == "tpu"
    if jax.default_backend() == "tpu":  # pragma: no cover — CPU CI
        assert decode_attn_eligible(16, 256, 1024, True)
        # masked tail: unaligned cache lengths are eligible now
        assert decode_attn_eligible(16, 256, 831, True)
        assert not decode_attn_eligible(16, 200, 1024, True)  # lanes not 128-aligned
        assert not decode_attn_eligible(3, 256, 1024, True)  # sub-tile head count


def test_supported_probe_is_cached_and_safe_off_tpu():
    """The routing probe must answer (and cache) without a TPU: the static
    tile check runs everywhere, the Mosaic lowering attempt only on TPU."""
    from trlx_tpu.ops import decode_attention as da

    da._PROBE_CACHE.clear()
    assert decode_attn_supported(32, 832, 16, 256, True)
    assert len(da._PROBE_CACHE) == 1
    # second call: pure cache hit (no recomputation observable, but the key
    # count must not grow)
    assert decode_attn_supported(32, 832, 16, 256, True)
    assert len(da._PROBE_CACHE) == 1


# ------------------------------------------------------------- paged kernel


def _paged_setup(B=32, h=16, d=256, bs=32, bps=4, seed=7, share=True):
    """A shared block pool + per-row tables exercising every row class the
    engine produces: tile-aligned valid spans, ragged mid-block frontiers,
    a fully-masked (dead) row, spans crossing block boundaries, and —
    when ``share`` — two rows aliasing the SAME physical prefix block
    (the prefix-cache hit layout)."""
    rng = np.random.default_rng(seed)
    T = bps * bs
    n_blocks = 1 + B * bps  # block 0 = the engine's trash block
    q = rng.normal(size=(B, h, d)).astype(np.float32)
    k_pool = rng.normal(size=(n_blocks, bs, h, d)).astype(np.float32)
    v_pool = rng.normal(size=(n_blocks, bs, h, d)).astype(np.float32)
    tables = np.arange(1, 1 + B * bps, dtype=np.int32).reshape(B, bps)
    if share:
        # rows 1..3 alias row 0's first block — prefix-cache sharing
        tables[1:4, 0] = tables[0, 0]
    # shuffle physical placement so virtual order != physical order
    perm = rng.permutation(np.unique(tables))
    remap = dict(zip(np.unique(tables).tolist(), perm.tolist()))
    tables = np.vectorize(remap.get)(tables).astype(np.int32)
    valid = np.ones((B, T), dtype=bool)
    valid[0, : bs] = False              # left pad = exactly one block
    valid[1, : bs // 2] = False         # left pad mid-block (ragged head)
    valid[2, T - bs - 3 :] = False      # frontier crosses into the last block
    valid[3, T - 1 :] = False           # frontier one short of full
    valid[4, :] = False                 # dead slot: fully masked
    valid[5, bs - 1 : 2 * bs + 1] = False  # hole spanning a block boundary
    bias = np.where(valid, 0.0, -1e9).astype(np.float32)
    return q, k_pool, v_pool, tables, bias


def _paged_reference(q, k_pool, v_pool, tables, bias, scale):
    """Gather-then-einsum: the model layer's paged fallback math."""
    B, bps = tables.shape
    bs = k_pool.shape[1]
    k = k_pool[tables].reshape(B, bps * bs, *k_pool.shape[2:])
    v = v_pool[tables].reshape(B, bps * bs, *v_pool.shape[2:])
    return _reference_einsum(q, k, v, bias, scale)


@pytest.mark.parametrize("share", (False, True))
def test_paged_plain_matches_gathered_einsum(share):
    from trlx_tpu.ops.decode_attention import paged_decode_attention

    q, k_pool, v_pool, tables, bias = _paged_setup(share=share)
    out = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool), None, None,
        jnp.asarray(tables), jnp.asarray(bias), scale=0.0625, interpret=True,
    )
    assert np.isfinite(np.asarray(out)).all()
    ref = _paged_reference(q, k_pool, v_pool, tables, bias, 0.0625)
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_paged_quant_matches_dequantized_gathered_einsum():
    from trlx_tpu.ops.decode_attention import paged_decode_attention

    q, k_pool, v_pool, tables, bias = _paged_setup(seed=8)
    kq, ks = quantize_kv(jnp.asarray(k_pool))
    vq, vs = quantize_kv(jnp.asarray(v_pool))
    out = paged_decode_attention(
        jnp.asarray(q), kq, vq, ks, vs,
        jnp.asarray(tables), jnp.asarray(bias), scale=0.0625, interpret=True,
    )
    k_dq = np.asarray(kq.astype(jnp.float32) * ks[..., None].astype(jnp.float32))
    v_dq = np.asarray(vq.astype(jnp.float32) * vs[..., None].astype(jnp.float32))
    ref = _paged_reference(q, k_dq, v_dq, tables, bias, 0.0625)
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_paged_eligibility_gate():
    from trlx_tpu.ops.decode_attention import paged_decode_eligible

    # off-TPU the gate must refuse (the gathered einsum stands in CI)
    on_tpu = jax.default_backend() == "tpu"
    assert paged_decode_eligible(16, 256, 128, 8, True) == on_tpu
    if on_tpu:  # pragma: no cover — CPU CI
        # the bias tile: block_size % 128 unless the slot is one block
        assert not paged_decode_eligible(16, 256, 96, 8, True)
        assert paged_decode_eligible(16, 256, 96, 1, True)
        assert not paged_decode_eligible(16, 200, 128, 8, True)
        assert not paged_decode_eligible(3, 256, 128, 8, True)


def test_paged_supported_probe_is_cached_and_safe_off_tpu():
    from trlx_tpu.ops import decode_attention as da

    da._PROBE_CACHE.clear()
    assert da.paged_decode_supported(32, 257, 128, 8, 16, 256, True)
    n = len(da._PROBE_CACHE)
    assert da.paged_decode_supported(32, 257, 128, 8, 16, 256, True)
    assert len(da._PROBE_CACHE) == n  # pure cache hit
