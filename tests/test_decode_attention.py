"""Fused decode-attention kernel vs the einsum reference math (interpret mode).

The kernel's contract: bit-comparable attention output to the model layer's
einsum decode path — including the dequant-folding identity
(ks·dot(K_int8, q) == dot(K_int8·ks, q) up to fp32 reassociation) and the
additive bias masking. CPU CI runs the same kernel code via pallas
interpret mode (the on-TPU routing gate is tested separately)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trlx_tpu.models.lm import quantize_kv
from trlx_tpu.ops.decode_attention import decode_attn_eligible, decode_attention

pytestmark = pytest.mark.slow


def _reference_einsum(q, k, v, bias_row, scale):
    """The model layer's decode einsum path, verbatim math."""
    scores = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32), k.astype(jnp.float32))
    scores = scores * scale + bias_row[:, None, :]
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", probs, v.astype(jnp.float32))


def _setup(B=2, T=64, h=2, d=128, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(B, h, d)).astype(np.float32)
    k = rng.normal(size=(B, T, h, d)).astype(np.float32)
    v = rng.normal(size=(B, T, h, d)).astype(np.float32)
    # validity mask with left padding + causal tail invalid
    valid = np.ones((B, T), dtype=bool)
    valid[0, :5] = False
    valid[1, T - 8 :] = False
    bias = np.where(valid, 0.0, -1e9).astype(np.float32)
    return q, k, v, bias


def test_plain_matches_einsum():
    q, k, v, bias = _setup()
    out = decode_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), None, None,
        jnp.asarray(bias), scale=0.125, interpret=True,
    )
    ref = _reference_einsum(q, k, v, bias, 0.125)
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_quant_matches_dequantized_einsum():
    q, k, v, bias = _setup(seed=1)
    kq, ks = quantize_kv(jnp.asarray(k))
    vq, vs = quantize_kv(jnp.asarray(v))
    out = decode_attention(
        jnp.asarray(q), kq, vq, ks, vs, jnp.asarray(bias), scale=0.125, interpret=True,
    )
    # reference: dequantize then einsum — the exact model-layer fallback
    k_dq = kq.astype(jnp.float32) * ks[..., None].astype(jnp.float32)
    v_dq = vq.astype(jnp.float32) * vs[..., None].astype(jnp.float32)
    ref = _reference_einsum(q, k_dq, v_dq, bias, 0.125)
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_fully_masked_rows_are_finite():
    q, k, v, bias = _setup(seed=2)
    bias[0, :] = -1e9  # every key invalid for row 0
    out = decode_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), None, None,
        jnp.asarray(bias), scale=0.125, interpret=True,
    )
    assert np.isfinite(np.asarray(out)).all()


def test_eligibility_gate():
    # off-TPU the gate must refuse (einsum path stands in CI)
    assert not decode_attn_eligible(16, 256, 1024, True) or jax.default_backend() == "tpu"
    if jax.default_backend() == "tpu":
        assert decode_attn_eligible(16, 256, 1024, True)
        assert not decode_attn_eligible(16, 200, 1024, True)  # lanes not 128-aligned
        assert not decode_attn_eligible(16, 256, 1000, True)  # int8 sublane tile
