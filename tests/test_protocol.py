"""BaseRL protocol surface: sample()/act() honor their arguments
(reference protocol: trlx/model/__init__.py:49-71) and the wandb.watch
equivalent (`train.watch_interval`) emits per-group grad norms + parameter
histograms."""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

from randomwalks import base_config, generate_random_walks  # noqa: E402


def _tiny_trainer(tmp_path, **cfg_overrides):
    from trlx_tpu.trainer.ppo import PPOTrainer

    config = base_config("ppo", 15, 8)
    config.train.checkpoint_dir = str(tmp_path)
    config.train.batch_size = 8
    config.method.chunk_size = 8
    config.method.num_rollouts = 8
    config.model.num_layers_unfrozen = 1
    for k, v in cfg_overrides.items():
        section, key = k.split(".")
        setattr(getattr(config, section), key, v)
    return PPOTrainer(config)


def test_sample_honors_n_samples_and_length(tmp_path):
    trainer = _tiny_trainer(tmp_path)
    P, R = trainer.prompt_length, trainer.response_length
    rng = np.random.default_rng(0)
    prompts = {
        "input_ids": rng.integers(1, 15, size=(4, P)).astype(np.int32),
        "attention_mask": np.ones((4, P), np.int32),
    }
    # n_samples > batch: tiled
    out = trainer.sample(prompts, length=None, n_samples=6)
    assert np.asarray(out).shape[0] == 6
    # n_samples < batch: truncated
    out = trainer.sample(prompts, length=None, n_samples=2)
    assert np.asarray(out).shape[0] == 2
    # length clips the response region (never exceeds compiled R)
    out = trainer.sample(prompts, length=3, n_samples=4)
    assert np.asarray(out).shape[1] == P + min(3, R)
    out = trainer.sample(prompts, length=10 * R, n_samples=4)
    assert np.asarray(out).shape[1] == P + R


def test_act_returns_tokens_and_mask(tmp_path):
    # act() keeps the orchestrator's contract: batches arrive mesh-divisible
    # (8 = the conftest virtual-device data axes), unlike sample() which pads.
    trainer = _tiny_trainer(tmp_path)
    P = trainer.prompt_length
    rng = np.random.default_rng(1)
    data = {
        "input_ids": rng.integers(1, 15, size=(8, P)).astype(np.int32),
        "attention_mask": np.ones((8, P), np.int32),
    }
    tokens, mask = trainer.act(data)
    assert np.asarray(tokens).shape == np.asarray(mask).shape
    assert np.asarray(tokens).shape == (8, P + trainer.response_length)


def test_watch_interval_logs_grad_norms_and_histograms(tmp_path):
    """watch_interval=1: every logged step carries per-group
    watch/grad_norm/* scalars, and param histograms land in metrics.jsonl."""
    import trlx_tpu

    walks, logit_mask, metric_fn, reward_fn = generate_random_walks(15, 8, 60, seed=1000)
    config = base_config("ppo", 15, 8)
    config.train.checkpoint_dir = str(tmp_path)
    config.train.batch_size = 16
    config.train.total_steps = 3
    config.train.eval_interval = 100
    config.train.watch_interval = 1
    config.model.num_layers_unfrozen = 1
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
        metric_fn=metric_fn, config=config, logit_mask=logit_mask,
    )

    grad_groups, hist_names = set(), set()
    with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            grad_groups.update(k for k in rec if k.startswith("watch/grad_norm/"))
            if "histogram" in rec and rec["histogram"].startswith("watch/params/"):
                hist_names.add(rec["histogram"])
    assert grad_groups, "no per-group grad norms logged"
    assert hist_names, "no parameter histograms logged"


def test_compile_cache_dir_populates(tmp_path):
    """train.compile_cache_dir: trainer construction with the knob set drops
    compiled programs into the persistent cache (warm restarts skip the
    cold-start compile measured in the head-to-head).

    Isolation: JAX's persistent cache binds to the FIRST directory it was
    initialized with for the life of the process, so the trainer resets it
    when the configured dir changes (trainer/base.py) and this test restores
    the unconfigured state on exit so later tests never write into this
    test's (deleted) tmp_path."""
    import jax
    from jax.experimental.compilation_cache import compilation_cache as cc

    cache = tmp_path / "xla_cache"
    # A warm process compiles these tiny programs in well under the persistent
    # cache's default min-compile-time threshold (1s), which silently skips
    # the write — the other half of the original order-dependent flake.
    min_compile = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        trainer = _tiny_trainer(tmp_path, **{"train.compile_cache_dir": str(cache)})
        # run one compiled program so at least one entry lands
        rng = np.random.default_rng(0)
        P = trainer.prompt_length
        trainer.sample(
            {"input_ids": rng.integers(1, 15, size=(8, P)).astype(np.int32),
             "attention_mask": np.ones((8, P), np.int32)},
            n_samples=8,
        )
        assert cache.exists() and any(cache.iterdir()), "compile cache stayed empty"
    finally:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", min_compile)
        jax.config.update("jax_compilation_cache_dir", None)
        cc.reset_cache()


def test_ppo_headtohead_assets_round_trip(tmp_path):
    """Guards bench_reference.py's PPO harness against bitrot: the shared
    init checkpoint + char tokenizer build offline, the tokenizer encodes/
    decodes the task alphabet, and trlx_tpu's streamed importer loads the
    checkpoint into a working trainer."""
    import sys as _sys

    _sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench_reference import PPO_PROTOCOL, build_ppo_assets, _ppo_prompts, _ppo_reward_fn

    assets = str(tmp_path / "assets")
    build_ppo_assets(assets)

    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(assets, use_fast=False)
    text = "abc 123!"
    ids = tok(text).input_ids
    assert tok.decode(ids) == text
    assert len(ids) == len(text)  # strictly char-level: no merges

    prompts = _ppo_prompts()
    assert len(prompts) == 64 and all(len(p) == 6 for p in prompts)
    assert _ppo_reward_fn(["a" * 24, "b" * 24]) == [1.0, 0.0]

    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.trainer.ppo import PPOTrainer

    p = PPO_PROTOCOL
    config = TRLConfig.from_dict(
        {
            "model": {"model_path": assets, "tokenizer_path": assets, "model_type": "ppo",
                      "num_layers_unfrozen": p["num_layers_unfrozen"], "dtype": "float32",
                      "param_dtype": "float32"},
            "train": {"seq_length": p["seq_length"], "epochs": 1, "total_steps": 1,
                      "batch_size": 8, "lr_ramp_steps": 1, "lr_decay_steps": 10,
                      "weight_decay": 0.0, "learning_rate_init": 1e-3,
                      "learning_rate_target": 1e-4, "checkpoint_dir": str(tmp_path / "ck"),
                      "mesh": [-1, 1, 1, 1], "seed": 0},
            "method": {"name": "ppoconfig", "num_rollouts": 8, "chunk_size": 8,
                       "gen_kwargs": {"prompt_length": 8, "max_new_tokens": 4, "do_sample": True}},
        }
    )
    trainer = PPOTrainer(config)
    assert trainer.model.branch_layer >= 0  # hydra engaged, as in the h2h
    enc = tok(prompts[:8], padding=False)
    import numpy as _np

    ids8 = _np.full((8, 8), tok.eos_token_id, dtype=_np.int32)
    mask8 = _np.zeros((8, 8), dtype=_np.int32)
    for i, row in enumerate(enc.input_ids):
        ids8[i, -len(row):] = row
        mask8[i, -len(row):] = 1
    tokens, _ = trainer.rollout_generate(ids8, mask8)
    assert _np.asarray(tokens).shape == (8, 12)
