"""Observability layer (trlx_tpu/observability/): span tracing, device
telemetry, anomaly-triggered incident capture, and the report renderer.

Unit tier: SpanTracer lane/metadata semantics (including OS-ident reuse),
torn-tail + concurrent-writer file contracts, AnomalyDetector baseline math,
IncidentCapture bundle contents and budget, DeviceMonitor compiled-cost
capture and the MFU arithmetic cross-check against bench.py's formula.

Integration tier (CPU): the acceptance run — a short overlapped PPO run at
max_staleness=1 with spans + telemetry + anomaly armed and the
``slow_step`` fault drill produces a Perfetto-loadable spans.jsonl with the
producer/score/train threads on distinct lanes and visible overlap, MFU
gauges in metrics.jsonl, an incident bundle with thread stacks, and a
report that renders every section.
"""

import json
import os
import sys
import threading
import time
import warnings

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402
from trlx_tpu.observability import anomaly as obs_anomaly  # noqa: E402
from trlx_tpu.observability import devicemon, report  # noqa: E402
from trlx_tpu.observability import graftscope as obs_graftscope  # noqa: E402
from trlx_tpu.observability import spans as obs_spans  # noqa: E402


@pytest.fixture(autouse=True)
def _span_isolation():
    """The tracer is a process global armed by trainers/tests — always disarm
    so one test's spans.jsonl (in a deleted tmp_path) never leaks forward."""
    yield
    obs_spans.shutdown()
    obs_graftscope.shutdown()
    obs_anomaly.register_emergency(None)


# ------------------------------------------------------------------- spans


def test_trace_span_disabled_is_shared_noop():
    obs_spans.shutdown()
    assert not obs_spans.enabled()
    a = obs_spans.trace_span("x", step=1)
    b = obs_spans.trace_span("y")
    assert a is b  # shared singleton: no per-call allocation on the off path
    with a:
        pass
    obs_spans.complete("z", time.time())  # no-ops, no file appears
    obs_spans.instant("w")


def test_span_lanes_survive_os_thread_ident_reuse(tmp_path):
    """Sequential threads commonly REUSE the OS thread ident; lanes are keyed
    by synthetic per-thread-object tids so each thread still gets its own
    lane + thread_name metadata (the bug this guards: a rollout producer
    inheriting a dead prefetch thread's ident and lane label)."""
    path = str(tmp_path / "spans.jsonl")
    obs_spans.configure(path)
    with obs_spans.trace_span("main/work", step=1):
        pass

    def worker():
        with obs_spans.trace_span("bg/work"):
            time.sleep(0.01)

    for name in ("lane-a", "lane-b"):  # b starts only after a exits
        t = threading.Thread(target=worker, name=name)
        t.start()
        t.join()
    obs_spans.instant("tick", step=2)
    obs_spans.shutdown()

    events = obs_spans.read_spans(path)
    meta = {e["tid"]: e["args"]["name"] for e in events if e["ph"] == "M"}
    assert len(meta) == 3  # three threads -> three lanes, no merging
    assert {"MainThread", "lane-a", "lane-b"} <= set(meta.values())
    xs = [e for e in events if e["ph"] == "X"]
    assert len({e["tid"] for e in xs if e["name"] == "bg/work"}) == 2
    main_span = next(e for e in xs if e["name"] == "main/work")
    assert main_span["args"] == {"step": 1}
    assert meta[main_span["tid"]] == "MainThread"
    instant = next(e for e in events if e["ph"] == "i")
    assert instant["s"] == "t" and instant["tid"] == main_span["tid"]


def test_span_exit_on_exception_annotates_error(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    obs_spans.configure(path)
    with pytest.raises(ValueError):
        with obs_spans.trace_span("rollout/decode", step=3):
            raise ValueError("boom")
    obs_spans.shutdown()
    span = next(e for e in obs_spans.read_spans(path) if e["ph"] == "X")
    assert span["args"] == {"step": 3, "error": "ValueError"}


def test_span_file_torn_tail_tolerated_like_metrics(tmp_path):
    """Both JSONL writers (Tracker's metrics.jsonl, SpanTracer's spans.jsonl)
    share one reader contract: a writer killed mid-append tears at most the
    final line, which readers drop with a warning; mid-file garbage raises."""
    path = str(tmp_path / "spans.jsonl")
    obs_spans.configure(path)
    for i in range(3):
        obs_spans.complete("train/step", time.time() - 0.01, step=i)
    obs_spans.shutdown()
    with open(path, "ab") as f:
        f.write(b'{"name": "train/step", "ph": "X", "ts": 12')  # torn mid-record
    with pytest.warns(UserWarning, match="torn final record"):
        events = obs_spans.read_spans(path)
    assert sum(e["ph"] == "X" for e in events) == 3

    # the SAME torn file mid-stream is corruption, not a tear
    with open(path, "ab") as f:
        f.write(b'\n{"name": "later", "ph": "i", "ts": 13}\n')
    with pytest.raises(json.JSONDecodeError):
        obs_spans.read_spans(path)


def test_concurrent_span_writers_never_interleave(tmp_path):
    """Line-atomicity under contention: many threads hammering one tracer
    (unbuffered O_APPEND, one write(2) per record) must yield a file where
    EVERY line parses — no interleaved or split records."""
    path = str(tmp_path / "spans.jsonl")
    obs_spans.configure(path)
    n_threads, n_spans = 8, 200

    def hammer(k):
        for i in range(n_spans):
            obs_spans.complete("stress/span", time.time(), writer=k, i=i)

    threads = [threading.Thread(target=hammer, args=(k,), name=f"stress-{k}") for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    obs_spans.shutdown()

    with open(path, "rb") as f:
        lines = [ln for ln in f.read().split(b"\n") if ln.strip()]
    events = [json.loads(ln) for ln in lines]  # raises if any line tore
    xs = [e for e in events if e["ph"] == "X"]
    assert len(xs) == n_threads * n_spans
    assert len({e["tid"] for e in xs}) == n_threads


def test_span_writer_disarms_on_io_error_instead_of_raising(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    obs_spans.configure(path)
    # simulate the disk going away mid-run: close the fd under the tracer
    obs_spans._STATE["tracer"]._file.close()
    with pytest.warns(UserWarning, match="span tracing disabled"):
        obs_spans.instant("after_close")
    assert not obs_spans.enabled()
    obs_spans.instant("noop")  # disarmed: silent no-op, run continues


# ------------------------------------------------------------------ anomaly


def test_anomaly_detector_baseline_seed_and_breach():
    det = obs_anomaly.AnomalyDetector(factor=3.0, window=16, min_samples=5)
    # seeding: nothing may trip before min_samples observations, even spikes
    for _ in range(4):
        assert not det.observe(1.0)
    assert not det.observe(50.0)  # 5th observation still seeds
    assert det.p50() == 1.0
    # breach: > factor * p50 trips, and is NOT absorbed into the baseline
    assert det.observe(4.0)
    assert det.p50() == 1.0
    assert not det.observe(2.9)  # under 3x median: normal


def test_anomaly_detector_factor_zero_disables():
    det = obs_anomaly.AnomalyDetector(factor=0.0)
    assert not any(det.observe(x) for x in [0.1] * 10 + [1000.0])


def test_incident_capture_bundle_contents_and_budget(tmp_path):
    metrics = tmp_path / "metrics.jsonl"
    metrics.write_text('{"loss": 1.0, "step": 1}\n{"loss": 0.5, "step": 2}\n')
    cap = obs_anomaly.IncidentCapture(
        str(tmp_path), metrics_path=str(metrics), max_incidents=2, last_n_metrics=1
    )
    bundle = cap.capture(7, "unit_drill", detail={"step_time": 9.9})
    assert bundle.endswith(os.path.join("incidents", "7"))

    with open(os.path.join(bundle, "incident.json")) as f:
        manifest = json.load(f)
    assert manifest["step"] == 7 and manifest["reason"] == "unit_drill"
    assert manifest["detail"] == {"step_time": 9.9}
    assert manifest["sections"]["threads"] == "ok"
    assert manifest["sections"]["memory"] == "ok"
    with open(os.path.join(bundle, "threads.txt")) as f:
        assert "MainThread" in f.read()
    with open(os.path.join(bundle, "memory.json")) as f:
        assert "gauges" in json.load(f)
    with open(os.path.join(bundle, "last_metrics.json")) as f:
        assert json.load(f) == [{"loss": 0.5, "step": 2}]  # tail only

    assert cap.capture(8, "unit_drill")
    assert cap.capture(9, "unit_drill") == ""  # budget spent: rate-limited


def test_emergency_capture_hook_roundtrip(tmp_path):
    cap = obs_anomaly.IncidentCapture(str(tmp_path), max_incidents=1)
    obs_anomaly.emergency_capture("collective_timeout")  # nothing registered: no-op
    obs_anomaly.register_emergency(cap, step_provider=lambda: 42)
    obs_anomaly.emergency_capture("collective_timeout", detail={"op": "psum"})
    with open(os.path.join(str(tmp_path), "incidents", "42", "incident.json")) as f:
        assert json.load(f)["reason"] == "collective_timeout"


# ---------------------------------------------------------------- devicemon


def test_peak_flops_env_override(monkeypatch):
    monkeypatch.setenv("TRLX_TPU_PEAK_TFLOPS", "0.5")
    assert devicemon.detect_peak_flops() == pytest.approx(0.5e12)


def test_device_monitor_capture_dispatch_accounting_and_mfu():
    """The acceptance arithmetic: the MFU gauge must match bench.py's formula
    (100 * flops / seconds / peak) computed by hand from the SAME captured
    cost_analysis FLOPs, to 2%."""
    import jax
    import jax.numpy as jnp

    peak = 1e9  # pinned synthetic peak: CPU has no table entry
    mon = devicemon.DeviceMonitor(peak_flops=peak)
    step = mon.wrap("train/step", jax.jit(lambda x: x @ x), phase="train")
    x = jnp.ones((64, 64), jnp.float32)
    for _ in range(3):
        step(x).block_until_ready()

    prog = mon.snapshot()["train/step"]
    assert prog["phase"] == "train" and prog["dispatches"] == 3
    assert len(prog["variants"]) == 1  # one signature -> ONE capture
    flops = prog["variants"][0]["flops"]
    assert flops > 0

    train_s = 2.0
    stats = mon.window({"train": train_s, "wall": train_s})
    expected_mfu = 100.0 * (3 * flops) / train_s / peak  # bench.py arithmetic
    assert stats["obs/train_mfu_pct"] == pytest.approx(expected_mfu, rel=0.02)
    assert stats["obs/iter_mfu_pct"] == pytest.approx(expected_mfu, rel=0.02)
    assert stats["obs/train_tflops_per_chip"] == pytest.approx(3 * flops / train_s / 1e12, rel=0.02)

    assert mon.window({"train": 1.0, "wall": 1.0}) == {}  # counters drained

    step(jnp.ones((32, 32), jnp.float32)).block_until_ready()  # new shape
    assert len(mon.snapshot()["train/step"]["variants"]) == 2


def test_monitored_fn_delegates_attributes_and_survives_capture_failure():
    mon = devicemon.DeviceMonitor(peak_flops=None)

    def fn(x):
        return x + 1

    fn.num_traces = 7  # the closure counters make_generate_fn exposes
    wrapped = mon.wrap("rollout/generate", fn, phase="rollout")
    assert wrapped.num_traces == 7
    assert wrapped(1) == 2  # plain fn: .lower() fails, call still goes through
    variant = mon.snapshot()["rollout/generate"]["variants"][0]
    assert variant["flops"] == 0.0 and "error" in variant


def test_routing_and_memory_gauges_have_stable_keys():
    routing = devicemon.kernel_routing_gauges()
    assert set(routing) == {
        "obs/decode_attn_active",
        "obs/decode_attn_fallback",
        "obs/fused_logprob_active",
        "obs/fused_logprob_fallback",
    }
    assert all(v in (0.0, 1.0) for v in routing.values())
    memory = devicemon.device_memory_gauges()
    assert memory  # CPU backend: live_array census fallback
    assert all(k.startswith("obs/") and v >= 0 for k, v in memory.items())


def test_rollup_is_identity_valued_on_single_process():
    """hostmean/hostmax of a one-host gather are the host's own values (pods
    exercise the real allgather; the keys are identical either way)."""
    stats = {"obs/train_mfu_pct": 12.5, "time/train_s": 3.0, "skip_me": "str"}
    assert report.rollup_window_stats(stats) == {
        "obs/train_mfu_pct/hostmean": 12.5,
        "obs/train_mfu_pct/hostmax": 12.5,
        "time/train_s/hostmean": 3.0,
        "time/train_s/hostmax": 3.0,
    }
    assert report.rollup_window_stats({}) == {}


# ------------------------------------------------------------ e2e acceptance


@pytest.fixture(scope="module")
def task():
    return generate_random_walks(n_nodes=15, max_length=8, n_walks=60, seed=1000)


def test_e2e_overlapped_run_spans_telemetry_incident_report(task, tmp_path, monkeypatch):
    """The PR's acceptance run: overlapped PPO (max_staleness=1) with every
    observability surface armed and the slow_step drill injected."""
    monkeypatch.setenv("TRLX_TPU_FAULTS", "slow_step@6")
    monkeypatch.setenv("TRLX_TPU_SLOW_STEP_SECONDS", "1.5")
    monkeypatch.setenv("TRLX_TPU_PEAK_TFLOPS", "0.01")  # only way to get MFU on CPU

    _, logit_mask, metric_fn, reward_fn = task
    config = base_config("ppo", 15, 8)
    config.train.total_steps = 8
    config.train.epochs = 4
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.checkpoint_dir = str(tmp_path)
    config.train.trace_spans = True
    config.train.device_telemetry = True
    config.train.anomaly_factor = 3.0
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    config.method.max_staleness = 1
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[1]],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert not any(t.name.startswith("trlx-") for t in threading.enumerate())

    # --- spans.jsonl: valid Chrome trace events on distinct thread lanes ---
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # no torn tail on a clean shutdown
        events = obs_spans.read_spans(os.path.join(str(tmp_path), "spans.jsonl"))
    assert events and {e["ph"] for e in events} <= {"X", "i", "M"}
    lanes = {e["args"]["name"]: e["tid"] for e in events if e["ph"] == "M"}
    assert "MainThread" in lanes
    assert "trlx-rollout-producer" in lanes
    assert "trlx-score-worker" in lanes
    assert len(set(lanes.values())) == len(lanes)  # one lane per thread

    xs = [e for e in events if e["ph"] == "X"]
    names = {e["name"] for e in xs}
    assert {
        "train/step", "rollout/produce", "rollout/generate", "rollout/decode",
        "rollout/reward_fn", "score/host", "ckpt/save",
    } <= names

    producer = [e for e in xs if e["name"] == "rollout/produce"]
    train = [e for e in xs if e["name"] == "train/step"]
    assert {e["tid"] for e in producer} == {lanes["trlx-rollout-producer"]}
    assert {e["tid"] for e in train} == {lanes["MainThread"]}
    # a fresh score worker spawns per experience window — every score/host
    # span must sit on SOME trlx-score-worker lane (and never the main lane)
    lane_names = {e["tid"]: e["args"]["name"] for e in events if e["ph"] == "M"}
    score_lanes = {lane_names[e["tid"]] for e in xs if e["name"] == "score/host"}
    assert score_lanes == {"trlx-score-worker"}

    def overlap_us(a, b):
        return min(a["ts"] + a["dur"], b["ts"] + b["dur"]) - max(a["ts"], b["ts"])

    # staleness=1: the producer builds store N+1 WHILE the trainer steps on N
    assert any(overlap_us(p, t) > 0 for p in producer for t in train)

    # --- metrics.jsonl: compiled-cost MFU + kernel-routing gauges ---------
    with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    mfu = [r["obs/train_mfu_pct"] for r in records if "obs/train_mfu_pct" in r]
    assert mfu and all(m > 0 for m in mfu)
    routed = [r for r in records if "obs/fused_logprob_active" in r]
    assert routed
    for key in ("obs/decode_attn_active", "obs/decode_attn_fallback", "obs/fused_logprob_fallback"):
        assert key in routed[-1]
    stale = [r["staleness/mean"] for r in records if "staleness/mean" in r]
    assert stale and stale[-1] == 1.0  # the pipeline genuinely ran ahead

    # programs.json: registry for the report's program table
    with open(os.path.join(str(tmp_path), "programs.json")) as f:
        programs = json.load(f)
    assert "train/step" in programs
    assert programs["train/step"]["dispatches"] >= 8

    # --- incident bundle from the slow_step drill -------------------------
    incidents_dir = os.path.join(str(tmp_path), "incidents")
    bundles = os.listdir(incidents_dir)
    assert bundles, "slow_step drill produced no incident bundle"
    with open(os.path.join(incidents_dir, bundles[0], "incident.json")) as f:
        manifest = json.load(f)
    assert manifest["reason"] == "slow_step"
    assert manifest["detail"]["step_time"] > 1.0  # the injected stall
    assert manifest["sections"]["threads"] == "ok"
    with open(os.path.join(incidents_dir, bundles[0], "threads.txt")) as f:
        assert "trlx-" in f.read()  # the pipeline threads ARE in the dump

    # --- report renders every section ------------------------------------
    md = report.build_report(str(tmp_path))
    for heading in (
        "# Performance report",
        "## Phase breakdown (per window)",
        "## MFU / FLOP throughput",
        "## Kernel routing",
        "### Monitored programs",
        "## Span lanes",
        "## Incidents",
    ):
        assert heading in md
    assert "slow_step" in md
    assert "trlx-rollout-producer" in md

    out_md = tmp_path / "report.md"
    trace_out = tmp_path / "trace.json"
    assert report.main([str(tmp_path), "-o", str(out_md), "--trace-out", str(trace_out)]) == 0
    assert "slow_step" in out_md.read_text()
    assert json.loads(trace_out.read_text())["traceEvents"]


# ---------------------------------------------------- graftscope ledger (PR 12)


def test_graftscope_ledger_conservation_on_synthetic_intervals(monkeypatch):
    """The conservation identity device + host + bubble == wall must hold
    exactly on hand-built interval sets covering every clipping case: a
    fence straddling the window start, one entirely outside it, overlapping
    programs, and host lanes partially hidden under device time."""
    import types

    gs = obs_graftscope.GraftScope()
    now = 1000.0
    gs._win_t0 = now
    monkeypatch.setattr(
        obs_graftscope, "time", types.SimpleNamespace(time=lambda: now + 10.0)
    )
    gs._device = [
        (now - 2.0, now + 0.5, "warmup"),  # straddles the window start
        (now - 5.0, now - 4.0, "ancient"),  # fully before: clipped away
        (now + 1.0, now + 3.0, "train/step"),
        (now + 2.0, now + 4.0, "rollout/generate"),  # overlaps train/step
    ]
    gs._host = [
        (now + 0.0, now + 5.0, "train"),
        (now + 4.5, now + 6.0, "producer"),
        (now + 20.0, now + 30.0, "score"),  # fully after: clipped away
    ]
    gauges = gs.window()

    assert gauges["obs/ledger_wall_s"] == pytest.approx(10.0)
    # device union: (now, now+0.5) + (now+1, now+4) = 3.5s
    assert gauges["obs/ledger_device_busy_s"] == pytest.approx(3.5)
    # host union (now, now+6) minus the device union = 2.5s
    assert gauges["obs/ledger_host_s"] == pytest.approx(2.5)
    assert gauges["obs/ledger_bubble_s"] == pytest.approx(4.0)
    assert gauges["obs/bubble_fraction"] == pytest.approx(0.4)
    assert gauges["obs/ledger_error_frac"] <= 1e-9  # identity by construction
    assert gauges["obs/lane_busy_train_s"] == pytest.approx(5.0)
    assert gauges["obs/lane_busy_producer_s"] == pytest.approx(1.5)
    assert gauges["obs/lane_busy_score_s"] == 0.0

    samples = gs.drain_samples()
    assert samples["lane_gaps"]["train"] == pytest.approx([5.0])  # trailing idle
    assert samples["lane_gaps"]["producer"] == pytest.approx([4.5, 4.0])
    assert gs.drain_samples() is None  # consumed once per window

    snap = gs.snapshot()
    assert snap["totals"]["wall_s"] == pytest.approx(10.0)
    assert snap["bubble_fraction"] == pytest.approx(0.4)
    assert dict(snap["windows"][-1]["top_programs"]) == pytest.approx(
        {"train/step": 2.0, "rollout/generate": 2.0, "warmup": 0.5}
    )


def test_graftscope_fence_drain_and_dropped_fences():
    """Real dispatch outputs get fenced OFF the dispatch path by the drain
    thread; a fence that raises (donated buffer consumed by the next step)
    is counted and dropped, never propagated; close() joins the thread."""
    import jax.numpy as jnp

    gs = obs_graftscope.GraftScope()
    out = {"loss": jnp.array(1.0), "big": jnp.ones((64,))}
    gs.track_dispatch("train/step", "train", out)
    deadline = time.time() + 10.0
    while time.time() < deadline:
        with gs._lock:
            if gs._device:
                break
        time.sleep(0.005)
    with gs._lock:
        assert gs._device, "drain thread never fenced the dispatch"
        t0, t1, name = gs._device[0]
        assert name == "train/step" and t1 >= t0

    class _DeadLeaf:
        size = 1

        def block_until_ready(self):
            raise RuntimeError("buffer donated to the next step")

    gs.track_dispatch("rollout/generate", "rollout", _DeadLeaf())
    while time.time() < deadline:
        with gs._lock:
            if gs._fences_dropped:
                break
        time.sleep(0.005)
    gauges = gs.window()
    assert gauges["obs/graftscope_fences_dropped_total"] == 1.0
    assert "rollout/generate" not in dict(
        gs.snapshot()["windows"][-1]["top_programs"]
    )
    gs.close()
    assert not any(
        t.name == obs_graftscope.DRAIN_THREAD_NAME for t in threading.enumerate()
    )


def test_e2e_graftscope_armed_run_conserves_ledger(task, tmp_path, monkeypatch):
    """The PR 12 acceptance bar: an armed overlapped CPU run keeps
    |device + host + bubble − wall| / wall ≤ 0.05 in EVERY window, writes
    the graftscope.json snapshot, and the report renders the attribution
    section with suggested knobs. Armed via the env override (the config
    knob path is exercised by obs_smoke.py)."""
    monkeypatch.setenv("TRLX_TPU_GRAFTSCOPE", "1")
    monkeypatch.setenv("TRLX_TPU_PEAK_TFLOPS", "0.01")
    _, logit_mask, metric_fn, reward_fn = task
    config = base_config("ppo", 15, 8)
    config.train.total_steps = 8
    config.train.epochs = 4
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.checkpoint_dir = str(tmp_path)
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    config.method.max_staleness = 1
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[1]],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert not any(t.name.startswith("trlx-") for t in threading.enumerate())
    assert not obs_graftscope.armed()  # learn() tears the global scope down

    with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    windows = [r for r in records if "obs/ledger_wall_s" in r]
    assert windows, "armed run produced no ledger windows"
    for r in windows:
        wall = r["obs/ledger_wall_s"]
        err = abs(
            r["obs/ledger_device_busy_s"]
            + r["obs/ledger_host_s"]
            + r["obs/ledger_bubble_s"]
            - wall
        ) / max(wall, 1e-9)
        assert err <= 0.05, (err, r)
        assert r["obs/ledger_error_frac"] <= 0.05
        assert 0.0 <= r["obs/bubble_fraction"] <= 1.0
    assert any(r["obs/ledger_device_busy_s"] > 0 for r in windows)
    assert any(r["obs/lane_busy_producer_s"] > 0 for r in windows)

    with open(os.path.join(str(tmp_path), obs_graftscope.SNAPSHOT_FILENAME)) as f:
        snap = json.load(f)
    assert snap["windows"] and snap["totals"]["wall_s"] > 0
    assert snap["programs_s"], "no per-program device attribution"

    md = report.build_report(str(tmp_path))
    assert "## Device-time attribution (graftscope)" in md
    assert "Top-3 time sinks" in md


# ----------------------------------------------------- RunManifest (PR 12)


def test_run_manifest_lifecycle_torn_tail_and_idempotent_finish(tmp_path):
    path = str(tmp_path / "m.jsonl")
    m = obs_graftscope.RunManifest(path, cmd="bench.py", backend="cpu")
    m.heartbeat("size_ladder", candidate="a")
    m.child("a", 0, "")
    m.partial({"metric": "x", "value": 1.5})
    m.finish(rc=0, metric="x", value=1.5)
    m.finish(rc=1, reason="late duplicate")  # idempotent: first end wins
    with open(path, "ab") as f:
        f.write(b'{"event": "heartbeat", "pha')  # SIGKILL tears the tail
    s = obs_graftscope.RunManifest.read(path)
    assert s["valid"] and s["complete"] and s["rc"] == 0
    assert s["reason"] == "completed rc=0"
    assert s["partial"] == {"metric": "x", "value": 1.5}
    assert s["children"] == [{"label": "a", "rc": 0}]
    with open(path, "rb") as f:
        raw = f.read()
    assert raw.count(b'"event": "end"') == 1  # the duplicate finish was dropped


def test_run_manifest_survives_sigkill_and_names_the_phase(tmp_path):
    """A bench child SIGKILLed mid-ladder (the BENCH_r04/r05 shape) must
    leave a manifest that says when and during what the run died, including
    the last child failure's rc and stderr tail."""
    import signal
    import subprocess

    path = str(tmp_path / "m.jsonl")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child_src = (
        "import sys, time\n"
        "sys.path.insert(0, %r)\n"
        "from trlx_tpu.observability.graftscope import RunManifest\n"
        "m = RunManifest(%r, cmd='bench.py drill')\n"
        "m.heartbeat('size_ladder', candidate='big')\n"
        "m.child('big', 1, 'Traceback...\\nValueError: mosaic lowering failed')\n"
        "m.heartbeat('size_ladder', candidate='small')\n"
        "print('ready', flush=True)\n"
        "time.sleep(60)\n"
    ) % (repo, path)
    proc = subprocess.Popen(
        [sys.executable, "-c", child_src],
        stdout=subprocess.PIPE,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        assert proc.stdout.readline().strip() == "ready"
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    s = obs_graftscope.RunManifest.read(path)
    assert s["valid"] and not s["complete"] and s["rc"] is None
    assert "killed mid-flight during size_ladder" in s["reason"]
    assert "(candidate small)" in s["reason"]
    assert "last child failure big rc=1" in s["reason"]
    assert "ValueError: mosaic lowering failed" in s["reason"]


def test_manifest_reader_parity_with_bench_trajectory_mirror(tmp_path):
    """bench_trajectory.py carries an inline stdlib mirror of
    RunManifest.read (it must stay import-light for CI) — the two must
    produce identical summaries for killed AND completed manifests."""
    import bench_trajectory

    path = str(tmp_path / "m.jsonl")
    m = obs_graftscope.RunManifest(path, cmd="bench.py")
    m.heartbeat("size_ladder", candidate="big")
    m.child("big", 1, "Traceback...\nValueError: mosaic lowering failed")
    m.heartbeat("size_ladder", candidate="small")
    m.partial({"metric": "samples/s/chip", "value": 2.0})
    for stage in ("killed", "completed"):
        if stage == "completed":
            m.finish(rc=0)
        a = obs_graftscope.RunManifest.read(path)
        b = bench_trajectory._read_manifest(path)
        for key in ("valid", "complete", "rc", "reason", "partial", "last_heartbeat"):
            assert a[key] == b[key], (stage, key, a[key], b[key])


def test_bench_trajectory_surfaces_manifest_reason_for_no_data_run(
    tmp_path, monkeypatch
):
    """A gap entry (rc=124, empty tail) picks up the per-run manifest's
    forensic reason instead of the generic artifact-side one."""
    import bench_trajectory

    monkeypatch.chdir(tmp_path)
    with open("BENCH_r07.json", "w") as f:
        json.dump({"rc": 124, "tail": ""}, f)
    m = obs_graftscope.RunManifest("BENCH_MANIFEST_r07.jsonl", cmd="bench")
    m.heartbeat("flagship")
    traj = bench_trajectory.build_trajectory(
        ["BENCH_r07.json"],
        smoke_path="missing.json",
        manifest_path="missing.jsonl",
    )
    entry = traj["runs"][0]
    assert entry["no_data"] and entry["manifest"]
    assert entry["reason"] == "run killed mid-flight during flagship"

    # a clean-finish manifest can NOT explain a no-data artifact: the
    # artifact-side reason must survive
    m.finish(rc=0)
    traj = bench_trajectory.build_trajectory(
        ["BENCH_r07.json"], smoke_path="missing.json", manifest_path="missing.jsonl"
    )
    assert traj["runs"][0]["reason"] == "bench run exited rc=124"
    assert "manifest" not in traj["runs"][0]


def test_bench_trajectory_parses_spec_decode_smoke_section(tmp_path):
    """The smoke fold must surface the spec-decode probe's paired numbers
    (rate + accept rate + speedup) and stay silent when the section is
    absent (pre-PR-19 artifacts)."""
    import bench_trajectory

    path = str(tmp_path / "BENCH_SMOKE.json")
    with open(path, "w") as f:
        json.dump(
            {
                "spec_decode": {
                    "decode_tokens_per_s": 39793.1,
                    "accept_rate": 1.0,
                    "speedup_vs_nonspec": 2.7,
                }
            },
            f,
        )
    out = bench_trajectory._parse_smoke(path)
    assert out["spec_decode_tokens_per_s"] == 39793.1
    assert out["spec_accept_rate"] == 1.0
    assert out["spec_speedup_vs_nonspec"] == 2.7

    with open(path, "w") as f:
        json.dump({"rollout": {"tokens_per_s": 5.0}}, f)
    out = bench_trajectory._parse_smoke(path)
    assert "spec_decode_tokens_per_s" not in out
    assert out["rollout_tokens_per_s"] == 5.0


def test_bench_trajectory_parses_and_gates_paged_kv_section(tmp_path):
    """The paged-KV record's capacity ratio and prefix savings are
    hardware-independent CONTRACTS: the smoke fold must surface them, and
    build_trajectory must flip ``regressed`` when either falls below its
    floor (1.5x slots in the same bytes, >0 prefill reduction) — the one
    smoke-sourced gate. Pre-PR-20 artifacts (no section) stay silent."""
    import bench_trajectory

    path = str(tmp_path / "BENCH_SMOKE.json")
    good = {
        "paged_kv": {
            "slot_capacity_ratio": 1.5,
            "prefill_token_reduction": 0.889,
            "prefix_hits_total": 12,
        }
    }
    with open(path, "w") as f:
        json.dump(good, f)
    out = bench_trajectory._parse_smoke(path)
    assert out["paged_slot_capacity_ratio"] == 1.5
    assert out["paged_prefill_token_reduction"] == 0.889
    assert out["paged_prefix_hits_total"] == 12

    traj = bench_trajectory.build_trajectory(
        [], smoke_path=path, manifest_path="missing.jsonl"
    )
    assert traj["regressed"] is False
    assert any("paged KV" in v and "ok" in v for v in traj["verdict"])

    # capacity below the floor -> gate trips even with no bench runs
    good["paged_kv"]["slot_capacity_ratio"] = 1.2
    with open(path, "w") as f:
        json.dump(good, f)
    traj = bench_trajectory.build_trajectory(
        [], smoke_path=path, manifest_path="missing.jsonl"
    )
    assert traj["regressed"] is True
    assert any("REGRESSION: paged KV" in v for v in traj["verdict"])

    # savings gone -> same trip
    good["paged_kv"].update(slot_capacity_ratio=1.5, prefill_token_reduction=0.0)
    with open(path, "w") as f:
        json.dump(good, f)
    assert bench_trajectory.build_trajectory(
        [], smoke_path=path, manifest_path="missing.jsonl"
    )["regressed"] is True

    # absent section: no paged fields, no paged verdict
    with open(path, "w") as f:
        json.dump({"rollout": {"tokens_per_s": 5.0}}, f)
    traj = bench_trajectory.build_trajectory(
        [], smoke_path=path, manifest_path="missing.jsonl"
    )
    assert "paged_slot_capacity_ratio" not in traj["smoke"]
    assert traj["regressed"] is False
    assert not any("paged" in v for v in traj["verdict"])
