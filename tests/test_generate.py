"""Generation engine: cache-consistency, determinism, eos/pad semantics,
sampler distribution properties (the reference has no generation tests at
all; HF .generate was its tested-by-proxy dependency)."""

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.models import LMConfig, LMWithValueHead
from trlx_tpu.ops.generate import make_generate_fn
from trlx_tpu.ops.sampling import GenerateConfig, top_p_mask, process_logits_default, NEG_INF

import pytest

pytestmark = pytest.mark.slow  # excluded from `make test-fast` (see conftest)


def setup_model():
    cfg = LMConfig(vocab_size=23, n_layer=2, n_head=2, d_model=32, max_position=64, dtype="float32")
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (3, 6), 2, cfg.vocab_size)
    ids = ids.at[0, :2].set(0)
    mask = jnp.ones((3, 6), jnp.int32).at[0, :2].set(0)
    params = {"params": model.init(rng, ids, mask)["params"]}
    return model, params, ids, mask


def test_greedy_matches_incremental_reference():
    model, params, ids, mask = setup_model()
    gcfg = GenerateConfig(max_new_tokens=6, do_sample=False, eos_token_id=1, pad_token_id=0)
    gen = make_generate_fn(model, gcfg)
    toks, m = gen(params, ids, mask, jax.random.PRNGKey(1))

    cur_ids, cur_mask = ids, mask
    B, P = ids.shape
    for _ in range(6):
        out = model.apply(params, cur_ids, cur_mask)
        nxt = jnp.argmax(out["logits"][:, -1].astype(jnp.float32), -1)[:, None]
        cur_ids = jnp.concatenate([cur_ids, nxt], 1)
        cur_mask = jnp.concatenate([cur_mask, jnp.ones((B, 1), jnp.int32)], 1)
    ref, got = np.array(cur_ids[:, P:]), np.array(toks[:, P:])
    m = np.array(m)
    for b in range(B):
        for i in range(6):
            if m[b, P + i] == 0:
                break
            assert ref[b, i] == got[b, i], (b, i)


def test_greedy_matches_hf_generate(tmp_path):
    """Cross-implementation generation parity: our compiled while_loop decode
    vs transformers on the SAME weights (bridged through the HF export),
    greedy, with a left-padded batch — positions, masking, and the KV cache
    all have to agree with a fully independent implementation. Step-wise
    check: HF's greedy choice at every step of OUR prefix must match our
    token, except where HF's top-2 margin is within cross-framework fp32
    error (a genuine near-tie, cf. the 2e-4 logits tolerance in
    tests/test_hf_parity.py)."""
    import pytest

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from trlx_tpu.models import TransformerLM
    from trlx_tpu.models.hf_export import export_hf

    cfg = LMConfig(
        vocab_size=53, n_layer=2, n_head=2, d_model=32, max_position=64,
        pos_type="learned", parallel_residual=False, fused_qkv=True,
        qkv_bias=True, out_bias=True, tie_word_embeddings=True,
        dtype="float32", param_dtype="float32",
    )
    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(7)
    B, P, N = 3, 6, 8
    ids = jax.random.randint(rng, (B, P), 2, cfg.vocab_size)
    ids = ids.at[0, :2].set(0)  # left padding on row 0
    mask = jnp.ones((B, P), jnp.int32).at[0, :2].set(0)
    params = model.init(rng, ids, mask)["params"]

    gcfg = GenerateConfig(max_new_tokens=N, do_sample=False, eos_token_id=None, pad_token_id=0)
    gen = make_generate_fn(model, gcfg)
    ours, _ = gen({"params": params}, ids, mask, jax.random.PRNGKey(1))
    ours = np.asarray(ours[:, P:])

    out_dir = export_hf({"transformer": params}, cfg, str(tmp_path))
    hf = transformers.AutoModelForCausalLM.from_pretrained(out_dir)
    full = np.concatenate([np.asarray(ids), ours], axis=1)
    full_mask = np.concatenate([np.asarray(mask), np.ones((B, N), np.int32)], axis=1)
    # HF's bare forward does NOT derive position ids from the attention mask
    # (only generate() does) — pass the left-pad-aware positions explicitly,
    # the same cumsum rule both we and HF generate() use.
    position_ids = np.maximum(full_mask.cumsum(axis=1) - 1, 0)
    with torch.no_grad():
        logits = hf(
            input_ids=torch.tensor(full),
            attention_mask=torch.tensor(full_mask),
            position_ids=torch.tensor(position_ids),
        ).logits.numpy()
    for b in range(B):
        for t in range(N):
            step = logits[b, P + t - 1]
            chosen = int(ours[b, t])
            top = int(step.argmax())
            if top != chosen:
                margin = float(step[top] - step[chosen])
                assert margin < 1e-3, (
                    f"row {b} step {t}: ours={chosen} hf_top={top} margin={margin}"
                )


def test_int8_kv_cache_decode_close_to_fp():
    """kv_cache_quant=True: cached decode logits stay close to the
    full-precision path (per-token-per-head absmax int8), and greedy decode
    emits the same tokens on a well-separated tiny model."""
    cfg = LMConfig(vocab_size=23, n_layer=2, n_head=2, d_model=32, max_position=64, dtype="float32")
    model = LMWithValueHead(cfg)
    model_q = LMWithValueHead(cfg.replace(kv_cache_quant=True))
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (3, 6), 2, cfg.vocab_size)
    mask = jnp.ones((3, 6), jnp.int32).at[0, :2].set(0)
    ids = ids.at[0, :2].set(0)
    params = {"params": model.init(rng, ids, mask)["params"]}

    gcfg = GenerateConfig(max_new_tokens=8, do_sample=False, eos_token_id=None, pad_token_id=0)
    toks_fp, m_fp = make_generate_fn(model, gcfg)(params, ids, mask, jax.random.PRNGKey(1))
    toks_q, m_q = make_generate_fn(model_q, gcfg)(params, ids, mask, jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(toks_fp), np.asarray(toks_q))
    np.testing.assert_array_equal(np.asarray(m_fp), np.asarray(m_q))

    # logits comparison under teacher forcing through the quantized cache
    from trlx_tpu.models.lm import init_cache

    T = int(toks_fp.shape[1])
    cfg_q = model_q.cfg
    cache = init_cache(cfg_q, 3, T + 1)  # room for one extra decode step
    prefill_mask = jnp.concatenate([m_fp, jnp.zeros((3, 1), jnp.int32)], axis=1)
    out_q = model_q.apply(
        params, toks_fp, m_fp, cache=cache, cache_index=0, cache_mask=prefill_mask
    )
    out_fp = model.apply(params, toks_fp, m_fp)
    # einsum prefill reads through the quantized cache → int8-grade closeness
    np.testing.assert_allclose(
        np.asarray(out_q["logits"]), np.asarray(out_fp["logits"]), atol=0.15
    )

    # single-token decode step reads the QUANTIZED cache → int8-grade
    step_out_q = model_q.apply(
        params,
        toks_fp[:, -1:] * 0 + 5,
        jnp.ones((3, 1), jnp.int32),
        cache=out_q["cache"],
        cache_index=T,
        cache_mask=jnp.concatenate([m_fp, jnp.ones((3, 1), jnp.int32)], axis=1),
    )
    # fp reference for the same step
    cache_fp = init_cache(cfg, 3, T + 1)
    out_fp_c = model.apply(
        params, toks_fp, m_fp, cache=cache_fp, cache_index=0, cache_mask=prefill_mask
    )
    step_out_fp = model.apply(
        params,
        toks_fp[:, -1:] * 0 + 5,
        jnp.ones((3, 1), jnp.int32),
        cache=out_fp_c["cache"],
        cache_index=T,
        cache_mask=jnp.concatenate([m_fp, jnp.ones((3, 1), jnp.int32)], axis=1),
    )
    np.testing.assert_allclose(
        np.asarray(step_out_q["logits"]), np.asarray(step_out_fp["logits"]), atol=0.15
    )
    rel = np.abs(np.asarray(step_out_q["logits"]) - np.asarray(step_out_fp["logits"])).max()
    assert rel > 0  # the quantized path is actually different code


def test_eos_finishes_and_pads():
    model, params, ids, mask = setup_model()
    # eos that the greedy decode definitely emits: run once to find one
    gcfg = GenerateConfig(max_new_tokens=8, do_sample=False, eos_token_id=None, pad_token_id=0)
    toks, _ = make_generate_fn(model, gcfg)(params, ids, mask, jax.random.PRNGKey(1))
    eos = int(np.array(toks)[0, 6 + 1])  # the 2nd generated token of row 0
    gcfg2 = GenerateConfig(max_new_tokens=8, do_sample=False, eos_token_id=eos, pad_token_id=0)
    toks2, mask2 = make_generate_fn(model, gcfg2)(params, ids, mask, jax.random.PRNGKey(1))
    toks2, mask2 = np.array(toks2), np.array(mask2)
    row = toks2[0, 6:]
    hit = np.nonzero(row == eos)[0]
    assert len(hit) > 0
    k = hit[0]
    # everything after the first eos is pad with mask 0
    assert (row[k + 1 :] == 0).all()
    assert (mask2[0, 6 + k + 1 :] == 0).all()
    assert mask2[0, 6 + k] == 1  # the eos token itself is real


def test_sampling_respects_bigram_mask():
    model, params, ids, mask = setup_model()
    vocab = 23
    allowed = np.zeros((vocab, vocab), dtype=bool)
    forbidden = ~allowed
    # only allow token (i+1) % vocab after token i
    for i in range(vocab):
        forbidden[i, (i + 1) % vocab] = False
    from trlx_tpu.ops.sampling import make_bigram_mask_processor, process_logits_default as chain

    bigram = make_bigram_mask_processor(jnp.asarray(forbidden))
    gcfg = GenerateConfig(max_new_tokens=5, do_sample=True, pad_token_id=0)

    def proc(logits, state):
        return chain(bigram(logits, state), gcfg, state["step"])

    gen = make_generate_fn(model, gcfg, processor=proc)
    toks, m = gen(params, ids, mask, jax.random.PRNGKey(3))
    toks = np.array(toks)
    for b in range(3):
        for i in range(6, 11):
            assert toks[b, i] == (toks[b, i - 1] + 1) % vocab


def test_top_p_mask_keeps_nucleus():
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    masked = top_p_mask(logits, 0.7)
    # 0.5 kept; 0.3 kept (cumulative before it = 0.5 < 0.7); rest dropped
    assert masked[0, 0] > NEG_INF / 2 and masked[0, 1] > NEG_INF / 2
    assert masked[0, 2] <= NEG_INF / 2 and masked[0, 3] <= NEG_INF / 2


def test_min_new_tokens_suppresses_eos():
    gcfg = GenerateConfig(max_new_tokens=4, min_new_tokens=3, eos_token_id=2, pad_token_id=0)
    logits = jnp.zeros((1, 5))
    out_early = process_logits_default(logits, gcfg, jnp.array(0))
    out_late = process_logits_default(logits, gcfg, jnp.array(3))
    assert out_early[0, 2] <= NEG_INF / 2
    assert out_late[0, 2] == 0.0


def test_local_attention_decode_matches_teacher_forcing():
    """gpt-neo-style alternating global/local layers: the KV-cache decode path
    must apply the same windowed mask as the full-sequence forward."""
    cfg = LMConfig(
        vocab_size=23,
        n_layer=2,
        n_head=2,
        d_model=32,
        max_position=64,
        dtype="float32",
        scale_attn=False,
        attention_layers=("global", "local"),
        window_size=4,
    )
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (2, 6), 2, cfg.vocab_size)
    mask = jnp.ones((2, 6), jnp.int32)
    params = {"params": model.init(rng, ids, mask)["params"]}

    gcfg = GenerateConfig(max_new_tokens=6, do_sample=False, eos_token_id=None, pad_token_id=0)
    toks, _ = make_generate_fn(model, gcfg)(params, ids, mask, jax.random.PRNGKey(1))

    cur_ids, cur_mask = ids, mask
    for _ in range(6):
        out = model.apply(params, cur_ids, cur_mask)
        nxt = jnp.argmax(out["logits"][:, -1].astype(jnp.float32), -1)[:, None]
        cur_ids = jnp.concatenate([cur_ids, nxt], 1)
        cur_mask = jnp.concatenate([cur_mask, jnp.ones((2, 1), jnp.int32)], 1)
    np.testing.assert_array_equal(np.array(cur_ids[:, 6:]), np.array(toks[:, 6:]))
