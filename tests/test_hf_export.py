"""HF export round-trip: our pytree → HF checkpoint dir → re-import → same
pytree, per family. The reference has no export path at all (its checkpoints
are raw Accelerate state dirs); this guarantees the tuned policy is a
first-class HF artifact."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trlx_tpu.models.hf_export import export_hf, infer_family
from trlx_tpu.models.hf_import import load_hf_trunk
from trlx_tpu.models.lm import LMConfig, TransformerLM

FAMILIES = {
    "gpt2": dict(
        pos_type="learned", parallel_residual=False, fused_qkv=True,
        qkv_bias=True, out_bias=True, tie_word_embeddings=True,
        activation="gelu_new",
    ),
    "gptj": dict(
        pos_type="rotary", rotary_dim=8, parallel_residual=True,
        use_parallel_ln=False, fused_qkv=False, qkv_bias=False,
        out_bias=False, tie_word_embeddings=False, activation="gelu_new",
        extra={"lm_head_bias": True},
    ),
    "gpt_neo": dict(
        pos_type="learned", parallel_residual=False, fused_qkv=False,
        qkv_bias=False, out_bias=True, scale_attn=False,
        attention_layers=("global", "local"), window_size=16,
        tie_word_embeddings=True, activation="gelu_new",
    ),
    "gpt_neox": dict(
        pos_type="rotary", rotary_dim=8, parallel_residual=True,
        use_parallel_ln=True, fused_qkv=True, qkv_bias=True,
        tie_word_embeddings=False, activation="gelu",
        extra={"neox_rotary": True},
    ),
}


def tiny_cfg(family):
    return LMConfig(
        vocab_size=128,
        n_layer=2,
        n_head=2,
        d_model=32,
        max_position=64,
        dtype="float32",
        param_dtype="float32",
        **FAMILIES[family],
    )


def assert_trees_close(a, b, path=""):
    assert set(a) == set(b), f"{path}: {set(a) ^ set(b)}"
    for k in a:
        if isinstance(a[k], dict):
            assert_trees_close(a[k], b[k], f"{path}/{k}")
        else:
            np.testing.assert_allclose(
                np.asarray(a[k], np.float32), np.asarray(b[k], np.float32),
                atol=1e-6, err_msg=f"{path}/{k}",
            )


@pytest.mark.parametrize("family", list(FAMILIES))
def test_roundtrip_trunk(family, tmp_path):
    cfg = tiny_cfg(family)
    assert infer_family(cfg) == family
    model = TransformerLM(cfg)
    ids = jnp.asarray(np.arange(8)[None] % 128)
    params = model.init(jax.random.PRNGKey(0), ids, jnp.ones_like(ids))["params"]

    out = export_hf({"transformer": params}, cfg, str(tmp_path / family))
    back = load_hf_trunk(out, cfg)
    assert_trees_close(params, back, family)


@pytest.mark.parametrize(
    "family,overrides",
    [
        ("gpt2", {"tie_word_embeddings": False}),  # untied head must export
        ("gptj", {"tie_word_embeddings": True, "extra": {}}),  # tied rotary
        ("gptj", {"extra": {}}),  # untied, no lm_head bias
        ("gpt2", {"d_ff": 48}),  # non-default inner dim → n_inner
        ("gpt_neox", {"tie_word_embeddings": True, "activation": "gelu_new",
                      "extra": {"neox_rotary": True}}),
    ],
)
def test_roundtrip_non_canonical_variants(family, overrides, tmp_path):
    """From-scratch archs that deviate from the family's canonical layout
    (tying, head bias, inner dim) must still round-trip exactly."""
    cfg = LMConfig(
        vocab_size=128, n_layer=2, n_head=2, d_model=32, max_position=64,
        dtype="float32", param_dtype="float32",
        **{**FAMILIES[family], **overrides},
    )
    model = TransformerLM(cfg)
    ids = jnp.asarray(np.arange(8)[None] % 128)
    params = model.init(jax.random.PRNGKey(1), ids, jnp.ones_like(ids))["params"]
    out = export_hf({"transformer": params}, cfg, str(tmp_path / "m"))
    back = load_hf_trunk(out, cfg)
    assert_trees_close(params, back, f"{family}+{overrides}")


def test_export_rejects_unrepresentable_semantics(tmp_path):
    """Semantics HF can't express must fail loudly, not export wrong logits."""
    from trlx_tpu.models.hf_export import validate_exportable

    scaled_neo = tiny_cfg("gpt_neo").replace(scale_attn=True)
    with pytest.raises(ValueError, match="UNSCALED"):
        validate_exportable(scaled_neo, "gpt_neo")
    unscaled_gpt2 = tiny_cfg("gpt2").replace(scale_attn=False)
    with pytest.raises(ValueError, match="scale_attn"):
        validate_exportable(unscaled_gpt2, "gpt2")
    neox_rot_gptj = tiny_cfg("gptj").replace(extra={"neox_rotary": True})
    with pytest.raises(ValueError, match="interleaved"):
        validate_exportable(neox_rot_gptj, "gptj")
    # structural mismatches: residual style, biases, local attention
    parallel_gpt2 = tiny_cfg("gpt2").replace(parallel_residual=True)
    with pytest.raises(ValueError, match="sequential"):
        validate_exportable(parallel_gpt2, "gpt2")
    biased_gptj = tiny_cfg("gptj").replace(qkv_bias=True)
    with pytest.raises(ValueError, match="qkv_bias"):
        validate_exportable(biased_gptj, "gptj")
    out_biased_gptj = tiny_cfg("gptj").replace(out_bias=True)
    with pytest.raises(ValueError, match="out_bias"):
        validate_exportable(out_biased_gptj, "gptj")
    local_gpt2 = tiny_cfg("gpt2").replace(attention_layers=("global", "local"))
    with pytest.raises(ValueError, match="local-attention"):
        validate_exportable(local_gpt2, "gpt2")


def test_soft_prompt_exports_to_sidecar(tmp_path):
    """A tuned soft prompt has no HF slot — it must land in the heads npz,
    not silently vanish."""
    cfg = tiny_cfg("gpt2").replace(n_soft_tokens=4)
    model = TransformerLM(cfg)
    ids = jnp.asarray(np.arange(8)[None] % 128)
    params = model.init(jax.random.PRNGKey(0), ids, jnp.ones_like(ids))["params"]
    assert "soft_prompt" in params
    out = export_hf({"transformer": params}, cfg, str(tmp_path / "m"))
    data = np.load(f"{out}/trlx_tpu_heads.npz")
    np.testing.assert_allclose(
        data["soft_prompt"], np.asarray(params["soft_prompt"], np.float32)
    )


def test_export_includes_rl_heads(tmp_path):
    cfg = tiny_cfg("gpt2")
    model = TransformerLM(cfg)
    ids = jnp.asarray(np.arange(8)[None] % 128)
    params = model.init(jax.random.PRNGKey(0), ids, jnp.ones_like(ids))["params"]
    heads = {"v_head": {"layers_0": {"kernel": np.ones((32, 64), np.float32)}}}
    out = export_hf({"transformer": params}, cfg, str(tmp_path / "m"), head_params=heads)
    data = np.load(f"{out}/trlx_tpu_heads.npz")
    np.testing.assert_array_equal(data["v_head/layers_0/kernel"], np.ones((32, 64)))


def test_ilql_trainer_save_pretrained_exports_q_heads(tmp_path):
    """ILQL export: trunk becomes the HF checkpoint; the vocab-wide Q heads
    and V head ride in the sidecar npz."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))
    from randomwalks import base_config

    from trlx_tpu.trainer.ilql import ILQLTrainer

    config = base_config("ilql", 15, 8)
    config.train.batch_size = 16
    config.train.checkpoint_dir = str(tmp_path / "ck")
    config.model.model_arch.update(
        {"pos_type": "learned", "fused_qkv": True, "tie_word_embeddings": True}
    )
    trainer = ILQLTrainer(config)
    out = trainer.save_pretrained(str(tmp_path / "hf"))
    data = np.load(f"{out}/trlx_tpu_heads.npz")
    head_keys = set(data.files)
    assert any(k.startswith("q1_head/") for k in head_keys)
    assert any(k.startswith("q2_head/") for k in head_keys)
    assert any(k.startswith("v_head/") for k in head_keys)
    back = load_hf_trunk(out, trainer.model.cfg)
    orig = jax.device_get(trainer.state.params)["transformer"]
    assert_trees_close(orig, back, "ilql-trainer")


def test_trainer_save_pretrained_roundtrips(tmp_path):
    """End-to-end: a PPOTrainer's trained params export to an HF dir that a
    FRESH trainer can load as model_path — the full RLHF→HF→RLHF cycle."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))
    from randomwalks import base_config

    from trlx_tpu.trainer.ppo import PPOTrainer

    config = base_config("ppo", 15, 8)
    config.train.batch_size = 16
    config.method.chunk_size = 16
    config.method.num_rollouts = 16
    config.train.checkpoint_dir = str(tmp_path / "ck")
    # randomwalks arch is gpt2-family modulo flags; force canonical gpt2
    config.model.model_arch.update(
        {"pos_type": "learned", "fused_qkv": True, "tie_word_embeddings": True}
    )
    trainer = PPOTrainer(config)
    out = trainer.save_pretrained(str(tmp_path / "hf"))

    from trlx_tpu.models.hf_import import load_hf_trunk

    back = load_hf_trunk(out, trainer.model.cfg)
    orig = jax.device_get(trainer.state.params)["transformer"]
    assert_trees_close(orig, back, "trainer")
