"""graftlint (trlx_tpu.analysis) fixtures: every rule fires on its violating
fixture, stays suppressed with a reason, and passes on the clean variant —
plus the tree-wide zero-findings gate, the CLI contract, and the
no-jax-import contract that keeps `make lint` CPU-only and fast.

These tests never import jax themselves on the lint path: the whole suite
runs on the stdlib ast machinery.
"""

import json
import os
import subprocess
import sys
import textwrap

from trlx_tpu.analysis import RULE_TITLES, lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_source(tmp_path, source, relpath="fixture.py", select=None):
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    findings, _ = lint_paths([str(path)], select=select)
    return findings


def _active(findings, rule):
    return [f for f in findings if not f.suppressed and f.rule == rule]


# ------------------------------------------------------------------- GL001


GL001_VIOLATION = """
class Trainer:
    def rollout(self, batch):
        tokens = self._generate_fn(self.state.params, batch)
        return tokens
"""

GL001_CLEAN = """
class Trainer:
    def rollout(self, batch):
        with self._dispatch_lock:
            tokens = self._generate_fn(self.state.params, batch)
        return tokens
"""


def test_gl001_fires_on_unlocked_dispatch(tmp_path):
    findings = _lint_source(tmp_path, GL001_VIOLATION)
    hits = _active(findings, "GL001")
    assert len(hits) == 1 and "_generate_fn" in hits[0].message


def test_gl001_clean_under_lock(tmp_path):
    assert _active(_lint_source(tmp_path, GL001_CLEAN), "GL001") == []


def test_gl001_engine_dispatch_context_counts_as_lock(tmp_path):
    src = """
    class Engine:
        def step(self):
            with self._dispatch():
                state, live = self._decode(self._variables, self._state)
            self._state = state
    """
    assert _active(_lint_source(tmp_path, src), "GL001") == []


def test_gl001_builder_call_of_call_fires(tmp_path):
    src = """
    class Trainer:
        def score(self, chunk):
            return self._score_fn_for(chunk.shape[1])(self.state.params, chunk)
    """
    hits = _active(_lint_source(tmp_path, src), "GL001")
    assert len(hits) == 1 and "_score_fn_for" in hits[0].message


def test_gl001_pr5_unlocked_producer_fixture_is_flagged(tmp_path):
    # The PR 5 incident shape: the rollout-producer thread dispatching the
    # generate program concurrently with the main thread's train_step —
    # exactly the interleaved-enqueue deadlock the rule encodes.
    src = """
    class OverlappedTrainer:
        def _producer_loop(self):
            while not self._stop.is_set():
                chunk = self.queue.get()
                ids, mask = self._generate_fn(self.state.params, chunk)
                self.out.put((ids, mask))
    """
    hits = _active(_lint_source(tmp_path, src), "GL001")
    assert len(hits) == 1


def test_gl001_suppression_with_reason_waives(tmp_path):
    src = """
    class Trainer:
        def rollout(self, batch):
            tokens = self._generate_fn(self.state.params, batch)  # graftlint: disable=GL001 -- serial harness, no worker threads
            return tokens
    """
    findings = _lint_source(tmp_path, src)
    assert _active(findings, "GL001") == []
    waived = [f for f in findings if f.suppressed and f.rule == "GL001"]
    assert len(waived) == 1 and "serial harness" in waived[0].reason


def test_gl000_suppression_without_reason_is_itself_a_finding(tmp_path):
    src = """
    class Trainer:
        def rollout(self, batch):
            tokens = self._generate_fn(self.state.params, batch)  # graftlint: disable=GL001
            return tokens
    """
    findings = _lint_source(tmp_path, src)
    assert len(_active(findings, "GL000")) == 1
    # a reasonless disable still waives nothing
    assert len(_active(findings, "GL001")) == 1


# ------------------------------------------------------------------- GL002


def test_gl002_fires_on_read_after_donate(tmp_path):
    src = """
    class Trainer:
        def learn(self, batch):
            new_state, stats = self.train_step(self.state, batch)
            grad_norm = self.state.params["w"]
            return new_state, grad_norm
    """
    hits = _active(_lint_source(tmp_path, src), "GL002")
    assert len(hits) == 1 and "self.state" in hits[0].message


def test_gl002_same_statement_rebind_is_clean(tmp_path):
    src = """
    class Trainer:
        def learn(self, batch):
            self.state, stats = self.train_step(self.state, batch)
            grad_norm = self.state.params["w"]
            return grad_norm
    """
    assert _active(_lint_source(tmp_path, src), "GL002") == []


def test_gl002_discovers_local_jit_donations(tmp_path):
    src = """
    import jax

    class Engine:
        def build(self):
            self._advance = jax.jit(self._advance_impl, donate_argnums=(1,))

        def run(self, carry, x):
            out = self._advance(self.vars, carry)
            stale = carry["kv"]
            return out, stale
    """
    hits = _active(_lint_source(tmp_path, src), "GL002")
    assert len(hits) == 1 and "'carry'" in hits[0].message


def test_gl002_rebind_then_read_is_clean(tmp_path):
    src = """
    class Engine:
        def run(self, carry, x):
            carry = self._decode(self.vars, carry)
            fresh = carry["kv"]
            return fresh
    """
    assert _active(_lint_source(tmp_path, src), "GL002") == []


# ------------------------------------------------------------------- GL003


def test_gl003_fires_on_host_side_effect_in_traced_body(tmp_path):
    src = """
    import jax

    def step_body(x):
        print("tracing", x)
        return x * 2

    step = jax.jit(step_body)
    """
    hits = _active(_lint_source(tmp_path, src), "GL003")
    assert len(hits) == 1 and "print()" in hits[0].message


def test_gl003_fires_on_time_call_in_scan_body(tmp_path):
    src = """
    import time
    import jax

    def scan_body(carry, x):
        t0 = time.time()
        return carry + x, t0

    out = jax.lax.scan(scan_body, 0, xs)
    """
    hits = _active(_lint_source(tmp_path, src), "GL003")
    assert len(hits) == 1 and "time.time" in hits[0].message


def test_gl003_pure_traced_body_is_clean(tmp_path):
    src = """
    import jax
    import jax.numpy as jnp

    def step_body(x):
        return jnp.tanh(x) * 2

    step = jax.jit(step_body)
    """
    assert _active(_lint_source(tmp_path, src), "GL003") == []


def test_gl003_host_calls_outside_traced_bodies_are_fine(tmp_path):
    src = """
    def host_loop(xs):
        print("host side is allowed to print")
        return [x * 2 for x in xs]
    """
    assert _active(_lint_source(tmp_path, src), "GL003") == []


# ------------------------------------------------------------------- GL004


def test_gl004_fires_on_bare_collective(tmp_path):
    src = """
    from jax.experimental import multihost_utils

    def agree(v):
        return multihost_utils.broadcast_one_to_all(v)
    """
    hits = _active(_lint_source(tmp_path, src), "GL004")
    assert len(hits) == 1 and "broadcast_one_to_all" in hits[0].message


def test_gl004_guarded_collective_is_clean(tmp_path):
    src = """
    from jax.experimental import multihost_utils
    from trlx_tpu.resilience.distributed import collective_guard

    def agree(v):
        with collective_guard("agree"):
            return multihost_utils.broadcast_one_to_all(v)
    """
    assert _active(_lint_source(tmp_path, src), "GL004") == []


def test_gl004_guard_home_is_exempt(tmp_path):
    src = """
    from jax.experimental import multihost_utils

    def _impl(v):
        return multihost_utils.broadcast_one_to_all(v)
    """
    findings = _lint_source(tmp_path, src, relpath="resilience/distributed.py")
    assert _active(findings, "GL004") == []


# ------------------------------------------------------------------- GL005


def test_gl005_fires_on_truthy_new_knob_default(tmp_path):
    src = """
    from dataclasses import dataclass

    @dataclass
    class FixtureConfig:
        shiny_new_feature: bool = True
    """
    findings = _lint_source(tmp_path, src, relpath="data/configs.py")
    hits = _active(findings, "GL005")
    assert len(hits) == 1 and "shiny_new_feature" in hits[0].message


def test_gl005_off_default_knob_is_clean(tmp_path):
    src = """
    from dataclasses import dataclass

    @dataclass
    class FixtureConfig:
        shiny_new_feature: bool = False
        optional_depth: int = 0
    """
    findings = _lint_source(tmp_path, src, relpath="data/configs.py")
    assert _active(findings, "GL005") == []


def test_gl005_fires_on_undeclared_knob_read(tmp_path):
    src = """
    def setup(config):
        depth = config.method.totally_undeclared_knob
        fallback = getattr(config.method, "typo_knbo", None)
        return depth, fallback
    """
    hits = _active(_lint_source(tmp_path, src), "GL005")
    assert len(hits) == 2
    assert any("totally_undeclared_knob" in f.message for f in hits)
    assert any("typo_knbo" in f.message for f in hits)


def test_gl005_declared_knob_read_is_clean(tmp_path):
    src = """
    def setup(config):
        g = config.method.gamma
        ci = config.train.checkpoint_interval
        m = config.method
        return g, ci, getattr(m, "chunk_size", 1)
    """
    assert _active(_lint_source(tmp_path, src), "GL005") == []


# ------------------------------------------------------------------- GL006


def test_gl006_fires_on_adhoc_blockspec_in_ops(tmp_path):
    src = """
    from jax.experimental import pallas as pl

    def kernel(x):
        spec = pl.BlockSpec((128, 128), lambda i: (i, 0))
        return spec
    """
    findings = _lint_source(tmp_path, src, relpath="ops/custom_kernel.py")
    hits = _active(findings, "GL006")
    assert len(hits) == 1 and "BlockSpec" in hits[0].message


def test_gl006_clean_with_tiling_provenance(tmp_path):
    src = """
    from jax.experimental import pallas as pl

    from trlx_tpu.ops.tiling import check_layout, flash_block_layout

    def kernel(x, bq, bk):
        check_layout(flash_block_layout(8, 128, 64, bq, bk))
        spec = pl.BlockSpec((bq, 64), lambda i: (i, 0))
        return spec
    """
    findings = _lint_source(tmp_path, src, relpath="ops/custom_kernel.py")
    assert _active(findings, "GL006") == []


def test_gl006_only_applies_under_ops(tmp_path):
    src = """
    from jax.experimental import pallas as pl

    def helper(x):
        return pl.BlockSpec((8, 8), lambda i: (i, 0))
    """
    findings = _lint_source(tmp_path, src, relpath="pipeline/helper.py")
    assert _active(findings, "GL006") == []


# ------------------------------------------------------------------- GL007


def test_gl007_fires_on_unsanitizable_key(tmp_path):
    src = """
    def stats():
        return {"rollout/mean reward": 1.0}
    """
    hits = _active(_lint_source(tmp_path, src), "GL007")
    assert len(hits) == 1 and "mean reward" in hits[0].message


def test_gl007_fires_on_cross_key_collision(tmp_path):
    src = """
    def stats(tracker):
        tracker.log({"engine/tps": 1.0})
        tracker.log({"engine.tps": 2.0})
    """
    hits = _active(_lint_source(tmp_path, src), "GL007")
    assert len(hits) == 2 and all("collides" in f.message for f in hits)


def test_gl007_namespaced_keys_are_clean(tmp_path):
    src = """
    def stats(tracker):
        tracker.log({"ppo/policy_loss": 0.1, "engine/slot_occupancy": 0.9})
        tracker.log_histogram("rollout/response_len", [1, 2, 3])
    """
    assert _active(_lint_source(tmp_path, src), "GL007") == []


# --------------------------------------------------------- tree-wide gates


def test_real_tree_lints_clean():
    """Tier-1 gate: the shipped tree must carry zero unsuppressed findings —
    new violations fail here before they fail in production."""
    findings, n_files = lint_paths([os.path.join(REPO, "trlx_tpu")])
    offenders = [f.render() for f in findings if not f.suppressed]
    assert offenders == [], "\n".join(offenders)
    assert n_files > 50  # the walk actually covered the tree


def test_rule_titles_cover_all_registered_rules():
    from trlx_tpu.analysis.rules import GLOBAL_RULES, PER_MODULE_RULES

    registered = {rid for rid, _ in PER_MODULE_RULES + GLOBAL_RULES}
    assert registered <= set(RULE_TITLES)


def test_gl007_sanitize_mirror_matches_exporter():
    """The lint-side sanitizer must not drift from the runtime exporter's
    (they are separate implementations so the lint path stays jax-free)."""
    from trlx_tpu.analysis.rules import _sanitize
    from trlx_tpu.observability.export import sanitize_metric_name

    for name in [
        "ppo/policy_loss", "engine.tps", "a b", "9lives", "watchdog-fires",
        "nested/a.b-c", "ok_name", ":colon", "Ünïcode/x",
    ]:
        assert _sanitize(name) == sanitize_metric_name(name), name


# ------------------------------------------------------------------- CLI


def test_cli_json_output_and_exit_code_on_findings(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(GL001_VIOLATION))
    proc = subprocess.run(
        [sys.executable, "-m", "trlx_tpu.analysis", str(bad), "--json"],
        cwd=REPO, capture_output=True, text=True,
    )
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["tool"] == "graftlint" and payload["files"] == 1
    assert any(f["rule"] == "GL001" for f in payload["findings"])


def test_cli_exit_zero_on_clean_fixture(tmp_path):
    good = tmp_path / "good.py"
    good.write_text(textwrap.dedent(GL001_CLEAN))
    proc = subprocess.run(
        [sys.executable, "-m", "trlx_tpu.analysis", str(good)],
        cwd=REPO, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_rejects_unknown_rule_selector(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "trlx_tpu.analysis", "--select", "GL999", str(tmp_path)],
        cwd=REPO, capture_output=True, text=True,
    )
    assert proc.returncode == 2


def test_lint_path_never_imports_jax():
    """`make lint` must run on CPU-only CI images in seconds: importing the
    analysis package and linting the full tree may not pull in jax."""
    code = (
        "import sys\n"
        "from trlx_tpu.analysis import lint_paths\n"
        "findings, n = lint_paths(['trlx_tpu'])\n"
        "assert n > 50\n"
        "assert 'jax' not in sys.modules, 'lint path imported jax'\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, capture_output=True, text=True
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
