"""Pallas flash attention: kernel numerics + full-model parity vs the XLA
einsum path (interpret mode on CPU — the same kernel code that runs on TPU).
The reference has no kernels of its own to test; this is new surface."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trlx_tpu.models import LMConfig, TransformerLM
from trlx_tpu.ops.flash_attention import flash_attention

pytestmark = pytest.mark.slow  # excluded from `make test-fast` (see conftest)


def ref_attn(q, k, v, kvmask, scale, window=0):
    T = q.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    qi = jnp.arange(T)[:, None]
    ki = jnp.arange(T)[None, :]
    m = ki <= qi
    if window:
        m = m & (ki > qi - window)
    m = m[None, None] & kvmask[:, None, None, :].astype(bool)
    s = jnp.where(m, s, -1e9)
    return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v.astype(jnp.float32))


@pytest.mark.parametrize("window", [0, 40])
def test_kernel_forward_and_grads_match_reference(window):
    rng = np.random.default_rng(0)
    b, T, h, d = 2, 256, 2, 32
    q, k, v = (jnp.asarray(rng.standard_normal((b, T, h, d)), jnp.float32) for _ in range(3))
    kvmask = jnp.ones((b, T), jnp.int32).at[0, :17].set(0)  # left padding
    qvalid = kvmask[:, :, None, None].astype(jnp.float32)
    scale = d**-0.5

    o = flash_attention(q, k, v, kvmask, scale=scale, window=window)
    r = ref_attn(q, k, v, kvmask, scale, window)
    # Pad query rows are excluded: both paths emit meaningless (differently
    # normalized) uniform mixes there, and every loss masks them.
    np.testing.assert_allclose(np.asarray((o - r) * qvalid), 0.0, atol=2e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v)) * qvalid)

    gf = jax.grad(loss(lambda q, k, v: flash_attention(q, k, v, kvmask, scale=scale, window=window)), (0, 1, 2))(q, k, v)
    gr = jax.grad(loss(lambda q, k, v: ref_attn(q, k, v, kvmask, scale, window)), (0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-5)


def test_model_flash_matches_xla_path():
    """Full TransformerLM (alternating local layers, rotary, left padding):
    attn_impl='flash' must reproduce attn_impl='xla' logits and grads."""
    base = dict(
        vocab_size=97,
        n_layer=2,
        n_head=2,
        d_model=32,
        max_position=512,
        pos_type="rotary",
        rotary_dim=8,
        attention_layers=("global", "local"),
        window_size=64,
        dtype="float32",
    )
    rng = np.random.default_rng(1)
    B, T = 2, 256
    ids = jnp.asarray(rng.integers(0, 97, (B, T)))
    mask = jnp.ones((B, T), jnp.int32).at[0, :13].set(0)
    fmask = mask[:, :, None].astype(jnp.float32)

    xla_model = TransformerLM(LMConfig(**base, attn_impl="xla"))
    flash_model = TransformerLM(LMConfig(**base, attn_impl="flash"))
    params = xla_model.init(jax.random.PRNGKey(0), ids, mask)["params"]

    lx = xla_model.apply({"params": params}, ids, mask)["logits"]
    lf = flash_model.apply({"params": params}, ids, mask)["logits"]
    np.testing.assert_allclose(
        np.asarray(lf * fmask), np.asarray(lx * fmask), atol=2e-4
    )

    def loss(model):
        def f(p):
            out = model.apply({"params": p}, ids, mask)["logits"]
            return jnp.sum(jnp.tanh(out) * fmask)

        return f

    from jax.flatten_util import ravel_pytree

    gx = jax.grad(loss(xla_model))(params)
    gf = jax.grad(loss(flash_model))(params)
    flat_x, _ = ravel_pytree(gx)
    flat_f, _ = ravel_pytree(gf)
    np.testing.assert_allclose(np.asarray(flat_f), np.asarray(flat_x), atol=5e-4)


def test_prefill_flash_matches_xla_cache_path():
    """Generation prefill (cache present, write offset 0) through the flash
    kernel must reproduce the einsum-over-cache path bit-for-nearly-bit:
    logits AND the written KV cache."""
    from trlx_tpu.models.lm import init_cache

    base = dict(
        vocab_size=97,
        n_layer=2,
        n_head=2,
        d_model=32,
        max_position=512,
        pos_type="rotary",
        rotary_dim=8,
        dtype="float32",
    )
    rng = np.random.default_rng(3)
    B, P, N = 2, 128, 32
    ids = jnp.asarray(rng.integers(0, 97, (B, P)))
    mask = jnp.ones((B, P), jnp.int32).at[0, :13].set(0)  # left padding

    xla_model = TransformerLM(LMConfig(**base, attn_impl="xla"))
    flash_model = TransformerLM(LMConfig(**base, attn_impl="flash"))
    params = xla_model.init(jax.random.PRNGKey(0), ids, mask)["params"]

    def prefill(model):
        cfg = model.cfg
        cache = init_cache(cfg, B, P + N)
        cache_mask = jnp.concatenate([mask, jnp.zeros((B, N), jnp.int32)], axis=1)
        return model.apply(
            {"params": params}, ids, mask, cache=cache, cache_index=0, cache_mask=cache_mask
        )

    ox = prefill(xla_model)
    of = prefill(flash_model)
    fmask = mask[:, :, None].astype(jnp.float32)
    np.testing.assert_allclose(
        np.asarray(of["logits"] * fmask), np.asarray(ox["logits"] * fmask), atol=2e-4
    )
    # The cache writes are identical at every VALID slot regardless of the
    # attention engine. (Pad-slot k/v in layers > 0 differ: each engine emits
    # a different — equally meaningless — attention mix for fully-masked pad
    # query rows, which feeds the next layer's projections there. Those slots
    # have cache_mask 0 and are never read by decode.)
    cmask = np.zeros((B, P + N, 1, 1), np.float32)
    cmask[:, :P] = np.asarray(mask, np.float32)[:, :, None, None]
    for (kf, vf), (kx, vx) in zip(of["cache"], ox["cache"]):
        np.testing.assert_allclose(np.asarray(kf) * cmask, np.asarray(kx) * cmask, atol=1e-5)
        np.testing.assert_allclose(np.asarray(vf) * cmask, np.asarray(vx) * cmask, atol=1e-5)


def test_auto_routing_thresholds(monkeypatch):
    from trlx_tpu.models import lm as lm_mod
    from trlx_tpu.models.lm import flash_eligible

    # Off-TPU (these tests), auto NEVER picks the (interpret-mode) kernel —
    # the einsum path is far faster there.
    auto = LMConfig(attn_impl="auto")
    assert not flash_eligible(auto, 512, has_cache=False)

    # On TPU, auto takes long aligned full-sequence passes only.
    monkeypatch.setattr(lm_mod.jax, "default_backend", lambda: "tpu")
    assert not flash_eligible(auto, 64, has_cache=False)  # short RLHF seqs
    assert flash_eligible(auto, 512, has_cache=False)
    assert flash_eligible(auto, 768, has_cache=False)  # 128-aligned, non-512
    assert not flash_eligible(auto, 512, has_cache=True)  # mid-decode replay
    assert not flash_eligible(auto, 1, has_cache=True, prefill_at_zero=False)  # decode step
    # generation prefill at write offset 0: eligible when long + aligned
    assert flash_eligible(auto, 512, has_cache=True, prefill_at_zero=True)
    assert not flash_eligible(auto, 64, has_cache=True, prefill_at_zero=True)
    assert not flash_eligible(auto, 300, has_cache=False)  # unaligned
    forced = LMConfig(attn_impl="flash")
    assert flash_eligible(forced, 48, has_cache=False)
    assert not flash_eligible(LMConfig(attn_impl="xla"), 512, has_cache=False)
    with pytest.raises(ValueError):
        flash_eligible(LMConfig(attn_impl="pallas"), 512, has_cache=False)

    from trlx_tpu.ops.flash_attention import pick_block

    assert pick_block(2048) == 512
    assert pick_block(768) == 256
    assert pick_block(48) == 48
