"""Native host data-path (C++ collate.cpp via ctypes): build, semantics, and
exact parity with the numpy fallbacks. The reference outsources this layer to
torch's C++ (pad_sequence/DataLoader, reference:
trlx/pipeline/ppo_pipeline.py:39-66); here it is first-party code and tested
against its own fallback."""

import numpy as np
import pytest

import trlx_tpu.native as native
from trlx_tpu.native import RolloutBuffer, native_available, pad_ragged


def test_native_builds():
    assert native_available(), f"g++ build failed: {native._lib_err}"


@pytest.mark.parametrize("left_pad", [True, False])
@pytest.mark.parametrize("keep_last", [True, False])
def test_pad_ragged_matches_fallback(monkeypatch, left_pad, keep_last):
    rng = np.random.default_rng(0)
    rows = [list(rng.integers(1, 100, rng.integers(0, 13))) for _ in range(37)]
    got = pad_ragged(rows, max_len=8, pad_id=0, left_pad=left_pad, keep_last=keep_last)

    monkeypatch.setattr(native, "_build_and_load", lambda: None)
    want = pad_ragged(rows, max_len=8, pad_id=0, left_pad=left_pad, keep_last=keep_last)
    np.testing.assert_array_equal(got[0], want[0])
    np.testing.assert_array_equal(got[1], want[1])


def test_pad_ragged_disciplines():
    ids, mask = pad_ragged([[1, 2, 3], [4], [5, 6, 7, 8, 9]], 4, pad_id=-1)
    assert ids.tolist() == [[-1, 1, 2, 3], [-1, -1, -1, 4], [6, 7, 8, 9]]
    assert mask.tolist() == [[0, 1, 1, 1], [0, 0, 0, 1], [1, 1, 1, 1]]
    ids, _ = pad_ragged([[5, 6, 7, 8, 9]], 4, pad_id=0, left_pad=False, keep_last=False)
    assert ids.tolist() == [[5, 6, 7, 8]]


def _roundtrip(buf):
    rng = np.random.default_rng(1)
    a1 = rng.standard_normal((5, 3)).astype(np.float32)
    b1 = rng.integers(0, 50, (5, 2)).astype(np.int32)
    buf.push({"a": a1, "b": b1})
    a2 = rng.standard_normal((4, 3)).astype(np.float32)
    b2 = rng.integers(0, 50, (4, 2)).astype(np.int32)
    buf.push({"a": a2, "b": b2})
    assert len(buf) == 9
    ixs = np.asarray([8, 0, 5, 5, 2])
    g = buf.gather(ixs)
    ref_a = np.concatenate([a1, a2])[ixs]
    ref_b = np.concatenate([b1, b2])[ixs]
    np.testing.assert_array_equal(g["a"], ref_a)
    np.testing.assert_array_equal(g["b"], ref_b)
    buf.clear()
    assert len(buf) == 0


def test_rollout_buffer_native():
    buf = RolloutBuffer([("a", 3, np.float32), ("b", 2, np.int32)])
    assert buf._lib is not None
    _roundtrip(buf)


def test_rollout_buffer_fallback(monkeypatch):
    monkeypatch.setattr(native, "_build_and_load", lambda: None)
    buf = RolloutBuffer([("a", 3, np.float32), ("b", 2, np.int32)])
    assert buf._lib is None
    _roundtrip(buf)


def test_ppo_storage_roundtrip():
    from trlx_tpu.pipeline.ppo_pipeline import PPORolloutStorage

    store = PPORolloutStorage(pad_token_id=0)
    rng = np.random.default_rng(2)
    P, R, N = 4, 3, 10
    store.push_batch(
        {
            "query_tensors": rng.integers(0, 9, (N, P)),
            "query_mask": np.ones((N, P), np.int32),
            "response_tensors": rng.integers(0, 9, (N, R)),
            "response_mask": np.ones((N, R), np.int32),
            "logprobs": rng.standard_normal((N, R)).astype(np.float32),
            "values": rng.standard_normal((N, R)).astype(np.float32),
            "rewards": rng.standard_normal((N, R)).astype(np.float32),
        }
    )
    # element API (reference-shaped) interops with the chunked path
    e = store[3]
    store.push([e, e])
    assert len(store) == 12

    loader = store.create_loader(batch_size=4, shuffle=True, seed=0)
    batches = list(loader)
    assert len(batches) == 3
    assert batches[0].query_tensors.shape == (4, P)
    assert batches[0].logprobs.dtype == np.float32
    store.clear_history()
    assert len(store) == 0


def test_gather_index_semantics():
    buf = RolloutBuffer([("a", 2, np.int32)])
    buf.push({"a": np.arange(10, dtype=np.int32).reshape(5, 2)})
    # negative indices normalize Python-style before the unchecked C memcpy
    np.testing.assert_array_equal(buf.gather(np.asarray([-1]))["a"], [[8, 9]])
    with pytest.raises(IndexError):
        buf.gather(np.asarray([5]))
    with pytest.raises(IndexError):
        buf.gather(np.asarray([-6]))
    # empty chunk push is a no-op
    assert buf.push({"a": np.zeros((0, 2), np.int32)}) == 5
