"""Fused in-decode rollout statistics: the decode loop emits the policy
logprobs/values/branch-hiddens the scorer needs, so rollout scoring becomes a
ref-branch replay only. These tests pin the fused path to the unfused full
re-forward numerically, and run it end to end."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402

pytestmark = pytest.mark.slow  # excluded from `make test-fast` (see conftest)


@pytest.fixture(scope="module")
def task():
    return generate_random_walks(n_nodes=15, max_length=8, n_walks=60, seed=1000)


def _hydra_config(tmp_path, total_steps=4):
    config = base_config("ppo", 15, 8)
    config.train.total_steps = total_steps
    config.train.epochs = 2
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.checkpoint_dir = str(tmp_path)
    config.model.num_layers_unfrozen = 1  # branch_layer = n_layer - 1 >= 0
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    return config


def test_fused_matches_unfused_scoring(task, tmp_path):
    """Same tokens, same scores: the fused scorer (decode-collected stats +
    ref-branch replay) must reproduce the unfused full-re-forward scorer's
    logprobs, values, rewards, and KL on valid response positions — and
    record EXACT ZEROS after a row finishes. No logit_mask here, so the tiny
    random policy samples eos (token 0) early in some rows, making the
    post-finish assertions non-vacuous (asserted below)."""
    from trlx_tpu.trainer.ppo import PPOTrainer

    walks, logit_mask, metric_fn, reward_fn = task
    config = _hydra_config(tmp_path)
    trainer = PPOTrainer(config)
    assert trainer.fused_rollout

    rng = np.random.default_rng(0)
    prompts = rng.integers(1, 15, size=(16, 1)).astype(np.int32)
    pmask = np.ones_like(prompts)

    tokens, mask, stats, prefill = trainer.rollout_generate_fused(prompts, pmask)
    scores = rng.normal(size=(16,)).astype(np.float32)

    f_lp, f_v, f_rw, f_kl = (
        np.asarray(x) for x in trainer.rollout_score_fused(tokens, mask, scores, (stats, prefill))
    )
    u_lp, u_v, u_rw, u_kl = (
        np.asarray(x) for x in trainer.rollout_score(tokens, mask, scores)
    )

    P = trainer.prompt_length
    rmask = np.asarray(mask)[:, P:].astype(bool)
    assert rmask.any()
    assert (~rmask).any(), "no row finished early — the zero-pad assertions would be vacuous"
    np.testing.assert_allclose(f_lp[rmask], u_lp[rmask], rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(f_v[rmask], u_v[rmask], rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(f_rw[rmask], u_rw[rmask], rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(f_kl[rmask], u_kl[rmask], rtol=1e-4, atol=1e-4)
    # Post-finish entries are exact zeros in the fused stats (generate()
    # masks step stats by liveness) — the pad_sequence convention.
    assert np.all(f_lp[~rmask] == 0)
    assert np.all(f_v[~rmask] == 0)


def test_fused_rollout_e2e_learns(task, tmp_path):
    """Full train() through the fused rollout path (hydra model): the run
    completes and the fused flag actually engaged."""
    from trlx_tpu.trainer.ppo import PPOTrainer  # noqa: F401

    walks, logit_mask, metric_fn, reward_fn = task
    config = _hydra_config(tmp_path)
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.fused_rollout
    assert model.iter_count >= 4
    assert len(model.store) > 0


def test_fused_disengages_without_branch(task, tmp_path):
    """Fully-unfrozen models (no hydra branch) must fall back to the unfused
    scorer — the frozen ref there is a full separate forward."""
    from trlx_tpu.trainer.ppo import PPOTrainer

    walks, logit_mask, metric_fn, reward_fn = task
    config = _hydra_config(tmp_path)
    config.model.num_layers_unfrozen = -1
    trainer = PPOTrainer(config, logit_mask=logit_mask)
    assert not trainer.fused_rollout


def test_fused_rollout_learning_gate(tmp_path):
    """Learning-QUALITY gate for the fused path (the default for hydra
    models): the n=21 randomwalks config must reach ≥0.8 eval optimality in
    48 steps with a frozen bottom layer — a fused-stats numerics regression
    (stale logprobs, wrong value alignment) fails this even if the smokes
    pass. Measured headroom: ~0.95 by step 48."""
    n_nodes, max_length = 21, 10
    walks, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=n_nodes, max_length=max_length
    )
    config = base_config("ppo", n_nodes, max_length)
    config.train.total_steps = 48
    config.train.eval_interval = 16
    config.train.checkpoint_interval = 10**6
    config.train.checkpoint_dir = str(tmp_path)
    config.train.batch_size = 48
    config.model.num_layers_unfrozen = 1
    config.method.num_rollouts = 96
    config.method.chunk_size = 48

    history = []

    def gated_metric(samples):
        m = metric_fn(samples)
        history.append(float(np.mean(m["optimality"])))
        return m

    prompts = [[int(np.random.default_rng(i).integers(1, n_nodes))] for i in range(96)]
    model = trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts,
        eval_prompts=[[i] for i in range(1, n_nodes)], metric_fn=gated_metric,
        config=config, logit_mask=logit_mask,
    )
    assert model.fused_rollout
    assert history, "no eval ever ran"
    assert max(history) >= 0.8, f"fused-path optimality history: {history}"


def test_fused_with_int8_kv_cache_close_to_recompute(task, tmp_path):
    """int8 decode KV cache + fused stats: the stored behavior logprobs are
    the quantized sampler's OWN (the true behavior distribution); their gap
    to the full-precision recompute must stay far below cliprange (measured
    ~0.003 mean / ~0.008 max on this model; asserted at 0.05). The fused+int8
    combination also passes the learning gate — see the trainer comment."""
    from trlx_tpu.trainer.ppo import PPOTrainer

    walks, logit_mask, metric_fn, reward_fn = task
    config = _hydra_config(tmp_path)
    config.model.kv_cache_quant = True
    trainer = PPOTrainer(config)
    assert trainer.fused_rollout

    rng = np.random.default_rng(0)
    prompts = rng.integers(1, 15, size=(16, 1)).astype(np.int32)
    tokens, mask, stats, prefill = trainer.rollout_generate_fused(prompts, np.ones_like(prompts))
    scores = np.zeros(16, np.float32)
    f_lp = np.asarray(trainer.rollout_score_fused(tokens, mask, scores, (stats, prefill))[0])
    u_lp = np.asarray(trainer.rollout_score(tokens, mask, scores)[0])
    rmask = np.asarray(mask)[:, trainer.prompt_length:].astype(bool)
    gap = np.abs(f_lp - u_lp)[rmask]
    assert gap.max() < 0.05, f"quantized-decode vs fp-recompute logprob gap too large: {gap.max()}"
