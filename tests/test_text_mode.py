"""Text-mode PPO end-to-end WITHOUT network: a character tokenizer stands in
for HF's (the sentiment examples need downloads), exercising the string
pipeline the tensor-prompt e2e tests skip — tokenize → left-pad → generate →
decode to text → reward_fn over strings → store → train."""

import numpy as np
import pytest

import trlx_tpu
from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.trainer.base import JaxBaseTrainer

pytestmark = pytest.mark.slow  # excluded from `make test-fast` (see conftest)


class CharTokenizer:
    """One token per lowercase letter; ids: pad/eos=1, bos=2, 'a'..'z'=3..28."""

    bos_token_id = 2
    eos_token_id = 1
    pad_token_id = 1
    padding_side = "left"

    def __call__(self, text, add_special_tokens=False):
        return {"input_ids": [3 + (ord(c) - ord("a")) % 26 for c in text if c.isalpha()]}

    def batch_decode(self, tokens, skip_special_tokens=True):
        out = []
        for row in np.asarray(tokens):
            out.append("".join(chr(ord("a") + int(t) - 3) for t in row if t >= 3))
        return out


def text_config(tmp_path) -> TRLConfig:
    return TRLConfig.from_dict(
        {
            "model": {
                "model_path": "",
                "tokenizer_path": "",  # tokenizer injected by the test
                "model_type": "ppo",
                "num_layers_unfrozen": -1,
                "dtype": "float32",
                "model_arch": {
                    "n_layer": 2,
                    "n_head": 2,
                    "d_model": 64,
                    "vocab_size": 32,
                    "max_position": 32,
                    "eos_token_id": 1,
                },
            },
            "train": {
                "seq_length": 16,
                "epochs": 2,
                "total_steps": 4,
                "batch_size": 16,
                "lr_ramp_steps": 2,
                "lr_decay_steps": 100,
                "weight_decay": 1.0e-6,
                "learning_rate_init": 1.0e-3,
                "learning_rate_target": 1.0e-4,
                "opt_betas": [0.9, 0.95],
                "checkpoint_interval": 10**6,
                "eval_interval": 3,
                "orchestrator": "PPOOrchestrator",
                "mesh": [-1, 1, 1, 1],
                "seed": 7,
                "checkpoint_dir": str(tmp_path),
            },
            "method": {
                "name": "ppoconfig",
                "num_rollouts": 16,
                "chunk_size": 16,
                "ppo_epochs": 2,
                "init_kl_coef": 0.05,
                "target": 6,
                "horizon": 10000,
                "gamma": 1.0,
                "lam": 0.95,
                "cliprange": 0.2,
                "cliprange_value": 0.2,
                "vf_coef": 1.0,
                "gen_kwargs": {
                    "prompt_length": 8,
                    "max_new_tokens": 8,
                    "do_sample": True,
                    "top_k": 0,
                    "top_p": 1.0,
                },
            },
        }
    )


def test_text_mode_ppo_end_to_end(tmp_path, monkeypatch):
    """Full text path: string prompts → tokenize → generate → decode →
    reward_fn(texts) → learn; eval samples arrive as strings."""
    monkeypatch.setattr(JaxBaseTrainer, "_build_tokenizer", lambda self, path: CharTokenizer())

    seen = {"texts": []}

    def reward_fn(texts):
        assert all(isinstance(t, str) for t in texts)
        seen["texts"].extend(texts)
        # reward: fraction of 'a's in the sample
        return np.asarray(
            [t.count("a") / max(len(t), 1) for t in texts], dtype=np.float32
        )

    def metric_fn(texts):
        assert all(isinstance(t, str) for t in texts)
        return {"len": np.asarray([float(len(t)) for t in texts])}

    prompts = ["abc", "bca", "cab", "aa", "bb", "cc", "abca", "baab"] * 4
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=["ab", "ba", "ca"],
        metric_fn=metric_fn,
        config=text_config(tmp_path),
    )
    assert model.iter_count >= 4
    assert len(model.store) > 0
    assert seen["texts"], "reward_fn never saw decoded text"
    # decoded rollouts include the prompt characters (queries + responses)
    assert any("a" in t or "b" in t or "c" in t for t in seen["texts"])
    stats = model.evaluate()
    assert "mean_reward" in stats and np.isfinite(stats["mean_reward"])


def test_text_mode_default_prompts_are_bos(tmp_path, monkeypatch):
    """train() with no prompts defaults to BOS×batch_size — the reference's
    default-prompt path (trlx/trlx.py:49-52) — which requires a tokenizer."""
    class Tok(CharTokenizer):
        bos_token = "a"  # train() uses tokenizer.bos_token strings

    monkeypatch.setattr(JaxBaseTrainer, "_build_tokenizer", lambda self, path: Tok())
    config = text_config(tmp_path)
    config.train.total_steps = 2
    model = trlx_tpu.train(
        reward_fn=lambda texts: np.zeros(len(texts), np.float32),
        config=config,
    )
    assert model.iter_count >= 2
