"""Ring attention (sequence parallelism over the sp axis): op- and
model-level parity with single-device attention on the 8-device CPU mesh —
distributed semantics the reference cannot test at all (SURVEY.md §4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trlx_tpu.models import LMConfig, TransformerLM
from trlx_tpu.parallel.mesh import make_mesh, set_mesh
from trlx_tpu.parallel.ring_attention import ring_attention_sharded

pytestmark = pytest.mark.slow  # excluded from `make test-fast` (see conftest)


@pytest.fixture()
def sp_mesh():
    mesh = make_mesh((2, 1, 2, 2))  # dp=2 fsdp=1 tp=2 sp=2
    set_mesh(mesh)
    yield mesh
    set_mesh(make_mesh((-1, 1, 1, 1)))


def test_op_matches_full_attention(sp_mesh):
    rng = np.random.default_rng(0)
    b, T, h, d = 4, 64, 4, 16
    q, k, v = (jnp.asarray(rng.standard_normal((b, T, h, d)), jnp.float32) for _ in range(3))
    kvmask = jnp.ones((b, T), jnp.int32).at[0, :9].set(0)
    qvalid = kvmask[:, :, None, None].astype(jnp.float32)
    scale = d**-0.5

    def ref(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        qi = jnp.arange(T)[:, None]
        ki = jnp.arange(T)[None, :]
        m = (ki <= qi)[None, None] & kvmask[:, None, None, :].astype(bool)
        return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(jnp.where(m, s, -1e9), -1), v)

    ring = jax.jit(lambda q, k, v: ring_attention_sharded(q, k, v, kvmask, scale=scale, mesh=sp_mesh))
    np.testing.assert_allclose(
        np.asarray((ring(q, k, v) - ref(q, k, v)) * qvalid), 0.0, atol=1e-5
    )

    g1 = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(ring(q, k, v)) * qvalid), (0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(ref(q, k, v)) * qvalid), (0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-5)


def test_model_sequence_parallel_matches_local(sp_mesh):
    """TransformerLM with sp_size=2 (ring) vs sp_size=0 (local einsum):
    same params, same logits and grads."""
    base = dict(
        vocab_size=61,
        n_layer=2,
        n_head=4,
        d_model=32,
        max_position=128,
        pos_type="rotary",
        rotary_dim=8,
        dtype="float32",
        attn_impl="xla",
    )
    rng = np.random.default_rng(1)
    B, T = 4, 64
    ids = jnp.asarray(rng.integers(0, 61, (B, T)))
    mask = jnp.ones((B, T), jnp.int32).at[0, :7].set(0)
    fmask = mask[:, :, None].astype(jnp.float32)

    local = TransformerLM(LMConfig(**base))
    ring = TransformerLM(LMConfig(**base, sp_size=2))
    params = local.init(jax.random.PRNGKey(0), ids, mask)["params"]

    ll = local.apply({"params": params}, ids, mask)["logits"]
    lr = jax.jit(lambda p: ring.apply({"params": p}, ids, mask)["logits"])(params)
    np.testing.assert_allclose(np.asarray(lr * fmask), np.asarray(ll * fmask), atol=2e-4)

    from jax.flatten_util import ravel_pytree

    def loss(model):
        return lambda p: jnp.sum(jnp.tanh(model.apply({"params": p}, ids, mask)["logits"]) * fmask)

    gl, _ = ravel_pytree(jax.grad(loss(local))(params))
    gr, _ = ravel_pytree(jax.jit(jax.grad(loss(ring)))(params))
    np.testing.assert_allclose(np.asarray(gr), np.asarray(gl), atol=5e-4)


def test_decode_stays_local(sp_mesh):
    """Generation with a KV cache must not route through the ring (q_len==1
    decode steps are sequence-local by construction)."""
    from trlx_tpu.models.lm import ring_eligible

    cfg = LMConfig(sp_size=2)
    assert ring_eligible(cfg, 64, has_cache=False)
    assert not ring_eligible(cfg, 64, has_cache=True)
    assert not ring_eligible(cfg, 63, has_cache=False)  # unaligned
    assert not ring_eligible(LMConfig(sp_size=0), 64, has_cache=False)


def test_ring_flash_path_matches_full_attention(sp_mesh):
    """Flash-kernel-per-chunk ring (offset-aware masking + exact lse
    combination) vs single-device attention, forward and gradients."""
    rng = np.random.default_rng(3)
    b, T, h, d = 4, 64, 4, 16
    q, k, v = (jnp.asarray(rng.standard_normal((b, T, h, d)), jnp.float32) for _ in range(3))
    kvmask = jnp.ones((b, T), jnp.int32).at[0, :9].set(0)
    qvalid = kvmask[:, :, None, None].astype(jnp.float32)
    scale = d**-0.5

    def ref(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        qi = jnp.arange(T)[:, None]
        ki = jnp.arange(T)[None, :]
        m = (ki <= qi)[None, None] & kvmask[:, None, None, :].astype(bool)
        return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(jnp.where(m, s, -1e9), -1), v)

    ring = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, kvmask, scale=scale, mesh=sp_mesh, use_flash=True
        )
    )
    np.testing.assert_allclose(
        np.asarray((ring(q, k, v) - ref(q, k, v)) * qvalid), 0.0, atol=1e-5
    )

    g1 = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(ring(q, k, v)) * qvalid), (0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(ref(q, k, v)) * qvalid), (0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-5)


def test_zigzag_live_work_balanced():
    """Causal live half-pair counts: zig-zag within ±1 across ranks (in fact
    exactly equal), contiguous skewed ~2× (rank r does r+1 live visits)."""
    from trlx_tpu.parallel.ring_attention import causal_live_half_pairs

    for n in (2, 4, 8):
        zz = causal_live_half_pairs(n, "zigzag")
        assert max(zz) - min(zz) <= 1, zz
        assert sum(zz) == n * (2 * n + 1)  # exactly the causal total: no waste
        cont = causal_live_half_pairs(n, "contiguous")
        assert max(cont) - min(cont) == (n - 1) * 2 * 2  # the skew zig-zag removes
        # Contiguous also does MORE total work (2n²+2n halves): its diagonal
        # visit computes the chunk's masked-future half. Zig-zag's 2n²+n is
        # exactly the causal minimum.
        assert sum(cont) == 2 * n * (n + 1)
        assert sum(zz) < sum(cont)


def test_zigzag_matches_contiguous_layout(sp_mesh):
    """Forced zig-zag vs forced contiguous on identical global inputs: same
    outputs and gradients (the permutation round-trips exactly)."""
    rng = np.random.default_rng(7)
    b, T, h, d = 2, 64, 4, 16
    q, k, v = (jnp.asarray(rng.standard_normal((b, T, h, d)), jnp.float32) for _ in range(3))
    kvmask = jnp.ones((b, T), jnp.int32).at[1, :7].set(0)
    # Compare only valid-query rows: a fully-masked causal row (pad query
    # attending only pad keys) degrades to a layout-dependent uniform mix —
    # garbage positions that every loss masks out.
    qvalid = kvmask[:, :, None, None].astype(jnp.float32)
    scale = d**-0.5

    def run(layout):
        f = jax.jit(
            lambda q, k, v: ring_attention_sharded(
                q, k, v, kvmask, scale=scale, mesh=sp_mesh, layout=layout
            )
        )
        out = f(q, k, v) * qvalid
        g = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(f(q, k, v)) * qvalid), (0, 1, 2))(q, k, v)
        return out, g

    out_z, g_z = run("zigzag")
    out_c, g_c = run("contiguous")
    np.testing.assert_allclose(np.asarray(out_z), np.asarray(out_c), atol=1e-5)
    for a, b_ in zip(g_z, g_c):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-5)


def test_zigzag_windowed_matches_full(sp_mesh):
    """Local (windowed) attention through the zig-zag liveness conditions."""
    rng = np.random.default_rng(8)
    b, T, h, d, W = 2, 64, 2, 8, 24
    q, k, v = (jnp.asarray(rng.standard_normal((b, T, h, d)), jnp.float32) for _ in range(3))
    kvmask = jnp.ones((b, T), jnp.int32)
    scale = d**-0.5

    def ref(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        qi = jnp.arange(T)[:, None]
        ki = jnp.arange(T)[None, :]
        m = ((ki <= qi) & (ki > qi - W))[None, None]
        return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(jnp.where(m, s, -1e9), -1), v)

    ring = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, kvmask, scale=scale, window=W, mesh=sp_mesh, layout="zigzag"
        )
    )
    np.testing.assert_allclose(np.asarray(ring(q, k, v)), np.asarray(ref(q, k, v)), atol=1e-5)
