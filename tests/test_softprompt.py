"""Soft-prompt capability: prefix changes outputs, only prefix+v_head train,
generation accounts for the prefix (capability parity with the fork's
SoftEmbedding, reference: trlx/model/accelerate_ppo_softprompt_model.py:26-81)."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.models import LMConfig, LMWithValueHead
from trlx_tpu.ops.generate import make_generate_fn
from trlx_tpu.ops.sampling import GenerateConfig

pytestmark = pytest.mark.slow  # excluded from `make test-fast` (see conftest)


def build(n_soft=4):
    cfg = LMConfig(vocab_size=29, n_layer=2, n_head=2, d_model=32, max_position=64,
                   dtype="float32", n_soft_tokens=n_soft)
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (2, 6), 1, cfg.vocab_size)
    mask = jnp.ones((2, 6), jnp.int32)
    params = model.init(rng, ids, mask)["params"]
    return cfg, model, params, ids, mask


def test_soft_prompt_changes_logits_and_preserves_shape():
    cfg, model, params, ids, mask = build()
    out = model.apply({"params": params}, ids, mask)
    assert out["logits"].shape == (2, 6, cfg.vocab_size)  # prefix sliced out

    p2 = jax.tree_util.tree_map(lambda x: x, params)
    # random (non-constant) perturbation — LayerNorm cancels uniform shifts
    noise = jax.random.normal(jax.random.PRNGKey(7), params["transformer"]["soft_prompt"].shape)
    p2["transformer"]["soft_prompt"] = params["transformer"]["soft_prompt"] + noise
    out2 = model.apply({"params": p2}, ids, mask)
    assert float(jnp.max(jnp.abs(out2["logits"] - out["logits"]))) > 1e-3


def test_soft_prompt_generate_cache_consistency():
    """Cached decode with the soft prefix must match the no-cache forward."""
    cfg, model, params, ids, mask = build()
    gcfg = GenerateConfig(max_new_tokens=4, do_sample=False, pad_token_id=0)
    gen = make_generate_fn(model, gcfg)
    toks, m = gen({"params": params}, ids, mask, jax.random.PRNGKey(1))

    cur_ids, cur_mask = ids, mask
    for _ in range(4):
        out = model.apply({"params": params}, cur_ids, cur_mask)
        nxt = jnp.argmax(out["logits"][:, -1].astype(jnp.float32), -1)[:, None]
        cur_ids = jnp.concatenate([cur_ids, nxt], 1)
        cur_mask = jnp.concatenate([cur_mask, jnp.ones((2, 1), jnp.int32)], 1)
    np.testing.assert_array_equal(np.array(toks), np.array(cur_ids))


def test_softprompt_trainable_mask():
    import trlx_tpu.trainer.api  # noqa: F401  (populates registries)
    from trlx_tpu.trainer import get_model

    cls = get_model("ppo_softprompt")
    # check mask builder in isolation (no full trainer construction needed)
    cfg, model, params, ids, mask = build()
    self_like = type("S", (), {})()
    tm = cls.build_trainable_mask(self_like, params)
    assert tm["transformer"]["soft_prompt"] is True
    assert tm["v_head"]["layers_0"]["kernel"] is True
    assert tm["transformer"]["h_0"]["attn"]["c_qkv"]["kernel"] is False
    assert tm["transformer"]["wte"]["embedding"] is False
