"""End-to-end smokes: a few PPO and ILQL steps on the randomwalks task
(the reference's de-facto integration suite is examples/, SURVEY.md §4 —
here it's in CI)."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402

pytestmark = pytest.mark.slow  # excluded from `make test-fast` (see conftest)


@pytest.fixture(scope="module")
def task():
    return generate_random_walks(n_nodes=15, max_length=8, n_walks=60, seed=1000)


def shrink(config):
    config.train.total_steps = 6
    config.train.epochs = 2
    config.train.batch_size = 16
    config.train.eval_interval = 4
    config.method.num_rollouts = 16 if hasattr(config.method, "num_rollouts") else None
    if hasattr(config.method, "chunk_size"):
        config.method.chunk_size = 16
    return config


def test_ppo_e2e_smoke(task, tmp_path):
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.model.kv_cache_quant = True  # int8 decode cache path in CI
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.iter_count >= 6
    assert len(model.store) > 0


def test_ppo_e2e_bucketed_prompts(task, tmp_path):
    """Mixed prompt lengths with prompt_buckets: rollouts generate at
    per-bucket widths, the store and train step stay at the single global
    prompt_length (the orchestrator re-pads queries before the push), and
    training completes. The trace-count proof lives in test_bucketing; this
    is the full train-loop integration."""
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.method.gen_kwargs["prompt_length"] = 3
    config.method.gen_kwargs["max_new_tokens"] = 5
    config.method.gen_kwargs["prompt_buckets"] = [1, 3]
    rng = np.random.default_rng(7)
    # walk prefixes of mixed lengths 1..3 (nodes stay in-vocab; the bigram
    # mask only constrains GENERATED steps)
    prompts = [list(rng.integers(1, 15, size=rng.integers(1, 4))) for _ in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.prompt_buckets == (1, 3)
    assert model.iter_count >= 6
    assert len(model.store) > 0
    # stored queries were re-padded to the GLOBAL prompt width
    el = model.store[0]
    assert el.query_tensor.shape[0] == model.prompt_length == 3
    assert el.response_tensor.shape[0] == model.response_length == 5


def test_ilql_e2e_smoke(task, tmp_path):
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ilql", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    lengths = metric_fn(walks)["lengths"]
    model = trlx_tpu.train(
        dataset=(walks, lengths),
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.iter_count >= 6


def test_checkpoint_save_load(task, tmp_path):
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.total_steps = 2
    config.train.checkpoint_dir = str(tmp_path / "ck")
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]], metric_fn=metric_fn,
        config=config, logit_mask=logit_mask,
    )
    import jax

    step_before = int(jax.device_get(model.state.step))
    model.load()
    assert int(jax.device_get(model.state.step)) == step_before


def test_ppo_fully_unfrozen_uses_ref_copy(task, tmp_path):
    """num_layers_unfrozen >= n_layer means no shared trunk: the trainer must
    fall back to a full frozen ref copy (a layer-0 branch replay would
    re-apply position embeddings — regression test)."""
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.train.total_steps = 2
    config.model.num_layers_unfrozen = config.model.model_arch["n_layer"]
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.model.branch_layer == -1
    assert model.iter_count >= 2


def test_preemption_checkpoints_and_stops(task, tmp_path):
    """SIGTERM mid-training must save a resumable checkpoint at the next step
    boundary and stop cleanly (the reference has no preemption handling)."""
    import os
    import signal

    from trlx_tpu.trainer.ppo import PPOTrainer

    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.train.epochs = 100
    config.train.total_steps = 50  # would run long; preemption cuts it short
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    fired = {"done": False}
    orig = PPOTrainer.post_backward_callback

    def fire_once(self, stats=None):
        orig(self, stats)
        if not fired["done"] and self.iter_count >= 2:
            fired["done"] = True
            os.kill(os.getpid(), signal.SIGTERM)

    PPOTrainer.post_backward_callback = fire_once
    try:
        model = trlx_tpu.train(
            reward_fn=reward_fn,
            prompts=prompts,
            eval_prompts=[[i] for i in range(1, 15)],
            metric_fn=metric_fn,
            config=config,
            logit_mask=logit_mask,
        )
    finally:
        PPOTrainer.post_backward_callback = orig

    assert fired["done"]
    assert model.iter_count < 50  # stopped at the preemption boundary
    with open(os.path.join(str(tmp_path), "latest.txt")) as f:
        assert f.read().strip()
    model.load()  # the checkpoint restores


def test_ppo_e2e_on_sharded_mesh(task, tmp_path):
    """Whole PPO path (generate → score → train) on a dp=2,tp=2,sp=2 mesh of
    virtual CPU devices — the multi-chip semantics the reference cannot test
    at all (SURVEY.md §4)."""
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.train.mesh = (2, 1, 2, 2)
    config.train.total_steps = 4
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.iter_count >= 4
    assert model.model.cfg.sp_size == 2  # ring attention was actually on


def test_ilql_e2e_on_sharded_mesh(task, tmp_path):
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ilql", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.train.mesh = (1, 2, 2, 2)
    config.train.total_steps = 3
    rewards = [float(reward_fn([w])[0]) for w in walks]
    model = trlx_tpu.train(
        dataset=(walks, rewards),
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.iter_count >= 3


def test_resume_from_checkpoint_continues_training(task, tmp_path):
    """train.resume_from_checkpoint restores the full state and continues
    counting from the saved step — true resume, which the reference's
    save-only checkpoints cannot do."""
    import jax

    walks, logit_mask, metric_fn, reward_fn = task
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    def run(total_steps, resume):
        config = shrink(base_config("ppo", 15, 8))
        config.train.total_steps = total_steps
        config.train.checkpoint_dir = str(tmp_path / "ck")
        config.train.resume_from_checkpoint = resume
        return trlx_tpu.train(
            reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
            metric_fn=metric_fn, config=config, logit_mask=logit_mask,
        )

    first = run(total_steps=2, resume=False)
    assert int(jax.device_get(first.state.step)) == 2

    second = run(total_steps=5, resume=True)
    # picked up at step 2 and trained only the remaining 3 steps
    assert int(jax.device_get(second.state.step)) == 5
    assert second.iter_count == 5


def test_resume_restores_host_state(task, tmp_path):
    """The adaptive KL coefficient and the sampling RNG are host-side Python
    state; a true resume must restore them too."""
    walks, logit_mask, metric_fn, reward_fn = task
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    def run(total_steps, resume):
        config = shrink(base_config("ppo", 15, 8))
        config.train.total_steps = total_steps
        config.train.checkpoint_dir = str(tmp_path / "ck")
        config.train.resume_from_checkpoint = resume
        return trlx_tpu.train(
            reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
            metric_fn=metric_fn, config=config, logit_mask=logit_mask,
        )

    first = run(total_steps=2, resume=False)
    first.kl_ctl.value = 0.0123  # pretend the controller adapted
    first.save()

    second = run(total_steps=4, resume=True)
    # restored at construction time, then possibly adapted during the 2
    # resumed steps — but never reset to init_kl_coef (0.05 in this config)
    assert second.kl_ctl.value != first.config.method.init_kl_coef
    assert second.kl_ctl.value == pytest.approx(0.0123, rel=0.2)


def test_ppo_learns_randomwalks(tmp_path):
    """Learning-QUALITY gate (not just a smoke): PPO on randomwalks must
    reach ≥0.8 eval optimality — a zero-learning regression passes the
    smoke tests above but fails here. Reference metric:
    trlx/examples/randomwalks.py:62-81; measured headroom: optimality
    reaches ~0.95 by step 48 on CPU with the example config."""
    n_nodes, max_length = 21, 10
    walks, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=n_nodes, max_length=max_length
    )
    config = base_config("ppo", n_nodes, max_length)
    config.train.total_steps = 48
    config.train.eval_interval = 16
    config.train.checkpoint_interval = 10**6
    config.train.checkpoint_dir = str(tmp_path)
    # batch must divide the 8-virtual-device dp mesh (conftest)
    config.train.batch_size = 48
    config.method.num_rollouts = 96
    config.method.chunk_size = 48

    history = []
    n_eval_prompts = n_nodes - 1  # 20 prompts at batch 50: one wrapped batch

    def recording_metric(samples):
        # eval must hand the metric exactly the valid rows — the loader's
        # static-shape wrap-around duplicates must have been dropped
        assert len(samples) == n_eval_prompts
        m = metric_fn(samples)
        history.append(float(np.mean(m["optimality"])))
        return m

    prompts = [[int(np.random.default_rng(i).integers(1, n_nodes))] for i in range(200)]
    trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[i] for i in range(1, n_nodes)],
        metric_fn=recording_metric,
        config=config,
        logit_mask=logit_mask,
    )
    assert history, "evaluate() never ran"
    assert max(history) >= 0.8, f"PPO failed to learn: optimality history {history}"


def test_ilql_learns_randomwalks(tmp_path):
    """ILQL on the offline randomwalks dataset must beat the random-walk
    baseline (~0.55 optimality) by a clear margin."""
    n_nodes, max_length = 21, 10
    walks, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=n_nodes, max_length=max_length
    )
    config = base_config("ilql", n_nodes, max_length)
    config.train.total_steps = 100
    config.train.eval_interval = 25
    config.train.checkpoint_interval = 10**6
    config.train.checkpoint_dir = str(tmp_path)
    # batch must divide the 8-virtual-device dp mesh (conftest)
    config.train.batch_size = 48

    history = []

    def recording_metric(samples):
        m = metric_fn(samples)
        history.append(float(np.mean(m["optimality"])))
        return m

    lengths = metric_fn(walks)["lengths"]
    trlx_tpu.train(
        dataset=(walks, lengths),
        eval_prompts=[[i] for i in range(1, n_nodes)],
        metric_fn=recording_metric,
        config=config,
        logit_mask=logit_mask,
    )
    assert history, "evaluate() never ran"
    assert max(history) >= 0.70, f"ILQL failed to learn: optimality history {history}"


def test_ppo_with_on_device_reward_model(task, tmp_path):
    """PPO driven by an ON-DEVICE reward model (no host reward_fn at all):
    rollout scoring and eval rewards come from the RM inside the fused
    sharded programs — the pod-scale RM path (BASELINE.json eval config 5)."""
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.train.total_steps = 2
    config.model.reward_model_arch = dict(config.model.model_arch)
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        prompts=prompts,
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.has_reward_model and model.reward_fn is None
    assert model.iter_count >= 2
    assert len(model.store) > 0
    stats = model.evaluate()
    assert "mean_reward" in stats  # RM-sourced eval rewards
    # the RM scores exactly one scalar per sequence
    import jax

    batch, n_valid = next(iter(model.eval_dataloader.iter_with_valid()))
    tokens, mask = model.rollout_generate(batch["input_ids"], batch["attention_mask"])
    scores = np.asarray(jax.device_get(model.rm_eval_scores(tokens, mask)))
    assert scores.shape == (batch["input_ids"].shape[0],)
    assert np.isfinite(scores).all()


def test_profile_dir_captures_trace(task, tmp_path):
    """train.profile_dir: steps [2,5) of the learn loop are traced with
    jax.profiler (the TPU-native upgrade over the reference's wall-clock
    timers, SURVEY.md §5) — trace artifacts must land on disk."""
    import os

    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.train.total_steps = 6
    config.train.profile_dir = str(tmp_path / "trace")
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
        metric_fn=metric_fn, config=config, logit_mask=logit_mask,
    )
    trace_files = []
    for root, _, files in os.walk(tmp_path / "trace"):
        trace_files.extend(files)
    assert trace_files, "profiler produced no trace artifacts"


def test_log_interval_skips_stat_reads(task, tmp_path):
    """train.log_interval > 1 logs (and syncs stats) only every Nth step —
    the reference reads this field but never defines it
    (trlx/model/__init__.py:137)."""
    import json

    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.train.total_steps = 4
    config.train.log_interval = 2
    config.train.eval_interval = 100  # no eval logs in the window
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
        metric_fn=metric_fn, config=config, logit_mask=logit_mask,
    )
    assert model.iter_count >= 4
    with open(tmp_path / "metrics.jsonl") as f:
        recs = [json.loads(line) for line in f]
    # train-step stat lines carry "loss"; rollout/eval lines don't
    logged_train_steps = [r["step"] for r in recs if "loss" in r]
    assert logged_train_steps, "nothing logged at all"
    assert set(logged_train_steps) <= {2, 4}, logged_train_steps


def test_offline_orchestrator_degenerate_samples(task):
    """Prompt-only / over-truncated samples must not crash experience
    building (empty action rows are padded no-ops in the storage)."""
    from trlx_tpu.orchestrator.offline_orchestrator import OfflineOrchestrator
    from trlx_tpu.trainer.ilql import ILQLTrainer

    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ilql", 15, 8))
    config.train.total_steps = 1
    model = ILQLTrainer(config, metric_fn=metric_fn, logit_mask=logit_mask)
    orch = OfflineOrchestrator(model)
    samples = [np.asarray([3]), np.asarray(walks[0]), np.asarray(walks[1])]
    orch.make_experience(samples, [0.5, 1.0, -1.0])
    assert len(model.store) == 3


def test_kl_controller_trajectory_invariant_to_log_interval(task, tmp_path):
    """The adaptive KL controller buffers every step's mean_kl and applies
    per-step updates in order, so its final coefficient is IDENTICAL for
    log_interval 1 and 4 on the same seeds/data (it used to react only to
    every Nth step's KL with a rescaled step count)."""

    def run(log_interval, ckpt_dir):
        walks, logit_mask, metric_fn, reward_fn = task
        config = shrink(base_config("ppo", 15, 8))
        config.train.checkpoint_dir = str(ckpt_dir)
        config.train.total_steps = 5
        config.train.log_interval = log_interval
        config.train.eval_interval = 100
        assert config.method.target is not None  # adaptive controller in play
        prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
        model = trlx_tpu.train(
            reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
            metric_fn=metric_fn, config=config, logit_mask=logit_mask,
        )
        model._flush_kl_updates()
        return model.kl_ctl.value

    v1 = run(1, tmp_path / "a")
    v4 = run(4, tmp_path / "b")
    assert v1 != pytest.approx(0.05), "controller never moved — test is vacuous"
    assert v4 == pytest.approx(v1, rel=1e-6)


def test_ppo_e2e_packed_train_batch(task, tmp_path):
    """method.pack_train_batch=True: episodes pack into dense bucketed rows
    (block-diagonal attention, segment-gated GAE) and the whole train loop
    completes, logging the packed-throughput metrics."""
    import json

    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.method.pack_train_batch = True
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.iter_count >= 6
    assert len(model.store) > 0
    # packed rows shard over the data axes like any train batch
    from trlx_tpu.data import PackedPPOBatch

    batch = next(iter(model.train_dataloader))
    assert isinstance(batch, PackedPPOBatch)
    assert batch.input_ids.shape[0] % model._pack_rows_multiple == 0
    # satellite metrics: tokens/s + fill fraction land in metrics.jsonl
    with open(tmp_path / "metrics.jsonl") as f:
        recs = [json.loads(line) for line in f]
    assert any("train_tokens_per_s" in r for r in recs)
    fills = [r["train_batch_fill"] for r in recs if "train_batch_fill" in r]
    assert fills and all(0 < v <= 1 for v in fills)


def test_ppo_packed_losses_match_unpacked(task, tmp_path):
    """Same seed, same rollouts: the packed train step must reproduce the
    unpacked losses (layout is a pure re-indexing of the same loss sum —
    only float reassociation differs). With packing OFF the loader still
    yields the plain PPORLBatch, i.e. the default path is untouched."""
    import json

    walks, logit_mask, metric_fn, reward_fn = task

    def run(packed, sub):
        config = shrink(base_config("ppo", 15, 8))
        config.train.checkpoint_dir = str(tmp_path / sub)
        config.train.total_steps = 2
        config.method.pack_train_batch = packed
        prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
        model = trlx_tpu.train(
            reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
            metric_fn=metric_fn, config=config, logit_mask=logit_mask,
        )
        with open(tmp_path / sub / "metrics.jsonl") as f:
            recs = [json.loads(line) for line in f]
        return model, {r["step"]: r for r in recs if "loss" in r}

    model_u, logs_u = run(False, "unpacked")
    model_p, logs_p = run(True, "packed")

    from trlx_tpu.data import PackedPPOBatch, PPORLBatch

    assert isinstance(next(iter(model_u.train_dataloader)), PPORLBatch)
    assert isinstance(next(iter(model_p.train_dataloader)), PackedPPOBatch)

    # step 1 trains on identical params + identical experience — packed vs
    # unpacked is the same loss up to reassociation
    assert 1 in logs_u and 1 in logs_p
    assert logs_u[1]["loss"] == pytest.approx(logs_p[1]["loss"], rel=5e-3, abs=1e-5)
    assert logs_u[1]["mean_kl"] == pytest.approx(logs_p[1]["mean_kl"], rel=5e-3, abs=1e-6)
