"""End-to-end smokes: a few PPO and ILQL steps on the randomwalks task
(the reference's de-facto integration suite is examples/, SURVEY.md §4 —
here it's in CI)."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402


@pytest.fixture(scope="module")
def task():
    return generate_random_walks(n_nodes=15, max_length=8, n_walks=60, seed=1000)


def shrink(config):
    config.train.total_steps = 6
    config.train.epochs = 2
    config.train.batch_size = 16
    config.train.eval_interval = 4
    config.method.num_rollouts = 16 if hasattr(config.method, "num_rollouts") else None
    if hasattr(config.method, "chunk_size"):
        config.method.chunk_size = 16
    return config


def test_ppo_e2e_smoke(task, tmp_path):
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.iter_count >= 6
    assert len(model.store) > 0


def test_ilql_e2e_smoke(task, tmp_path):
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ilql", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    lengths = metric_fn(walks)["lengths"]
    model = trlx_tpu.train(
        dataset=(walks, lengths),
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.iter_count >= 6


def test_checkpoint_save_load(task, tmp_path):
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.total_steps = 2
    config.train.checkpoint_dir = str(tmp_path / "ck")
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]], metric_fn=metric_fn,
        config=config, logit_mask=logit_mask,
    )
    import jax

    step_before = int(jax.device_get(model.state.step))
    model.load()
    assert int(jax.device_get(model.state.step)) == step_before


def test_ppo_fully_unfrozen_uses_ref_copy(task, tmp_path):
    """num_layers_unfrozen >= n_layer means no shared trunk: the trainer must
    fall back to a full frozen ref copy (a layer-0 branch replay would
    re-apply position embeddings — regression test)."""
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.train.total_steps = 2
    config.model.num_layers_unfrozen = config.model.model_arch["n_layer"]
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.model.branch_layer == -1
    assert model.iter_count >= 2


def test_preemption_checkpoints_and_stops(task, tmp_path):
    """SIGTERM mid-training must save a resumable checkpoint at the next step
    boundary and stop cleanly (the reference has no preemption handling)."""
    import os
    import signal

    from trlx_tpu.trainer.ppo import PPOTrainer

    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.train.epochs = 100
    config.train.total_steps = 50  # would run long; preemption cuts it short
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    fired = {"done": False}
    orig = PPOTrainer.post_backward_callback

    def fire_once(self, stats=None):
        orig(self, stats)
        if not fired["done"] and self.iter_count >= 2:
            fired["done"] = True
            os.kill(os.getpid(), signal.SIGTERM)

    PPOTrainer.post_backward_callback = fire_once
    try:
        model = trlx_tpu.train(
            reward_fn=reward_fn,
            prompts=prompts,
            eval_prompts=[[i] for i in range(1, 15)],
            metric_fn=metric_fn,
            config=config,
            logit_mask=logit_mask,
        )
    finally:
        PPOTrainer.post_backward_callback = orig

    assert fired["done"]
    assert model.iter_count < 50  # stopped at the preemption boundary
    with open(os.path.join(str(tmp_path), "latest.txt")) as f:
        assert f.read().strip()
    model.load()  # the checkpoint restores


def test_ppo_e2e_on_sharded_mesh(task, tmp_path):
    """Whole PPO path (generate → score → train) on a dp=2,tp=2,sp=2 mesh of
    virtual CPU devices — the multi-chip semantics the reference cannot test
    at all (SURVEY.md §4)."""
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ppo", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.train.mesh = (2, 1, 2, 2)
    config.train.total_steps = 4
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.iter_count >= 4
    assert model.model.cfg.sp_size == 2  # ring attention was actually on


def test_ilql_e2e_on_sharded_mesh(task, tmp_path):
    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ilql", 15, 8))
    config.train.checkpoint_dir = str(tmp_path)
    config.train.mesh = (1, 2, 2, 2)
    config.train.total_steps = 3
    rewards = [float(reward_fn([w])[0]) for w in walks]
    model = trlx_tpu.train(
        dataset=(walks, rewards),
        eval_prompts=[[i] for i in range(1, 15)],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.iter_count >= 3


def test_resume_from_checkpoint_continues_training(task, tmp_path):
    """train.resume_from_checkpoint restores the full state and continues
    counting from the saved step — true resume, which the reference's
    save-only checkpoints cannot do."""
    import jax

    walks, logit_mask, metric_fn, reward_fn = task
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    def run(total_steps, resume):
        config = shrink(base_config("ppo", 15, 8))
        config.train.total_steps = total_steps
        config.train.checkpoint_dir = str(tmp_path / "ck")
        config.train.resume_from_checkpoint = resume
        return trlx_tpu.train(
            reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
            metric_fn=metric_fn, config=config, logit_mask=logit_mask,
        )

    first = run(total_steps=2, resume=False)
    assert int(jax.device_get(first.state.step)) == 2

    second = run(total_steps=5, resume=True)
    # picked up at step 2 and trained only the remaining 3 steps
    assert int(jax.device_get(second.state.step)) == 5
    assert second.iter_count == 5


def test_resume_restores_host_state(task, tmp_path):
    """The adaptive KL coefficient and the sampling RNG are host-side Python
    state; a true resume must restore them too."""
    walks, logit_mask, metric_fn, reward_fn = task
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    def run(total_steps, resume):
        config = shrink(base_config("ppo", 15, 8))
        config.train.total_steps = total_steps
        config.train.checkpoint_dir = str(tmp_path / "ck")
        config.train.resume_from_checkpoint = resume
        return trlx_tpu.train(
            reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
            metric_fn=metric_fn, config=config, logit_mask=logit_mask,
        )

    first = run(total_steps=2, resume=False)
    first.kl_ctl.value = 0.0123  # pretend the controller adapted
    first.save()

    second = run(total_steps=4, resume=True)
    # restored at construction time, then possibly adapted during the 2
    # resumed steps — but never reset to init_kl_coef (0.05 in this config)
    assert second.kl_ctl.value != first.config.method.init_kl_coef
    assert second.kl_ctl.value == pytest.approx(0.0123, rel=0.2)


def test_offline_orchestrator_degenerate_samples(task):
    """Prompt-only / over-truncated samples must not crash experience
    building (empty action rows are padded no-ops in the storage)."""
    from trlx_tpu.orchestrator.offline_orchestrator import OfflineOrchestrator
    from trlx_tpu.trainer.ilql import ILQLTrainer

    walks, logit_mask, metric_fn, reward_fn = task
    config = shrink(base_config("ilql", 15, 8))
    config.train.total_steps = 1
    model = ILQLTrainer(config, metric_fn=metric_fn, logit_mask=logit_mask)
    orch = OfflineOrchestrator(model)
    samples = [np.asarray([3]), np.asarray(walks[0]), np.asarray(walks[1])]
    orch.make_experience(samples, [0.5, 1.0, -1.0])
    assert len(model.store) == 3
