"""Scale-fit audit: the GPT-J-6B / NeoX-20B recipes must shard onto pod
meshes within per-chip HBM. Uses jax.eval_shape + the partition rules — no
allocation, runs on CPU — validating the sharding math BASELINE.md's
targets depend on (the reference can only discover OOM by crashing)."""

import numpy as np

import jax
from jax.sharding import PartitionSpec as P

from trlx_tpu.models.heads import LMWithValueHead
from trlx_tpu.models.lm import LMConfig
from trlx_tpu.parallel.mesh import MESH_AXES
from trlx_tpu.parallel.sharding import lm_partition_rules, match_partition_rules

GPTJ_6B = LMConfig(
    vocab_size=50400,
    n_layer=28,
    n_head=16,
    d_model=4096,
    max_position=2048,
    pos_type="rotary",
    rotary_dim=64,
    parallel_residual=True,
    fused_qkv=False,
    qkv_bias=False,
    out_bias=False,
    tie_word_embeddings=False,
    extra={"lm_head_bias": True},
)

NEOX_20B = LMConfig(
    vocab_size=50432,
    n_layer=44,
    n_head=64,
    d_model=6144,
    d_ff=24576,
    max_position=2048,
    pos_type="rotary",
    rotary_dim=24,
    parallel_residual=True,
    use_parallel_ln=True,
    fused_qkv=True,
    extra={"neox_rotary": True},
    tie_word_embeddings=False,
)


def per_device_param_bytes(cfg, mesh_shape, trainable_frac=1.0):
    """Shapes via eval_shape; per-device bytes from the partition specs."""
    model = LMWithValueHead(cfg, branch_layer=cfg.n_layer - 2)
    ids = jax.ShapeDtypeStruct((1, 8), np.int32)

    shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0), ids, ids)["params"]
    specs = match_partition_rules(lm_partition_rules(), shapes)
    axis_size = dict(zip(MESH_AXES, mesh_shape))

    total_global = 0
    total_per_device = 0
    for (path, leaf), (_, spec) in zip(
        jax.tree_util.tree_leaves_with_path(shapes),
        jax.tree_util.tree_leaves_with_path(specs, is_leaf=lambda x: isinstance(x, P)),
    ):
        shard = 1
        for dim_spec in spec:
            names = dim_spec if isinstance(dim_spec, tuple) else (dim_spec,)
            for name in names:
                if name is not None:
                    shard *= axis_size[name]
        nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        total_global += nbytes
        total_per_device += nbytes // shard
    return total_global, total_per_device


def test_gptj_6b_fits_v4_32():
    """6B on a (1, 8, 4, 1) mesh (v4-32, BASELINE.md's DP recipe target):
    fp32 params + 2 Adam moments must sit well under 32GB/chip."""
    total, per_dev = per_device_param_bytes(GPTJ_6B, (1, 8, 4, 1))
    assert total > 22e9  # ~6B fp32 params — sanity that this IS the 6B model
    # params + adam m/v (moments shard like params)
    assert per_dev * 3 < 8e9, f"per-device state {per_dev*3/1e9:.1f}GB too large"


def test_gptj_6b_single_host_v5e_8():
    """6B sharded over one v5e-8 host (1, 8, 1, 1): params+moments must fit
    16GB/chip with layer freezing (num_layers_unfrozen=2 → moments only for
    the top blocks + heads, the reference's ppo_gptj recipe)."""
    total, per_dev = per_device_param_bytes(GPTJ_6B, (1, 8, 1, 1))
    moments_frac = 0.25  # ~2/28 layers + wte/lm_head/value head trainable
    budget = per_dev + 2 * per_dev * moments_frac
    assert budget < 6e9, f"{budget/1e9:.1f}GB/chip exceeds v5e headroom"


def test_neox_20b_fits_pod():
    """20B PPO (BASELINE.md pod-scale target) on a (1, 16, 8, 1) v4-256-like
    mesh."""
    total, per_dev = per_device_param_bytes(NEOX_20B, (1, 16, 8, 1))
    assert total > 75e9  # ~20B fp32
    assert per_dev * 3 < 4e9, f"per-device state {per_dev*3/1e9:.1f}GB too large"


def test_neox_20b_policy_plus_rm_fits_pod():
    """The ppo_neox20b_rm recipe (BASELINE.md eval config 5): policy master
    params + masked Adam moments + frozen hydra branch + a FULL on-device
    20B reward model, all sharded over the recipe's fsdp=8 × tp=4 axes, must
    sit well inside a v4 chip's 32GB HBM."""
    total, per_dev = per_device_param_bytes(NEOX_20B, (1, 8, 4, 1))
    assert total > 75e9  # ~20B fp32 each
    rm_per_dev = per_dev  # same arch, same partition rules
    moments_frac = 0.15  # num_layers_unfrozen=2 of 44 + embeddings/heads
    branch_frac = 0.12  # top-2 blocks + ln_f + lm_head snapshot
    budget = per_dev * (1 + 2 * moments_frac + branch_frac) + rm_per_dev
    assert budget < 12e9, f"{budget/1e9:.1f}GB/chip static state too large for v4"


def test_every_large_param_is_sharded():
    """No >=d_model^2 tensor may fall through the partition rules to full
    replication — that is how pods OOM at scale."""
    model = LMWithValueHead(GPTJ_6B, branch_layer=26)
    ids = jax.ShapeDtypeStruct((1, 8), np.int32)
    shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0), ids, ids)["params"]
    specs = match_partition_rules(lm_partition_rules(), shapes)

    offenders = []
    for (path, leaf), (_, spec) in zip(
        jax.tree_util.tree_leaves_with_path(shapes),
        jax.tree_util.tree_leaves_with_path(specs, is_leaf=lambda x: isinstance(x, P)),
    ):
        n = int(np.prod(leaf.shape))
        sharded = any(d is not None for d in spec)
        if n >= GPTJ_6B.d_model * GPTJ_6B.d_model and not sharded:
            offenders.append(jax.tree_util.keystr(path))
    assert not offenders, f"large replicated params: {offenders}"
