"""Mesh + sharding semantics on 8 virtual CPU devices — a capability the
reference cannot test at all (its distributed path is exercised only by
manual `accelerate launch`, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from trlx_tpu.models import LMConfig, LMWithValueHead
from trlx_tpu.parallel import make_mesh, match_partition_rules, lm_partition_rules, shard_pytree, batch_sharding
from trlx_tpu.parallel.mesh import resolve_mesh_shape

pytestmark = pytest.mark.slow  # excluded from `make test-fast` (see conftest)


def test_device_count():
    assert jax.device_count() == 8


def test_resolve_mesh_shape():
    assert resolve_mesh_shape((-1, 1, 1, 1), 8) == (8, 1, 1, 1)
    assert resolve_mesh_shape((2, -1, 2, 1), 8) == (2, 2, 2, 1)
    with pytest.raises(ValueError):
        resolve_mesh_shape((3, 1, 1, 1), 8)
    with pytest.raises(ValueError):
        resolve_mesh_shape((-1, -1, 1, 1), 8)


def test_partition_rules_megatron_layout():
    cfg = LMConfig(vocab_size=32, n_layer=2, n_head=4, d_model=64, dtype="float32")
    model = LMWithValueHead(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32), jnp.ones((1, 4), jnp.int32))["params"]
    specs = match_partition_rules(lm_partition_rules(), params)
    t = specs["transformer"]
    assert t["h_0"]["attn"]["c_qkv"]["kernel"] == P("fsdp", "tp")
    assert t["h_0"]["attn"]["c_proj"]["kernel"] == P("tp", "fsdp")
    assert t["h_0"]["mlp"]["c_fc"]["kernel"] == P("fsdp", "tp")
    assert t["h_0"]["mlp"]["c_proj"]["kernel"] == P("tp", "fsdp")
    assert t["wte"]["embedding"] == P("tp", "fsdp")
    assert t["ln_f"]["scale"] == P()


def test_sharded_train_step_matches_single_device():
    """A jitted loss+grad step over a dp×fsdp×tp mesh must agree numerically
    with the unsharded computation (XLA collectives are semantically
    transparent)."""
    cfg = LMConfig(vocab_size=32, n_layer=2, n_head=4, d_model=64, dtype="float32")
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (8, 6), 0, 32)
    mask = jnp.ones((8, 6), jnp.int32)
    params = model.init(rng, ids, mask)["params"]

    def loss_fn(p, i, m):
        out = model.apply({"params": p}, i, m)
        return jnp.mean(out["logits"].astype(jnp.float32) ** 2) + jnp.mean(out["values"] ** 2)

    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params, ids, mask)

    mesh = make_mesh((2, 2, 2, 1))
    sharded_params, _ = shard_pytree(params, mesh)
    sharded_ids = jax.device_put(ids, batch_sharding(mesh, extra_dims=1))
    sharded_mask = jax.device_put(mask, batch_sharding(mesh, extra_dims=1))
    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(sharded_params, sharded_ids, sharded_mask)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    flat_ref = jax.tree_util.tree_leaves(ref_grads)
    flat_sh = jax.tree_util.tree_leaves(jax.device_get(grads))
    for a, b in zip(flat_ref, flat_sh):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_optimizer_state_shards_like_params():
    """ZeRO equivalence: Adam moments follow the param partition specs."""
    cfg = LMConfig(vocab_size=32, n_layer=1, n_head=2, d_model=32, dtype="float32")
    model = LMWithValueHead(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32), jnp.ones((1, 4), jnp.int32))["params"]
    opt = optax.adamw(1e-4)
    opt_state = opt.init(params)
    mesh = make_mesh((1, 2, 4, 1))
    sharded, shardings = shard_pytree(opt_state, mesh)
    adam_state = sharded[0]  # ScaleByAdamState
    mu_qkv = adam_state.mu["transformer"]["h_0"]["attn"]["c_qkv"]["kernel"]
    assert mu_qkv.sharding.spec == P("fsdp", "tp")


def test_sharded_generation_matches_single_device():
    """Greedy decode with params sharded over (fsdp, tp) and the KV cache
    pinned to the mesh must emit the same tokens as unsharded decode."""
    from trlx_tpu.models import LMWithValueHead
    from trlx_tpu.ops.generate import make_generate_fn
    from trlx_tpu.ops.sampling import GenerateConfig
    from trlx_tpu.parallel.mesh import peek_mesh, set_mesh
    from trlx_tpu.parallel.sharding import batch_sharding

    cfg = LMConfig(vocab_size=32, n_layer=2, n_head=4, d_model=64, max_position=64, dtype="float32")
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (8, 6), 2, 32)
    mask = jnp.ones((8, 6), jnp.int32)
    params = model.init(rng, ids, mask)["params"]
    gcfg = GenerateConfig(max_new_tokens=5, do_sample=False, eos_token_id=None, pad_token_id=0)
    gen = make_generate_fn(model, gcfg)

    ref_toks, _ = gen({"params": params}, ids, mask, jax.random.PRNGKey(1))

    prior = peek_mesh()
    mesh = make_mesh((1, 2, 4, 1))
    set_mesh(mesh)
    try:
        # A generate fn is bound to the mesh it was built under: calling the
        # old one after set_mesh must fail LOUDLY (stale KV-cache placement),
        # and a freshly built one must work.
        with np.testing.assert_raises(RuntimeError):
            gen({"params": params}, ids, mask, jax.random.PRNGKey(1))
        gen_sharded = make_generate_fn(model, gcfg)
        sharded_params, _ = shard_pytree(params, mesh)
        s_ids = jax.device_put(ids, batch_sharding(mesh, extra_dims=1))
        s_mask = jax.device_put(mask, batch_sharding(mesh, extra_dims=1))
        toks, _ = gen_sharded({"params": sharded_params}, s_ids, s_mask, jax.random.PRNGKey(1))
    finally:
        set_mesh(prior)  # restore the exact prior global (possibly None)
    np.testing.assert_array_equal(np.asarray(ref_toks), np.asarray(toks))


@pytest.mark.slow
def test_dryrun_all_four_axes_16_devices():
    """All four mesh axes >1 simultaneously ({dp:2, fsdp:2, tp:2, sp:2} on 16
    virtual devices): the full PPO + on-device-RM + fused + ILQL dry run.
    Subprocess because this pytest process is pinned to 8 virtual devices
    (conftest) and the device count is fixed at backend init."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=16").strip()
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "__graft_entry__.py"), "dryrun", "16"],
        env=env,
        cwd=repo,
        capture_output=True,
        text=True,
        timeout=540,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "'dp': 2, 'fsdp': 2, 'tp': 2, 'sp': 2" in proc.stdout, proc.stdout
