"""BlockPool: the host-side allocator behind the paged KV cache
(trlx_tpu/engine/paged_pool.py).

Fast tier — pure host bookkeeping, no device. Covers: transactional
admission with full worst-case commitment, chained prefix digests and the
share-iff-bit-identical rule, pin/refcount lifecycle across overlapping
slots, the diverge-means-stop-sharing (copy-on-write without the copy)
layout, LRU eviction of warm templates, version-flush semantics, and the
leak audit the engine runs at abort/shutdown."""

import numpy as np
import pytest

from trlx_tpu.engine.paged_pool import (
    TRASH_BLOCK,
    BlockPool,
    PoolExhausted,
    prefix_block_digests,
)


def _row(*toks):
    ids = np.asarray(toks, dtype=np.int32)
    return ids, np.ones_like(ids)


# --------------------------------------------------------------- digests


def test_digests_are_chained_and_content_addressed():
    ids, msk = _row(*range(8))
    d = prefix_block_digests(ids, msk, 4, 8)
    assert len(d) == 2  # only FULL blocks digest
    # same content -> same chain
    assert prefix_block_digests(ids.copy(), msk.copy(), 4, 8) == d
    # block 1's digest commits to block 0: editing block 0 changes BOTH
    ids2 = ids.copy()
    ids2[0] += 1
    d2 = prefix_block_digests(ids2, msk, 4, 8)
    assert d2[0] != d[0] and d2[1] != d[1]
    # mask is content too (left padding participates)
    msk2 = msk.copy()
    msk2[1] = 0
    assert prefix_block_digests(ids, msk2, 4, 8)[0] != d[0]
    # cap respects n_blocks_max
    assert len(prefix_block_digests(ids, msk, 4, 1)) == 1


# -------------------------------------------------------------- admission


def test_admit_allocates_full_span_and_release_frees():
    pool = BlockPool(n_blocks=9, block_size=4, blocks_per_slot=2, n_slots=4)
    ids, msk = _row(*range(8))
    row, hit = pool.admit(0, 1, ids, msk)
    assert hit == 0 and row.shape == (2,)
    assert TRASH_BLOCK not in row
    assert pool.used_blocks() == 2 and pool.available() == 6
    assert (pool.tables[0] == row).all()
    pool.leak_audit()
    pool.release(0)
    assert pool.used_blocks() == 0 and pool.available() == 8
    assert (pool.tables[0] == TRASH_BLOCK).all()
    pool.leak_audit(expect_idle=True)


def test_admit_is_transactional_on_exhaustion():
    pool = BlockPool(n_blocks=5, block_size=4, blocks_per_slot=2, n_slots=4)
    pool.admit(0, 1, *_row(*range(8)))
    pool.admit(1, 1, *_row(*range(100, 108)))
    free_before = list(pool.free)
    with pytest.raises(PoolExhausted):
        pool.admit(2, 1, *_row(*range(200, 208)))
    # nothing mutated: free list, refcounts, tables all unchanged
    assert pool.free == free_before
    assert pool.used_blocks() == 4
    assert (pool.tables[2] == TRASH_BLOCK).all()
    pool.leak_audit()


def test_double_admit_same_slot_raises():
    pool = BlockPool(n_blocks=9, block_size=4, blocks_per_slot=2, n_slots=4)
    pool.admit(0, 1, *_row(*range(8)))
    with pytest.raises(RuntimeError, match="still owning"):
        pool.admit(0, 1, *_row(*range(8)))


# ---------------------------------------------------------- prefix sharing


def test_prefix_hit_pins_shared_block_and_skips_its_tokens():
    pool = BlockPool(n_blocks=9, block_size=4, blocks_per_slot=2, n_slots=4)
    ids, msk = _row(*range(8))
    row0, hit0 = pool.admit(0, 1, ids, msk)
    assert hit0 == 0  # empty registry: no hit
    pool.register_prefix(0, 1, ids, msk)
    # same content, width 8, cap (8-1)//4 = 1: block 0 shares, block 1 stays
    # private even though its digest is registered (full-prompt-hit cap)
    row1, hit1 = pool.admit(1, 1, ids, msk)
    assert hit1 == 4
    assert row1[0] == row0[0] and row1[1] != row0[1]
    assert pool.ref[row0[0]] == 2  # pinned by both slots
    assert pool.hits_total == 1 and pool.tokens_saved_total == 4
    assert pool.shared_blocks(1) == [row0[0]] and pool.prefix_hit_tokens(1) == 4
    # releasing the ORIGINAL owner keeps the shared block alive for slot 1
    pool.release(0)
    assert pool.ref[row0[0]] == 1
    pool.leak_audit()
    pool.release(1)
    # registered block parks warm, the private ones free
    assert pool.cached_blocks() >= 1
    pool.leak_audit(expect_idle=True)


def test_divergent_tail_stops_sharing_without_copy():
    # 3 blocks/slot, width 12: blocks 0-1 registrable under the hit cap
    pool = BlockPool(n_blocks=12, block_size=4, blocks_per_slot=3, n_slots=4)
    ids, msk = _row(*range(12))
    row0, _ = pool.admit(0, 1, ids, msk)
    pool.register_prefix(0, 1, ids, msk)
    # same block 0, divergent block 1: hit stops at the first mismatch
    ids2 = ids.copy()
    ids2[5] += 1
    row1, hit = pool.admit(1, 1, ids2, msk)
    assert hit == 4
    assert row1[0] == row0[0]
    assert row1[1] != row0[1] and row1[2] != row0[2]  # private from divergence on
    pool.release(0)
    pool.release(1)
    pool.leak_audit(expect_idle=True)


def test_no_hit_across_weight_versions():
    pool = BlockPool(n_blocks=9, block_size=4, blocks_per_slot=2, n_slots=4)
    ids, msk = _row(*range(8))
    pool.admit(0, 1, ids, msk)
    pool.register_prefix(0, 1, ids, msk)
    pool.release(0)
    _, hit = pool.admit(1, 2, ids, msk)  # version 2: stale KV must not share
    assert hit == 0


def test_flush_registry_on_version_switch():
    pool = BlockPool(n_blocks=9, block_size=4, blocks_per_slot=2, n_slots=4)
    ids, msk = _row(*range(8))
    pool.admit(0, 1, ids, msk)
    pool.register_prefix(0, 1, ids, msk)
    other, om = _row(*range(50, 58))
    pool.admit(1, 1, other, om)
    pool.register_prefix(1, 1, other, om)
    pool.release(0)  # slot 0's registered blocks park warm
    assert pool.cached_blocks() == 2
    pool.flush_registry()  # in-flight weight switch mid-decode
    # warm entry freed outright; slot 1's pinned block only unregistered
    assert pool.cached_blocks() == 0
    assert pool.used_blocks() == 2
    _, hit = pool.admit(2, 1, ids, msk)
    assert hit == 0  # old-version KV is gone from the registry
    pool.release(1)
    pool.release(2)
    pool.leak_audit(expect_idle=True)


# --------------------------------------------------------------- eviction


def test_lru_eviction_oldest_first():
    # blocks 1..3, single-block spans, 6-token rows (block 0 registrable)
    pool = BlockPool(n_blocks=4, block_size=4, blocks_per_slot=1, n_slots=4)
    a, am = _row(*range(6))
    b, bm = _row(*range(10, 16))
    pool.admit(0, 1, a, am)
    pool.register_prefix(0, 1, a, am)
    pool.release(0)  # a's template parks warm (oldest)
    pool.admit(1, 1, b, bm)
    pool.register_prefix(1, 1, b, bm)
    pool.release(1)  # b's template parks warm (youngest)
    assert pool.cached_blocks() == 2 and len(pool.free) == 1
    pool.admit(2, 1, *_row(*range(20, 26)))  # last free block, no eviction
    assert pool.evictions == 0
    pool.admit(3, 1, *_row(*range(30, 36)))  # dry -> evict the OLDEST only
    assert pool.evictions == 1 and pool.cached_blocks() == 1
    pool.release(2)
    pool.release(3)
    # a's template was evicted -> miss; b's (younger) survived -> hit
    _, hit_a = pool.admit(0, 1, a, am)
    assert hit_a == 0
    pool.release(0)
    _, hit_b = pool.admit(1, 1, b, bm)
    assert hit_b == 4
    pool.release(1)
    pool.leak_audit(expect_idle=True)


def test_pinned_warm_hit_costs_availability():
    pool = BlockPool(n_blocks=4, block_size=4, blocks_per_slot=2, n_slots=4)
    ids, msk = _row(*range(8))
    pool.admit(0, 1, ids, msk)
    pool.register_prefix(0, 1, ids, msk)
    pool.release(0)  # 1 warm template + 2 free: 3 allocatable
    _, hit = pool.admit(1, 1, ids, msk)  # pins the warm block + 1 private
    assert hit == 4
    # the pinned template left the evictable set: one block remains, so a
    # 2-block span no longer fits (the feasibility check counts fresh pins)
    assert pool.available() == 1
    with pytest.raises(PoolExhausted):
        pool.admit(2, 1, *_row(*range(40, 48)))
    pool.release(1)
    pool.leak_audit(expect_idle=True)


# -------------------------------------------------------------- leak audit


def test_leak_audit_names_violations():
    pool = BlockPool(n_blocks=5, block_size=4, blocks_per_slot=2, n_slots=2)
    pool.admit(0, 1, *_row(*range(8)))
    with pytest.raises(RuntimeError, match="still owned"):
        pool.leak_audit(expect_idle=True)
    # a lost block: simulate bookkeeping damage
    blk = pool._slot_private[0].pop()
    with pytest.raises(RuntimeError, match=f"block {blk}"):
        pool.leak_audit()


def test_release_detects_negative_refcount():
    pool = BlockPool(n_blocks=5, block_size=4, blocks_per_slot=2, n_slots=2)
    pool.admit(0, 1, *_row(*range(8)))
    stolen = list(pool._slot_private[0])
    pool.release(0)
    pool._slot_private[0] = stolen  # replay the release
    with pytest.raises(RuntimeError, match="negative"):
        pool.release(0)


def test_constructor_validation():
    with pytest.raises(ValueError, match=">= 2 blocks"):
        BlockPool(n_blocks=1, block_size=4, blocks_per_slot=1, n_slots=1)
    with pytest.raises(ValueError, match="worst-case span"):
        BlockPool(n_blocks=3, block_size=4, blocks_per_slot=4, n_slots=1)
