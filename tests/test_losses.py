"""Loss golden-value tests: GAE against a plain-Python recurrence, PPO loss
directionality, ILQL loss against hand-computed values, math primitives."""

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.ops.modeling import masked_whiten, logprobs_from_logits, topk_mask
from trlx_tpu.ops.rl_losses import gae_advantages, kl_penalty_rewards, ppo_loss
from trlx_tpu.ops.ilql_loss import ilql_loss


def reference_gae(rewards, values, gamma, lam):
    """The reference's reversed Python loop
    (reference: trlx/model/accelerate_ppo_model.py:83-97), verbatim math."""
    R = rewards.shape[1]
    lastgaelam = np.zeros(rewards.shape[0])
    advs = []
    for t in reversed(range(R)):
        nextvalues = values[:, t + 1] if t < R - 1 else 0.0
        delta = rewards[:, t] + gamma * nextvalues - values[:, t]
        lastgaelam = delta + gamma * lam * lastgaelam
        advs.append(lastgaelam.copy())
    return np.stack(advs[::-1], axis=1)


def test_gae_matches_reference_loop():
    rng = np.random.default_rng(0)
    rewards = rng.normal(size=(4, 7)).astype(np.float32)
    values = rng.normal(size=(4, 7)).astype(np.float32)
    mask = np.ones((4, 7), np.float32)
    adv, ret = gae_advantages(jnp.asarray(rewards), jnp.asarray(values), jnp.asarray(mask), 0.98, 0.95)
    expected = reference_gae(rewards, values, 0.98, 0.95)
    np.testing.assert_allclose(np.asarray(adv), expected, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ret), expected + values, rtol=1e-5, atol=1e-5)


def test_gae_masked_tail_is_clean():
    """A sample of valid length L inside an R-padded batch must get the same
    advantages as the same sample in an exactly-L batch."""
    rng = np.random.default_rng(1)
    L, R = 4, 8
    rewards = np.zeros((1, R), np.float32)
    values = np.zeros((1, R), np.float32)
    rewards[0, :L] = rng.normal(size=L)
    values[0, :L] = rng.normal(size=L)
    mask = np.zeros((1, R), np.float32)
    mask[0, :L] = 1
    adv_padded, _ = gae_advantages(jnp.asarray(rewards), jnp.asarray(values), jnp.asarray(mask), 0.99, 0.9)
    adv_exact, _ = gae_advantages(
        jnp.asarray(rewards[:, :L]), jnp.asarray(values[:, :L]), jnp.ones((1, L), jnp.float32), 0.99, 0.9
    )
    np.testing.assert_allclose(np.asarray(adv_padded)[0, :L], np.asarray(adv_exact)[0], rtol=1e-5, atol=1e-6)
    assert np.all(np.asarray(adv_padded)[0, L:] == 0)


def test_kl_penalty_terminal_score_on_last_valid_token():
    lp = jnp.zeros((2, 5))
    rlp = jnp.zeros((2, 5))
    mask = jnp.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], jnp.int32)
    scores = jnp.asarray([2.0, 3.0])
    rewards, kl = kl_penalty_rewards(lp, rlp, mask, scores, jnp.asarray(0.1))
    rewards = np.asarray(rewards)
    assert rewards[0, 2] == 2.0 and rewards[0, 3] == 0.0  # last VALID token
    assert rewards[1, 4] == 3.0


def test_ppo_loss_direction():
    """At ratio == 1 the pg gradient w.r.t. logprobs equals −whitened_adv /
    n_tokens — positive (whitened) advantage pushes the action's logprob up."""
    from trlx_tpu.ops.modeling import masked_whiten

    rng = np.random.default_rng(2)
    b, R = 2, 4
    old_logprobs = jnp.asarray(rng.normal(size=(b, R)).astype(np.float32)) * 0.1
    old_values = jnp.zeros((b, R), jnp.float32)
    rewards = jnp.asarray(rng.normal(size=(b, R)).astype(np.float32))
    mask = jnp.ones((b, R), jnp.float32)

    def loss_of(lp):
        loss, _ = ppo_loss(lp, old_values, old_logprobs, old_values, rewards, mask,
                           gamma=1.0, lam=0.95, cliprange=0.2, cliprange_value=0.2, vf_coef=0.0)
        return loss

    g = np.asarray(jax.grad(loss_of)(old_logprobs))
    adv, _ = gae_advantages(rewards, old_values, mask, 1.0, 0.95)
    wadv = np.asarray(masked_whiten(adv, mask))
    np.testing.assert_allclose(g, -wadv / (b * R), rtol=1e-4, atol=1e-6)


def test_ppo_loss_stats_keys():
    b, R = 2, 3
    z = jnp.zeros((b, R), jnp.float32)
    loss, stats = ppo_loss(z, z, z, z, z, jnp.ones((b, R)), gamma=1.0, lam=1.0,
                           cliprange=0.2, cliprange_value=0.2, vf_coef=1.0)
    for k in ["loss", "pg_loss", "vf_loss", "mean_kl", "pg_clipfrac"]:
        assert k in stats


def test_ilql_loss_golden():
    """Hand-computable single-sample case: 2 tokens, 1 action."""
    V_vocab = 3
    logits = jnp.zeros((1, 2, V_vocab), jnp.float32)
    # one action at hidden position 0, action token = input_ids[1] = 2
    qs = (jnp.asarray([[[0.0, 0.0, 1.0]]]), jnp.asarray([[[0.0, 0.0, 0.5]]]))
    target_qs = (jnp.asarray([[[0.0, 0.0, 2.0]]]), jnp.asarray([[[0.0, 0.0, 1.5]]]))
    vs = jnp.asarray([[0.5, 9.9]])  # V(s0)=0.5; V(s1) zeroed by dones
    input_ids = jnp.asarray([[1, 2]])
    attn = jnp.ones((1, 2), jnp.int32)
    actions_ixs = jnp.asarray([[0]])
    rewards = jnp.asarray([[1.0]])
    dones = jnp.asarray([[1, 0]])
    loss, stats = ilql_loss(logits, qs, target_qs, vs, input_ids, attn, actions_ixs,
                            rewards, dones, gamma=0.9, tau=0.7, cql_scale=0.0, awac_scale=0.0)
    # Q_ = r + gamma * Vnext*done = 1.0 + 0; loss_q = (1-1)^2 + (0.5-1)^2 = 0.25
    # targetQ = min(2.0, 1.5) = 1.5 >= V=0.5 ⇒ loss_v = 0.7*(1.0)^2 = 0.7
    np.testing.assert_allclose(float(stats["losses/loss_q"]), 0.25, rtol=1e-5)
    np.testing.assert_allclose(float(stats["losses/loss_v"]), 0.7, rtol=1e-5)


def test_masked_whiten_ignores_padding():
    x = jnp.asarray([[1.0, 2.0, 3.0, 100.0]])
    mask = jnp.asarray([[1, 1, 1, 0]], jnp.float32)
    w = np.asarray(masked_whiten(x, mask))
    assert abs(w[0, :3].mean()) < 1e-5
    assert w[0, 3] == 0.0


def test_logprobs_from_logits():
    logits = jnp.asarray([[[1.0, 2.0, 3.0]]])
    labels = jnp.asarray([[2]])
    lp = float(logprobs_from_logits(logits, labels)[0, 0])
    expected = 3.0 - np.log(np.exp(1) + np.exp(2) + np.exp(3))
    np.testing.assert_allclose(lp, expected, rtol=1e-5)


def test_topk_mask():
    x = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
    out = np.asarray(topk_mask(x, 2))
    assert out[0, 1] == 5.0 and out[0, 2] == 3.0
    assert np.isinf(out[0, 0]) and np.isinf(out[0, 3])
