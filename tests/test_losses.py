"""Loss golden-value tests: GAE against a plain-Python recurrence, PPO loss
directionality, ILQL loss against hand-computed values, math primitives."""

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.ops.modeling import masked_whiten, logprobs_from_logits, topk_mask
from trlx_tpu.ops.rl_losses import gae_advantages, kl_penalty_rewards, ppo_loss
from trlx_tpu.ops.ilql_loss import ilql_loss


def reference_gae(rewards, values, gamma, lam):
    """The reference's reversed Python loop
    (reference: trlx/model/accelerate_ppo_model.py:83-97), verbatim math."""
    R = rewards.shape[1]
    lastgaelam = np.zeros(rewards.shape[0])
    advs = []
    for t in reversed(range(R)):
        nextvalues = values[:, t + 1] if t < R - 1 else 0.0
        delta = rewards[:, t] + gamma * nextvalues - values[:, t]
        lastgaelam = delta + gamma * lam * lastgaelam
        advs.append(lastgaelam.copy())
    return np.stack(advs[::-1], axis=1)


def test_gae_matches_reference_loop():
    rng = np.random.default_rng(0)
    rewards = rng.normal(size=(4, 7)).astype(np.float32)
    values = rng.normal(size=(4, 7)).astype(np.float32)
    mask = np.ones((4, 7), np.float32)
    adv, ret = gae_advantages(jnp.asarray(rewards), jnp.asarray(values), jnp.asarray(mask), 0.98, 0.95)
    expected = reference_gae(rewards, values, 0.98, 0.95)
    np.testing.assert_allclose(np.asarray(adv), expected, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ret), expected + values, rtol=1e-5, atol=1e-5)


def test_gae_masked_tail_is_clean():
    """A sample of valid length L inside an R-padded batch must get the same
    advantages as the same sample in an exactly-L batch."""
    rng = np.random.default_rng(1)
    L, R = 4, 8
    rewards = np.zeros((1, R), np.float32)
    values = np.zeros((1, R), np.float32)
    rewards[0, :L] = rng.normal(size=L)
    values[0, :L] = rng.normal(size=L)
    mask = np.zeros((1, R), np.float32)
    mask[0, :L] = 1
    adv_padded, _ = gae_advantages(jnp.asarray(rewards), jnp.asarray(values), jnp.asarray(mask), 0.99, 0.9)
    adv_exact, _ = gae_advantages(
        jnp.asarray(rewards[:, :L]), jnp.asarray(values[:, :L]), jnp.ones((1, L), jnp.float32), 0.99, 0.9
    )
    np.testing.assert_allclose(np.asarray(adv_padded)[0, :L], np.asarray(adv_exact)[0], rtol=1e-5, atol=1e-6)
    assert np.all(np.asarray(adv_padded)[0, L:] == 0)


def test_kl_penalty_terminal_score_on_last_valid_token():
    lp = jnp.zeros((2, 5))
    rlp = jnp.zeros((2, 5))
    mask = jnp.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], jnp.int32)
    scores = jnp.asarray([2.0, 3.0])
    rewards, kl = kl_penalty_rewards(lp, rlp, mask, scores, jnp.asarray(0.1))
    rewards = np.asarray(rewards)
    assert rewards[0, 2] == 2.0 and rewards[0, 3] == 0.0  # last VALID token
    assert rewards[1, 4] == 3.0


def test_ppo_loss_direction():
    """At ratio == 1 the pg gradient w.r.t. logprobs equals −whitened_adv /
    n_tokens — positive (whitened) advantage pushes the action's logprob up."""
    from trlx_tpu.ops.modeling import masked_whiten

    rng = np.random.default_rng(2)
    b, R = 2, 4
    old_logprobs = jnp.asarray(rng.normal(size=(b, R)).astype(np.float32)) * 0.1
    old_values = jnp.zeros((b, R), jnp.float32)
    rewards = jnp.asarray(rng.normal(size=(b, R)).astype(np.float32))
    mask = jnp.ones((b, R), jnp.float32)

    def loss_of(lp):
        loss, _ = ppo_loss(lp, old_values, old_logprobs, old_values, rewards, mask,
                           gamma=1.0, lam=0.95, cliprange=0.2, cliprange_value=0.2, vf_coef=0.0)
        return loss

    g = np.asarray(jax.grad(loss_of)(old_logprobs))
    adv, _ = gae_advantages(rewards, old_values, mask, 1.0, 0.95)
    wadv = np.asarray(masked_whiten(adv, mask))
    np.testing.assert_allclose(g, -wadv / (b * R), rtol=1e-4, atol=1e-6)


def test_ppo_loss_stats_keys():
    b, R = 2, 3
    z = jnp.zeros((b, R), jnp.float32)
    loss, stats = ppo_loss(z, z, z, z, z, jnp.ones((b, R)), gamma=1.0, lam=1.0,
                           cliprange=0.2, cliprange_value=0.2, vf_coef=1.0)
    for k in ["loss", "pg_loss", "vf_loss", "mean_kl", "pg_clipfrac"]:
        assert k in stats


def test_ilql_loss_golden():
    """Hand-computable single-sample case: 2 tokens, 1 action."""
    V_vocab = 3
    logits = jnp.zeros((1, 2, V_vocab), jnp.float32)
    # one action at hidden position 0, action token = input_ids[1] = 2
    qs = (jnp.asarray([[[0.0, 0.0, 1.0]]]), jnp.asarray([[[0.0, 0.0, 0.5]]]))
    target_qs = (jnp.asarray([[[0.0, 0.0, 2.0]]]), jnp.asarray([[[0.0, 0.0, 1.5]]]))
    vs = jnp.asarray([[0.5, 9.9]])  # V(s0)=0.5; V(s1) zeroed by dones
    input_ids = jnp.asarray([[1, 2]])
    attn = jnp.ones((1, 2), jnp.int32)
    actions_ixs = jnp.asarray([[0]])
    rewards = jnp.asarray([[1.0]])
    dones = jnp.asarray([[1, 0]])
    loss, stats = ilql_loss(logits, qs, target_qs, vs, input_ids, attn, actions_ixs,
                            rewards, dones, gamma=0.9, tau=0.7, cql_scale=0.0, awac_scale=0.0)
    # Q_ = r + gamma * Vnext*done = 1.0 + 0; loss_q = (1-1)^2 + (0.5-1)^2 = 0.25
    # targetQ = min(2.0, 1.5) = 1.5 >= V=0.5 ⇒ loss_v = 0.7*(1.0)^2 = 0.7
    np.testing.assert_allclose(float(stats["losses/loss_q"]), 0.25, rtol=1e-5)
    np.testing.assert_allclose(float(stats["losses/loss_v"]), 0.7, rtol=1e-5)


def test_masked_whiten_ignores_padding():
    x = jnp.asarray([[1.0, 2.0, 3.0, 100.0]])
    mask = jnp.asarray([[1, 1, 1, 0]], jnp.float32)
    w = np.asarray(masked_whiten(x, mask))
    assert abs(w[0, :3].mean()) < 1e-5
    assert w[0, 3] == 0.0


def test_logprobs_from_logits():
    logits = jnp.asarray([[[1.0, 2.0, 3.0]]])
    labels = jnp.asarray([[2]])
    lp = float(logprobs_from_logits(logits, labels)[0, 0])
    expected = 3.0 - np.log(np.exp(1) + np.exp(2) + np.exp(3))
    np.testing.assert_allclose(lp, expected, rtol=1e-5)


def test_topk_mask():
    x = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
    out = np.asarray(topk_mask(x, 2))
    assert out[0, 1] == 5.0 and out[0, 2] == 3.0
    assert np.isinf(out[0, 0]) and np.isinf(out[0, 3])


# ---------------------------------------------------------------------------
# Fused-logprob kernel parity (interpret mode on CPU) + segment-aware losses
# ---------------------------------------------------------------------------

import pytest

from trlx_tpu.ops.fused_logprob import fused_logprob, naive_logprob, routed_logprob


def _head_case(rng, B, T, D, V, dtype, tied, bias):
    x = jnp.asarray(rng.normal(size=(B, T, D)), dtype) * 0.3
    w = (
        jnp.asarray(rng.normal(size=(V, D) if tied else (D, V)), dtype) * 0.2
    )
    b = jnp.asarray(rng.normal(size=(V,)), jnp.float32) if bias else None
    y = jnp.asarray(rng.integers(0, V, size=(B, T)), jnp.int32)
    return x, w, b, y


@pytest.mark.parametrize("tied,bias", [(True, False), (False, False), (False, True)])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_logprob_matches_naive(tied, bias, dtype):
    """Interpret-mode kernel == materialized log_softmax chain, at a shape
    that exercises BOTH padded tails: N=2*19=38 (pads to 128) and V=300
    with block_v=128 (partial 44-wide vocab tail block)."""
    rng = np.random.default_rng(0)
    x, w, b, y = _head_case(rng, 2, 19, 64, 300, dtype, tied, bias)
    lp_k, lse_k, ent_k = fused_logprob(
        x, w, y, b, tied=tied, interpret=True, block_v=128
    )
    lp_n, lse_n, ent_n = naive_logprob(x, w, y, b, tied=tied)
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(np.asarray(lp_k), np.asarray(lp_n), rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(lse_k), np.asarray(lse_n), rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(ent_k), np.asarray(ent_n), rtol=tol, atol=tol)


@pytest.mark.parametrize("tied,bias", [(True, False), (False, False), (False, True)])
def test_fused_logprob_grads_match_naive(tied, bias):
    """jax.grad through the custom VJP == autodiff through the naive chain
    (fp32, a weighted sum of all three outputs so every cotangent is live)."""
    rng = np.random.default_rng(1)
    x, w, b, y = _head_case(rng, 2, 19, 64, 300, jnp.float32, tied, bias)

    def scalar(fn):
        def f(x, w, b):
            lp, lse, ent = fn(x, w, y, b, tied=tied)
            return jnp.sum(lp) + 0.5 * jnp.sum(lse) - 0.25 * jnp.sum(ent)

        return f

    fused = lambda x_, w_, y_, b_, tied: fused_logprob(
        x_, w_, y_, b_, tied=tied, interpret=True, block_v=128
    )
    args = (x, w, b)
    argnums = (0, 1, 2) if bias else (0, 1)
    g_k = jax.grad(scalar(fused), argnums=argnums)(*args)
    g_n = jax.grad(scalar(naive_logprob), argnums=argnums)(*args)
    for a, bb in zip(g_k, g_n):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("mode", ["force", "off"])
def test_routed_logprob_masked_rows_are_zero_and_finite(mode):
    """Ragged masks incl. FULLY-masked rows: outputs exactly 0 there, grads
    finite everywhere, on both the kernel route and the naive fallback."""
    rng = np.random.default_rng(2)
    x, w, b, y = _head_case(rng, 2, 8, 64, 300, jnp.float32, False, True)
    mask = jnp.ones((2, 8), jnp.int32).at[0, 5:].set(0).at[1, :].set(0)  # row 1 fully masked

    lp, lse, ent = routed_logprob(x, w, y, b, tied=False, mode=mode, mask=mask)
    for v in (lp, lse, ent):
        v = np.asarray(v)
        assert np.all(np.isfinite(v))
        assert np.all(v[0, 5:] == 0) and np.all(v[1] == 0)

    def loss(x, w, b):
        lp, lse, ent = routed_logprob(x, w, y, b, tied=False, mode=mode, mask=mask)
        return jnp.sum(lp) + jnp.sum(lse) + jnp.sum(ent)

    grads = jax.grad(loss, argnums=(0, 1, 2))(x, w, b)
    for g in grads:
        assert np.all(np.isfinite(np.asarray(g)))


def test_logprobs_from_logits_mask_skips_garbage_rows():
    """Non-finite logits in masked rows must not leak NaN (the fallback's
    pad-safety contract); unmasked rows match the no-mask result."""
    logits = jnp.asarray([[[1.0, 2.0, 3.0], [np.inf, -np.inf, np.nan]]])
    labels = jnp.asarray([[2, 0]])
    mask = jnp.asarray([[1, 0]], jnp.int32)
    out = np.asarray(logprobs_from_logits(logits, labels, mask))
    assert np.isfinite(out).all() and out[0, 1] == 0.0
    np.testing.assert_allclose(
        out[0, 0], float(logprobs_from_logits(logits[:, :1], labels[:, :1])[0, 0])
    )


def test_label_logit_identity_lp_plus_lse():
    """The fused-ILQL identity: gathered label LOGIT == logprob + logsumexp
    (how the trainer reads per-action Q values out of the streaming head)."""
    rng = np.random.default_rng(3)
    x, w, b, y = _head_case(rng, 2, 6, 32, 200, jnp.float32, False, True)
    lp, lse, _ = routed_logprob(x, w, y, b, tied=False, mode="force")
    logits = (x @ w + b).astype(jnp.float32)
    gathered = jnp.take_along_axis(logits, y[..., None], axis=-1)[..., 0]
    np.testing.assert_allclose(np.asarray(lp + lse), np.asarray(gathered), rtol=1e-4, atol=1e-4)


def test_gae_segment_ids_match_unpacked():
    """Two episodes packed into one row == the same episodes in separate
    rows: the segment-gated recurrence resets bootstrap AND lam-carry."""
    rng = np.random.default_rng(4)
    R = 5
    r = jnp.asarray(rng.normal(size=(2, R)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, R)), jnp.float32)
    m = jnp.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 0]], jnp.float32)
    a_u, ret_u = gae_advantages(r, v, m, 0.95, 0.9)

    rp = jnp.concatenate([r[0, :3], r[1, :4]])[None]
    vp = jnp.concatenate([v[0, :3], v[1, :4]])[None]
    seg = jnp.asarray([[1, 1, 1, 2, 2, 2, 2]])
    a_p, ret_p = gae_advantages(
        rp, vp, jnp.ones((1, 7), jnp.float32), 0.95, 0.9, segment_ids=seg
    )
    np.testing.assert_allclose(
        np.asarray(a_p)[0], np.concatenate([a_u[0, :3], a_u[1, :4]]), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(ret_p)[0], np.concatenate([ret_u[0, :3], ret_u[1, :4]]), rtol=1e-5, atol=1e-6
    )


def test_ppo_loss_packed_per_sequence_stats():
    """mean_kl / mean_return normalize by the true episode count (n_seqs)
    in packed layout — matching the unpacked per-row means."""
    rng = np.random.default_rng(5)
    R = 5
    m = jnp.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 0]], jnp.float32)
    lp = jnp.asarray(rng.normal(size=(2, R)), jnp.float32) * 0.01 * m
    olp = lp + jnp.asarray(rng.normal(size=(2, R)), jnp.float32) * 0.01 * m
    v = jnp.asarray(rng.normal(size=(2, R)), jnp.float32) * m
    r = jnp.asarray(rng.normal(size=(2, R)), jnp.float32) * m
    kw = dict(gamma=0.95, lam=0.9, cliprange=0.2, cliprange_value=0.2, vf_coef=1.0)
    _, st_u = ppo_loss(lp, v, olp, v, r, m, **kw)

    def packrow(a):
        return jnp.concatenate([a[0, :3], a[1, :4]])[None]

    seg = jnp.asarray([[1, 1, 1, 2, 2, 2, 2]])
    mp = jnp.ones((1, 7), jnp.float32)
    _, st_p = ppo_loss(
        packrow(lp), packrow(v), packrow(olp), packrow(v), packrow(r), mp,
        segment_ids=seg, n_seqs=2, **kw,
    )
    for k in ("mean_kl", "mean_return"):
        np.testing.assert_allclose(float(st_u[k]), float(st_p[k]), rtol=1e-4, atol=1e-6)


def test_ilql_loss_terms_matches_dense_wrapper():
    """ilql_loss (dense wrapper) == ilql_loss_terms fed with manually
    gathered Q / target-Q / CQL-NLL and the AWAC scalar."""
    from trlx_tpu.ops.ilql_loss import action_tokens, ilql_loss_terms

    rng = np.random.default_rng(6)
    b, T, A, V = 2, 8, 3, 11
    logits = jnp.asarray(rng.normal(size=(b, T, V)), jnp.float32)
    qs = tuple(jnp.asarray(rng.normal(size=(b, A, V)), jnp.float32) for _ in range(2))
    tqs = tuple(jnp.asarray(rng.normal(size=(b, A, V)), jnp.float32) for _ in range(2))
    vs = jnp.asarray(rng.normal(size=(b, A + 1)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, V, size=(b, T)), jnp.int32)
    attn = jnp.ones((b, T), jnp.int32)
    aix = jnp.asarray([[1, 2, 3], [2, 3, 4]], jnp.int32)
    rew = jnp.asarray(rng.normal(size=(b, A)), jnp.float32)
    dones = jnp.ones((b, A + 1), jnp.float32).at[:, -1].set(0)
    kw = dict(gamma=0.9, tau=0.7, cql_scale=0.3, awac_scale=0.5)

    loss_d, st_d = ilql_loss(logits, qs, tqs, vs, ids, attn, aix, rew, dones, **kw)

    actions = action_tokens(ids, aix)
    gather = lambda q: jnp.take_along_axis(q, actions[..., None], axis=-1)[..., 0]
    nlls = [-logprobs_from_logits(q, actions) for q in qs]
    attn1 = attn[:, 1:].astype(jnp.float32)
    nll = -logprobs_from_logits(logits[:, :-1], ids[:, 1:])
    awac = jnp.sum(nll * attn1) / jnp.maximum(jnp.sum(attn1), 1.0)
    loss_t, st_t = ilql_loss_terms(
        [gather(q) for q in qs], [gather(q) for q in tqs], nlls, vs, rew, dones, awac, **kw
    )
    np.testing.assert_allclose(float(loss_d), float(loss_t), rtol=1e-6)
    for k in st_d:
        np.testing.assert_allclose(float(st_d[k]), float(st_t[k]), rtol=1e-6)
