"""Training-health monitor (trlx_tpu/observability/health.py + export.py).

Unit tier: the hysteresis state machine (escalation streaks, CRIT passing
through WARN, one-level-at-a-time de-escalation, the monotonic transition
counter, the guarded on_crit hook), each detector's judgment math
(reward-drift z-score vs the frozen warmup baseline, KL ratio/saturation,
entropy-collapse fractions, explained-variance thresholds, the rollout
sentinels), lineage-record round-trips, the CRIT -> emergency_capture
escalation, Prometheus name sanitization, and a live MetricsExporter
scraped over HTTP with urllib.

Integration tier (CPU): the PR's acceptance run — an overlapped PPO run at
max_staleness=1 with the health monitor + live exporter armed and the
``reward_drift`` drill injected walks the detector OK -> WARN -> CRIT,
escalates a ``health_reward_drift`` incident bundle, serves degraded
``/healthz`` + ``health/*`` gauges over HTTP DURING the run, leaves
lineage.jsonl behind, and renders the report's health section.
"""

import json
import os
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402
from trlx_tpu.observability import anomaly as obs_anomaly  # noqa: E402
from trlx_tpu.observability import report  # noqa: E402
from trlx_tpu.observability import spans as obs_spans  # noqa: E402
from trlx_tpu.observability.export import (  # noqa: E402
    MetricsExporter,
    _VALID,
    sanitize_metric_name,
)
from trlx_tpu.observability.health import (  # noqa: E402
    CRIT,
    OK,
    WARN,
    EntropyCollapseDetector,
    ExplainedVarianceDetector,
    HealthMonitor,
    HysteresisDetector,
    KLHealthDetector,
    LineageRecord,
    MixedVersionDetector,
    RewardDriftDetector,
    RolloutSentinel,
    degenerate_rate,
    truncation_rate,
)


@pytest.fixture(autouse=True)
def _emergency_isolation():
    """The emergency hook is a process global the monitor escalates through —
    always disarm so a test's fake capture never leaks into a later run."""
    yield
    obs_spans.shutdown()
    obs_anomaly.register_emergency(None)


class _Direct(HysteresisDetector):
    """Severity passthrough: observe(0|1|2) exercises ONLY the state machine."""

    name = "direct"

    def severity(self, obs):
        return int(obs)


# ------------------------------------------------------------- hysteresis


def test_hysteresis_escalates_through_warn_with_streaks():
    d = _Direct(warn_streak=2, crit_streak=3)
    crits = []
    d.on_crit = lambda det, obs: crits.append((det.name, obs))
    assert d.observe(2) == OK  # streak 1 < warn_streak
    assert d.observe(2) == WARN  # streak 2: WARN, not CRIT — passes through
    assert d.observe(2) == CRIT  # crit streak 3
    assert d.state_changes == 2
    assert crits == [("direct", 2)]  # fired exactly once, on the transition
    assert d.observe(2) == CRIT  # steady state: no further transitions
    assert d.state_changes == 2 and len(crits) == 1


def test_hysteresis_single_bad_window_never_flips_state():
    d = _Direct(warn_streak=2, crit_streak=4)
    for sev in (1, 0, 2, 0, 1, 0):  # isolated spikes, never consecutive
        d.observe(sev)
    assert d.state == OK and d.state_changes == 0


def test_hysteresis_deescalates_one_level_per_clean_streak():
    d = _Direct(warn_streak=1, crit_streak=2, clear_streak=2)
    d.observe(2), d.observe(2)
    assert d.state == CRIT
    assert d.observe(0) == CRIT  # clean streak 1 < clear_streak
    assert d.observe(0) == WARN  # one level down...
    assert d.observe(0) == WARN  # ...and the next level costs a FULL streak
    assert d.observe(0) == OK
    assert d.state_changes == 4  # ok->warn->crit->warn->ok


def test_hysteresis_warn_resurgence_never_demotes_crit():
    d = _Direct(warn_streak=1, crit_streak=1, clear_streak=3)
    d.observe(2)
    assert d.state == CRIT
    for _ in range(5):  # sustained sev-1: bad streak says "WARN", state holds
        assert d.observe(1) == CRIT


def test_hysteresis_on_crit_exception_is_swallowed():
    d = _Direct(warn_streak=1, crit_streak=1)

    def boom(det, obs):
        raise RuntimeError("escalation must never take the loop down")

    d.on_crit = boom
    assert d.observe(2) == CRIT  # no raise


# --------------------------------------------------------------- detectors


def test_reward_drift_baseline_frozen_then_z_scored():
    d = RewardDriftDetector(warmup=3, warn_z=3.0, crit_z=6.0, warn_streak=1, crit_streak=2)
    for x in (1.0, 1.2, 0.8):  # warmup: builds the baseline, judges nothing
        assert d.severity(x) == 0
    assert d.severity(1.1) == 0  # in-distribution stays clean
    assert d.mu0 == pytest.approx(1.0) and d.sigma0 > 0
    assert d.severity(1000.0) == 2  # the drill's offset: z >> crit_z
    assert d.z > 6.0


def test_reward_drift_sigma_floor_absorbs_quiet_warmup():
    # identical warmup samples -> std 0; the 0.1*|mu| floor keeps ordinary
    # fluctuation around a mean of 10 from registering as drift
    d = RewardDriftDetector(warmup=2, recent_window=1)
    d.severity(10.0), d.severity(10.0)
    assert d.severity(11.0) == 0  # z = 1/1.0 with the floored sigma
    assert d.sigma0 == pytest.approx(1.0)


def test_kl_detector_ratio_bands_and_saturation():
    d = KLHealthDetector(warmup=0, warn_ratio=2.0, crit_ratio=4.0, sat_factor=10.0)
    base = {"target": 0.1, "coef": 0.05, "init_coef": 0.05}
    assert d.severity({**base, "kl": 0.1}) == 0  # on target
    assert d.severity({**base, "kl": 0.25}) == 1  # 2.5x above
    assert d.severity({**base, "kl": 0.5}) == 2  # 5x above
    assert d.severity({**base, "kl": 0.01}) == 1  # over-tight leash: WARN only
    # coefficient pinned 10x from init WARNs even with KL on target
    assert d.severity({"kl": 0.1, "target": 0.1, "coef": 0.5, "init_coef": 0.05}) == 1
    assert d.severity({"kl": 0.1, "target": 0.1, "coef": 0.005, "init_coef": 0.05}) == 1


def test_kl_detector_silent_without_adaptive_target():
    d = KLHealthDetector(warmup=0)
    assert d.severity({"kl": 99.0, "target": None, "coef": 1.0}) == 0
    assert d.severity({"kl": 99.0, "target": 0.0}) == 0  # fixed controller
    assert d.severity({"kl": None, "target": 0.1}) == 0


def test_kl_detector_warmup_exempts_early_kl():
    d = KLHealthDetector(warmup=2, warn_ratio=2.0)
    obs = {"kl": 1.0, "target": 0.1}  # 10x above target
    assert d.severity(obs) == 0 and d.severity(obs) == 0  # warmup
    assert d.severity(obs) == 2


def test_entropy_collapse_fractions_of_warmup_baseline():
    d = EntropyCollapseDetector(warmup=2, warn_frac=0.5, crit_frac=0.2)
    d.severity(2.0), d.severity(2.0)  # baseline mean 2.0
    assert d.severity(1.9) == 0
    assert d.severity(0.8) == 1  # < 0.5 * base
    assert d.severity(0.3) == 2  # < 0.2 * base
    zero = EntropyCollapseDetector(warmup=1)
    zero.severity(0.0)
    assert zero.severity(0.0) == 0  # degenerate baseline judges nothing


def test_explained_variance_negative_means_critic_worse_than_mean():
    d = ExplainedVarianceDetector(warmup=1, warn_ev=0.0, crit_ev=-0.5)
    assert d.severity(-5.0) == 0  # warmup: fresh value heads start here
    assert d.severity(0.4) == 0
    assert d.severity(-0.2) == 1
    assert d.severity(-0.9) == 2


def test_truncation_and_degenerate_rates():
    P, T = 2, 8
    mask = np.ones((4, T), dtype=np.int32)
    mask[0, 5:] = 0  # row 0: EOS inside the budget
    mask[1, 3:] = 0  # row 1: short response
    assert truncation_rate(mask, P) == pytest.approx(0.5)  # rows 2,3 fill it
    assert truncation_rate(np.ones((0, T), dtype=np.int32), P) == 0.0
    assert truncation_rate(mask, T) == 0.0  # no decode budget -> no signal

    loop = np.tile([7, 8, 9], 4)[: T - P]  # repeats its 3-gram
    fresh = np.arange(T - P) + 100
    tokens = np.zeros((3, T), dtype=np.int32)
    tokens[0, P:] = loop
    tokens[1, P:] = fresh
    tokens[2, P:] = fresh  # row 2 masked short: < 2n tokens counts clean
    m = np.ones((3, T), dtype=np.int32)
    m[2, P + 4 :] = 0
    assert degenerate_rate(tokens, m, P, n=3) == pytest.approx(1 / 3)


def test_rollout_sentinel_degeneracy_drives_crit():
    d = RolloutSentinel(warn_trunc=0.95, warn_degen=0.3, crit_degen=0.7)
    assert d.severity({"trunc": 0.5, "degen": 0.1}) == 0
    assert d.severity({"trunc": 1.0, "degen": 0.0}) == 1  # truncation wall
    assert d.severity({"trunc": 0.0, "degen": 0.4}) == 1
    assert d.severity({"trunc": 0.0, "degen": 0.9}) == 2


def test_mixed_version_detector_fraction_bands():
    """Token-granularity staleness watch (in-flight weight updates): the
    fraction of a batch's tokens NOT at its freshest version drives the
    severity — some mix is normal, a mostly-old batch is the problem."""
    d = MixedVersionDetector(warn_frac=0.5, crit_frac=0.9, warn_streak=1, crit_streak=2)
    assert d.severity({"mixed_tokens": 0.0, "total_tokens": 128.0}) == 0
    assert d.severity({"mixed_tokens": 40.0, "total_tokens": 128.0}) == 0
    assert d.severity({"mixed_tokens": 64.0, "total_tokens": 128.0}) == 1
    assert d.severity({"mixed_tokens": 120.0, "total_tokens": 128.0}) == 2
    assert d.frac == pytest.approx(120.0 / 128.0)
    # An empty window (no tokens consumed) is OK, not a zero-division.
    assert d.severity({"mixed_tokens": 0.0, "total_tokens": 0.0}) == 0
    # Through the hysteresis machine: a single mostly-old batch only WARNs
    # (crit needs a streak), sustained mix escalates.
    assert d.observe({"mixed_tokens": 127.0, "total_tokens": 128.0}) == WARN
    assert d.observe({"mixed_tokens": 127.0, "total_tokens": 128.0}) == CRIT


# ----------------------------------------------------- lineage + monitor


def test_lineage_record_roundtrip():
    r = LineageRecord(step=3, weight_version=2, staleness=1.0, rows=16,
                      truncation_rate=0.25, degenerate_rate=0.0,
                      mean_score=-1.5, time=123.0)
    assert LineageRecord.from_json(r.to_json()) == r
    # extra keys from a newer writer are ignored, not fatal
    line = json.dumps({**json.loads(r.to_json()), "future_field": 1})
    assert LineageRecord.from_json(line) == r


def test_lineage_record_version_spans_roundtrip_and_back_compat():
    """Span-form lineage (in-flight weight updates) round-trips; PRE-span
    lineage lines (no version_spans key) still load, defaulting to None —
    old lineage.jsonl files stay readable."""
    r = LineageRecord(step=9, weight_version=4, staleness=0.5, rows=8,
                      truncation_rate=0.0, degenerate_rate=0.0,
                      mean_score=2.0, time=9.0,
                      version_spans=[[3, 40], [4, 24]])
    got = LineageRecord.from_json(r.to_json())
    assert got == r and got.version_spans == [[3, 40], [4, 24]]
    old = {k: v for k, v in json.loads(r.to_json()).items() if k != "version_spans"}
    loaded = LineageRecord.from_json(json.dumps(old))
    assert loaded.version_spans is None
    assert loaded.weight_version == 4


def test_monitor_observe_chunk_writes_lineage_and_sentinels(tmp_path):
    path = str(tmp_path / "lineage.jsonl")
    m = HealthMonitor(warmup=1, lineage_path=path)
    tokens = np.zeros((4, 6), dtype=np.int32)
    mask = np.ones((4, 6), dtype=np.int32)
    for step in range(2):
        m.observe_chunk(tokens, mask, 2, scores=[1.0, 2.0, 3.0, 2.0],
                        weight_version=step, staleness=1, step=step)
    with open(path) as f:
        records = [LineageRecord.from_json(line) for line in f]
    assert [r.weight_version for r in records] == [0, 1]
    assert records[0].mean_score == pytest.approx(2.0)
    assert records[0].rows == 4 and records[0].staleness == 1.0
    g = m.gauges()
    assert g["health/truncation_rate"] == 1.0  # all-ones mask: budget filled
    assert g["health/reward_drift_state"] == 0.0


def test_monitor_crit_escalates_through_emergency_hook():
    captured = []

    class FakeCapture:
        def capture(self, step, reason, detail=None):
            captured.append((step, reason, detail))

    obs_anomaly.register_emergency(FakeCapture(), step_provider=lambda: 7)
    m = HealthMonitor(warmup=1, warn_streak=1, crit_streak=2)
    m.observe_reward(1.0)  # baseline
    m.observe_reward(1000.0)  # WARN
    assert m.status() == "degraded"
    m.observe_reward(1000.0)  # CRIT -> incident
    assert m.status() == "critical"
    assert len(captured) == 1
    step, reason, detail = captured[0]
    assert step == 7 and reason == "health_reward_drift"
    assert detail["detector"] == "reward_drift" and detail["severity"] == 2
    hz = m.healthz()
    assert hz["status"] == "critical"
    assert hz["detectors"]["reward_drift"]["state"] == CRIT
    assert hz["detectors"]["reward_drift"]["state_changes"] == 2


def test_monitor_drill_latches_shift_observed_stats_only(monkeypatch):
    monkeypatch.setenv("TRLX_TPU_REWARD_DRIFT_DELTA", "50")
    monkeypatch.setenv("TRLX_TPU_ENTROPY_COLLAPSE_SCALE", "0.5")
    m = HealthMonitor(warmup=1)
    m.inject_reward_drift()
    m.inject_entropy_collapse()
    assert m.reward_offset == 50.0 and m.entropy_scale == 0.5
    m.observe_reward(1.0)
    assert m.reward._baseline == [51.0]  # offset applied at the observation
    m.observe_train({"mean_entropy": 2.0}, step=0)
    assert m.entropy._baseline == [1.0]


def test_monitor_drift_offset_keyed_by_reward_call():
    """The drill fires on the score-worker thread while EARLIER calls'
    observations are still in flight — keying by call index keeps those
    baseline observations clean no matter the thread interleaving."""
    m = HealthMonitor(warmup=1)
    m.inject_reward_drift(from_call=2)
    assert m._reward_offset_for(1) == 0.0  # pre-drill call: clean baseline
    assert m._reward_offset_for(2) == m.reward_offset
    assert m._reward_offset_for(3) == m.reward_offset
    assert m._reward_offset_for(None) == m.reward_offset  # unknown: drifted
    tokens = np.zeros((2, 4), dtype=np.int32)
    mask = np.ones((2, 4), dtype=np.int32)
    m.observe_chunk(tokens, mask, 1, scores=[1.0, 1.0], weight_version=0,
                    staleness=0, step=0, reward_call=1)
    m.observe_chunk(tokens, mask, 1, scores=[1.0, 1.0], weight_version=0,
                    staleness=0, step=0, reward_call=2)
    assert [r.mean_score for r in m.lineage] == [1.0, 1001.0]


def test_monitor_gauges_and_state_change_counter_are_monotonic():
    m = HealthMonitor(warmup=1, warn_streak=1, crit_streak=2)
    totals = []
    for x in (1.0, 999.0, 999.0, 999.0):
        m.observe_reward(x)
        totals.append(m.gauges()["health/state_changes_total"])
    assert totals == sorted(totals) and totals[-1] == 2.0
    g = m.gauges()
    assert g["health/reward_drift_state"] == 2.0
    for key in g:
        assert _VALID.match(sanitize_metric_name("trlx_tpu_" + key)), key


# ---------------------------------------------------------------- exporter


def test_sanitize_metric_name_makes_every_key_legal():
    cases = {
        "health/reward_drift_state": "health_reward_drift_state",
        "time/overlap-fraction": "time_overlap_fraction",
        "obs/train_mfu_pct": "obs_train_mfu_pct",
        "9starts_with_digit": "_9starts_with_digit",
        "weird key.v2": "weird_key_v2",
        "": "_",
    }
    for key, expected in cases.items():
        got = sanitize_metric_name(key)
        assert got == expected and _VALID.match(got), key


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.headers.get("Content-Type"), r.read().decode()


def test_exporter_serves_prometheus_text_and_healthz():
    ex = MetricsExporter(port=0)  # ephemeral port: parallel-safe tests
    try:
        ex.update(
            {"health/reward_drift_state": 2.0,
             "health/state_changes_total": 3.0,
             "time/overlap_fraction": float("nan"),
             "loss": float("inf"),
             "note": "dropped — not numeric"},
            step=7,
            health={"status": "critical", "detectors": {}},
        )
        ctype, body = _get(ex.port, "/metrics")
        assert ctype.startswith("text/plain") and "version=0.0.4" in ctype
        assert "# TYPE trlx_tpu_health_reward_drift_state gauge" in body
        assert "trlx_tpu_health_reward_drift_state 2.0" in body
        assert "# TYPE trlx_tpu_health_state_changes_total counter" in body
        assert "trlx_tpu_time_overlap_fraction NaN" in body
        assert "trlx_tpu_loss +Inf" in body
        assert "trlx_tpu_last_step 7" in body
        assert "note" not in body
        # text-format conformance: every sample line's name is legal and has
        # exactly one HELP + one TYPE line above it
        samples = [ln for ln in body.splitlines() if ln and not ln.startswith("#")]
        for line in samples:
            assert _VALID.match(line.split()[0]), line
        names = [ln.split()[0] for ln in samples]
        assert len(names) == len(set(names))  # no duplicate metric names

        ctype, body = _get(ex.port, "/healthz")
        payload = json.loads(body)
        assert ctype == "application/json"
        assert payload["status"] == "critical" and payload["step"] == 7

        with pytest.raises(urllib.error.HTTPError) as err:
            _get(ex.port, "/nope")
        assert err.value.code == 404
    finally:
        ex.close()


def test_exporter_update_merges_and_collisions_keep_last_writer():
    ex = MetricsExporter(port=0)
    try:
        ex.update({"a/b": 1.0})
        ex.update({"c": 2.0})  # different cadence: both survive the merge
        _, body = _get(ex.port, "/metrics")
        assert "trlx_tpu_a_b 1.0" in body and "trlx_tpu_c 2.0" in body
        ex.update({"a_b": 9.0})  # sanitizes to the same name as a/b
        _, body = _get(ex.port, "/metrics")
        samples = [ln for ln in body.splitlines()
                   if ln.startswith("trlx_tpu_a_b ")]
        assert samples == ["trlx_tpu_a_b 9.0"]  # never a duplicate exposition
    finally:
        ex.close()


def test_exporter_histogram_exposition_cumulative_with_labels():
    """graftscope's distribution feeds (lane gaps, refill waits, straggler
    steps) render as conformant Prometheus histograms: cumulative buckets,
    an explicit +Inf bucket, _sum/_count, labels splitting series under one
    metric name, NaN samples dropped."""
    ex = MetricsExporter(port=0)
    try:
        ex.observe("obs/lane_gap_s", [0.004, 0.004, 0.8, float("nan")],
                   buckets=(0.005, 0.1, 1.0), labels={"lane": "score"})
        ex.observe("obs/lane_gap_s", [2.5], buckets=(0.005, 0.1, 1.0),
                   labels={"lane": "producer"})
        ex.observe("engine/refill_wait_ms", [3.0, 40.0], buckets=(5.0, 50.0))
        ex.observe("engine/refill_wait_ms", [4.0], buckets=(5.0, 50.0))  # folds
        _, body = _get(ex.port, "/metrics")
        assert "# TYPE trlx_tpu_obs_lane_gap_s histogram" in body
        assert 'trlx_tpu_obs_lane_gap_s_bucket{lane="score",le="0.005"} 2' in body
        assert 'trlx_tpu_obs_lane_gap_s_bucket{lane="score",le="1.0"} 3' in body
        assert 'trlx_tpu_obs_lane_gap_s_bucket{lane="score",le="+Inf"} 3' in body
        assert 'trlx_tpu_obs_lane_gap_s_count{lane="score"} 3' in body  # NaN gone
        assert 'trlx_tpu_obs_lane_gap_s_bucket{lane="producer",le="1.0"} 0' in body
        assert 'trlx_tpu_obs_lane_gap_s_bucket{lane="producer",le="+Inf"} 1' in body
        assert 'trlx_tpu_engine_refill_wait_ms_bucket{le="5.0"} 2' in body
        assert 'trlx_tpu_engine_refill_wait_ms_bucket{le="+Inf"} 3' in body
        assert "trlx_tpu_engine_refill_wait_ms_sum 47.0" in body
        assert "trlx_tpu_engine_refill_wait_ms_count 3" in body
        # every non-comment line still carries a legal metric name
        for line in body.splitlines():
            if line and not line.startswith("#"):
                assert _VALID.match(line.split("{")[0].split()[0]), line
    finally:
        ex.close()


# ------------------------------------------------------------ e2e acceptance


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_e2e_reward_drift_drill_trips_crit_incident_and_live_endpoint(
    tmp_path, monkeypatch
):
    """The PR's acceptance run: overlapped PPO (max_staleness=1) with the
    health monitor + live exporter armed and the reward_drift drill latched
    from reward call 2 on. chunk_size=8 gives two reward calls per store, so
    the walk is obs1 clean baseline (warmup=1) -> obs2 WARN (warn_streak=1)
    -> obs3 CRIT (crit_streak=2), early enough that the endpoint serves the
    degraded state for most of the run."""
    monkeypatch.setenv("TRLX_TPU_FAULTS", "reward_drift@2")
    port = _free_port()

    _, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=15, max_length=8, n_walks=60, seed=1000
    )
    config = base_config("ppo", 15, 8)
    config.train.total_steps = 8
    config.train.epochs = 4
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.checkpoint_dir = str(tmp_path)
    config.train.health_monitor = True
    config.train.health_warmup = 1
    config.train.health_warn_streak = 1
    config.train.health_crit_streak = 2
    config.train.metrics_port = port
    config.method.num_rollouts = 16
    config.method.chunk_size = 8
    config.method.max_staleness = 1
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    # live scrape: poll from a background thread WHILE train() blocks — the
    # exporter closes in learn()'s finally, so after-the-fact scrapes would
    # prove nothing about the endpoint being up during training
    scraped = {"metrics": "", "statuses": set(), "n": 0}
    stop = threading.Event()

    def poll():
        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=1
                ) as r:
                    scraped["metrics"] = r.read().decode()
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=1
                ) as r:
                    scraped["statuses"].add(json.loads(r.read().decode())["status"])
                scraped["n"] += 1
            except OSError:
                pass
            stop.wait(0.05)

    poller = threading.Thread(target=poll, daemon=True)
    poller.start()
    try:
        model = trlx_tpu.train(
            reward_fn=reward_fn,
            prompts=prompts,
            eval_prompts=[[1]],
            metric_fn=metric_fn,
            config=config,
            logit_mask=logit_mask,
        )
    finally:
        stop.set()
        poller.join(timeout=5)
    assert model.iter_count >= 8
    assert not any(t.name.startswith("trlx-") for t in threading.enumerate())

    # --- detector walked to CRIT; gauges in metrics.jsonl -----------------
    with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    states = [r["health/reward_drift_state"] for r in records
              if "health/reward_drift_state" in r]
    assert states and max(states) == 2.0, states
    changes = [r["health/state_changes_total"] for r in records
               if "health/state_changes_total" in r]
    assert changes == sorted(changes) and changes[-1] >= 2.0
    hists = [r for r in records if r.get("histogram") == "health/lineage_staleness"]
    assert hists and hists[-1]["count"] > 0

    # --- CRIT escalated into an incident bundle ---------------------------
    incidents_dir = os.path.join(str(tmp_path), "incidents")
    reasons = {}
    for b in os.listdir(incidents_dir):
        with open(os.path.join(incidents_dir, b, "incident.json")) as f:
            reasons[json.load(f)["reason"]] = b
    assert "health_reward_drift" in reasons, reasons
    with open(
        os.path.join(incidents_dir, reasons["health_reward_drift"], "incident.json")
    ) as f:
        manifest = json.load(f)
    assert manifest["detail"]["detector"] == "reward_drift"

    # --- live endpoint served the degraded state DURING the run -----------
    assert scraped["n"] > 0, "never scraped the live endpoint"
    assert "# TYPE trlx_tpu_health_reward_drift_state gauge" in scraped["metrics"]
    assert "# TYPE trlx_tpu_health_state_changes_total counter" in scraped["metrics"]
    assert scraped["statuses"] & {"degraded", "critical"}, scraped["statuses"]

    # --- lineage audit trail ----------------------------------------------
    with open(os.path.join(str(tmp_path), "lineage.jsonl")) as f:
        lineage = [LineageRecord.from_json(line) for line in f]
    assert lineage and all(r.rows == 8 for r in lineage)
    assert {r.staleness for r in lineage} <= {0.0, 1.0}

    # --- report renders the health section --------------------------------
    md = report.build_report(str(tmp_path))
    assert "## Training health" in md
    assert "reward_drift" in md and "CRIT" in md
    assert "health_reward_drift" in md  # incident cross-link


def test_health_off_means_no_monitor_no_endpoint_no_lineage(tmp_path):
    """Default config: no health gauges, no lineage file, no exporter thread
    — the serial path must be byte-identical with the knobs off."""
    _, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=15, max_length=8, n_walks=60, seed=1000
    )
    config = base_config("ppo", 15, 8)
    config.train.total_steps = 2
    config.train.epochs = 1
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.checkpoint_dir = str(tmp_path)
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[1]],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model._health is None and model._metrics_exporter is None
    assert not os.path.exists(os.path.join(str(tmp_path), "lineage.jsonl"))
    assert not any(
        t.name == "trlx-metrics-exporter" for t in threading.enumerate()
    )
    with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
        assert not any("health/" in line for line in f)
