"""AOT-compile the REAL 6B recipe — beyond test_scale_fit's byte math.

test_scale_fit audits sharded sizes with jax.eval_shape (no allocation); this
module goes the rest of the way: it lowers AND compiles the production PPO
train step (`make_ppo_train_step` — the exact function PPOTrainer jits) and
the decode program at the `ppo_gptj_config.yml` shapes (GPT-J-6B: 28 layers,
d 4096, vocab 50400) over the recipe's fsdp×tp mesh, from ABSTRACT arrays —
params are never allocated. Asserts:

- compilation succeeds (no spec mismatch first seen on real hardware),
- the SPMD partitioner emits NO "Involuntary full rematerialization"
  (= full-tensor replication traffic on a pod),
- per-device argument bytes from the compiled executable's memory analysis
  agree with test_scale_fit's partition-rule byte math.

Reference capability matched: configs/ppo_gptj.yml:9-12,29-30 is the recipe
being claimed; the reference can only discover sharding/memory surprises by
OOM-crashing on the real cluster.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from trlx_tpu.data import PPORLBatch
from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.models.heads import LMWithValueHead, trainable_mask
from trlx_tpu.models.lm import LMConfig
from trlx_tpu.parallel.mesh import DATA_AXES, MESH_AXES, make_mesh
from trlx_tpu.parallel.sharding import (
    lm_partition_rules,
    match_partition_rules,
    sanitize_specs,
    specs_to_shardings,
)
from trlx_tpu.trainer.base import TrainState, build_optimizer
from trlx_tpu.trainer.ppo import make_ppo_train_step

pytestmark = pytest.mark.slow

YAML_PATH = "trlx_tpu/configs/ppo_gptj_config.yml"

# GPT-J-6B architecture (reference: configs/ppo_gptj.yml model_path
# EleutherAI/gpt-j-6B; dims are the public checkpoint's).
GPTJ_6B_ARCH = dict(
    vocab_size=50400,
    n_layer=28,
    n_head=16,
    d_model=4096,
    max_position=2048,
    pos_type="rotary",
    rotary_dim=64,
    parallel_residual=True,
    fused_qkv=False,
    qkv_bias=False,
    out_bias=False,
    tie_word_embeddings=False,
    extra={"lm_head_bias": True},
)

INVOLUNTARY = "Involuntary full rematerialization"


def _recipe():
    config = TRLConfig.load_yaml(YAML_PATH)
    cfg = LMConfig(
        **GPTJ_6B_ARCH,
        dtype=config.model.dtype,
        param_dtype=config.model.param_dtype,
        remat=config.model.remat,
    )
    return config, cfg


def _abstract_state_and_shardings(model, config, cfg, mesh):
    """Abstract TrainState + shardings exactly as the trainer would build
    them (partition rules + sanitize + eval_shape'd optax init)."""
    ids = jax.ShapeDtypeStruct((1, 8), np.int32)
    abstract_params = jax.eval_shape(model.init, jax.random.PRNGKey(0), ids, ids)["params"]
    opt_mask = trainable_mask(abstract_params, cfg, config.model.num_layers_unfrozen)
    optimizer, schedule = build_optimizer(config.train, opt_mask)

    def detach_frozen(params):
        return jax.tree_util.tree_map(
            lambda p, t: p if t else jax.lax.stop_gradient(p), params, opt_mask
        )

    abstract_opt = jax.eval_shape(optimizer.init, abstract_params)
    abstract_state = TrainState(
        step=jax.ShapeDtypeStruct((), np.int32),
        params=abstract_params,
        opt_state=abstract_opt,
        extras=None,
    )
    specs = sanitize_specs(
        mesh, abstract_state, match_partition_rules(lm_partition_rules(), abstract_state)
    )
    shardings = specs_to_shardings(mesh, specs)
    return abstract_state, shardings, optimizer, schedule, detach_frozen, opt_mask


def _with_shardings(abstract, shardings):
    return jax.tree_util.tree_map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s), abstract, shardings
    )


def _batch_abstract(mesh, config, P_len, R_len):
    B = config.train.batch_size
    data = NamedSharding(mesh, P(DATA_AXES))
    data2 = NamedSharding(mesh, P(DATA_AXES, None))

    def tok(n):
        return jax.ShapeDtypeStruct((B, n), np.int32, sharding=data2)

    def f32(n):
        return jax.ShapeDtypeStruct((B, n), np.float32, sharding=data2)

    return PPORLBatch(
        query_tensors=tok(P_len),
        response_tensors=tok(R_len),
        logprobs=f32(R_len),
        values=f32(R_len),
        rewards=f32(R_len),
        response_mask=tok(R_len),
        query_mask=tok(P_len),
    )


def _assert_no_involuntary_remat(capfd):
    err = capfd.readouterr().err
    hits = [line for line in err.splitlines() if INVOLUNTARY in line]
    assert not hits, "SPMD partitioner fell back to full replication:\n" + "\n".join(hits[:4])


def test_gptj6b_train_step_aot_compiles_on_recipe_mesh(capfd):
    config, cfg = _recipe()
    mesh_spec = list(config.train.mesh)
    assert mesh_spec[1:3] == [4, 2], "recipe changed: expected fsdp=4, tp=2"
    mesh = make_mesh([1, 4, 2, 1])  # the recipe's fsdp×tp over 8 virtual chips

    model = LMWithValueHead(cfg, branch_layer=cfg.n_layer - config.model.num_layers_unfrozen)
    abstract_state, shardings, optimizer, schedule, detach_frozen, opt_mask = (
        _abstract_state_and_shardings(model, config, cfg, mesh)
    )

    gen_kwargs = config.method.gen_kwargs
    P_len = int(gen_kwargs["prompt_length"])
    R_len = config.train.seq_length - P_len
    train_step = make_ppo_train_step(
        model, optimizer, config, P_len, schedule, detach_frozen
    )

    with mesh:
        compiled = train_step.lower(
            _with_shardings(abstract_state, shardings),
            _batch_abstract(mesh, config, P_len, R_len),
        ).compile()
    _assert_no_involuntary_remat(capfd)

    # Per-device argument bytes must agree with test_scale_fit's byte math:
    # fp32 params ≈ 24.2GB global over fsdp*tp=8 → ≈3GB/device, plus masked
    # Adam moments (only top-2 blocks + embeddings/heads train) and the
    # int32/float batch. memory_analysis is per-device.
    ma = compiled.memory_analysis()
    arg_gb = ma.argument_size_in_bytes / 1e9
    # independent byte math from the abstract shapes + shardings
    expect = 0
    for leaf, sh in zip(
        jax.tree_util.tree_leaves(abstract_state), jax.tree_util.tree_leaves(shardings)
    ):
        shard = np.prod([
            dict(zip(MESH_AXES, [1, 4, 2, 1]))[n]
            for d in sh.spec
            for n in (d if isinstance(d, tuple) else (d,))
            if n is not None
        ]) if any(d is not None for d in sh.spec) else 1
        expect += int(np.prod(leaf.shape)) * leaf.dtype.itemsize // int(shard)
    expect_gb = expect / 1e9
    assert abs(arg_gb - expect_gb) / expect_gb < 0.15, (
        f"compiled per-device args {arg_gb:.2f}GB vs partition-rule math "
        f"{expect_gb:.2f}GB — sharding spec mismatch"
    )
    # and the whole per-device state must fit a v4 chip's 32GB (recipe claim)
    total_gb = (ma.argument_size_in_bytes + ma.temp_size_in_bytes + ma.output_size_in_bytes) / 1e9
    assert total_gb < 32, f"{total_gb:.1f}GB/chip exceeds v4 HBM"


def test_gptj6b_decode_prefill_aot_compiles_on_recipe_mesh(capfd):
    """The rollout prefill+decode program at the recipe shapes. Decode is the
    other program a 6B PPO run lives in; a sharding pathology here would be
    per-token collective traffic."""
    from functools import partial

    from trlx_tpu.ops.generate import generate
    from trlx_tpu.ops.sampling import GenerateConfig

    config, cfg = _recipe()
    mesh = make_mesh([1, 4, 2, 1])
    model = LMWithValueHead(cfg, branch_layer=cfg.n_layer - config.model.num_layers_unfrozen)

    gen_kwargs = dict(config.method.gen_kwargs)
    P_len = int(gen_kwargs.pop("prompt_length"))
    gcfg = GenerateConfig.from_gen_kwargs(
        gen_kwargs, prompt_len=P_len, pad_token_id=50256, eos_token_id=50256
    )

    ids = jax.ShapeDtypeStruct((1, 8), np.int32)
    abstract_params = jax.eval_shape(model.init, jax.random.PRNGKey(0), ids, ids)["params"]
    specs = sanitize_specs(
        mesh, abstract_params, match_partition_rules(lm_partition_rules(), abstract_params)
    )
    shardings = specs_to_shardings(mesh, specs)
    variables = {"params": _with_shardings(abstract_params, shardings)}

    B = config.method.chunk_size
    data2 = NamedSharding(mesh, P(DATA_AXES, None))
    prompt_ids = jax.ShapeDtypeStruct((B, P_len), np.int32, sharding=data2)
    prompt_mask = jax.ShapeDtypeStruct((B, P_len), np.int32, sharding=data2)
    rng = jax.ShapeDtypeStruct((2,), np.uint32)

    fn = jax.jit(partial(generate, model=model, gcfg=gcfg))
    from trlx_tpu.parallel import set_mesh

    set_mesh(mesh)
    try:
        with mesh:
            compiled = fn.lower(variables, prompt_ids, prompt_mask, rng).compile()
    finally:
        set_mesh(None)
    _assert_no_involuntary_remat(capfd)
    ma = compiled.memory_analysis()
    total_gb = (ma.argument_size_in_bytes + ma.temp_size_in_bytes + ma.output_size_in_bytes) / 1e9
    assert total_gb < 32, f"decode {total_gb:.1f}GB/chip exceeds v4 HBM"


def _small_head_heavy_recipe(fused_mode):
    """A head-dominated arch (d 256, 4 layers, GPT-J's 50400 vocab) at
    B=64: the [B, R+1, V] fp32 logits buffer (184MB/device on the recipe
    mesh) dwarfs everything else in the step, so memory_analysis cleanly
    separates the materialized-logits path from the streaming kernel."""
    config = TRLConfig.load_yaml(YAML_PATH)
    config.train.batch_size = 64
    cfg = LMConfig(
        vocab_size=50400,
        n_layer=4,
        n_head=4,
        d_model=256,
        max_position=128,
        pos_type="rotary",
        rotary_dim=64,
        tie_word_embeddings=False,
        dtype="float32",
        param_dtype="float32",
        extra={"lm_head_bias": True, "fused_logprob": fused_mode},
    )
    return config, cfg


def _compile_train_step_memory(fused_mode):
    config, cfg = _small_head_heavy_recipe(fused_mode)
    mesh = make_mesh([1, 4, 2, 1])
    model = LMWithValueHead(cfg, branch_layer=2)
    abstract_state, shardings, optimizer, schedule, detach_frozen, _ = (
        _abstract_state_and_shardings(model, config, cfg, mesh)
    )
    P_len, R_len = 16, 56
    train_step = make_ppo_train_step(
        model, optimizer, config, P_len, schedule, detach_frozen
    )
    with mesh:
        compiled = train_step.lower(
            _with_shardings(abstract_state, shardings),
            _batch_abstract(mesh, config, P_len, R_len),
        ).compile()
    ma = compiled.memory_analysis()
    # per-device [B, R+1, V] fp32: batch dim sharded over dp*fsdp = 4
    logits_bytes = (config.train.batch_size // 4) * (R_len + 1) * cfg.vocab_size * 4
    return ma, logits_bytes


def test_fused_logprob_train_step_never_materializes_logits():
    """The PR's memory claim, asserted from the compiled executable: with
    the fused head (extra.fused_logprob="force") the jitted PPO train step's
    peak temp allocation stays BELOW one [B, R+1, V] fp32 logits buffer —
    i.e. no full-vocab activation is ever live, forward or backward. The
    dense path compiled from the same model/state holds at least one (which
    also proves the threshold is not vacuous)."""
    ma_fused, logits_bytes = _compile_train_step_memory("force")
    ma_dense, _ = _compile_train_step_memory("off")

    assert ma_dense.temp_size_in_bytes > logits_bytes, (
        f"dense path temp {ma_dense.temp_size_in_bytes/1e6:.0f}MB below one "
        f"logits buffer {logits_bytes/1e6:.0f}MB — threshold is vacuous"
    )
    assert ma_fused.temp_size_in_bytes < logits_bytes, (
        f"fused step holds {ma_fused.temp_size_in_bytes/1e6:.0f}MB temp — a "
        f"full [B,R+1,V] logits buffer ({logits_bytes/1e6:.0f}MB) is live"
    )
    assert ma_fused.temp_size_in_bytes < ma_dense.temp_size_in_bytes
