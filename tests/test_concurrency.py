"""graftrace static-half fixtures (GL008-GL011): every concurrency rule
fires on its violating fixture, stays suppressed with a reason, and passes
on the clean variant — including the PR 5 two-thread dispatch deadlock
re-expressed as a GL009 lock-order cycle and the trlx-* thread-naming
contract the teardown leak assertions depend on.

Same contract as test_analysis.py: stdlib ast only, no jax on the lint path.
"""

import os
import subprocess
import sys
import textwrap

from trlx_tpu.analysis import lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_source(tmp_path, source, relpath="fixture.py", select=None):
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    findings, _ = lint_paths([str(path)], select=select)
    return findings


def _active(findings, rule):
    return [f for f in findings if not f.suppressed and f.rule == rule]


# ------------------------------------------------------------------- GL008


GL008_VIOLATION = """
import threading

class Producer:
    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="trlx-producer", daemon=True
        )
        self._thread.start()

    def _run(self):
        self.count += 1

    def snapshot(self):
        return self.count

    def close(self):
        self._thread.join(timeout=5)
"""


def test_gl008_fires_on_unlocked_cross_thread_write(tmp_path):
    hits = _active(_lint_source(tmp_path, GL008_VIOLATION), "GL008")
    assert len(hits) == 1
    assert "self.count" in hits[0].message and "_run" in hits[0].message


def test_gl008_clean_under_common_lock(tmp_path):
    src = """
    import threading

    class Producer:
        def start(self):
            self._thread = threading.Thread(
                target=self._run, name="trlx-producer", daemon=True
            )
            self._thread.start()

        def _run(self):
            with self._lock:
                self.count += 1

        def snapshot(self):
            with self._lock:
                return self.count

        def close(self):
            self._thread.join(timeout=5)
    """
    assert _active(_lint_source(tmp_path, src), "GL008") == []


def test_gl008_allowlists_bounded_deque_handoff(tmp_path):
    # deque(maxlen=...) is the overlap pipeline's handoff structure — the
    # producer appends, the consumer pops, and the allowlist covers both
    # mutation directions without a lock.
    src = """
    import threading
    from collections import deque

    class Producer:
        def __init__(self):
            self._ready = deque(maxlen=4)

        def start(self):
            self._thread = threading.Thread(
                target=self._run, name="trlx-producer", daemon=True
            )
            self._thread.start()

        def _run(self):
            self._ready.append(1)

        def take(self):
            return self._ready.popleft()

        def close(self):
            self._thread.join(timeout=5)
    """
    assert _active(_lint_source(tmp_path, src), "GL008") == []


def test_gl008_resolves_helper_and_callback_one_level(tmp_path):
    # The write hides one call deep (the producer loop calls self._step());
    # the entry-point expansion must still attribute it to the worker thread.
    src = """
    import threading

    class Producer:
        def start(self):
            self._thread = threading.Thread(
                target=self._run, name="trlx-producer", daemon=True
            )
            self._thread.start()

        def _run(self):
            while True:
                self._step()

        def _step(self):
            self.count += 1

        def snapshot(self):
            return self.count

        def close(self):
            self._thread.join(timeout=5)
    """
    hits = _active(_lint_source(tmp_path, src), "GL008")
    assert len(hits) == 1 and "self.count" in hits[0].message


def test_gl008_suppressed_with_reason(tmp_path):
    src = GL008_VIOLATION.replace(
        "self.count += 1",
        "self.count += 1  # graftlint: disable=GL008 -- fixture: benign stat",
    )
    findings = _lint_source(tmp_path, src)
    assert _active(findings, "GL008") == []
    assert any(f.suppressed and f.rule == "GL008" for f in findings)


# ------------------------------------------------------------------- GL009


GL009_VIOLATION = """
class Trainer:
    def dispatch_then_stats(self):
        with self._dispatch_lock:
            with self._stats_lock:
                self.n += 1

    def stats_then_dispatch(self):
        with self._stats_lock:
            with self._dispatch_lock:
                self.m += 1
"""


def test_gl009_fires_on_lock_order_cycle(tmp_path):
    # The PR 5 incident shape: one thread holds the dispatch lock and wants
    # the tracker lock, the other holds the tracker lock and wants dispatch.
    hits = _active(_lint_source(tmp_path, GL009_VIOLATION), "GL009")
    assert len(hits) == 1
    assert "_dispatch_lock" in hits[0].message
    assert "Trainer._stats_lock" in hits[0].message


def test_gl009_clean_with_consistent_order(tmp_path):
    src = """
    class Trainer:
        def a(self):
            with self._dispatch_lock:
                with self._stats_lock:
                    self.n += 1

        def b(self):
            with self._dispatch_lock:
                with self._stats_lock:
                    self.m += 1
    """
    assert _active(_lint_source(tmp_path, src), "GL009") == []


def test_gl009_same_lock_name_in_unrelated_classes_does_not_merge(tmp_path):
    # Both classes have a `_lock` and a `_q_lock` acquired in opposite
    # nesting order — but each class's locks are distinct objects; the
    # class-scoped node names must keep the graphs separate.
    src = """
    class A:
        def f(self):
            with self._lock:
                with self._q_lock:
                    self.n = 1

    class B:
        def g(self):
            with self._q_lock:
                with self._lock:
                    self.m = 1
    """
    assert _active(_lint_source(tmp_path, src), "GL009") == []


def test_gl009_cycle_through_helper_call(tmp_path):
    # Edge discovered through one-level call resolution: f holds the stats
    # lock and calls a helper that takes the dispatch lock.
    src = """
    class Trainer:
        def f(self):
            with self._stats_lock:
                self._flush()

        def _flush(self):
            with self._dispatch_lock:
                self.n += 1

        def g(self):
            with self._dispatch_lock:
                with self._stats_lock:
                    self.m += 1
    """
    hits = _active(_lint_source(tmp_path, src), "GL009")
    assert len(hits) == 1


# ------------------------------------------------------------------- GL010


def test_gl010_fires_on_unjoined_undaemonized_thread(tmp_path):
    src = """
    import threading

    def kick(work):
        t = threading.Thread(target=work)
        t.start()
    """
    hits = _active(_lint_source(tmp_path, src), "GL010")
    assert len(hits) == 1 and "neither daemonized nor joined" in hits[0].message


def test_gl010_fires_on_unnamed_worker_stored_on_self(tmp_path):
    src = """
    import threading

    class Worker:
        def start(self):
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

        def _run(self):
            pass

        def close(self):
            self._thread.join(timeout=5)
    """
    hits = _active(_lint_source(tmp_path, src), "GL010")
    assert len(hits) == 1 and "trlx-" in hits[0].message


def test_gl010_clean_named_daemon_joined_worker(tmp_path):
    src = """
    import threading

    class Worker:
        def start(self):
            self._thread = threading.Thread(
                target=self._run, name="trlx-worker", daemon=True
            )
            self._thread.start()

        def _run(self):
            pass

        def close(self):
            self._thread.join(timeout=5)
    """
    assert _active(_lint_source(tmp_path, src), "GL010") == []


def test_gl010_timer_exempt_from_naming_contract(tmp_path):
    # threading.Timer accepts no name= and a cancelled Timer can linger
    # briefly — the deadline-timer idiom (collective_guard) is cancelled,
    # not joined-by-name, so the naming half must not fire on Timers.
    src = """
    import threading

    class Guard:
        def arm(self):
            self._timer = threading.Timer(5.0, self._fire)
            self._timer.start()

        def _fire(self):
            pass

        def disarm(self):
            self._timer.cancel()
    """
    assert _active(_lint_source(tmp_path, src), "GL010") == []


# ------------------------------------------------------------------- GL011


def test_gl011_fires_on_sleep_under_dispatch_lock(tmp_path):
    src = """
    import time

    class Trainer:
        def step(self):
            with self._dispatch_lock:
                time.sleep(0.5)
                out = self._train_fn(self.state)
            return out
    """
    hits = _active(_lint_source(tmp_path, src), "GL011")
    assert len(hits) == 1 and "time.sleep" in hits[0].message


def test_gl011_fires_on_untimed_queue_get_under_dispatch_lock(tmp_path):
    src = """
    class Trainer:
        def step(self):
            with self._dispatch_lock:
                item = self._pending.get()
            return item
    """
    hits = _active(_lint_source(tmp_path, src), "GL011")
    assert len(hits) == 1 and "no timeout" in hits[0].message


def test_gl011_fires_on_collective_under_dispatch_lock(tmp_path):
    src = """
    class Trainer:
        def sync(self):
            with self._dispatch_lock:
                collective_guard("sync", lambda: None)
    """
    hits = _active(_lint_source(tmp_path, src), "GL011")
    assert len(hits) == 1 and "collective_guard" in hits[0].message


def test_gl011_clean_timed_get_and_outside_sleep(tmp_path):
    src = """
    import time

    class Trainer:
        def step(self):
            time.sleep(0.5)
            with self._dispatch_lock:
                item = self._pending.get(timeout=1.0)
                out = self._train_fn(self.state)
            return out
    """
    assert _active(_lint_source(tmp_path, src), "GL011") == []


def test_gl011_other_locks_unrestricted(tmp_path):
    src = """
    import time

    class Tracker:
        def flush(self):
            with self._stats_lock:
                time.sleep(0.01)
    """
    assert _active(_lint_source(tmp_path, src), "GL011") == []


# ----------------------------------------------------------------- CLI/meta


def test_list_rules_groups_families_and_states_reason_contract():
    out = subprocess.run(
        [sys.executable, "-m", "trlx_tpu.analysis", "--list-rules"],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert out.returncode == 0
    assert "invariant (graftlint, PR 11):" in out.stdout
    assert "concurrency (graftrace, PR 13):" in out.stdout
    for rule in ("GL008", "GL009", "GL010", "GL011"):
        assert rule in out.stdout
    assert "REQUIRED" in out.stdout


def test_scripts_lint_clean_with_script_rule_subset():
    # The Makefile's second lint pass: the top-level scripts under the
    # rule families that apply outside the package.
    scripts = [
        os.path.join(REPO, name)
        for name in (
            "bench.py",
            "bench_smoke.py",
            "bench_decode_probe.py",
            "bench_reference.py",
            "bench_trajectory.py",
            "obs_smoke.py",
            "acceptance_network.py",
        )
        if os.path.exists(os.path.join(REPO, name))
    ]
    assert scripts, "expected top-level scripts in the repo root"
    findings, _ = lint_paths(
        scripts,
        select=["GL003", "GL004", "GL007", "GL008", "GL009", "GL010", "GL011"],
    )
    assert [f for f in findings if not f.suppressed] == []
