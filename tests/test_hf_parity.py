"""HF numerical parity: convert locally-instantiated (random) torch models
and compare logits — validates the weight conversion + architecture fidelity
that reward parity depends on (SURVEY.md §7 "hard parts" #1), with zero
downloads."""

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from trlx_tpu.models import TransformerLM
from trlx_tpu.models.hf_import import (
    convert_gpt2,
    convert_gptj,
    convert_neox,
    lm_config_from_hf,
)


def compare(hf_model, converter, atol=2e-4, seq_len=12):
    hf_model.eval()
    cfg = lm_config_from_hf(hf_model.config, dtype="float32", param_dtype="float32")
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    trunk = converter(sd, cfg)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(2, seq_len))
    with torch.no_grad():
        ref = hf_model(torch.as_tensor(ids)).logits.numpy()

    model = TransformerLM(cfg)
    out = model.apply({"params": trunk}, jnp.asarray(ids), jnp.ones(ids.shape, jnp.int32))
    got = np.asarray(out["logits"], dtype=np.float32)
    np.testing.assert_allclose(got, ref, atol=atol, rtol=1e-3)


def test_gpt2_parity():
    config = transformers.GPT2Config(n_layer=2, n_head=4, n_embd=64, vocab_size=128, n_positions=64)
    compare(transformers.GPT2LMHeadModel(config), convert_gpt2)


def test_gptj_parity():
    config = transformers.GPTJConfig(
        n_layer=2, n_head=4, n_embd=64, vocab_size=128, n_positions=64, rotary_dim=8
    )
    compare(transformers.GPTJForCausalLM(config), convert_gptj)


def test_neox_parity():
    config = transformers.GPTNeoXConfig(
        num_hidden_layers=2,
        num_attention_heads=4,
        hidden_size=64,
        intermediate_size=256,
        vocab_size=128,
        max_position_embeddings=64,
        rotary_pct=0.25,
    )
    compare(transformers.GPTNeoXForCausalLM(config), convert_neox)


def test_gpt_neo_parity():
    """Alternating global/local layers with a window SHORTER than the
    sequence, so the windowed mask actually changes the logits; unscaled
    attention is gpt-neo's other quirk."""
    from trlx_tpu.models.hf_import import convert_gpt_neo

    config = transformers.GPTNeoConfig(
        num_layers=2,
        num_heads=4,
        hidden_size=64,
        vocab_size=128,
        max_position_embeddings=64,
        attention_types=[[["global", "local"], 1]],
        window_size=8,
    )
    compare(transformers.GPTNeoForCausalLM(config), convert_gpt_neo, seq_len=24)
