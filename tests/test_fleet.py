"""graftfleet (trlx_tpu/observability/fleet.py): cross-host trace federation,
collective straggler attribution, and fleet health rollup — unit tier.

Covers the pure readers (read_fleet_spans merge semantics, the per-collective
skew table), the FleetStragglerDetector hysteresis (persistent straggler vs
one-off hiccup), the single-process FleetMonitor degradation (a one-host
fleet: trivial clock, arrival recording, gauges, healthz block, incident
bundles), the collective_guard arrival hook, and the MetricsExporter
port-collision fallback. The 2-process CPU drills that exercise the REAL
cross-host join live in tests/test_fleet_drill.py (slow tier).
"""

import json
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402
from trlx_tpu.observability import fleet as obs_fleet
from trlx_tpu.observability import spans as obs_spans
from trlx_tpu.observability.export import MetricsExporter, sanitize_metric_name
from trlx_tpu.resilience.distributed import collective_guard
from trlx_tpu.utils import jsonl


@pytest.fixture(autouse=True)
def _fleet_isolation():
    """The fleet monitor is a process global armed by trainers/tests — always
    disarm so one test's files (in a deleted tmp_path) never leak forward."""
    yield
    obs_fleet.shutdown()
    obs_spans.shutdown()


def _write_host_spans(d, host, events):
    path = os.path.join(d, obs_spans.host_spans_filename(host))
    for e in events:
        jsonl.append_record(path, e)
    return path


def _clock_record(d, offsets_s, uncertainty_s=0.001, drift_s=0.0005, step=0):
    jsonl.append_record(
        os.path.join(d, obs_spans.FLEET_CLOCK_FILENAME),
        {
            "offsets_s": offsets_s,
            "uncertainty_s": uncertainty_s,
            "drift_s": drift_s,
            "step": step,
        },
    )


# ----------------------------------------------------------- span federation


def test_read_fleet_spans_merges_host_lanes_with_clock_alignment(tmp_path):
    d = str(tmp_path)
    # Overlapping synthetic tids on purpose: host 0 and host 1 both use
    # tid 1/2 — the merge must keep the lanes distinct.
    _write_host_spans(
        d,
        0,
        [
            {"name": "train/step", "ph": "X", "ts": 1_000_000, "dur": 10, "pid": 9, "tid": 1},
            {"name": "producer", "ph": "X", "ts": 1_000_050, "dur": 5, "pid": 9, "tid": 2},
        ],
    )
    _write_host_spans(
        d,
        1,
        [
            {"name": "train/step", "ph": "X", "ts": 2_000_000, "dur": 10, "pid": 9, "tid": 1},
        ],
    )
    # Host 1's wall clock runs 1s ahead of host 0's.
    _clock_record(d, [0.0, 1.0], uncertainty_s=0.002, drift_s=0.001)

    merged = obs_spans.read_fleet_spans(d)
    assert merged["hosts"] == [0, 1]
    # Stated alignment bound = estimate uncertainty + drift bound.
    assert merged["alignment_error_s"] == pytest.approx(0.003)

    spans = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    by_host = {h: [e for e in spans if e["pid"] == h] for h in (0, 1)}
    # pids forced to the host index; tids remapped host*TID_STRIDE + tid.
    assert {e["tid"] for e in by_host[0]} == {1, 2}
    assert {e["tid"] for e in by_host[1]} == {obs_spans.TID_STRIDE + 1}
    # Host 1's timestamps shifted into host 0's frame by −offset (1s → µs).
    assert by_host[1][0]["ts"] == 2_000_000 - 1_000_000
    assert by_host[0][0]["ts"] == 1_000_000  # host 0 is the reference frame

    # One process_name metadata lane per host, stating offset ± bound.
    lanes = {
        e["pid"]: e["args"]["name"]
        for e in merged["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert "host0" in lanes[0]
    assert "+1000.000ms" in lanes[1] and "3.000ms" in lanes[1]


def test_read_fleet_spans_tolerates_torn_tail_per_file(tmp_path):
    d = str(tmp_path)
    _write_host_spans(d, 0, [{"name": "a", "ph": "X", "ts": 1, "dur": 1, "tid": 1}])
    path1 = _write_host_spans(
        d, 1, [{"name": "b", "ph": "X", "ts": 2, "dur": 1, "tid": 1}]
    )
    with open(path1, "a") as f:
        f.write('{"name": "torn')  # killed writer: partial final line
    merged = obs_spans.read_fleet_spans(d)
    names = {e["name"] for e in merged["traceEvents"] if e.get("ph") == "X"}
    assert names == {"a", "b"}  # torn tail dropped, both hosts still merge
    assert merged["hosts"] == [0, 1]


def test_read_fleet_spans_falls_back_to_plain_spans_jsonl(tmp_path):
    d = str(tmp_path)
    jsonl.append_record(
        os.path.join(d, obs_spans.SPANS_FILENAME),
        {"name": "solo", "ph": "X", "ts": 5, "dur": 1, "pid": 0, "tid": 7},
    )
    merged = obs_spans.read_fleet_spans(d)
    [event] = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    # Legacy single file: events pass through untouched (no remap, no shift).
    assert event["tid"] == 7 and event["ts"] == 5
    assert merged["clock"] is None and merged["alignment_error_s"] == 0.0
    # And an empty dir yields an empty merge, not a crash.
    assert obs_spans.read_fleet_spans(str(tmp_path / "nope"))["traceEvents"] == []


# ----------------------------------------------------- straggler attribution


def _write_arrivals(d, host, records):
    path = os.path.join(d, obs_fleet.host_collectives_filename(host))
    for r in records:
        jsonl.append_record(path, r)


def test_collective_skew_table_names_the_laggard(tmp_path):
    d = str(tmp_path)
    base = 1000.0
    rec0, rec1 = [], []
    for seq in range(10):
        t = base + seq
        rec0.append({"site": "allgather_host", "seq": seq, "host": 0, "t0": t, "t1": t + 0.01})
        # Host 1 arrives 50ms late at this site, every occurrence.
        rec1.append({"site": "allgather_host", "seq": seq, "host": 1, "t0": t + 0.05, "t1": t + 0.06})
        # A balanced site: both hosts arrive within the noise floor.
        rec0.append({"site": "barrier", "seq": seq, "host": 0, "t0": t, "t1": t + 0.001})
        rec1.append({"site": "barrier", "seq": seq, "host": 1, "t0": t + 0.002, "t1": t + 0.003})
    _write_arrivals(d, 0, rec0)
    _write_arrivals(d, 1, rec1)

    rows = {r["site"]: r for r in obs_fleet.collective_skew_table(d, offsets=[0.0, 0.0])}
    lag = rows["allgather_host"]
    assert lag["count"] == 10
    assert lag["worst_host"] == 1 and lag["worst_share"] == pytest.approx(1.0)
    assert lag["p50_ms"] == pytest.approx(50.0, abs=1.0)
    assert lag["max_ms"] == pytest.approx(50.0, abs=1.0)
    # Sub-floor skew is measured but attributed to nobody.
    assert rows["barrier"]["worst_host"] is None
    assert rows["barrier"]["p50_ms"] == pytest.approx(2.0, abs=0.5)


def test_collective_skew_table_applies_clock_offsets(tmp_path):
    d = str(tmp_path)
    # Host 1's RAW stamps are 1s ahead (clock offset), but it arrives in
    # sync — without alignment it would look like a 1s straggler.
    _write_arrivals(d, 0, [{"site": "s", "seq": 0, "host": 0, "t0": 10.0, "t1": 10.1}])
    _write_arrivals(d, 1, [{"site": "s", "seq": 0, "host": 1, "t0": 11.0, "t1": 11.1}])
    _clock_record(d, [0.0, 1.0])
    [row] = obs_fleet.collective_skew_table(d)  # offsets default from the clock file
    assert row["max_ms"] == pytest.approx(0.0, abs=1e-6)
    assert row["worst_host"] is None


def test_read_collective_arrivals_tolerates_torn_and_garbage(tmp_path):
    d = str(tmp_path)
    _write_arrivals(d, 0, [{"site": "s", "seq": 0, "host": 0, "t0": 1.0, "t1": 2.0}])
    path = os.path.join(d, obs_fleet.host_collectives_filename(1))
    jsonl.append_record(path, {"site": "s", "seq": "not-an-int", "t0": 1, "t1": 2})
    with open(path, "a") as f:
        f.write('{"site": "s", "se')
    arrivals = obs_fleet.read_collective_arrivals(d)
    assert arrivals == {("s", 0): {0: (1.0, 2.0)}}


def test_fleet_straggler_detector_persistence_and_reset():
    det = obs_fleet.FleetStragglerDetector(warn_streak=2, crit_streak=4)
    obs = lambda host, share: {"host": host, "share": share, "samples": 5}  # noqa: E731

    # A one-off hiccup that migrates between hosts never escalates: the
    # candidate change resets the persistence clock each window.
    for host in (0, 1, 0, 1, 0, 1):
        assert det.observe(obs(host, 1.0)) == "ok"

    # The same host staying worst escalates WARN → CRIT on the streaks.
    det = obs_fleet.FleetStragglerDetector(warn_streak=2, crit_streak=4)
    assert det.observe(obs(1, 0.95)) == "ok"  # candidate set, clock starts
    states = [det.observe(obs(1, 0.95)) for _ in range(5)]
    assert states[1] == "warn" and states[-1] == "crit"
    assert det.host == 1 and det.share == pytest.approx(0.95)

    # Idle/thin windows (few above-floor samples) don't judge.
    det = obs_fleet.FleetStragglerDetector(min_samples=3)
    assert det.observe({"host": 1, "share": 1.0, "samples": 2}) == "ok"
    assert det.observe({"host": None, "share": 0.0, "samples": 0}) == "ok"


# ------------------------------------------------------------- FleetMonitor


def test_single_process_monitor_degrades_to_one_host_fleet(tmp_path):
    d = str(tmp_path)
    monitor = obs_fleet.configure(d, process_index=0, process_count=1)
    assert obs_fleet.armed() and obs_fleet.fleet() is monitor

    # Clock sync without peers: trivial offsets, record still lands.
    rec = monitor.clock_sync(step=0)
    assert rec["offsets_s"] == [0.0] and rec["hosts"] == 1
    clock = obs_spans._last_clock_record(d)
    assert clock is not None and clock["offsets_s"] == [0.0]

    # The module hook (collective_guard's path) records (site, seq) arrivals.
    t = time.time()
    obs_fleet.collective_complete("allgather_host", t, t + 0.01)
    obs_fleet.collective_complete("allgather_host", t + 1, t + 1.01)
    arrivals = obs_fleet.read_collective_arrivals(d)
    assert set(arrivals) == {("allgather_host", 0), ("allgather_host", 1)}
    assert arrivals[("allgather_host", 0)][0] == (pytest.approx(t), pytest.approx(t + 0.01))

    gauges = monitor.on_log_boundary(step=3)
    assert gauges["fleet/hosts"] == 1.0
    assert gauges["fleet/collective_skew_ms_max"] == pytest.approx(0.0)
    assert gauges["fleet/straggler_state"] == 0.0

    block = monitor.health_block()
    assert block["hosts"] == 1 and block["desync"] == {"status": "unchecked"}
    assert block["straggler"]["state"] == "ok"
    monitor.note_desync(3, ok=True)
    assert monitor.health_block()["desync"] == {"step": 3, "ok": True}


def test_disarmed_hooks_are_noops_and_write_no_files(tmp_path):
    obs_fleet.shutdown()
    assert not obs_fleet.armed()
    obs_fleet.collective_complete("x", 1.0, 2.0)
    assert obs_fleet.incident_bundle(0, "collective_timeout") is None
    assert os.listdir(str(tmp_path)) == []  # nothing appeared anywhere near us


def test_collective_guard_records_arrival_when_armed(tmp_path):
    obs_fleet.configure(str(tmp_path), process_index=0, process_count=1)
    with collective_guard("drill_site", deadline=30.0, on_timeout=lambda e: None):
        pass
    # deadline 0 guards still stamp arrivals (attribution without the timer).
    with collective_guard("drill_site", deadline=0.0, on_timeout=lambda e: None):
        pass
    arrivals = obs_fleet.read_collective_arrivals(str(tmp_path))
    assert set(arrivals) == {("drill_site", 0), ("drill_site", 1)}


def test_window_rollup_watermark_defers_incomplete_occurrences(tmp_path):
    d = str(tmp_path)
    monitor = obs_fleet.configure(d, process_index=0, process_count=2)
    base = 100.0
    _write_arrivals(d, 0, [
        {"site": "s", "seq": 0, "host": 0, "t0": base, "t1": base + 0.01},
        {"site": "s", "seq": 1, "host": 0, "t0": base + 1, "t1": base + 1.01},
    ])
    # Host 1 has only seq 0 so far (lagging writer).
    _write_arrivals(d, 1, [
        {"site": "s", "seq": 0, "host": 1, "t0": base + 0.05, "t1": base + 0.06},
    ])
    gauges = monitor.on_log_boundary(step=1)
    assert gauges["fleet/collective_skew_ms_max"] == pytest.approx(50.0, abs=1.0)
    assert gauges["fleet/slowest_host"] == 1.0
    assert gauges["fleet/host1_worst_arrivals_total"] == 1.0

    # Host 1's seq 1 lands later: the next window picks it up (not dropped),
    # and the already-judged seq 0 is not double-counted.
    _write_arrivals(d, 1, [
        {"site": "s", "seq": 1, "host": 1, "t0": base + 1.05, "t1": base + 1.06},
    ])
    gauges = monitor.on_log_boundary(step=2)
    assert gauges["fleet/host1_worst_arrivals_total"] == 2.0


def test_incident_bundle_collects_all_hosts_span_tails(tmp_path):
    d = str(tmp_path)
    monitor = obs_fleet.configure(d, process_index=0, process_count=2)
    _write_host_spans(d, 0, [{"name": "a", "ph": "X", "ts": 1, "dur": 1, "tid": 1}])
    _write_host_spans(d, 1, [{"name": "b", "ph": "X", "ts": 2, "dur": 1, "tid": 1}])
    monitor.note_fingerprint(7, np.asarray([7, 123, 456]))

    base = monitor.incident_bundle(7, "collective_timeout", detail={"collective": "s"})
    assert base == os.path.join(d, "incidents", "7")
    # BOTH hosts' span tails — the aborting host collects its wedged peer's
    # file from the shared dir.
    for host, name in ((0, "a"), (1, "b")):
        tail = os.path.join(base, f"host{host}", "spans_tail.jsonl")
        records = jsonl.read_jsonl(tail)
        assert records and records[0]["name"] == name
    with open(os.path.join(base, "host0", "heartbeat.json")) as f:
        hb0 = json.load(f)
    assert hb0["last_fingerprint"]["step"] == 7  # the aborting host's own
    with open(os.path.join(base, "fleet_incident.json")) as f:
        manifest = json.load(f)
    assert manifest["reason"] == "collective_timeout"
    assert manifest["hosts"] == [0, 1] and manifest["collected_by"] == 0

    # Budget: a flapping guard cannot fill the disk.
    for step in range(8, 8 + 2 * obs_fleet.MAX_FLEET_BUNDLES):
        monitor.incident_bundle(step, "collective_timeout")
    bundles = [n for n in os.listdir(os.path.join(d, "incidents"))]
    assert len(bundles) == obs_fleet.MAX_FLEET_BUNDLES


def test_tail_whole_lines_trims_partial_first_line(tmp_path):
    path = str(tmp_path / "t.jsonl")
    lines = [json.dumps({"i": i, "pad": "x" * 100}) for i in range(50)]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    tail = obs_fleet._tail_whole_lines(path, max_bytes=500)
    parsed = [json.loads(ln) for ln in tail.decode().splitlines()]
    assert parsed  # something survived
    assert parsed[-1]["i"] == 49  # ends at the true tail
    assert all(p["i"] > 40 for p in parsed)  # only the tail window


# ---------------------------------------------------------- exporter pieces


def test_metrics_exporter_port_collision_rebinds_ephemeral(tmp_path):
    first = MetricsExporter(0)  # ephemeral
    port_file = str(tmp_path / "metrics_port")
    second = MetricsExporter(first.port, port_file=port_file)  # busy port
    try:
        assert second.requested_port == first.port
        assert second.port != first.port and second.port > 0
        # The actual port is discoverable: gauge + breadcrumb file.
        assert f"{second.port}" in open(port_file).read()
        body = second.render_metrics()
        name = sanitize_metric_name("trlx_tpu_obs/metrics_port")
        assert f"{name} {float(second.port)!r}" in body
    finally:
        first.close()
        second.close()


def test_exporter_healthz_fleet_block(tmp_path):
    exporter = MetricsExporter(0)
    try:
        payload = exporter.render_healthz()
        assert "fleet" not in payload  # absent until a fleet arms
        monitor = obs_fleet.configure(str(tmp_path), process_index=0, process_count=1)
        monitor.on_log_boundary(step=5, exporter=exporter)
        payload = exporter.render_healthz()
        assert payload["fleet"]["hosts"] == 1
        assert payload["fleet"]["straggler"]["state"] == "ok"
        assert "clock" in payload["fleet"]
        # And the gauges rode along into the exposition.
        body = exporter.render_metrics()
        assert sanitize_metric_name("trlx_tpu_fleet/hosts") + " 1.0" in body
    finally:
        exporter.close()


# ----------------------------------------------------------- report section


def test_report_fleet_section_renders_artifacts(tmp_path):
    from trlx_tpu.observability.report import _fleet_section

    d = str(tmp_path)
    # Nothing armed → the actionable fallback, not a crash.
    lines = _fleet_section(d)
    assert any("train.graftfleet off" in ln for ln in lines)

    _write_host_spans(d, 0, [{"name": "a", "ph": "X", "ts": 1, "dur": 1, "tid": 1}])
    _write_host_spans(d, 1, [{"name": "b", "ph": "X", "ts": 2, "dur": 1, "tid": 1}])
    _clock_record(d, [0.0, 0.25])
    base = 50.0
    _write_arrivals(d, 0, [{"site": "s", "seq": 0, "host": 0, "t0": base, "t1": base + 0.1}])
    _write_arrivals(d, 1, [{"site": "s", "seq": 0, "host": 1, "t0": base + 0.3, "t1": base + 0.4}])
    text = "\n".join(_fleet_section(d))
    assert "clock-alignment error" in text
    assert "| s |" in text and "host 1" in text  # skew table names the laggard
    assert "host1 +250.000ms" in text


# ------------------------------------------------------------ e2e (1 host)


def test_e2e_single_process_armed_run_degrades_to_one_host_fleet(tmp_path, monkeypatch):
    """graftfleet armed on ONE process: the fleet degrades to a one-host
    fleet — host-suffixed span file, clock history (trivial offsets, startup
    + every fleet_resync_interval steps), fleet/* gauges in metrics.jsonl,
    and a renderable Fleet report section. Armed via the env override (the
    config knob path is the 2-process drill's job)."""
    from trlx_tpu.observability.report import build_report

    monkeypatch.setenv("TRLX_TPU_GRAFTFLEET", "1")
    _, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=15, max_length=8, n_walks=60, seed=1000
    )
    config = base_config("ppo", 15, 8)
    config.train.total_steps = 6
    config.train.epochs = 4
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.checkpoint_dir = str(tmp_path)
    config.train.fleet_resync_interval = 2
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[1]],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert not obs_fleet.armed()  # learn() tears the global monitor down

    d = str(tmp_path)
    # Fleet owns the span filename: host-suffixed, no plain spans.jsonl.
    assert os.path.exists(os.path.join(d, obs_spans.host_spans_filename(0)))
    assert not os.path.exists(os.path.join(d, obs_spans.SPANS_FILENAME))
    # Clock history: startup sync + resyncs at steps 2/4/6, trivial offsets.
    clock_records = jsonl.read_jsonl(os.path.join(d, obs_spans.FLEET_CLOCK_FILENAME))
    assert len(clock_records) >= 3
    assert all(r["offsets_s"] == [0.0] and r["hosts"] == 1 for r in clock_records)
    assert {r["step"] for r in clock_records} >= {0, 2, 4}

    merged = obs_spans.read_fleet_spans(d)
    assert merged["hosts"] == [0]
    assert any(e.get("ph") == "X" for e in merged["traceEvents"])

    with open(os.path.join(d, "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    fleet_rows = [r for r in records if "fleet/hosts" in r]
    assert fleet_rows and all(r["fleet/hosts"] == 1.0 for r in fleet_rows)
    assert all("fleet/straggler_state" in r for r in fleet_rows)

    md = build_report(d)
    assert "## Fleet (graftfleet)" in md
    assert "clock-alignment error" in md
