"""Config system + registries (reference has no such tests; ours cover the
YAML → dataclass path the whole framework hangs off)."""

import os

import pytest

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.method_configs import get_method, PPOConfig, ILQLConfig

CONFIG_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "trlx_tpu", "configs")


def test_load_default_ppo_yaml():
    cfg = TRLConfig.load_yaml(os.path.join(CONFIG_DIR, "ppo_config.yml"))
    assert cfg.method.name == "ppoconfig"
    assert cfg.method.ppo_epochs == 4
    assert cfg.train.mesh == (-1, 1, 1, 1)
    assert cfg.model.num_layers_unfrozen == 2
    d = cfg.to_dict()
    assert "cliprange" in d and "seq_length" in d


def test_load_default_ilql_yaml():
    cfg = TRLConfig.load_yaml(os.path.join(CONFIG_DIR, "ilql_config.yml"))
    assert cfg.method.name == "ilqlconfig"
    assert cfg.method.two_qs is True
    assert cfg.method.betas == [16]


def test_method_registry():
    assert get_method("ppoconfig") is PPOConfig
    assert get_method("ILQLConfig") is ILQLConfig
    with pytest.raises(Exception):
        get_method("nonexistent")


def test_trainer_registry_names():
    import trlx_tpu.trainer.api  # populates registries
    from trlx_tpu.trainer import get_model

    # reference-compatible names resolve (reference: configs/*.yml model_type)
    # + the BASELINE north-star's backend names
    from trlx_tpu.trainer.ilql import ILQLTrainer
    from trlx_tpu.trainer.ppo import PPOTrainer

    assert get_model("TPUJaxPPOModel") is PPOTrainer
    assert get_model("TPUJaxILQLModel") is ILQLTrainer
    assert get_model("AcceleratePPOModel") is get_model("ppo")
    assert get_model("ILQLModel") is get_model("ilql")


def test_orchestrator_registry():
    import trlx_tpu.trainer.api  # noqa: F401
    from trlx_tpu.orchestrator import get_orchestrator

    assert get_orchestrator("PPOOrchestrator") is not None
    assert get_orchestrator("OfflineOrchestrator") is not None


def test_all_shipped_configs_load():
    import glob

    paths = sorted(glob.glob(os.path.join(CONFIG_DIR, "*.yml")))
    assert len(paths) >= 5
    for path in paths:
        cfg = TRLConfig.load_yaml(path)
        assert cfg.train.batch_size > 0, path


def test_sentiment_score_shapes():
    from trlx_tpu.utils import sentiment_score

    top1 = [{"label": "POSITIVE", "score": 0.9}, {"label": "NEGATIVE", "score": 0.8}]
    assert sentiment_score(top1) == [0.9, pytest.approx(0.2)]
    all_scores = [[{"label": "NEGATIVE", "score": 0.3}, {"label": "POSITIVE", "score": 0.7}]]
    assert sentiment_score(all_scores) == [pytest.approx(0.7)]


def test_indivisible_batch_and_chunk_fail_at_construction(tmp_path):
    """Batch/chunk sizes that cannot shard over the mesh's data axes must
    fail at trainer construction with a clear message, not as a cryptic
    sharding error at the first put_batch."""
    import os
    import sys

    import pytest

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))
    from randomwalks import base_config
    from trlx_tpu.trainer.ppo import PPOTrainer

    config = base_config("ppo", 15, 8)
    config.train.checkpoint_dir = str(tmp_path)
    config.train.mesh = [8, 1, 1, 1]
    config.train.batch_size = 12  # 12 % 8 != 0
    config.method.chunk_size = 16  # valid, so the error is the BATCH check's
    with pytest.raises(ValueError, match="train.batch_size"):
        PPOTrainer(config)

    config.train.batch_size = 16
    config.method.chunk_size = 20  # 20 % 8 != 0
    with pytest.raises(ValueError, match="chunk_size"):
        PPOTrainer(config)


def test_r4_train_config_fields_round_trip():
    """watch_interval / compile_cache_dir survive dict round-trips and carry
    their documented defaults (off)."""
    from trlx_tpu.data.configs import TRLConfig

    cfg = TRLConfig.from_dict(
        {
            "model": {"model_path": "", "tokenizer_path": "", "model_type": "ppo"},
            "train": {
                "total_steps": 1, "seq_length": 8, "epochs": 1, "batch_size": 2,
                "lr_ramp_steps": 1, "lr_decay_steps": 1, "weight_decay": 0.0,
                "learning_rate_init": 1e-3, "learning_rate_target": 1e-4,
                "watch_interval": 7, "compile_cache_dir": "/tmp/xla-cache",
            },
            "method": {"name": "ppoconfig"},
        }
    )
    assert cfg.train.watch_interval == 7
    assert cfg.train.compile_cache_dir == "/tmp/xla-cache"
    default = TRLConfig.from_dict(
        {
            "model": {"model_path": "", "tokenizer_path": "", "model_type": "ppo"},
            "train": {
                "total_steps": 1, "seq_length": 8, "epochs": 1, "batch_size": 2,
                "lr_ramp_steps": 1, "lr_decay_steps": 1, "weight_decay": 0.0,
                "learning_rate_init": 1e-3, "learning_rate_target": 1e-4,
            },
            "method": {"name": "ppoconfig"},
        }
    )
    assert default.train.watch_interval == 0
    assert default.train.compile_cache_dir is None
