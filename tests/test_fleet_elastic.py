"""Elastic N-worker rollout fleet (trlx_tpu/fleet + method.fleet_elastic).

Fast tier (in-process): the lease ledger's atomic claim/renew/expire/
reclaim-generation lifecycle, the O_EXCL worker registry with clean leave
and incarnation bumps, the deterministic prompt-shard seek that lets ANY
worker reproduce ANY work unit, and the acceptance identity — a COLOCATED
elastic run (the inline producer IS worker 0, claiming leases and tagging
units through the whole elastic machinery) at max_staleness=0 is
bitwise-identical to the non-elastic colocated fleet. Fully sanitized.

Slow tier (multi-process CPU drills, learner + N workers, each its own
single-controller JAX world coupled only via train.fleet_dir):

- ``worker_kill_mid_lease@N``: one of two workers dies holding a lease,
  nothing streamed → the survivor reclaims the unit at the next lease
  generation and the learner consumes EVERY work unit exactly once — no
  gap, no duplicate — and training completes.
- ``slow_worker_reclaim@N``: a worker outsleeps its lease TTL mid-hold,
  then produces anyway → the reclaimer already produced the same unit, two
  records land, and the (work_unit, episode_key) dedup consumes exactly one.
- join + leave: a worker deregisters cleanly mid-run while another worker
  JOINS mid-run (adopting the latest broadcast, never a historical one).
- all-workers-dead: the sole worker dies → per-worker triage reads dead,
  the learner degrades gracefully per the PR 16 contract and exits 0.
- 2-worker staleness-0 parity: N-worker elastic losses bitwise equal to a
  serial run.

When ``TRLX_TPU_DRILL_ARTIFACTS`` is set (the CI fleet-drill job does),
each drill exports the lease ledger, every per-worker stream index, and
the dedup/reclaim counters alongside the PR 16 artifacts.
"""

import json
import os
import shutil
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402
from trlx_tpu.fleet import (  # noqa: E402
    ElasticStreamReader,
    FleetPaths,
    LeaseLedger,
    WorkerRegistry,
    validate_fleet_config,
)
from trlx_tpu.fleet.topology import (  # noqa: E402
    WORKER_ENV,
    read_jsonl_or_empty,
    role_timeouts,
)

SANITIZE = "dispatch,donation,race"


@pytest.fixture(scope="module")
def task():
    return generate_random_walks(n_nodes=15, max_length=8, n_walks=60, seed=1000)


# ----------------------------------------------------------- lease ledger


def _ledger(tmp_path, ttl=60.0):
    paths = FleetPaths(root=str(tmp_path / "fleet")).ensure_elastic()
    return LeaseLedger(paths.leases_dir, ttl=ttl), paths


def test_lease_claim_is_exclusive_and_renewable(tmp_path):
    led, _ = _ledger(tmp_path)
    lease = led.try_claim(0, worker=0)
    assert lease is not None and lease.gen == 0 and lease.worker == 0
    # A fresh-held unit is unclaimable by a peer; the owner re-adopts it.
    assert led.try_claim(0, worker=1) is None
    again = led.try_claim(0, worker=0)
    assert again is not None and again.gen == 0
    renewed = led.renew(lease)
    assert renewed is not None and renewed.expires >= lease.expires
    assert [l.unit for l in led.held_by(0)] == [0]
    assert led.reclaimed_units() == []


def test_expired_lease_reclaims_at_next_generation(tmp_path):
    led, _ = _ledger(tmp_path, ttl=0.2)
    l0 = led.try_claim(3, worker=0)
    assert l0.gen == 0
    time.sleep(0.3)
    l1 = led.try_claim(3, worker=1)
    assert l1 is not None and l1.gen == 1 and l1.worker == 1
    # The dead owner's stale handle lost: renew/complete refuse quietly.
    assert led.renew(l0) is None
    assert led.complete(l0) is False
    assert led.complete(l1) is True
    assert led.reclaimed_units() == [3]
    # A done unit is never claimable again, any worker, any generation.
    assert led.try_claim(3, worker=0) is None
    assert led.try_claim(3, worker=1) is None


def test_released_lease_reclaims_without_waiting_for_ttl(tmp_path):
    led, _ = _ledger(tmp_path, ttl=60.0)
    l0 = led.try_claim(1, worker=0)
    assert led.release(l0)
    l1 = led.try_claim(1, worker=1)  # instant: no TTL wait on a clean leave
    assert l1 is not None and l1.gen == 1
    assert led.reclaimed_units() == [1]


def test_torn_claim_file_reads_as_fresh_hold_not_free(tmp_path):
    """A lease file caught mid-write must read HELD (claimable only after
    the mtime+ttl grace), never free — two workers double-claiming a unit
    on a torn read is exactly the race the O_EXCL ledger exists to kill."""
    led, paths = _ledger(tmp_path, ttl=0.3)
    with open(os.path.join(paths.leases_dir, "unit_000007.gen000.json"), "w") as f:
        f.write('{"unit": 7, "wor')
    assert led.try_claim(7, worker=1) is None  # fresh torn file: held
    time.sleep(0.4)
    got = led.try_claim(7, worker=1)  # grace elapsed: reclaim, next gen
    assert got is not None and got.gen == 1


def test_worker_registry_auto_ids_leave_and_incarnation(tmp_path):
    paths = FleetPaths(root=str(tmp_path / "fleet")).ensure_elastic()
    reg = WorkerRegistry(paths.workers_dir)
    assert reg.register() == 0
    assert reg.register() == 1  # lowest free slot via O_EXCL
    assert sorted(reg.active()) == [0, 1]
    reg.leave(0)
    assert reg.active() == [1]
    assert reg.workers()[0]["status"] == "left"
    # A left slot is NOT auto-reused (ids stay stable for the event log)...
    assert reg.register() == 2
    # ...but an explicit re-register of the same id bumps its incarnation.
    assert reg.register(0) == 0
    assert reg.workers()[0]["status"] == "active"
    assert reg.workers()[0]["incarnation"] == 1


# ------------------------------------------------------------- validation


def _config(**train_overrides):
    config = base_config("ppo", 15, 8)
    for k, v in train_overrides.items():
        setattr(config.train, k, v)
    return config


def test_fleet_elastic_requires_disaggregate(monkeypatch):
    monkeypatch.delenv(WORKER_ENV, raising=False)
    config = _config()
    config.method.fleet_elastic = True
    with pytest.raises(ValueError, match="fleet_disaggregate"):
        validate_fleet_config(config)


def test_worker_env_and_lease_ttl_require_elastic(monkeypatch):
    config = _config()
    config.method.fleet_disaggregate = True
    monkeypatch.setenv(WORKER_ENV, "1")
    with pytest.raises(ValueError, match=WORKER_ENV):
        validate_fleet_config(config)
    monkeypatch.delenv(WORKER_ENV, raising=False)
    config.train.fleet_lease_ttl = 5.0
    with pytest.raises(ValueError, match="fleet_lease_ttl"):
        validate_fleet_config(config)
    config.method.fleet_elastic = True
    assert validate_fleet_config(config) == "colocated"
    monkeypatch.setenv(WORKER_ENV, "banana")
    with pytest.raises(ValueError, match="non-negative"):
        validate_fleet_config(config)


def test_lease_ttl_resolution_defaults_from_heartbeat(monkeypatch):
    t = _config().train
    assert role_timeouts(t)["lease_ttl"] == 3.0  # max(6 * 0.5, 3.0)
    t = _config(heartbeat_interval=2.0).train
    assert role_timeouts(t)["lease_ttl"] == 12.0
    t = _config(fleet_lease_ttl=7.5).train
    assert role_timeouts(t)["lease_ttl"] == 7.5


# ---------------------------------------------- deterministic prompt seek


def test_seek_chunks_reproduces_any_units_prompt_shard():
    """Work-unit portability: any worker, at any time, must rebuild the
    exact prompt chunks of any unit — that is what makes a reclaimed unit
    carry the dead owner's episode_key. seek_chunks forward-winds (or
    rebuilds + winds, for a unit behind the local position) the seeded
    shuffle loader, so two orchestrators at different histories converge."""
    from trlx_tpu.orchestrator.ppo_orchestrator import PPOOrchestrator
    from trlx_tpu.pipeline.prompt_pipeline import PromptPipeline

    class _Orch:
        chunks_per_unit = PPOOrchestrator.chunks_per_unit
        seek_chunks = PPOOrchestrator.seek_chunks
        _next_prompt_batch = PPOOrchestrator._next_prompt_batch

        def __init__(self):
            self.pipeline = PromptPipeline(
                [[i % 13 + 1] for i in range(32)], max_prompt_length=1
            )
            self.chunk_size = 8
            self.pipeline_loader = self.pipeline.create_loader(self.chunk_size, shuffle=True)
            self.pipeline_iterator = iter(self.pipeline_loader)
            self._chunks_consumed = 0

    a = _Orch()
    schedule = [np.asarray(a._next_prompt_batch()["input_ids"]).copy() for _ in range(10)]
    assert a.chunks_per_unit(16) == 2  # ceil(16 rollouts / 8 chunk)

    # A joiner seeks forward to unit 3's shard (chunks 6,7) from scratch.
    b = _Orch()
    b.seek_chunks(3 * 2)
    assert np.array_equal(np.asarray(b._next_prompt_batch()["input_ids"]), schedule[6])
    assert np.array_equal(np.asarray(b._next_prompt_batch()["input_ids"]), schedule[7])
    # A reclaimer seeks BACKWARD (rebuild + rewind) to unit 1's shard.
    b.seek_chunks(1 * 2)
    assert np.array_equal(np.asarray(b._next_prompt_batch()["input_ids"]), schedule[2])
    # And the original, past an epoch wrap, stays on the same schedule.
    a.seek_chunks(4)
    assert np.array_equal(np.asarray(a._next_prompt_batch()["input_ids"]), schedule[4])


# ------------------------------------------------ colocated parity (fast)


def _run_ppo(task, ckpt_dir, fleet=False, elastic=False, steps=4, **overrides):
    _, logit_mask, metric_fn, reward_fn = task
    config = base_config("ppo", 15, 8)
    config.train.total_steps = steps
    config.train.epochs = 4
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.checkpoint_dir = str(ckpt_dir)
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    if fleet:
        config.method.fleet_disaggregate = True
        config.train.fleet_dir = str(ckpt_dir) + "_fleet"
    if elastic:
        config.method.fleet_elastic = True
    for k, v in overrides.items():
        setattr(config.method, k, v)
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[1]],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    with open(os.path.join(str(ckpt_dir), "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    return model, records


def test_colocated_elastic_staleness0_matches_non_elastic_bitwise(task, tmp_path, monkeypatch):
    """Acceptance identity: flipping method.fleet_elastic on the colocated
    staleness-0 fleet — every unit now lease-claimed, seek-scheduled, and
    unit-tagged through the ledger — changes the loss trajectory by
    NOTHING (bitwise). The elastic run's stream records carry unit/worker/
    episode_key; the non-elastic run's stay byte-identical to PR 16's."""
    from trlx_tpu.utils import sanitize

    monkeypatch.setenv(sanitize.ENV_VAR, SANITIZE)
    try:
        _, plain = _run_ppo(task, tmp_path / "plain", fleet=True, max_staleness=0)
        model, elastic = _run_ppo(
            task, tmp_path / "elastic", fleet=True, elastic=True, max_staleness=0
        )
    finally:
        monkeypatch.delenv(sanitize.ENV_VAR, raising=False)
        sanitize.refresh()
        sanitize.clear_donated()
        sanitize.clear_races()

    losses_plain = [r["loss"] for r in plain if "loss" in r]
    losses_elastic = [r["loss"] for r in elastic if "loss" in r]
    assert len(losses_plain) == 4
    assert losses_elastic == losses_plain

    plain_dir = str(tmp_path / "plain") + "_fleet"
    elastic_dir = str(tmp_path / "elastic") + "_fleet"
    # Non-elastic layout untouched: no ledger, no registry, PR 16 records.
    assert not os.path.exists(os.path.join(plain_dir, "leases"))
    stream_plain = read_jsonl_or_empty(os.path.join(plain_dir, "stream.jsonl"))
    assert stream_plain and all("unit" not in r for r in stream_plain)
    # Elastic layout: every record unit-tagged by worker 0, every unit's
    # lease claimed at gen 0 and completed, registry holds the inline worker.
    stream = read_jsonl_or_empty(os.path.join(elastic_dir, "stream.jsonl"))
    assert stream and [r["unit"] for r in stream] == [r["seq"] for r in stream]
    assert all(r["worker"] == 0 and r["episode_key"] for r in stream)
    paths = FleetPaths(root=elastic_dir)
    ledger = LeaseLedger(paths.leases_dir, ttl=60.0)
    states = ledger.units()
    assert sorted(states) == [r["unit"] for r in stream]
    assert all(l.status == "done" and l.gen == 0 for l in states.values())
    assert WorkerRegistry(paths.workers_dir).workers()[0]["status"] == "active"
    # Elastic consume events carry unit+worker; cursor carries stream marks.
    events = read_jsonl_or_empty(os.path.join(elastic_dir, "fleet_events.jsonl"))
    consumed = [e for e in events if e["event"] == "episode_consumed"]
    assert consumed and [e["unit"] for e in consumed] == sorted({e["unit"] for e in consumed})
    with open(os.path.join(elastic_dir, "learner_cursor.json")) as f:
        cursor = json.load(f)
    assert cursor["streams"]["0"] == cursor["consumed"]
    assert model._fleet_feed is None
    assert not any(t.name.startswith("trlx-") for t in threading.enumerate())


# --------------------------------------------------- multi-process drills

_ELASTIC_WORKER = r"""
import json, os, sys, threading, time
import urllib.request
import numpy as np

role = sys.argv[1]            # "serial" | "rollout" | "learner"
ckpt = sys.argv[2]
fleet_dir = sys.argv[3]
S = int(sys.argv[4])

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["TRLX_TPU_NO_PROGRESS"] = "1"

sys.path.insert(0, os.path.join(os.environ["TRLX_REPO"], "examples"))
import trlx_tpu
from randomwalks import base_config, generate_random_walks

_, logit_mask, metric_fn, reward_fn = generate_random_walks(
    n_nodes=15, max_length=8, n_walks=60, seed=1000
)

config = base_config("ppo", 15, 8)
config.train.total_steps = int(os.environ.get("TOTAL", "8"))
config.train.epochs = int(os.environ.get("EPOCHS", "4"))
config.train.batch_size = 16
config.train.eval_interval = 100
config.train.checkpoint_dir = ckpt
config.method.num_rollouts = 16
config.method.chunk_size = 16
if role != "serial":
    config.method.fleet_disaggregate = True
    config.method.fleet_elastic = True
    config.method.max_staleness = S
    config.train.fleet_dir = fleet_dir
    # Drill-scale timing: seconds, not the production minutes.
    config.train.heartbeat_interval = 0.2
    config.train.fleet_episode_timeout = 2.0
    config.train.fleet_stream_retries = 1
    config.train.fleet_stream_backoff = 0.2
    config.train.fleet_heartbeat_timeout = float(os.environ.get("HB_TIMEOUT", "3.0"))
    config.train.fleet_broadcast_deadline = float(os.environ.get("BDEADLINE", "120"))
    config.train.fleet_lease_ttl = float(os.environ.get("LEASE_TTL", "1.0"))

scrapes_stop = threading.Event()

def scrape_loop():
    # Live witnesses: the per-worker /healthz workers block (satellite:
    # worker id, heartbeat age, lease count, triage state) and the
    # worker-labeled fleet/* gauge series must be observable DURING the
    # run, not reconstructed post-hoc.
    mport = int(os.environ.get("TRLX_TPU_METRICS_PORT", "0"))
    while not scrapes_stop.is_set():
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/healthz", timeout=2
            ) as r:
                payload = json.loads(r.read().decode())
            fleet = payload.get("fleet", {})
            if fleet.get("workers"):
                with open(os.path.join(ckpt, "scrape_workers.json"), "w") as f:
                    json.dump(fleet, f)
            if fleet.get("disaggregated", {}).get("state") == "degraded":
                with open(os.path.join(ckpt, "scrape_degraded.json"), "w") as f:
                    json.dump(payload, f)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/metrics", timeout=2
            ) as r:
                body = r.read().decode()
            if 'trlx_tpu_fleet_worker_state{worker="' in body:
                with open(os.path.join(ckpt, "scrape_metrics.txt"), "w") as f:
                    f.write(body)
        except Exception:
            pass  # exporter not up yet / mid-teardown
        scrapes_stop.wait(0.05)

scraper = None
if role == "learner" and os.environ.get("TRLX_TPU_METRICS_PORT"):
    os.makedirs(ckpt, exist_ok=True)
    scraper = threading.Thread(target=scrape_loop, daemon=True)
    scraper.start()

prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
try:
    trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
        metric_fn=metric_fn, config=config, logit_mask=logit_mask,
    )
finally:
    scrapes_stop.set()
    if scraper is not None:
        scraper.join(timeout=5)

if role in ("serial", "learner"):
    with open(os.path.join(ckpt, "metrics.jsonl")) as f:
        losses = [json.loads(l).get("loss") for l in f]
    print("LOSSES", json.dumps([l for l in losses if l is not None]))
print("THREADS", json.dumps([t.name for t in threading.enumerate() if t.name.startswith("trlx-")]))
print(f"fleet role {role} DONE")
"""


def _script(tmp_path):
    script = tmp_path / "fleet_elastic_worker.py"
    script.write_text(_ELASTIC_WORKER)
    return str(script)


def _launch(tmp_path, role, ckpt, fleet_dir, staleness, extra_env=None):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("TRLX_TPU_FAULTS", None)
    env.pop("TRLX_TPU_METRICS_PORT", None)
    env.pop(WORKER_ENV, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo
    env["TRLX_REPO"] = repo
    env["TRLX_TPU_SANITIZE"] = SANITIZE
    if role != "serial":
        env["TRLX_TPU_FLEET_ROLE"] = role
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, _script(tmp_path), role, str(ckpt), str(fleet_dir), str(staleness)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )


def _communicate(proc, timeout=900):
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        pytest.skip("elastic fleet drill did not complete in this environment")
    return out.decode(errors="replace")


def _events(fleet_dir):
    return read_jsonl_or_empty(os.path.join(str(fleet_dir), "fleet_events.jsonl"))


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _export_artifacts(fleet_dir, logs):
    dest = os.environ.get("TRLX_TPU_DRILL_ARTIFACTS")
    if not dest:
        return
    os.makedirs(dest, exist_ok=True)
    fleet_dir = str(fleet_dir)
    for name in ("broadcast.jsonl", "fleet_events.jsonl", "weights_latest.json",
                 "abort.json", "learner_cursor.json"):
        src = os.path.join(fleet_dir, name)
        if os.path.exists(src):
            shutil.copy(src, os.path.join(dest, name))
    # Elastic surface: every per-worker stream index, the lease ledger, and
    # the dedup/reclaim counters the drill asserted on.
    if os.path.isdir(fleet_dir):
        for name in sorted(os.listdir(fleet_dir)):
            if name == "stream.jsonl" or (name.startswith("stream.w") and name.endswith(".jsonl")):
                shutil.copy(os.path.join(fleet_dir, name), os.path.join(dest, name))
    leases = os.path.join(fleet_dir, "leases")
    if os.path.isdir(leases):
        shutil.copytree(leases, os.path.join(dest, "leases"), dirs_exist_ok=True)
    workers = os.path.join(fleet_dir, "workers")
    if os.path.isdir(workers):
        shutil.copytree(workers, os.path.join(dest, "workers"), dirs_exist_ok=True)
    paths = FleetPaths(root=fleet_dir)
    if os.path.isdir(fleet_dir):
        reader = ElasticStreamReader(paths)
        ledger = LeaseLedger(paths.leases_dir, ttl=60.0)
        with open(os.path.join(dest, "dedup_counters.json"), "w") as f:
            json.dump(
                {
                    "episodes_deduped_total": reader.duplicates(),
                    "units_reclaimed_total": len(ledger.reclaimed_units())
                    if os.path.isdir(leases)
                    else 0,
                    "units": sorted(reader.chosen()),
                },
                f,
            )
    for name, text in logs.items():
        with open(os.path.join(dest, name), "w") as f:
            f.write(text)


def _assert_clean_threads(out, who):
    lines = [l for l in out.splitlines() if l.startswith("THREADS ")]
    assert lines, f"{who} never reported its thread census:\n{out[-2000:]}"
    assert json.loads(lines[-1][len("THREADS "):]) == [], f"{who} leaked threads: {lines[-1]}"


def _consumed_units(fleet_dir):
    return [e["unit"] for e in _events(fleet_dir) if e["event"] == "episode_consumed"]


@pytest.mark.slow
def test_fleet_drill_worker_kill_mid_lease_exactly_once(tmp_path):
    """The flagship elastic drill: learner + 2 workers, worker 0 dies
    abruptly RIGHT AFTER claiming its first unit >= 1 — lease held, nothing
    streamed. The survivor reclaims the orphaned unit at the next lease
    generation and the learner consumes every work unit EXACTLY once (no
    gap where the dead worker's unit was, no duplicate from the reclaim),
    completes training, and coordinates a clean shutdown."""
    fleet_dir = tmp_path / "fleet"
    # 4 work units: each epoch trains one unit for ppo_epochs (4) steps, so
    # TOTAL = 4 * EPOCHS walks the bootstrap unit + 3 post-epoch consumes.
    # HB_TIMEOUT stays generous: triage is not under test here — the TTL
    # reclaim is — and a mid-compile worker must not read as stalled.
    env = {"TOTAL": "16", "EPOCHS": "4", "LEASE_TTL": "1.0", "HB_TIMEOUT": "10"}
    w0 = _launch(
        tmp_path, "rollout", tmp_path / "ckpt_w0", fleet_dir, 1,
        {**env, WORKER_ENV: "0", "TRLX_TPU_FAULTS": "worker_kill_mid_lease@1"},
    )
    w1 = _launch(
        tmp_path, "rollout", tmp_path / "ckpt_w1", fleet_dir, 1,
        {**env, WORKER_ENV: "1"},
    )
    logs = {}
    try:
        mport = _free_port()
        learner = _launch(
            tmp_path, "learner", tmp_path / "ckpt_l", fleet_dir, 1,
            {**env, "TRLX_TPU_METRICS_PORT": str(mport)},
        )
        out_l = logs["learner.log"] = _communicate(learner)
        out_w0 = logs["worker0.log"] = _communicate(w0, timeout=120)
        out_w1 = logs["worker1.log"] = _communicate(w1, timeout=120)
        assert learner.returncode == 0, f"learner failed:\n{out_l[-4000:]}"
        assert w0.returncode == 1, f"worker0 should os._exit(1):\n{out_w0[-4000:]}"
        assert w1.returncode == 0, f"worker1 failed:\n{out_w1[-4000:]}"

        # EXACTLY once: units 0..3, strictly in order, no gap, no repeat.
        assert _consumed_units(fleet_dir) == [0, 1, 2, 3]
        events = _events(fleet_dir)
        # The orphaned unit came back at a bumped lease generation, claimed
        # by the survivor.
        reclaims = [e for e in events if e["event"] == "lease_reclaimed"]
        assert reclaims and all(e["gen"] >= 1 for e in reclaims)
        assert any(e["worker"] == 1 for e in reclaims)
        paths = FleetPaths(root=str(fleet_dir))
        ledger = LeaseLedger(paths.leases_dir, ttl=60.0)
        assert ledger.reclaimed_units()
        # Both workers registered; every consumed record's producer is one
        # of them; the survivor produced the tail.
        registered = {e["worker"] for e in events if e["event"] == "worker_registered"}
        assert registered == {0, 1}
        producers = {e["worker"] for e in events if e["event"] == "episode_consumed"}
        assert producers <= {0, 1} and 1 in producers
        with open(os.path.join(str(fleet_dir), "abort.json")) as f:
            assert json.load(f)["reason"] == "complete"

        # Live satellite witness: per-worker labeled gauges and the
        # /healthz workers block were scraped DURING the run.
        with open(os.path.join(str(tmp_path / "ckpt_l"), "scrape_metrics.txt")) as f:
            body = f.read()
        assert 'trlx_tpu_fleet_worker_state{worker="0"}' in body
        assert 'trlx_tpu_fleet_worker_state{worker="1"}' in body
        assert 'trlx_tpu_fleet_worker_heartbeat_age{worker="' in body
        assert "trlx_tpu_fleet_units_reclaimed_total" in body
        with open(os.path.join(str(tmp_path / "ckpt_l"), "scrape_workers.json")) as f:
            fleet_block = json.load(f)
        for wid, w in fleet_block["workers"].items():
            assert wid in ("0", "1")
            assert {"state", "heartbeat_age", "leases_held", "incarnation"} <= set(w)
        _assert_clean_threads(out_l, "learner")
        _assert_clean_threads(out_w1, "worker1")
    finally:
        for p in (w0, w1):
            if p.poll() is None:
                p.kill()
                p.communicate()
        _export_artifacts(fleet_dir, logs)


@pytest.mark.slow
def test_fleet_drill_slow_worker_reclaim_dedups_exactly_once(tmp_path):
    """slow_worker_reclaim@1 on worker 0: it outsleeps its lease TTL while
    holding a unit, the peer reclaims AND produces that unit, then the
    sleeper wakes and produces it AGAIN. Two records land for one work
    unit; the learner's (work_unit, episode_key) intake consumes exactly
    one and counts the duplicate. Nobody crashes; training completes."""
    fleet_dir = tmp_path / "fleet"
    # 6 work units (TOTAL = 4 * EPOCHS). The sleep fires at the first claim
    # of a unit >= 2, so the sleeper has already produced (and compiled) at
    # least one unit: its duplicate lands seconds before the run can end.
    env = {"TOTAL": "24", "EPOCHS": "6", "LEASE_TTL": "1.0", "HB_TIMEOUT": "10"}
    w0 = _launch(
        tmp_path, "rollout", tmp_path / "ckpt_w0", fleet_dir, 1,
        {**env, WORKER_ENV: "0", "TRLX_TPU_FAULTS": "slow_worker_reclaim@2",
         "TRLX_TPU_SLOW_WORKER_SECONDS": "2.5"},
    )
    w1 = _launch(
        tmp_path, "rollout", tmp_path / "ckpt_w1", fleet_dir, 1,
        {**env, WORKER_ENV: "1"},
    )
    logs = {}
    try:
        learner = _launch(tmp_path, "learner", tmp_path / "ckpt_l", fleet_dir, 1, env)
        out_l = logs["learner.log"] = _communicate(learner)
        out_w0 = logs["worker0.log"] = _communicate(w0, timeout=120)
        out_w1 = logs["worker1.log"] = _communicate(w1, timeout=120)
        assert learner.returncode == 0, f"learner failed:\n{out_l[-4000:]}"
        assert w0.returncode == 0, f"worker0 failed:\n{out_w0[-4000:]}"
        assert w1.returncode == 0, f"worker1 failed:\n{out_w1[-4000:]}"

        # Exactly-once intake despite the double production.
        assert _consumed_units(fleet_dir) == list(range(6))
        paths = FleetPaths(root=str(fleet_dir))
        reader = ElasticStreamReader(paths)
        assert reader.duplicates() >= 1
        # The duplicated unit landed in BOTH workers' streams with the SAME
        # prompt-shard content key (deterministic seek), different seqs.
        dup_units = [u for u, recs in reader.by_unit().items() if len(recs) > 1]
        assert dup_units
        for u in dup_units:
            recs = reader.by_unit()[u]
            assert {r["worker"] for r in recs} == {0, 1}
            assert len({r["episode_key"] for r in recs}) == 1
        ledger = LeaseLedger(paths.leases_dir, ttl=60.0)
        assert set(dup_units) <= set(ledger.reclaimed_units())
        with open(os.path.join(str(fleet_dir), "abort.json")) as f:
            assert json.load(f)["reason"] == "complete"
        _assert_clean_threads(out_l, "learner")
    finally:
        for p in (w0, w1):
            if p.poll() is None:
                p.kill()
                p.communicate()
        _export_artifacts(fleet_dir, logs)


@pytest.mark.slow
def test_fleet_drill_worker_join_and_leave_mid_run(tmp_path):
    """Dynamic membership: worker 0 produces two units then deregisters
    CLEANLY (releasing any held lease); worker 1 defers registration until
    the learner's cursor reaches 2 — a true mid-run join that adopts the
    LATEST broadcast — and carries the run to completion. Every unit is
    consumed exactly once across the membership change."""
    fleet_dir = tmp_path / "fleet"
    # 6 work units (TOTAL = 4 * EPOCHS). No reclaim belongs in this drill —
    # a clean leave releases instantly — so the TTL is slack enough that the
    # leaver's units never expire mid-produce, and HB_TIMEOUT rides out the
    # joiner's first JIT compile (progress frozen while the learner is hot).
    env = {"TOTAL": "24", "EPOCHS": "6", "LEASE_TTL": "5.0", "HB_TIMEOUT": "15"}
    w0 = _launch(
        tmp_path, "rollout", tmp_path / "ckpt_w0", fleet_dir, 1,
        {**env, WORKER_ENV: "0", "TRLX_TPU_FLEET_LEAVE_AFTER": "2"},
    )
    w1 = _launch(
        tmp_path, "rollout", tmp_path / "ckpt_w1", fleet_dir, 1,
        {**env, "TRLX_TPU_FAULTS": "worker_join_mid_run@2"},  # auto worker id
    )
    logs = {}
    try:
        learner = _launch(tmp_path, "learner", tmp_path / "ckpt_l", fleet_dir, 1, env)
        out_l = logs["learner.log"] = _communicate(learner)
        out_w0 = logs["worker0.log"] = _communicate(w0, timeout=300)
        out_w1 = logs["worker1.log"] = _communicate(w1, timeout=120)
        assert learner.returncode == 0, f"learner failed:\n{out_l[-4000:]}"
        assert w0.returncode == 0, f"worker0 failed:\n{out_w0[-4000:]}"
        assert w1.returncode == 0, f"worker1 failed:\n{out_w1[-4000:]}"

        assert _consumed_units(fleet_dir) == list(range(6))
        events = _events(fleet_dir)
        # The leaver: exactly 2 units produced, then a clean deregistration.
        left = [e for e in events if e["event"] == "worker_left"]
        assert len(left) == 1
        assert left[0]["worker"] == 0 and left[0]["produced"] == 2
        # The joiner: registered mid-run (cursor >= 2), adopted weights.
        # Publish-before-cursor-advance means cursor 2 implies ordinal 1 is
        # out, so a bootstrap fetch of ordinal 0 here would prove the joiner
        # adopted a HISTORICAL broadcast instead of the latest.
        joins = [e for e in events if e["event"] == "worker_registered" and e["worker"] != 0]
        assert len(joins) == 1 and joins[0]["joined_at"] == 2 and joins[0]["cursor"] >= 2
        joiner = joins[0]["worker"]
        fetched = [e for e in events if e["event"] == "weights_fetched" and e.get("worker") == joiner]
        assert fetched and fetched[0]["ordinal"] >= 1  # latest, not historical
        producers = {e["worker"]: 0 for e in events if e["event"] == "episode_consumed"}
        for e in events:
            if e["event"] == "episode_consumed":
                producers[e["worker"]] += 1
        assert producers[0] == 2 and producers[joiner] == 4
        # Registry end-state: 0 left, the joiner active until coordinated
        # shutdown flipped it to left on exit.
        paths = FleetPaths(root=str(fleet_dir))
        reg = WorkerRegistry(paths.workers_dir).workers()
        assert reg[0]["status"] == "left"
        assert reg[joiner]["status"] == "left"
        with open(os.path.join(str(fleet_dir), "abort.json")) as f:
            assert json.load(f)["reason"] == "complete"
        _assert_clean_threads(out_l, "learner")
        _assert_clean_threads(out_w0, "worker0")
        _assert_clean_threads(out_w1, "worker1")
    finally:
        for p in (w0, w1):
            if p.poll() is None:
                p.kill()
                p.communicate()
        _export_artifacts(fleet_dir, logs)


@pytest.mark.slow
def test_fleet_drill_all_workers_dead_degrades_cleanly(tmp_path):
    """PR 16 contract under elastic triage: the ONLY worker dies holding a
    lease → the per-worker triage reads dead, the aggregate goes dead, the
    learner drains what landed, flips fleet/degraded on a LIVE scrape, and
    exits 0 — never a hang."""
    fleet_dir = tmp_path / "fleet"
    env = {"TOTAL": "100", "EPOCHS": "100", "LEASE_TTL": "1.0"}
    w0 = _launch(
        tmp_path, "rollout", tmp_path / "ckpt_w0", fleet_dir, 2,
        {**env, WORKER_ENV: "0", "TRLX_TPU_FAULTS": "worker_kill_mid_lease@1"},
    )
    logs = {}
    try:
        mport = _free_port()
        learner = _launch(
            tmp_path, "learner", tmp_path / "ckpt_l", fleet_dir, 2,
            {**env, "TRLX_TPU_METRICS_PORT": str(mport)},
        )
        out_l = logs["learner.log"] = _communicate(learner)
        logs["worker0.log"] = _communicate(w0, timeout=60)
        assert learner.returncode == 0, f"learner failed:\n{out_l[-4000:]}"
        assert w0.returncode == 1
        assert "[fleet] learner stopped cleanly" in out_l
        events = _events(fleet_dir)
        degraded = [e for e in events if e["event"] == "degraded"]
        assert degraded and degraded[0]["triage"] == "dead"
        with open(os.path.join(str(fleet_dir), "abort.json")) as f:
            assert json.load(f)["reason"] in ("degraded", "stream_dry")
        # Live degraded scrape carries the per-worker verdict.
        with open(os.path.join(str(tmp_path / "ckpt_l"), "scrape_degraded.json")) as f:
            scrape = json.load(f)
        assert scrape["fleet"]["disaggregated"]["state"] == "degraded"
        assert scrape["fleet"]["workers"]["0"]["state"] == "dead"
        _assert_clean_threads(out_l, "learner")
    finally:
        if w0.poll() is None:
            w0.kill()
            w0.communicate()
        _export_artifacts(fleet_dir, logs)


@pytest.mark.slow
def test_two_worker_staleness0_matches_serial_bitwise(tmp_path):
    """The N-worker acceptance identity: 2 elastic workers at
    max_staleness=0 — units lease-serialized across two real processes,
    episodes crossing as npz, weights crossing back as byte-leaves —
    reproduce the serial loss trajectory bitwise."""
    # 3 work units (TOTAL = 4 * EPOCHS), identical for both legs. The TTL
    # is slack: at staleness 0 the units serialize anyway, and a live worker
    # losing its lease mid-compile would only add churn, never divergence.
    env = {"TOTAL": "12", "EPOCHS": "3"}
    serial = _launch(tmp_path, "serial", tmp_path / "ckpt_s", tmp_path / "unused", 0, env)
    out_s = _communicate(serial)
    assert serial.returncode == 0, f"serial run failed:\n{out_s[-4000:]}"

    fleet_dir = tmp_path / "fleet"
    env = {**env, "LEASE_TTL": "30", "HB_TIMEOUT": "10"}
    w0 = _launch(tmp_path, "rollout", tmp_path / "ckpt_w0", fleet_dir, 0, {**env, WORKER_ENV: "0"})
    w1 = _launch(tmp_path, "rollout", tmp_path / "ckpt_w1", fleet_dir, 0, {**env, WORKER_ENV: "1"})
    logs = {}
    try:
        learner = _launch(tmp_path, "learner", tmp_path / "ckpt_l", fleet_dir, 0, env)
        out_l = logs["learner.log"] = _communicate(learner)
        out_w0 = logs["worker0.log"] = _communicate(w0, timeout=120)
        out_w1 = logs["worker1.log"] = _communicate(w1, timeout=120)
        assert learner.returncode == 0, f"learner failed:\n{out_l[-4000:]}"
        assert w0.returncode == 0, f"worker0 failed:\n{out_w0[-4000:]}"
        assert w1.returncode == 0, f"worker1 failed:\n{out_w1[-4000:]}"

        def losses(out):
            line = next(l for l in out.splitlines() if l.startswith("LOSSES "))
            return json.loads(line[len("LOSSES "):])

        assert losses(out_s) == losses(out_l)
        assert len(losses(out_s)) == 12

        consumed = [e for e in _events(fleet_dir) if e["event"] == "episode_consumed"]
        assert consumed and all(e["staleness"] == 0 for e in consumed)
        assert [e["unit"] for e in consumed] == list(range(len(consumed)))
        with open(os.path.join(str(fleet_dir), "abort.json")) as f:
            assert json.load(f)["reason"] == "complete"
        _assert_clean_threads(out_l, "learner")
        _assert_clean_threads(out_w0, "worker0")
        _assert_clean_threads(out_w1, "worker1")
    finally:
        for p in (w0, w1):
            if p.poll() is None:
                p.kill()
                p.communicate()
        _export_artifacts(fleet_dir, logs)
