"""Golden numerical-parity harness: runs the reference's OWN torch loss code
(`/root/reference/trlx`, imported read-only) against this repo's JAX losses on
identical synthetic tensors, asserting loss AND gradient parity to 1e-5.

What is executed on the torch side is the real, unmodified reference method —
`AcceleratePPOModel.loss` (reference: trlx/model/accelerate_ppo_model.py:76-155)
and `AccelerateILQLModel.loss` (reference: trlx/model/accelerate_ilql_model.py:
50-156) — bound to a stub `self` whose `model` returns pre-made differentiable
tensors, so the full arithmetic (GAE loop, whiten, clipped pg/vf, double-Q TD,
expectile-V, CQL, AWAC) runs exactly as shipped.

Documented deviations (SURVEY.md §7 do-not-reproduce list), and how each is
handled here:

1. Advantage whitening over padding. The reference whitens advantages over the
   FULL padded [b, R] tensor (trlx/model/accelerate_ppo_model.py:100 →
   trlx/utils/modeling.py:5-11), so padded zeros pollute mean/var on ragged
   batches; this repo whitens over valid tokens only (masked_whiten). Full-mask
   cases therefore assert parity against the VERBATIM reference; ragged cases
   assert parity against the reference with its `whiten` monkeypatched to the
   mask-aware version ("corrected reference"), and additionally check that the
   verbatim/corrected outputs genuinely differ (i.e. the deviation is real and
   deliberate, not untested).
2. Value indexing off-by-one. The reference stores rollout V at positions
   [P-1, P+R-1) but its loss reads vpred at [P, P+R)
   (trlx/orchestrator/ppo_orchestrator.py:94-96 vs
   trlx/model/accelerate_ppo_model.py:120). That is an orchestrator-side slice
   choice, not loss arithmetic; both sides here are fed the same [b, R] slices
   so the loss math itself is compared apples-to-apples.
3. Terminal score placement (kl_penalty_rewards): the reference adds the score
   at column R-1 even when the row terminated early
   (trlx/orchestrator/ppo_orchestrator.py:101-104); this repo adds it at the
   last VALID token. Parity is asserted on full-length rows where the two
   agree, and the ragged deviation is asserted explicitly.
"""

import importlib
import importlib.machinery
import os
import sys
import types
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from trlx_tpu.ops.ilql_loss import ilql_loss
from trlx_tpu.ops.modeling import logprobs_from_logits
from trlx_tpu.ops.rl_losses import kl_penalty_rewards, ppo_loss

REFERENCE_ROOT = "/root/reference"

if not os.path.isdir(os.path.join(REFERENCE_ROOT, "trlx")):
    pytest.skip(
        f"reference checkout not present at {REFERENCE_ROOT}/trlx — parity "
        "asserts against the reference's own torch loss code, so without the "
        "checkout there is nothing to compare to",
        allow_module_level=True,
    )

_ref_cache = {}


def _reference_modules():
    """Import the reference's trainer modules with stubs for deps absent from
    this image (deepspeed, wandb, torchtyping). The stubs only satisfy import
    statements; none of their attributes participate in the loss arithmetic."""
    if _ref_cache:
        return _ref_cache["ppo"], _ref_cache["ilql"]
    if REFERENCE_ROOT not in sys.path:
        sys.path.insert(0, REFERENCE_ROOT)
    inserted = []
    for name in ("deepspeed", "wandb", "torchtyping"):
        if name in sys.modules:
            continue
        m = types.ModuleType(name)
        m.__spec__ = importlib.machinery.ModuleSpec(name, None)
        sys.modules[name] = m
        inserted.append(name)
    # Only flesh out modules WE inserted — if a real wandb/deepspeed is
    # installed and already imported, it must not be clobbered.
    if "deepspeed" in inserted:
        sys.modules["deepspeed"].comm = SimpleNamespace(get_rank=lambda: 0)
        sys.modules["deepspeed"].zero = SimpleNamespace()
    if "wandb" in inserted:
        sys.modules["wandb"].Histogram = object
        sys.modules["wandb"].Table = object
    if "torchtyping" in inserted:

        class _TensorType:
            def __class_getitem__(cls, item):
                return cls

        sys.modules["torchtyping"].TensorType = _TensorType
    try:
        _ref_cache["ppo"] = importlib.import_module("trlx.model.accelerate_ppo_model")
        _ref_cache["ilql"] = importlib.import_module("trlx.model.accelerate_ilql_model")
    finally:
        # Un-stub: the imported reference modules keep their direct references,
        # but a later bare `import wandb` elsewhere in this pytest process must
        # fail with ImportError again (trlx_tpu/utils/logging.py gates on that),
        # not resolve to an attribute-less stub.
        for name in inserted:
            sys.modules.pop(name, None)
    return _ref_cache["ppo"], _ref_cache["ilql"]


PAD = 0  # pad_token_id; valid tokens drawn from [1, V)

PPO_HP = dict(gamma=0.99, lam=0.95, cliprange=0.2, cliprange_value=0.2, vf_coef=1.0)


def _make_ppo_case(seed, b, P, R, V, lengths=None):
    """Synthetic rollout batch. lengths[i] = valid response length of row i
    (None → all full). Padded tails hold zeros / PAD ids exactly as the
    reference's pad_sequence collation produces."""
    rng = np.random.default_rng(seed)
    queries = rng.integers(1, V, size=(b, P)).astype(np.int64)
    responses = rng.integers(1, V, size=(b, R)).astype(np.int64)
    old_logprobs = (rng.normal(size=(b, R)) * 0.3).astype(np.float32)
    old_values = rng.normal(size=(b, R)).astype(np.float32)
    rewards = rng.normal(size=(b, R)).astype(np.float32)
    mask = np.ones((b, R), np.float32)
    if lengths is not None:
        for i, L in enumerate(lengths):
            responses[i, L:] = PAD
            old_logprobs[i, L:] = 0.0
            old_values[i, L:] = 0.0
            rewards[i, L:] = 0.0
            mask[i, L:] = 0.0
    logits = (rng.normal(size=(b, P + R, V)) * 0.7).astype(np.float32)
    vpred_full = rng.normal(size=(b, P + R)).astype(np.float32)
    return dict(
        queries=queries,
        responses=responses,
        old_logprobs=old_logprobs,
        old_values=old_values,
        rewards=rewards,
        mask=mask,
        logits=logits,
        vpred_full=vpred_full,
    )


def _reference_ppo(case, corrected_whiten=False):
    """Run the reference's real `AcceleratePPOModel.loss` on the case; returns
    (loss, grad_logits, grad_vpred_full) as numpy."""
    ref_ppo, _ = _reference_modules()
    logits_t = torch.tensor(case["logits"], requires_grad=True)
    vpred_t = torch.tensor(case["vpred_full"], requires_grad=True)

    model = object.__new__(ref_ppo.AcceleratePPOModel)
    model.accelerator = SimpleNamespace(device="cpu")
    model.config = SimpleNamespace(method=SimpleNamespace(**PPO_HP))
    model.tokenizer = SimpleNamespace(pad_token_id=PAD)
    model.model = lambda tokens, attention_mask, position_ids=None: (logits_t, None, vpred_t)

    batch = SimpleNamespace(
        query_tensors=torch.tensor(case["queries"]),
        response_tensors=torch.tensor(case["responses"]),
        logprobs=torch.tensor(case["old_logprobs"]),
        values=torch.tensor(case["old_values"]),
        rewards=torch.tensor(case["rewards"]),
    )

    saved_whiten = ref_ppo.whiten
    if corrected_whiten:
        mask_t = torch.tensor(case["mask"])

        def masked_whiten_torch(adv):
            n = mask_t.sum()
            mean = (adv * mask_t).sum() / n
            var = ((adv - mean).pow(2) * mask_t).sum() / (n - 1)  # ddof=1 = torch.var
            return (adv - mean) * torch.rsqrt(var + 1e-8) * mask_t

        ref_ppo.whiten = masked_whiten_torch
    try:
        loss, _stats = ref_ppo.AcceleratePPOModel.loss(model, batch)
    finally:
        ref_ppo.whiten = saved_whiten
    loss.backward()
    return (
        float(loss.detach()),
        logits_t.grad.numpy().copy(),
        vpred_t.grad.numpy().copy(),
    )


def _ours_ppo(case):
    """This repo's ppo_loss through the same logits→logprobs composition the
    reference uses, so gradients are comparable at the logits leaf."""
    R = case["responses"].shape[1]
    tokens = jnp.asarray(np.concatenate([case["queries"], case["responses"]], axis=1))
    old_logprobs = jnp.asarray(case["old_logprobs"])
    old_values = jnp.asarray(case["old_values"])
    rewards = jnp.asarray(case["rewards"])
    mask = jnp.asarray(case["mask"])

    def loss_fn(logits, vpred_full):
        lp = logprobs_from_logits(logits[:, :-1], tokens[:, 1:])[:, -R:]
        vp = vpred_full[:, -R:]
        loss, _ = ppo_loss(lp, vp, old_logprobs, old_values, rewards, mask, **PPO_HP)
        return loss

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
        jnp.asarray(case["logits"]), jnp.asarray(case["vpred_full"])
    )
    return float(loss), np.asarray(grads[0]), np.asarray(grads[1])


@pytest.mark.parametrize(
    "seed,b,P,R,V",
    [(0, 4, 5, 8, 13), (1, 2, 3, 16, 29), (2, 6, 7, 6, 11)],
)
def test_ppo_loss_parity_full_mask(seed, b, P, R, V):
    """Full-length responses: VERBATIM reference parity — loss and both grads."""
    case = _make_ppo_case(seed, b, P, R, V)
    ref_loss, ref_gl, ref_gv = _reference_ppo(case, corrected_whiten=False)
    our_loss, our_gl, our_gv = _ours_ppo(case)
    np.testing.assert_allclose(our_loss, ref_loss, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(our_gl, ref_gl, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(our_gv, ref_gv, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize(
    "seed,b,P,R,V,lengths",
    [
        (3, 4, 5, 8, 13, [8, 5, 3, 1]),
        (4, 3, 4, 12, 17, [12, 7, 2]),
        (5, 5, 2, 6, 11, [6, 6, 4, 3, 5]),
    ],
)
def test_ppo_loss_parity_ragged(seed, b, P, R, V, lengths):
    """Ragged tails: parity vs the reference with mask-aware whitening (the
    corrected form — see module docstring deviation #1), and evidence that the
    verbatim form actually differs (so the deviation is real)."""
    case = _make_ppo_case(seed, b, P, R, V, lengths=lengths)
    ref_loss, ref_gl, ref_gv = _reference_ppo(case, corrected_whiten=True)
    our_loss, our_gl, our_gv = _ours_ppo(case)
    np.testing.assert_allclose(our_loss, ref_loss, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(our_gl, ref_gl, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(our_gv, ref_gv, rtol=1e-5, atol=1e-5)

    verbatim_loss, _, _ = _reference_ppo(case, corrected_whiten=False)
    assert abs(verbatim_loss - ref_loss) > 1e-7, (
        "verbatim and corrected whitening agreed on a ragged batch — the "
        "documented deviation would be vacuous"
    )


def test_kl_penalty_rewards_parity_full_length():
    """kl_penalty_rewards vs the reference's reward assembly
    (trlx/orchestrator/ppo_orchestrator.py:101-104) on full-length rows, where
    the terminal-score placement conventions coincide."""
    rng = np.random.default_rng(6)
    b, R = 4, 9
    lp = rng.normal(size=(b, R)).astype(np.float32)
    rlp = rng.normal(size=(b, R)).astype(np.float32)
    scores = rng.normal(size=(b,)).astype(np.float32)
    kl_coef = 0.13

    # reference arithmetic, verbatim:
    lp_t, rlp_t = torch.tensor(lp), torch.tensor(rlp)
    kls_t = lp_t - rlp_t
    rewards_t = -kl_coef * kls_t
    rewards_t[:, -1] += torch.tensor(scores)

    mask = jnp.ones((b, R), jnp.float32)
    rewards_j, kl_j = kl_penalty_rewards(
        jnp.asarray(lp), jnp.asarray(rlp), mask, jnp.asarray(scores), jnp.asarray(kl_coef)
    )
    np.testing.assert_allclose(np.asarray(rewards_j), rewards_t.numpy(), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(kl_j), kls_t.numpy(), rtol=1e-6, atol=1e-6)


def test_kl_penalty_rewards_terminal_deviation_ragged():
    """Deviation #3 stated as an assertion: on an early-terminated row the
    reference puts the score on the padded final column (masked out of its
    loss); this repo puts it on the last valid token."""
    b, R, L = 1, 6, 3
    lp = jnp.zeros((b, R))
    mask = jnp.zeros((b, R)).at[0, :L].set(1.0)
    scores = jnp.asarray([5.0])
    rewards, _ = kl_penalty_rewards(lp, lp, mask, scores, jnp.asarray(0.1))
    rewards = np.asarray(rewards)
    assert rewards[0, L - 1] == 5.0  # ours: last valid token
    assert rewards[0, R - 1] == 0.0  # reference would have put it here


# ---------------------------------------------------------------------------
# ILQL


ILQL_HP = dict(gamma=0.99, tau=0.7, cql_scale=0.1, awac_scale=1.0)


def _make_ilql_case(seed, b, T, A, V, n_actions=None, two_qs=True):
    """Synthetic ILQL batch. n_actions[i] = valid actions of row i (None → A).
    Index/done/reward padding follows the reference collate (pad_sequence → 0)."""
    rng = np.random.default_rng(seed)
    input_ids = rng.integers(1, V, size=(b, T)).astype(np.int64)
    attention_mask = np.ones((b, T), np.int64)
    actions_ixs = np.zeros((b, A), np.int64)
    dones = np.zeros((b, A + 1), np.int64)
    rewards = np.zeros((b, A), np.float32)
    for i in range(b):
        n = A if n_actions is None else n_actions[i]
        # distinct, increasing indices into input_ids[:, 1:] (length T-1)
        ixs = np.sort(rng.choice(T - 1, size=n, replace=False))
        actions_ixs[i, :n] = ixs
        dones[i, : n + 1] = 1
        dones[i, n] = 0  # terminal state
        rewards[i, :n] = rng.normal(size=n)
    n_heads = 2 if two_qs else 1
    qs = [(rng.normal(size=(b, A, V)) * 0.5).astype(np.float32) for _ in range(n_heads)]
    tqs = [(rng.normal(size=(b, A, V)) * 0.5).astype(np.float32) for _ in range(n_heads)]
    vs = rng.normal(size=(b, A + 1, 1)).astype(np.float32)
    logits = (rng.normal(size=(b, T, V)) * 0.7).astype(np.float32)
    return dict(
        input_ids=input_ids,
        attention_mask=attention_mask,
        actions_ixs=actions_ixs,
        dones=dones,
        rewards=rewards,
        qs=qs,
        tqs=tqs,
        vs=vs,
        logits=logits,
        two_qs=two_qs,
    )


def _reference_ilql(case):
    """Run the reference's real `AccelerateILQLModel.loss`; returns
    (loss, grad_logits, [grad_q...], grad_vs)."""
    _, ref_ilql = _reference_modules()
    logits_t = torch.tensor(case["logits"], requires_grad=True)
    qs_t = [torch.tensor(q, requires_grad=True) for q in case["qs"]]
    tqs_t = [torch.tensor(q) for q in case["tqs"]]
    vs_t = torch.tensor(case["vs"], requires_grad=True)

    two_qs = case["two_qs"]
    fwd_qs = tuple(qs_t) if two_qs else qs_t[0]
    fwd_tqs = tuple(tqs_t) if two_qs else tqs_t[0]

    model = object.__new__(ref_ilql.AccelerateILQLModel)
    model.accelerator = SimpleNamespace(device="cpu")
    model.params = SimpleNamespace(two_qs=two_qs, **ILQL_HP)
    model.model = lambda **kw: (logits_t, fwd_qs, fwd_tqs, vs_t, None)

    A = case["actions_ixs"].shape[1]
    batch = SimpleNamespace(
        input_ids=torch.tensor(case["input_ids"]),
        attention_mask=torch.tensor(case["attention_mask"]),
        rewards=torch.tensor(case["rewards"]),
        states_ixs=torch.zeros((case["input_ids"].shape[0], A + 1), dtype=torch.long),
        actions_ixs=torch.tensor(case["actions_ixs"]),
        dones=torch.tensor(case["dones"]),
    )
    loss, _stats = ref_ilql.AccelerateILQLModel.loss(model, batch)
    loss.backward()
    return (
        float(loss.detach()),
        logits_t.grad.numpy().copy(),
        [q.grad.numpy().copy() for q in qs_t],
        vs_t.grad.numpy().copy(),
    )


def _ours_ilql(case):
    input_ids = jnp.asarray(case["input_ids"])
    attention_mask = jnp.asarray(case["attention_mask"])
    actions_ixs = jnp.asarray(case["actions_ixs"])
    rewards = jnp.asarray(case["rewards"])
    dones = jnp.asarray(case["dones"])
    tqs = tuple(jnp.asarray(q) for q in case["tqs"])

    def loss_fn(logits, qs, vs3):
        loss, _ = ilql_loss(
            logits,
            tuple(qs),
            tqs,
            vs3[..., 0],
            input_ids,
            attention_mask,
            actions_ixs,
            rewards,
            dones,
            **ILQL_HP,
        )
        return loss

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
        jnp.asarray(case["logits"]),
        [jnp.asarray(q) for q in case["qs"]],
        jnp.asarray(case["vs"]),
    )
    return (
        float(loss),
        np.asarray(grads[0]),
        [np.asarray(g) for g in grads[1]],
        np.asarray(grads[2]),
    )


@pytest.mark.parametrize(
    "seed,b,T,A,V,n_actions,two_qs",
    [
        (10, 4, 10, 6, 13, None, True),          # full actions, double-Q
        (11, 3, 12, 8, 17, [8, 5, 2], True),     # ragged actions, double-Q
        (12, 2, 9, 5, 11, [5, 3], False),        # ragged, single-Q
        (13, 5, 8, 4, 23, [4, 4, 2, 1, 3], True),
    ],
)
def test_ilql_loss_parity(seed, b, T, A, V, n_actions, two_qs):
    """Loss + gradients at every differentiable leaf (logits, each online Q
    head, V head) match the reference's own torch implementation to 1e-5."""
    case = _make_ilql_case(seed, b, T, A, V, n_actions=n_actions, two_qs=two_qs)
    ref_loss, ref_gl, ref_gq, ref_gv = _reference_ilql(case)
    our_loss, our_gl, our_gq, our_gv = _ours_ilql(case)
    np.testing.assert_allclose(our_loss, ref_loss, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(our_gl, ref_gl, rtol=1e-5, atol=1e-5)
    for og, rg in zip(our_gq, ref_gq):
        np.testing.assert_allclose(og, rg, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(our_gv, ref_gv, rtol=1e-5, atol=1e-5)
