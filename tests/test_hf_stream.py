"""Streamed (per-tensor, torch-free) safetensors loading: the pod-scale load
path. Covers the exact on-disk format a real 6B/20B download has — multiple
shards + model.safetensors.index.json, fp16/bf16 tensors — plus the
O(largest-tensor) memory discipline that replaces the capability the
reference gets from DeepSpeed zero3_init
(reference: trlx/model/nn/ilql_models.py:39-45)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from trlx_tpu.models import TransformerLM

pytestmark = pytest.mark.slow  # excluded from `make test-fast` (see conftest)
from trlx_tpu.models.hf_import import (
    LazySafetensors,
    lm_config_from_hf,
    load_hf_trunk,
    make_stream_put,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _save_sharded_mixed_dtype(model, out_dir, n_shards=3):
    """Write the model's state dict as n_shards safetensors files + an
    index.json — the exact layout of a real multi-shard HF download — with
    mixed tensor dtypes (fp16 / bf16 / fp32 round-robin by shard)."""
    from safetensors.torch import save_file

    os.makedirs(out_dir, exist_ok=True)
    sd = {k: v.detach().clone() for k, v in model.state_dict().items()}
    keys = list(sd)
    dtypes = [torch.float16, torch.bfloat16, torch.float32]
    weight_map = {}
    for s in range(n_shards):
        fname = f"model-{s + 1:05d}-of-{n_shards:05d}.safetensors"
        shard = {}
        for k in keys[s::n_shards]:
            shard[k] = sd[k].to(dtypes[s % len(dtypes)]).contiguous()
            weight_map[k] = fname
        save_file(shard, os.path.join(out_dir, fname))
    with open(os.path.join(out_dir, "model.safetensors.index.json"), "w") as f:
        json.dump({"metadata": {}, "weight_map": weight_map}, f)
    return weight_map


def test_sharded_mixed_dtype_load_logits_parity(tmp_path):
    """3-shard fp16/bf16/fp32 checkpoint → streamed load → logits match a
    torch forward over the SAME rounded weights to fp32 tolerance."""
    config = transformers.GPTJConfig(
        n_layer=3, n_head=4, n_embd=64, vocab_size=128, n_positions=64, rotary_dim=8
    )
    hf_model = transformers.GPTJForCausalLM(config)
    ckpt = str(tmp_path / "ckpt")
    _save_sharded_mixed_dtype(hf_model, ckpt, n_shards=3)
    assert os.path.exists(os.path.join(ckpt, "model.safetensors.index.json"))

    # torch reference: reload the rounded weights fp32 (load_state_dict casts)
    sd = LazySafetensors(ckpt)
    rounded = {k: torch.as_tensor(np.asarray(sd[k]).astype(np.float32)) for k in sd.keys()}
    hf_model.load_state_dict(rounded)
    hf_model.eval()

    cfg = lm_config_from_hf(hf_model.config, dtype="float32", param_dtype="float32")
    trunk = load_hf_trunk(ckpt, cfg, put=lambda path, arr: jnp.asarray(np.asarray(arr, np.float32)))

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 12))
    with torch.no_grad():
        ref = hf_model(torch.as_tensor(ids)).logits.numpy()
    model = TransformerLM(cfg)
    out = model.apply({"params": trunk}, jnp.asarray(ids), jnp.ones(ids.shape, jnp.int32))
    np.testing.assert_allclose(np.asarray(out["logits"], np.float32), ref, atol=2e-4, rtol=1e-3)


def test_single_file_safetensors_load(tmp_path):
    """save_pretrained's single model.safetensors file (no index) streams
    through the same lazy path."""
    config = transformers.GPT2Config(n_layer=2, n_head=4, n_embd=64, vocab_size=128, n_positions=64)
    hf_model = transformers.GPT2LMHeadModel(config)
    ckpt = str(tmp_path / "single")
    hf_model.save_pretrained(ckpt, safe_serialization=True)
    assert os.path.exists(os.path.join(ckpt, "model.safetensors"))

    hf_model.eval()
    cfg = lm_config_from_hf(hf_model.config, dtype="float32", param_dtype="float32")
    trunk = load_hf_trunk(ckpt, cfg)

    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 10))
    with torch.no_grad():
        ref = hf_model(torch.as_tensor(ids)).logits.numpy()
    model = TransformerLM(cfg)
    out = model.apply({"params": trunk}, jnp.asarray(ids), jnp.ones(ids.shape, jnp.int32))
    np.testing.assert_allclose(np.asarray(out["logits"], np.float32), ref, atol=2e-4, rtol=1e-3)


def test_export_roundtrip_through_streamed_loader(tmp_path):
    """hf_export's safetensors output re-imports through the streamed loader
    bit-exactly (fp32): our export → our lazy import closes the loop."""
    from trlx_tpu.models.hf_export import export_hf
    from trlx_tpu.models.lm import LMConfig
    import jax

    cfg = LMConfig.from_dict(
        dict(
            vocab_size=97, n_layer=2, n_head=4, d_model=32, max_position=64,
            pos_type="rotary", rotary_dim=8, parallel_residual=True,
            use_parallel_ln=False, fused_qkv=False, qkv_bias=False,
            out_bias=False, tie_word_embeddings=False, activation="gelu_new",
            extra={"lm_head_bias": True},
        )
    )
    model = TransformerLM(cfg)
    ids = jnp.zeros((1, 4), jnp.int32)
    # bare TransformerLM: its params ARE the trunk (no "transformer" wrapper)
    params = model.init(jax.random.PRNGKey(0), ids, jnp.ones_like(ids))["params"]
    out_dir = str(tmp_path / "export")
    export_hf(params, cfg, out_dir, family="gptj")

    trunk = load_hf_trunk(out_dir, cfg)
    ref_leaves, ref_tree = jax.tree_util.tree_flatten(params)
    got_leaves, got_tree = jax.tree_util.tree_flatten(trunk)
    assert ref_tree == got_tree
    for a, b in zip(ref_leaves, got_leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_stream_put_shards_on_mesh(tmp_path):
    """make_stream_put places each tensor against the lm partition rules on
    the live mesh as it is converted — the tensors arrive sharded, never
    resident as a full host tree."""
    import jax
    from trlx_tpu.parallel.mesh import AXIS_FSDP, AXIS_TP, make_mesh, peek_mesh, set_mesh

    config = transformers.GPT2Config(n_layer=2, n_head=4, n_embd=64, vocab_size=128, n_positions=64)
    hf_model = transformers.GPT2LMHeadModel(config)
    ckpt = str(tmp_path / "mesh_ckpt")
    hf_model.save_pretrained(ckpt, safe_serialization=True)

    prior = peek_mesh()  # restore EXACT prior state: load_or_init_params
    mesh = make_mesh((2, 2, 2, 1))  # branches on peek_mesh(), so a leaked
    set_mesh(mesh)  # mesh would change later tests' init path
    try:
        cfg = lm_config_from_hf(hf_model.config, dtype="float32", param_dtype="float32")
        model = TransformerLM(cfg)
        dummy = jnp.zeros((1, 2), jnp.int32)
        init = model.init(jax.random.PRNGKey(0), dummy, jnp.ones_like(dummy))["params"]
        trunk = load_hf_trunk(ckpt, cfg, put=make_stream_put(init))
        qkv = trunk["h_0"]["attn"]["c_qkv"]["kernel"]
        assert isinstance(qkv, jax.Array)
        spec = qkv.sharding.spec  # column-parallel: [d_model(fsdp), 3d(tp)]
        assert tuple(spec) == (AXIS_FSDP, AXIS_TP)
        ln = trunk["h_0"]["ln_1"]["scale"]
        assert tuple(ln.sharding.spec) in ((), (None,))  # replicated
    finally:
        set_mesh(prior)


MEMORY_PROBE = r"""
import json, os, sys, tracemalloc
import numpy as np
sys.path.insert(0, sys.argv[1])
ckpt = sys.argv[2]

from trlx_tpu.models.hf_import import load_hf_trunk
from trlx_tpu.models.lm import LMConfig

with open(os.path.join(ckpt, "lm_config.json")) as f:
    cfg = LMConfig.from_dict(json.load(f))

seen = {"bytes": 0, "count": 0, "largest": 0}

def discard_put(path, arr):
    # emulates the pod path: the tensor leaves host RAM for device HBM
    seen["bytes"] += arr.nbytes
    seen["count"] += 1
    seen["largest"] = max(seen["largest"], arr.nbytes)
    return np.zeros((), np.float32)

tracemalloc.start()
load_hf_trunk(ckpt, cfg, put=discard_put)
_, peak = tracemalloc.get_traced_memory()
tracemalloc.stop()
print(json.dumps({"peak": peak, **seen}))
"""


def test_streamed_load_memory_is_o_largest_tensor(tmp_path):
    """Peak heap during a multi-shard load stays O(largest tensor) — NOT
    O(model). A ~90 MB 4-shard checkpoint with a 16 MB largest tensor must
    load (tensors discarded as a stand-in for device placement) within ~3×
    the largest tensor of traced allocations (transpose + cast temporaries)."""
    from safetensors.numpy import save_file

    # gpt2-family synthetic arch: wte [8192, 512] fp32 = 16 MB is the largest
    n_layer, d, vocab = 8, 512, 8192
    cfg_dict = dict(
        vocab_size=vocab, n_layer=n_layer, n_head=8, d_model=d,
        max_position=128, pos_type="learned", parallel_residual=False,
        fused_qkv=True, qkv_bias=True, tie_word_embeddings=True,
        activation="gelu_new",
    )
    ckpt = str(tmp_path / "big")
    os.makedirs(ckpt)
    rng = np.random.default_rng(0)

    def t(*shape):
        return rng.standard_normal(size=shape).astype(np.float32)

    weight_map = {}
    shard, shard_id, shard_bytes = {}, 1, 0

    def flush(final=False):
        nonlocal shard, shard_id, shard_bytes
        if not shard:
            return
        fname = f"model-{shard_id:05d}.safetensors"
        save_file(shard, os.path.join(ckpt, fname))
        for k in shard:
            weight_map[k] = fname
        shard, shard_bytes = {}, 0
        shard_id += 1

    def add(key, arr):
        nonlocal shard_bytes
        shard[key] = arr
        shard_bytes += arr.nbytes
        if shard_bytes > 24e6:
            flush()

    add("transformer.wte.weight", t(vocab, d))
    add("transformer.wpe.weight", t(128, d))
    for i in range(n_layer):
        h = f"transformer.h.{i}"
        add(f"{h}.ln_1.weight", t(d)); add(f"{h}.ln_1.bias", t(d))
        add(f"{h}.ln_2.weight", t(d)); add(f"{h}.ln_2.bias", t(d))
        add(f"{h}.attn.c_attn.weight", t(d, 3 * d)); add(f"{h}.attn.c_attn.bias", t(3 * d))
        add(f"{h}.attn.c_proj.weight", t(d, d)); add(f"{h}.attn.c_proj.bias", t(d))
        add(f"{h}.mlp.c_fc.weight", t(d, 4 * d)); add(f"{h}.mlp.c_fc.bias", t(4 * d))
        add(f"{h}.mlp.c_proj.weight", t(4 * d, d)); add(f"{h}.mlp.c_proj.bias", t(d))
    add("transformer.ln_f.weight", t(d)); add("transformer.ln_f.bias", t(d))
    flush(final=True)
    with open(os.path.join(ckpt, "model.safetensors.index.json"), "w") as f:
        json.dump({"metadata": {}, "weight_map": weight_map}, f)
    with open(os.path.join(ckpt, "lm_config.json"), "w") as f:
        json.dump(cfg_dict, f)

    probe = str(tmp_path / "probe.py")
    with open(probe, "w") as f:
        f.write(MEMORY_PROBE)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, probe, REPO, ckpt],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rep = json.loads(out.stdout.strip().splitlines()[-1])
    largest = rep["largest"]
    total = rep["bytes"]
    assert largest == vocab * d * 4  # wte is the largest tensor
    assert total > 5 * largest  # the model is much bigger than one tensor
    # The streaming claim: peak heap ~ a few transpose/cast temporaries of
    # ONE tensor, not the whole model.
    assert rep["peak"] < 3 * largest + 8e6, (
        f"peak heap {rep['peak']/1e6:.1f} MB vs largest tensor {largest/1e6:.1f} MB "
        f"(model total {total/1e6:.1f} MB) — load is not streaming"
    )
