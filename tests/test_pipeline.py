"""BatchLoader valid-count, pad_ragged edge cases, truncation conventions."""

import numpy as np

from trlx_tpu.native import pad_ragged
from trlx_tpu.pipeline import BatchLoader


def test_batchloader_reports_valid_count():
    """14 items at batch 16 → one batch padded by wrap-around, n_valid == 14."""
    data = np.arange(14) * 10

    def collate(ixs):
        return data[ixs]

    loader = BatchLoader(14, 16, collate, shuffle=False, drop_last=False)
    batches = list(loader.iter_with_valid())
    assert len(batches) == 1
    batch, n_valid = batches[0]
    assert n_valid == 14
    assert batch.shape == (16,)
    # rows [n_valid:] are wrap-around duplicates of the head of the order
    assert batch[14] == data[0] and batch[15] == data[1]


def test_batchloader_valid_count_multiple_batches():
    data = np.arange(20)

    def collate(ixs):
        return data[ixs]

    loader = BatchLoader(20, 8, collate, shuffle=False, drop_last=False)
    batches = list(loader.iter_with_valid())
    assert [nv for _, nv in batches] == [8, 8, 4]
    assert all(b.shape == (8,) for b, _ in batches)
    # plain iteration drops the counts but yields identical batches
    assert all(
        np.array_equal(a, b)
        for a, (b, _) in zip(loader, BatchLoader(20, 8, collate, drop_last=False).iter_with_valid())
    )


def test_batchloader_drop_last_has_no_partial_batches():
    loader = BatchLoader(14, 16, lambda ixs: ixs, shuffle=False, drop_last=True)
    assert list(loader) == []


def test_pad_ragged_normalizes_non_1d_rows():
    """Rows arriving as [n, 1] column vectors (or nested lists) must not
    corrupt the flat-buffer offsets in the native path."""
    rows = [np.arange(3).reshape(3, 1), np.arange(5).reshape(1, 5), [[7], [8]]]
    ids, mask = pad_ragged(rows, max_len=6, pad_id=-1, left_pad=False, keep_last=False)
    np.testing.assert_array_equal(ids[0], [0, 1, 2, -1, -1, -1])
    np.testing.assert_array_equal(ids[1], [0, 1, 2, 3, 4, -1])
    np.testing.assert_array_equal(ids[2], [7, 8, -1, -1, -1, -1])
    np.testing.assert_array_equal(mask.sum(1), [3, 5, 2])


class CharTokenizer:
    """Minimal tokenizer stand-in: one token per character (no downloads)."""

    bos_token_id = 1
    eos_token_id = 0
    pad_token_id = 0

    def __call__(self, text, add_special_tokens=False):
        return {"input_ids": [ord(c) % 256 for c in text]}

    def batch_decode(self, tokens, skip_special_tokens=True):
        return ["".join(chr(int(t)) for t in row if t > 1) for row in tokens]


def test_tokenize_truncation_keeps_trailing_tokens():
    """Framework-wide prompt rule: overlong prompts keep the TRAILING tokens
    (the most recent context), matching PromptPipeline's keep_last."""
    from trlx_tpu.trainer.base import JaxBaseTrainer

    class Host:
        tokenizer = CharTokenizer()

        class config:
            class train:
                seq_length = 4

    text = "abcdefgh"
    ids = JaxBaseTrainer.tokenize(Host(), [text])[0]
    expected_tail = [ord(c) for c in "efgh"]
    assert list(ids) == expected_tail  # BOS itself truncated away: tail wins


def test_prompt_pipeline_truncates_keeping_tail():
    from trlx_tpu.pipeline.prompt_pipeline import PromptPipeline

    pipe = PromptPipeline(["abcdefgh"], tokenizer=CharTokenizer(), max_prompt_length=4)
    row = pipe[0]
    assert list(row["input_ids"]) == [ord(c) for c in "efgh"]
    assert list(row["attention_mask"]) == [1, 1, 1, 1]
