"""Unit tier for the disaggregated fleet transports (trlx_tpu/fleet).

Everything here runs in-process and fast: construction-time config
validation (the stray-knob error that replaced the RolloutProducer-era
mid-run raise), the on-disk path contract, bitwise npz round-trips for
both transports (episode stream AND weight broadcast), resume-safe
seq/ordinal numbering, the shared staleness-gate predicate, the new fault
kinds, and the effective-timeout resolution. The cross-process story —
parity through the stream, degradation ladders, host-failure drills —
lives in tests/test_fleet_disagg.py.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402,F401  (registers ml_dtypes via jax import)
from randomwalks import base_config  # noqa: E402
from trlx_tpu.fleet import (  # noqa: E402
    EpisodeStreamReader,
    EpisodeStreamTimeout,
    EpisodeStreamWriter,
    FleetPaths,
    WeightPublisher,
    WeightSubscriber,
    fleet_paths,
    put_leaves,
    resolve_role,
    role_timeouts,
    validate_fleet_config,
)
from trlx_tpu.fleet.topology import ROLE_ENV, read_jsonl_or_empty  # noqa: E402
from trlx_tpu.pipeline.overlap import staleness_gate_open  # noqa: E402
from trlx_tpu.pipeline.ppo_pipeline import PPORolloutStorage  # noqa: E402
from trlx_tpu.resilience.faults import FaultPlan  # noqa: E402


def _config(**train_overrides):
    config = base_config("ppo", 15, 8)
    for k, v in train_overrides.items():
        setattr(config.train, k, v)
    return config


# ------------------------------------------------- construction-time checks


def test_stray_fleet_knob_without_disaggregate_is_a_config_error():
    """Satellite 1: fleet knobs set while method.fleet_disaggregate is off
    must raise at validation (trainer construction), never mid-run."""
    config = _config(fleet_episode_timeout=30.0)
    with pytest.raises(ValueError, match="fleet_episode_timeout"):
        validate_fleet_config(config)
    with pytest.raises(ValueError, match="fleet_disaggregate"):
        validate_fleet_config(config)


def test_stray_role_env_without_disaggregate_is_a_config_error(monkeypatch):
    monkeypatch.setenv(ROLE_ENV, "rollout")
    with pytest.raises(ValueError, match=ROLE_ENV):
        validate_fleet_config(_config())


def test_no_fleet_config_validates_to_none(monkeypatch):
    monkeypatch.delenv(ROLE_ENV, raising=False)
    assert validate_fleet_config(_config()) is None


def test_role_resolution_env_wins_over_config(monkeypatch):
    config = _config(fleet_role="learner")
    config.method.fleet_disaggregate = True
    monkeypatch.delenv(ROLE_ENV, raising=False)
    assert resolve_role(config) == "learner"
    assert validate_fleet_config(config) == "learner"
    monkeypatch.setenv(ROLE_ENV, "rollout")
    assert resolve_role(config) == "rollout"
    assert validate_fleet_config(config) == "rollout"


def test_fleet_without_role_is_colocated(monkeypatch):
    monkeypatch.delenv(ROLE_ENV, raising=False)
    config = _config()
    config.method.fleet_disaggregate = True
    assert validate_fleet_config(config) == "colocated"


def test_unknown_role_rejected(monkeypatch):
    monkeypatch.delenv(ROLE_ENV, raising=False)
    config = _config(fleet_role="replayer")
    config.method.fleet_disaggregate = True
    with pytest.raises(ValueError, match="replayer"):
        validate_fleet_config(config)


def test_fleet_and_rollout_overlap_are_mutually_exclusive(monkeypatch):
    monkeypatch.delenv(ROLE_ENV, raising=False)
    config = _config()
    config.method.fleet_disaggregate = True
    config.method.rollout_overlap = True
    with pytest.raises(ValueError, match="mutually exclusive"):
        validate_fleet_config(config)


def test_trainer_constructor_rejects_stray_fleet_knobs(tmp_path):
    """The end-to-end form of satellite 1: the error surfaces from trainer
    construction inside trlx_tpu.train, before any training work."""
    from randomwalks import generate_random_walks

    _, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=15, max_length=8, n_walks=10, seed=1000
    )
    config = _config(fleet_dir=str(tmp_path / "fleet"), checkpoint_dir=str(tmp_path / "ckpt"))
    config.train.batch_size = 16
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    with pytest.raises(ValueError, match="fleet_dir"):
        trlx_tpu.train(
            reward_fn=reward_fn,
            prompts=[[1], [2]],
            eval_prompts=[[1]],
            metric_fn=metric_fn,
            config=config,
            logit_mask=logit_mask,
        )


# ------------------------------------------------------------ path contract


def test_fleet_paths_layout_and_abort(tmp_path):
    paths = FleetPaths(root=str(tmp_path / "fleet")).ensure()
    assert os.path.isdir(paths.episodes_dir)
    assert os.path.isdir(paths.weights_dir)
    assert os.path.isdir(paths.heartbeats_dir)
    assert paths.episode_file(3).endswith("batch_000003.npz")
    assert paths.weight_file(7).endswith("weights_00000007.npz")
    assert paths.read_abort() is None
    with open(paths.abort, "w") as f:
        f.write('{"reason": "compl')  # torn write mid-flight
    assert paths.read_abort() is None
    with open(paths.abort, "w") as f:
        json.dump({"reason": "complete"}, f)
    assert paths.read_abort()["reason"] == "complete"


def test_fleet_paths_default_root_is_under_checkpoint_dir(tmp_path):
    config = _config(checkpoint_dir=str(tmp_path / "ckpt"))
    assert fleet_paths(config.train).root == str(tmp_path / "ckpt" / "fleet")
    config = _config(fleet_dir=str(tmp_path / "shared"))
    assert fleet_paths(config.train).root == str(tmp_path / "shared")


def test_read_jsonl_or_empty_tolerates_absence_and_torn_tail(tmp_path):
    path = str(tmp_path / "log.jsonl")
    assert read_jsonl_or_empty(path) == []
    with open(path, "w") as f:
        f.write('{"seq": 0}\n{"seq": 1}\n{"seq": 2')  # torn tail
    assert [r["seq"] for r in read_jsonl_or_empty(path)] == [0, 1]


# ---------------------------------------------------------- episode stream


def _columns(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "query_tensors": rng.integers(0, 15, (n, 3)).astype(np.int32),
        "query_mask": np.ones((n, 3), np.int32),
        "response_tensors": rng.integers(0, 15, (n, 5)).astype(np.int32),
        "response_mask": np.ones((n, 5), np.int32),
        "logprobs": rng.standard_normal((n, 5)).astype(np.float32),
        "values": rng.standard_normal((n, 5)).astype(np.float32),
        "rewards": rng.standard_normal((n, 5)).astype(np.float32),
    }


def test_stream_roundtrip_is_bitwise_and_indexed(tmp_path):
    paths = FleetPaths(root=str(tmp_path)).ensure()
    writer = EpisodeStreamWriter(paths)
    cols = _columns()
    assert writer.append(cols, weight_version=12) == 0
    reader = EpisodeStreamReader(paths)
    rec = reader.poll(0)
    assert rec["n"] == 4 and rec["weight_version"] == 12
    got = reader.load(rec)
    assert set(got) == set(cols)
    for k in cols:
        assert got[k].dtype == cols[k].dtype
        assert np.array_equal(got[k], cols[k])


def test_stream_columns_rebuild_a_storage_bitwise(tmp_path):
    """The wire format IS PPORolloutStorage.columns(): round-tripping it
    through the stream and push_batch rebuilds an identical store —
    including the staleness column the fleet consumer appends."""
    store = PPORolloutStorage(pad_token_id=0, record_staleness=True)
    store.push_batch(_columns(seed=3))
    cols = store.columns()
    paths = FleetPaths(root=str(tmp_path)).ensure()
    EpisodeStreamWriter(paths).append(cols, weight_version=0)
    reader = EpisodeStreamReader(paths)
    rebuilt = PPORolloutStorage(pad_token_id=0, record_staleness=True)
    rebuilt.push_batch(reader.load(reader.poll(0)))
    got = rebuilt.columns()
    assert set(got) == set(cols)
    for k in cols:
        assert np.array_equal(got[k], cols[k])


def test_stream_writer_resumes_seq_numbering(tmp_path):
    paths = FleetPaths(root=str(tmp_path)).ensure()
    writer = EpisodeStreamWriter(paths)
    writer.append(_columns(), weight_version=0)
    writer.append(_columns(), weight_version=0)
    # A restarted worker continues from the index, never clobbers.
    assert EpisodeStreamWriter(paths).next_seq == 2


def test_stream_reader_queued_from_cursor(tmp_path):
    paths = FleetPaths(root=str(tmp_path)).ensure()
    writer = EpisodeStreamWriter(paths)
    for _ in range(4):
        writer.append(_columns(), weight_version=0)
    reader = EpisodeStreamReader(paths)
    assert [r["seq"] for r in reader.queued_from(2)] == [2, 3]
    assert reader.queued_from(9) == []


def test_stream_wait_times_out_through_retry_wrapper(tmp_path):
    paths = FleetPaths(root=str(tmp_path)).ensure()
    reader = EpisodeStreamReader(paths)
    start = time.monotonic()
    with pytest.raises(EpisodeStreamTimeout, match="seq=5"):
        reader.wait(5, timeout=0.15, retries=1, backoff=0.01)
    # 2 attempts x ~0.15s + backoff, bounded — no hang, no watchdog thread.
    assert time.monotonic() - start < 5.0


def test_stream_wait_returns_when_batch_lands(tmp_path):
    paths = FleetPaths(root=str(tmp_path)).ensure()
    EpisodeStreamWriter(paths).append(_columns(), weight_version=7)
    rec = EpisodeStreamReader(paths).wait(0, timeout=1.0, retries=0, backoff=0.0)
    assert rec["weight_version"] == 7


# --------------------------------------------------------- weight broadcast


def _params():
    import jax.numpy as jnp

    return {
        "w": jnp.arange(12, dtype=jnp.bfloat16).reshape(3, 4) / 7,
        "b": jnp.linspace(-1.0, 1.0, 5, dtype=jnp.float32),
    }


def test_broadcast_roundtrip_is_bitwise_even_for_bfloat16(tmp_path):
    paths = FleetPaths(root=str(tmp_path)).ensure()
    params = _params()
    pub = WeightPublisher(paths)
    assert pub.publish(params, version=4, meta={"kl_coef": 0.125}) == 0
    sub = WeightSubscriber(paths)
    latest = sub.latest()
    assert latest["ordinal"] == 0 and latest["version"] == 4
    # Lockstep scalars ride the pointer with the weights (the adaptive KL
    # coefficient shapes rollout rewards exactly like params shape tokens).
    assert latest["kl_coef"] == 0.125
    got = put_leaves(params, sub.load(latest))
    for k in params:
        assert got[k].dtype == params[k].dtype
        raw_a = np.asarray(got[k]).view(np.uint8)
        raw_b = np.asarray(params[k]).view(np.uint8)
        assert np.array_equal(raw_a, raw_b), f"leaf {k} not bitwise"


def test_put_leaves_rejects_mismatched_trees(tmp_path):
    paths = FleetPaths(root=str(tmp_path)).ensure()
    params = _params()
    WeightPublisher(paths).publish(params, version=0)
    sub = WeightSubscriber(paths)
    leaves = sub.load(sub.latest())
    with pytest.raises(ValueError, match="leaf-count mismatch"):
        put_leaves({"w": params["w"]}, leaves)
    import jax.numpy as jnp

    wrong = {"w": params["w"], "b": jnp.zeros(9, jnp.float32)}
    with pytest.raises(ValueError, match="size mismatch"):
        put_leaves(wrong, leaves)


def test_broadcast_timeout_fault_skips_snapshot_but_logs_ordinal(tmp_path):
    paths = FleetPaths(root=str(tmp_path)).ensure()
    plan = FaultPlan.parse("broadcast_timeout@1")
    pub = WeightPublisher(paths, fault_plan=plan)
    params = _params()
    pub.publish(params, version=0)
    pub.publish(params, version=1)  # injected: no file, pointer stays put
    pub.publish(params, version=2)
    records = read_jsonl_or_empty(paths.broadcast_log)
    assert [r["status"] for r in records] == ["published", "injected_timeout", "published"]
    assert [r["ordinal"] for r in records] == [0, 1, 2]
    assert not os.path.exists(paths.weight_file(1))
    assert WeightSubscriber(paths).latest()["ordinal"] == 2
    assert [r["ordinal"] for r in pub.published()] == [0, 2]
    # Dense resume: injected ordinals still consumed a slot.
    assert WeightPublisher(paths).next_ordinal == 3


# ------------------------------------------------- gate / faults / timeouts


def test_staleness_gate_predicate_is_shared_and_exact():
    # seq - consumed <= S: the same predicate gates the in-process producer
    # (pipeline/overlap.py) and the disaggregated worker (fleet/runner.py).
    assert staleness_gate_open(0, 0, 0)
    assert not staleness_gate_open(1, 0, 0)
    assert staleness_gate_open(3, 1, 2)
    assert not staleness_gate_open(4, 1, 2)
    assert staleness_gate_open(5, 5, -3)  # negative caps clamp to 0


def test_fault_plan_parses_fleet_kinds():
    plan = FaultPlan.parse("rollout_host_kill@3,broadcast_timeout@1,episode_stream_stall@2")
    assert plan.fire("rollout_host_kill", 3)
    assert not plan.fire("rollout_host_kill", 3)  # one-shot
    assert plan.fire("broadcast_timeout", 1)
    assert plan.fire("episode_stream_stall", 2)
    with pytest.raises(ValueError):
        FaultPlan.parse("rollout_host_explode@1")


def test_role_timeouts_resolve_documented_defaults():
    t = _config().train
    got = role_timeouts(t)
    assert got["heartbeat_interval"] == 0.5
    assert got["episode_timeout"] == 60.0
    assert got["stream_retries"] == 2
    assert got["stream_backoff"] == 0.5
    assert got["heartbeat_timeout"] == 10.0
    assert got["broadcast_deadline"] == 60.0
    t = _config(
        heartbeat_interval=2.0,
        fleet_episode_timeout=5.0,
        fleet_stream_retries=4,
        fleet_stream_backoff=0.1,
        fleet_heartbeat_timeout=9.0,
        collective_deadline=45.0,
    ).train
    got = role_timeouts(t)
    assert got["heartbeat_interval"] == 2.0
    assert got["episode_timeout"] == 5.0
    assert got["stream_retries"] == 4
    assert got["stream_backoff"] == 0.1
    assert got["heartbeat_timeout"] == 9.0
    # fleet deadline falls back to the collective deadline before 60s.
    assert got["broadcast_deadline"] == 45.0


# -------------------------------------- in-flight weight updates (PR 17)


def test_cursor_torn_read_falls_back_to_last_indexed_seq(tmp_path):
    """A PRESENT-but-garbage cursor must NOT read as 0 — a restarted
    learner would silently re-train on every streamed batch. The fallback
    is 1 + the last indexed seq (at-most-once); a MISSING cursor is a
    fresh fleet and genuinely means 0."""
    from trlx_tpu.fleet.runner import _read_cursor

    paths = FleetPaths(root=str(tmp_path)).ensure()
    assert _read_cursor(paths) == 0  # missing = fresh fleet
    writer = EpisodeStreamWriter(paths)
    for _ in range(3):
        writer.append(_columns(), weight_version=0)
    with open(paths.cursor, "w") as f:
        f.write('{"consu')  # torn write mid-flight
    assert _read_cursor(paths) == 3  # 1 + max indexed seq (2)
    with open(paths.cursor, "w") as f:
        json.dump({"consumed": 1}, f)
    assert _read_cursor(paths) == 1  # intact cursor wins over the index


def test_elastic_cursor_resumes_over_interleaved_stream_files(tmp_path):
    """Elastic resume (satellite): the unit cursor is the single authority
    across N interleaved per-worker stream files — an intact cursor wins no
    matter how units interleave across stream.jsonl / stream.w001.jsonl,
    and the recovered per-stream marks ride along for forensics."""
    from trlx_tpu.fleet import ElasticStreamReader
    from trlx_tpu.fleet.runner import _read_cursor

    paths = FleetPaths(root=str(tmp_path)).ensure_elastic()
    w0 = EpisodeStreamWriter(paths, worker=0)
    w1 = EpisodeStreamWriter(paths, worker=1)
    # Units interleave across the two streams: w0 produces 0 and 2 (its
    # seqs 0,1), w1 produces 1 and 3 (its seqs 0,1).
    w0.append(_columns(seed=0), weight_version=0, unit=0)
    w1.append(_columns(seed=1), weight_version=0, unit=1)
    w0.append(_columns(seed=2), weight_version=1, unit=2)
    w1.append(_columns(seed=3), weight_version=1, unit=3)
    reader = ElasticStreamReader(paths)
    assert sorted(reader.chosen()) == [0, 1, 2, 3]
    assert reader.duplicates() == 0
    # Per-worker seqs restart at 0 in each file; units stay globally unique.
    assert [r["seq"] for r in reader.indexes()[1]] == [0, 1]
    with open(paths.cursor, "w") as f:
        json.dump({"consumed": 3, "ordinal": 2, "streams": {"0": 2, "1": 1}}, f)
    assert _read_cursor(paths) == 3  # intact cursor wins over every index


def test_elastic_cursor_torn_read_falls_back_over_all_stream_files(tmp_path):
    """Torn elastic cursor with TWO writers: the at-most-once fallback must
    scan EVERY per-worker index — falling back to worker 0's file alone
    would re-consume whatever only landed in a peer's stream."""
    from trlx_tpu.fleet.runner import _read_cursor

    paths = FleetPaths(root=str(tmp_path)).ensure_elastic()
    w0 = EpisodeStreamWriter(paths, worker=0)
    w1 = EpisodeStreamWriter(paths, worker=1)
    w0.append(_columns(seed=0), weight_version=0, unit=0)
    w1.append(_columns(seed=1), weight_version=0, unit=1)
    w1.append(_columns(seed=2), weight_version=1, unit=4)  # peer holds the max
    with open(paths.cursor, "w") as f:
        f.write('{"consumed": 2, "stre')  # torn write mid-flight
    assert _read_cursor(paths) == 5  # 1 + max unit across ALL indexes
    os.remove(paths.cursor)
    # MISSING (vs torn) keeps the PR 16 fresh-fleet contract: nothing was
    # ever consumed, so 0 — only a PRESENT-but-garbage cursor scans.
    assert _read_cursor(paths) == 0


def test_elastic_reader_dedupes_reclaim_races_by_unit(tmp_path):
    """Two records for one unit (a reclaimer racing its slow original
    owner): chosen() keeps the first to land, duplicates() counts the
    loser, and both productions carry the same prompt-shard episode_key."""
    from trlx_tpu.fleet import ElasticStreamReader, episode_key

    paths = FleetPaths(root=str(tmp_path)).ensure_elastic()
    cols = _columns(seed=7)
    w0 = EpisodeStreamWriter(paths, worker=0)
    w1 = EpisodeStreamWriter(paths, worker=1)
    w1.append(cols, weight_version=0, unit=0)  # reclaimer lands first
    time.sleep(0.01)
    w0.append(cols, weight_version=1, unit=0)  # slow owner lands late
    reader = ElasticStreamReader(paths)
    assert reader.duplicates() == 1
    chosen = reader.chosen()[0]
    assert chosen["worker"] == 1
    records = reader.by_unit()[0]
    # Same deterministic prompt shard → same content key on BOTH records,
    # even though a weight version landed between the two productions.
    assert {r["episode_key"] for r in records} == {episode_key(cols)}
    # The npz the learner loads is the chosen record's, bitwise.
    got = reader.load(chosen)
    assert all(np.array_equal(got[k], cols[k]) for k in cols)


def test_put_leaves_names_first_dtype_mismatched_leaf(tmp_path):
    """Satellite: a same-shape dtype misconfig (f32 learner streaming to a
    bf16 rollout world) must fail NAMING the first mismatched leaf path,
    not with an anonymous byte-count skew."""
    import jax.numpy as jnp

    paths = FleetPaths(root=str(tmp_path)).ensure()
    params = _params()
    WeightPublisher(paths).publish(params, version=0)
    sub = WeightSubscriber(paths)
    leaves = sub.load(sub.latest())
    # Same tree, same shapes — but "b" is bf16 here while the published
    # snapshot's "b" is f32: half the bytes per element.
    wrong = {"w": params["w"], "b": params["b"].astype(jnp.bfloat16)}
    with pytest.raises(ValueError, match=r"leaf size mismatch at param leaf") as e:
        put_leaves(wrong, leaves)
    assert "'b'" in str(e.value)
    assert "dtype mismatch" in str(e.value)


def test_torn_publish_is_rejected_by_try_load_but_raises_from_load(tmp_path):
    """weight_push_torn drill contract: the latest pointer names the torn
    ordinal, ``try_load`` treats it as not-there (keep the held version),
    plain ``load`` raises — and the previous intact ordinal still loads."""
    paths = FleetPaths(root=str(tmp_path)).ensure()
    params = _params()
    pub = WeightPublisher(paths, fault_plan=FaultPlan.parse("weight_push_torn@1"))
    pub.publish(params, version=0)
    pub.publish(params, version=1)  # injected: pointer flips, file truncated
    statuses = [r["status"] for r in read_jsonl_or_empty(paths.broadcast_log)]
    assert statuses == ["published", "published", "injected_torn"]
    sub = WeightSubscriber(paths)
    latest = sub.latest()
    assert latest["ordinal"] == 1  # the pointer DID flip before the tear
    assert sub.try_load(latest) is None
    with pytest.raises(Exception):
        sub.load(latest)
    intact = [r for r in pub.published() if r["ordinal"] == 0][0]
    got = sub.try_load(intact)
    assert got is not None and len(got) == 2


def test_stream_index_records_version_spans_only_when_given(tmp_path):
    """Index-record compatibility: no spans argument → the record is the
    PR 16 shape (no key at all); spans given → normalized [[v, n], ...]."""
    paths = FleetPaths(root=str(tmp_path)).ensure()
    writer = EpisodeStreamWriter(paths)
    writer.append(_columns(), weight_version=5)
    writer.append(
        _columns(), weight_version=7, version_spans=[(np.int64(6), 5), (7, 2)]
    )
    recs = read_jsonl_or_empty(paths.stream_index)
    assert "version_spans" not in recs[0]
    assert recs[1]["version_spans"] == [[6, 5], [7, 2]]
    # json round-trip kept plain ints (np scalars normalized at append)
    assert all(isinstance(v, int) for span in recs[1]["version_spans"] for v in span)
