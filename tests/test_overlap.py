"""Overlapped rollout/train pipeline (trlx_tpu/pipeline/overlap.py).

Unit tier: the threading primitives (PrefetchIterator, ScoreWorker,
RolloutProducer staleness gate, PhaseTimer) plus the staleness column in the
rollout store. Integration tier (still fast, CPU): the acceptance identity —
a full PPO run with the pipeline on at max_staleness=0 produces the
bitwise-identical loss trajectory to the serial schedule — and the
reward_hang fault drill through the background score worker.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402
from trlx_tpu.pipeline.overlap import (  # noqa: E402
    PhaseTimer,
    PrefetchIterator,
    RolloutProducer,
    ScoreWorker,
    SerialFeed,
)
from trlx_tpu.pipeline.ppo_pipeline import PPORolloutStorage  # noqa: E402


def wait_until(cond, timeout=10.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# ------------------------------------------------------------- batch prefetch


def test_prefetch_iterator_ordering_and_exhaustion():
    feed = PrefetchIterator(range(10), transform=lambda x: x * 2, depth=3)
    assert list(feed) == [x * 2 for x in range(10)]
    # exhaustion is sticky — the epoch loop may probe again
    with pytest.raises(StopIteration):
        next(feed)
    with pytest.raises(StopIteration):
        next(feed)
    feed.close()  # idempotent after exhaustion


def test_prefetch_iterator_transform_error_reraises_in_order():
    def transform(x):
        if x == 3:
            raise RuntimeError("boom at 3")
        return x

    feed = PrefetchIterator(range(6), transform=transform, depth=2)
    assert [next(feed), next(feed), next(feed)] == [0, 1, 2]
    with pytest.raises(RuntimeError, match="boom at 3"):
        while True:
            next(feed)
    feed.close()


def test_prefetch_iterator_close_unblocks_full_queue():
    # Consumer abandons mid-epoch (the preemption return path) while the
    # worker is parked on a full queue: close() must unblock and join it.
    feed = PrefetchIterator(range(1000), depth=1)
    assert next(feed) == 0
    feed.close()
    assert not feed._thread.is_alive()
    with pytest.raises(StopIteration):
        next(feed)


def test_serial_feed_is_lazy_and_inline():
    calls = []

    def transform(x):
        calls.append(x)
        return x + 1

    feed = SerialFeed([1, 2, 3], transform=transform)
    assert calls == []  # nothing runs ahead of the consumer
    assert next(feed) == 2
    assert calls == [1]
    assert list(feed) == [3, 4]
    feed.close()


# --------------------------------------------------------------- score worker


def test_score_worker_fifo_results_and_busy_accounting():
    def fn(x):
        time.sleep(0.01)
        return x * 10

    w = ScoreWorker(fn, depth=2)
    for i in range(5):
        w.submit(i)
    assert [w.result(timeout=10) for _ in range(5)] == [0, 10, 20, 30, 40]
    w.close()
    assert not w.alive
    assert w.busy_s > 0.0


def test_score_worker_error_propagates_and_close_never_deadlocks():
    def fn(x):
        if x == 1:
            raise ValueError("bad chunk")
        return x

    w = ScoreWorker(fn, depth=2)
    w.submit(0)
    w.submit(1)
    w.submit(2)  # queued BEHIND the failure — still drains on close
    assert w.result(timeout=10) == 0
    with pytest.raises(ValueError, match="bad chunk"):
        w.result(timeout=10)
    w.close()
    assert not w.alive


# ---------------------------------------------------------------- phase timer


def test_phase_timer_window_keys_and_overlap_fraction():
    timer = PhaseTimer()
    # Synthetic phase seconds far exceeding the real wall → high overlap.
    timer.add("rollout", 1.0)
    timer.add("score", 1.0)
    with timer.timed("train"):
        time.sleep(0.01)
    w = timer.window()
    for k in ("time/rollout_s", "time/score_s", "time/train_s", "time/window_wall_s", "time/overlap_fraction"):
        assert k in w
    assert w["time/rollout_s"] == pytest.approx(1.0)
    assert 0.0 < w["time/overlap_fraction"] <= 1.0
    # a drained window reads serial/empty
    w2 = timer.window()
    assert w2["time/rollout_s"] == 0.0
    assert w2["time/overlap_fraction"] == 0.0


def test_phase_timer_serial_phases_report_no_overlap():
    timer = PhaseTimer()
    with timer.timed("rollout"):
        time.sleep(0.02)
    with timer.timed("train"):
        time.sleep(0.02)
    w = timer.window()
    # back-to-back phases cannot sum past the wall
    assert w["time/overlap_fraction"] == pytest.approx(0.0, abs=0.05)


# ----------------------------------------------------------- rollout producer


def _producer(max_staleness, log, chunk_sleep=0.0):
    def produce(store, index, snapshot, staleness, stop):
        if chunk_sleep:
            for _ in range(50):
                if stop():
                    return
                time.sleep(chunk_sleep / 50)
        log.append((index, staleness, snapshot))
        store.append(index)

    return RolloutProducer(produce, new_store=list, max_staleness=max_staleness)


def test_producer_staleness_zero_blocks_until_consume():
    log = []
    p = _producer(0, log).start()
    try:
        # gate: index 1 - consumed 0 = 1 > 0 — nothing may produce yet
        time.sleep(0.3)
        assert log == [] and p.pending == 0
        p.consume_done()
        store = p.next_store(timeout=10)
        assert store == [1]
        assert log[0][:2] == (1, 0)  # staleness 0: fully on-policy
        # and the NEXT store is gated again
        time.sleep(0.3)
        assert len(log) == 1
    finally:
        p.shutdown()
    assert not p.alive


def test_producer_staleness_one_runs_ahead_and_records_staleness():
    log = []
    p = _producer(1, log).start(snapshot="snap0")
    try:
        # runs ahead immediately: store 1 off the initial snapshot
        assert wait_until(lambda: p.pending == 1)
        assert log[0] == (1, 1, "snap0")
        # ...but store 2 is gated (2 - 0 > 1)
        time.sleep(0.3)
        assert len(log) == 1
        p.consume_done(snapshot="snap1")
        assert p.next_store(timeout=10) == [1]
        assert wait_until(lambda: len(log) == 2)
        assert log[1] == (2, 1, "snap1")  # new boundary snapshot picked up
    finally:
        p.shutdown()


def test_producer_error_reraises_from_next_store():
    err = RuntimeError("producer died")

    def produce(store, index, snapshot, staleness, stop):
        raise err

    p = RolloutProducer(produce, new_store=list, max_staleness=0).start()
    p.consume_done()
    with pytest.raises(RuntimeError) as ei:
        p.next_store(timeout=10)
    assert ei.value is err
    p.shutdown()


def test_producer_shutdown_drains_mid_phase():
    log = []
    p = _producer(1, log, chunk_sleep=30.0).start()
    assert wait_until(lambda: p.alive)
    t0 = time.time()
    p.shutdown(timeout=30)
    # the stop poll fires between chunks — seconds, not the 30s phase
    assert time.time() - t0 < 10
    assert not p.alive
    assert p.pending == 0  # the partial store was dropped


# ------------------------------------------------------- store staleness column


def _rows(n, val=0.0, staleness=None):
    rows = {
        "query_tensors": np.ones((n, 3), np.int32),
        "query_mask": np.ones((n, 3), np.int32),
        "response_tensors": np.ones((n, 5), np.int32),
        "response_mask": np.ones((n, 5), np.int32),
        "logprobs": np.full((n, 5), val, np.float32),
        "values": np.zeros((n, 5), np.float32),
        "rewards": np.zeros((n, 5), np.float32),
    }
    if staleness is not None:
        rows["staleness"] = np.full((n, 1), staleness, np.float32)
    return rows


def test_store_staleness_column_surfaces_in_batch_extras():
    store = PPORolloutStorage(pad_token_id=0, record_staleness=True)
    store.push_batch(_rows(8))  # producer omitted the column → zeros
    store.push_batch(_rows(8, staleness=2.0))
    loader = store.create_loader(16, shuffle=False)
    batch = next(iter(loader))
    assert batch.extras is not None
    st = np.asarray(batch.extras["staleness"])
    assert st.shape == (16,)
    assert st[:8].tolist() == [0.0] * 8
    assert st[8:].tolist() == [2.0] * 8


def test_store_without_staleness_keeps_serial_layout():
    store = PPORolloutStorage(pad_token_id=0)
    store.push_batch(_rows(8))
    batch = next(iter(store.create_loader(8, shuffle=False)))
    assert batch.extras is None


# ------------------------------------------------------------ e2e acceptance


@pytest.fixture(scope="module")
def task():
    return generate_random_walks(n_nodes=15, max_length=8, n_walks=60, seed=1000)


def _run_ppo(task, ckpt_dir, **method_overrides):
    _, logit_mask, metric_fn, reward_fn = task
    config = base_config("ppo", 15, 8)
    config.train.total_steps = 8
    config.train.epochs = 4
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.checkpoint_dir = str(ckpt_dir)
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    for k, v in method_overrides.items():
        setattr(config.method, k, v)
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[1]],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    with open(os.path.join(str(ckpt_dir), "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    return model, records


def test_overlap_at_staleness_zero_matches_serial_exactly(task, tmp_path):
    """The acceptance identity: rollout_overlap=True at max_staleness=0 runs
    the producer + score worker + prefetch machinery yet yields the
    BITWISE-identical loss trajectory — same rollouts in the same order, same
    reward-call numbering, same RNG stream, same device programs."""
    _, serial = _run_ppo(task, tmp_path / "serial")
    model, overlap = _run_ppo(task, tmp_path / "overlap", rollout_overlap=True)

    losses_serial = [r["loss"] for r in serial if "loss" in r]
    losses_overlap = [r["loss"] for r in overlap if "loss" in r]
    assert len(losses_serial) == 8
    assert losses_overlap == losses_serial

    # pipeline machinery ran and tore down cleanly
    assert model._rollout_producer is None
    assert not any(t.name.startswith("trlx-") for t in threading.enumerate())
    # phase windows flowed to metrics.jsonl
    assert any("time/overlap_fraction" in r for r in overlap)
    assert any("time/rollout_s" in r for r in overlap)
    # per-sample staleness stats surface at log boundaries, all on-policy
    stale = [r["staleness/mean"] for r in overlap if "staleness/mean" in r]
    assert stale and all(s == 0.0 for s in stale)
    # the serial run carries NO pipeline artifacts (byte-compatible default)
    assert not any("staleness/mean" in r for r in serial)


def test_max_staleness_one_trains_and_reports_staleness(task, tmp_path, monkeypatch):
    # Fully-armed sanitizer (utils/sanitize): the overlapped pipeline's
    # producer / score-worker threads dispatch concurrently with the train
    # loop, so this run doubles as the proof that every dispatch site holds
    # the lock, no donated buffer is read back, and every declared shared
    # field keeps a non-empty lockset (the Eraser race tracker) — violations
    # raise instead of deadlocking or corrupting silently.
    from trlx_tpu.utils import sanitize

    monkeypatch.setenv(sanitize.ENV_VAR, "dispatch,donation,race")
    try:
        model, records = _run_ppo(task, tmp_path / "stale", max_staleness=1)
    finally:
        monkeypatch.delenv(sanitize.ENV_VAR, raising=False)
        sanitize.refresh()
        sanitize.clear_donated()
        sanitize.clear_races()
    assert model.iter_count >= 8
    stale = [r["staleness/mean"] for r in records if "staleness/mean" in r]
    # iteration 0's store is on-policy; every later batch is 1 stale
    assert stale and stale[0] == 0.0 and stale[-1] == 1.0
    assert any("time/overlap_fraction" in r for r in records)
    assert not any(t.name.startswith("trlx-") for t in threading.enumerate())


# ---------------------------------------------------------------- fault drill


def test_reward_hang_inside_score_worker_drains_cleanly(task, tmp_path, monkeypatch):
    """TRLX_TPU_FAULTS=reward_hang through the BACKGROUND scorer: the
    retry/timeout wrapper fires on the worker thread, the error re-raises on
    the make_experience thread, and the pipeline tears down without a
    deadlock or a leaked worker."""
    from trlx_tpu.orchestrator.ppo_orchestrator import PPOOrchestrator
    from trlx_tpu.pipeline.prompt_pipeline import PromptPipeline
    from trlx_tpu.trainer.ppo import PPOTrainer

    monkeypatch.setenv("TRLX_TPU_FAULTS", "reward_hang@1")
    _, logit_mask, metric_fn, reward_fn = task
    config = base_config("ppo", 15, 8)
    config.train.checkpoint_dir = str(tmp_path / "ck")
    config.train.batch_size = 16
    config.train.reward_fn_timeout = 0.2
    config.train.reward_fn_retries = 0
    config.train.reward_fn_backoff = 0.0
    config.method.num_rollouts = 32
    config.method.chunk_size = 16
    config.method.rollout_overlap = True
    trainer = PPOTrainer(config, reward_fn=reward_fn, metric_fn=metric_fn, logit_mask=logit_mask)
    assert trainer.overlap_rollouts

    pipeline = PromptPipeline([[1]] * 32, tokenizer=None, max_prompt_length=1)
    orch = PPOOrchestrator(trainer, pipeline, reward_fn, chunk_size=16)
    with pytest.raises(TimeoutError, match="still running"):
        orch.make_experience(num_rollouts=32)
    # worker joined on the error path — nothing left to wedge shutdown
    assert not any(t.name == "trlx-score-worker" for t in threading.enumerate())

    # with retries restored the SAME injected hang is absorbed
    monkeypatch.setenv("TRLX_TPU_FAULTS", "reward_hang@3")
    from trlx_tpu.resilience import FaultPlan

    trainer.fault_plan = FaultPlan.from_env_or_config("")
    trainer.config.train.reward_fn_retries = 2
    store = PPORolloutStorage(pad_token_id=trainer.pad_token_id, record_staleness=True)
    orch.make_experience(num_rollouts=32, store=store, staleness=1)
    assert len(store) == 32
    assert all(f.fired for f in trainer.fault_plan.faults)
    g = store._buffer.gather(np.arange(32))
    assert np.all(g["staleness"] == 1.0)
    assert not any(t.name == "trlx-score-worker" for t in threading.enumerate())
