"""Resilience subsystem tests (trlx_tpu/resilience/): fault injection,
non-finite guard, divergence watchdog + rollback, checkpoint hardening.

Everything runs on CPU in the fast tier — the FaultPlan harness makes the
failure paths (NaN grads, reward_fn exceptions/hangs, corrupted checkpoints,
preemption SIGTERM) reproducible without a TPU or a real eviction.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402
from trlx_tpu.resilience import (  # noqa: E402
    CheckpointError,
    CollectiveTimeout,
    DivergenceWatchdog,
    FaultInjected,
    FaultPlan,
    Heartbeat,
    HostDesync,
    TrainingDiverged,
    all_finite,
    call_with_retries,
    collective_guard,
    compare_fingerprints,
    guarded_update,
    host_fingerprint,
    perturb_local_replicas,
    poison_nan,
    read_heartbeats,
    stall_report,
    verify_fingerprints,
)
from trlx_tpu.resilience import checkpoint as ckpt_util  # noqa: E402
from trlx_tpu.trainer.base import lr_schedule  # noqa: E402


# ----------------------------------------------------------------- fault plan


def test_fault_plan_parse_fire_once_and_env_override(monkeypatch):
    plan = FaultPlan.parse("nan_grad@3,reward_exc@2, sigterm@5")
    assert bool(plan)
    assert not plan.fire("nan_grad", 2)
    assert plan.fire("nan_grad", 3)
    assert not plan.fire("nan_grad", 3)  # fires exactly once
    assert plan.fire("reward_exc", 2) and plan.fire("sigterm", 5)

    assert not FaultPlan.parse("")  # empty spec = no faults

    with pytest.raises(ValueError):
        FaultPlan.parse("explode@1")
    with pytest.raises(ValueError):
        FaultPlan.parse("nan_grad@x")

    monkeypatch.setenv("TRLX_TPU_FAULTS", "ckpt_corrupt@1")
    plan = FaultPlan.from_env_or_config("nan_grad@3")
    assert plan.fire("ckpt_corrupt", 1)
    assert not plan.fire("nan_grad", 3)  # env var replaced the config spec


def test_poison_nan_floats_only():
    tree = {"f": jnp.ones((3,), jnp.float32), "i": jnp.ones((3,), jnp.int32)}
    out = poison_nan(tree)
    assert np.isnan(np.asarray(out["f"])).all()
    assert np.array_equal(np.asarray(out["i"]), np.ones(3, np.int32))


# ---------------------------------------------------------- non-finite guard


def test_all_finite_flags_nan_and_skips_int_leaves():
    ok = {"a": jnp.ones((2, 2)), "i": jnp.arange(3)}
    bad = {"a": jnp.asarray([1.0, float("nan")]), "i": jnp.arange(3)}
    assert bool(jax.device_get(all_finite(ok)))
    assert not bool(jax.device_get(all_finite(bad)))
    # int-only trees are trivially finite (isfinite would reject them)
    assert bool(jax.device_get(all_finite({"i": jnp.arange(3)})))


def test_guarded_update_skips_nonfinite_and_counts_consecutive():
    optimizer = optax.adam(1e-1)
    params = {"w": jnp.ones((4,), jnp.float32)}
    opt_state = optimizer.init(params)
    bad = jnp.zeros((), jnp.int32)
    step = jax.jit(lambda g, loss, p, s, b: guarded_update(optimizer, g, loss, p, s, b))

    good_grads = {"w": jnp.full((4,), 0.5, jnp.float32)}
    nan_grads = {"w": jnp.asarray([0.5, float("nan"), 0.5, 0.5], jnp.float32)}

    # finite step: params move, counter stays 0
    p1, s1, bad1, finite1 = step(good_grads, jnp.asarray(1.0), params, opt_state, bad)
    assert bool(jax.device_get(finite1))
    assert int(jax.device_get(bad1)) == 0
    assert not np.allclose(np.asarray(p1["w"]), np.asarray(params["w"]))

    # NaN grads: params AND opt_state pass through bitwise unchanged
    p2, s2, bad2, finite2 = step(nan_grads, jnp.asarray(1.0), p1, s1, bad1)
    assert not bool(jax.device_get(finite2))
    assert int(jax.device_get(bad2)) == 1
    assert np.array_equal(np.asarray(p2["w"]), np.asarray(p1["w"]))
    for new, old in zip(jax.tree_util.tree_leaves(s2), jax.tree_util.tree_leaves(s1)):
        assert np.array_equal(np.asarray(new), np.asarray(old))
    # no NaN ever reached the Adam moments
    assert all(np.isfinite(np.asarray(leaf)).all() for leaf in jax.tree_util.tree_leaves(s2))

    # NaN LOSS alone (finite grads) also skips; counter is consecutive
    p3, s3, bad3, _ = step(good_grads, jnp.asarray(float("nan")), p2, s2, bad2)
    assert int(jax.device_get(bad3)) == 2
    # a finite step resets the consecutive counter
    _, _, bad4, _ = step(good_grads, jnp.asarray(1.0), p3, s3, bad3)
    assert int(jax.device_get(bad4)) == 0


# ------------------------------------------------------------------ watchdog


def test_watchdog_requires_positive_threshold():
    with pytest.raises(ValueError):
        DivergenceWatchdog(0.0)


def test_watchdog_triggers_on_sustained_divergence_only():
    wd = DivergenceWatchdog(threshold=0.5, patience=2, ema_alpha=0.5, warmup=2)
    # warmup: even a spike must not trigger while the baseline settles
    assert not wd.observe(100.0)
    assert not wd.observe(1.0)
    # settled around ~O(10); a single spike is not "sustained"
    assert not wd.observe(1000.0)
    assert wd.breaches == 1
    assert not wd.observe(1.0)  # recovery resets the consecutive count
    assert wd.breaches == 0
    # sustained: patience consecutive breaches trigger
    assert not wd.observe(1000.0)
    assert wd.observe(1000.0)
    # breaching values must NOT have dragged the EMA up to the divergence
    assert wd.ema < 100.0

    wd.reset()
    assert wd.breaches == 0 and wd.ema is None

    # non-finite losses past warmup count as breaches too
    wd2 = DivergenceWatchdog(threshold=0.5, patience=2, warmup=0)
    wd2.observe(1.0)
    assert not wd2.observe(float("nan"))
    assert wd2.observe(float("inf"))


# --------------------------------------------------------------------- retry


def test_call_with_retries_recovers_exhausts_and_times_out():
    calls = {"n": 0}

    def flaky_once():
        calls["n"] += 1
        if calls["n"] == 1:
            raise FaultInjected("first call fails")
        return "ok"

    assert call_with_retries(flaky_once, retries=2, backoff=0.0) == "ok"
    assert calls["n"] == 2

    def always_fails():
        raise FaultInjected("no luck")

    with pytest.raises(FaultInjected, match="no luck"):
        call_with_retries(always_fails, retries=1, backoff=0.0)

    # hang watchdog: first call sleeps past the timeout, retry succeeds
    state = {"n": 0}

    def hangs_once():
        state["n"] += 1
        if state["n"] == 1:
            time.sleep(1.0)
        return state["n"]

    assert call_with_retries(hangs_once, retries=1, backoff=0.0, timeout=0.1) == 2

    with pytest.raises(TimeoutError):
        call_with_retries(lambda: time.sleep(1.0), retries=0, backoff=0.0, timeout=0.1)


# ------------------------------------------------------- checkpoint hardening


def _fake_checkpoint(directory, step, payload=b"x" * 4096):
    name = f"state_{step}"
    path = os.path.join(directory, name, "shard")
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "data.bin"), "wb") as f:
        f.write(payload)
    with open(os.path.join(directory, name, "meta.json"), "w") as f:
        f.write("{}")
    ckpt_util.write_manifest(directory, name, step)
    return name


def test_atomic_write_replaces_not_appends(tmp_path):
    p = str(tmp_path / "latest.txt")
    ckpt_util.atomic_write_text(p, "state_1")
    ckpt_util.atomic_write_text(p, "state_22")
    with open(p) as f:
        assert f.read() == "state_22"
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]  # no litter


def test_manifest_verifies_and_catches_truncation(tmp_path):
    d = str(tmp_path)
    name = _fake_checkpoint(d, 3)
    ok, reason = ckpt_util.verify_checkpoint(d, name)
    assert ok, reason

    rel = ckpt_util.corrupt_checkpoint(d, name)  # truncates the largest file
    assert rel is not None
    ok, reason = ckpt_util.verify_checkpoint(d, name)
    assert not ok and "truncated" in reason

    # a missing manifest (pre-manifest checkpoint) passes with a note
    os.remove(ckpt_util.manifest_path(d, name))
    ok, reason = ckpt_util.verify_checkpoint(d, name)
    assert ok and "no manifest" in reason

    # a missing directory never verifies
    ok, _ = ckpt_util.verify_checkpoint(d, "state_404")
    assert not ok


def test_multihost_fault_kinds_parse_and_fire_once():
    plan = FaultPlan.parse("host_hang@1,host_kill@2,slow_host@3,host_desync@4")
    for kind, tick in (("host_hang", 1), ("host_kill", 2), ("slow_host", 3), ("host_desync", 4)):
        assert not plan.fire(kind, tick + 10)
        assert plan.fire(kind, tick)
        assert not plan.fire(kind, tick)  # exactly once


def test_gc_keeps_newest_and_protected(tmp_path):
    d = str(tmp_path)
    for step in (1, 2, 3, 4):
        _fake_checkpoint(d, step)
        ckpt_util.atomic_write_json(os.path.join(d, f"state_{step}.host.json"), {})

    assert ckpt_util.gc_checkpoints(d, keep=0) == []  # 0 disables retention
    removed = ckpt_util.gc_checkpoints(d, keep=2, protect=("state_1",))
    assert removed == ["state_2"]  # state_1 protected, 3+4 newest
    assert ckpt_util.list_checkpoints(d) == ["state_4", "state_3", "state_1"]
    # sidecars of the removed checkpoint are gone too
    assert not os.path.exists(os.path.join(d, "state_2.host.json"))
    assert not os.path.exists(ckpt_util.manifest_path(d, "state_2"))


def test_gc_never_deletes_latest_pointer_or_in_use(tmp_path):
    """Satellite regression: retention GC must not delete the checkpoint
    latest.txt references (it can be OLDER than `keep` newer directories
    after a watchdog rollback), nor one a concurrent reader marked in-use."""
    d = str(tmp_path)
    for step in (1, 2, 3, 4, 5):
        _fake_checkpoint(d, step)
    ckpt_util.atomic_write_text(os.path.join(d, "latest.txt"), "state_1")

    with ckpt_util.mark_in_use(d, "state_2"):
        # keep=2 would normally drop state_3/2/1 — but 1 is the latest
        # pointer and 2 is mid-restore.
        assert ckpt_util.gc_checkpoints(d, keep=2) == ["state_3"]
        assert sorted(ckpt_util.list_checkpoints(d)) == [
            "state_1", "state_2", "state_4", "state_5",
        ]
    # marker gone on clean exit → the next GC may collect state_2, but the
    # latest pointer stays protected forever
    assert ckpt_util.gc_checkpoints(d, keep=2) == ["state_2"]
    assert ckpt_util.latest_pointer(d) == "state_1"
    assert "state_1" in ckpt_util.list_checkpoints(d)

    # a stale marker (killed reader) ages out instead of pinning forever
    marker = os.path.join(d, "state_4.inuse.99999")
    ckpt_util.atomic_write_json(marker, {})
    old = time.time() - 2 * ckpt_util.IN_USE_MAX_AGE
    os.utime(marker, (old, old))
    assert "state_4" not in ckpt_util._names_in_use(d)


# ------------------------------------------------------------- trainer level


@pytest.fixture(scope="module")
def task():
    return generate_random_walks(n_nodes=15, max_length=8, n_walks=60, seed=1000)


def small_config(**train_overrides):
    config = base_config("ppo", 15, 8)
    config.train.total_steps = 8
    config.train.epochs = 4
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    for k, v in train_overrides.items():
        setattr(config.train, k, v)
    return config


def make_trainer(task, ckpt_dir, **train_overrides):
    from trlx_tpu.trainer.ppo import PPOTrainer

    _, logit_mask, metric_fn, reward_fn = task
    config = small_config(checkpoint_dir=str(ckpt_dir), **train_overrides)
    return PPOTrainer(
        config, reward_fn=reward_fn, metric_fn=metric_fn, logit_mask=logit_mask
    )


def test_load_without_any_checkpoint_is_actionable(task, tmp_path):
    trainer = make_trainer(task, tmp_path / "ck")
    with pytest.raises(CheckpointError, match="resume_from_checkpoint"):
        trainer.load(str(tmp_path / "empty"))

    # latest.txt pointing at a checkpoint that no longer exists: a clear
    # CheckpointError naming the candidate, not a raw FileNotFoundError
    d = tmp_path / "dangling"
    os.makedirs(d)
    ckpt_util.atomic_write_text(str(d / "latest.txt"), "state_99")
    with pytest.raises(CheckpointError, match="state_99"):
        trainer.load(str(d))


def test_async_save_defers_sidecars_until_finalize(task, tmp_path):
    d = str(tmp_path / "ck")
    trainer = make_trainer(task, d, async_checkpointing=True)
    trainer.save(d, block=False)
    # the pointer only flips at finalize — a crash mid-async-save must leave
    # the previous checkpoint as the resume point
    assert not os.path.exists(os.path.join(d, "latest.txt"))
    name = trainer._finalize_pending_save()
    assert name == "state_0"
    with open(os.path.join(d, "latest.txt")) as f:
        assert f.read().strip() == "state_0"
    ok, reason = ckpt_util.verify_checkpoint(d, "state_0")
    assert ok, reason
    assert os.path.exists(os.path.join(d, "state_0.host.json"))


def test_save_retention_and_fallback_restore(task, tmp_path):
    d = str(tmp_path / "ck")
    trainer = make_trainer(task, d, keep_checkpoints=2)
    for _ in range(3):  # saves state_0, state_1, state_2
        trainer.save(d)
        trainer.state = trainer.state.replace(step=trainer.state.step + 1)
    assert ckpt_util.list_checkpoints(d) == ["state_2", "state_1"]  # GC'd state_0

    # corrupt the latest: load() must fall back to the previous intact one
    ckpt_util.corrupt_checkpoint(d, "state_2")
    trainer.load(d)
    assert trainer.last_restore_fallback is True
    assert int(jax.device_get(trainer.state.step)) == 1


def test_max_bad_steps_aborts_with_clear_error(task, tmp_path):
    trainer = make_trainer(task, tmp_path / "ck", max_bad_steps=3)
    trainer._res_pending = [
        (jnp.asarray(float("nan")), jnp.asarray(1.0), jnp.asarray(3.0))
    ]
    with pytest.raises(TrainingDiverged, match="max_bad_steps"):
        trainer._flush_resilience()
    assert trainer.skipped_steps == 1


def test_watchdog_rollback_restores_and_decays_lr(task, tmp_path):
    d = str(tmp_path / "ck")
    trainer = make_trainer(
        task,
        d,
        watchdog_threshold=0.5,
        watchdog_patience=2,
        watchdog_warmup=1,
        watchdog_lr_decay=0.5,
        max_rollbacks=2,
    )
    trainer.save(d)  # the good state at step 0
    trainer.state = trainer.state.replace(step=trainer.state.step + 5)
    trainer.iter_count = 5

    losses = [1.0, 1.0, 100.0, 100.0]  # settle, then sustained divergence
    trainer._res_pending = [(jnp.asarray(v), None, None) for v in losses]
    trainer._flush_resilience()

    assert int(jax.device_get(trainer.state.step)) == 0  # rolled back
    assert trainer.iter_count == 0
    assert trainer._rollbacks == 1
    assert trainer._lr_scale == pytest.approx(0.5)
    assert trainer.watchdog.breaches == 0  # reset for the resumed run
    # the LR the train step will actually use is scaled
    base_lr = float(lr_schedule(trainer.config.train)(10))  # past warmup
    assert base_lr > 0
    assert float(trainer.schedule(10)) == pytest.approx(0.5 * base_lr)
    # a restored (pre-rollback) host state must not reset the safety budget
    trainer.load_host_state({"resilience": {"rollbacks": 0, "lr_scale": 1.0}})
    assert trainer._rollbacks == 1
    assert trainer._lr_scale == pytest.approx(0.5)

    # budget exhausted → abort instead of looping forever
    trainer._rollbacks = trainer.config.train.max_rollbacks
    with pytest.raises(TrainingDiverged, match="max_rollbacks"):
        trainer._rollback()


def test_watchdog_multiple_rollbacks_compound_lr_and_abort(task, tmp_path):
    """Satellite: across SEVERAL rollbacks the LR decay compounds
    (0.5 → 0.25) into the live schedule, and the max_rollbacks abort is
    deterministic — exactly at budget + 1, with the budget not reset by the
    restores in between."""
    d = str(tmp_path / "ck")
    trainer = make_trainer(
        task,
        d,
        watchdog_threshold=0.5,
        watchdog_patience=2,
        watchdog_warmup=1,
        watchdog_lr_decay=0.5,
        max_rollbacks=2,
    )
    trainer.save(d)  # the good state at step 0
    base_lr = float(lr_schedule(trainer.config.train)(10))  # past warmup
    assert base_lr > 0

    def diverge():
        trainer.state = trainer.state.replace(step=trainer.state.step + 5)
        trainer.iter_count = int(jax.device_get(trainer.state.step))
        losses = [1.0, 1.0, 100.0, 100.0]  # settle, then sustained spike
        trainer._res_pending = [(jnp.asarray(v), None, None) for v in losses]
        trainer._flush_resilience()

    diverge()  # rollback 1
    assert trainer._rollbacks == 1
    assert trainer._lr_scale == pytest.approx(0.5)
    assert float(trainer.schedule(10)) == pytest.approx(0.5 * base_lr)

    diverge()  # rollback 2: the decay COMPOUNDS, the restore resets state
    assert trainer._rollbacks == 2
    assert trainer._lr_scale == pytest.approx(0.25)
    assert float(trainer.schedule(10)) == pytest.approx(0.25 * base_lr)
    assert int(jax.device_get(trainer.state.step)) == 0
    assert trainer.iter_count == 0

    # rollback 3 exceeds max_rollbacks=2 → deterministic abort, budget kept
    with pytest.raises(TrainingDiverged, match="max_rollbacks"):
        diverge()
    assert trainer._rollbacks == 3
    assert trainer._lr_scale == pytest.approx(0.25)  # no decay past the abort


# ---------------------------------------------------- distributed resilience


def test_heartbeat_write_read_and_stall_report(tmp_path):
    d = str(tmp_path / "hb")
    hb0 = Heartbeat(d, interval=0.0, process_index=0).start()  # no thread
    hb0.beat(step=7, phase="collective:allgather_host")
    hb0._write()
    hb1 = Heartbeat(d, interval=0.0, process_index=1).start()
    hb1.beat(step=3, phase="train")
    hb1.progress_t = time.time() - 100.0  # frozen progress stamp
    hb1._write()

    beats = read_heartbeats(d)
    assert set(beats) == {0, 1}
    assert beats[0]["step"] == 7 and beats[1]["phase"] == "train"
    # Dual clock bases in every payload: wall (progress_t/written_t) for
    # cross-host comparison, monotonic twins for NTP-slew-proof ages —
    # graftfleet's health block and skew estimation read both.
    for rec in beats.values():
        assert {"progress_t", "progress_mono", "written_t", "written_mono"} <= set(rec)
        assert rec["written_mono"] >= rec["progress_mono"] > 0.0

    # host 0 is INSIDE the collective (a waiter); host 1 never arrived and
    # has the oldest progress → the report names host 1
    report = stall_report(d, "allgather_host")
    assert "slowest host: host 1" in report
    assert "host 0" in report  # per-host summary included

    # a torn heartbeat file is skipped, not fatal
    with open(os.path.join(d, "host_2.json"), "w") as f:
        f.write('{"process": 2, "ste')
    assert set(read_heartbeats(d)) == {0, 1}

    # empty directory → actionable fallback text, no crash
    assert "heartbeat" in stall_report(str(tmp_path / "none"), "barrier")


def test_heartbeat_thread_advances_written_t(tmp_path):
    hb = Heartbeat(str(tmp_path), interval=0.05, process_index=0).start()
    try:
        first = read_heartbeats(str(tmp_path))[0]["written_t"]
        deadline = time.time() + 5.0
        while time.time() < deadline:
            rec = read_heartbeats(str(tmp_path)).get(0)
            if rec and rec["written_t"] > first:
                break
            time.sleep(0.02)
        else:
            pytest.fail("heartbeat thread never flushed a newer written_t")
        # written_t advanced while progress_t stayed put: the
        # alive-but-stuck signature the stall report keys on
        assert rec["progress_t"] == pytest.approx(hb.progress_t)
    finally:
        hb.stop()


def test_collective_guard_fires_on_slow_body_only():
    fired = []
    with collective_guard("drill", deadline=0.15, on_timeout=fired.append):
        time.sleep(0.5)
    assert len(fired) == 1
    exc = fired[0]
    assert isinstance(exc, CollectiveTimeout)
    assert "'drill'" in str(exc) and "collective_deadline" in str(exc)

    # fast body: the timer is cancelled, nothing fires afterwards
    fired2 = []
    with collective_guard("drill", deadline=0.1, on_timeout=fired2.append):
        pass
    time.sleep(0.3)
    assert not fired2

    # deadline 0 disarms the guard entirely (the default path)
    with collective_guard("drill", deadline=0.0, on_timeout=fired2.append):
        time.sleep(0.05)
    assert not fired2


def test_collective_guard_uses_global_config_and_heartbeat(tmp_path):
    from trlx_tpu.resilience import distributed as dist_res

    hb = Heartbeat(str(tmp_path), interval=0.0, process_index=1).start()
    hb.beat(step=9, phase="train")
    hb.progress_t = time.time() - 50.0
    hb._write()
    fired = []
    dist_res.configure(
        deadline=0.1,
        heartbeat=hb,
        step_provider=lambda: 42,
        on_timeout=fired.append,
    )
    try:
        with collective_guard("barrier:init"):
            time.sleep(0.4)
    finally:
        dist_res.configure()  # disarm — never leak into other tests
    assert len(fired) == 1
    msg = str(fired[0])
    assert "at step 42" in msg
    assert "slowest host: host 1" in msg  # stall report rode along


def test_fingerprint_compare_and_perturb():
    params = {
        "ln": {"scale": jnp.ones((8,), jnp.float32)},
        "w": jnp.arange(4, dtype=jnp.float32),
    }
    fp = host_fingerprint(3, params, rng=jax.random.PRNGKey(0))
    assert fp.shape == (3,) and fp.dtype == np.int64
    assert int(fp[0]) == 3
    # deterministic: same state → same fingerprint
    np.testing.assert_array_equal(fp, host_fingerprint(3, params, rng=jax.random.PRNGKey(0)))
    # a different rng changes only the rng component
    fp_rng = host_fingerprint(3, params, rng=jax.random.PRNGKey(1))
    assert int(fp_rng[1]) == int(fp[1]) and int(fp_rng[2]) != int(fp[2])

    compare_fingerprints(np.stack([fp, fp]))  # agreement → no raise
    verify_fingerprints(fp)  # single process → trivially consistent

    bad = fp.copy()
    bad[1] ^= 1
    with pytest.raises(HostDesync, match=r"host 1.*param replica crc32"):
        compare_fingerprints(np.stack([fp, bad]))
    worse = fp.copy()
    worse[0] += 2
    with pytest.raises(HostDesync, match=r"host 2.*step counter"):
        compare_fingerprints(np.stack([fp, fp, worse]))

    # the drill's perturbation changes exactly the param component
    perturbed = perturb_local_replicas(params, scale=1e-3)
    fp_pert = host_fingerprint(3, perturbed, rng=jax.random.PRNGKey(0))
    assert int(fp_pert[1]) != int(fp[1])
    assert int(fp_pert[0]) == int(fp[0]) and int(fp_pert[2]) == int(fp[2])
    # structure and shapes untouched; non-target leaves bitwise identical
    np.testing.assert_array_equal(
        np.asarray(perturbed["w"]), np.asarray(params["w"])
    )
    assert perturbed["ln"]["scale"].shape == (8,)


def test_reward_fn_faults_are_retried(task, tmp_path):
    """reward_exc / reward_hang fire through the orchestrator's hardened
    score(): one bounded retry each, training never sees the failure."""
    from trlx_tpu.orchestrator.ppo_orchestrator import PPOOrchestrator
    from trlx_tpu.pipeline.prompt_pipeline import PromptPipeline

    trainer = make_trainer(
        task,
        tmp_path / "ck",
        fault_plan="reward_exc@1,reward_hang@2",
        reward_fn_timeout=0.2,
        reward_fn_retries=2,
        reward_fn_backoff=0.0,
    )
    calls = {"n": 0}
    real_reward_fn = trainer.reward_fn

    def counting_reward(texts):
        calls["n"] += 1
        return real_reward_fn(texts)

    pipeline = PromptPipeline([[1]] * 16, tokenizer=None, max_prompt_length=1)
    orch = PPOOrchestrator(trainer, pipeline, counting_reward, chunk_size=16)
    scores = orch.score([np.asarray([1, 2, 0])] * 16)
    assert np.asarray(scores).shape == (16,)  # call 1: exception, then retry
    scores = orch.score([np.asarray([1, 2, 0])] * 16)
    assert np.asarray(scores).shape == (16,)  # call 2: hang, timeout, retry
    assert all(f.fired for f in trainer.fault_plan.faults)

    # with no retries left the failure surfaces as the injected error
    trainer.fault_plan = FaultPlan.parse("reward_exc@3")
    trainer.config.train.reward_fn_retries = 0
    with pytest.raises(FaultInjected):
        orch.score([np.asarray([1, 2, 0])] * 16)


def test_preemption_resume_restores_step_and_rng(task, tmp_path):
    """Satellite: SIGTERM mid-run → checkpoint lands → a fresh trainer with
    resume_from_checkpoint=True continues from the saved step with the
    identical host RNG."""
    _, logit_mask, metric_fn, reward_fn = task
    d = str(tmp_path / "ck")
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    config = small_config(
        checkpoint_dir=d, total_steps=50, epochs=100, fault_plan="sigterm@2"
    )
    model = trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
        metric_fn=metric_fn, config=config, logit_mask=logit_mask,
    )
    assert model.iter_count == 2  # preempted, not finished
    with open(os.path.join(d, "latest.txt")) as f:
        assert f.read().strip() == "state_2"
    with open(os.path.join(d, "state_2.host.json")) as f:
        saved = json.load(f)

    resumed = make_trainer(task, d, resume_from_checkpoint=True)
    assert resumed._resumed
    assert int(jax.device_get(resumed.state.step)) == 2
    assert [int(x) for x in np.asarray(jax.device_get(resumed.rng)).reshape(-1)] == saved["rng"]


def test_fault_drill_full_recovery(task, tmp_path):
    """The acceptance drill: one run absorbs an injected reward_fn exception,
    a NaN-grad step, a corrupted checkpoint, and a synthetic SIGTERM; the
    follow-up run falls back past the corrupted checkpoint and finishes with
    a finite loss."""
    _, logit_mask, metric_fn, reward_fn = task
    d = str(tmp_path / "ck")
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    def run(fault_plan, resume):
        config = small_config(
            checkpoint_dir=d,
            checkpoint_interval=2,
            fault_plan=fault_plan,
            resume_from_checkpoint=resume,
            reward_fn_backoff=0.0,
        )
        return trlx_tpu.train(
            reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
            metric_fn=metric_fn, config=config, logit_mask=logit_mask,
        )

    # Run 1: reward_exc on the first reward call (retried), NaN grads at
    # step 3 (guard skips the update), interval saves at steps 2 and 4, then
    # SIGTERM after step 5 → preemption save state_5 (the 3rd completed
    # save), which ckpt_corrupt@3 truncates post-commit.
    first = run("reward_exc@1,nan_grad@3,ckpt_corrupt@3,sigterm@5", resume=False)
    assert first.iter_count == 5  # preempted before total_steps=8
    assert first.skipped_steps > 0  # the guard skipped the NaN step
    assert all(f.fired for f in first.fault_plan.faults)
    with open(os.path.join(d, "latest.txt")) as f:
        assert f.read().strip() == "state_5"
    ok, _ = ckpt_util.verify_checkpoint(d, "state_5")
    assert not ok  # latest really is corrupt

    # Run 2: resume. latest (state_5) fails manifest verification → fall
    # back to state_4 → train the remaining steps to completion.
    second = run("", resume=True)
    assert second.last_restore_fallback is True
    assert second.iter_count == 8
    assert int(jax.device_get(second.state.step)) == 8

    with open(os.path.join(d, "metrics.jsonl")) as f:
        recs = [json.loads(line) for line in f]
    losses = [r["loss"] for r in recs if "loss" in r]
    assert losses and np.isfinite(losses[-1])
