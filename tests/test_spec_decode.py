"""Per-slot speculative decoding in the rollout engine (ISSUE 19).

Parity tier (the acceptance criterion): with greedy sampling the speculative
engine is token-for-token identical to the non-speculative engine — int8 KV
on and off, soft prompts on and off — with exactly ONE compiled verify
program. Accounting tier: dispatches vs accepted tokens split, accept-rate
gauges, a perfect drafter reaching accept rate 1.0 with ceil(R/K) dispatches.
Interaction tier: a draft window straddling an in-flight weight switch
carries correct version_spans over ACCEPTED tokens only; rejection sampling
against a point-mass (forced-bigram) target is exact. E2E tier: a PPO run
with the engine + speculation + an on-device RM trains and tears down, and
the soft-prompt trainer runs through the engine — the two guards this PR
lifted."""

import json
import os
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402
from trlx_tpu.engine import NgramDrafter, RolloutEngine, make_drafter  # noqa: E402
from trlx_tpu.models import LMConfig, LMWithValueHead  # noqa: E402
from trlx_tpu.ops.generate import make_generate_fn  # noqa: E402
from trlx_tpu.ops.sampling import GenerateConfig  # noqa: E402


def _tiny_model(**overrides):
    cfg = LMConfig(
        vocab_size=23, n_layer=2, n_head=2, d_model=32, max_position=64,
        dtype="float32", **overrides,
    )
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (3, 6), 2, cfg.vocab_size)
    ids = ids.at[0, :2].set(0)
    mask = jnp.ones((3, 6), jnp.int32).at[0, :2].set(0)
    params = {"params": model.init(rng, ids, mask)["params"]}
    return model, params, np.asarray(ids), np.asarray(mask)


def _drain(engine):
    episodes = []
    for _ in range(300):
        episodes.extend(engine.step())
        if engine.idle:
            break
    return episodes


def _by_prompt(episodes):
    return {tuple(e.prompt_ids.tolist()): e for e in episodes}


def _run_engine(model, params, groups, gcfg, **kw):
    engine = RolloutEngine(
        model, gcfg, n_slots=kw.pop("n_slots", 2), prompt_width=6,
        prefill_batch=2, steps_per_sync=3, rng=jax.random.PRNGKey(2), **kw,
    )
    engine.update_weights(params, version=1)
    for ids, msk in groups:
        engine.submit(ids, msk)
    episodes = _drain(engine)
    stats = engine.stats(reset=False)
    return engine, episodes, stats


class OracleDrafter:
    """Perfect drafter for tests: replays a known-good continuation per
    prompt, so every window position matches the model and the engine's
    accept rate must hit exactly 1.0. Implements the drafter protocol
    (reset_slot/observe/propose) and tracks each slot's frontier position
    from the observed accepted tokens only. Keyed by the UNPADDED prompt —
    what reset_slot receives."""

    def __init__(self, ref, pad=0):
        self.ref = {k: [int(t) for t in v] for k, v in ref.items()}
        self.pad = int(pad)
        self.pos = {}
        self.rows = {}

    def reset_slot(self, slot, prompt_tokens):
        self.rows[slot] = self.ref[tuple(int(t) for t in prompt_tokens)]
        self.pos[slot] = 0

    def observe(self, slot, tokens):
        self.pos[slot] = self.pos.get(slot, 0) + max(0, len(tokens) - 1)

    def propose(self, slot, last_token, k):
        row = self.rows.get(slot, [])
        p = self.pos.get(slot, 0)
        out = row[p : p + k]
        return out + [self.pad] * (k - len(out))


# -------------------------------------------------------------- greedy parity


@pytest.mark.parametrize("quant", [None, "int8"])
def test_spec_greedy_parity_token_for_token(quant):
    """THE acceptance test: spec_decode="ngram" greedy decode equals the
    non-spec engine token for token and mask bit for mask bit — mixed
    response lengths via a discovered eos, slot refill mid-run, ONE compiled
    verify program."""
    model, params, ids, msk = _tiny_model(kv_cache_quant=quant)
    free = GenerateConfig(max_new_tokens=8, do_sample=False, eos_token_id=None, pad_token_id=0)
    toks, _ = make_generate_fn(model, free)(
        params, jnp.asarray(ids), jnp.asarray(msk), jax.random.PRNGKey(1)
    )
    # an eos the greedy decode emits at different depths → mixed lengths
    first_at = {}
    for row in np.asarray(toks)[:, ids.shape[1] :]:
        seen = {}
        for step, t in enumerate(row.tolist()):
            seen.setdefault(int(t), step)
        for t, step in seen.items():
            first_at.setdefault(t, set()).add(step)
    eos = max(first_at, key=lambda t: len(first_at[t]))
    gcfg = GenerateConfig(max_new_tokens=8, do_sample=False, eos_token_id=eos, pad_token_id=0)

    e0, ref_eps, _ = _run_engine(model, params, [(ids, msk)], gcfg)
    e0.shutdown()
    ref = _by_prompt(ref_eps)

    e1, eps, stats = _run_engine(
        model, params, [(ids, msk)], gcfg, spec_decode="ngram", spec_k=4
    )
    assert len(eps) == 3
    assert e1.num_verify_traces == 1, "verify retraced: slot state leaked into shapes"
    assert e1.num_decode_traces == 0  # spec engines never touch the 1-token path
    for ep in eps:
        r = ref[tuple(ep.prompt_ids.tolist())]
        np.testing.assert_array_equal(ep.response_ids, r.response_ids)
        np.testing.assert_array_equal(ep.response_mask, r.response_mask)
        assert ep.decode_steps == r.decode_steps

    # the dispatch/token split: tokens are ACCEPTED tokens, dispatches paid
    # K window positions each, and the accept-rate gauge ties them together
    total = sum(ep.decode_steps for ep in eps)
    assert stats["engine/decode_tokens"] == stats["engine/gen_tokens"] == total
    assert stats["engine/decode_dispatches"] < total  # speculation paid off
    assert 0.0 < stats["engine/spec_accept_rate"] <= 1.0
    assert stats["engine/spec_accepted"] == total
    e1.shutdown()


def test_spec_off_path_is_cold_and_config_defaults_off():
    """spec_decode off must leave NO speculative machinery armed: no drafter,
    no verify program, no spec_resid state key, no spec stats keys, no cache
    scratch tail — and the method-config defaults keep it off (GL005: the
    default must be falsy, not "off")."""
    from trlx_tpu.data.method_configs import PPOConfig

    assert PPOConfig.spec_decode == "" and PPOConfig.spec_k == 0

    model, params, ids, msk = _tiny_model()
    gcfg = GenerateConfig(max_new_tokens=4, do_sample=False, eos_token_id=None, pad_token_id=0)
    engine = RolloutEngine(model, gcfg, n_slots=2, prompt_width=6, prefill_batch=2)
    assert engine._verify is None and engine.drafter is None
    assert engine.cache_len == 6 + 4  # no spec_k-1 scratch tail
    engine.update_weights(params)
    engine.submit(ids, msk)
    _drain(engine)
    assert "spec_resid" not in engine._state
    stats = engine.stats(reset=False)
    assert "engine/spec_accept_rate" not in stats
    # the split gauges exist on BOTH paths; off-path they reconcile as
    # dispatches * steps_per_sync >= tokens (whole-pool steps paid)
    assert stats["engine/decode_tokens"] == stats["engine/gen_tokens"]
    assert stats["engine/decode_dispatches"] == stats["engine/decode_calls"]
    engine.shutdown()

    # "off" normalizes to the cold path too; junk raises; k<2 raises
    e2 = RolloutEngine(model, gcfg, n_slots=2, prompt_width=6, spec_decode="off")
    assert e2._verify is None
    e2.shutdown()
    with pytest.raises(ValueError, match="spec_decode"):
        RolloutEngine(model, gcfg, n_slots=2, prompt_width=6, spec_decode="beam")
    with pytest.raises(ValueError, match="spec_k"):
        RolloutEngine(model, gcfg, n_slots=2, prompt_width=6, spec_decode="ngram", spec_k=1)
    with pytest.raises(NotImplementedError):
        make_drafter("model", 0)


def test_oracle_drafter_reaches_accept_rate_one():
    """Perfect-draft degenerate case: a drafter that replays the model's own
    greedy continuation must be accepted in full — accept rate exactly 1.0
    and ceil(R/K) dispatches per episode wave, the upper bound the bench
    probe's >= 2x assertion rides on."""
    model, params, ids, msk = _tiny_model()
    R, K = 8, 4
    gcfg = GenerateConfig(max_new_tokens=R, do_sample=False, eos_token_id=None, pad_token_id=0)
    e0, ref_eps, _ = _run_engine(model, params, [(ids, msk)], gcfg, n_slots=3)
    e0.shutdown()
    ref = {
        tuple(e.prompt_ids[e.prompt_mask > 0].tolist()): e.response_ids[: e.decode_steps]
        for e in ref_eps
    }
    oracle = OracleDrafter(ref, pad=0)
    e1, eps, stats = _run_engine(
        model, params, [(ids, msk)], gcfg,
        n_slots=3, spec_decode="ngram", spec_k=K, drafter=oracle,
    )
    assert len(eps) == 3
    assert stats["engine/spec_accept_rate"] == 1.0
    assert stats["engine/decode_tokens"] == 3 * R
    # all 3 slots ride the same waves: R/K dispatches total
    assert stats["engine/decode_dispatches"] == R // K
    for ep in eps:
        np.testing.assert_array_equal(
            ep.response_ids[:R], ref[tuple(ep.prompt_ids[ep.prompt_mask > 0].tolist())]
        )
    e1.shutdown()


# ----------------------------------------------- speculation x in-flight push


def test_spec_version_spans_straddle_inflight_switch():
    """A draft window straddling an in-flight weight switch: the push lands
    at the sync boundary between two verify dispatches, and the harvested
    episodes split their version_spans at the ACCEPTED-token position — the
    span arithmetic counts accepted tokens, never window positions paid."""
    model, params, ids, msk = _tiny_model()
    R, K = 6, 3
    gcfg = GenerateConfig(max_new_tokens=R, do_sample=False, eos_token_id=None, pad_token_id=0)
    e0, ref_eps, _ = _run_engine(model, params, [(ids, msk)], gcfg, n_slots=3)
    e0.shutdown()
    ref = {
        tuple(e.prompt_ids[e.prompt_mask > 0].tolist()): e.response_ids[: e.decode_steps]
        for e in ref_eps
    }
    oracle = OracleDrafter(ref, pad=0)
    engine = RolloutEngine(
        model, gcfg, n_slots=3, prompt_width=6, prefill_batch=3,
        steps_per_sync=3, rng=jax.random.PRNGKey(2),
        spec_decode="ngram", spec_k=K, drafter=oracle,
    )
    engine.update_weights(params, version=1)
    engine.submit(ids, msk)
    eps = engine.step()
    assert eps == []  # one verify dispatch: K of R tokens accepted
    assert [s["n_gen"] for s in engine.slot_states()] == [K, K, K]
    # slots are mid-decode RIGHT NOW — push without draining
    engine.update_weights(params, version=2)
    eps = _drain(engine)
    assert len(eps) == 3
    for ep in eps:
        assert ep.version_spans == [(1, K), (2, R - K)]
        assert ep.weight_version == 2
        # same params under a new version: the decode stream is unchanged
        np.testing.assert_array_equal(
            ep.response_ids[:R], ref[tuple(ep.prompt_ids[ep.prompt_mask > 0].tolist())]
        )
    # accepted-token accounting survived the switch
    stats = engine.stats(reset=False)
    assert stats["engine/decode_tokens"] == 3 * R
    assert stats["engine/weight_switches"] == 1
    engine.shutdown()


def test_spec_schedule_fingerprint_deterministic():
    """Speculation folds each dispatch's accepted-token total into the slot
    schedule crc — two identical runs must fingerprint identically (the
    2-process drill in test_fleet_drill.py checks the same crc across
    hosts)."""
    model, params, ids, msk = _tiny_model()
    gcfg = GenerateConfig(max_new_tokens=6, do_sample=False, eos_token_id=None, pad_token_id=0)

    def fingerprint():
        e, _, _ = _run_engine(
            model, params, [(ids, msk)], gcfg, spec_decode="ngram", spec_k=3
        )
        fp = e.schedule_fingerprint()
        e.shutdown()
        return fp

    fp1, fp2 = fingerprint(), fingerprint()
    assert fp1 == fp2 != 0


# -------------------------------------------------------- rejection sampling


def test_spec_sampled_point_mass_bigram_is_exact():
    """Rejection sampling against a deterministic target: a forced-bigram
    logit processor makes the sampled distribution a point mass, so the
    matching bigram drafter must be accepted with probability exactly 1
    (p_draft == 1.0 in fp32 — the bench probe's perfect-draft case) and the
    spec stream must equal the non-spec sampled stream token for token."""
    model, params, ids, msk = _tiny_model()
    V, eos = 23, 22
    allow = jnp.asarray(
        np.stack([np.eye(V, dtype=np.float32)[(t + 1) % V] for t in range(V)])
    )

    def forced_bigram(logits, ctx):
        gate = allow[ctx["last_token"]]
        return jnp.where(gate > 0, 0.0, -1e9)

    gcfg = GenerateConfig(max_new_tokens=8, do_sample=True, temperature=1.0,
                          eos_token_id=eos, pad_token_id=0)
    # prompts ending at eos-5 .. eos-3 → response lengths 5, 4, 3
    ids = np.array(ids)
    for b in range(3):
        ids[b, -1] = eos - 5 + b
    e0, ref_eps, _ = _run_engine(model, params, [(ids, msk)], gcfg, processor=forced_bigram)
    e0.shutdown()
    ref = _by_prompt(ref_eps)
    for key, ep in ref.items():
        assert ep.decode_steps == eos - key[-1]  # the forced chain ran to eos

    drafter = NgramDrafter(0, transition=lambda t: (t + 1) % V)
    e1, eps, stats = _run_engine(
        model, params, [(ids, msk)], gcfg,
        processor=forced_bigram, spec_decode="ngram", spec_k=4, drafter=drafter,
    )
    for ep in eps:
        r = ref[tuple(ep.prompt_ids.tolist())]
        np.testing.assert_array_equal(ep.response_ids, r.response_ids)
        np.testing.assert_array_equal(ep.response_mask, r.response_mask)
    # point-mass target: nothing inside the chain is ever rejected — only
    # eos/budget clipping keeps the rate below 1
    assert stats["engine/spec_accept_rate"] > 0.5
    assert stats["engine/decode_dispatches"] < stats["engine/decode_tokens"]
    e1.shutdown()


def test_spec_sampled_free_distribution_runs_clean():
    """Unconstrained sampled speculation (the realistic low-accept regime):
    episodes complete with well-formed masks, every gauge stays in range,
    and the forced position 0 keeps progress >= 1 token per dispatch."""
    model, params, ids, msk = _tiny_model()
    gcfg = GenerateConfig(max_new_tokens=8, do_sample=True, temperature=1.0,
                          eos_token_id=None, pad_token_id=0)
    engine, eps, stats = _run_engine(
        model, params, [(ids, msk)], gcfg, spec_decode="ngram", spec_k=4
    )
    assert len(eps) == 3
    for ep in eps:
        assert ep.decode_steps == 8
        assert ep.response_mask.sum() == 8
    assert 0.0 < stats["engine/spec_accept_rate"] <= 1.0
    # forced position 0: dispatches can never exceed tokens generated
    assert stats["engine/decode_dispatches"] <= stats["engine/decode_tokens"]
    assert engine.num_verify_traces == 1
    engine.shutdown()


# ------------------------------------------------------ lifted engine guards


def test_soft_prompt_engine_parity_with_and_without_spec():
    """Lifted guard 1: a soft-prompt model decodes through the engine — the
    per-slot prefill replays the learned prefix into cache rows [0, n_soft)
    — and both the plain and speculative engines match whole-batch
    generate() token for token."""
    model, params, ids, msk = _tiny_model(n_soft_tokens=3)
    gcfg = GenerateConfig(max_new_tokens=8, do_sample=False, eos_token_id=None, pad_token_id=0)
    toks, m = make_generate_fn(model, gcfg)(
        params, jnp.asarray(ids), jnp.asarray(msk), jax.random.PRNGKey(1)
    )
    toks, m = np.asarray(toks), np.asarray(m)
    P = ids.shape[1]
    ref = {tuple(ids[b].tolist()): (toks[b, P:], m[b, P:]) for b in range(3)}

    for kw in ({}, dict(spec_decode="ngram", spec_k=4)):
        engine, eps, _ = _run_engine(model, params, [(ids, msk)], gcfg, **kw)
        assert len(eps) == 3
        for ep in eps:
            rt, rm = ref[tuple(ep.prompt_ids.tolist())]
            np.testing.assert_array_equal(ep.response_ids, rt)
            np.testing.assert_array_equal(ep.response_mask, rm)
        engine.shutdown()


# ------------------------------------------------------------ e2e acceptance


@pytest.fixture(scope="module")
def task():
    return generate_random_walks(n_nodes=15, max_length=8, n_walks=60, seed=1000)


def _train(task, ckpt_dir, config):
    _, logit_mask, metric_fn, reward_fn = task
    config.train.checkpoint_dir = str(ckpt_dir)
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=None if config.model.has_reward_model else reward_fn,
        prompts=prompts,
        eval_prompts=[[1]],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    with open(os.path.join(str(ckpt_dir), "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    return model, records


def _lean(config, total_steps=3):
    config.train.total_steps = total_steps
    config.train.epochs = 2
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    config.method.rollout_engine = True
    config.method.engine_slots = 8
    config.method.prefill_batch = 4
    config.method.engine_steps_per_sync = 4
    return config


def test_ppo_engine_spec_with_on_device_rm_trains(task, tmp_path):
    """Lifted guard 2 + the full speculative stack: PPO through the engine
    with spec_decode armed AND rollout scoring by an on-device reward model
    (no host reward_fn) — trains, exports the dispatch/token split and
    accept-rate gauges, and tears down without leaking threads."""
    config = _lean(base_config("ppo", 15, 8))
    config.model.reward_model_arch = dict(config.model.model_arch)
    config.method.spec_decode = "ngram"
    config.method.spec_k = 3
    model, records = _train(task, tmp_path / "rm_spec", config)
    losses = [r["loss"] for r in records if "loss" in r]
    assert len(losses) == 3 and all(np.isfinite(losses))
    assert model.has_reward_model and model.reward_fn is None
    # the dispatch/token split flowed to the tracker, and speculation paid
    # accepted tokens into the same ledger the non-spec engine fills
    split = [r for r in records if "exp_decode_dispatches" in r]
    assert split, "exp_decode_dispatches never exported"
    for r in split:
        assert r["exp_decode_dispatches"] <= r["exp_decode_tokens"]
    rates = [r["engine/spec_accept_rate"] for r in records if "engine/spec_accept_rate" in r]
    assert rates and all(0.0 < x <= 1.0 for x in rates)
    occ = [r["engine/slot_occupancy"] for r in records if "engine/slot_occupancy" in r]
    assert occ and all(0.0 < o <= 1.0 for o in occ)
    assert model._rollout_engine is None
    assert not any(t.name.startswith("trlx-") for t in threading.enumerate())


def test_ppo_softprompt_trains_through_engine(task, tmp_path):
    """Lifted guard 1 end to end: the soft-prompt trainer (frozen trunk,
    learned prefix) routes experience through the rollout engine — the
    per-slot prefill replays the prefix — and the run completes cleanly."""
    import dataclasses

    from trlx_tpu.data.method_configs import PPOSoftpromptConfig

    config = _lean(base_config("ppo", 15, 8), total_steps=2)
    config.model.model_type = "ppo_softprompt"
    config.method = PPOSoftpromptConfig(
        **{
            **dataclasses.asdict(config.method),
            "name": "pposoftpromptconfig",
            "n_soft_tokens": 4,
        }
    )
    model, records = _train(task, tmp_path / "soft_eng", config)
    losses = [r["loss"] for r in records if "loss" in r]
    assert len(losses) == 2 and all(np.isfinite(losses))
    assert model.model.cfg.n_soft_tokens == 4
    occ = [r["engine/slot_occupancy"] for r in records if "engine/slot_occupancy" in r]
    assert occ and all(0.0 < o <= 1.0 for o in occ)
    assert model._rollout_engine is None
