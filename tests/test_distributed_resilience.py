"""2-process distributed fault drills (trlx_tpu/resilience/distributed.py).

The single-process resilience suite (tests/test_resilience.py) proves the
mechanisms in isolation; these drills prove the COORDINATED behavior only a
fleet exhibits, with real jax.distributed processes on CPU:

- drill A (``host_hang``): one host wedges mid-step → the healthy host's
  ``collective_guard`` deadline fires inside the next fingerprint allgather
  and aborts with exit code EXIT_COLLECTIVE_TIMEOUT and a CollectiveTimeout
  diagnostic naming the hung host.
- drill B (preemption): SIGTERM lands on ONE host → the save-and-exit flag
  is process-agreed, both hosts write the SAME checkpoint step, latest.txt
  flips only after both committed — and a 2-process resume continues to
  completion with host-identical state (the per-step desync guard is the
  witness) and finite losses.
- drill C (``host_desync``): one host's local replica of a replicated param
  is silently perturbed → the fingerprint check catches it within one check
  period and EVERY host raises the identical HostDesync naming host 1.

Skipped gracefully (same patterns as tests/test_multihost.py) when the
environment can't run two coordinated jax.distributed processes. Run via
``make test-multihost`` — slow-marked, excluded from the fast tier.
"""

import os
import socket
import subprocess
import sys

import pytest

from trlx_tpu.resilience.distributed import EXIT_COLLECTIVE_TIMEOUT

pytestmark = pytest.mark.slow  # excluded from `make test-fast` (see conftest)

_DRILL_WORKER = r"""
import json, os, sys
import numpy as np

mode = sys.argv[1]            # "hang" | "preempt" | "desync"
pid = int(sys.argv[2])
port = sys.argv[3]
ckpt = sys.argv[4]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["TRLX_TPU_NO_PROGRESS"] = "1"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid,
    local_device_ids=[0, 1],
)
assert jax.process_count() == 2

sys.path.insert(0, os.path.join(os.environ["TRLX_REPO"], "examples"))
import trlx_tpu
from randomwalks import base_config, generate_random_walks
from trlx_tpu.resilience import distributed as dist_res

walks, logit_mask, metric_fn, reward_fn = generate_random_walks(
    n_nodes=15, max_length=8, n_walks=60, seed=1000
)

per = 8  # per-process rows

def make_config(total_steps, resume=False):
    config = base_config("ppo", 15, 8)
    config.train.total_steps = total_steps
    config.train.epochs = 100
    config.train.batch_size = per
    config.train.eval_interval = 10**6
    # log_interval huge on purpose: the buffered resilience scalars never
    # flush mid-drill, so the first cross-host BLOCKING op after an injected
    # hang is the GUARDED fingerprint allgather, not an unguarded stats sync.
    config.train.log_interval = 10**6
    config.train.checkpoint_interval = 10**6
    config.train.checkpoint_dir = ckpt
    config.train.mesh = [4, 1, 1, 1]
    config.train.resume_from_checkpoint = resume
    config.method.num_rollouts = per
    config.method.chunk_size = per
    config.method.ppo_epochs = 2
    # distributed resilience knobs under drill
    config.train.heartbeat_interval = 0.2
    # Generous deadline: it must cover first-call compilation of any program
    # launched INSIDE a guarded collective on a loaded CI core, while still
    # converting a real hang into an abort within the test budget.
    config.train.collective_deadline = 30.0
    config.train.desync_check_interval = 2 if mode == "desync" else 1
    config.train.preempt_check_interval = 1
    return config

prompts = [[(i % 14) + 1] for i in range(8 * pid, 8 * (pid + 1))]
eval_prompts = [[1], [2]]

def run(total_steps, resume=False):
    return trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=eval_prompts,
        metric_fn=metric_fn, config=make_config(total_steps, resume),
        logit_mask=logit_mask,
    )

if mode == "hang":
    # Faults come from each process's own env (set by the test harness):
    # proc 1 carries host_hang@2 and wedges after step 2; proc 0 blocks in
    # the step-2 fingerprint allgather and must be aborted by the guard
    # (exit 117) — this print is only reachable if detection FAILED.
    run(total_steps=10)
    print(f"hang proc {pid} FINISHED WITHOUT ABORT")

elif mode == "preempt":
    # Proc 1 carries sigterm@2: SIGTERM on one host only. The agreement
    # allgather (preempt_check_interval=1) flips both hosts, both enter the
    # collective save at step 2, latest.txt lands only after both committed.
    model = run(total_steps=10)
    assert model.iter_count == 2, model.iter_count
    with open(os.path.join(ckpt, "latest.txt")) as f:
        assert f.read().strip() == "state_2"
    states = [e for e in os.listdir(ckpt) if e.startswith("state_") and
              os.path.isdir(os.path.join(ckpt, e))]
    assert states == ["state_2"], states  # ONE coordinated checkpoint
    print(f"preempt proc {pid} SAVED state_2")

    # Resume on both hosts and run to completion. The per-step desync guard
    # (desync_check_interval=1) is the witness that the restored state is
    # host-identical at EVERY step — any divergence raises HostDesync.
    os.environ.pop("TRLX_TPU_FAULTS", None)
    model2 = run(total_steps=4, resume=True)
    assert model2._resumed, "did not resume from the coordinated checkpoint"
    assert model2.iter_count == 4, model2.iter_count
    dist_res.verify_fingerprints(
        dist_res.host_fingerprint(
            model2.iter_count, model2.state.params, rng=model2.rng
        )
    )
    if pid == 0:
        from trlx_tpu.utils.logging import read_jsonl
        losses = [r["loss"] for r in read_jsonl(os.path.join(ckpt, "metrics.jsonl"))
                  if "loss" in r]
        assert losses and all(np.isfinite(losses)), losses
    print(f"preempt proc {pid} OK")

elif mode == "desync":
    # Proc 1 carries host_desync@1: its local replica of a replicated param
    # leaf is perturbed after step 1. The step-2 fingerprint check must
    # catch it — on BOTH hosts, with the identical error naming host 1.
    try:
        run(total_steps=10)
    except dist_res.HostDesync as e:
        assert "host 1" in str(e), str(e)
        assert "param replica crc32" in str(e), str(e)
        print(f"desync proc {pid} OK")
    else:
        print(f"desync proc {pid} GUARD MISSED THE DIVERGENCE")
"""


def _launch(tmp_path, mode, faults_by_pid):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "drill_worker.py"
    script.write_text(_DRILL_WORKER)
    ckpt = str(tmp_path / f"ckpt_{mode}")
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("TRLX_TPU_FAULTS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo
        env["TRLX_REPO"] = repo
        if pid in faults_by_pid:
            env["TRLX_TPU_FAULTS"] = faults_by_pid[pid]
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script), mode, str(pid), str(port), ckpt],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            )
        )
    return procs, ckpt


def _communicate(procs, timeout, skip_on_timeout=True):
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out.decode(errors="replace"))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        if skip_on_timeout:
            pytest.skip("2-process drill did not complete in this environment")
        raise
    return outs


def _skip_if_distributed_unavailable(proc, out):
    if proc.returncode != 0 and (
        ("initialize" in out and "failed" in out.lower())
        # jaxlib builds without cross-process CPU collectives raise this from
        # the very first sync_global_devices — nothing distributed can run.
        or "Multiprocess computations aren't implemented" in out
    ):
        pytest.skip(f"jax.distributed unavailable here: {out[-400:]}")


def test_drill_host_hang_aborts_with_collective_timeout(tmp_path):
    """Drill A: host 1 wedges after step 2 → host 0's guarded fingerprint
    allgather hits the deadline → CollectiveTimeout diagnostic naming the
    hung host + hard abort with the dedicated exit code."""
    procs, _ = _launch(tmp_path, "hang", {1: "host_hang@2"})
    try:
        out0, _ = procs[0].communicate(timeout=900)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process drill did not complete in this environment")
    finally:
        procs[1].kill()  # intentionally hung for TRLX_TPU_HANG_SECONDS
        procs[1].communicate()
    out0 = out0.decode(errors="replace")
    _skip_if_distributed_unavailable(procs[0], out0)
    assert procs[0].returncode == EXIT_COLLECTIVE_TIMEOUT, (
        f"expected exit {EXIT_COLLECTIVE_TIMEOUT}, got {procs[0].returncode}:\n{out0[-4000:]}"
    )
    assert "CollectiveTimeout" in out0
    assert "collective_deadline" in out0
    assert "slowest host: host 1" in out0  # heartbeat stall report named it
    assert "FINISHED WITHOUT ABORT" not in out0


def test_drill_preemption_coordinated_save_and_resume(tmp_path):
    """Drill B: SIGTERM on host 1 only → both hosts agree, write ONE
    checkpoint at the identical step, and a 2-process resume runs to
    completion with host-identical state and finite losses."""
    procs, _ = _launch(tmp_path, "preempt", {1: "sigterm@2"})
    outs = _communicate(procs, timeout=900)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        _skip_if_distributed_unavailable(p, out)
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
        assert f"preempt proc {pid} SAVED state_2" in out
        assert f"preempt proc {pid} OK" in out


def test_drill_host_desync_caught_by_fingerprint_guard(tmp_path):
    """Drill C: host 1's replica silently perturbed after step 1 → the
    step-2 fingerprint check raises the identical HostDesync (naming host 1
    and the mismatched component) on BOTH hosts."""
    procs, _ = _launch(tmp_path, "desync", {1: "host_desync@1"})
    outs = _communicate(procs, timeout=900)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        _skip_if_distributed_unavailable(p, out)
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
        assert f"desync proc {pid} OK" in out
        assert "GUARD MISSED THE DIVERGENCE" not in out
