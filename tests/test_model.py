"""Model-layer invariants.

`test_hydra_forward_equivalence` is the counterpart of the reference's
load-bearing KL-reference test (reference: tests/test_ppo.py:33-46): the
frozen branch replayed from the branch-point hidden state must reproduce the
trunk logits exactly before any training diverges them.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from trlx_tpu.models import LMConfig, LMWithValueHead, LMWithILQLHeads, extract_branch_params
from trlx_tpu.models.heads import trainable_mask
from trlx_tpu.models.lm import init_cache


def tiny_cfg(**kw):
    base = dict(vocab_size=29, n_layer=4, n_head=2, d_model=32, max_position=64, dtype="float32")
    base.update(kw)
    return LMConfig(**base)


@pytest.fixture(scope="module")
def value_model():
    cfg = tiny_cfg()
    model = LMWithValueHead(cfg, branch_layer=2)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (2, 10), 0, cfg.vocab_size)
    mask = jnp.ones((2, 10), dtype=jnp.int32)
    params = model.init(rng, ids, mask)["params"]
    return cfg, model, params, ids, mask


def test_forward_shapes(value_model):
    cfg, model, params, ids, mask = value_model
    out = model.apply({"params": params}, ids, mask)
    assert out["logits"].shape == (2, 10, cfg.vocab_size)
    assert out["values"].shape == (2, 10)


def test_hydra_forward_equivalence(value_model):
    """Frozen-branch replay == trunk logits at init (diff == 0), mirroring
    reference tests/test_ppo.py:33-46."""
    cfg, model, params, ids, mask = value_model
    out = model.apply({"params": params}, ids, mask, collect_branch_hidden=True)
    branch_params = extract_branch_params(params, cfg, 2)
    ref_logits = model.apply({"params": branch_params}, out["branch_hidden"], mask, method="forward_branch")
    diff = jnp.max(jnp.abs(ref_logits - out["logits"]))
    assert float(diff) == 0.0


def test_hydra_branch_insensitive_to_trained_trunk(value_model):
    """After perturbing the UNFROZEN top layers, the ref branch (old params)
    must still equal the ORIGINAL model's logits computed from the new
    branch-point hidden — i.e. the branch params are a true snapshot."""
    cfg, model, params, ids, mask = value_model
    branch_params = extract_branch_params(params, cfg, 2)
    # perturb top blocks (the trainable ones)
    perturbed = jax.tree_util.tree_map(lambda x: x, params)
    for blk in ["h_2", "h_3"]:
        perturbed["transformer"][blk] = jax.tree_util.tree_map(lambda x: x + 0.01, params["transformer"][blk])
    out_p = model.apply({"params": perturbed}, ids, mask, collect_branch_hidden=True)
    ref_logits = model.apply({"params": branch_params}, out_p["branch_hidden"], mask, method="forward_branch")
    out_orig = model.apply({"params": params}, ids, mask)
    # branch-point hidden is produced by the FROZEN bottom → identical inputs,
    # so the ref branch must reproduce the original (unperturbed) logits.
    assert float(jnp.max(jnp.abs(ref_logits - out_orig["logits"]))) < 1e-5


def test_kv_cache_decode_matches_full_forward(value_model):
    cfg, model, params, ids, mask = value_model
    T = 12
    cache = init_cache(cfg, 2, T)
    cache_mask = jnp.pad(mask, ((0, 0), (0, T - 10)))
    out_pre = model.apply({"params": params}, ids, mask, cache=cache, cache_index=0, cache_mask=cache_mask)
    nxt = jnp.argmax(out_pre["logits"][:, -1], -1)[:, None]
    cache_mask2 = cache_mask.at[:, 10].set(1)
    out_step = model.apply(
        {"params": params}, nxt, jnp.ones((2, 1), jnp.int32),
        cache=out_pre["cache"], cache_index=10, cache_mask=cache_mask2,
    )
    out_full = model.apply({"params": params}, jnp.concatenate([ids, nxt], 1), jnp.ones((2, 11), jnp.int32))
    assert float(jnp.max(jnp.abs(out_step["logits"][:, 0] - out_full["logits"][:, -1]))) < 1e-4


def test_left_padding_equivalence():
    """A left-padded prompt must produce the same last-position logits as the
    unpadded prompt (mask + position-id correction, reference quirk at
    trlx/model/accelerate_ppo_model.py:110-112 handled natively)."""
    cfg = tiny_cfg()
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(1)
    ids = jax.random.randint(rng, (1, 6), 1, cfg.vocab_size)
    params = model.init(rng, ids, jnp.ones((1, 6), jnp.int32))["params"]
    out_nopad = model.apply({"params": params}, ids, jnp.ones((1, 6), jnp.int32))
    padded = jnp.concatenate([jnp.zeros((1, 3), ids.dtype), ids], axis=1)
    pmask = jnp.concatenate([jnp.zeros((1, 3), jnp.int32), jnp.ones((1, 6), jnp.int32)], axis=1)
    out_pad = model.apply({"params": params}, padded, pmask)
    assert float(jnp.max(jnp.abs(out_pad["logits"][:, -1] - out_nopad["logits"][:, -1]))) < 1e-4


@pytest.mark.parametrize("style", ["gptj", "neox"])
def test_rotary_variants_run(style):
    if style == "gptj":
        cfg = tiny_cfg(n_layer=2, pos_type="rotary", rotary_dim=8, parallel_residual=True,
                       fused_qkv=False, qkv_bias=False, tie_word_embeddings=False)
    else:
        cfg = tiny_cfg(n_layer=2, pos_type="rotary", rotary_dim=8, parallel_residual=True,
                       use_parallel_ln=True, fused_qkv=True, tie_word_embeddings=False,
                       extra={"neox_rotary": True})
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (2, 7), 0, cfg.vocab_size)
    mask = jnp.ones((2, 7), jnp.int32)
    params = model.init(rng, ids, mask)["params"]
    out = model.apply({"params": params}, ids, mask)
    assert out["logits"].shape == (2, 7, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(out["logits"])))


def test_ilql_heads_shapes():
    cfg = tiny_cfg(n_layer=2)
    model = LMWithILQLHeads(cfg, two_qs=True)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (2, 8), 0, cfg.vocab_size)
    mask = jnp.ones((2, 8), jnp.int32)
    params = model.init(rng, ids, mask)["params"]
    actions_ixs = jnp.tile(jnp.arange(7)[None], (2, 1))
    states_ixs = jnp.tile(jnp.arange(8)[None], (2, 1))
    out = model.apply({"params": params}, ids, mask, states_ixs=states_ixs, actions_ixs=actions_ixs)
    assert out["qs"][0].shape == (2, 7, cfg.vocab_size)
    assert out["qs"][1].shape == (2, 7, cfg.vocab_size)
    assert out["vs"].shape == (2, 8)


def test_trainable_mask_freezes_bottom_layers():
    cfg = tiny_cfg()
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jnp.zeros((1, 4), jnp.int32)
    params = model.init(rng, ids, jnp.ones_like(ids))["params"]
    mask = trainable_mask(params, cfg, num_layers_unfrozen=2)
    assert mask["transformer"]["h_0"]["attn"]["c_qkv"]["kernel"] is False
    assert mask["transformer"]["h_1"]["mlp"]["c_fc"]["bias"] is False
    assert mask["transformer"]["h_2"]["attn"]["c_qkv"]["kernel"] is True
    assert mask["transformer"]["h_3"]["mlp"]["c_fc"]["kernel"] is True
    assert mask["v_head"]["layers_0"]["kernel"] is True
    # embeddings stay trainable like the reference
    assert mask["transformer"]["wte"]["embedding"] is True


def test_remat_grads_match():
    """cfg.remat=True (nn.remat over blocks — the memory/FLOPs trade for 6B+
    training) must not change gradients."""
    import jax
    from jax.flatten_util import ravel_pytree
    import numpy as np

    from trlx_tpu.models import TransformerLM

    base = dict(vocab_size=31, n_layer=2, n_head=2, d_model=32, max_position=32, dtype="float32")
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 31, (2, 10)))
    mask = jnp.ones((2, 10), jnp.int32)

    plain = TransformerLM(LMConfig(**base))
    remat = TransformerLM(LMConfig(**base, remat=True))
    params = plain.init(jax.random.PRNGKey(0), ids, mask)["params"]

    def loss(model):
        return lambda p: jnp.sum(jnp.tanh(model.apply({"params": p}, ids, mask)["logits"].astype(jnp.float32)))

    g1, _ = ravel_pytree(jax.grad(loss(plain))(params))
    g2, _ = ravel_pytree(jax.jit(jax.grad(loss(remat)))(params))
    np.testing.assert_allclose(np.asarray(g2), np.asarray(g1), atol=1e-4)


# ---------------------------------------------------------------------------
# Labels mode (the fused/streamed head API) + packed segments
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("tied,bias", [(True, False), (False, False), (False, True)])
def test_labels_mode_matches_logits_chain(tied, bias):
    """labels= forward == logprobs_from_logits over the logits= forward,
    bit-exact on CPU fp32 (the default route is the same math, only the head
    application moves inside the model)."""
    from trlx_tpu.ops.modeling import logprobs_from_logits

    cfg = tiny_cfg(tie_word_embeddings=tied, extra={"lm_head_bias": bias})
    model = LMWithValueHead(cfg, branch_layer=2)
    rng = jax.random.PRNGKey(1)
    ids = jax.random.randint(rng, (2, 10), 0, cfg.vocab_size)
    mask = jnp.ones((2, 10), dtype=jnp.int32)
    params = model.init(rng, ids, mask)["params"]

    P = 4
    labels = ids[:, P:]
    lmask = mask[:, P:]
    out = model.apply(
        {"params": params}, ids, mask, logits_start=P - 1,
        labels=labels, labels_mask=lmask,
    )
    ref = model.apply({"params": params}, ids, mask, logits_start=P - 1)
    want = logprobs_from_logits(ref["logits"][:, :-1].astype(jnp.float32), labels, lmask)
    assert out["logits"] is None
    np.testing.assert_array_equal(np.asarray(out["logprobs"]), np.asarray(want))
    # init under labels mode must yield the IDENTICAL param tree (the head
    # module shares scope/shapes with the logits-mode head)
    p2 = model.init(rng, ids, mask, logits_start=P - 1, labels=labels, labels_mask=lmask)["params"]
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(p2)
    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(p2)):
        assert a.shape == b.shape and a.dtype == b.dtype


def test_segment_ids_block_diagonal_attention():
    """Two episodes packed into one row with segment_ids + per-segment
    positions reproduce the separate-row logprobs — no cross-episode
    attention leaks."""
    cfg = tiny_cfg()
    model = LMWithValueHead(cfg, branch_layer=2)
    rng = jax.random.PRNGKey(2)
    a = jax.random.randint(rng, (6,), 0, cfg.vocab_size)
    b = jax.random.randint(jax.random.PRNGKey(3), (4,), 0, cfg.vocab_size)
    ids = jnp.zeros((2, 6), jnp.int32).at[0, :6].set(a).at[1, :4].set(b)
    mask = jnp.asarray([[1] * 6, [1] * 4 + [0] * 2], jnp.int32)
    params = model.init(rng, ids, mask)["params"]
    sep = model.apply({"params": params}, ids, mask)

    packed = jnp.concatenate([a, b])[None]
    seg = jnp.asarray([[1] * 6 + [2] * 4])
    pos = jnp.asarray([list(range(6)) + list(range(4))])
    out = model.apply(
        {"params": params},
        packed,
        jnp.ones((1, 10), jnp.int32),
        position_ids=pos,
        segment_ids=seg,
    )
    got = np.asarray(out["logits"])
    want = np.asarray(sep["logits"])
    np.testing.assert_allclose(got[0, :6], want[0, :6], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(got[0, 6:], want[1, :4], rtol=1e-5, atol=1e-5)
