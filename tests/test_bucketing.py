"""Prompt-length bucketing: the rollout path compiles per BUCKET, not per
novel ragged shape.

Tier-1 (fast, CPU): the loader/pipeline mechanics are pure numpy, and the
trace-count proof runs a tiny model with a 2-token budget. The acceptance
property is the last test: over mixed prompt lengths, the generate fn traces
at most n_buckets distinct programs (counted via make_generate_fn's
trace-count hook, which increments INSIDE the traced body)."""

import numpy as np
import pytest

from trlx_tpu.pipeline import BucketedBatchLoader
from trlx_tpu.pipeline.prompt_pipeline import PromptPipeline, normalize_buckets


def test_normalize_buckets():
    assert normalize_buckets(None, 64) is None
    assert normalize_buckets((), 64) is None
    # sorted, deduped, clamped to (0, max], max always terminal
    assert normalize_buckets([8, 4, 8], 16) == (4, 8, 16)
    assert normalize_buckets([4, 16], 16) == (4, 16)
    assert normalize_buckets([99, -3, 0], 16) == (16,)


def _tensor_prompts():
    # Lengths 2..9 — two buckets under widths (4, 8): {2,3,4} -> 4, {5..8} -> 8,
    # and the length-9 prompt truncates into the terminal bucket (8).
    rng = np.random.default_rng(0)
    return [list(rng.integers(2, 50, size=n)) for n in (2, 3, 4, 5, 6, 7, 8, 9, 3, 6)]


def test_pipeline_buckets_pad_to_smallest_fitting_width():
    prompts = _tensor_prompts()
    pipe = PromptPipeline(prompts, tokenizer=None, max_prompt_length=8, bucket_widths=(4, 8))
    assert pipe.bucket_widths == (4, 8)
    # every prompt landed in exactly one bucket
    assert sum(len(r) for r in pipe._bucket_rows.values()) == len(prompts)
    for w, ids in pipe._bucket_ids.items():
        assert ids.shape[1] == w
        msk = pipe._bucket_mask[w]
        for row, m in zip(ids, msk):
            n = int(m.sum())
            assert n <= w
            # left-padded: validity is the RIGHT edge
            assert (m[w - n :] == 1).all() and (m[: w - n] == 0).all()
    # the max-width view is intact for non-bucketed consumers
    assert pipe.input_ids.shape == (len(prompts), 8)


def test_bucketed_loader_batches_are_bucket_uniform():
    prompts = _tensor_prompts()
    pipe = PromptPipeline(prompts, tokenizer=None, max_prompt_length=8, bucket_widths=(4, 8))
    loader = pipe.create_loader(batch_size=3, shuffle=True, drop_last=False, seed=1)
    assert isinstance(loader, BucketedBatchLoader)
    widths = set()
    rows = 0
    for batch, n_valid in loader.iter_with_valid():
        assert batch["input_ids"].shape == batch["attention_mask"].shape
        assert batch["input_ids"].shape[0] == 3  # static batch, wrap-padded
        widths.add(batch["input_ids"].shape[1])
        rows += n_valid
    assert widths <= {4, 8}
    assert rows == len(prompts)  # every prompt seen exactly once as a valid row


def test_bucketed_loader_wraps_within_bucket():
    # bucket "a" has 2 rows, batch_size 4: the wrap pad must reuse bucket-"a"
    # rows, never leak rows from bucket "b"
    buckets = {"a": [0, 1], "b": [2, 3, 4]}
    seen = []

    def collate(key, ixs):
        seen.append((key, list(ixs)))
        return key, np.asarray(ixs)

    loader = BucketedBatchLoader(buckets, 4, collate, drop_last=False)
    batches = list(loader.iter_with_valid())
    assert len(batches) == len(loader) == 2
    for (key, ixs), n_valid in batches:
        member = set(buckets[key])
        assert set(ixs.tolist()) <= member
        assert n_valid == len(member) if len(member) < 4 else 4


def test_rollout_decode_stats():
    from trlx_tpu.trainer.base import JaxBaseTrainer

    # P=3 prompt, budget 4; row 0 generated 2 tokens, row 1 all 4 — the
    # while_loop ran until the longest live row: 4 steps.
    mask = np.array(
        [[1, 1, 1, 1, 1, 0, 0], [0, 1, 1, 1, 1, 1, 1]], dtype=np.int32
    )
    s = JaxBaseTrainer.rollout_decode_stats(mask, 3)
    episode_steps = s.pop("episode_steps")
    assert episode_steps.tolist() == [2, 4]  # what each row USED (vs PAID: 4)
    assert s == {"gen_tokens": 6, "decode_steps": 4, "decode_step_budget": 4}


def test_generate_traces_bounded_by_buckets():
    """Mixed prompt lengths through a bucketed loader: the jitted generate fn
    must trace at most n_buckets programs (one per bucket width), and the
    trace count must not grow when a bucket shape repeats."""
    import jax
    import jax.numpy as jnp

    from trlx_tpu.models import LMConfig, LMWithValueHead
    from trlx_tpu.ops.generate import make_generate_fn
    from trlx_tpu.ops.sampling import GenerateConfig

    cfg = LMConfig(vocab_size=19, n_layer=1, n_head=2, d_model=16, max_position=32, dtype="float32")
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    init_ids = jnp.ones((2, 4), jnp.int32)
    params = {"params": model.init(rng, init_ids, jnp.ones_like(init_ids))["params"]}

    gcfg = GenerateConfig(max_new_tokens=2, do_sample=False, eos_token_id=None, pad_token_id=0)
    gen = make_generate_fn(model, gcfg)
    assert gen.num_traces == 0

    pipe = PromptPipeline(_tensor_prompts(), tokenizer=None, max_prompt_length=8, bucket_widths=(4, 8))
    loader = pipe.create_loader(batch_size=2, shuffle=True, drop_last=False, seed=3)
    n_batches = 0
    for batch in loader:
        ids = jnp.asarray(batch["input_ids"] % cfg.vocab_size)
        msk = jnp.asarray(batch["attention_mask"])
        toks, m = gen(params, ids, msk, jax.random.PRNGKey(n_batches))
        assert toks.shape == (2, ids.shape[1] + 2)
        n_batches += 1
    assert n_batches > len(pipe.bucket_widths)  # shapes really did repeat
    assert gen.num_traces <= len(pipe.bucket_widths)
    assert {s[1] for s in gen.traced_shapes} <= {4, 8}
