"""graftnum streaming numerics observatory (trlx_tpu/observability/numerics.py).

Unit tier: the disarmed probe tap's trace-transparency (identical jaxpr —
the byte-identity contract), per-subtree reduction parity against a naive
host loop, the nonfinite census naming the exact poisoned leaf, the
first-NaN forward bisector on both a synthetic tap chain and a real tiny
TransformerLM, quantization-error gauges across two engine
``update_weights`` versions, the grad-spike / update-ratio detectors'
hysteresis walks, the no-monitor CRIT escalation, and GL007-style
sanitize-mirror conformance of every emitted ``num/*`` key.

Integration tier (CPU): the PR's acceptance run — an armed PPO run under
``TRLX_TPU_FAULTS=nan_layer@2`` whose guard-skip incident bundle carries a
``numerics.json`` naming the injected layer as first-NaN and the nonfinite
grad leaves by path; and the disarmed satellite — ``nan_grad`` with
graftnum OFF still gets a census-only ``numerics.json`` (the default-on
guard finally names its culprit) while metrics.jsonl stays free of any
``num/*`` residue.
"""

import json
import os
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402
from trlx_tpu.models import LMConfig, LMWithValueHead  # noqa: E402
from trlx_tpu.models.lm import quantize_kv, quantize_weights  # noqa: E402
from trlx_tpu.observability import anomaly as obs_anomaly  # noqa: E402
from trlx_tpu.observability import numerics as obs_numerics  # noqa: E402
from trlx_tpu.observability import report  # noqa: E402
from trlx_tpu.observability import spans as obs_spans  # noqa: E402
from trlx_tpu.observability.export import _VALID, sanitize_metric_name  # noqa: E402
from trlx_tpu.observability.health import CRIT, OK, WARN  # noqa: E402
from trlx_tpu.observability.numerics import (  # noqa: E402
    GradNormSpikeDetector,
    UpdateRatioDetector,
    bisect_forward,
    nonfinite_census,
    param_subtrees,
    probe_tap,
    train_step_stats,
)


@pytest.fixture(autouse=True)
def _numerics_isolation():
    """graftnum state is process-global (trainer construction owns it) —
    always disarm after each test so gauges, latched injections, and the
    emergency hook never leak into a later run."""
    yield
    obs_numerics.shutdown()
    obs_spans.shutdown()
    obs_anomaly.register_emergency(None)


def _tiny_model(**overrides):
    cfg = LMConfig(
        vocab_size=23, n_layer=2, n_head=2, d_model=32, max_position=64,
        dtype="float32", **overrides,
    )
    model = LMWithValueHead(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (2, 6), 2, cfg.vocab_size)
    mask = jnp.ones((2, 6), jnp.int32)
    params = {"params": model.init(rng, ids, mask)["params"]}
    return model, params, ids, mask


# --------------------------------------------------------- disarmed contract


def test_disarmed_tap_is_trace_transparent():
    """The byte-identity contract: with no armed session, probe_tap is the
    identity at trace time — the jaxpr of a tapped function is EXACTLY the
    jaxpr of the untapped one, so a disarmed run compiles the pre-graftnum
    program."""
    x = jnp.ones((3, 4), jnp.float32)
    tapped = jax.make_jaxpr(lambda a: probe_tap("block_0", a) * 2.0 + 1.0)(x)
    plain = jax.make_jaxpr(lambda a: a * 2.0 + 1.0)(x)
    assert str(tapped) == str(plain)
    # And eagerly, the disarmed tap returns the very same object.
    assert probe_tap("embed", x) is x


def test_armed_resolves_config_or_env(monkeypatch):
    class T:
        graftnum = False

    monkeypatch.delenv("TRLX_TPU_GRAFTNUM", raising=False)
    assert not obs_numerics.armed(T())
    T.graftnum = True
    assert obs_numerics.armed(T())
    T.graftnum = False
    monkeypatch.setenv("TRLX_TPU_GRAFTNUM", "1")
    assert obs_numerics.armed(T())
    monkeypatch.setenv("TRLX_TPU_GRAFTNUM", "0")
    assert not obs_numerics.armed(T())


# ------------------------------------------------------ reduction parity


def test_train_step_stats_parity_vs_naive_host_loop():
    rng = np.random.default_rng(7)

    def leaf(*shape):
        return jnp.asarray(rng.normal(size=shape), jnp.float32)

    params = {
        "policy": {
            "h_0": {"w": leaf(4, 4), "b": leaf(4)},
            "wte": {"embedding": leaf(9, 4)},
        },
        "value_head": {"kernel": leaf(4, 1)},
    }
    grads = jax.tree_util.tree_map(lambda p: p * 0.1 + 0.3, params)
    new_params = jax.tree_util.tree_map(lambda p, g: p - 0.01 * g, params, grads)

    stats = {k: float(v) for k, v in train_step_stats(grads, params, new_params).items()}

    def host_norm(tree):
        return float(
            np.sqrt(
                sum(float(np.sum(np.asarray(a) ** 2)) for a in jax.tree_util.tree_leaves(tree))
            )
        )

    subs = param_subtrees(grads)
    assert set(subs) == {"policy/h_0", "policy/wte", "value_head/kernel"}
    for name in subs:
        g = host_norm(param_subtrees(grads)[name])
        p = host_norm(param_subtrees(params)[name])
        d = host_norm(
            jax.tree_util.tree_map(
                lambda a, b: np.asarray(a) - np.asarray(b),
                param_subtrees(new_params)[name],
                param_subtrees(params)[name],
            )
        )
        np.testing.assert_allclose(stats[f"num/grad_norm/{name}"], g, rtol=1e-5)
        np.testing.assert_allclose(stats[f"num/param_norm/{name}"], p, rtol=1e-5)
        np.testing.assert_allclose(
            stats[f"num/update_ratio/{name}"], d / (p + 1e-12), rtol=1e-5
        )
    np.testing.assert_allclose(stats["num/grad_global_norm"], host_norm(grads), rtol=1e-5)


def test_train_step_stats_is_jit_safe():
    params = {"g": {"w": jnp.ones((3, 3))}}
    grads = {"g": {"w": jnp.full((3, 3), 2.0)}}

    @jax.jit
    def step(g, p):
        return train_step_stats(g, p, jax.tree_util.tree_map(lambda a: a * 0.5, p))

    out = step(grads, params)
    assert float(out["num/grad_global_norm"]) == pytest.approx(6.0)
    assert float(out["num/update_ratio/g/w"]) == pytest.approx(0.5, rel=1e-5)


# ----------------------------------------------------------------- census


def test_census_names_exact_poisoned_leaf():
    tree = {
        "policy": {
            "h_0": {"kernel": jnp.ones((4, 4))},
            "h_1": {"kernel": jnp.ones((4, 4)).at[1, 2].set(jnp.nan)},
        },
        "ids": jnp.ones((3,), jnp.int32),  # integer leaves are skipped
    }
    census = nonfinite_census(tree)
    assert census["total_nonfinite_leaves"] == 1
    (entry,) = census["nonfinite_leaves"]
    assert entry["path"].endswith("h_1/kernel")
    assert entry["nan"] == 1 and entry["inf"] == 0 and entry["size"] == 16


def test_census_caps_named_leaves_but_counts_all():
    tree = {f"g_{i}": jnp.full((2,), jnp.inf) for i in range(40)}
    census = nonfinite_census(tree, max_leaves=5)
    assert census["total_nonfinite_leaves"] == 40
    assert len(census["nonfinite_leaves"]) == 5
    assert all(e["inf"] == 2 for e in census["nonfinite_leaves"])


# --------------------------------------------------------------- bisector


def test_bisect_synthetic_chain_names_injected_tap():
    seen = []

    def forward():
        x = jnp.ones((2, 3))
        for i in range(3):
            x = probe_tap(f"block_{i}", x * 1.5)
            seen.append(float(jnp.sum(x)))

    out = bisect_forward(forward, inject="block_1")
    assert out["first_nonfinite"] == "block_1"
    assert out["injected"] == "block_1"
    names = [t["tap"] for t in out["taps"]]
    assert names == ["block_0", "block_1", "block_2"]
    assert out["taps"][0]["nan"] == 0
    assert out["taps"][1]["nan"] == out["taps"][1]["size"] == 6
    # the session is torn down — later taps are identity again
    x = jnp.ones(())
    assert probe_tap("block_1", x) is x


def test_bisect_clean_forward_and_error_capture():
    assert bisect_forward(lambda: probe_tap("a", jnp.ones(())))["first_nonfinite"] is None

    def boom():
        probe_tap("a", jnp.ones(()))
        raise RuntimeError("mid-forward assert")

    out = bisect_forward(boom)
    assert out["first_nonfinite"] is None
    assert out["taps"][-1]["tap"] == "<error>"
    assert "mid-forward assert" in out["taps"][-1]["error"]


def test_bisect_real_model_names_injected_layer():
    """The taps models/lm.py registers (embed -> block_<i> -> ln_f) fire in
    an EAGER apply, and injecting at block_1 names exactly block_1 — the
    ground truth the nan_layer drill asserts end-to-end."""
    model, params, ids, mask = _tiny_model()
    out = bisect_forward(lambda: model.apply(params, ids, mask), inject="block_1")
    names = [t["tap"] for t in out["taps"]]
    assert names[:2] == ["embed", "block_0"] and "block_1" in names and "ln_f" in names
    assert out["first_nonfinite"] == "block_1"
    by_name = {t["tap"]: t for t in out["taps"]}
    assert by_name["embed"]["nan"] == 0 and by_name["block_0"]["nan"] == 0
    assert by_name["block_1"]["nan"] > 0 and by_name["ln_f"]["nan"] > 0


def test_injection_latch_is_one_shot():
    obs_numerics.latch_injection("block_3")
    assert obs_numerics.consume_injection() == "block_3"
    assert obs_numerics.consume_injection() is None


# ------------------------------------------------------ quantization error


def test_quant_probe_accumulates_and_gauges_are_sane():
    rng = np.random.default_rng(3)
    params = {
        "h_0": {"attn": {"c_qkv": {"kernel": jnp.asarray(rng.normal(size=(8, 24)), jnp.float32)}}},
        "mlp": {"c_fc": {"kernel": jnp.asarray(rng.normal(size=(8, 32)), jnp.float32)}},
    }
    probe = {}
    qw = quantize_weights(params, probe=probe)
    assert set(probe) == {"c_qkv", "c_fc"}
    assert qw["h_0"]["attn"]["c_qkv"]["kernel_q"].dtype == jnp.int8
    gauges = obs_numerics._quant_gauges(probe, version=4)
    assert gauges["num/quant_weight_version"] == 4.0
    for cls in ("c_qkv", "c_fc"):
        assert 0.0 < gauges[f"num/quant_err_rms/{cls}"] < 1.0  # int8 round trip
        assert gauges[f"num/quant_err_max/{cls}"] >= gauges[f"num/quant_err_rms/{cls}"]
        assert 20.0 < gauges[f"num/quant_snr_db/{cls}"] <= 200.0

    kv_probe = {}
    quantize_kv(jnp.asarray(rng.normal(size=(1, 4, 2, 8)), jnp.float32), probe=kv_probe, probe_class="kv")
    kv_gauges = obs_numerics._quant_gauges(kv_probe)
    assert kv_gauges["num/quant_err_rms/kv"] > 0.0


def test_quant_probe_default_none_keeps_trace_identical():
    params = {"h_0": {"c_proj": {"kernel": jnp.ones((4, 4))}}}
    with_probe = jax.make_jaxpr(lambda p: quantize_weights(p))(params)
    plain = jax.make_jaxpr(quantize_weights)(params)
    assert str(with_probe) == str(plain)


def test_quant_gauges_across_two_engine_weight_versions():
    """The engine-path satellite: two ``update_weights`` handoffs refresh
    the armed observatory's gauges with the new version tag, and perturbing
    the weights MOVES the error gauges (it is a live probe, not a cached
    constant)."""
    from trlx_tpu.engine import RolloutEngine
    from trlx_tpu.ops.sampling import GenerateConfig

    model, params, _, _ = _tiny_model()
    gcfg = GenerateConfig(max_new_tokens=4, do_sample=False, pad_token_id=0)
    engine = RolloutEngine(model, gcfg, n_slots=2, prompt_width=4)
    obs_numerics.configure()
    try:
        engine.update_weights(params, version=0)
        g0 = obs_numerics.instance().gauges()
        rms_keys = [k for k in g0 if k.startswith("num/quant_err_rms/")]
        assert rms_keys, g0
        assert g0["num/quant_weight_version"] == 0.0
        assert any(k.startswith("num/quant_snr_db/kv") for k in g0)  # embedding proxy

        bigger = jax.tree_util.tree_map(lambda a: a * 3.0, params)
        engine.update_weights(bigger, version=1)
        g1 = obs_numerics.instance().gauges()
        assert g1["num/quant_weight_version"] == 1.0
        assert any(g1[k] != g0[k] for k in rms_keys)
    finally:
        engine.shutdown()


def test_record_functions_are_noops_when_disarmed():
    model, params, _, _ = _tiny_model()
    assert obs_numerics.record_weight_quant(params["params"]) == {}
    assert obs_numerics.record_weight_handoff(params, version=1) == {}
    assert not obs_numerics.enabled()


# -------------------------------------------------------------- detectors


def test_grad_spike_detector_walks_warn_then_crit():
    d = GradNormSpikeDetector(warn_factor=3.0, crit_factor=10.0, warmup=4,
                              warn_streak=1, crit_streak=2)
    for _ in range(6):
        assert d.observe(1.0) == OK  # clean baseline, p50 = 1.0
    assert d.observe(5.0) == WARN  # 3x < 5 < 10x
    assert d.observe(50.0) == WARN  # crit streak 1 of 2
    assert d.observe(50.0) == CRIT
    # spikes never entered the baseline: p50 still the clean 1.0
    assert d.p50() == pytest.approx(1.0)
    # nonfinite observation is CRIT-severity on its own
    assert d.severity(float("nan")) == 2


def test_grad_spike_detector_warmup_suppresses_judgment():
    d = GradNormSpikeDetector(warmup=5, warn_streak=1, crit_streak=1)
    for v in (1.0, 100.0, 1.0, 100.0):  # fewer than warmup clean obs seeded
        assert d.severity(v) in (0,) or len(d._history) < 5


def test_update_ratio_detector_bands():
    d = UpdateRatioDetector(lo=1e-6, hi=1e-2, warmup=1, warn_streak=1, crit_streak=2)
    ok = {"a": 1e-4, "b": 1e-3, "c": 1e-3}
    assert d.observe(ok) == OK  # warmup observation
    assert d.observe(ok) == OK  # in-band
    assert d.observe({**ok, "a": 5e-2}) == WARN  # one subtree of three hot
    assert d.observe({**ok, "a": 5e-1}) == WARN  # extreme: crit streak 1 of 2
    assert d.observe({**ok, "a": 5e-1}) == CRIT
    # a wholly stalled step (all ratios exactly 0 — guard skip) violates
    d2 = UpdateRatioDetector(warmup=0, warn_streak=1, crit_streak=1)
    assert d2.observe({"a": 0.0, "b": 0.0}) == CRIT


def test_escalate_without_monitor_captures_health_incident():
    captured = []

    class _FakeCapture:
        def capture(self, step, reason, detail=None):
            captured.append((reason, detail))

    obs_anomaly.register_emergency(_FakeCapture())
    d = GradNormSpikeDetector(warmup=1, warn_streak=1, crit_streak=1)
    d.on_crit = obs_numerics.escalate
    d.observe(1.0), d.observe(1.0)
    d.observe(1e6)
    assert captured and captured[0][0] == "health_grad_norm_spike"
    assert captured[0][1]["detector"] == "grad_norm_spike"


def test_numerics_instance_feeds_detectors_and_emits_states():
    inst = obs_numerics.configure()
    stats = {
        "num/grad_global_norm": 1.0,
        "num/update_ratio/policy/h_0": 1e-4,
        "loss": 0.5,  # unrelated keys ignored
    }
    for _ in range(8):
        inst.observe_train(stats)
    g = inst.gauges(include_states=True)
    assert g["health/grad_norm_spike_state"] == 0.0
    assert g["health/update_ratio_state"] == 0.0
    assert inst.grad_detector.observations == 8
    # with include_states=False (a HealthMonitor owns the states) only the
    # quant gauges remain — empty here
    assert obs_numerics.instance().gauges(include_states=False) == {}


# ------------------------------------------------- sanitize-mirror (GL007)


def test_all_num_keys_survive_prometheus_sanitization_without_collisions():
    """Every key graftnum can emit must sanitize to a UNIQUE legal
    Prometheus name (the GL007 mirror contract) — a collision would make
    two gauges silently overwrite each other on /metrics."""
    params = {"policy": {"h_0": {"w": jnp.ones((2, 2))}}, "head": jnp.ones((2,))}
    keys = set(train_step_stats(params, params, params))
    probe = {}
    quantize_weights(
        {"h_0": {"c_qkv": {"kernel": jnp.ones((4, 8))}}}, probe=probe
    )
    keys |= set(obs_numerics._quant_gauges(probe, version=1))
    inst = obs_numerics.configure()
    keys |= set(inst.gauges(include_states=True))
    assert keys, "no keys collected"
    sanitized = {}
    for k in keys:
        name = sanitize_metric_name(k)
        assert _VALID.match(name), (k, name)
        assert name not in sanitized, f"collision: {k} vs {sanitized[name]}"
        sanitized[name] = k


# ------------------------------------------------------------ integration


def test_e2e_nan_layer_drill_names_layer_and_leaves(tmp_path, monkeypatch):
    """The PR's acceptance run: armed PPO under nan_layer@2 on a 4-layer
    model — the guard genuinely skips step 2, the incident bundle's
    numerics.json names block_2 as first-NaN (the latched injection) and
    the nonfinite grad leaves by path, num/* telemetry rides metrics.jsonl,
    and the report renders the Numerics section."""
    monkeypatch.setenv("TRLX_TPU_FAULTS", "nan_layer@2")
    _, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=15, max_length=8, n_walks=60, seed=1000
    )
    config = base_config("ppo", 15, 8)
    config.model.model_arch["n_layer"] = 4  # nan_layer@2 targets block_2
    config.train.total_steps = 4
    config.train.epochs = 1
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.checkpoint_dir = str(tmp_path)
    config.train.graftnum = True
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[1]],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model.skipped_steps >= 1  # the guard really tripped
    assert not any(t.name.startswith("trlx-") for t in threading.enumerate())

    # --- num/* telemetry in metrics.jsonl ---------------------------------
    with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    scalars = [r for r in records if "num/grad_global_norm" in r]
    assert scalars, "no num/* telemetry logged"
    assert any(k.startswith("num/update_ratio/") for k in scalars[-1])
    assert any(k.startswith("num/grad_norm/") for k in scalars[-1])

    # --- incident bundle carries the provenance artifact ------------------
    incidents_dir = os.path.join(str(tmp_path), "incidents")
    payloads = []
    for name in sorted(os.listdir(incidents_dir)):
        p = os.path.join(incidents_dir, name, "numerics.json")
        if os.path.exists(p):
            with open(p) as f:
                payloads.append(json.load(f))
    assert payloads, "no numerics.json in any incident bundle"
    payload = payloads[0]
    census = payload["grad_census"]
    assert census["total_nonfinite_leaves"] > 0
    assert all("/" in e["path"] for e in census["nonfinite_leaves"])
    bisect = payload["forward_bisect"]
    assert bisect["injected"] == "block_2"
    assert bisect["first_nonfinite"] == "block_2"
    taps = {t["tap"]: t for t in bisect["taps"] if "tap" in t}
    assert taps.get("block_1", {}).get("nan") == 0  # layers BEFORE are clean
    assert obs_numerics.consume_injection() is None  # latch was consumed

    # --- report renders the section ---------------------------------------
    md = report.build_report(str(tmp_path))
    assert "## Numerics (graftnum)" in md
    assert "block_2" in md and "nonfinite grad leaves" in md


def test_disarmed_nan_grad_still_gets_census_and_zero_num_residue(tmp_path, monkeypatch):
    """The disarmed satellite: graftnum OFF, nonfinite_guard on (default),
    incidents armed via the anomaly knob — a nan_grad trip still writes a
    census-only numerics.json (no forward bisect, no latched taps), and the
    run leaves ZERO num/* residue in metrics.jsonl."""
    monkeypatch.setenv("TRLX_TPU_FAULTS", "nan_grad@2")
    monkeypatch.delenv("TRLX_TPU_GRAFTNUM", raising=False)
    _, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=15, max_length=8, n_walks=60, seed=1000
    )
    config = base_config("ppo", 15, 8)
    config.train.total_steps = 3
    config.train.epochs = 1
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.checkpoint_dir = str(tmp_path)
    config.train.anomaly_factor = 1000.0  # arms IncidentCapture only
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]
    model = trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=[[1]],
        metric_fn=metric_fn,
        config=config,
        logit_mask=logit_mask,
    )
    assert model._graftnum is None and not obs_numerics.enabled()
    assert model.skipped_steps >= 1

    incidents_dir = os.path.join(str(tmp_path), "incidents")
    payloads = []
    for name in sorted(os.listdir(incidents_dir)):
        p = os.path.join(incidents_dir, name, "numerics.json")
        if os.path.exists(p):
            with open(p) as f:
                payloads.append(json.load(f))
    assert payloads, "disarmed guard trip lost its census"
    payload = payloads[0]
    assert payload["grad_census"]["total_nonfinite_leaves"] > 0
    assert "forward_bisect" not in payload  # bisector is graftnum-armed only

    with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
        assert not any('"num/' in line for line in f), "num/* residue while disarmed"
