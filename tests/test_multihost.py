"""True multi-process semantics: 2 jax.distributed CPU processes.

The reference never tests its distributed path at all (SURVEY.md §4); here
the device→host boundary helpers (put_batch / to_local_host /
allgather_host / _gather_valid_rows) are exercised with process_count == 2,
which is exactly where np.asarray-on-global-arrays would throw. Skipped
gracefully when the environment can't run two coordinated processes.
"""

import os
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
import numpy as np

pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid,
    local_device_ids=[0, 1],
)
assert jax.process_count() == 2, jax.process_count()

from jax.sharding import NamedSharding, PartitionSpec as P
from trlx_tpu.parallel.mesh import MESH_AXES, allgather_host, make_mesh, to_local_host

mesh = make_mesh((4, 1, 1, 1))  # 2 procs x 2 local devices

# put_batch direction: each process feeds DISTINCT local rows...
local = np.arange(4 * 3, dtype=np.int32).reshape(4, 3) + 100 * pid
from jax.experimental import multihost_utils
spec = P(("dp", "fsdp"), None)
glob = multihost_utils.host_local_array_to_global_array(local, mesh, spec)
assert glob.shape == (8, 3), glob.shape

# ...a sharded computation runs on the global array...
import jax.numpy as jnp
out = jax.jit(lambda x: x * 2, out_shardings=NamedSharding(mesh, spec))(glob)

# ...and to_local_host returns exactly this process's (doubled) rows.
back = to_local_host(out, mesh=mesh)
np.testing.assert_array_equal(back, local * 2)

# allgather_host concatenates both processes' rows in process order.
full = allgather_host(back)
assert full.shape == (8, 3)
np.testing.assert_array_equal(full[:4], (np.arange(12).reshape(4, 3)) * 2)
np.testing.assert_array_equal(full[4:], (np.arange(12).reshape(4, 3) + 100) * 2)

# Preemption agreement — the REAL trainer method on both processes: only
# proc 1 has the SIGTERM flag, yet both must agree True so the collective
# save is entered together.
from trlx_tpu.trainer.base import JaxBaseTrainer
stub = object.__new__(JaxBaseTrainer)
stub._preempted = (pid == 1)
assert stub._preemption_agreed(), f"proc {pid} disagreed on preemption"
stub._preempted = False
# (all-False must agree False — no spurious saves; note BOTH procs must
# still enter the collective with the same flag values)
assert not stub._preemption_agreed(), f"proc {pid} false-positive preemption"

print(f"proc {pid} OK")
"""


def test_two_process_boundary_helpers(tmp_path):
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out.decode(errors="replace"))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process jax.distributed did not complete in this environment")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0 and "initialize" in out and "failed" in out.lower():
            pytest.skip(f"jax.distributed unavailable here: {out[-400:]}")
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} OK" in out
