"""True multi-process semantics: 2 jax.distributed CPU processes.

The reference never tests its distributed path at all (SURVEY.md §4); here
the device→host boundary helpers (put_batch / to_local_host /
allgather_host / _gather_valid_rows) are exercised with process_count == 2,
which is exactly where np.asarray-on-global-arrays would throw. Skipped
gracefully when the environment can't run two coordinated processes.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # excluded from `make test-fast` (see conftest)


def _skip_if_distributed_unavailable(proc, out):
    if proc.returncode != 0 and (
        ("initialize" in out and "failed" in out.lower())
        # jaxlib builds without cross-process CPU collectives raise this from
        # the first multi-process jit/sync — nothing distributed can run.
        or "Multiprocess computations aren't implemented" in out
    ):
        pytest.skip(f"jax.distributed unavailable here: {out[-400:]}")


_WORKER = r"""
import os, sys
import numpy as np

pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid,
    local_device_ids=[0, 1],
)
assert jax.process_count() == 2, jax.process_count()

from jax.sharding import NamedSharding, PartitionSpec as P
from trlx_tpu.parallel.mesh import MESH_AXES, allgather_host, make_mesh, to_local_host

mesh = make_mesh((4, 1, 1, 1))  # 2 procs x 2 local devices

# put_batch direction: each process feeds DISTINCT local rows...
local = np.arange(4 * 3, dtype=np.int32).reshape(4, 3) + 100 * pid
from jax.experimental import multihost_utils
spec = P(("dp", "fsdp"), None)
glob = multihost_utils.host_local_array_to_global_array(local, mesh, spec)
assert glob.shape == (8, 3), glob.shape

# ...a sharded computation runs on the global array...
import jax.numpy as jnp
out = jax.jit(lambda x: x * 2, out_shardings=NamedSharding(mesh, spec))(glob)

# ...and to_local_host returns exactly this process's (doubled) rows.
back = to_local_host(out, mesh=mesh)
np.testing.assert_array_equal(back, local * 2)

# allgather_host concatenates both processes' rows in process order.
full = allgather_host(back)
assert full.shape == (8, 3)
np.testing.assert_array_equal(full[:4], (np.arange(12).reshape(4, 3)) * 2)
np.testing.assert_array_equal(full[4:], (np.arange(12).reshape(4, 3) + 100) * 2)

# Preemption agreement — the REAL trainer method on both processes: only
# proc 1 has the SIGTERM flag, yet both must agree True so the collective
# save is entered together.
from trlx_tpu.trainer.base import JaxBaseTrainer
stub = object.__new__(JaxBaseTrainer)
stub._preempted = (pid == 1)
assert stub._preemption_agreed(), f"proc {pid} disagreed on preemption"
stub._preempted = False
# (all-False must agree False — no spurious saves; note BOTH procs must
# still enter the collective with the same flag values)
assert not stub._preemption_agreed(), f"proc {pid} false-positive preemption"

print(f"proc {pid} OK")
"""


def test_two_process_boundary_helpers(tmp_path):
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out.decode(errors="replace"))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process jax.distributed did not complete in this environment")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        _skip_if_distributed_unavailable(p, out)
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} OK" in out


_TRAIN_WORKER = r"""
import json, os, sys
import numpy as np

mode = sys.argv[1]            # "dist" or "solo"
pid = int(sys.argv[2])
port = sys.argv[3]
ckpt = sys.argv[4]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["TRLX_TPU_NO_PROGRESS"] = "1"
n_local = 2 if mode == "dist" else 4
os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_local}"

import jax
jax.config.update("jax_platforms", "cpu")
if mode == "dist":
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid,
        local_device_ids=[0, 1],
    )
    assert jax.process_count() == 2

# Deterministic data order everywhere: the dist global batch is the
# concatenation of per-process shards, so the solo twin can reproduce it
# exactly only with shuffling off.
from trlx_tpu.pipeline import BatchLoader
_orig_init = BatchLoader.__init__
def _no_shuffle_init(self, n, batch_size, collate, shuffle=False, drop_last=True, seed=0):
    _orig_init(self, n, batch_size, collate, shuffle=False, drop_last=drop_last, seed=seed)
BatchLoader.__init__ = _no_shuffle_init

sys.path.insert(0, os.path.join(os.environ["TRLX_REPO"], "examples"))
import trlx_tpu
from randomwalks import base_config, generate_random_walks

walks, logit_mask, metric_fn, reward_fn = generate_random_walks(
    n_nodes=15, max_length=8, n_walks=60, seed=1000
)

per = 8 if mode == "dist" else 16   # per-process rows
def make_config(total_steps, epochs, resume):
    config = base_config("ppo", 15, 8)
    config.train.total_steps = total_steps
    config.train.epochs = epochs
    config.train.batch_size = per
    config.train.eval_interval = 1000
    config.train.log_interval = 1
    config.train.checkpoint_interval = 10**6
    config.train.checkpoint_dir = ckpt
    config.train.mesh = [4, 1, 1, 1]
    config.train.resume_from_checkpoint = resume
    config.method.num_rollouts = per
    config.method.chunk_size = per
    config.method.ppo_epochs = 2
    return config

full_prompts = [[(i % 14) + 1] for i in range(16)]
prompts = full_prompts[8 * pid: 8 * (pid + 1)] if mode == "dist" else full_prompts
eval_prompts = [[1], [2]]

model = trlx_tpu.train(
    reward_fn=reward_fn, prompts=prompts, eval_prompts=eval_prompts,
    metric_fn=metric_fn, config=make_config(4, 2, False), logit_mask=logit_mask,
)
assert model.iter_count == 4, model.iter_count
assert os.path.exists(os.path.join(ckpt, "latest.txt"))

if mode == "dist":
    # Resume on BOTH processes from the collective orbax checkpoint and
    # continue: restore is entered together (process-agreed), training picks
    # up at step 4 and runs to 6, then saves again.
    model2 = trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=eval_prompts,
        metric_fn=metric_fn, config=make_config(6, 3, True), logit_mask=logit_mask,
    )
    assert model2._resumed, "did not resume from the checkpoint"
    assert model2.iter_count == 6, model2.iter_count
    with open(os.path.join(ckpt, "latest.txt")) as f:
        assert f.read().strip() == "state_6"

print(f"worker {mode} {pid} OK")
"""


def _run_train_worker(tmp_path, mode, port):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo
    env["TRLX_REPO"] = repo
    script = tmp_path / "train_worker.py"
    script.write_text(_TRAIN_WORKER)
    ckpt = str(tmp_path / f"ckpt_{mode}")
    n = 2 if mode == "dist" else 1
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), mode, str(pid), str(port), ckpt],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for pid in range(n)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=900)
            outs.append(out.decode(errors="replace"))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip(f"{mode} train worker did not complete in this environment")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if mode == "dist":
            _skip_if_distributed_unavailable(p, out)
        assert p.returncode == 0, f"{mode} proc {pid} failed:\n{out[-4000:]}"
        assert f"worker {mode} {pid} OK" in out
    return ckpt


def _loss_records(ckpt, max_step):
    import json

    with open(os.path.join(ckpt, "metrics.jsonl")) as f:
        recs = [json.loads(line) for line in f]
    return {
        r["step"]: r
        for r in recs
        if "loss" in r and r["step"] <= max_step
    }


def test_two_process_end_to_end_train_save_resume(tmp_path):
    """The full pod path, not just the boundary helpers: a complete tiny PPO
    learn() (rollout with per-process prompt shards -> store -> 4 train steps
    -> collective Orbax save) under jax.distributed with 2 processes, then a
    RESUME run continuing to step 6 — and the 4-step loss trajectory equals a
    single-process run over the identical global data and seeds (the dist
    global batch is [proc0 rows ; proc1 rows]; the solo twin feeds the same
    16 rows through the same 4-device mesh program).
    Reference behavior being claimed: eval gather + rank-0 save
    (reference: trlx/model/accelerate_base_model.py:126-128,149-158)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    dist_ckpt = _run_train_worker(tmp_path, "dist", port)
    solo_ckpt = _run_train_worker(tmp_path, "solo", port)

    dist = _loss_records(dist_ckpt, 4)
    solo = _loss_records(solo_ckpt, 4)
    assert set(dist) == set(solo) == {1, 2, 3, 4}, (sorted(dist), sorted(solo))
    for step in sorted(dist):
        for key in ("loss", "pg_loss", "vf_loss", "mean_kl"):
            a, b = dist[step][key], solo[step][key]
            assert abs(a - b) <= 1e-4 * max(1.0, abs(b)), (
                f"step {step} {key}: dist={a} solo={b}"
            )


_STREAM_WORKER = r"""
import json, os, sys
import numpy as np

pid = int(sys.argv[1])
port = sys.argv[2]
ckpt = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid,
    local_device_ids=[0, 1],
)
assert jax.process_count() == 2

from trlx_tpu.models import TransformerLM
from trlx_tpu.models.hf_import import LazySafetensors, lm_config_from_hf, load_hf_trunk, make_stream_put
from trlx_tpu.parallel.mesh import make_mesh, set_mesh

import transformers
hf_cfg = transformers.GPT2Config(n_layer=2, n_head=4, n_embd=64, vocab_size=128, n_positions=64)
cfg = lm_config_from_hf(hf_cfg, dtype="float32", param_dtype="float32")

mesh = make_mesh((1, 2, 2, 1))  # fsdp=2 x tp=2 over 2 procs x 2 devices
set_mesh(mesh)

model = TransformerLM(cfg)
import jax.numpy as jnp
dummy = jnp.zeros((1, 2), jnp.int32)
init = jax.eval_shape(lambda r: model.init(r, dummy, jnp.ones_like(dummy))["params"], jax.random.PRNGKey(0))

# Streamed load: every process reads the same file, each contributes its
# addressable shards via make_array_from_callback.
trunk = load_hf_trunk(ckpt, cfg, put=make_stream_put(init))

qkv = trunk["h_0"]["attn"]["c_qkv"]["kernel"]
assert tuple(qkv.sharding.spec) == ("fsdp", "tp"), qkv.sharding.spec
assert len(qkv.addressable_shards) == 2  # this process's 2 local devices

# The GLOBAL content must equal the raw file tensor: check this process's
# shards slice-for-slice against the lazily-read source.
src = np.asarray(LazySafetensors(ckpt)["transformer.h.0.attn.c_attn.weight"], np.float32)
for shard in qkv.addressable_shards:
    np.testing.assert_array_equal(np.asarray(shard.data), src[shard.index])

# And a sharded forward runs on the streamed params.
ids = np.arange(8, dtype=np.int32).reshape(2, 4) + 1
out = jax.jit(lambda p, i: model.apply({"params": p}, i, jnp.ones_like(i))["logits"])(trunk, ids)
assert out.shape == (2, 4, cfg.vocab_size)
print(f"stream proc {pid} OK")
"""


def test_two_process_streamed_load(tmp_path):
    """Pod path of the streamed safetensors loader: 2 jax.distributed
    processes each read the checkpoint file and contribute ONLY their
    addressable shards (make_array_from_callback); shard contents match the
    source tensor slice-for-slice and a sharded forward runs."""
    import socket

    transformers = pytest.importorskip("transformers")

    ckpt = str(tmp_path / "ckpt")
    hf_cfg = transformers.GPT2Config(n_layer=2, n_head=4, n_embd=64, vocab_size=128, n_positions=64)
    transformers.GPT2LMHeadModel(hf_cfg).save_pretrained(ckpt, safe_serialization=True)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    script = tmp_path / "stream_worker.py"
    script.write_text(_STREAM_WORKER)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port), ckpt],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out.decode(errors="replace"))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process jax.distributed did not complete in this environment")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        _skip_if_distributed_unavailable(p, out)
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
        assert f"stream proc {pid} OK" in out


_EXPORT_WORKER = r"""
import os, sys
import numpy as np

pid = int(sys.argv[1])
port = sys.argv[2]
out_root = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["TRLX_TPU_NO_PROGRESS"] = "1"

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid,
    local_device_ids=[0, 1],
)
assert jax.process_count() == 2

from trlx_tpu.trainer.api import default_config
from trlx_tpu.trainer.ppo import PPOTrainer

config = default_config("ppo")
config.model.model_path = ""
config.model.tokenizer_path = ""
config.model.dtype = "float32"
config.model.param_dtype = "float32"
config.model.num_layers_unfrozen = 1
config.model.model_arch = {
    "vocab_size": 128, "n_layer": 2, "n_head": 4, "d_model": 64,
    "max_position": 64, "eos_token_id": 1, "pos_type": "learned",
    "fused_qkv": True, "tie_word_embeddings": True,
}
config.train.mesh = [1, 2, 2, 1]   # fsdp=2 x tp=2: params REALLY sharded across procs
config.train.batch_size = 4
config.train.seq_length = 16
config.train.checkpoint_dir = os.path.join(out_root, "ckpts")
config.method.gen_kwargs = {"prompt_length": 4, "max_new_tokens": 4, "do_sample": True}
config.method.chunk_size = 4
config.method.num_rollouts = 4

trainer = PPOTrainer(config)
hf_dir = os.path.join(out_root, "hf")
result = trainer.save_pretrained(hf_dir, family="gpt2")
assert (result == hf_dir) if pid == 0 else (result is None), (pid, result)

# Independent numerical check: the sharded policy's logits (replicated out)
# vs torch's forward on the EXPORTED checkpoint, same tokens.
import jax.numpy as jnp
from jax.experimental import multihost_utils
from jax.sharding import NamedSharding, PartitionSpec as P

ids = (np.arange(8, dtype=np.int32).reshape(2, 4) % 120) + 1
g_ids = multihost_utils.host_local_array_to_global_array(ids, trainer.mesh, P())
rep = NamedSharding(trainer.mesh, P())
logits = jax.jit(
    lambda p, i: trainer.model.apply({"params": p}, i, jnp.ones_like(i))["logits"],
    out_shardings=rep,
)(trainer.state.params, g_ids)
l_jax = np.asarray(logits.addressable_data(0), np.float32)

if pid == 0:
    import torch
    import transformers

    m = transformers.AutoModelForCausalLM.from_pretrained(hf_dir)
    with torch.no_grad():
        l_t = m(torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(l_jax, l_t, rtol=2e-4, atol=2e-4)
print(f"export proc {pid} OK")
"""


def test_two_process_save_pretrained(tmp_path):
    """Pod-scale HF export: save_pretrained under jax.distributed with the
    params genuinely sharded over fsdp x tp across 2 processes — leaf-wise
    replicate-gather, rank-0 write, barrier — and the exported checkpoint's
    torch forward matches the sharded policy's logits."""
    import socket

    pytest.importorskip("transformers")

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    script = tmp_path / "export_worker.py"
    script.write_text(_EXPORT_WORKER)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out.decode(errors="replace"))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process jax.distributed did not complete in this environment")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        _skip_if_distributed_unavailable(p, out)
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
        assert f"export proc {pid} OK" in out
