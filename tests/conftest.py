"""Test env: force CPU JAX with 8 virtual devices BEFORE any jax backend init.

The reference has no distributed tests at all (SURVEY.md §4); here mesh
semantics are tested single-host via --xla_force_host_platform_device_count.
"""

import os

# Force CPU: the container's default JAX_PLATFORMS=axon points at a single
# tunneled TPU that test processes must not contend for. The axon
# sitecustomize calls jax.config.update("jax_platforms", "axon,cpu") at
# interpreter startup, which overrides the env var — so the env var alone is
# not enough; jax.config.update below wins because it runs later.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    # Two-tier suite (the reference's whole suite is one 46-LoC file and runs
    # per-push, reference: .github/workflows/build.yml:33-41; this repo's suite
    # outgrew a per-commit budget, so the fast tier is the per-commit signal):
    #   make test-fast  → -m "not slow"  (< ~3 min CPU)
    #   make test       → everything     (nightly / pre-release)
    config.addinivalue_line(
        "markers",
        "slow: learning-gate / e2e / multihost / pallas-kernel tests; excluded by `make test-fast`",
    )
    config.addinivalue_line(
        "markers",
        "network: needs internet + HF checkpoint downloads; skipped unless TRLX_TPU_NETWORK=1 (see RUNBOOK.md)",
    )
