"""Runtime sanitizer (utils/sanitize, TRLX_TPU_SANITIZE) contract tests.

Two halves, mirroring the module:

- unarmed: ZERO residue — plain RLock, identity wrap, no-op mark/check;
- armed: dispatch-lock ownership asserted whenever other trlx-* threads are
  alive, and donated-buffer host reads raise naming the donation site.
"""

import threading

import numpy as np
import pytest

from trlx_tpu.utils import sanitize


@pytest.fixture(autouse=True)
def _sanitize_state(monkeypatch):
    """Each test starts unarmed and leaves no residue: monkeypatch restores
    the env; we re-sync the module global and drop donation records."""
    monkeypatch.delenv(sanitize.ENV_VAR, raising=False)
    sanitize.refresh()
    yield monkeypatch
    monkeypatch.delenv(sanitize.ENV_VAR, raising=False)
    sanitize.refresh()
    sanitize.clear_donated()
    sanitize.clear_races()


def _arm(monkeypatch, modes):
    monkeypatch.setenv(sanitize.ENV_VAR, modes)
    sanitize.refresh()


# ---------------------------------------------------------------- unarmed


def test_unarmed_lock_is_plain_rlock():
    lock = sanitize.make_dispatch_lock()
    assert not isinstance(lock, sanitize.SanitizedDispatchLock)
    with lock:  # still a working RLock
        pass


def test_unarmed_wrap_is_identity():
    def fn(x):
        return x + 1

    lock = sanitize.make_dispatch_lock()
    assert sanitize.wrap_dispatch("prog", fn, lock) is fn
    # even a None lock (engine built without one) keeps identity
    assert sanitize.wrap_dispatch("prog", fn, None) is fn


def test_unarmed_mark_and_check_are_noops():
    buf = np.zeros((2, 2), np.float32)
    sanitize.mark_donated({"w": buf}, "nowhere")
    sanitize.check_host_read({"w": buf}, "read")  # must not raise


def test_unknown_mode_raises():
    import os

    os.environ[sanitize.ENV_VAR] = "dispatch,bogus"
    with pytest.raises(ValueError, match="bogus"):
        sanitize.refresh()
    del os.environ[sanitize.ENV_VAR]
    sanitize.refresh()


# --------------------------------------------------------------- dispatch


def test_armed_lock_tracks_ownership(_sanitize_state):
    _arm(_sanitize_state, "dispatch")
    lock = sanitize.make_dispatch_lock()
    assert isinstance(lock, sanitize.SanitizedDispatchLock)
    assert not lock.owned()
    with lock:
        assert lock.owned()
        with lock:  # reentrant
            assert lock.owned()
        assert lock.owned()
    assert not lock.owned()


def test_armed_wrap_catches_unlocked_dispatch_from_worker_thread(_sanitize_state):
    _arm(_sanitize_state, "dispatch")
    lock = sanitize.make_dispatch_lock()
    calls = []
    wrapped = sanitize.wrap_dispatch("test/prog", lambda: calls.append(1), lock)
    assert wrapped.__wrapped__ is not None  # actually wrapped when armed

    errors = []

    def rogue():
        try:
            wrapped()  # intentionally unlocked — the PR 5 bug shape
        except sanitize.DispatchLockViolation as e:
            errors.append(e)

    t = threading.Thread(target=rogue, name="trlx-rogue-dispatcher")
    t.start()
    t.join()
    assert len(errors) == 1 and "test/prog" in str(errors[0])
    assert calls == []  # the dispatch was blocked, not executed

    # the same dispatch under the lock goes through
    def locked():
        with lock:
            wrapped()

    t = threading.Thread(target=locked, name="trlx-locked-dispatcher")
    t.start()
    t.join()
    assert calls == [1]


def test_armed_wrap_allows_serial_main_thread(_sanitize_state):
    """No other trlx-* thread alive → no hazard → unlocked main-thread
    dispatch is fine (the serial path must not need the lock)."""
    _arm(_sanitize_state, "dispatch")
    lock = sanitize.make_dispatch_lock()
    wrapped = sanitize.wrap_dispatch("p", lambda: "ok", lock)
    assert wrapped() == "ok"


# --------------------------------------------------------------- donation


def test_armed_donation_roundtrip_names_site(_sanitize_state):
    _arm(_sanitize_state, "donation")
    buf = np.zeros((4,), np.float32)
    tree = {"params": {"w": buf}, "step": 3}
    sanitize.mark_donated(tree, "train_step(state) [test]")
    with pytest.raises(sanitize.DonatedBufferRead, match=r"train_step\(state\)"):
        sanitize.check_host_read({"w": buf}, "checkpoint save")
    # unrelated buffers pass
    sanitize.check_host_read({"w": np.ones((4,), np.float32)}, "other")
    sanitize.clear_donated()
    sanitize.check_host_read({"w": buf}, "after clear")  # records dropped


def test_donation_walks_nested_containers(_sanitize_state):
    _arm(_sanitize_state, "donation")
    a, b = np.zeros((1,)), np.ones((2,))
    sanitize.mark_donated([{"x": (a,)}, b], "nested")
    for leaf in (a, b):
        with pytest.raises(sanitize.DonatedBufferRead):
            sanitize.check_host_read(leaf if leaf is b else {"k": [leaf]}, "read")
        sanitize.clear_donated()
        sanitize.mark_donated([{"x": (a,)}, b], "nested")


def test_donation_registry_is_capped(_sanitize_state):
    _arm(_sanitize_state, "donation")
    keep = [np.zeros((1,)) for _ in range(sanitize._DONATED_CAP + 10)]
    sanitize.mark_donated(keep, "bulk")
    assert len(sanitize._DONATED) <= sanitize._DONATED_CAP

# ------------------------------------------------------------------- race


class _Shared:
    pass


def _on_thread(fn, name="trlx-test-worker"):
    err = []

    def run():
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            err.append(e)

    t = threading.Thread(target=run, name=name, daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    if err:
        raise err[0]


def test_unarmed_race_factories_are_plain_and_access_is_noop():
    lock = sanitize.make_lock("X.lock")
    cond = sanitize.make_condition("X.cv")
    assert type(lock) is type(threading.Lock())
    assert type(cond) is threading.Condition
    obj = _Shared()
    sanitize.race_access(obj, "f", write=True)
    _on_thread(lambda: sanitize.race_access(obj, "f", write=True))
    assert len(sanitize._RACE_FIELDS) == 0  # zero residue


def test_race_two_thread_conflict_names_both_sites(_sanitize_state):
    _arm(_sanitize_state, "race")
    obj = _Shared()
    _on_thread(lambda: sanitize.race_access(obj, "count", write=True))
    with pytest.raises(sanitize.RaceViolation) as exc:
        sanitize.race_access(obj, "count", write=True)
    msg = str(exc.value)
    assert "'count'" in msg and "_Shared" in msg
    assert "trlx-test-worker" in msg  # the other thread, by name
    assert "MainThread" in msg
    assert msg.count("test_sanitize.py") >= 2  # both stacks point here
    # the raise resets the field to the current thread: no raise-storm
    sanitize.race_access(obj, "count", write=True)


def test_race_common_tracked_lock_is_clean(_sanitize_state):
    _arm(_sanitize_state, "race")
    obj = _Shared()
    lock = sanitize.make_lock("Shared.lock")
    assert isinstance(lock, sanitize.TrackedLock)

    def locked_write():
        with lock:
            sanitize.race_access(obj, "count", write=True)

    _on_thread(locked_write)
    locked_write()  # same lock on the main thread: lockset stays non-empty


def test_race_tracked_condition_counts_as_held(_sanitize_state):
    _arm(_sanitize_state, "race")
    obj = _Shared()
    cv = sanitize.make_condition("Shared.cv")
    assert isinstance(cv, sanitize.TrackedCondition)

    def guarded():
        with cv:
            sanitize.race_access(obj, "ready", write=True)
            cv.notify_all()

    _on_thread(guarded)
    guarded()


def test_race_queue_handoff_with_forget_is_clean(_sanitize_state):
    # The allowlisted-handoff pattern at runtime: worker builds the object,
    # ships it through a Queue (a happens-before edge), and the consumer
    # marks the ownership transfer with race_forget before touching it.
    import queue

    _arm(_sanitize_state, "race")
    box = queue.Queue()

    def producer():
        obj = _Shared()
        sanitize.race_access(obj, "payload", write=True)
        obj.payload = 1
        box.put(obj)

    _on_thread(producer)
    obj = box.get(timeout=5)
    sanitize.race_forget(obj)
    sanitize.race_access(obj, "payload", write=True)  # no raise: new owner


def test_race_read_read_never_raises(_sanitize_state):
    _arm(_sanitize_state, "race")
    obj = _Shared()
    _on_thread(lambda: sanitize.race_access(obj, "cfg"))
    sanitize.race_access(obj, "cfg")  # concurrent reads are fine


def test_race_registry_is_capped(_sanitize_state):
    _arm(_sanitize_state, "race")
    keep = [_Shared() for _ in range(sanitize._RACE_CAP + 16)]
    for obj in keep:
        sanitize.race_access(obj, "f", write=True)
    assert len(sanitize._RACE_FIELDS) <= sanitize._RACE_CAP
