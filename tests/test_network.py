"""Network-gated acceptance suite — the reference's de-facto acceptance bar
(its examples running to reward improvement, reference:
examples/ppo_sentiments.py:10-26, README.md:22-43) as executable gates.

Skipped unless TRLX_TPU_NETWORK=1: each test downloads HF checkpoints +
datasets (lvwerra/gpt2-imdb, lvwerra/distilbert-imdb, imdb, EleutherAI/gpt-j-6B)
and runs minutes-to-hours depending on hardware. See RUNBOOK.md for the
one-command-per-config invocations and the day-one calibration notes.

Pass criterion: ABSOLUTE threshold or IMPROVEMENT over the run's own first
eval — robust to the unmeasured starting point of each checkpoint.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

NETWORK = os.environ.get("TRLX_TPU_NETWORK") == "1"

pytestmark = [
    pytest.mark.network,
    pytest.mark.skipif(not NETWORK, reason="needs network + HF downloads (set TRLX_TPU_NETWORK=1)"),
]


def _trajectory(checkpoint_dir, key):
    """All values of `key` logged to the run's metrics.jsonl, in order.
    A `histogram:<name>` key reads the mean of that logged histogram (the
    Tracker stores summary stats for histograms, utils/logging.py)."""
    vals = []
    hist = key.split(":", 1)[1] if key.startswith("histogram:") else None
    with open(os.path.join(checkpoint_dir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if hist is not None:
                if rec.get("histogram") == hist:
                    vals.append(float(rec["mean"]))
            elif key in rec:
                vals.append(float(rec[key]))
    return vals


def _assert_learned(vals, absolute, improvement, what):
    assert vals, f"no {what} evals were logged"
    first, best = vals[0], max(vals)
    assert best >= absolute or best >= first + improvement, (
        f"{what}: first={first:.3f} best={best:.3f} — neither the absolute "
        f"gate ({absolute}) nor +{improvement} improvement was reached; "
        f"trajectory={['%.3f' % v for v in vals]}"
    )


def test_ppo_sentiments(tmp_path):
    """gpt2-imdb + distilbert sentiment reward (reference acceptance config:
    configs/ppo_config.yml). Gate: mean positive-sentiment score reaches 0.8,
    or improves ≥0.15 over the run's own first eval."""
    from datasets import load_dataset

    import ppo_sentiments
    import trlx_tpu
    from trlx_tpu.trainer.api import default_config

    config = default_config("ppo")
    config.train.checkpoint_dir = str(tmp_path)
    config.train.total_steps = int(os.environ.get("TRLX_TPU_NETWORK_STEPS", 400))

    imdb = load_dataset("imdb", split="train+test")
    prompts = [" ".join(review.split()[:4]) for review in imdb["text"]]
    trlx_tpu.train(
        "lvwerra/gpt2-imdb",
        reward_fn=ppo_sentiments.build_reward_fn(),
        prompts=prompts,
        eval_prompts=["I don't know much about Hungarian underground"] * 64,
        config=config,
    )
    _assert_learned(_trajectory(str(tmp_path), "mean_reward"), 0.8, 0.15, "ppo_sentiments mean_reward")


def test_ilql_sentiments(tmp_path):
    """gpt2 on (imdb text, label) pairs (reference acceptance config:
    configs/ilql_config.yml). Gate: mean sentiment metric reaches 0.7, or
    improves ≥0.1 over the first eval."""
    from datasets import load_dataset

    import ilql_sentiments
    import trlx_tpu
    from trlx_tpu.trainer.api import default_config

    config = default_config("ilql")
    config.train.checkpoint_dir = str(tmp_path)
    config.train.total_steps = int(os.environ.get("TRLX_TPU_NETWORK_STEPS", 400))

    imdb = load_dataset("imdb", split="train")
    trlx_tpu.train(
        "gpt2",
        dataset=(imdb["text"], imdb["label"]),
        eval_prompts=["I don't know much about Hungarian underground"] * 64,
        metric_fn=ilql_sentiments.build_metric_fn(),
        config=config,
    )
    _assert_learned(
        _trajectory(str(tmp_path), "metrics/sentiments"), 0.7, 0.1, "ilql_sentiments metric"
    )


def test_ppo_gptj(tmp_path):
    """GPT-J-6B PPO (the reference's largest shipped recipe,
    reference: configs/ppo_gptj.yml). Needs a mesh that fits 6B — a v4-32
    slice per ppo_gptj_config.yml (fsdp=4 × tp=2). Gate: reward improves
    ≥0.15 over the run's first eval (absolute sentiment 0.8 also passes)."""
    from datasets import load_dataset

    import ppo_sentiments
    import trlx_tpu
    from trlx_tpu.trainer.api import default_config

    config = default_config("ppo_gptj")
    config.train.checkpoint_dir = str(tmp_path)
    config.train.total_steps = int(os.environ.get("TRLX_TPU_NETWORK_STEPS", 200))

    imdb = load_dataset("imdb", split="train+test")
    prompts = [" ".join(review.split()[:4]) for review in imdb["text"]]
    trlx_tpu.train(
        "EleutherAI/gpt-j-6B",
        reward_fn=ppo_sentiments.build_reward_fn(),
        prompts=prompts,
        eval_prompts=["I don't know much about Hungarian underground"] * 32,
        config=config,
    )
    _assert_learned(_trajectory(str(tmp_path), "mean_reward"), 0.8, 0.15, "ppo_gptj mean_reward")


def test_simulacra(tmp_path):
    """Offline ILQL on Simulacra aesthetic ratings (reference:
    examples/simulacra.py). No task metric_fn exists, so the gate is on the
    eval generations' mean value-head estimate ("metrics" are the rating
    scale 1-10): the advantage-steered sampler's mean predicted return must
    improve ≥0.3 over the run's first eval."""
    import simulacra
    import trlx_tpu
    from trlx_tpu.trainer.api import default_config

    config = default_config("ilql")
    config.train.checkpoint_dir = str(tmp_path)
    config.train.total_steps = int(os.environ.get("TRLX_TPU_NETWORK_STEPS", 400))

    prompts, ratings = simulacra.load_ratings(str(tmp_path / "sac.sqlite"))
    trlx_tpu.train("gpt2", dataset=(prompts, ratings), eval_prompts=["Hatsune Miku, Red Dress"] * 64, config=config)
    vals = _trajectory(str(tmp_path), "histogram:decode/vs")
    _assert_learned(vals, 6.0, 0.3, "simulacra mean predicted rating (V head)")


def test_architext(tmp_path):
    """PPO room-count reward on architext/gptj-162M (reference:
    examples/architext.py). Reward = −(":" count); gate: mean reward improves
    ≥0.5 rooms over the run's first eval (fewer rooms drawn)."""
    import architext
    import trlx_tpu
    from trlx_tpu.trainer.api import default_config

    config = default_config("ppo")
    config.train.checkpoint_dir = str(tmp_path)
    config.train.total_steps = int(os.environ.get("TRLX_TPU_NETWORK_STEPS", 400))

    trlx_tpu.train(
        "architext/gptj-162M",
        reward_fn=architext.reward_fn,
        prompts=architext.PROMPTS,
        eval_prompts=architext.PROMPTS,
        config=config,
    )
    _assert_learned(_trajectory(str(tmp_path), "mean_reward"), -1.0, 0.5, "architext mean_reward")
