"""W8A16 decode: int8 weight-only trunk kernels for rollout sampling.
QDense without the `qw` collection must be exactly nn.Dense (the whole
existing suite pins that); these tests cover the quantized path."""

import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import trlx_tpu  # noqa: E402
from randomwalks import base_config, generate_random_walks  # noqa: E402
from trlx_tpu.models import TransformerLM  # noqa: E402
from trlx_tpu.models.lm import LMConfig, quantize_weights  # noqa: E402

pytestmark = pytest.mark.slow  # excluded from `make test-fast` (see conftest)


def _tiny_cfg():
    return LMConfig.from_dict(
        dict(
            vocab_size=97, n_layer=2, n_head=4, d_model=64, max_position=64,
            pos_type="rotary", rotary_dim=8, parallel_residual=True,
            fused_qkv=False, qkv_bias=False, out_bias=False,
            tie_word_embeddings=False, activation="gelu_new",
        )
    )


def test_quantized_logits_close_and_structure():
    """`qw` collection: every trunk matmul kernel gets an int8 copy +
    per-output-channel scale; logits with quantized weights stay close to
    full precision (W8 per-channel is near-lossless)."""
    cfg = _tiny_cfg()
    model = TransformerLM(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 97, size=(2, 12)))
    mask = jnp.ones_like(ids)
    params = model.init(jax.random.PRNGKey(0), ids, mask)["params"]

    qw = quantize_weights(params)
    # structure: per-layer attn/mlp kernels + lm_head, int8 + f32 scales
    assert qw["h_0"]["attn"]["q_proj"]["kernel_q"].dtype == jnp.int8
    assert qw["h_0"]["mlp"]["c_fc"]["scale"].shape == (cfg.ff_dim,)
    assert "lm_head" in qw
    assert "wte" not in qw and "ln_f" not in qw  # embeddings/norms stay fp

    full = model.apply({"params": params}, ids, mask)["logits"]
    quant = model.apply({"params": params, "qw": qw}, ids, mask)["logits"]
    full, quant = np.asarray(full, np.float32), np.asarray(quant, np.float32)
    assert not np.array_equal(full, quant)  # the int8 path actually ran
    # near-lossless: small absolute logit perturbation relative to the range
    denom = np.abs(full).max()
    assert np.abs(quant - full).max() / denom < 0.05, (
        np.abs(quant - full).max(), denom
    )


def test_w8_decode_learning_gate(tmp_path):
    """Learning-quality gate with W8A16 decode ON (+ fused stats + int8 KV —
    the full quantized sampling stack): randomwalks must still reach ≥0.8
    optimality; the stored behavior logprobs are the quantized sampler's
    own, so PPO stays on-policy by construction."""
    n_nodes, max_length = 21, 10
    walks, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=n_nodes, max_length=max_length
    )
    config = base_config("ppo", n_nodes, max_length)
    config.train.total_steps = 48
    config.train.eval_interval = 16
    config.train.checkpoint_interval = 10**6
    config.train.checkpoint_dir = str(tmp_path)
    config.train.batch_size = 48
    config.model.num_layers_unfrozen = 1
    config.model.kv_cache_quant = True
    config.model.decode_weight_quant = True
    config.method.num_rollouts = 96
    config.method.chunk_size = 48

    history = []

    def gated_metric(samples):
        m = metric_fn(samples)
        history.append(float(np.mean(m["optimality"])))
        return m

    prompts = [[int(np.random.default_rng(i).integers(1, n_nodes))] for i in range(96)]
    model = trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts,
        eval_prompts=[[i] for i in range(1, n_nodes)], metric_fn=gated_metric,
        config=config, logit_mask=logit_mask,
    )
    assert model._qw is not None  # the quantized path actually engaged
    assert history and max(history) >= 0.8, f"W8-decode optimality history: {history}"


def test_w8_requantizes_after_policy_update(tmp_path):
    """The int8 decode kernels must track the LIVE policy: after training
    steps + post_epoch_callback, the qw tree differs from the initial one."""
    from trlx_tpu.trainer.ppo import PPOTrainer

    walks, logit_mask, metric_fn, reward_fn = generate_random_walks(15, 8, 60, seed=1000)
    config = base_config("ppo", 15, 8)
    # total_steps must cross an epoch boundary (ppo_epochs=4 × 1 batch per
    # epoch) so post_epoch_callback — where the re-quantize lives — fires.
    config.train.total_steps = 6
    config.train.epochs = 2
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.checkpoint_dir = str(tmp_path)
    config.model.num_layers_unfrozen = 1  # hydra → fused path (W8 requires it)
    config.model.decode_weight_quant = True
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    initial_q = None

    orig_refresh = PPOTrainer._refresh_decode_weights
    changed = {"seen": False}

    def spy(self):
        nonlocal initial_q
        if initial_q is None:
            initial_q = np.asarray(self._qw["transformer"]["h_1"]["mlp"]["c_fc"]["kernel_q"]).copy()
        orig_refresh(self)
        if not np.array_equal(
            np.asarray(self._qw["transformer"]["h_1"]["mlp"]["c_fc"]["kernel_q"]), initial_q
        ):
            changed["seen"] = True

    PPOTrainer._refresh_decode_weights = spy
    try:
        trlx_tpu.train(
            reward_fn=reward_fn, prompts=prompts, eval_prompts=[[1]],
            metric_fn=metric_fn, config=config, logit_mask=logit_mask,
        )
    finally:
        PPOTrainer._refresh_decode_weights = orig_refresh
    assert changed["seen"], "decode kernels never re-quantized after updates"


def test_w8_refused_without_fused_path(tmp_path):
    """decode_weight_quant without the fused stats path (here: fully
    unfrozen, no hydra branch) must be refused — unfused scoring would
    recompute behavior logprobs at full precision against int8-sampled
    tokens, a silent off-policy bias."""
    from trlx_tpu.trainer.ppo import PPOTrainer

    config = base_config("ppo", 15, 8)
    config.train.checkpoint_dir = str(tmp_path)
    config.train.batch_size = 16
    config.method.chunk_size = 16
    config.model.num_layers_unfrozen = -1
    config.model.decode_weight_quant = True
    with pytest.raises(ValueError, match="fused"):
        PPOTrainer(config)


def test_w8_ref_branch_bias_bounded(tmp_path):
    """The KL's REF side also feels decode quantization: the fused scorer
    replays the frozen branch from hiddens produced by the int8 trunk
    (trainer/ppo.py rollout_score_fused), so ref logprobs carry a small
    quantization-induced bias vs a full-precision ref forward. Bound it
    directly: fused (quantized-hidden) vs unfused (full-precision) scoring on
    IDENTICAL tokens — the per-token ref-logprob delta must stay small."""
    from trlx_tpu.trainer.ppo import PPOTrainer

    walks, logit_mask, metric_fn, reward_fn = generate_random_walks(15, 8, 60, seed=1000)
    config = base_config("ppo", 15, 8)
    config.train.checkpoint_dir = str(tmp_path)
    config.train.batch_size = 16
    config.model.num_layers_unfrozen = 1  # hydra branch → fused path
    config.model.decode_weight_quant = True
    config.method.num_rollouts = 16
    config.method.chunk_size = 16
    trainer = PPOTrainer(config)
    assert trainer._qw is not None and trainer.fused_rollout

    B, P = 16, trainer.prompt_length
    rng = np.random.default_rng(0)
    prompts = rng.integers(1, 15, size=(B, P)).astype(np.int32)
    pmask = np.ones((B, P), np.int32)
    tokens, mask, stats, prefill = trainer.rollout_generate_fused(prompts, pmask)
    scores = rng.normal(size=(B,)).astype(np.float32)

    lp_f, _, _, kl_f = trainer.rollout_score_fused(tokens, mask, scores, (stats, prefill))
    lp_u, _, _, kl_u = trainer.rollout_score(tokens, mask, scores)

    # kl = lp - ref_lp per token (ops/rl_losses.kl_penalty_rewards), so the
    # ref-side logprobs are recoverable from each scorer's outputs.
    rlp_fused = np.asarray(lp_f) - np.asarray(kl_f)
    rlp_full = np.asarray(lp_u) - np.asarray(kl_u)
    rmask = np.asarray(mask)[:, P:].astype(bool)
    delta = np.abs(rlp_fused - rlp_full)[rmask]
    assert delta.max() < 0.05, f"ref-logprob quantization bias too large: {delta.max()}"
