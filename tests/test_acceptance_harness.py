"""Offline smoke of the network-day acceptance harness.

The four reference acceptance examples can only EXECUTE with egress
(RUNBOOK.md); this keeps `make acceptance-network` itself from bitrotting:
run the harness with network off, assert it completes, classifies every test
as skipped, and writes a well-formed ACCEPTANCE.json."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_acceptance_harness_offline(tmp_path):
    out = tmp_path / "ACCEPTANCE.json"
    env = dict(os.environ)
    env.pop("TRLX_TPU_NETWORK", None)
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import sys; sys.path.insert(0, %r); import acceptance_network as a; "
        "r = a.main(out_path=%r); sys.exit(0 if r['status'] == 'skipped-no-network' else 2)"
        % (REPO, str(out))
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO)
    assert proc.returncode == 0

    result = json.loads(out.read_text())
    assert result["status"] == "skipped-no-network"
    assert set(result["tests"]) == {
        "test_ppo_sentiments", "test_ilql_sentiments", "test_ppo_gptj",
        "test_simulacra", "test_architext",
    }
    for t, rec in result["tests"].items():
        assert rec["outcome"] == "skipped", (t, rec)
        assert rec["trajectory"] == []
        assert rec["reference_config"]
