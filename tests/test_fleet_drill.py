"""2-process graftfleet drills (trlx_tpu/observability/fleet.py).

tests/test_fleet.py proves the federation pieces in isolation; these drills
prove the CROSS-HOST story with real jax.distributed processes on CPU:

- drill A (``slow_host``): host 1 stalls 2s at steps 2 and 4 with graftfleet
  + the metrics endpoint armed → ONE merged Chrome trace with a lane per
  host and a stated clock-alignment bound, a per-collective skew table whose
  worst-host column names the injected laggard, live ``trlx_tpu_fleet_*``
  gauges (per-host labeled) in a /metrics scrape taken DURING the run, and a
  /healthz ``fleet`` block carrying both hosts' heartbeats.
- drill B (``host_hang``): host 1 wedges → host 0's collective_guard abort
  (exit EXIT_COLLECTIVE_TIMEOUT) leaves a fleet incident bundle under
  ``incidents/<step>/`` containing BOTH hosts' span tails — the aborting
  host collects its wedged peer's file from the shared checkpoint dir.

When ``TRLX_TPU_DRILL_ARTIFACTS`` is set (the CI job does), the merged
fleet trace, the report's Fleet section, and the live scrapes are copied
there for upload. Skipped gracefully (same patterns as
tests/test_distributed_resilience.py) when the environment can't run two
coordinated jax.distributed processes. Run via ``make fleet-drill`` (which
also arms TRLX_TPU_SANITIZE=dispatch,donation,race) or ``make
test-multihost`` — slow-marked, excluded from the fast tier.
"""

import json
import os
import shutil
import socket
import subprocess
import sys

import pytest

from trlx_tpu.resilience.distributed import EXIT_COLLECTIVE_TIMEOUT

pytestmark = pytest.mark.slow  # excluded from `make test-fast` (see conftest)

_DRILL_WORKER = r"""
import json, os, sys, threading, time
import urllib.request

mode = sys.argv[1]  # "slow" | "hang" | "engine" | "engine_spec" | "engine_kill"
pid = int(sys.argv[2])
port = sys.argv[3]
ckpt = sys.argv[4]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["TRLX_TPU_NO_PROGRESS"] = "1"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid,
    local_device_ids=[0, 1],
)
assert jax.process_count() == 2

sys.path.insert(0, os.path.join(os.environ["TRLX_REPO"], "examples"))
import trlx_tpu
from randomwalks import base_config, generate_random_walks

walks, logit_mask, metric_fn, reward_fn = generate_random_walks(
    n_nodes=15, max_length=8, n_walks=60, seed=1000
)

per = 8  # per-process rows

def make_config(total_steps):
    config = base_config("ppo", 15, 8)
    config.train.total_steps = total_steps
    config.train.epochs = 100
    config.train.batch_size = per
    config.train.eval_interval = 10**6
    config.train.checkpoint_interval = 10**6
    config.train.checkpoint_dir = ckpt
    config.train.mesh = [4, 1, 1, 1]
    config.method.num_rollouts = per
    config.method.chunk_size = per
    config.method.ppo_epochs = 2
    config.train.graftfleet = True  # config-consistent across hosts
    config.train.heartbeat_interval = 0.2
    # Generous deadline: it must cover first-call compilation of any program
    # launched INSIDE a guarded collective on a loaded CI core, while still
    # converting drill B's real hang into an abort within the test budget.
    config.train.collective_deadline = 30.0
    config.train.desync_check_interval = 1  # a guarded allgather every step
    if mode == "slow":
        # Per-step log boundaries feed the fleet window rollup + exporter;
        # a resync mid-run exercises the periodic clock re-estimate.
        config.train.log_interval = 1
        config.train.fleet_resync_interval = 2
    else:
        # Buffered scalars must never flush mid-drill: the first cross-host
        # BLOCKING op after the injected hang has to be the GUARDED
        # fingerprint allgather, not an unguarded stats sync.
        config.train.log_interval = 10**6
    return config

if mode in ("engine", "engine_spec", "engine_kill"):
    # Multi-process ENGINE contract (engine/rollout_engine.py): every host
    # submits the SAME global prompt set — identical slot schedules by
    # construction, verified per phase by the slot-schedule crc.
    prompts = [[(i % 14) + 1] for i in range(8)]
else:
    prompts = [[(i % 14) + 1] for i in range(8 * pid, 8 * (pid + 1))]
eval_prompts = [[1], [2]]

scrapes_stop = threading.Event()

def scrape_loop():
    # Live-endpoint witness: poll the exporter DURING the run and keep the
    # freshest scrape that already carries fleet gauges / the fleet block.
    mport = int(os.environ.get("TRLX_TPU_METRICS_PORT", "0"))
    while not scrapes_stop.is_set():
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/metrics", timeout=2
            ) as r:
                body = r.read().decode()
            if "trlx_tpu_fleet_hosts" in body:
                with open(os.path.join(ckpt, "scrape_metrics.txt"), "w") as f:
                    f.write(body)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/healthz", timeout=2
            ) as r:
                payload = json.loads(r.read().decode())
            if "fleet" in payload:
                with open(os.path.join(ckpt, "scrape_healthz.json"), "w") as f:
                    json.dump(payload, f)
        except Exception:
            pass  # exporter not up yet / mid-teardown
        scrapes_stop.wait(0.3)

if mode == "slow":
    scraper = None
    if pid == 0:
        os.makedirs(ckpt, exist_ok=True)
        scraper = threading.Thread(target=scrape_loop, daemon=True)
        scraper.start()
    try:
        trlx_tpu.train(
            reward_fn=reward_fn, prompts=prompts, eval_prompts=eval_prompts,
            metric_fn=metric_fn, config=make_config(6), logit_mask=logit_mask,
        )
    finally:
        scrapes_stop.set()
        if scraper is not None:
            scraper.join(timeout=5)
    print(f"fleet slow proc {pid} DONE")

elif mode == "hang":
    # Proc 1 carries host_hang@2 (from its env) and wedges after step 2;
    # proc 0 blocks in the step-3 fingerprint allgather, the guard aborts it
    # (exit 117) and its _fire path writes the FLEET incident bundle — this
    # print is only reachable if detection FAILED.
    trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=eval_prompts,
        metric_fn=metric_fn, config=make_config(10), logit_mask=logit_mask,
    )
    print(f"fleet hang proc {pid} FINISHED WITHOUT ABORT")

elif mode in ("engine", "engine_spec", "engine_kill"):
    # 2-process continuous-batching engine run: replicated slot state
    # (_globalize), identical schedules cross-checked per phase by
    # verify_engine_schedule under the engine/schedule_verify guard.
    # - clean leg: completes → proves the per-phase crc check passes when
    #   schedules really match;
    # - engine_spec: same clean leg with SPECULATION armed — each verify
    #   dispatch folds its accepted-token total into the schedule crc, so
    #   the per-phase check also proves the two hosts accepted identical
    #   draft prefixes on every dispatch;
    # - TRLX_TPU_ENGINE_SCHEDULE_SKEW on proc 1: the phase-end check raises
    #   HostDesync NAMING host 1 on every host — desync by name, not hang;
    # - engine_kill: proc 1 carries mid_decode_host_kill@2 and dies abruptly
    #   between decode syncs with slots live; proc 0 blocks on the dead peer
    #   at its next guarded cross-host sync and aborts exit-117 with an
    #   incident bundle carrying its slot states — this FINISHED print is
    #   only reachable on proc 0 if detection FAILED.
    config = make_config(10 if mode == "engine_kill" else 3)
    config.method.rollout_engine = True
    config.method.engine_steps_per_sync = 2
    if mode == "engine_spec":
        config.method.spec_decode = "ngram"
        config.method.spec_k = 3
    if mode in ("engine_spec", "engine_kill"):
        # Paged KV armed: these drills double as POOL LEAK drills. Trainer
        # teardown runs engine.abort(), whose BlockPool.leak_audit raises a
        # named RuntimeError on any lost/double-freed block — so the DONE
        # marker below is unreachable if the fleet path leaks pool blocks,
        # and the pool's table rows fold into the same slot-schedule crc
        # the per-phase check verifies across hosts.
        config.method.paged_kv = True
        config.method.kv_block_size = 4
    trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=eval_prompts,
        metric_fn=metric_fn, config=config, logit_mask=logit_mask,
    )
    print(f"fleet {mode} proc {pid} FINISHED WITHOUT ABORT"
          if mode == "engine_kill" else f"fleet {mode} proc {pid} DONE")
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(tmp_path, mode, faults_by_pid, metrics_port=0, env_by_pid=None):
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "fleet_drill_worker.py"
    script.write_text(_DRILL_WORKER)
    ckpt = str(tmp_path / f"ckpt_fleet_{mode}")
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("TRLX_TPU_FAULTS", None)
        env.pop("TRLX_TPU_ENGINE_SCHEDULE_SKEW", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo
        env["TRLX_REPO"] = repo
        if metrics_port:
            # Same knob on EVERY process (the multi-host gauge rollup is a
            # collective); only process 0 actually binds the exporter.
            env["TRLX_TPU_METRICS_PORT"] = str(metrics_port)
        if pid in faults_by_pid:
            env["TRLX_TPU_FAULTS"] = faults_by_pid[pid]
        env.update((env_by_pid or {}).get(pid, {}))
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script), mode, str(pid), str(port), ckpt],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            )
        )
    return procs, ckpt


def _skip_if_distributed_unavailable(proc, out):
    if proc.returncode != 0 and (
        ("initialize" in out and "failed" in out.lower())
        or "Multiprocess computations aren't implemented" in out
    ):
        pytest.skip(f"jax.distributed unavailable here: {out[-400:]}")


def _export_artifacts(ckpt, extra=()):
    """Copy the drill's fleet artifacts where CI uploads them (no-op when
    TRLX_TPU_DRILL_ARTIFACTS is unset)."""
    dest = os.environ.get("TRLX_TPU_DRILL_ARTIFACTS")
    if not dest:
        return
    os.makedirs(dest, exist_ok=True)
    from trlx_tpu.observability.report import _fleet_section
    from trlx_tpu.observability.spans import read_fleet_spans

    merged = read_fleet_spans(ckpt)
    with open(os.path.join(dest, "fleet_trace.json"), "w") as f:
        json.dump({"traceEvents": merged["traceEvents"]}, f)
    with open(os.path.join(dest, "fleet_report.md"), "w") as f:
        f.write("\n".join(_fleet_section(ckpt)))
    for name in extra:
        src = os.path.join(ckpt, name)
        if os.path.exists(src):
            if os.path.isdir(src):
                shutil.copytree(src, os.path.join(dest, name), dirs_exist_ok=True)
            else:
                shutil.copy(src, os.path.join(dest, name))


def _communicate(procs):
    """Collect both drill processes' merged output, skipping (not failing)
    when the environment can't finish a 2-process run in the budget."""
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=900)
            outs.append(out.decode(errors="replace"))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process drill did not complete in this environment")
    return outs


def test_fleet_drill_slow_host_attribution_and_live_gauges(tmp_path):
    """Drill A: host 1 stalls at steps 2 and 4 → merged trace, skew table
    naming host 1, live fleet gauges, and the /healthz fleet block."""
    from trlx_tpu.observability import fleet as obs_fleet
    from trlx_tpu.observability.export import sanitize_metric_name
    from trlx_tpu.observability.report import _fleet_section
    from trlx_tpu.observability.spans import read_fleet_spans

    metrics_port = _free_port()
    procs, ckpt = _launch(
        tmp_path, "slow", {1: "slow_host@2,slow_host@4"}, metrics_port=metrics_port
    )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=900)
            outs.append(out.decode(errors="replace"))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process drill did not complete in this environment")
    try:
        for pid, (p, out) in enumerate(zip(procs, outs)):
            _skip_if_distributed_unavailable(p, out)
            assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
            assert f"fleet slow proc {pid} DONE" in out

        # ONE merged Chrome trace: a process lane per host, clocks aligned
        # into host 0's frame under a STATED error bound.
        merged = read_fleet_spans(ckpt)
        assert merged["hosts"] == [0, 1]
        assert merged["clock"] is not None
        assert 0.0 < merged["alignment_error_s"] < 5.0
        lanes = {
            e["pid"]: e["args"]["name"]
            for e in merged["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        assert set(lanes) == {0, 1}
        assert "clock offset" in lanes[1]
        # Both hosts contributed real spans (the guards' collective/* boxes).
        for host in (0, 1):
            assert any(
                e.get("ph") == "X" and e.get("pid") == host
                for e in merged["traceEvents"]
            ), f"host {host} has no spans in the merged trace"

        # Per-collective skew table: the worst-host column names the
        # injected laggard, and the 2s stall dominates the max column.
        rows = obs_fleet.collective_skew_table(ckpt)
        assert rows, "no collective arrival records federated"
        worst_rows = [r for r in rows if r["worst_host"] is not None]
        assert worst_rows, f"no site attributed a straggler: {rows}"
        assert all(r["worst_host"] == 1 for r in worst_rows), worst_rows
        assert max(r["max_ms"] for r in worst_rows) > 1000.0  # the 2s sleeps

        # Live /metrics scrape taken DURING the run: fleet gauges with
        # per-host labels/keys, via the real exporter.
        with open(os.path.join(ckpt, "scrape_metrics.txt")) as f:
            scrape = f.read()
        assert sanitize_metric_name("trlx_tpu_fleet/hosts") + " 2.0" in scrape
        assert sanitize_metric_name("trlx_tpu_fleet/collective_skew_ms") + "_bucket" in scrape
        assert 'site="' in scrape  # per-site histogram labels
        assert sanitize_metric_name("trlx_tpu_fleet/host1_worst_arrivals_total") in scrape
        # per_host rollup rows: every host's own value, labeled by key path.
        assert sanitize_metric_name("trlx_tpu_fleet/host0/") in scrape
        assert sanitize_metric_name("trlx_tpu_fleet/host1/") in scrape

        # /healthz fleet block: both hosts' heartbeats + straggler verdict.
        with open(os.path.join(ckpt, "scrape_healthz.json")) as f:
            healthz = json.load(f)
        fleet_block = healthz["fleet"]
        assert fleet_block["hosts"] == 2
        assert {"0", "1"} <= set(fleet_block["heartbeats"])
        assert fleet_block["straggler"]["state"] in ("ok", "warn", "crit")
        assert len(fleet_block["clock"]["offsets_s"]) == 2

        # The report's Fleet section renders the same story.
        section = "\n".join(_fleet_section(ckpt))
        assert "clock-alignment error" in section
        assert "host 1" in section
    finally:
        _export_artifacts(ckpt, extra=("scrape_metrics.txt", "scrape_healthz.json"))


def test_fleet_drill_hang_leaves_cross_host_incident_bundle(tmp_path):
    """Drill B: host 1 wedges after step 2 → host 0's guard abort writes a
    fleet incident bundle holding BOTH hosts' span tails."""
    procs, ckpt = _launch(tmp_path, "hang", {1: "host_hang@2"})
    try:
        out0, _ = procs[0].communicate(timeout=900)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process drill did not complete in this environment")
    finally:
        procs[1].kill()  # intentionally hung for TRLX_TPU_HANG_SECONDS
        procs[1].communicate()
    out0 = out0.decode(errors="replace")
    _skip_if_distributed_unavailable(procs[0], out0)
    try:
        assert procs[0].returncode == EXIT_COLLECTIVE_TIMEOUT, (
            f"expected exit {EXIT_COLLECTIVE_TIMEOUT}, got {procs[0].returncode}:\n{out0[-4000:]}"
        )
        assert "FINISHED WITHOUT ABORT" not in out0

        incidents = os.path.join(ckpt, "incidents")
        fleet_bundles = [
            d
            for d in (os.listdir(incidents) if os.path.isdir(incidents) else [])
            if os.path.exists(os.path.join(incidents, d, "fleet_incident.json"))
        ]
        assert fleet_bundles, f"no fleet incident bundle under {incidents}"
        bundle = os.path.join(incidents, fleet_bundles[0])
        with open(os.path.join(bundle, "fleet_incident.json")) as f:
            manifest = json.load(f)
        assert manifest["reason"] == "collective_timeout"
        assert manifest["collected_by"] == 0  # the healthy host collected
        assert set(manifest["hosts"]) >= {0, 1}
        # BOTH hosts' span tails: the wedged peer's file came off the shared
        # checkpoint dir.
        for host in (0, 1):
            tail = os.path.join(bundle, f"host{host}", "spans_tail.jsonl")
            assert os.path.exists(tail), f"missing {tail}"
            assert os.path.getsize(tail) > 0
            with open(os.path.join(bundle, f"host{host}", "heartbeat.json")) as f:
                json.load(f)  # well-formed forensics payload
    finally:
        _export_artifacts(ckpt, extra=("incidents",))


# --------------------------------------- multi-host engine drills (PR 17)


def test_fleet_drill_engine_two_process_clean(tmp_path):
    """Drill C (clean leg): the continuous-batching engine runs at
    process_count()==2 — replicated slot state, identical per-host
    admission/harvest schedules — and the per-phase slot-schedule crc check
    passes on every phase. Both procs finish cleanly, no incident bundle."""
    procs, ckpt = _launch(tmp_path, "engine", {})
    outs = _communicate(procs)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        _skip_if_distributed_unavailable(p, out)
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
        assert f"fleet engine proc {pid} DONE" in out
    # A clean run must not leave collective-timeout forensics behind.
    incidents = os.path.join(ckpt, "incidents")
    bundles = [
        d
        for d in (os.listdir(incidents) if os.path.isdir(incidents) else [])
        if os.path.exists(os.path.join(incidents, d, "fleet_incident.json"))
    ]
    assert not bundles, f"clean engine drill left incident bundles: {bundles}"


def test_fleet_drill_engine_spec_two_process_clean(tmp_path):
    """Drill C (speculative leg, ISSUE 19): the engine runs at
    process_count()==2 WITH spec_decode armed. The host-side drafter makes
    identical proposals on every host (same prompt set, same accepted
    stream), every verify dispatch folds its accepted-token total into the
    slot-schedule crc, and the per-phase crc check stays clean — speculation
    does not desync the slot managers. The leg also arms method.paged_kv:
    every admission's block-table row folds into the same crc (identical
    allocators on identical streams), and teardown's pool leak_audit makes
    the DONE marker unreachable if spec verify windows leaked pool blocks."""
    procs, ckpt = _launch(tmp_path, "engine_spec", {})
    outs = _communicate(procs)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        _skip_if_distributed_unavailable(p, out)
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
        assert f"fleet engine_spec proc {pid} DONE" in out
    incidents = os.path.join(ckpt, "incidents")
    bundles = [
        d
        for d in (os.listdir(incidents) if os.path.isdir(incidents) else [])
        if os.path.exists(os.path.join(incidents, d, "fleet_incident.json"))
    ]
    assert not bundles, f"clean engine_spec drill left incident bundles: {bundles}"


def test_fleet_drill_engine_schedule_skew_is_named_desync(tmp_path):
    """Drill C (skew leg): host 1 reports a skewed slot-schedule crc
    (TRLX_TPU_ENGINE_SCHEDULE_SKEW — the injection signature of a desynced
    slot manager) → the phase-end check raises the identical HostDesync
    NAMING host 1 on BOTH hosts. Desync by name, never a hung collective."""
    procs, _ = _launch(
        tmp_path,
        "engine",
        {},
        env_by_pid={1: {"TRLX_TPU_ENGINE_SCHEDULE_SKEW": "1"}},
    )
    outs = _communicate(procs)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        _skip_if_distributed_unavailable(p, out)
        assert p.returncode != 0, (
            f"proc {pid} should have aborted on HostDesync:\n{out[-4000:]}"
        )
        assert f"fleet engine proc {pid} DONE" not in out
        # The coordinated abort names the skewed host and the component.
        assert "engine slot-schedule check failed" in out, out[-4000:]
        assert "host 1" in out
        assert "slot schedule crc32" in out


def test_fleet_drill_mid_decode_host_kill_exit117_with_slot_states(tmp_path):
    """Drill D: host 1 dies abruptly (os._exit) between decode syncs with
    slots mid-decode → host 0 hits its guarded cross-host engine sync, the
    collective_guard converts the dead peer into exit 117, and the fleet
    incident bundle names the wedged engine collective AND carries host 0's
    per-slot states at abort time. Runs with method.paged_kv armed: the
    kill lands with pool blocks pinned mid-decode, and the survivor's
    teardown must not trip the pool leak audit on its way to the bundle."""
    procs, ckpt = _launch(tmp_path, "engine_kill", {1: "mid_decode_host_kill@2"})
    try:
        out0, _ = procs[0].communicate(timeout=900)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process drill did not complete in this environment")
    finally:
        procs[1].kill()  # no-op when the fault already os._exit(1)'d it
        procs[1].communicate()
    out0 = out0.decode(errors="replace")
    _skip_if_distributed_unavailable(procs[0], out0)
    try:
        assert procs[1].returncode == 1, (
            f"proc 1 should have died via mid_decode_host_kill, "
            f"got {procs[1].returncode}"
        )
        assert procs[0].returncode == EXIT_COLLECTIVE_TIMEOUT, (
            f"expected exit {EXIT_COLLECTIVE_TIMEOUT}, "
            f"got {procs[0].returncode}:\n{out0[-4000:]}"
        )
        assert "FINISHED WITHOUT ABORT" not in out0

        incidents = os.path.join(ckpt, "incidents")
        fleet_bundles = [
            d
            for d in (os.listdir(incidents) if os.path.isdir(incidents) else [])
            if os.path.exists(os.path.join(incidents, d, "fleet_incident.json"))
        ]
        assert fleet_bundles, f"no fleet incident bundle under {incidents}"
        bundle = os.path.join(incidents, fleet_bundles[0])
        with open(os.path.join(bundle, "fleet_incident.json")) as f:
            manifest = json.load(f)
        assert manifest["reason"] == "collective_timeout"
        assert manifest["collected_by"] == 0  # the surviving host collected
        detail = manifest["detail"]
        # The wedged collective is one of the engine's guarded syncs — both
        # carry the engine's slot states in their forensics detail.
        assert detail["collective"] in (
            "engine/schedule_verify",
            "engine/decode_sync",
        ), detail
        assert "slot_states" in detail, detail
        assert isinstance(detail["slot_states"], list)
        for slot in detail["slot_states"]:
            assert "slot" in slot and "n_gen" in slot and "version" in slot, slot
        # The survivor's own span tail made it into the bundle.
        tail = os.path.join(bundle, "host0", "spans_tail.jsonl")
        assert os.path.exists(tail) and os.path.getsize(tail) > 0
    finally:
        _export_artifacts(ckpt, extra=("incidents",))
