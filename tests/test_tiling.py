"""Tier-1 (fast, CPU) static tile-legality tests for the Pallas kernels.

The Mosaic last-two-dims (8, 128)-or-full rule only bites at lowering time
on a real TPU — exactly how the old decode-attention kernel's (1, 1, d)
blocks survived CPU CI and then crashed BENCH_r05 mid-bench. These tests
run the rule statically at the REAL bench shapes (B=32, h=16, d=256,
T=832), so an illegal block mapping in ops/ fails the fast tier without
any TPU."""

import pytest

from trlx_tpu.ops.tiling import (
    BlockLayout,
    TileError,
    block_tile_issues,
    check_layout,
    decode_block_layout,
    flash_block_layout,
    is_tile_legal,
)

# The flagship bench decode shape (gptj-l8-d4096-2.0B: chunk 32 rows/host,
# 16 heads x 256 head_dim, prompt 768 + 64 decoded = 832 cache slots).
BENCH_B, BENCH_H, BENCH_D, BENCH_T = 32, 16, 256, 832


def test_rule_basics():
    # full blocks are always legal, any size
    assert not block_tile_issues((3, 5), (3, 5))
    # divisible blocks are legal
    assert not block_tile_issues((8, 128), (64, 832))
    assert not block_tile_issues((16, 256), (32, 16, 256)[1:])
    # sublane violation
    assert block_tile_issues((1, 128), (64, 832))
    # lane violation
    assert block_tile_issues((8, 100), (64, 832))
    # block larger than array can never map
    assert block_tile_issues((16, 128), (8, 832))
    # rank mismatch is flagged, not crashed on
    assert block_tile_issues((8, 128), (4, 64, 832))
    # rank-0/1 blocks are out of scope for the last-two-dims rule
    assert not block_tile_issues((7,), (9,))


def test_old_decode_specs_are_rejected():
    """The exact block shapes of the pre-rewrite kernel at the BENCH_r05
    crash shape — the validator must reject every one of them."""
    old = [
        BlockLayout("q", (1, 1, BENCH_D), (BENCH_B, BENCH_H, BENCH_D)),
        BlockLayout("k_cache", (1, BENCH_T, 1, BENCH_D), (BENCH_B, BENCH_T, BENCH_H, BENCH_D)),
        BlockLayout("k_scale", (1, BENCH_T, 1), (BENCH_B, BENCH_T, BENCH_H)),
        BlockLayout("bias", (1, BENCH_T), (BENCH_B, BENCH_T)),
    ]
    assert not is_tile_legal(old)
    # and each operand individually carries a violation the error names
    for lay in old:
        issues = block_tile_issues(lay.block_shape, lay.array_shape, lay.name)
        assert issues, f"{lay.name} should be illegal"
        assert lay.name in issues[0]
    with pytest.raises(TileError):
        check_layout(old)


@pytest.mark.parametrize("quant", (True, False))
def test_new_decode_specs_are_legal_at_bench_shape(quant):
    layouts = decode_block_layout(BENCH_B, BENCH_T, BENCH_H, BENCH_D, quant)
    check_layout(layouts)  # raises on violation
    # the q/out blocks really are the full [n_head, head_dim] planes
    by_name = {l.name: l for l in layouts}
    assert by_name["q"].block_shape == (1, BENCH_H, BENCH_D)
    assert by_name["out"].block_shape == (1, BENCH_H, BENCH_D)


@pytest.mark.parametrize(
    "T", (64, 100, 128, 200, 832, 833, 4096)
)
def test_decode_specs_legal_for_ragged_cache_lengths(T):
    """The masked tail removed the cache-length alignment restriction: the
    layout must stay tile-legal for ANY cache length, aligned or not."""
    check_layout(decode_block_layout(BENCH_B, T, BENCH_H, BENCH_D, True))
    check_layout(decode_block_layout(BENCH_B, T, BENCH_H, BENCH_D, False))


def test_decode_specs_legal_for_test_model_shapes():
    """Tiny shapes (CPU test models) are legal too — full blocks everywhere."""
    check_layout(decode_block_layout(2, 17, 2, 16, True))


def test_flash_specs_legal_at_bench_shape():
    from trlx_tpu.ops.flash_attention import pick_block

    T = 1024
    blk = pick_block(T)
    check_layout(flash_block_layout(BENCH_B * BENCH_H, T, BENCH_D, blk, blk))


def test_routing_probe_refuses_illegal_layout(monkeypatch):
    """decode_attn_supported answers False (with a warning, once) when the
    static layout check fails — the einsum fallback path in the model layer
    keys off this instead of crashing in Mosaic."""
    import warnings

    from trlx_tpu.ops import decode_attention as da
    from trlx_tpu.ops import tiling

    def bad_layout(B, T, h, d, quant, block_t=None):
        return [BlockLayout("q", (1, 1, d), (B, h, d))]

    da._PROBE_CACHE.clear()
    monkeypatch.setattr(tiling, "decode_block_layout", bad_layout)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert not da.decode_attn_supported(4, 64, 4, 128, True)
        assert any("falling back to the einsum" in str(x.message) for x in w)
    # cached: the next call must not warn again
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert not da.decode_attn_supported(4, 64, 4, 128, True)
        assert not w
    da._PROBE_CACHE.clear()
