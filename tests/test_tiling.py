"""Tier-1 (fast, CPU) static tile-legality tests for the Pallas kernels.

The Mosaic last-two-dims (8, 128)-or-full rule only bites at lowering time
on a real TPU — exactly how the old decode-attention kernel's (1, 1, d)
blocks survived CPU CI and then crashed BENCH_r05 mid-bench. These tests
run the rule statically at the REAL bench shapes (B=32, h=16, d=256,
T=832), so an illegal block mapping in ops/ fails the fast tier without
any TPU."""

import pytest

from trlx_tpu.ops.tiling import (
    BlockLayout,
    TileError,
    block_tile_issues,
    check_layout,
    decode_block_layout,
    flash_block_layout,
    is_tile_legal,
)

# The flagship bench decode shape (gptj-l8-d4096-2.0B: chunk 32 rows/host,
# 16 heads x 256 head_dim, prompt 768 + 64 decoded = 832 cache slots).
BENCH_B, BENCH_H, BENCH_D, BENCH_T = 32, 16, 256, 832


def test_rule_basics():
    # full blocks are always legal, any size
    assert not block_tile_issues((3, 5), (3, 5))
    # divisible blocks are legal
    assert not block_tile_issues((8, 128), (64, 832))
    assert not block_tile_issues((16, 256), (32, 16, 256)[1:])
    # sublane violation
    assert block_tile_issues((1, 128), (64, 832))
    # lane violation
    assert block_tile_issues((8, 100), (64, 832))
    # block larger than array can never map
    assert block_tile_issues((16, 128), (8, 832))
    # rank mismatch is flagged, not crashed on
    assert block_tile_issues((8, 128), (4, 64, 832))
    # rank-0/1 blocks are out of scope for the last-two-dims rule
    assert not block_tile_issues((7,), (9,))


def test_old_decode_specs_are_rejected():
    """The exact block shapes of the pre-rewrite kernel at the BENCH_r05
    crash shape — the validator must reject every one of them."""
    old = [
        BlockLayout("q", (1, 1, BENCH_D), (BENCH_B, BENCH_H, BENCH_D)),
        BlockLayout("k_cache", (1, BENCH_T, 1, BENCH_D), (BENCH_B, BENCH_T, BENCH_H, BENCH_D)),
        BlockLayout("k_scale", (1, BENCH_T, 1), (BENCH_B, BENCH_T, BENCH_H)),
        BlockLayout("bias", (1, BENCH_T), (BENCH_B, BENCH_T)),
    ]
    assert not is_tile_legal(old)
    # and each operand individually carries a violation the error names
    for lay in old:
        issues = block_tile_issues(lay.block_shape, lay.array_shape, lay.name)
        assert issues, f"{lay.name} should be illegal"
        assert lay.name in issues[0]
    with pytest.raises(TileError):
        check_layout(old)


@pytest.mark.parametrize("quant", (True, False))
def test_new_decode_specs_are_legal_at_bench_shape(quant):
    layouts = decode_block_layout(BENCH_B, BENCH_T, BENCH_H, BENCH_D, quant)
    check_layout(layouts)  # raises on violation
    # the q/out blocks really are the full [n_head, head_dim] planes
    by_name = {l.name: l for l in layouts}
    assert by_name["q"].block_shape == (1, BENCH_H, BENCH_D)
    assert by_name["out"].block_shape == (1, BENCH_H, BENCH_D)


@pytest.mark.parametrize(
    "T", (64, 100, 128, 200, 832, 833, 4096)
)
def test_decode_specs_legal_for_ragged_cache_lengths(T):
    """The masked tail removed the cache-length alignment restriction: the
    layout must stay tile-legal for ANY cache length, aligned or not."""
    check_layout(decode_block_layout(BENCH_B, T, BENCH_H, BENCH_D, True))
    check_layout(decode_block_layout(BENCH_B, T, BENCH_H, BENCH_D, False))


def test_decode_specs_legal_for_test_model_shapes():
    """Tiny shapes (CPU test models) are legal too — full blocks everywhere."""
    check_layout(decode_block_layout(2, 17, 2, 16, True))


def test_flash_specs_legal_at_bench_shape():
    from trlx_tpu.ops.flash_attention import pick_block

    T = 1024
    blk = pick_block(T)
    check_layout(flash_block_layout(BENCH_B * BENCH_H, T, BENCH_D, blk, blk))


def test_routing_probe_refuses_illegal_layout(monkeypatch):
    """decode_attn_supported answers False (with a warning, once) when the
    static layout check fails — the einsum fallback path in the model layer
    keys off this instead of crashing in Mosaic."""
    import warnings

    from trlx_tpu.ops import decode_attention as da
    from trlx_tpu.ops import tiling

    def bad_layout(B, T, h, d, quant, block_t=None):
        return [BlockLayout("q", (1, 1, d), (B, h, d))]

    da._PROBE_CACHE.clear()
    monkeypatch.setattr(tiling, "decode_block_layout", bad_layout)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert not da.decode_attn_supported(4, 64, 4, 128, True)
        assert any("falling back to the einsum" in str(x.message) for x in w)
    # cached: the next call must not warn again
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert not da.decode_attn_supported(4, 64, 4, 128, True)
        assert not w
    da._PROBE_CACHE.clear()


# ---------------------------------------------------------------------------
# Fused logprob head kernel (ops/fused_logprob.py)
# ---------------------------------------------------------------------------

from trlx_tpu.ops.tiling import fused_logprob_block_layout

# The flagship bench HEAD shape: gptj-l8-d4096-2.0B trains with 8 rows of
# T=832 per step (N = 8*832 = 6656 flattened states), d_model 4096, and the
# GPT-J vocab of 50400 (NOT 512-divisible: the last bv=512 vocab tile is a
# partial 224-wide block, masked in-kernel).
HEAD_N, HEAD_D, HEAD_V = 8 * BENCH_T, 4096, 50400


@pytest.mark.parametrize("tied,has_bias", [(True, False), (False, False), (False, True)])
def test_fused_logprob_layout_legal_at_bench_head_shape(tied, has_bias):
    layouts = fused_logprob_block_layout(
        HEAD_N, HEAD_D, HEAD_V, 128, 512, tied, has_bias
    )
    check_layout(layouts)  # raises TileError on violation
    # the weight streams in vocab tiles — it must never be the full [V, D]
    w = next(l for l in layouts if l.name == "w")
    assert w.block_shape != w.array_shape


def test_fused_logprob_layout_rejects_unaligned_vocab_tile():
    # bv=100: lane dim neither 128-divisible nor the full V — Mosaic would
    # reject this at lowering; the static check must catch it on CPU.
    with pytest.raises(TileError):
        check_layout(
            fused_logprob_block_layout(HEAD_N, HEAD_D, HEAD_V, 128, 100, False, False)
        )
    # bn=4: sublane dim of the hidden block violates the 8-row rule.
    with pytest.raises(TileError):
        check_layout(
            fused_logprob_block_layout(HEAD_N, HEAD_D, HEAD_V, 4, 512, True, False)
        )


def test_fused_probe_refuses_illegal_layout(monkeypatch):
    """fused_logprob_supported answers False (with a warning, once) when the
    static layout check fails — the model's head routing keys off this
    instead of crashing in Mosaic mid-train."""
    import warnings

    from trlx_tpu.ops import fused_logprob as fl
    from trlx_tpu.ops import tiling

    def bad_layout(N, D, V, bn, bv, tied, has_bias):
        return [BlockLayout("x", (4, D), (N, D))]

    fl._PROBE_CACHE.clear()
    monkeypatch.setattr(tiling, "fused_logprob_block_layout", bad_layout)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert not fl.fused_logprob_supported(256, 128, 1024, False, False)
        assert any("falling back to the log_softmax" in str(x.message) for x in w)
    # cached: the next call must not warn again
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert not fl.fused_logprob_supported(256, 128, 1024, False, False)
        assert not w
    fl._PROBE_CACHE.clear()


def test_fused_logprob_eligibility_is_static():
    from trlx_tpu.ops.fused_logprob import BLOCK_V, fused_logprob_eligible

    import jax

    on_tpu = jax.default_backend() == "tpu"
    # the flagship head qualifies wherever a TPU is attached
    assert fused_logprob_eligible(HEAD_D, HEAD_V) == on_tpu
    # sub-block vocabs and unaligned d_model never qualify
    assert not fused_logprob_eligible(HEAD_D, BLOCK_V - 1)
    assert not fused_logprob_eligible(HEAD_D + 1, HEAD_V)
