"""Tracker coverage: both the wandb branch and the JSONL fallback.

The reference exercises its rank-0 wandb path in every run (init, scalar log,
sample tables, Q/V/adv histograms; reference:
trlx/model/accelerate_base_model.py:66-79,197 and
trlx/model/nn/ilql_models.py:238-249). This container has no wandb, so the
wandb branch is driven end-to-end with a recording stub so its first
execution is not in production.
"""

import json
import os

import numpy as np
import pytest

import trlx_tpu.utils.logging as tlog
from trlx_tpu.utils.logging import Tracker


class _StubRun:
    def __init__(self):
        self.logged = []
        self.finished = False

    def log(self, payload, step=None):
        self.logged.append((payload, step))

    def finish(self):
        self.finished = True


class _StubTable:
    def __init__(self, columns, data):
        self.columns = columns
        self.data = data


class _StubHistogram:
    def __init__(self, values):
        self.values = np.asarray(values)


class _StubWandb:
    Table = _StubTable
    Histogram = _StubHistogram

    def __init__(self):
        self.run = _StubRun()
        self.init_kwargs = None

    def init(self, **kwargs):
        self.init_kwargs = kwargs
        return self.run


def _drive(tracker):
    tracker.log({"loss": 1.5, "tag": "x"}, step=3)
    tracker.log_table("samples", ["prompt", "output"], [["a", "b"], ["c", "d"]], step=3)
    tracker.log_histogram("qs", np.arange(8, dtype=np.float32), step=3)
    tracker.finish()


def test_jsonl_fallback_branch(tmp_path, monkeypatch):
    monkeypatch.delenv("TRLX_TPU_DISABLE_TRACKER", raising=False)
    monkeypatch.delenv("debug", raising=False)
    monkeypatch.setattr(tlog, "_HAS_WANDB", False)
    tracker = Tracker("proj", config={"lr": 1e-4}, log_dir=str(tmp_path))
    assert tracker.enabled and tracker._wandb is None
    _drive(tracker)
    lines = [json.loads(line) for line in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    kinds = [next(iter(rec)) for rec in lines]
    assert kinds == ["_config", "step", "table", "histogram"]
    assert lines[1]["loss"] == 1.5 and lines[1]["step"] == 3
    assert lines[2]["rows"] == [["a", "b"], ["c", "d"]]
    assert lines[3]["count"] == 8 and lines[3]["mean"] == pytest.approx(3.5)


def test_wandb_branch_with_stub(tmp_path, monkeypatch):
    monkeypatch.delenv("TRLX_TPU_DISABLE_TRACKER", raising=False)
    monkeypatch.delenv("debug", raising=False)
    stub = _StubWandb()
    monkeypatch.setattr(tlog, "wandb", stub)
    monkeypatch.setattr(tlog, "_HAS_WANDB", True)
    tracker = Tracker("proj", config={"lr": 1e-4}, run_name="run", entity_name="ent", log_dir=str(tmp_path))
    assert tracker._wandb is stub.run
    assert stub.init_kwargs["project"] == "proj"
    assert stub.init_kwargs["name"] == "run"
    assert stub.init_kwargs["entity"] == "ent"
    _drive(tracker)
    # scalar log, table log, histogram log — all routed through wandb AND the JSONL mirror
    assert len(stub.run.logged) == 3
    scalars, step = stub.run.logged[0]
    assert scalars["loss"] == 1.5 and step == 3
    table_payload, _ = stub.run.logged[1]
    assert isinstance(table_payload["samples"], _StubTable)
    assert table_payload["samples"].data == [["a", "b"], ["c", "d"]]
    hist_payload, _ = stub.run.logged[2]
    assert isinstance(hist_payload["qs"], _StubHistogram)
    assert stub.run.finished
    assert (tmp_path / "metrics.jsonl").exists()


def test_records_land_unbuffered_line_atomic(tmp_path, monkeypatch):
    monkeypatch.delenv("TRLX_TPU_DISABLE_TRACKER", raising=False)
    monkeypatch.delenv("debug", raising=False)
    monkeypatch.setattr(tlog, "_HAS_WANDB", False)
    tracker = Tracker("proj", log_dir=str(tmp_path))
    tracker.log({"loss": 1.0}, step=1)
    # No flush/close: unbuffered O_APPEND means the record already landed as
    # ONE complete newline-terminated write — a kill between log() calls
    # (preemption, host_kill drill) can never leave a torn line.
    data = (tmp_path / "metrics.jsonl").read_bytes()
    assert data.endswith(b"\n")
    assert json.loads(data.splitlines()[-1])["loss"] == 1.0
    tracker.finish()


def test_read_jsonl_tolerates_torn_final_line_only(tmp_path):
    p = str(tmp_path / "metrics.jsonl")
    with open(p, "wb") as f:
        f.write(b'{"step": 1}\n{"step": 2}\n{"step": 3, "lo')  # killed mid-append
    with pytest.warns(UserWarning, match="torn final record"):
        recs = tlog.read_jsonl(p)
    assert recs == [{"step": 1}, {"step": 2}]

    # a malformed line in the MIDDLE is real corruption and still raises
    with open(p, "wb") as f:
        f.write(b'{"step": 1}\n{"bad\n{"step": 2}\n')
    with pytest.raises(json.JSONDecodeError):
        tlog.read_jsonl(p)

    # intact files round-trip without warnings
    with open(p, "wb") as f:
        f.write(b'{"step": 1}\n{"step": 2}\n')
    assert tlog.read_jsonl(p) == [{"step": 1}, {"step": 2}]


def test_disable_via_explicit_env(tmp_path, monkeypatch):
    monkeypatch.setenv("TRLX_TPU_DISABLE_TRACKER", "1")
    tracker = Tracker("proj", log_dir=str(tmp_path))
    assert not tracker.enabled
    _drive(tracker)  # all no-ops, nothing written
    assert not (tmp_path / "metrics.jsonl").exists()


def test_disable_env_zero_means_enabled(tmp_path, monkeypatch):
    monkeypatch.setenv("TRLX_TPU_DISABLE_TRACKER", "0")
    monkeypatch.delenv("debug", raising=False)
    monkeypatch.setattr(tlog, "_HAS_WANDB", False)
    tracker = Tracker("proj", log_dir=str(tmp_path))
    assert tracker.enabled
    tracker.finish()


def test_legacy_debug_env_warns(tmp_path, monkeypatch):
    monkeypatch.delenv("TRLX_TPU_DISABLE_TRACKER", raising=False)
    monkeypatch.setenv("debug", "")
    with pytest.warns(DeprecationWarning, match="TRLX_TPU_DISABLE_TRACKER"):
        tracker = Tracker("proj", log_dir=str(tmp_path))
    assert not tracker.enabled
