# Dev targets (reference: Makefile style/quality; upgraded to ruff).
.PHONY: test test-fast quality style bench bench-reference

# Full suite (learning gates, multihost, kernels): nightly / pre-release.
test:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	python -m pytest tests/ -q

# Fast tier: per-commit CI signal, < ~3 min on CPU.
test-fast:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	python -m pytest tests/ -q -m "not slow"

quality:
	ruff check trlx_tpu/ tests/ examples/ bench.py

style:
	ruff format trlx_tpu/ tests/ examples/ bench.py

bench:
	python bench.py

# CPU head-to-head vs the reference's own training loop (writes HEADTOHEAD.json).
bench-reference:
	python bench_reference.py
