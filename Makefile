# Dev targets (reference: Makefile style/quality; upgraded to ruff).
.PHONY: test quality style bench

test:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	python -m pytest tests/ -q

quality:
	ruff check trlx_tpu/ tests/ examples/ bench.py

style:
	ruff format trlx_tpu/ tests/ examples/ bench.py

bench:
	python bench.py
