# Dev targets (reference: Makefile style/quality; upgraded to ruff).
.PHONY: test test-fast test-shard1 test-shard2 test-shard3 test-multihost fleet-drill lint typecheck quality style bench bench-reference bench-smoke bench-trajectory obs-smoke acceptance-network sanitize-drill

TEST_ENV = JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8

# Full suite (learning gates, multihost, kernels): nightly / pre-release.
# Exceeds a 10-min single-command budget — use the three shards below for
# full-suite green within per-command limits (timings: README "Testing").
test:
	$(TEST_ENV) python -m pytest tests/ -q

# Fast tier: per-commit CI signal, < ~4 min on CPU. Includes the resilience
# suite (tests/test_resilience.py — fault drills, guard/watchdog/checkpoint
# hardening): single-process CPU drills, so nothing there needs a slow mark.
test-fast:
	$(TEST_ENV) python -m pytest tests/ -q -m "not slow"

# Full-suite green in three bounded commands: shard1 = fast tier + kernel/
# generate slow tests; shard2 = e2e learning gates; shard3 = mesh/multihost/
# scale. Every test runs in exactly one shard.
test-shard1:
	$(TEST_ENV) python -m pytest tests/ -q -m "not slow" \
	    && $(TEST_ENV) python -m pytest -q -m slow \
	        tests/test_flash.py tests/test_ring_attention.py tests/test_generate.py \
	        tests/test_weight_quant.py tests/test_hf_stream.py

test-shard2:
	$(TEST_ENV) python -m pytest -q -m slow \
	    tests/test_e2e.py tests/test_text_mode.py tests/test_softprompt.py \
	    tests/test_fused_rollout.py

test-shard3:
	$(TEST_ENV) python -m pytest -q -m slow \
	    tests/test_mesh.py tests/test_multihost.py tests/test_scale_compile.py

# 2-process distributed drills: boundary-helper/train-resume semantics plus
# the fault drills (host_hang → CollectiveTimeout, coordinated preemption
# save/resume, host_desync → fingerprint guard), and the disaggregated
# rollout/learner fleet drills (rollout_host_kill → degraded drain,
# broadcast_timeout → starved-worker abort, episode_stream_stall → STALLED
# triage, 2-process staleness-0 parity; RUNBOOK §16). Non-blocking CI job —
# jax.distributed on shared runners can be flaky; see RUNBOOK §3b for the
# local drill command and the triage table.
test-multihost:
	$(TEST_ENV) python -m pytest -q -m slow \
	    tests/test_multihost.py tests/test_distributed_resilience.py \
	    tests/test_fleet_drill.py tests/test_fleet_disagg.py \
	    tests/test_fleet_elastic.py

# 2-process fleet drills under the full runtime sanitizer set: graftfleet's
# slow_host drill (merged clock-aligned trace, skew table naming the
# laggard, live fleet gauges) and hang drill (cross-host incident bundle),
# plus the disaggregated rollout/learner drills (host kill + preemption +
# resume, broadcast timeout, stream stall, 2-process parity; RUNBOOK §16),
# plus the in-flight weight-update drills (torn push rejection, switch-storm
# coalescing, 2-process engine schedule verify + skew, mid-decode host kill
# with slot-state forensics, staleness-0 bitwise parity; RUNBOOK §17).
# Set TRLX_TPU_DRILL_ARTIFACTS=<dir> to keep the merged trace, report
# section, episode-stream index, broadcast log and fleet event log (the CI
# job uploads them). Non-blocking CI job — jax.distributed caveats apply to
# test_fleet_drill.py only (the disagg drills spawn independent
# single-controller worlds); RUNBOOK §14/§16 have the triage.
fleet-drill:
	$(TEST_ENV) TRLX_TPU_SANITIZE=dispatch,donation,race python -m pytest -q \
	    -m slow tests/test_fleet_drill.py tests/test_fleet_disagg.py \
	    tests/test_fleet_elastic.py

# graftlint + graftrace: AST invariant (GL001-GL007, RUNBOOK §11) and
# concurrency (GL008-GL011, RUNBOOK §13) checks in one pass. Blocking,
# < 30 s, stdlib only — the analysis package must never import jax (pinned
# by tests/test_analysis.py), so this runs on CPU-only CI images as-is.
# Second pass: the top-level scripts, under the rule families that apply
# outside the package (no dispatch-lock/trace-purity surface there).
SCRIPT_LINT_RULES = GL003,GL004,GL007,GL008,GL009,GL010,GL011
lint:
	python -m trlx_tpu.analysis trlx_tpu/
	python -m trlx_tpu.analysis --select $(SCRIPT_LINT_RULES) \
	    bench.py bench_smoke.py bench_decode_probe.py bench_reference.py \
	    bench_trajectory.py obs_smoke.py acceptance_network.py

# graftrace runtime half, fully armed: the thread-heavy suites (resilience
# fault drills, overlap pipeline, rollout engine) under
# TRLX_TPU_SANITIZE=dispatch,donation,race so lock-discipline, donation, and
# lockset (Eraser) violations raise instead of deadlocking. Non-blocking CI
# job; RUNBOOK §13 has the triage table for RaceViolation reports.
sanitize-drill:
	$(TEST_ENV) TRLX_TPU_SANITIZE=dispatch,donation,race python -m pytest -q \
	    -m "not slow" tests/test_resilience.py tests/test_overlap.py \
	    tests/test_engine.py tests/test_sanitize.py

# Non-blocking type pass over the typed subset (analysis + engine). Degrades
# to a notice when mypy isn't installed — nothing at runtime needs it, and
# the container must not pip install.
typecheck:
	@if python -c "import mypy" 2>/dev/null; then \
	    python -m mypy --ignore-missing-imports --follow-imports=silent \
	        trlx_tpu/analysis/ trlx_tpu/engine/; \
	else \
	    echo "mypy not installed; skipping typecheck (advisory only)"; \
	fi

quality:
	ruff check trlx_tpu/ tests/ examples/ bench.py

style:
	ruff format trlx_tpu/ tests/ examples/ bench.py

bench:
	python bench.py

# CPU head-to-head vs the reference's own training loop (writes HEADTOHEAD.json).
bench-reference:
	python bench_reference.py

# CPU decode-path smoke, ~2 min: interpret-mode flash-decode parity at the
# flagship head layout + static tile legality at the full bench shape +
# a tiny bucketed rollout (trace count <= n_buckets) + the decode_engine
# probe (slot decode parity vs static batch, occupancy > 0.85, engine
# tokens/s above the static rate) + the fleet_elastic probe (episodes/s
# through the real lease/stream/intake transports at 1 vs 2 workers,
# exactly-once asserted, 2-worker speedup > 1.3x). Writes BENCH_SMOKE.json.
bench-smoke:
	$(TEST_ENV) python bench_smoke.py

# Bench-trajectory regression gate, stdlib-only, seconds: folds the tracked
# BENCH_r0*.json / BENCH_SMOKE.json artifacts into BENCH_TRAJECTORY.json and
# exits 1 when samples/s/chip or train MFU regresses >10% vs the best prior
# run with the same bench config. Non-blocking CI job.
bench-trajectory:
	python bench_trajectory.py

# CPU observability smoke, ~1 min: a short overlapped PPO run with span
# tracing, device telemetry, the slow_step anomaly drill, the health monitor
# with the reward_drift drill, and the live /metrics exporter armed (scraped
# from a background thread mid-run), then the report renderer over the
# artifacts. Writes OBS_SMOKE.json + OBS_REPORT.md + OBS_METRICS.prom.
obs-smoke:
	$(TEST_ENV) python obs_smoke.py

# Network-day acceptance: the four reference acceptance examples + gates in
# one command, distilled to ACCEPTANCE.json (RUNBOOK.md). Offline it still
# runs end-to-end with every test skipped — that's the smoke path CI covers.
acceptance-network:
	TRLX_TPU_NETWORK=1 python acceptance_network.py
