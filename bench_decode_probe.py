"""Decode-bandwidth attribution probe: split decode time into HBM traffic vs fixed per-step cost.

BENCH_r04 measured the flagship decode at 58.7% of the modeled HBM roofline.
This probe answers WHERE the other 41% goes: it measures the SAME flagship
decode under quantization/chunk combinations whose modeled byte traffic is
known, then least-squares fits

    t_decode(combo) = bytes(combo) / BW_eff  +  R * c0

over the combos — BW_eff is the bandwidth the decode loop actually achieves
on its memory traffic, c0 the fixed per-decode-step cost (kernel issue,
while_loop step overhead, sampling, cache-index bookkeeping) that no byte
reduction can touch. If BW_eff is near peak, the utilization gap is
latency-bound (c0·R dominates), not bandwidth-bound — the falsifiable form
of VERDICT r4's ask.

Runs each combo through `python bench.py` (subprocess OOM isolation, the
same measurement path as the published flagship) with optional points off.
Writes DECODE_PROBE.json. Real TPU, ~20 min.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(REPO, "DECODE_PROBE.json")

# (label, env overrides). Chunk 32 is the published flagship shape; the
# W8/KV combos scan weight and cache bytes independently; chunk 64 halves
# per-token weight traffic (weights are read once per step, shared by rows).
COMBOS = [
    ("w8-kv8-c32", {"BENCH_W8": "1", "BENCH_KV_QUANT": "1", "BENCH_CHUNK": "32"}),
    ("w8-kv16-c32", {"BENCH_W8": "1", "BENCH_KV_QUANT": "0", "BENCH_CHUNK": "32"}),
    ("w16-kv8-c32", {"BENCH_W8": "0", "BENCH_KV_QUANT": "1", "BENCH_CHUNK": "32"}),
    ("w16-kv16-c32", {"BENCH_W8": "0", "BENCH_KV_QUANT": "0", "BENCH_CHUNK": "32"}),
    ("w8-kv8-c64", {"BENCH_W8": "1", "BENCH_KV_QUANT": "1", "BENCH_CHUNK": "64"}),
]


def run_combo(label, overrides):
    env = dict(os.environ)
    env.update(overrides)
    env.update(
        BENCH_ORCH="0",           # serialized decode/score/train phases only
        BENCH_FP32_POINT="0",
        BENCH_ILQL_POINT="0",
        BENCH_ITERS="2",
    )
    t = time.time()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, cwd=REPO,
    )
    if proc.returncode != 0:
        print(f"[probe] {label} FAILED rc={proc.returncode}\n{proc.stderr[-1500:]}", file=sys.stderr)
        return None
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    for f in rec.get("failed_candidates", []):
        # bench.py records non-OOM candidate failures and falls through to a
        # smaller size — the probe must say so, because a silently-smaller
        # flagship shape would corrupt the byte model's combo comparison.
        print(
            f"[probe] {label}: candidate {f['candidate']} failed rc={f['rc']} "
            f"before the measured size\n{f['tail'][-500:]}",
            file=sys.stderr,
        )
    model = rec.get("decode_hbm_model")
    if not model:
        print(f"[probe] {label}: no decode_hbm_model in output", file=sys.stderr)
        return None
    m = rec["metric"]  # ppo_samples_per_sec_per_chip[name,seqT,prefillP+decodeR,chunkC,bB]
    R = int(m.split("+decode")[1].split(",")[0])
    out = {
        "label": label,
        "R": R,
        "decode_seconds": model["decode_seconds_modeled"],
        "bytes_gb": model["weight_bytes_per_step_gb"] * R + model["kv_bytes_total_gb"],
        "util_pct": rec.get("decode_hbm_util_pct"),
        "peak_hbm_gbps": model["peak_hbm_gbps"],
        "samples_per_s_per_chip": rec["value"],
        "wall_s": round(time.time() - t, 1),
    }
    print(f"[probe] {label}: t_dec={out['decode_seconds']}s bytes={out['bytes_gb']:.1f}GB "
          f"util={out['util_pct']}% ({out['wall_s']}s)", flush=True)
    return out


def fit(points):
    """Least squares for t = bytes/BW + R*c0 → returns (BW GB/s, c0 ms)."""
    A = np.array([[p["bytes_gb"], p["R"]] for p in points], dtype=np.float64)
    t = np.array([p["decode_seconds"] for p in points], dtype=np.float64)
    # unknowns x = [1/BW (s/GB), c0 (s/step)]
    x, residuals, *_ = np.linalg.lstsq(A, t, rcond=None)
    inv_bw, c0 = float(x[0]), float(x[1])
    bw = 1.0 / max(inv_bw, 1e-12)
    pred = A @ x
    return bw, c0, [round(float(p), 3) for p in pred]


def main():
    points = [p for p in (run_combo(l, o) for l, o in COMBOS) if p]
    result = {"points": points, "recorded_at": time.strftime("%Y-%m-%d %H:%M:%S")}
    if len(points) >= 3:
        bw, c0, pred = fit(points)
        peak = points[0]["peak_hbm_gbps"]
        result["fit"] = {
            "achieved_bw_gbps": round(bw, 1),
            "achieved_bw_frac_of_peak": round(bw / peak, 3),
            "fixed_cost_ms_per_step": round(1e3 * c0, 3),
            "predicted_decode_seconds": pred,
            "model": "t_decode = bytes/BW_eff + R*c0 (least squares over combos)",
        }
        # attribution of the flagship's utilization gap
        flag = points[0]
        t_bw = flag["bytes_gb"] / bw
        result["fit"]["flagship_share_bandwidth_pct"] = round(100 * t_bw / flag["decode_seconds"], 1)
        result["fit"]["flagship_share_fixed_pct"] = round(
            100 * (flag["R"] * c0) / flag["decode_seconds"], 1
        )
    with open(OUT, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"probe": "done", "fit": result.get("fit"), "out": OUT}))


if __name__ == "__main__":
    main()
