"""CPU smoke of the observability layer: minutes, no TPU, CI-safe.

One probe run — a short overlapped PPO randomwalks run (max_staleness=1)
with every observability surface armed:

- span tracing (train.trace_spans): spans.jsonl must hold valid Chrome
  trace events with the producer / score-worker / main threads on distinct
  lanes and producer/train wall-clock overlap actually visible;
- device telemetry (train.device_telemetry, TRLX_TPU_PEAK_TFLOPS pinned so
  CPU gets an MFU %): metrics.jsonl must carry obs/train_mfu_pct and the
  kernel-routing gauges, and programs.json must register the train step;
- anomaly capture (train.anomaly_factor + the TRLX_TPU_FAULTS=slow_step
  drill): an incident bundle with thread stacks must land;
- training health (train.health_monitor + the reward_drift drill): the
  reward-drift detector must walk OK→WARN→CRIT, escalate a
  health_reward_drift incident bundle, and leave lineage.jsonl behind;
- live exporter (train.metrics_port): /metrics must serve the health/*
  gauges in Prometheus text format and /healthz must report degraded
  WHILE the run is alive (scraped from a background thread);
- reporting: trlx_tpu.observability.report must render every section from
  the run's artifacts and export the chrome://tracing JSON.

Two follow-up probes ride along: ``graftscope_probe`` (PR 12 — ledger
conservation, slot timeline, crash-proof manifest) and ``numerics_probe``
(PR 15 — an armed graftnum run under the ``nan_layer@2`` drill whose
incident bundle names the injected layer, with ``num/*`` gauges on the
live scrape and a rendered Numerics report section; writes
OBS_NUMERICS.json).

Writes OBS_SMOKE.json + OBS_REPORT.md + OBS_METRICS.prom (the last live
scrape) and prints one JSON summary line; exits 1 on any failure. Wall
time ~2 min on a laptop CPU.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(REPO, "OBS_SMOKE.json")
REPORT_OUT = os.path.join(REPO, "OBS_REPORT.md")
METRICS_OUT = os.path.join(REPO, "OBS_METRICS.prom")


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Scraper:
    """Background poller proving the endpoint is LIVE during the run: keeps
    the last successful /metrics text and the worst /healthz status seen."""

    def __init__(self, port):
        import threading

        self.port = port
        self.metrics_text = ""
        self.worst_status = None
        self.scrapes = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="trlx-obs-scraper", daemon=True
        )
        self._thread.start()

    def _run(self):
        import urllib.request

        rank = {"ok": 0, "degraded": 1, "critical": 2}
        while not self._stop.is_set():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{self.port}/metrics", timeout=1
                ) as r:
                    self.metrics_text = r.read().decode()
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{self.port}/healthz", timeout=1
                ) as r:
                    status = json.loads(r.read().decode()).get("status")
                self.scrapes += 1
                if rank.get(status, -1) > rank.get(self.worst_status, -1):
                    self.worst_status = status
            except OSError:
                pass  # exporter not up yet / torn down
            self._stop.wait(0.05)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)


def observability_probe():
    import tempfile
    import threading

    import numpy as np

    # slow_step drills the anomaly detector; reward_drift (from reward call
    # 2 on — call 1 seeds the warmup baseline) drills the health monitor.
    os.environ["TRLX_TPU_FAULTS"] = "slow_step@6,reward_drift@2"
    os.environ["TRLX_TPU_SLOW_STEP_SECONDS"] = "1.5"
    os.environ["TRLX_TPU_PEAK_TFLOPS"] = "0.01"

    sys.path.insert(0, os.path.join(REPO, "examples"))
    import trlx_tpu
    from randomwalks import base_config, generate_random_walks
    from trlx_tpu.observability import report, spans

    _, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=15, max_length=8, n_walks=60, seed=1000
    )
    config = base_config("ppo", 15, 8)
    config.train.total_steps = 8
    config.train.epochs = 4
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.trace_spans = True
    config.train.device_telemetry = True
    config.train.anomaly_factor = 3.0
    # Health monitor: chunk_size=8 gives 2 reward calls per store, so the
    # drift walk is obs1 clean baseline (warmup=1) → obs2 drifted WARN
    # (warn_streak=1) → obs3 drifted CRIT (crit_streak=2), all in the first
    # few seconds — the exporter then serves CRIT for the rest of the run.
    config.train.health_monitor = True
    config.train.health_warmup = 1
    config.train.health_warn_streak = 1
    config.train.health_crit_streak = 2
    port = _free_port()
    config.train.metrics_port = port
    config.method.num_rollouts = 16
    config.method.chunk_size = 8
    config.method.max_staleness = 1
    d = tempfile.mkdtemp(prefix="obs_smoke_")
    config.train.checkpoint_dir = d
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    scraper = _Scraper(port)
    t0 = time.time()
    try:
        model = trlx_tpu.train(
            reward_fn=reward_fn,
            prompts=prompts,
            eval_prompts=[[1]],
            metric_fn=metric_fn,
            config=config,
            logit_mask=logit_mask,
        )
    finally:
        wall_s = time.time() - t0
        scraper.stop()
    assert model.iter_count >= 8
    leaked = [t.name for t in threading.enumerate() if t.name.startswith("trlx-")]
    assert not leaked, f"pipeline threads leaked: {leaked}"

    # --- spans: distinct lanes, visible producer/train overlap ------------
    events = spans.read_spans(os.path.join(d, spans.SPANS_FILENAME))
    assert events and {e["ph"] for e in events} <= {"X", "i", "M"}, "bad trace events"
    lanes = {e["args"]["name"]: e["tid"] for e in events if e["ph"] == "M"}
    for thread in ("MainThread", "trlx-rollout-producer", "trlx-score-worker"):
        assert thread in lanes, f"missing span lane: {thread} (have {sorted(lanes)})"
    xs = [e for e in events if e["ph"] == "X"]
    producer = [e for e in xs if e["name"] == "rollout/produce"]
    train = [e for e in xs if e["name"] == "train/step"]
    assert producer and train, "producer/train spans missing"

    def overlap_us(a, b):
        return min(a["ts"] + a["dur"], b["ts"] + b["dur"]) - max(a["ts"], b["ts"])

    overlap_s = max(
        (overlap_us(p, t) for p in producer for t in train), default=0
    ) / 1e6
    assert overlap_s > 0, "no producer/train overlap visible in spans"

    # --- telemetry: MFU + routing gauges + program registry ---------------
    with open(os.path.join(d, "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    mfu = [r["obs/train_mfu_pct"] for r in records if "obs/train_mfu_pct" in r]
    assert mfu and all(m > 0 for m in mfu), f"MFU gauges missing/zero: {mfu}"
    routed = [r for r in records if "obs/fused_logprob_active" in r]
    assert routed, "kernel-routing gauges missing"
    with open(os.path.join(d, "programs.json")) as f:
        programs = json.load(f)
    assert "train/step" in programs and programs["train/step"]["dispatches"] >= 8

    # --- anomaly + health escalation: both drills produced bundles --------
    incidents_dir = os.path.join(d, "incidents")
    bundles = sorted(os.listdir(incidents_dir)) if os.path.isdir(incidents_dir) else []
    reasons = {}
    for b in bundles:
        with open(os.path.join(incidents_dir, b, "incident.json")) as f:
            reasons[json.load(f)["reason"]] = b
    assert "slow_step" in reasons, f"slow_step drill produced no bundle: {reasons}"
    assert "health_reward_drift" in reasons, (
        f"reward_drift CRIT did not escalate into an incident: {reasons}"
    )
    with open(os.path.join(incidents_dir, reasons["slow_step"], "threads.txt")) as f:
        assert "trlx-" in f.read(), "pipeline threads absent from stack dump"

    # --- health: detector walked to CRIT, lineage landed ------------------
    drift_states = [
        r["health/reward_drift_state"]
        for r in records
        if "health/reward_drift_state" in r
    ]
    assert drift_states and max(drift_states) == 2, (
        f"reward_drift detector never reached CRIT: {drift_states}"
    )
    changes = [
        r["health/state_changes_total"]
        for r in records
        if "health/state_changes_total" in r
    ]
    assert changes and changes[-1] >= 2, f"state-change counter: {changes}"
    with open(os.path.join(d, "lineage.jsonl")) as f:
        lineage = [json.loads(line) for line in f]
    assert lineage and all("weight_version" in r and "staleness" in r for r in lineage)

    # --- live exporter: scraped DURING the run ----------------------------
    assert scraper.scrapes > 0, "never scraped the live /metrics endpoint"
    prom = scraper.metrics_text
    assert "# TYPE trlx_tpu_health_reward_drift_state gauge" in prom, prom[:2000]
    assert "# TYPE trlx_tpu_health_state_changes_total counter" in prom
    assert scraper.worst_status in ("degraded", "critical"), scraper.worst_status
    with open(METRICS_OUT, "w") as f:
        f.write(prom)

    # --- report: renders every section + exports the trace ----------------
    trace_out = os.path.join(d, "trace.json")
    assert report.main([d, "-o", REPORT_OUT, "--trace-out", trace_out]) == 0
    with open(REPORT_OUT) as f:
        md = f.read()
    for heading in (
        "## Span lanes",
        "## MFU / FLOP throughput",
        "## Training health",
        "## Incidents",
    ):
        assert heading in md, f"report section missing: {heading}"
    assert "slow_step" in md and "health_reward_drift" in md

    return {
        "steps": model.iter_count,
        "span_events": len(events),
        "lanes": sorted(lanes),
        "producer_train_overlap_s": round(overlap_s, 2),
        "mfu_windows": len(mfu),
        "mfu_last_pct": round(mfu[-1], 3),
        "incidents": reasons,
        "health_worst_status": scraper.worst_status,
        "live_scrapes": scraper.scrapes,
        "lineage_rows": len(lineage),
        "report_bytes": len(md),
        "seconds": round(wall_s, 2),
    }


def graftscope_probe():
    """PR 12 smoke: an armed overlapped+engine run must produce the
    conservation ledger, a bubble fraction, slot-timeline rows, and /metrics
    histograms — and a SIGKILLed bench child must still leave a RunManifest
    that bench_trajectory turns into a reason instead of ``no_data``."""
    import signal
    import subprocess
    import tempfile
    import threading

    import numpy as np

    # The first probe's drills must not pollute this run's timings.
    os.environ.pop("TRLX_TPU_FAULTS", None)
    os.environ.pop("TRLX_TPU_SLOW_STEP_SECONDS", None)
    os.environ["TRLX_TPU_PEAK_TFLOPS"] = "0.01"

    sys.path.insert(0, os.path.join(REPO, "examples"))
    import trlx_tpu
    from randomwalks import base_config, generate_random_walks
    from trlx_tpu.observability import spans
    from trlx_tpu.observability.graftscope import RunManifest

    _, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=15, max_length=8, n_walks=60, seed=1000
    )
    config = base_config("ppo", 15, 8)
    config.train.total_steps = 8
    config.train.epochs = 4
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.graftscope = True  # implies spans + device telemetry
    port = _free_port()
    config.train.metrics_port = port
    config.method.num_rollouts = 16
    config.method.chunk_size = 8
    config.method.max_staleness = 1
    config.method.rollout_engine = True
    config.method.engine_slots = 4
    config.method.prefill_batch = 2
    d = tempfile.mkdtemp(prefix="obs_smoke_gs_")
    config.train.checkpoint_dir = d
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    scraper = _Scraper(port)
    t0 = time.time()
    try:
        model = trlx_tpu.train(
            reward_fn=reward_fn,
            prompts=prompts,
            eval_prompts=[[1]],
            metric_fn=metric_fn,
            config=config,
            logit_mask=logit_mask,
        )
    finally:
        wall_s = time.time() - t0
        scraper.stop()
    assert model.iter_count >= 8
    leaked = [t.name for t in threading.enumerate() if t.name.startswith("trlx-")]
    assert not leaked, f"threads leaked (graftscope drain?): {leaked}"

    # --- conservation ledger in metrics.jsonl -----------------------------
    with open(os.path.join(d, "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    windows = [r for r in records if "obs/ledger_wall_s" in r]
    assert windows, "no ledger windows in metrics.jsonl"
    for r in windows:
        wall = r["obs/ledger_wall_s"]
        err = abs(
            r["obs/ledger_device_busy_s"]
            + r["obs/ledger_host_s"]
            + r["obs/ledger_bubble_s"]
            - wall
        ) / max(wall, 1e-9)
        assert err <= 0.05, f"ledger conservation violated: {err:.4f} in {r}"
        assert 0.0 <= r["obs/bubble_fraction"] <= 1.0
    assert any(r["obs/ledger_device_busy_s"] > 0 for r in windows), (
        "fence drain attributed zero device time across every window"
    )

    # --- slot timeline in spans.jsonl + snapshot rollups ------------------
    events = spans.read_spans(os.path.join(d, spans.SPANS_FILENAME))
    slot_spans = [e for e in events if e.get("name") == "engine/slot"]
    admits = [e for e in events if e.get("name") == "engine/slot/admit"]
    harvests = [e for e in events if e.get("name") == "engine/slot/harvest"]
    assert slot_spans and admits and harvests, (
        f"slot timeline missing: {len(slot_spans)} spans, {len(admits)} admits, "
        f"{len(harvests)} harvests"
    )
    gs_path = os.path.join(d, "graftscope.json")
    with open(gs_path) as f:
        snap = json.load(f)
    assert snap["windows"], "graftscope.json has no windows"
    assert snap["slots"] and all(row["episodes"] > 0 for row in snap["slots"]), (
        f"slot occupancy rows missing/empty: {snap.get('slots')}"
    )
    with open(os.path.join(REPO, "OBS_GRAFTSCOPE.json"), "w") as f:
        json.dump(snap, f, indent=1)

    # --- /metrics histograms (lane gaps at minimum) -----------------------
    prom = scraper.metrics_text
    assert "trlx_tpu_obs_lane_gap_s_bucket" in prom, prom[:2000]
    assert "trlx_tpu_obs_bubble_fraction" in prom

    # --- forced-kill bench child → valid manifest with a reason -----------
    mdir = tempfile.mkdtemp(prefix="obs_smoke_manifest_")
    mpath = os.path.join(mdir, "BENCH_MANIFEST_r99.jsonl")
    child_src = (
        "import os, sys, time\n"
        "sys.path.insert(0, %r)\n"
        "from trlx_tpu.observability.graftscope import RunManifest\n"
        "m = RunManifest(%r, cmd='bench.py (smoke drill)')\n"
        "m.heartbeat('size_ladder', candidate='gptj-l8-d4096-2.0B-w8-bf16')\n"
        "m.child('gptj-l8-d4096-2.0B-w8-bf16', 1, 'ValueError: mosaic lowering failed')\n"
        "m.heartbeat('size_ladder', candidate='gptj-l6-d2048-0.4B-w8-bf16')\n"
        "print('ready', flush=True)\n"
        "time.sleep(60)\n"
    ) % (REPO, mpath)
    proc = subprocess.Popen(
        [sys.executable, "-c", child_src],
        stdout=subprocess.PIPE,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.stdout.readline().strip() == "ready"
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30)
    summary = RunManifest.read(mpath)
    assert summary["valid"] and not summary["complete"], summary
    assert "killed mid-flight during size_ladder" in summary["reason"], summary
    assert "rc=1" in summary["reason"], summary

    # --- bench_trajectory ingests the manifest reason ---------------------
    import bench_trajectory

    art = os.path.join(mdir, "BENCH_r99.json")
    with open(art, "w") as f:
        json.dump({"n": 99, "cmd": "timeout -k 10 900 python bench.py", "rc": 124, "tail": ""}, f)
    traj = bench_trajectory.build_trajectory(
        [art], smoke_path=os.path.join(mdir, "missing.json"),
        manifest_path=os.path.join(mdir, "missing.jsonl"),
    )
    entry = traj["runs"][0]
    assert entry.get("no_data") and entry.get("manifest"), entry
    assert entry["reason"] == summary["reason"], (entry["reason"], summary["reason"])

    return {
        "steps": model.iter_count,
        "ledger_windows": len(windows),
        "worst_conservation_error": round(
            max(
                abs(
                    r["obs/ledger_device_busy_s"]
                    + r["obs/ledger_host_s"]
                    + r["obs/ledger_bubble_s"]
                    - r["obs/ledger_wall_s"]
                )
                / max(r["obs/ledger_wall_s"], 1e-9)
                for r in windows
            ),
            6,
        ),
        "bubble_fraction_last": round(windows[-1]["obs/bubble_fraction"], 4),
        "slot_spans": len(slot_spans),
        "slot_admits": len(admits),
        "snapshot_slots": len(snap["slots"]),
        "killed_manifest_reason": summary["reason"],
        "seconds": round(wall_s, 2),
    }


def numerics_probe():
    """PR 15 smoke: an armed overlapped graftnum run under the nan_layer
    drill must stream num/* gauges to the LIVE /metrics endpoint, attach a
    numerics.json to the guard-skip incident bundle that names the injected
    layer as first-NaN (plus the nonfinite grad leaves by path), and render
    the report's Numerics section. Writes OBS_NUMERICS.json."""
    import tempfile
    import threading

    import numpy as np

    # nan_layer@2: step 2's batch is NaN-poisoned (guard trips for real) AND
    # the bisector's injection target block_2 is latched — so the model needs
    # n_layer > 2 for the clamp min(2, n_layer-1) to name a distinct layer.
    os.environ["TRLX_TPU_FAULTS"] = "nan_layer@2"
    os.environ.pop("TRLX_TPU_SLOW_STEP_SECONDS", None)
    os.environ["TRLX_TPU_PEAK_TFLOPS"] = "0.01"

    sys.path.insert(0, os.path.join(REPO, "examples"))
    import trlx_tpu
    from randomwalks import base_config, generate_random_walks
    from trlx_tpu.observability import report

    _, logit_mask, metric_fn, reward_fn = generate_random_walks(
        n_nodes=15, max_length=8, n_walks=60, seed=1000
    )
    config = base_config("ppo", 15, 8)
    config.model.model_arch["n_layer"] = 4
    config.train.total_steps = 8
    config.train.epochs = 4
    config.train.batch_size = 16
    config.train.eval_interval = 100
    config.train.graftnum = True
    port = _free_port()
    config.train.metrics_port = port
    config.method.num_rollouts = 16
    config.method.chunk_size = 8
    config.method.max_staleness = 1
    d = tempfile.mkdtemp(prefix="obs_smoke_num_")
    config.train.checkpoint_dir = d
    prompts = [[int(np.random.default_rng(i).integers(1, 15))] for i in range(32)]

    scraper = _Scraper(port)
    t0 = time.time()
    try:
        model = trlx_tpu.train(
            reward_fn=reward_fn,
            prompts=prompts,
            eval_prompts=[[1]],
            metric_fn=metric_fn,
            config=config,
            logit_mask=logit_mask,
        )
    finally:
        wall_s = time.time() - t0
        scraper.stop()
        os.environ.pop("TRLX_TPU_FAULTS", None)
    assert model.iter_count >= 8
    assert model.skipped_steps >= 1, "nan_layer drill never tripped the guard"
    leaked = [t.name for t in threading.enumerate() if t.name.startswith("trlx-")]
    assert not leaked, f"pipeline threads leaked: {leaked}"

    # --- num/* telemetry in metrics.jsonl ---------------------------------
    with open(os.path.join(d, "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    gnorm = [r["num/grad_global_norm"] for r in records if "num/grad_global_norm" in r]
    assert gnorm, "no num/grad_global_norm records"
    subtree_keys = sorted(
        {k for r in records for k in r if k.startswith("num/update_ratio/")}
    )
    assert subtree_keys, "no per-subtree update-ratio gauges"

    # --- num/* gauges on the LIVE /metrics scrape -------------------------
    assert scraper.scrapes > 0, "never scraped the live /metrics endpoint"
    prom = scraper.metrics_text
    assert "trlx_tpu_num_grad_global_norm" in prom, prom[:2000]
    assert "trlx_tpu_num_update_ratio_" in prom

    # --- incident bundle: numerics.json names the injected layer ----------
    incidents_dir = os.path.join(d, "incidents")
    payload = None
    for b in sorted(os.listdir(incidents_dir) if os.path.isdir(incidents_dir) else []):
        p = os.path.join(incidents_dir, b, "numerics.json")
        if os.path.exists(p):
            with open(p) as f:
                payload = json.load(f)
            break
    assert payload is not None, "no numerics.json in any incident bundle"
    census = payload["grad_census"]
    assert census["total_nonfinite_leaves"] > 0, census
    bisect = payload["forward_bisect"]
    assert bisect["first_nonfinite"] == "block_2", bisect
    assert bisect["injected"] == "block_2", bisect

    # --- report renders the Numerics section ------------------------------
    md = report.build_report(d)
    assert "## Numerics (graftnum)" in md, "Numerics section missing from report"
    assert "block_2" in md and "nonfinite grad leaves" in md

    out = {
        "steps": model.iter_count,
        "skipped_steps": model.skipped_steps,
        "grad_norm_records": len(gnorm),
        "subtree_gauges": len(subtree_keys),
        "first_nonfinite": bisect["first_nonfinite"],
        "nonfinite_grad_leaves": census["total_nonfinite_leaves"],
        "leaf_paths": [e["path"] for e in census["nonfinite_leaves"][:4]],
        "live_scrapes": scraper.scrapes,
        "seconds": round(wall_s, 2),
    }
    with open(os.path.join(REPO, "OBS_NUMERICS.json"), "w") as f:
        json.dump(out, f, indent=1)
    return out


def main():
    t0 = time.time()
    result = {"observability": observability_probe()}
    result["graftscope"] = graftscope_probe()
    result["numerics"] = numerics_probe()
    result["wall_s"] = round(time.time() - t0, 1)
    with open(OUT, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"smoke": "ok", **result}))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — CI needs the one-line verdict
        print(json.dumps({"smoke": "FAIL", "error": f"{type(e).__name__}: {e}"}))
        sys.exit(1)
