"""Benchmark: PPO iteration throughput + MFU on real hardware.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Measures the full PPO cadence — compiled rollout generation (prefill +
while_loop decode), fused rollout scoring, and ppo_epochs donated train
steps — and reports, alongside samples/s/chip:

- per-phase wall time (generate / score / train),
- modeled TFLOP/s and %-of-peak (MFU) for the train step and for the whole
  iteration, against the detected chip's peak bf16 FLOP/s,
- the honest model identity (a GPT-J-family architecture auto-sized to the
  chip's HBM — "gptj-l28-d4096" IS 6B; smaller chips bench a smaller
  truthfully-named proxy),
- the PIPELINED orchestrator path (PPOOrchestrator.make_experience, where
  the next chunk's generation is dispatched before the current chunk's host
  scoring) measured against the same phases run serialized, as
  "overlap_gain_pct" — the design claim, measured rather than asserted,
- an fp32-master measured point (the production master-weights dtype) on a
  smaller HBM-fitting size, alongside the flagship bf16 throughput entry.

The default preset is "auto": the largest HBM-fitting entry from SIZES at
seq 1024 (768-token prefill + 256-token decode), which routes scoring and
training attention through the pallas flash kernel. The HEADLINE value is
the PRODUCTION cadence (pipelined + fused-scoring rollouts + measured train
phase); the serialized-unfused phase loop is kept as
`ablation_serialized_unfused_samples_per_sec_per_chip`. `decode_hbm_util_pct`
states the "decode at its bandwidth floor" claim as a falsifiable percentage.

vs_baseline: the reference publishes no numbers and no Accelerate-GPU
baseline can run here (BASELINE.md), so the TPU-vs-GPU gate stays open; the
ratio reported is the MEASURED CPU head-to-head against the reference's own
training loop (bench_reference.py → HEADTOHEAD.json), scope-labeled.
"""

import gc
import json
import os
import re
import sys
import time

import numpy as np

# (name, n_layer, d_model, n_head, vocab, prompt, new_tokens, train_batch,
#  unfrozen, rollout_chunk)
# Auto sizes run with bf16 params (master + moments) — throughput benching,
# named honestly in the metric. A 16GB v5e fits the 2.0B entry; fp32-master
# production recipes shard over fsdp instead (ppo_gptj_config.yml).
# rollout_chunk > train_batch amortizes the bandwidth/latency-bound decode
# over more samples (the real orchestrator's chunk_size/batch_size split):
# measured on a v5e at 2.0B, chunk 32 over batch 8 is +57% samples/s.
# (name, L, d, heads, vocab, P, R, B, unfrozen, chunk[, w8]) — the optional
# 11th field turns on W8A16 decode for that entry (BENCH_W8 env still wins).
# The W8 2.0B entry (chunk 32 — the int8 copies cost ~+2.3 GB so chunk 48
# doesn't fit with them) measured 2.715 production samples/s/chip vs 2.647
# for chunk-48 full-precision (r4); the non-W8 entry right after it is the
# SAME-SIZE fallback if the marginal fit ever tips over, so an OOM degrades
# the quantization, not the model size.
SIZES = [
    ("gptj-l28-d4096-6.1B-bf16", 28, 4096, 16, 50400, 768, 256, 8, 2, 16),
    ("gptj-l16-d4096-3.7B-bf16", 16, 4096, 16, 50400, 768, 256, 8, 2, 16),
    ("gptj-l8-d4096-2.0B-w8-bf16", 8, 4096, 16, 50400, 768, 256, 8, 2, 32, 1),
    ("gptj-l8-d4096-2.0B-bf16", 8, 4096, 16, 50400, 768, 256, 8, 2, 48),
    ("gptj-l4-d4096-1.2B-bf16", 4, 4096, 16, 50400, 768, 256, 8, 2, 32),
    ("gptj-l4-d2048-0.4B-bf16", 4, 2048, 16, 50400, 768, 256, 8, 2, 32),
    ("gptj-l2-d512-tiny", 2, 512, 8, 1024, 256, 128, 4, 1, 8),
]
# fp32-master measured points (production master-weights dtype; the big
# recipes shard fp32 masters over fsdp on a pod — single-chip benches the
# largest fp32 size that fits). Largest-fitting entry runs as a SECONDARY
# measurement alongside the flagship bf16 number.
FP32_SIZES = [
    ("gptj-l6-d2048-0.5B-fp32", 6, 2048, 16, 50400, 768, 256, 8, 2, 32),
    ("gptj-l4-d2048-0.4B-fp32", 4, 2048, 16, 50400, 768, 256, 8, 2, 32),
    ("gptj-l2-d1024-0.1B-fp32", 2, 1024, 16, 50400, 768, 256, 8, 1, 16),
]
# Legacy fixed presets (BENCH_PRESET env) — the r1 shapes, kept comparable.
# ILQL bench sizes: the reference's ILQL cadence is short-sequence offline
# batches (seq 64, configs/ilql_config.yml:8) and the method trains ALL
# layers + 4 vocab-wide Q heads (2 online + 2 target) — different memory
# shape than PPO (full-trunk Adam moments + [B,T,vocab] Q tensors in the
# loss), so the candidate list is its own. (name, L, d, heads, vocab, P, R,
# B, unfrozen(-1=all), C unused)
ILQL_SIZES = [
    # d4096 at -1 unfrozen was dropped after measurement (r4): the tunneled
    # backend's remote compile helper 500s on it deterministically (two
    # same-size retries), burning ~6 min of bench budget before the fallback.
    # Batch 128 is the reference's own ilql_config batch size and measured
    # +47% over b32 here (358 vs 243 samples/s/chip, 61.9% vs 42.0% MFU —
    # short seq-64 rows need the batch dim for arithmetic intensity).
    ("ilql-l4-d2048-0.4B-bf16", 4, 2048, 16, 50400, 16, 48, 128, -1, 32),
    # SAME-SIZE fallback at b32 (the b128 loss holds ~4x larger [B,T,vocab]
    # Q tensors): an OOM degrades the batch, not the model size.
    ("ilql-l4-d2048-0.4B-b32-bf16", 4, 2048, 16, 50400, 16, 48, 32, -1, 32),
    ("ilql-l2-d512-tiny", 2, 512, 8, 1024, 16, 48, 16, -1, 16),
]

PRESETS = {
    "tiny": ("gptj-l2-d256", 2, 256, 8, 1024, 16, 32, 16, 1, 16),
    "small": ("gptj-l8-d1024", 8, 1024, 16, 50400, 16, 32, 16, 4, 16),
    "medium": ("gptj-l16-d2048", 16, 2048, 16, 50400, 16, 32, 8, 8, 8),
    "long": ("gptj-l8-d1024", 8, 1024, 16, 50400, 768, 256, 4, 4, 4),
}

# Peak dense bf16 FLOP/s per chip by device_kind substring.
PEAK_TFLOPS = [
    ("v6", 918.0),  # trillium
    ("v5p", 459.0),
    ("v5e", 197.0),  # v5 litepod
    ("v5", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),  # bf16
    ("v2", 45.0),
]


def detect_peak_tflops():
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for key, peak in PEAK_TFLOPS:
        if key in kind:
            return peak, kind
    return None, kind


# Peak HBM bandwidth (GB/s) per chip by device_kind substring — for the
# decode_hbm_util_pct derivation (decode is the bandwidth-bound phase).
HBM_GBPS = [
    ("v5 lite", 819),
    ("v5e", 819),
    ("v5p", 2765),
    ("v6", 1638),
    ("v4", 1228),
    ("v3", 900),
    ("v2", 700),
]


def detect_hbm_gbps():
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for key, bw in HBM_GBPS:
        if key in kind:
            return bw
    return None


# HBM per chip by device_kind substring, for environments (like the tunneled
# axon chip) where memory_stats() is unavailable.
HBM_BYTES = [
    ("v5 lite", 16e9),
    ("v5e", 16e9),
    ("v5p", 95e9),
    ("v6", 32e9),
    ("v4", 32e9),
    ("v3", 32e9),
    ("v2", 16e9),
]


def hbm_bytes():
    import jax

    dev = jax.devices()[0]
    try:
        stats = dev.memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:
        pass
    kind = dev.device_kind.lower()
    for key, hbm in HBM_BYTES:
        if key in kind:
            return int(hbm)
    return None


# Allocator-specific phrases only: a bare 'alloc'/'memory'/'hbm' net would
# classify unrelated runtime errors ('invalid memory access', layout/allocation
# asserts) as OOM and silently fall back to a smaller size.
_OOM_PHRASES = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "failed to allocate",
    "allocation failed",
)
# "oom" only as a whole word (plus the oom_kill/oomkilled variants) — a bare
# substring would match unrelated text ("zoom", "bloomfilter", file paths)
# and wrongly trigger the size fallback.
_OOM_WORD = re.compile(r"\boom(?:_?kill(?:ed|er)?)?\b")


def is_transient_compile_failure(e: Exception) -> bool:
    """The tunneled backend can fail transiently: remote-compile HTTP 500s
    (tpu_compile_helper subprocess failures) and FAILED_PRECONDITION device
    states right after a previous process released the chip. Those deserve
    ONE same-size retry — falling straight back to a smaller size would
    silently shrink the flagship measurement."""
    msg = str(e).lower()
    return ("remote_compile" in msg and "http 5" in msg) or "failed_precondition" in msg


def is_oom(e: Exception) -> bool:
    """Allocator-failure detection for the auto-size fallback. The classified
    error is logged to stderr so a misclassification is visible in the bench
    transcript rather than silently becoming a smaller model size."""
    msg = str(e).lower()
    hit = any(s in msg for s in _OOM_PHRASES) or bool(_OOM_WORD.search(msg))
    if hit:
        print(f"[bench] classified as OOM ({type(e).__name__}): {str(e)[:500]}", file=sys.stderr)
    return hit


def fits_hbm(L, d, vocab, unfrozen, hbm, param_bytes=2):
    """Rough static-memory model: master params + Adam moments on trainable
    params (top `unfrozen` blocks + embeddings + heads) + frozen ref branch
    copy, all at `param_bytes` per element, with a 1.6x activation/workspace
    margin. Conservative on purpose — the auto-sizer also try/excepts OOM."""
    block = 12 * d * d
    emb = 2 * vocab * d  # wte + untied lm_head
    params = L * block + emb
    trainable = unfrozen * block + emb + 3 * 2 * d * d  # + value head approx
    branch = unfrozen * block + emb  # frozen ref branch copy (hydra extras)
    bytes_needed = (params + trainable * 2 + branch) * param_bytes
    return bytes_needed * 1.6 < hbm


def lm_flops(L, d, vocab, n_tokens, kv_avg, logits_tokens, value_head=False):
    """Modeled fwd matmul FLOPs: per LAYER 12·d² MACs/token in blocks
    (qkv+proj+mlp) + 2·kv·d MACs/token attention; plus d·vocab MACs per
    logits token and (value_head) 4·d² MACs/token; ×2 FLOP/MAC."""
    per_tok = L * (12 * d * d + 2 * kv_avg * d)
    if value_head:
        per_tok += 4 * d * d  # MLPHead d -> 2d -> 1
    return 2.0 * (n_tokens * per_tok + logits_tokens * d * vocab)


def _setup_compile_cache():
    import jax

    cache_dir = os.environ.get("BENCH_COMPILE_CACHE", os.path.expanduser("~/.cache/trlx_tpu/xla"))
    if cache_dir:
        try:
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass


OOM_EXIT_CODE = 77

# Crash-proof run forensics (graftscope.RunManifest): the manifest is opened
# at the top of main() and every heartbeat / child rc / partial result is one
# line-atomic append, so a `timeout -k`-killed bench (the BENCH_r04/r05
# shapes) still leaves a parseable journal bench_trajectory.py can turn into
# a reason string. Module-global so the __main__ crash handler can close it.
_MANIFEST = None


def main():
    global _MANIFEST
    import jax

    from trlx_tpu.observability.graftscope import MANIFEST_FILENAME, RunManifest

    _setup_compile_cache()
    manifest = _MANIFEST = RunManifest(
        os.path.join(os.path.dirname(os.path.abspath(__file__)), MANIFEST_FILENAME),
        cmd=" ".join(sys.argv),
        backend=jax.default_backend(),
    )

    preset = os.environ.get("BENCH_PRESET", "auto")
    fp32_point = os.environ.get("BENCH_FP32_POINT", "1") == "1"
    if preset != "auto":
        candidates = [PRESETS[preset]]
        fp32_candidates = []
    else:
        hbm = hbm_bytes()
        candidates = [
            s for s in SIZES if hbm is None or fits_hbm(s[1], s[2], s[4], s[8], hbm)
        ] or [SIZES[-1]]
        fp32_candidates = [
            s
            for s in FP32_SIZES
            if hbm is None or fits_hbm(s[1], s[2], s[4], s[8], hbm, param_bytes=4)
        ] or [FP32_SIZES[-1]]
        if jax.default_backend() != "tpu":  # CPU dev runs: smallest only —
            # and no default fp32 point (seq-1024 fp32 on CPU takes hours);
            # set BENCH_FP32_POINT=1 explicitly to force it.
            candidates = [SIZES[-1]]
            fp32_candidates = [FP32_SIZES[-1]]
            fp32_point = os.environ.get("BENCH_FP32_POINT") == "1"

    # On the real TPU each size candidate runs in a SUBPROCESS: an OOM'd
    # attempt's device memory is only reliably reclaimed when its process
    # dies (measured on the tunneled axon backend: after one in-process OOM
    # even the tiny size fails), so in-process fallback would poison every
    # subsequent size. CPU dev runs stay in-process (no such leak; subprocess
    # jax re-init would dominate).
    use_subproc = (
        jax.default_backend() == "tpu" and os.environ.get("BENCH_SUBPROC", "1") == "1"
    )

    # Non-OOM candidate failures (a child rc!=0, an in-process exception) are
    # RECORDED here and the size ladder continues — they must never abort the
    # whole bench. Observed live (BENCH_r05): a Mosaic lowering ValueError in
    # the flagship child raised RuntimeError at this layer and the run
    # produced no JSON at all, when the next size down would have run fine.
    failed_candidates = []

    def _record_failure(cand, rc, tail):
        failed_candidates.append(
            {"candidate": cand[0], "rc": rc, "tail": tail[-2000:] if tail else ""}
        )
        manifest.child(cand[0], rc, tail or "")
        print(
            f"bench: {cand[0]} failed (rc={rc}); recorded, trying next size",
            file=sys.stderr,
        )

    def try_one(cand, _retried=False, **kwargs):
        nonlocal use_subproc
        if not use_subproc:
            try:
                return run_one(cand, **kwargs)
            except Exception as e:
                if not is_oom(e):
                    if is_transient_compile_failure(e) and not _retried:
                        # same-size retry exists on this path too — without
                        # it a transient FAILED_PRECONDITION on the flagship
                        # would abort the whole bench in in-process mode.
                        print("bench: transient backend failure; retrying this size once", file=sys.stderr)
                        return try_one(cand, _retried=True, **kwargs)
                    _record_failure(cand, None, f"{type(e).__name__}: {str(e)}")
                    e.__traceback__ = None
                    del e
                    gc.collect()
                    return None
                # Drop the traceback BEFORE collecting: its frames pin the
                # failed trainer's device arrays.
                e.__traceback__ = None
                del e
                gc.collect()
                return None
        import subprocess

        payload = json.dumps({"cand": cand, "kwargs": kwargs})
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one", payload],
            capture_output=True,
            text=True,
        )
        if proc.returncode == OOM_EXIT_CODE or proc.returncode < 0:
            # OOM exit, or the runtime hard-aborted the child (SIGABRT from a
            # native allocator failure never reaches the Python handler) —
            # either way this size doesn't fit; keep the attempt debuggable.
            manifest.child(cand[0], proc.returncode, proc.stderr)
            sys.stderr.write(proc.stderr[-1500:])
            return None
        if proc.returncode != 0:
            # Standard TPU VMs hold libtpu exclusively per process: the
            # parent's backend probe already claimed the device, so children
            # can't. Fall back to in-process attempts there (the axon
            # tunneled backend, where subprocess isolation is REQUIRED for
            # OOM recovery, has no such exclusivity). Keyed on the SPECIFIC
            # exclusivity message — a generic libtpu mention also appears in
            # ordinary abort logs and must not disable isolation.
            if "already in use" in proc.stderr:
                use_subproc = False
                print(
                    "bench: TPU is process-exclusive here — falling back to "
                    "in-process size attempts",
                    file=sys.stderr,
                )
                return try_one(cand, **kwargs)
            sys.stderr.write(proc.stderr[-4000:])
            _record_failure(cand, proc.returncode, proc.stderr)
            return None
        if proc.stderr.strip():
            sys.stderr.write(proc.stderr[-1500:])
        return json.loads(proc.stdout.strip().splitlines()[-1])

    # Expected train-phase seconds per iteration at DEFAULT knobs, from
    # measured history (BASELINE.md). Used only to detect the post-OOM
    # degraded-device pathology (a 4x-slow train phase was measured once on
    # the tunneled chip after an OOM'd attempt, r3): a wildly slow phase
    # triggers ONE fresh-subprocess re-run instead of publishing a poisoned
    # number.
    EXPECTED_TRAIN_SECONDS = {
        "gptj-l8-d4096-2.0B-w8-bf16": 8.6,
        "gptj-l8-d4096-2.0B-bf16": 12.7,
    }
    # The expectations above were measured on one tunneled v5e; on any other
    # chip generation a slower train phase is legitimate, so the degraded
    # check only applies when the measured device kind matches (both spellings
    # runtimes use for that chip, mirroring the HBM/TFLOP tables above).
    EXPECTED_TRAIN_DEVICE_KINDS = ("v5 lite", "v5e")
    _knobs_overridden = any(
        os.environ.get(k)
        for k in (
            "BENCH_BATCH",
            "BENCH_CHUNK",
            "BENCH_PROMPT",
            "BENCH_DECODE",
            "BENCH_REMAT",
            "BENCH_REMAT_POLICY",
            "BENCH_ITERS",
            "BENCH_W8",
            "BENCH_KV_QUANT",
        )
    )

    def _train_seconds(result):
        return (result or {}).get("phase_seconds_per_iter", {}).get("train")

    def _degraded(cand, result):
        exp = EXPECTED_TRAIN_SECONDS.get(cand[0])
        t = _train_seconds(result)
        kind = str((result or {}).get("device_kind", "")).lower()
        kind_matches = any(k in kind for k in EXPECTED_TRAIN_DEVICE_KINDS)
        return bool(exp and t and kind_matches and not _knobs_overridden and t > 2.5 * exp)

    def first_fitting(cands, **kwargs):
        for cand in cands:
            # Journal BEFORE launching: a hard kill mid-candidate leaves
            # this heartbeat as the manifest's "died during X" evidence.
            manifest.heartbeat(
                "size_ladder", candidate=cand[0], mode=kwargs.get("mode", "ppo")
            )
            result = try_one(cand, **kwargs)
            if result is None:
                print(f"bench: {cand[0]} did not complete, trying next size", file=sys.stderr)
                continue
            if _degraded(cand, result):
                if use_subproc:
                    # a FRESH subprocess is the only thing that clears the
                    # post-OOM state; in-process mode (process-exclusive TPU
                    # VMs) would just re-measure the same pathology, so skip
                    # straight to flagging there.
                    print(
                        f"bench: {cand[0]} train phase {_train_seconds(result):.1f}s vs "
                        f"~{EXPECTED_TRAIN_SECONDS[cand[0]]}s expected — device may be "
                        "degraded (post-OOM pathology); re-running once in a fresh "
                        "subprocess",
                        file=sys.stderr,
                    )
                    retry = try_one(cand, **kwargs)
                    if retry is not None and (_train_seconds(retry) or 1e9) < _train_seconds(result):
                        result = retry
                if _degraded(cand, result):
                    result["degraded_suspect"] = True  # publish, but flagged
            return result
        return None

    bench_t0 = time.time()
    # Parse up front: a malformed value must fail BEFORE the flagship run,
    # not crash the bench after it (losing the very JSON line this guard
    # protects).
    try:
        optional_deadline = float(os.environ.get("BENCH_OPTIONAL_DEADLINE", "900"))
    except ValueError:
        print("bench: invalid BENCH_OPTIONAL_DEADLINE; using 900s", file=sys.stderr)
        optional_deadline = 900.0

    def _optional_budget_left(label):
        """The flagship number must never be lost to a driver-side timeout
        because optional points pushed the total past the budget: once
        elapsed exceeds BENCH_OPTIONAL_DEADLINE seconds (e.g. the flagship
        needed slow OOM fallbacks), skip remaining optional points with a
        note instead of gambling the whole JSON line."""
        elapsed = time.time() - bench_t0
        if elapsed > optional_deadline:
            print(
                f"bench: skipping {label} — {elapsed:.0f}s elapsed exceeds "
                f"BENCH_OPTIONAL_DEADLINE={optional_deadline:.0f}s",
                file=sys.stderr,
            )
            return False
        return True

    result = first_fitting(candidates)
    if result is None:
        detail = "; ".join(
            f"{f['candidate']} rc={f['rc']}" for f in failed_candidates
        )
        msg = "no bench size fit the device" + (
            f" (non-OOM failures: {detail})" if detail else ""
        )
        manifest.finish(rc=1, reason=msg)
        raise RuntimeError(msg)
    # The flagship number exists from here on: journal it immediately so a
    # kill during the OPTIONAL points (fp32/ILQL) cannot lose it.
    manifest.partial(
        {k: result.get(k) for k in ("metric", "value", "unit", "size") if k in result}
    )
    if failed_candidates:
        # Published alongside the flagship number: which larger sizes failed
        # for non-OOM reasons, with the stderr tail for triage.
        result["failed_candidates"] = failed_candidates
    def _optional_point(label, fn):
        """Optional points are failure-isolated: ANY error in one (transient
        backend states, subprocess deaths) must cost that point only — never
        the flagship JSON line measured above. Observed live: a
        FAILED_PRECONDITION in the fp32 subprocess after the flagship
        completed would have discarded the whole run."""
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — the whole point is isolation
            print(f"bench: {label} failed ({type(e).__name__}: {str(e)[:300]}); continuing without it", file=sys.stderr)
            return None

    if fp32_candidates and fp32_point and _optional_budget_left("fp32 point"):
        gc.collect()
        manifest.heartbeat("fp32_point")
        fp32 = _optional_point(
            "fp32 point", lambda: first_fitting(fp32_candidates, iters=2, orchestrator=False)
        )
        if fp32 is not None:
            result["fp32_master_point"] = {
                k: fp32[k]
                for k in (
                    "metric",
                    "value",
                    "unit",
                    "phase_seconds_per_iter",
                    "train_mfu_pct",
                    "iter_mfu_pct",
                )
                if k in fp32
            }

    # ILQL measured point (the reference ships two methods; both get a perf
    # story). Heads add ~4x(2d*V) params over the PPO config, so the fitting
    # size may be smaller — the same OOM-fallback machinery sizes it.
    if os.environ.get("BENCH_ILQL_POINT", "1") == "1" and _optional_budget_left("ILQL point"):
        gc.collect()
        manifest.heartbeat("ilql_point")
        ilql_candidates = ILQL_SIZES if preset == "auto" else [ILQL_SIZES[-1]]
        if jax.default_backend() != "tpu":
            ilql_candidates = [ILQL_SIZES[-1]]
        ilql = _optional_point(
            "ILQL point", lambda: first_fitting(ilql_candidates, mode="ilql", iters=2)
        )
        if ilql is not None:
            result["ilql_point"] = ilql

    # The first MEASURED baseline ratio: bench_reference.py runs the
    # reference's OWN trlx.train head-to-head against trlx_tpu on CPU
    # (identical dataset + protocol, the reference's own metric) and records
    # HEADTOHEAD.json. Scope-labeled — a same-hardware implementation ratio,
    # NOT the v4-32 ≥2x gate (which needs hardware this environment lacks).
    h2h_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "HEADTOHEAD.json")
    if os.path.exists(h2h_path):
        # Assemble in a temp dict so a malformed file leaves `result`
        # untouched (vs_baseline really does stay null on any failure).
        try:
            with open(h2h_path) as f:
                h2h = json.load(f)
            if "reference" in h2h:  # legacy single-task layout
                h2h = {"ilql": h2h}
            # The headline metric is a PPO throughput number, so the primary
            # `vs_baseline` carries the PPO ratio (same method); both methods
            # are exposed symmetrically under vs_baseline_{ppo,ilql}_* keys.
            fields = {}
            if "ppo" in h2h:
                ppo = h2h["ppo"]
                fields["vs_baseline"] = ppo["vs_baseline_samples_per_s"]
                fields["vs_baseline_scope"] = (
                    "CPU head-to-head vs the reference's own training loop "
                    "(randomwalks PPO, identical dataset/protocol/metric — "
                    "HEADTOHEAD.json; cold-compile included). Warm-cache: "
                    f"{ppo.get('vs_baseline_warm_cache')}, full-step steady-state: "
                    f"{ppo.get('vs_baseline_steady_state')}. Not the v4-32 gate."
                )
                fields["vs_baseline_ppo"] = ppo["vs_baseline_samples_per_s"]
                fields["vs_baseline_ppo_warm_cache"] = ppo.get("vs_baseline_warm_cache")
                fields["vs_baseline_ppo_steady_state"] = ppo.get("vs_baseline_steady_state")
                fields["vs_baseline_ppo_steady_cycle"] = ppo.get("vs_baseline_steady_cycle")
            if "ilql" in h2h:
                ilql = h2h["ilql"]
                fields["vs_baseline_ilql"] = ilql["vs_baseline_samples_per_s"]
                fields["vs_baseline_ilql_warm_cache"] = ilql.get("vs_baseline_warm_cache")
                fields["vs_baseline_ilql_steady_state"] = ilql.get("vs_baseline_steady_state")
                fields["vs_baseline_final_optimality"] = {
                    "reference": ilql["reference"]["final_optimality"],
                    "ours": ilql["ours"]["final_optimality"],
                }
                if "vs_baseline" not in fields:
                    # ILQL-only (or legacy single-task) file: a measured ratio
                    # on disk must not surface as null — fall back with an
                    # explicit cross-method scope label.
                    fields["vs_baseline"] = ilql["vs_baseline_samples_per_s"]
                    fields["vs_baseline_scope"] = (
                        "CPU head-to-head vs the reference's own training loop "
                        "(randomwalks ILQL — no PPO section in HEADTOHEAD.json; "
                        "note the headline metric is a PPO throughput). "
                        f"Warm-cache: {ilql.get('vs_baseline_warm_cache')}, "
                        f"steady-state: {ilql.get('vs_baseline_steady_state')}. "
                        "Not the v4-32 gate."
                    )
            result.update(fields)
        except (KeyError, ValueError, TypeError) as e:
            print(f"bench: HEADTOHEAD.json unreadable ({e}); vs_baseline stays null", file=sys.stderr)
    print(json.dumps(result))
    manifest.finish(rc=0, metric=result.get("metric"), value=result.get("value"))


def device_sync(tree):
    """True device sync: host-read one scalar of the result. On the tunneled
    axon backend block_until_ready does NOT actually block, so a tiny
    transfer is the only reliable phase barrier (and the real PPO cadence
    has exactly these host reads anyway). Do NOT 'simplify' to
    block_until_ready — it would silently skew every phase timing on axon."""
    import jax

    leaf = jax.tree_util.tree_leaves(tree)[0]
    np.asarray(jax.device_get(leaf[(0,) * leaf.ndim]))


def run_one(cand, iters=None, orchestrator=True, mode="ppo"):
    import jax

    if mode == "ilql":
        return run_one_ilql(cand, iters=iters)

    name, n_layer, d_model, n_head, vocab, P, R, B, unfrozen, C = cand[:10]
    cand_w8 = bool(cand[10]) if len(cand) > 10 else False
    # Tuning knobs (experimentation; the shipped SIZES carry the defaults).
    B = int(os.environ.get("BENCH_BATCH", B))
    C = int(os.environ.get("BENCH_CHUNK", C))
    P = int(os.environ.get("BENCH_PROMPT", P))
    R = int(os.environ.get("BENCH_DECODE", R))
    remat_env = os.environ.get("BENCH_REMAT")
    from trlx_tpu.data import PPORLBatch
    from trlx_tpu.trainer.api import default_config
    from trlx_tpu.trainer.ppo import PPOTrainer

    n_dev = jax.device_count()
    B = ((B + n_dev - 1) // n_dev) * n_dev
    C = max(((C + B - 1) // B) * B, B)  # chunk = whole train batches
    T = P + R

    config = default_config("ppo")
    config.model.model_path = ""
    config.model.tokenizer_path = ""
    config.model.num_layers_unfrozen = unfrozen
    config.model.model_arch = {
        "vocab_size": vocab,
        "n_layer": n_layer,
        "n_head": n_head,
        "d_model": d_model,
        "max_position": max(2048, T),
        "eos_token_id": 0,
        "pos_type": "rotary",
        "rotary_dim": 64 if d_model // n_head >= 64 else d_model // n_head,
        "parallel_residual": True,
        "fused_qkv": False,
        "qkv_bias": False,
        "out_bias": False,
        "tie_word_embeddings": False,
        "extra": {"lm_head_bias": True},
    }
    config.model.remat = d_model >= 4096 if remat_env is None else remat_env == "1"
    config.model.remat_policy = os.environ.get("BENCH_REMAT_POLICY", "full")
    # int8 decode KV cache ON by default for the bench: decode is HBM-bound
    # on cache reads, int8 halves that traffic (+6% samples/s at 2.0B) and
    # frees HBM for a larger rollout chunk. Learning-quality verified: PPO
    # randomwalks reaches 1.0 optimality with it; training re-forwards are
    # always full precision, and under fused rollout stats the stored
    # behavior logprobs are the quantized sampler's own (≤0.008 from the fp
    # recompute — tests/test_fused_rollout.py).
    config.model.kv_cache_quant = os.environ.get("BENCH_KV_QUANT", "1") == "1"
    # W8A16 decode (int8 trunk kernels for sampling only): measured −18..21%
    # decode time (BASELINE.md). Per-entry default via the SIZES w8 field —
    # the flagship 2.0B entry runs W8 at chunk 32 (2.715 vs 2.647 production
    # samples/s/chip measured r4; chunk 48 + the ~+2.3 GB int8 copies don't
    # fit). BENCH_W8 env overrides either way.
    w8_env = os.environ.get("BENCH_W8")
    config.model.decode_weight_quant = (w8_env == "1") if w8_env is not None else cand_w8
    if name.endswith("-bf16"):
        # Throughput benching at the largest HBM-fitting size: bf16 master
        # params + moments (named honestly in the metric). Production fp32-
        # master recipes shard over fsdp instead.
        config.model.param_dtype = "bfloat16"
    config.train.batch_size = B
    config.train.seq_length = T
    config.train.mesh = [-1, 1, 1, 1]
    config.method.gen_kwargs = {
        "prompt_length": P,
        "max_new_tokens": R,
        "min_new_tokens": R,  # fixed-length decode: measure the full loop
        "do_sample": True,
        "top_k": 0,
        "top_p": 1.0,
    }
    config.method.chunk_size = C
    config.method.num_rollouts = C
    config.method.ppo_epochs = 4

    trainer = PPOTrainer(config)
    rng = np.random.default_rng(0)
    prompt_ids = rng.integers(2, vocab, size=(C, P)).astype(np.int32)
    prompt_mask = np.ones((C, P), dtype=np.int32)

    sync = device_sync

    def phase_generate():
        tokens, mask = trainer.rollout_generate(prompt_ids, prompt_mask)
        sync(tokens)
        return tokens, mask

    def phase_score(tokens, mask):
        scores = rng.normal(size=(C,)).astype(np.float32)
        out = trainer.rollout_score(tokens, mask, scores)
        sync(out[0])
        return out

    def phase_train(tokens, mask, logprobs, values, rewards, warmup=False):
        """The chunk trains as C/B donated sub-batches × ppo_epochs steps —
        the orchestrator's chunk_size/batch_size split. Warmup compiles with
        just the first sub-batch (all sub-batches share one program)."""
        tk, mk, lp, v, r = (np.asarray(x) for x in (tokens, mask, logprobs, values, rewards))
        for s in range(0, B if warmup else C, B):
            sl = slice(s, s + B)
            batch = trainer.put_batch(
                PPORLBatch(
                    query_tensors=tk[sl, :P],
                    response_tensors=tk[sl, P:],
                    logprobs=lp[sl],
                    values=v[sl],
                    rewards=r[sl],
                    response_mask=mk[sl, P:],
                    query_mask=mk[sl, :P],
                )
            )
            for _ in range(config.method.ppo_epochs):
                trainer.state, stats = trainer.train_step(trainer.state, batch)
        sync(trainer.state.params)

    # Warmup / compile all three programs once.
    tokens, mask = phase_generate()
    logprobs, values, rewards, _ = phase_score(tokens, mask)
    phase_train(tokens, mask, logprobs, values, rewards, warmup=True)

    iters = iters if iters is not None else int(os.environ.get("BENCH_ITERS", "3"))
    t_gen = t_score = t_train = 0.0
    t0 = time.time()
    for _ in range(iters):
        t = time.time()
        tokens, mask = phase_generate()
        t_gen += time.time() - t
        t = time.time()
        logprobs, values, rewards, _ = phase_score(tokens, mask)
        t_score += time.time() - t
        t = time.time()
        phase_train(tokens, mask, logprobs, values, rewards)
        t_train += time.time() - t
    elapsed = time.time() - t0

    n_chips = jax.device_count()
    samples = iters * C
    sps_per_chip = samples / elapsed / n_chips

    # ---- modeled FLOPs (see lm_flops) -------------------------------------
    L, d, V = n_layer, d_model, vocab
    resp = T - P + 1  # logits region [P-1, T)
    kv_train = T / 2  # causal average
    fwd_train = lm_flops(L, d, V, B * T, kv_train, B * resp, value_head=True)
    # bwd = activation-grad pass over everything + weight-grad pass over the
    # trainable fraction (stop_gradient skips frozen weight grads).
    f_train = (unfrozen * 12 * d * d + 2 * V * d) / (L * 12 * d * d + 2 * V * d)
    train_step = fwd_train * (2.0 + f_train)
    train_flops = config.method.ppo_epochs * (C // B) * train_step
    # scoring: policy fwd + frozen branch replay over `unfrozen` layers
    score_flops = lm_flops(L, d, V, C * T, kv_train, C * resp, value_head=True) + lm_flops(
        unfrozen, d, V, C * T, kv_train, C * resp
    )
    # generation: prefill + R single-token decode steps (kv grows P..T)
    gen_flops = lm_flops(L, d, V, C * P, P / 2, C) + lm_flops(
        L, d, V, C * R, (P + T) / 2, C * R
    )
    iter_flops = gen_flops + score_flops + train_flops

    peak, kind = detect_peak_tflops()
    train_tflops = train_flops * iters / max(t_train, 1e-9) / n_chips / 1e12
    iter_tflops = iter_flops * iters / max(elapsed, 1e-9) / n_chips / 1e12

    out = {
        "metric": f"ppo_samples_per_sec_per_chip[{name},seq{T},prefill{P}+decode{R},chunk{C},b{B}]",
        "value": round(sps_per_chip, 3),
        # No measured Accelerate-GPU reference exists in this environment
        # (BASELINE.md) — null, not a fabricated ratio.
        "vs_baseline": None,
        "unit": "samples/s/chip",
        "device_kind": kind,
        "n_chips": n_chips,
        "phase_seconds_per_iter": {
            "generate": round(t_gen / iters, 3),
            "score": round(t_score / iters, 3),
            "train": round(t_train / iters, 3),
        },
        "train_tflops_per_chip": round(train_tflops, 2),
        "iter_tflops_per_chip": round(iter_tflops, 2),
    }
    if peak:
        out["peak_bf16_tflops"] = peak
        out["train_mfu_pct"] = round(100 * train_tflops / peak, 2)
        out["iter_mfu_pct"] = round(100 * iter_tflops / peak, 2)

    # ---- decode HBM utilization (the falsifiable form of "decode runs at
    # its bandwidth floor"): modeled bytes the decode loop must move —
    # weights re-read every step + growing KV-cache reads/writes — over the
    # measured decode seconds. Decode time = generate phase minus a modeled
    # prefill (prefill FLOPs at the measured TRAIN MFU — both are
    # large-batch matmul phases). 100% ≈ the roofline; the gap is the
    # remaining W8/int8-KV headroom.
    bw_gbps = detect_hbm_gbps()
    if bw_gbps and peak and t_gen > 0:
        w8 = bool(config.model.decode_weight_quant)
        wb = 1.0 if w8 else 2.0  # int8 trunk kernels vs bf16
        kvb = 1.0 if config.model.kv_cache_quant else 2.0
        # per-step weight reads: trunk matmuls + lm_head (batch C shares one
        # read); wte is a C-row gather — negligible.
        step_weight_bytes = (L * 12 * d * d + V * d) * wb
        # KV reads grow P→T over the R steps (keys+values), one write/step.
        kv_bytes = C * L * 2 * d * kvb * (R * (P + T) / 2 + R)
        decode_bytes = R * step_weight_bytes + kv_bytes
        prefill_flops = lm_flops(L, d, V, C * P, P / 2, C)
        mfu = max(train_tflops / peak, 1e-3)
        t_prefill = prefill_flops / (peak * 1e12 * mfu)
        t_decode = max(t_gen / iters - t_prefill, 1e-6)
        out["decode_hbm_util_pct"] = round(
            100.0 * decode_bytes / t_decode / (bw_gbps * 1e9), 1
        )
        out["decode_hbm_model"] = {
            "peak_hbm_gbps": bw_gbps,
            "decode_seconds_modeled": round(t_decode, 3),
            "prefill_seconds_modeled": round(t_prefill, 3),
            "weight_bytes_per_step_gb": round(step_weight_bytes / 1e9, 3),
            "kv_bytes_total_gb": round(kv_bytes / 1e9, 3),
        }
    if orchestrator and os.environ.get("BENCH_ORCH", "1") == "1":
        orch_out = bench_orchestrator(trainer, C, P, vocab)
        out["orchestrator"] = orch_out
        # THE HEADLINE IS THE PRODUCTION PATH: full-cadence throughput with
        # rollouts going through the REAL pipelined (+fused) orchestrator —
        # chunk rollout time from the orchestrator measurement + the measured
        # train phase. The serialized-phase loop measured above (unfused
        # scorer, full sync between phases) is kept as the ablation field.
        rollout_s = C / max(orch_out["samples_per_sec_per_chip"] * n_chips, 1e-9)
        production = C / (rollout_s + t_train / iters) / n_chips
        out["ablation_serialized_unfused_samples_per_sec_per_chip"] = out["value"]
        out["value"] = round(production, 3)
        out["metric"] = out["metric"].replace(
            "ppo_samples_per_sec_per_chip", "ppo_production_samples_per_sec_per_chip"
        )
        # iteration MFU at the production cadence. With fused stats the
        # scoring pass is a ref-branch replay only — model THAT flop count,
        # not the unfused full re-forward, so the MFU is not inflated by a
        # faster wall clock against phantom FLOPs.
        if peak:
            if orch_out.get("fused_rollout_stats"):
                prod_score_flops = lm_flops(unfrozen, d, V, C * T, kv_train, C * resp)
            else:
                prod_score_flops = score_flops
            prod_flops = gen_flops + prod_score_flops + train_flops
            prod_iter_tflops = prod_flops / max(rollout_s + t_train / iters, 1e-9) / n_chips / 1e12
            out["production_iter_mfu_pct"] = round(100 * prod_iter_tflops / peak, 2)
    return out


def bench_orchestrator(trainer, C, P, vocab):
    """Measure the PIPELINED rollout path (PPOOrchestrator.make_experience:
    the next chunk's generation is dispatched before the current chunk's
    decode + host reward_fn + scoring) against the SAME work run serialized
    (full device sync between every phase). The delta is the overlap the
    orchestrator design buys; reported as overlap_gain_pct.

    The host reward here is a real (cheap) numpy pass over the decoded token
    rows; BENCH_HOST_MS adds emulated heavier host scoring (e.g. a sentiment
    model) per chunk to probe how the gain scales with host cost."""
    import jax

    from trlx_tpu.orchestrator.ppo_orchestrator import PPOOrchestrator
    from trlx_tpu.pipeline.prompt_pipeline import PromptPipeline

    host_ms = float(os.environ.get("BENCH_HOST_MS", "0"))
    rng = np.random.default_rng(7)

    def reward_fn(rows):
        if host_ms:
            time.sleep(host_ms / 1e3)
        return [float(np.mean(np.asarray(r, np.float32)) / vocab) for r in rows]

    prompts = [list(map(int, rng.integers(2, vocab, size=P))) for _ in range(C)]
    pipeline = PromptPipeline(prompts, None, max_prompt_length=P)
    orch = PPOOrchestrator(trainer, pipeline, reward_fn, chunk_size=C)
    n_chunks = int(os.environ.get("BENCH_ORCH_CHUNKS", "3"))
    rows_per_chunk = C // jax.process_count()
    sync = device_sync

    # Warmup: one pipelined pass compiles generate+score for this shape.
    trainer.store.clear_history()
    orch.make_experience(rows_per_chunk)

    trainer.store.clear_history()
    t0 = time.time()
    orch.make_experience(n_chunks * rows_per_chunk)
    t_pipelined = time.time() - t0

    def serialized_pass(fused: bool) -> float:
        """The same chunks with hard syncs between every phase (the
        reference's serial structure, reference:
        trlx/orchestrator/ppo_orchestrator.py:58-110); `fused` picks the
        in-decode-stats scorer vs the full policy re-forward."""
        trainer.store.clear_history()
        t0 = time.time()
        for _ in range(n_chunks):
            # Same prompt pipeline as every other pass — the comparison must
            # time identical work, not different prompt sets.
            tokens, mask, p_len, aux = orch._generate_next_chunk(fused=fused)
            sync(tokens)
            tokens_h, mask_h = trainer.to_local_host((tokens, mask))
            scores = np.asarray(reward_fn(trainer.decode(tokens_h, mask_h)), np.float32)
            if aux is not None:
                outs = trainer.rollout_score_fused(tokens, mask, scores, aux)
            else:
                outs = trainer.rollout_score(tokens, mask, scores)
            sync(outs[0])
            logprobs, values, rewards, _ = trainer.to_local_host(outs)
            trainer.store.push_batch(
                {
                    "query_tensors": tokens_h[:, :p_len],
                    "query_mask": mask_h[:, :p_len],
                    "response_tensors": tokens_h[:, p_len:],
                    "response_mask": mask_h[:, p_len:],
                    "logprobs": logprobs,
                    "values": values,
                    "rewards": rewards,
                }
            )
        trainer.store.clear_history()
        return time.time() - t0

    fused_on = bool(getattr(trainer, "fused_rollout", False))
    # serialized with the SAME scorer the pipelined path used → isolates the
    # overlap gain; serialized unfused → isolates the fused-scoring gain.
    t_serial = serialized_pass(fused=fused_on)
    t_serial_unfused = serialized_pass(fused=False) if fused_on else t_serial

    samples = n_chunks * C
    # All *_gain_pct fields are THROUGHPUT (rate) gains: rate_a/rate_b − 1.
    out = {
        "samples_per_sec_per_chip": round(samples / t_pipelined / jax.device_count(), 3),
        "serialized_samples_per_sec_per_chip": round(samples / t_serial / jax.device_count(), 3),
        "overlap_gain_pct": round(100.0 * (t_serial / max(t_pipelined, 1e-9) - 1.0), 2),
        "fused_rollout_stats": fused_on,
        "host_ms_emulated_per_chunk": host_ms,
        "n_chunks": n_chunks,
    }
    if fused_on:
        out["serialized_unfused_samples_per_sec_per_chip"] = round(
            samples / t_serial_unfused / jax.device_count(), 3
        )
        out["fused_scoring_gain_pct"] = round(
            100.0 * (t_serial_unfused / max(t_serial, 1e-9) - 1.0), 2
        )
    return out


def run_one_ilql(cand, iters=None):
    """ILQL full-cadence bench (the reference's second method had no perf
    story until now — capability: trlx/model/accelerate_ilql_model.py:50-156,
    trlx/model/nn/ilql_models.py:162-251):

    - train samples/s/chip + modeled MFU over the jitted ILQL step (trunk +
      double vocab-wide Q heads + target heads + V head + AWAC logits) at
      the reference cadence incl. the jitted Polyak target sync every
      `steps_for_target_q_sync` steps,
    - advantage-steered decode tokens/s/chip (target-Q steering
      `pi_beta + beta*(Q−V)`, top-k, in-loop stat collection).

    Dataset is synthetic full-length token rows (compute, not learning, is
    under measurement; learning gates live in tests/test_e2e.py)."""
    import jax

    from trlx_tpu.orchestrator.offline_orchestrator import OfflineOrchestrator
    from trlx_tpu.trainer.api import default_config
    from trlx_tpu.trainer.ilql import ILQLTrainer

    name, n_layer, d_model, n_head, vocab, P, R, B, unfrozen, C = cand[:10]
    # ILQL-specific knobs (the BENCH_PROMPT/BENCH_DECODE PPO knobs don't
    # apply — ILQL's cadence is short-sequence offline, ILQL_SIZES).
    B = int(os.environ.get("BENCH_ILQL_BATCH", B))
    n_dev = jax.device_count()
    B = ((B + n_dev - 1) // n_dev) * n_dev
    T = P + R

    config = default_config("ilql")
    config.model.model_path = ""
    config.model.tokenizer_path = ""
    config.model.num_layers_unfrozen = -1  # reference ILQL default: all train
    config.model.model_arch = {
        "vocab_size": vocab,
        "n_layer": n_layer,
        "n_head": n_head,
        "d_model": d_model,
        "max_position": max(2048, T),
        "eos_token_id": 0,
        "pos_type": "rotary",
        "rotary_dim": 64 if d_model // n_head >= 64 else d_model // n_head,
        "parallel_residual": True,
        "fused_qkv": False,
        "qkv_bias": False,
        "out_bias": False,
        "tie_word_embeddings": False,
        "extra": {"lm_head_bias": True},
    }
    config.model.remat = d_model >= 4096 if os.environ.get("BENCH_REMAT") is None else os.environ.get("BENCH_REMAT") == "1"
    config.model.kv_cache_quant = os.environ.get("BENCH_KV_QUANT", "1") == "1"
    if name.endswith("-bf16"):
        config.model.param_dtype = "bfloat16"
    config.train.batch_size = B
    config.train.seq_length = T
    config.train.mesh = [-1, 1, 1, 1]
    config.method.gen_kwargs = {
        "prompt_length": P,
        "max_new_tokens": R,
        "min_new_tokens": R,
        "top_k": 20,
    }
    trainer = ILQLTrainer(config)

    rng = np.random.default_rng(0)
    samples = [rng.integers(2, vocab, size=(T,)).astype(np.int32) for _ in range(2 * B)]
    rewards = rng.normal(size=(2 * B,)).astype(np.float32).tolist()
    OfflineOrchestrator(trainer).make_experience(samples, rewards)
    batch = next(iter(trainer.store.create_loader(B, shuffle=True)))
    device_batch = trainer.put_batch(batch)

    sync_every = max(int(config.method.steps_for_target_q_sync), 1)

    def train_steps(n):
        for _ in range(n):
            trainer.state, stats = trainer.train_step(trainer.state, device_batch)
            trainer.iter_count += 1
            trainer.post_backward_callback(stats)  # Polyak sync at cadence
        device_sync(trainer.state.params)

    train_steps(1)  # compile
    steps = (iters if iters is not None else int(os.environ.get("BENCH_ITERS", "3"))) * max(
        4, sync_every
    )
    t0 = time.time()
    train_steps(steps)
    t_train = time.time() - t0

    prompt_ids = rng.integers(2, vocab, size=(B, P)).astype(np.int32)
    pmask = np.ones((B, P), dtype=np.int32)
    tokens, _ = trainer.rollout_generate(prompt_ids, pmask)  # compile
    device_sync(tokens)
    dec_iters = 2
    t0 = time.time()
    for _ in range(dec_iters):
        tokens, _ = trainer.rollout_generate(prompt_ids, pmask)
        device_sync(tokens)
    t_dec = (time.time() - t0) / dec_iters

    # Plain-sampling ablation: the same model/params/shapes WITHOUT advantage
    # steering (no Q/V carry, no per-step head evals, default logit chain) —
    # the measured price of ILQL's steered decode vs vanilla sampling.
    from trlx_tpu.ops.generate import make_generate_fn as _mk_gen

    plain_fn = _mk_gen(trainer.model, trainer.gen_cfg)
    swapped = {"params": {**trainer.state.params, **trainer.state.extras}}
    batch_io = trainer.put_batch({"i": prompt_ids, "m": pmask})
    ptok, _ = plain_fn(swapped, batch_io["i"], batch_io["m"], trainer.next_rng())  # compile
    device_sync(ptok)
    t0 = time.time()
    for _ in range(dec_iters):
        ptok, _ = plain_fn(swapped, batch_io["i"], batch_io["m"], trainer.next_rng())
        device_sync(ptok)
    t_plain = (time.time() - t0) / dec_iters

    n_chips = jax.device_count()
    sps_per_chip = steps * B / t_train / n_chips
    decode_tps_per_chip = B * R / t_dec / n_chips

    # ---- modeled FLOPs. Per-token head MACs (d→2d→vocab MLP): online Q
    # heads train (fwd+bwd ≈ 3x fwd), target heads are fwd-only, V head
    # trains; trunk is fully trainable here (num_layers_unfrozen = -1).
    L, d, V = n_layer, d_model, vocab
    mac_q = 2 * d * d + 2 * d * V
    mac_v = 2 * d * d + 2 * d
    trunk_fwd = lm_flops(L, d, V, B * T, T / 2, B * T)
    # trunk fwd+bwd ≈ 3x fwd; heads: 2 online Q at 3x, 2 target Q at 1x
    # (fwd only, no grads), V head at 3x — all per token, x2 FLOP/MAC.
    step_flops = 3.0 * trunk_fwd + 2.0 * B * T * (3 * 2 * mac_q + 1 * 2 * mac_q + 3 * mac_v)
    train_tflops = step_flops * steps / max(t_train, 1e-9) / n_chips / 1e12

    peak, kind = detect_peak_tflops()
    out = {
        "metric": f"ilql_train_samples_per_sec_per_chip[{name},seq{T},b{B}]",
        "value": round(sps_per_chip, 3),
        "unit": "samples/s/chip",
        "device_kind": kind,
        "ilql_decode_tokens_per_s_per_chip": round(decode_tps_per_chip, 1),
        "decode_seconds_per_batch": round(t_dec, 3),
        "train_seconds_per_step": round(t_train / steps, 4),
        "target_q_sync_every": sync_every,
        "ilql_train_tflops_per_chip": round(train_tflops, 2),
    }
    if peak:
        out["ilql_train_mfu_pct"] = round(100 * train_tflops / peak, 2)

    out["plain_decode_tokens_per_s_per_chip"] = round(B * R / t_plain / n_chips, 1)
    out["steering_overhead_pct"] = round(100.0 * (t_dec - t_plain) / max(t_plain, 1e-9), 1)

    # ---- decode HBM roofline (same honesty the PPO point gets): modeled
    # bytes the steered decode must move per batch — trunk + lm_head weights
    # re-read every step, the two (target) Q heads + V head the steering
    # evaluates per step, and the growing KV cache — over the measured decode
    # seconds net of a modeled prefill (prefill FLOPs at the measured train
    # MFU, the same large-batch-matmul proxy the PPO model uses).
    bw_gbps = detect_hbm_gbps()
    if bw_gbps and peak and t_dec > 0:
        # trunk/head param bytes follow param_dtype (ILQL has no W8 path)
        pb = 2.0 if config.model.param_dtype == "bfloat16" else 4.0
        kvb = 1.0 if config.model.kv_cache_quant else 2.0
        head_bytes = 2 * (d * 2 * d + 2 * d * V) + (d * 2 * d + 2 * d)
        step_weight_bytes = (L * 12 * d * d + V * d + head_bytes) * pb
        kv_bytes = B * L * 2 * d * kvb * (R * (P + T) / 2 + R)
        decode_bytes = R * step_weight_bytes + kv_bytes
        prefill_flops = lm_flops(L, d, V, B * P, P / 2, B)
        mfu = max(train_tflops / peak, 1e-3)
        t_prefill = prefill_flops / (peak * 1e12 * mfu)
        t_decode = max(t_dec - t_prefill, 1e-6)
        out["decode_hbm_util_pct"] = round(100.0 * decode_bytes / t_decode / (bw_gbps * 1e9), 1)
        out["decode_hbm_model"] = {
            "peak_hbm_gbps": bw_gbps,
            "decode_seconds_modeled": round(t_decode, 3),
            "prefill_seconds_modeled": round(t_prefill, 3),
            "weight_bytes_per_step_gb": round(step_weight_bytes / 1e9, 3),
            "head_bytes_per_step_gb": round(head_bytes * pb / 1e9, 3),
            "kv_bytes_total_gb": round(kv_bytes / 1e9, 3),
        }
    return out


def _main_one(payload: str):
    """Subprocess entry: run ONE size candidate, print its JSON; exit
    OOM_EXIT_CODE on allocator failure so the parent tries the next size
    with a clean device."""
    _setup_compile_cache()
    spec = json.loads(payload)
    try:
        result = run_one(tuple(spec["cand"]), **spec["kwargs"])
    except Exception as e:
        # OOM outranks the transient class: a FAILED_PRECONDITION whose text
        # also matches an allocator phrase means this process's memory is
        # already poisoned (post-OOM state is unrecoverable in-process) —
        # exit for the parent's clean-device size fallback, don't retry here.
        if is_oom(e):
            sys.exit(OOM_EXIT_CODE)
        if is_transient_compile_failure(e):
            print("bench: transient backend failure; retrying this size once", file=sys.stderr)
            try:
                result = run_one(tuple(spec["cand"]), **spec["kwargs"])
            except Exception as e2:
                if is_oom(e2):
                    sys.exit(OOM_EXIT_CODE)
                raise
            print(json.dumps(result))
            return
        raise
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--one":
        _main_one(sys.argv[2])
        sys.exit(0)
    try:
        sys.exit(main())
    except BaseException as e:
        # SystemExit(0) falls through finish() above; anything else gets a
        # forensic end record (finish() is idempotent, so a reason already
        # journaled — e.g. "no bench size fit" — stands). A SIGKILL never
        # reaches here, which is exactly what the heartbeat trail is for.
        if _MANIFEST is not None and not isinstance(e, SystemExit):
            _MANIFEST.finish(rc=1, reason=f"{type(e).__name__}: {str(e)[:300]}")
        raise
