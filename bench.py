"""Benchmark: PPO iteration throughput + MFU on real hardware.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Measures the full PPO cadence — compiled rollout generation (prefill +
while_loop decode), fused rollout scoring, and ppo_epochs donated train
steps — and reports, alongside samples/s/chip:

- per-phase wall time (generate / score / train),
- modeled TFLOP/s and %-of-peak (MFU) for the train step and for the whole
  iteration, against the detected chip's peak bf16 FLOP/s,
- the honest model identity (a GPT-J-family architecture auto-sized to the
  chip's HBM — "gptj-l28-d4096" IS 6B; smaller chips bench a smaller
  truthfully-named proxy),
- the PIPELINED orchestrator path (PPOOrchestrator.make_experience, where
  the next chunk's generation is dispatched before the current chunk's host
  scoring) measured against the same phases run serialized, as
  "overlap_gain_pct" — the design claim, measured rather than asserted,
- an fp32-master measured point (the production master-weights dtype) on a
  smaller HBM-fitting size, alongside the flagship bf16 throughput entry.

The default preset is "auto": the largest HBM-fitting entry from SIZES at
seq 1024 (768-token prefill + 256-token decode), which routes scoring and
training attention through the pallas flash kernel. The reference publishes
no numbers and no measured Accelerate-GPU baseline exists in this
environment (BASELINE.md), so vs_baseline is null — not a placeholder ratio.
"""

import gc
import json
import os
import sys
import time

import numpy as np

# (name, n_layer, d_model, n_head, vocab, prompt, new_tokens, train_batch,
#  unfrozen, rollout_chunk)
# Auto sizes run with bf16 params (master + moments) — throughput benching,
# named honestly in the metric. A 16GB v5e fits the 2.0B entry; fp32-master
# production recipes shard over fsdp instead (ppo_gptj_config.yml).
# rollout_chunk > train_batch amortizes the bandwidth/latency-bound decode
# over more samples (the real orchestrator's chunk_size/batch_size split):
# measured on a v5e at 2.0B, chunk 32 over batch 8 is +57% samples/s.
SIZES = [
    ("gptj-l28-d4096-6.1B-bf16", 28, 4096, 16, 50400, 768, 256, 8, 2, 16),
    ("gptj-l16-d4096-3.7B-bf16", 16, 4096, 16, 50400, 768, 256, 8, 2, 16),
    ("gptj-l8-d4096-2.0B-bf16", 8, 4096, 16, 50400, 768, 256, 8, 2, 48),
    ("gptj-l4-d4096-1.2B-bf16", 4, 4096, 16, 50400, 768, 256, 8, 2, 32),
    ("gptj-l4-d2048-0.4B-bf16", 4, 2048, 16, 50400, 768, 256, 8, 2, 32),
    ("gptj-l2-d512-tiny", 2, 512, 8, 1024, 256, 128, 4, 1, 8),
]
# fp32-master measured points (production master-weights dtype; the big
# recipes shard fp32 masters over fsdp on a pod — single-chip benches the
# largest fp32 size that fits). Largest-fitting entry runs as a SECONDARY
# measurement alongside the flagship bf16 number.
FP32_SIZES = [
    ("gptj-l6-d2048-0.5B-fp32", 6, 2048, 16, 50400, 768, 256, 8, 2, 32),
    ("gptj-l4-d2048-0.4B-fp32", 4, 2048, 16, 50400, 768, 256, 8, 2, 32),
    ("gptj-l2-d1024-0.1B-fp32", 2, 1024, 16, 50400, 768, 256, 8, 1, 16),
]
# Legacy fixed presets (BENCH_PRESET env) — the r1 shapes, kept comparable.
PRESETS = {
    "tiny": ("gptj-l2-d256", 2, 256, 8, 1024, 16, 32, 16, 1, 16),
    "small": ("gptj-l8-d1024", 8, 1024, 16, 50400, 16, 32, 16, 4, 16),
    "medium": ("gptj-l16-d2048", 16, 2048, 16, 50400, 16, 32, 8, 8, 8),
    "long": ("gptj-l8-d1024", 8, 1024, 16, 50400, 768, 256, 4, 4, 4),
}

# Peak dense bf16 FLOP/s per chip by device_kind substring.
PEAK_TFLOPS = [
    ("v6", 918.0),  # trillium
    ("v5p", 459.0),
    ("v5e", 197.0),  # v5 litepod
    ("v5", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),  # bf16
    ("v2", 45.0),
]


def detect_peak_tflops():
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for key, peak in PEAK_TFLOPS:
        if key in kind:
            return peak, kind
    return None, kind


# HBM per chip by device_kind substring, for environments (like the tunneled
# axon chip) where memory_stats() is unavailable.
HBM_BYTES = [
    ("v5 lite", 16e9),
    ("v5e", 16e9),
    ("v5p", 95e9),
    ("v6", 32e9),
    ("v4", 32e9),
    ("v3", 32e9),
    ("v2", 16e9),
]


def hbm_bytes():
    import jax

    dev = jax.devices()[0]
    try:
        stats = dev.memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:
        pass
    kind = dev.device_kind.lower()
    for key, hbm in HBM_BYTES:
        if key in kind:
            return int(hbm)
    return None


def is_oom(e: Exception) -> bool:
    """Robust allocator-failure detection for the auto-size fallback: match
    the jaxlib error type when available, else a broad substring net —
    differently-worded allocator errors must try the next size, not abort."""
    try:
        from jax.errors import JaxRuntimeError

        if isinstance(e, JaxRuntimeError) and any(
            s in str(e).lower() for s in ("alloc", "exhausted", "memory", "oom", "hbm")
        ):
            return True
    except ImportError:
        pass
    msg = str(e).lower()
    return any(
        s in msg for s in ("resource_exhausted", "out of memory", "exhausted", "alloc", "oom", "hbm")
    )


def fits_hbm(L, d, vocab, unfrozen, hbm, param_bytes=2):
    """Rough static-memory model: master params + Adam moments on trainable
    params (top `unfrozen` blocks + embeddings + heads) + frozen ref branch
    copy, all at `param_bytes` per element, with a 1.6x activation/workspace
    margin. Conservative on purpose — the auto-sizer also try/excepts OOM."""
    block = 12 * d * d
    emb = 2 * vocab * d  # wte + untied lm_head
    params = L * block + emb
    trainable = unfrozen * block + emb + 3 * 2 * d * d  # + value head approx
    branch = unfrozen * block + emb  # frozen ref branch copy (hydra extras)
    bytes_needed = (params + trainable * 2 + branch) * param_bytes
    return bytes_needed * 1.6 < hbm


def lm_flops(L, d, vocab, n_tokens, kv_avg, logits_tokens, value_head=False):
    """Modeled fwd matmul FLOPs: per LAYER 12·d² MACs/token in blocks
    (qkv+proj+mlp) + 2·kv·d MACs/token attention; plus d·vocab MACs per
    logits token and (value_head) 4·d² MACs/token; ×2 FLOP/MAC."""
    per_tok = L * (12 * d * d + 2 * kv_avg * d)
    if value_head:
        per_tok += 4 * d * d  # MLPHead d -> 2d -> 1
    return 2.0 * (n_tokens * per_tok + logits_tokens * d * vocab)


def _setup_compile_cache():
    import jax

    cache_dir = os.environ.get("BENCH_COMPILE_CACHE", os.path.expanduser("~/.cache/trlx_tpu/xla"))
    if cache_dir:
        try:
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass


OOM_EXIT_CODE = 77


def main():
    import jax

    _setup_compile_cache()

    preset = os.environ.get("BENCH_PRESET", "auto")
    fp32_point = os.environ.get("BENCH_FP32_POINT", "1") == "1"
    if preset != "auto":
        candidates = [PRESETS[preset]]
        fp32_candidates = []
    else:
        hbm = hbm_bytes()
        candidates = [
            s for s in SIZES if hbm is None or fits_hbm(s[1], s[2], s[4], s[8], hbm)
        ] or [SIZES[-1]]
        fp32_candidates = [
            s
            for s in FP32_SIZES
            if hbm is None or fits_hbm(s[1], s[2], s[4], s[8], hbm, param_bytes=4)
        ] or [FP32_SIZES[-1]]
        if jax.default_backend() != "tpu":  # CPU dev runs: smallest only —
            # and no default fp32 point (seq-1024 fp32 on CPU takes hours);
            # set BENCH_FP32_POINT=1 explicitly to force it.
            candidates = [SIZES[-1]]
            fp32_candidates = [FP32_SIZES[-1]]
            fp32_point = os.environ.get("BENCH_FP32_POINT") == "1"

    # On the real TPU each size candidate runs in a SUBPROCESS: an OOM'd
    # attempt's device memory is only reliably reclaimed when its process
    # dies (measured on the tunneled axon backend: after one in-process OOM
    # even the tiny size fails), so in-process fallback would poison every
    # subsequent size. CPU dev runs stay in-process (no such leak; subprocess
    # jax re-init would dominate).
    use_subproc = (
        jax.default_backend() == "tpu" and os.environ.get("BENCH_SUBPROC", "1") == "1"
    )

    def try_one(cand, **kwargs):
        nonlocal use_subproc
        if not use_subproc:
            try:
                return run_one(cand, **kwargs)
            except Exception as e:
                if not is_oom(e):
                    raise
                # Drop the traceback BEFORE collecting: its frames pin the
                # failed trainer's device arrays.
                e.__traceback__ = None
                del e
                gc.collect()
                return None
        import subprocess

        payload = json.dumps({"cand": cand, "kwargs": kwargs})
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one", payload],
            capture_output=True,
            text=True,
        )
        if proc.returncode == OOM_EXIT_CODE or proc.returncode < 0:
            # OOM exit, or the runtime hard-aborted the child (SIGABRT from a
            # native allocator failure never reaches the Python handler) —
            # either way this size doesn't fit; keep the attempt debuggable.
            sys.stderr.write(proc.stderr[-1500:])
            return None
        if proc.returncode != 0:
            # Standard TPU VMs hold libtpu exclusively per process: the
            # parent's backend probe already claimed the device, so children
            # can't. Fall back to in-process attempts there (the axon
            # tunneled backend, where subprocess isolation is REQUIRED for
            # OOM recovery, has no such exclusivity). Keyed on the SPECIFIC
            # exclusivity message — a generic libtpu mention also appears in
            # ordinary abort logs and must not disable isolation.
            if "already in use" in proc.stderr:
                use_subproc = False
                print(
                    "bench: TPU is process-exclusive here — falling back to "
                    "in-process size attempts",
                    file=sys.stderr,
                )
                return try_one(cand, **kwargs)
            sys.stderr.write(proc.stderr[-4000:])
            raise RuntimeError(f"bench subprocess failed for {cand[0]} (rc={proc.returncode})")
        if proc.stderr.strip():
            sys.stderr.write(proc.stderr[-1500:])
        return json.loads(proc.stdout.strip().splitlines()[-1])

    def first_fitting(cands, **kwargs):
        for cand in cands:
            result = try_one(cand, **kwargs)
            if result is not None:
                return result
            print(f"bench: {cand[0]} OOM, trying next size", file=sys.stderr)
        return None

    result = first_fitting(candidates)
    if result is None:
        raise RuntimeError("no bench size fit the device")
    if fp32_candidates and fp32_point:
        gc.collect()
        fp32 = first_fitting(fp32_candidates, iters=2, orchestrator=False)
        if fp32 is not None:
            result["fp32_master_point"] = {
                k: fp32[k]
                for k in (
                    "metric",
                    "value",
                    "unit",
                    "phase_seconds_per_iter",
                    "train_mfu_pct",
                    "iter_mfu_pct",
                )
                if k in fp32
            }
    print(json.dumps(result))


def device_sync(tree):
    """True device sync: host-read one scalar of the result. On the tunneled
    axon backend block_until_ready does NOT actually block, so a tiny
    transfer is the only reliable phase barrier (and the real PPO cadence
    has exactly these host reads anyway). Do NOT 'simplify' to
    block_until_ready — it would silently skew every phase timing on axon."""
    import jax

    leaf = jax.tree_util.tree_leaves(tree)[0]
    np.asarray(jax.device_get(leaf[(0,) * leaf.ndim]))


def run_one(cand, iters=None, orchestrator=True):
    import jax

    name, n_layer, d_model, n_head, vocab, P, R, B, unfrozen, C = cand
    # Tuning knobs (experimentation; the shipped SIZES carry the defaults).
    B = int(os.environ.get("BENCH_BATCH", B))
    C = int(os.environ.get("BENCH_CHUNK", C))
    P = int(os.environ.get("BENCH_PROMPT", P))
    R = int(os.environ.get("BENCH_DECODE", R))
    remat_env = os.environ.get("BENCH_REMAT")
    from trlx_tpu.data import PPORLBatch
    from trlx_tpu.trainer.api import default_config
    from trlx_tpu.trainer.ppo import PPOTrainer

    n_dev = jax.device_count()
    B = ((B + n_dev - 1) // n_dev) * n_dev
    C = max(((C + B - 1) // B) * B, B)  # chunk = whole train batches
    T = P + R

    config = default_config("ppo")
    config.model.model_path = ""
    config.model.tokenizer_path = ""
    config.model.num_layers_unfrozen = unfrozen
    config.model.model_arch = {
        "vocab_size": vocab,
        "n_layer": n_layer,
        "n_head": n_head,
        "d_model": d_model,
        "max_position": max(2048, T),
        "eos_token_id": 0,
        "pos_type": "rotary",
        "rotary_dim": 64 if d_model // n_head >= 64 else d_model // n_head,
        "parallel_residual": True,
        "fused_qkv": False,
        "qkv_bias": False,
        "out_bias": False,
        "tie_word_embeddings": False,
        "extra": {"lm_head_bias": True},
    }
    config.model.remat = d_model >= 4096 if remat_env is None else remat_env == "1"
    config.model.remat_policy = os.environ.get("BENCH_REMAT_POLICY", "full")
    # int8 decode KV cache ON by default for the bench: decode is HBM-bound
    # on cache reads, int8 halves that traffic (+6% samples/s at 2.0B) and
    # frees HBM for a larger rollout chunk. Learning-quality verified: PPO
    # randomwalks reaches 1.0 optimality with it; training re-forwards are
    # always full precision, and under fused rollout stats the stored
    # behavior logprobs are the quantized sampler's own (≤0.008 from the fp
    # recompute — tests/test_fused_rollout.py).
    config.model.kv_cache_quant = os.environ.get("BENCH_KV_QUANT", "1") == "1"
    # W8A16 decode (int8 trunk kernels for sampling only): measured −18..21%
    # decode time (BASELINE.md), but the int8 copies cost ~+2.3 GB at 2.0B so
    # the default chunk-48 flagship no longer fits — default off; enable with
    # BENCH_W8=1 (pair with BENCH_CHUNK=32 at 2.0B).
    config.model.decode_weight_quant = os.environ.get("BENCH_W8", "0") == "1"
    if name.endswith("-bf16"):
        # Throughput benching at the largest HBM-fitting size: bf16 master
        # params + moments (named honestly in the metric). Production fp32-
        # master recipes shard over fsdp instead.
        config.model.param_dtype = "bfloat16"
    config.train.batch_size = B
    config.train.seq_length = T
    config.train.mesh = [-1, 1, 1, 1]
    config.method.gen_kwargs = {
        "prompt_length": P,
        "max_new_tokens": R,
        "min_new_tokens": R,  # fixed-length decode: measure the full loop
        "do_sample": True,
        "top_k": 0,
        "top_p": 1.0,
    }
    config.method.chunk_size = C
    config.method.num_rollouts = C
    config.method.ppo_epochs = 4

    trainer = PPOTrainer(config)
    rng = np.random.default_rng(0)
    prompt_ids = rng.integers(2, vocab, size=(C, P)).astype(np.int32)
    prompt_mask = np.ones((C, P), dtype=np.int32)

    sync = device_sync

    def phase_generate():
        tokens, mask = trainer.rollout_generate(prompt_ids, prompt_mask)
        sync(tokens)
        return tokens, mask

    def phase_score(tokens, mask):
        scores = rng.normal(size=(C,)).astype(np.float32)
        out = trainer.rollout_score(tokens, mask, scores)
        sync(out[0])
        return out

    def phase_train(tokens, mask, logprobs, values, rewards, warmup=False):
        """The chunk trains as C/B donated sub-batches × ppo_epochs steps —
        the orchestrator's chunk_size/batch_size split. Warmup compiles with
        just the first sub-batch (all sub-batches share one program)."""
        tk, mk, lp, v, r = (np.asarray(x) for x in (tokens, mask, logprobs, values, rewards))
        for s in range(0, B if warmup else C, B):
            sl = slice(s, s + B)
            batch = trainer.put_batch(
                PPORLBatch(
                    query_tensors=tk[sl, :P],
                    response_tensors=tk[sl, P:],
                    logprobs=lp[sl],
                    values=v[sl],
                    rewards=r[sl],
                    response_mask=mk[sl, P:],
                    query_mask=mk[sl, :P],
                )
            )
            for _ in range(config.method.ppo_epochs):
                trainer.state, stats = trainer.train_step(trainer.state, batch)
        sync(trainer.state.params)

    # Warmup / compile all three programs once.
    tokens, mask = phase_generate()
    logprobs, values, rewards, _ = phase_score(tokens, mask)
    phase_train(tokens, mask, logprobs, values, rewards, warmup=True)

    iters = iters if iters is not None else int(os.environ.get("BENCH_ITERS", "3"))
    t_gen = t_score = t_train = 0.0
    t0 = time.time()
    for _ in range(iters):
        t = time.time()
        tokens, mask = phase_generate()
        t_gen += time.time() - t
        t = time.time()
        logprobs, values, rewards, _ = phase_score(tokens, mask)
        t_score += time.time() - t
        t = time.time()
        phase_train(tokens, mask, logprobs, values, rewards)
        t_train += time.time() - t
    elapsed = time.time() - t0

    n_chips = jax.device_count()
    samples = iters * C
    sps_per_chip = samples / elapsed / n_chips

    # ---- modeled FLOPs (see lm_flops) -------------------------------------
    L, d, V = n_layer, d_model, vocab
    resp = T - P + 1  # logits region [P-1, T)
    kv_train = T / 2  # causal average
    fwd_train = lm_flops(L, d, V, B * T, kv_train, B * resp, value_head=True)
    # bwd = activation-grad pass over everything + weight-grad pass over the
    # trainable fraction (stop_gradient skips frozen weight grads).
    f_train = (unfrozen * 12 * d * d + 2 * V * d) / (L * 12 * d * d + 2 * V * d)
    train_step = fwd_train * (2.0 + f_train)
    train_flops = config.method.ppo_epochs * (C // B) * train_step
    # scoring: policy fwd + frozen branch replay over `unfrozen` layers
    score_flops = lm_flops(L, d, V, C * T, kv_train, C * resp, value_head=True) + lm_flops(
        unfrozen, d, V, C * T, kv_train, C * resp
    )
    # generation: prefill + R single-token decode steps (kv grows P..T)
    gen_flops = lm_flops(L, d, V, C * P, P / 2, C) + lm_flops(
        L, d, V, C * R, (P + T) / 2, C * R
    )
    iter_flops = gen_flops + score_flops + train_flops

    peak, kind = detect_peak_tflops()
    train_tflops = train_flops * iters / max(t_train, 1e-9) / n_chips / 1e12
    iter_tflops = iter_flops * iters / max(elapsed, 1e-9) / n_chips / 1e12

    out = {
        "metric": f"ppo_samples_per_sec_per_chip[{name},seq{T},prefill{P}+decode{R},chunk{C},b{B}]",
        "value": round(sps_per_chip, 3),
        # No measured Accelerate-GPU reference exists in this environment
        # (BASELINE.md) — null, not a fabricated ratio.
        "vs_baseline": None,
        "unit": "samples/s/chip",
        "device_kind": kind,
        "n_chips": n_chips,
        "phase_seconds_per_iter": {
            "generate": round(t_gen / iters, 3),
            "score": round(t_score / iters, 3),
            "train": round(t_train / iters, 3),
        },
        "train_tflops_per_chip": round(train_tflops, 2),
        "iter_tflops_per_chip": round(iter_tflops, 2),
    }
    if peak:
        out["peak_bf16_tflops"] = peak
        out["train_mfu_pct"] = round(100 * train_tflops / peak, 2)
        out["iter_mfu_pct"] = round(100 * iter_tflops / peak, 2)
    if orchestrator and os.environ.get("BENCH_ORCH", "1") == "1":
        orch_out = bench_orchestrator(trainer, C, P, vocab)
        out["orchestrator"] = orch_out
        # Derived full-cadence throughput when rollouts go through the REAL
        # pipelined (+fused) orchestrator path instead of the serialized
        # phase loop the primary metric uses: chunk rollout time from the
        # orchestrator measurement + the measured train phase.
        rollout_s = C / max(orch_out["samples_per_sec_per_chip"] * n_chips, 1e-9)
        out["production_samples_per_sec_per_chip"] = round(
            C / (rollout_s + t_train / iters) / n_chips, 3
        )
    return out


def bench_orchestrator(trainer, C, P, vocab):
    """Measure the PIPELINED rollout path (PPOOrchestrator.make_experience:
    the next chunk's generation is dispatched before the current chunk's
    decode + host reward_fn + scoring) against the SAME work run serialized
    (full device sync between every phase). The delta is the overlap the
    orchestrator design buys; reported as overlap_gain_pct.

    The host reward here is a real (cheap) numpy pass over the decoded token
    rows; BENCH_HOST_MS adds emulated heavier host scoring (e.g. a sentiment
    model) per chunk to probe how the gain scales with host cost."""
    import jax

    from trlx_tpu.orchestrator.ppo_orchestrator import PPOOrchestrator
    from trlx_tpu.pipeline.prompt_pipeline import PromptPipeline

    host_ms = float(os.environ.get("BENCH_HOST_MS", "0"))
    rng = np.random.default_rng(7)

    def reward_fn(rows):
        if host_ms:
            time.sleep(host_ms / 1e3)
        return [float(np.mean(np.asarray(r, np.float32)) / vocab) for r in rows]

    prompts = [list(map(int, rng.integers(2, vocab, size=P))) for _ in range(C)]
    pipeline = PromptPipeline(prompts, None, max_prompt_length=P)
    orch = PPOOrchestrator(trainer, pipeline, reward_fn, chunk_size=C)
    n_chunks = int(os.environ.get("BENCH_ORCH_CHUNKS", "3"))
    rows_per_chunk = C // jax.process_count()
    sync = device_sync

    # Warmup: one pipelined pass compiles generate+score for this shape.
    trainer.store.clear_history()
    orch.make_experience(rows_per_chunk)

    trainer.store.clear_history()
    t0 = time.time()
    orch.make_experience(n_chunks * rows_per_chunk)
    t_pipelined = time.time() - t0

    def serialized_pass(fused: bool) -> float:
        """The same chunks with hard syncs between every phase (the
        reference's serial structure, reference:
        trlx/orchestrator/ppo_orchestrator.py:58-110); `fused` picks the
        in-decode-stats scorer vs the full policy re-forward."""
        trainer.store.clear_history()
        t0 = time.time()
        for _ in range(n_chunks):
            # Same prompt pipeline as every other pass — the comparison must
            # time identical work, not different prompt sets.
            tokens, mask, p_len, aux = orch._generate_next_chunk(fused=fused)
            sync(tokens)
            tokens_h, mask_h = trainer.to_local_host((tokens, mask))
            scores = np.asarray(reward_fn(trainer.decode(tokens_h, mask_h)), np.float32)
            if aux is not None:
                outs = trainer.rollout_score_fused(tokens, mask, scores, aux)
            else:
                outs = trainer.rollout_score(tokens, mask, scores)
            sync(outs[0])
            logprobs, values, rewards, _ = trainer.to_local_host(outs)
            trainer.store.push_batch(
                {
                    "query_tensors": tokens_h[:, :p_len],
                    "query_mask": mask_h[:, :p_len],
                    "response_tensors": tokens_h[:, p_len:],
                    "response_mask": mask_h[:, p_len:],
                    "logprobs": logprobs,
                    "values": values,
                    "rewards": rewards,
                }
            )
        trainer.store.clear_history()
        return time.time() - t0

    fused_on = bool(getattr(trainer, "fused_rollout", False))
    # serialized with the SAME scorer the pipelined path used → isolates the
    # overlap gain; serialized unfused → isolates the fused-scoring gain.
    t_serial = serialized_pass(fused=fused_on)
    t_serial_unfused = serialized_pass(fused=False) if fused_on else t_serial

    samples = n_chunks * C
    # All *_gain_pct fields are THROUGHPUT (rate) gains: rate_a/rate_b − 1.
    out = {
        "samples_per_sec_per_chip": round(samples / t_pipelined / jax.device_count(), 3),
        "serialized_samples_per_sec_per_chip": round(samples / t_serial / jax.device_count(), 3),
        "overlap_gain_pct": round(100.0 * (t_serial / max(t_pipelined, 1e-9) - 1.0), 2),
        "fused_rollout_stats": fused_on,
        "host_ms_emulated_per_chunk": host_ms,
        "n_chunks": n_chunks,
    }
    if fused_on:
        out["serialized_unfused_samples_per_sec_per_chip"] = round(
            samples / t_serial_unfused / jax.device_count(), 3
        )
        out["fused_scoring_gain_pct"] = round(
            100.0 * (t_serial_unfused / max(t_serial, 1e-9) - 1.0), 2
        )
    return out


def _main_one(payload: str):
    """Subprocess entry: run ONE size candidate, print its JSON; exit
    OOM_EXIT_CODE on allocator failure so the parent tries the next size
    with a clean device."""
    _setup_compile_cache()
    spec = json.loads(payload)
    try:
        result = run_one(tuple(spec["cand"]), **spec["kwargs"])
    except Exception as e:
        if is_oom(e):
            sys.exit(OOM_EXIT_CODE)
        raise
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--one":
        _main_one(sys.argv[2])
        sys.exit(0)
    sys.exit(main())
