"""Benchmark: PPO iteration throughput (samples/sec/chip) on real hardware.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Measures the full PPO cadence — compiled rollout generation (prefill +
while_loop decode), fused rollout scoring, and ppo_epochs donated train steps
— on a GPT-J-family model sized to the chip (BENCH_PRESET env: tiny|small|
medium|long; long runs seq-1024 through the pallas flash path). The reference publishes no numbers (BASELINE.md); the recorded
Accelerate-GPU comparison baseline is 1.0 samples/sec/chip until a measured
reference lands, so vs_baseline == value.
"""

import json
import os
import sys
import time

import numpy as np


PRESETS = {
    # name: (n_layer, d_model, n_head, vocab, prompt_len, new_tokens, batch)
    "tiny": (2, 256, 8, 1024, 16, 32, 16),
    "small": (8, 1024, 16, 50400, 16, 32, 16),
    "medium": (16, 2048, 16, 50400, 16, 32, 8),
    # long-context: seq 1024 routes scoring/training attention through the
    # pallas flash kernel (and the sp ring when run on an sp>1 mesh)
    "long": (8, 1024, 16, 50400, 768, 256, 4),
}


def main():
    preset = os.environ.get("BENCH_PRESET", "small")
    n_layer, d_model, n_head, vocab, P, R, B = PRESETS[preset]

    import jax

    # Persistent XLA compilation cache: repeated bench runs (the driver runs
    # this every round) skip the 20-40s first-compile cost.
    cache_dir = os.environ.get("BENCH_COMPILE_CACHE", os.path.expanduser("~/.cache/trlx_tpu/xla"))
    if cache_dir:
        try:
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass

    from trlx_tpu.data import PPORLBatch
    from trlx_tpu.trainer.api import default_config
    from trlx_tpu.trainer.ppo import PPOTrainer

    # Batch must shard evenly over the data-parallel axis on multi-chip hosts.
    n_dev = jax.device_count()
    B = ((B + n_dev - 1) // n_dev) * n_dev

    config = default_config("ppo")
    config.model.model_path = ""
    config.model.tokenizer_path = ""
    config.model.num_layers_unfrozen = max(n_layer // 2, 1)
    config.model.model_arch = {
        "vocab_size": vocab,
        "n_layer": n_layer,
        "n_head": n_head,
        "d_model": d_model,
        "max_position": 2048,
        "eos_token_id": 0,
        "pos_type": "rotary",
        "rotary_dim": 64 if d_model // n_head >= 64 else d_model // n_head,
        "parallel_residual": True,
        "fused_qkv": False,
        "qkv_bias": False,
        "out_bias": False,
        "tie_word_embeddings": False,
        "extra": {"lm_head_bias": True},
    }
    config.train.batch_size = B
    config.train.seq_length = P + R
    config.train.mesh = [-1, 1, 1, 1]
    config.method.gen_kwargs = {
        "prompt_length": P,
        "max_new_tokens": R,
        "min_new_tokens": R,  # fixed-length decode: measure the full loop
        "do_sample": True,
        "top_k": 0,
        "top_p": 1.0,
    }
    config.method.chunk_size = B
    config.method.num_rollouts = B
    config.method.ppo_epochs = 4

    trainer = PPOTrainer(config)
    n_chips = jax.device_count()
    rng = np.random.default_rng(0)
    prompt_ids = rng.integers(2, vocab, size=(B, P)).astype(np.int32)
    prompt_mask = np.ones((B, P), dtype=np.int32)

    def ppo_iteration():
        tokens, mask = trainer.rollout_generate(prompt_ids, prompt_mask)
        scores = rng.normal(size=(B,)).astype(np.float32)
        logprobs, values, rewards, _ = trainer.rollout_score(tokens, mask, scores)
        batch = trainer.put_batch(
            PPORLBatch(
                query_tensors=np.asarray(tokens[:, :P]),
                response_tensors=np.asarray(tokens[:, P:]),
                logprobs=np.asarray(logprobs),
                values=np.asarray(values),
                rewards=np.asarray(rewards),
                response_mask=np.asarray(mask[:, P:]),
                query_mask=np.asarray(mask[:, :P]),
            )
        )
        for _ in range(config.method.ppo_epochs):
            trainer.state, stats = trainer.train_step(trainer.state, batch)
        jax.block_until_ready(trainer.state.params)

    # warmup / compile
    ppo_iteration()

    iters = int(os.environ.get("BENCH_ITERS", "3"))
    t0 = time.time()
    for _ in range(iters):
        ppo_iteration()
    elapsed = time.time() - t0

    samples = iters * B
    sps_per_chip = samples / elapsed / n_chips
    print(
        json.dumps(
            {
                "metric": f"ppo_samples_per_sec_per_chip[{preset},gptj-arch,l{n_layer},d{d_model},seq{P+R}]",
                "value": round(sps_per_chip, 3),
                "unit": "samples/s/chip",
                "vs_baseline": round(sps_per_chip, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
