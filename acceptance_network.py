"""One-command network-day acceptance: run the gated example suite, write ACCEPTANCE.json.

`make acceptance-network` (or `python acceptance_network.py`) runs
`pytest -m network` — the four reference acceptance examples
(ppo_sentiments, ilql_sentiments, simulacra, architext; reference:
README.md:22-43, examples/*.py) with their learning gates — and distills one
machine-readable verdict:

- per-test outcome (passed / failed / skipped) from pytest's junit xml,
- each run's metric trajectory (mean_reward / metrics/sentiment / ...)
  harvested from the tracker's metrics.jsonl under --basetemp,
- the environment (device kind, steps knob) the run used.

Without TRLX_TPU_NETWORK=1 every test skips (this container has no egress);
the harness still runs end-to-end and writes ACCEPTANCE.json with
status "skipped-no-network" — that IS the offline smoke test
(tests/test_acceptance_harness.py) keeping the network-day command from
bitrotting. See RUNBOOK.md for the day-one checklist.
"""

import glob
import json
import os
import subprocess
import sys
import time
import xml.etree.ElementTree as ET

REPO = os.path.dirname(os.path.abspath(__file__))
RESULT_PATH = os.path.join(REPO, "ACCEPTANCE.json")

# test name -> (trajectory key in metrics.jsonl, reference config it mirrors)
TESTS = {
    "test_ppo_sentiments": ("mean_reward", "configs/ppo_config.yml"),
    "test_ilql_sentiments": ("metrics/sentiments", "configs/ilql_config.yml"),
    "test_ppo_gptj": ("mean_reward", "configs/ppo_gptj.yml"),
    "test_simulacra": ("histogram:decode/vs", "examples/simulacra.py"),
    "test_architext": ("mean_reward", "examples/architext.py"),
}


def _trajectories(basetemp):
    """metrics.jsonl files under pytest's basetemp, keyed by the test whose
    tmp_path contains them (tmp dirs are named <test_name><idx>)."""
    out = {}
    for path in glob.glob(os.path.join(basetemp, "**", "metrics.jsonl"), recursive=True):
        rel = os.path.relpath(path, basetemp)
        test = next((t for t in TESTS if rel.startswith(t)), None)
        if test is None:
            continue
        key = TESTS[test][0]
        hist = key.split(":", 1)[1] if key.startswith("histogram:") else None
        vals = []
        # Tolerant reader: a run killed mid-append (preemption, host_kill
        # drill) leaves a torn final line; the completed records still count.
        from trlx_tpu.utils.logging import read_jsonl

        for rec in read_jsonl(path):
            if hist is not None:
                if rec.get("histogram") == hist:
                    vals.append(round(float(rec["mean"]), 4))
            elif key in rec:
                vals.append(round(float(rec[key]), 4))
        out[test] = vals
    return out


def main(out_path: str = RESULT_PATH, extra_args=None) -> dict:
    basetemp = os.path.join(REPO, "acceptance_tmp")
    junit = os.path.join(basetemp, "junit.xml")
    os.makedirs(basetemp, exist_ok=True)

    t0 = time.time()
    cmd = [
        sys.executable, "-m", "pytest", "-m", "network", "-q",
        "--basetemp", basetemp, "--junitxml", junit, "tests/test_network.py",
    ] + (extra_args or [])
    proc = subprocess.run(cmd, cwd=REPO)
    wall = time.time() - t0

    outcomes = {}
    suite = ET.parse(junit).getroot()
    for case in suite.iter("testcase"):
        name = case.get("name")
        if case.find("skipped") is not None:
            outcomes[name] = "skipped"
        elif case.find("failure") is not None or case.find("error") is not None:
            outcomes[name] = "failed"
        else:
            outcomes[name] = "passed"

    networked = os.environ.get("TRLX_TPU_NETWORK") == "1"
    trajectories = _trajectories(basetemp)
    result = {
        "status": (
            "skipped-no-network" if not networked
            else ("passed" if proc.returncode == 0 else "failed")
        ),
        "recorded_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "wallclock_s": round(wall, 1),
        "steps_knob": os.environ.get("TRLX_TPU_NETWORK_STEPS", "default"),
        "tests": {
            t: {
                "outcome": outcomes.get(t, "missing"),
                "metric_key": TESTS[t][0],
                "reference_config": TESTS[t][1],
                "trajectory": trajectories.get(t, []),
            }
            for t in TESTS
        },
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"acceptance": result["status"], "out": out_path,
                      "outcomes": {t: v["outcome"] for t, v in result["tests"].items()}}))
    return result


if __name__ == "__main__":
    sys.exit(0 if main()["status"] in ("passed", "skipped-no-network") else 1)
