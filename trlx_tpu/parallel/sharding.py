"""Partition rules: param paths → PartitionSpecs over the (dp, fsdp, tp, sp) mesh.

This module is where ZeRO and Megatron-TP live in the TPU-native design. The
reference gets ZeRO stage 2/3 from a DeepSpeed YAML
(reference: configs/deepspeed_configs/default_configs.yml:2-9) and has NO
tensor parallelism (vestigial dead flags only, reference:
trlx/model/nn/ppo_models.py:120-122). Here both are just sharding specs:

- **ZeRO** — shard every large param (and its optimizer moments, which follow
  the same spec because optax states mirror the param pytree) over ``fsdp``.
- **TP** — Megatron layout: column-parallel qkv/mlp-up (shard output dim on
  ``tp``), row-parallel attn-out/mlp-down (shard input dim on ``tp``); XLA
  inserts the all-reduces.

Rules are (regex, PartitionSpec) pairs matched against the '/'-joined param
path, first match wins — the t5x convention.
"""

import re
from typing import Any, List, Sequence, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from trlx_tpu.parallel.mesh import AXIS_FSDP, AXIS_SP, AXIS_TP, DATA_AXES


def lm_partition_rules() -> List[Tuple[str, P]]:
    """Sharding rules for trlx_tpu.models.lm.TransformerLM parameters.

    Megatron-style TP + fsdp on the complementary dim, so a 6B/20B model's
    params and Adam moments spread over both axes.
    """
    return [
        # token embedding [vocab, d_model] — shard vocab on tp, d_model on fsdp
        (r"wte/embedding$", P(AXIS_TP, AXIS_FSDP)),
        (r"wpe/embedding$", P(None, AXIS_FSDP)),
        # attention: fused qkv [d_model, 3*d] column-parallel
        (r"attn/c_qkv/kernel$", P(AXIS_FSDP, AXIS_TP)),
        (r"attn/c_qkv/bias$", P(AXIS_TP)),
        (r"attn/(q_proj|k_proj|v_proj)/kernel$", P(AXIS_FSDP, AXIS_TP)),
        (r"attn/(q_proj|k_proj|v_proj)/bias$", P(AXIS_TP)),
        # attention output [d, d_model] row-parallel
        (r"attn/c_proj/kernel$", P(AXIS_TP, AXIS_FSDP)),
        (r"attn/c_proj/bias$", P(None)),
        # MLP up [d_model, d_ff] column-parallel
        (r"mlp/c_fc/kernel$", P(AXIS_FSDP, AXIS_TP)),
        (r"mlp/c_fc/bias$", P(AXIS_TP)),
        # MLP down [d_ff, d_model] row-parallel
        (r"mlp/c_proj/kernel$", P(AXIS_TP, AXIS_FSDP)),
        (r"mlp/c_proj/bias$", P(None)),
        # untied LM head [d_model, vocab]
        (r"lm_head/kernel$", P(AXIS_FSDP, AXIS_TP)),
        (r"lm_head/bias$", P(AXIS_TP)),
        # layer norms / scalars — replicated
        (r"(ln_1|ln_2|ln_f|layernorm.*)/(scale|bias)$", P()),
        # value / Q heads (2-layer MLPs, small) — shard the wide hidden dim
        (r"(v_head|q1_head|q2_head|target_q1_head|target_q2_head)/layers_0/kernel$", P(AXIS_FSDP, AXIS_TP)),
        (r"(v_head|q1_head|q2_head|target_q1_head|target_q2_head)/layers_0/bias$", P(AXIS_TP)),
        (r"(v_head|q1_head|q2_head|target_q1_head|target_q2_head)/layers_1/kernel$", P(AXIS_TP, None)),
        # soft-prompt prefix embeddings [n_tokens, d_model]
        (r"soft_prompt$", P(None, AXIS_FSDP)),
        # fallback: replicate
        (r".*", P()),
    ]


def match_partition_rules(rules: Sequence[Tuple[str, P]], tree: Any) -> Any:
    """Map each leaf's path through the rule list (first regex match wins)."""

    def match(path, _leaf):
        path_str = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        for pattern, spec in rules:
            if re.search(pattern, path_str):
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(match, tree)


def sanitize_specs(mesh, tree: Any, specs: Any) -> Any:
    """Drop per-dimension sharding that does not divide the dim evenly
    (tiny/odd vocab or head counts on a big mesh) — those dims replicate
    instead of erroring at device_put."""
    import warnings

    import numpy as _np

    def fix(leaf, spec):
        if not isinstance(spec, P):
            return spec
        dims = []
        for i, d in enumerate(spec):
            if d is None:
                dims.append(None)
                continue
            names = d if isinstance(d, tuple) else (d,)
            size = int(_np.prod([mesh.shape[n] for n in names]))
            if i < leaf.ndim and leaf.shape[i] % size == 0:
                dims.append(d)
            else:
                warnings.warn(
                    f"replicating dim {i} of a {tuple(leaf.shape)} param: "
                    f"not divisible by mesh axes {names} (size {size}) — "
                    "expect higher per-chip memory for this tensor"
                )
                dims.append(None)
        return P(*dims)

    return jax.tree_util.tree_map(fix, tree, specs)


def specs_to_shardings(mesh, specs: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda x: isinstance(x, P)
    )


def shard_pytree(tree: Any, mesh, rules: Sequence[Tuple[str, P]] = None) -> Tuple[Any, Any]:
    """Place a pytree onto the mesh per the rules.

    Returns (sharded_tree, shardings). This is the moment the reference calls
    ``accelerator.prepare`` (reference: trlx/model/accelerate_ppo_model.py:46-48)
    — param placement + ZeRO partitioning in one device_put.
    """
    rules = rules if rules is not None else lm_partition_rules()
    specs = sanitize_specs(mesh, tree, match_partition_rules(rules, tree))
    shardings = specs_to_shardings(mesh, specs)
    sharded = jax.device_put(tree, shardings)
    return sharded, shardings


def batch_sharding(mesh, extra_dims: int = 1, seq_axis: int = None) -> NamedSharding:
    """Sharding for a [batch, ...] array: batch over (dp, fsdp), optionally the
    sequence dim over sp (context parallelism)."""
    dims = [DATA_AXES] + [None] * extra_dims
    if seq_axis is not None:
        dims[seq_axis] = AXIS_SP
    return NamedSharding(mesh, P(*dims))


def replicated(mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
