"""Ring attention: sequence/context parallelism over the `sp` mesh axis.

The reference has NO long-context machinery (SURVEY.md §2c/§5 — max seq 64);
this is a TPU-first capability extension that the mesh design reserved the
`sp` axis for. Each device holds a [b, T/n, h, d] sequence chunk; K/V chunks
rotate around the ring via `lax.ppermute` over ICI while every device
accumulates attention of its local queries against each visiting chunk with
an online softmax (the same math as the pallas flash kernel, at chunk
granularity). Peak memory per device is O(T/n) in sequence — the [T, T]
score matrix never exists, and neither does a gathered K/V.

Differentiable by construction: `ppermute` and `scan` have exact transposes,
so `jax.grad` through a shard_map'd ring pass yields the reverse ring — no
hand-written backward needed.

Causality uses GLOBAL positions (chunk offset × chunk len + local index), so
results match single-device attention bit-for-bit up to reduction order.
"""

from functools import partial

import jax
import jax.numpy as jnp

try:  # jax >= 0.7 stabilized name
    from jax import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          check_vma=check_rep)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              check_rep=check_rep)

from trlx_tpu.parallel.mesh import AXIS_SP, AXIS_TP, DATA_AXES, get_mesh

MASK_VAL = -1e9
M_INIT = -1e30


def _flash_in_ring_ok(t: int, use_flash) -> bool:
    if use_flash is not None:
        return bool(use_flash)
    from trlx_tpu.ops.flash_attention import auto_flash_ok

    return auto_flash_ok(t)


def ring_attention(q, k, v, kv_mask, *, axis_name: str, n_ring: int, scale: float,
                   causal: bool = True, window: int = 0, use_flash=None):
    """Per-device body (call inside shard_map over `axis_name`).

    q/k/v: [b, t_local, h, d] — this device's sequence chunk, rotary already
    applied. kv_mask: [b, t_local] key validity (left padding). Returns
    [b, t_local, h, d] attention outputs for the local queries.

    Two per-chunk engines: the pallas flash kernel (long aligned chunks on
    TPU; exact cross-chunk combination via the kernel's log-sum-exp output,
    with the visiting chunk's displacement passed as the kernel offset) or an
    XLA einsum online-softmax (everything else). `use_flash` forces a path.
    """
    if _flash_in_ring_ok(q.shape[1], use_flash):
        return _ring_flash(q, k, v, kv_mask, axis_name=axis_name, n_ring=n_ring,
                           scale=scale, causal=causal, window=window)
    b, t, h, d = q.shape
    idx = jax.lax.axis_index(axis_name)
    q_pos = idx * t + jnp.arange(t)  # global positions of local queries

    qf = q.astype(jnp.float32)
    m0 = jnp.full((b, h, t, 1), M_INIT, jnp.float32)
    l0 = jnp.zeros((b, h, t, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, t, d), jnp.float32)
    perm = [(j, (j + 1) % n_ring) for j in range(n_ring)]

    def attend(k_c, v_c, mask_c, i, m, l, acc):
        src = (idx - i) % n_ring  # which chunk is visiting this step

        def live(_):
            k_pos = src * t + jnp.arange(t)
            s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_c.astype(jnp.float32)) * scale
            pair = mask_c[:, None, None, :] > 0
            kp = k_pos[None, None, None, :]
            qp = q_pos[None, None, :, None]
            if causal:
                pair = pair & (kp <= qp)
            if window > 0:
                pair = pair & (kp > qp - window)
            s = jnp.where(pair, s, MASK_VAL)

            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + jnp.einsum("bhqk,bkhd->bhqd", p, v_c.astype(jnp.float32))
            return m_new, l_new, acc_new

        def dead(_):
            return m, l, acc

        # Skip chunks the mask would zero out ENTIRELY — the einsum twin of
        # the flash engine's per-block liveness test: a causal pass never pays
        # for fully-future chunks (src > idx), a windowed pass never pays for
        # chunks wholly older than the window. The ppermute rotation still
        # runs every step (the ring must keep turning); only the O(t²·d)
        # einsum work is skipped. Under causality this contiguous layout is
        # load-imbalanced (rank r does r+1 live chunks) — the sharded entry
        # therefore routes causal, evenly-divisible shapes to the zig-zag
        # layout (ring_attention_zigzag below), which equalizes live work;
        # this body remains for non-causal and non-divisible shapes.
        dead_conds = []
        if causal:
            dead_conds.append(src > idx)
        if window > 0:
            dead_conds.append(src * t + t - 1 <= idx * t - window)
        if not dead_conds:
            return live(None)
        is_dead = dead_conds[0]
        for c in dead_conds[1:]:
            is_dead = is_dead | c
        return jax.lax.cond(is_dead, dead, live, None)

    def step(carry, i):
        k_c, v_c, mask_c, m, l, acc = carry
        m, l, acc = attend(k_c, v_c, mask_c, i, m, l, acc)
        k_nxt = jax.lax.ppermute(k_c, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_c, axis_name, perm)
        mask_nxt = jax.lax.ppermute(mask_c, axis_name, perm)
        return (k_nxt, v_nxt, mask_nxt, m, l, acc), None

    # The last visiting chunk is attended OUTSIDE the scan so its rotation
    # (whose result would be discarded) is never issued.
    carry = (k, v, kv_mask, m0, l0, acc0)
    if n_ring > 1:
        carry, _ = jax.lax.scan(step, carry, jnp.arange(n_ring - 1))
    k_c, v_c, mask_c, m, l, acc = carry
    _, l, acc = attend(k_c, v_c, mask_c, jnp.asarray(n_ring - 1), m, l, acc)
    out = acc / l  # fully-masked pad rows degrade to a uniform mix, like the
    # einsum/flash paths; every loss masks them.
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _ring_flash(q, k, v, kv_mask, *, axis_name: str, n_ring: int, scale: float,
                causal: bool, window: int):
    """Ring pass whose per-chunk attention is the pallas flash kernel.

    Each visiting chunk contributes (o_c, lse_c); outputs combine exactly via
    log-sum-exp weights. Chunks entirely in the future (src > idx under
    causality) cost nothing: every k block fails the kernel's offset-aware
    liveness test. Gradients flow through the combine into dlse, which the
    kernel backward folds into its delta term."""
    from trlx_tpu.ops.flash_attention import flash_attention, pick_block

    b, t, h, d = q.shape
    blk = pick_block(t)
    idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n_ring) for j in range(n_ring)]

    o0 = jnp.zeros((b, t, h, d), jnp.float32)
    lse0 = jnp.full((b, h, t), M_INIT, jnp.float32)

    def attend(k_c, v_c, mask_c, i, o, lse):
        src = (idx - i) % n_ring
        offset = ((src - idx) * t).astype(jnp.float32)
        o_c, lse_c = flash_attention(
            q, k_c, v_c, mask_c, scale=scale, causal=causal, window=window,
            offset=offset, return_lse=True, block_q=blk, block_k=blk,
        )
        lse_new = jnp.logaddexp(lse, lse_c)
        w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
        w_new = jnp.exp(lse_c - lse_new).transpose(0, 2, 1)[..., None]
        return o * w_old + o_c.astype(jnp.float32) * w_new, lse_new

    def step(carry, i):
        k_c, v_c, mask_c, o, lse = carry
        o, lse = attend(k_c, v_c, mask_c, i, o, lse)
        k_nxt = jax.lax.ppermute(k_c, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_c, axis_name, perm)
        mask_nxt = jax.lax.ppermute(mask_c, axis_name, perm)
        return (k_nxt, v_nxt, mask_nxt, o, lse), None

    carry = (k, v, kv_mask, o0, lse0)
    if n_ring > 1:
        carry, _ = jax.lax.scan(step, carry, jnp.arange(n_ring - 1))
    k_c, v_c, mask_c, o, lse = carry
    o, _ = attend(k_c, v_c, mask_c, jnp.asarray(n_ring - 1), o, lse)
    return o.astype(q.dtype)


def _zigzag_indices(T: int, n_ring: int):
    """Permutation putting the sequence in zig-zag order: rank r's contiguous
    shard of the permuted array holds half-chunks {r, 2n−1−r} of the
    original. Returns (perm, inverse) as static numpy index vectors."""
    import numpy as np

    c = T // (2 * n_ring)
    order = []
    for r in range(n_ring):
        order.append(np.arange(r * c, (r + 1) * c))
        order.append(np.arange((2 * n_ring - 1 - r) * c, (2 * n_ring - r) * c))
    zz = np.concatenate(order)
    return zz, np.argsort(zz)


def causal_live_half_pairs(n_ring: int, layout: str):
    """Per-rank count of LIVE half-chunk attends in one full causal ring pass
    — the load-balance model the layouts are judged by (and the exact
    liveness rule ring_attention_zigzag's lax.cond gates on). Contiguous
    counts whole chunks in half-chunk units (2 halves per live visit)."""
    counts = []
    for r in range(n_ring):
        if layout == "zigzag":
            cqs = (r, 2 * n_ring - 1 - r)
            n = 0
            for src in range(n_ring):
                for ck in (src, 2 * n_ring - 1 - src):
                    n += sum(1 for cq in cqs if ck <= cq)
            counts.append(n)
        else:
            counts.append(2 * (r + 1) * 2)  # (r+1) live visits × 4 half-pairs
    return counts


def ring_attention_zigzag(q, k, v, kv_mask, *, axis_name: str, n_ring: int,
                          scale: float, window: int = 0, use_flash=None):
    """Causal ring body for the ZIG-ZAG layout: this rank's local sequence is
    [half-chunk idx ; half-chunk 2n−1−idx], each of length c = t/2 (global
    positions follow). Every (q-half, k-half) pair attends independently and
    combines exactly via log-sum-exp, with pairs failing the causal/window
    liveness test skipped by lax.cond. Causal live work is 2n+1 half-pairs on
    EVERY rank — the layout exists to equalize what the contiguous layout
    skews as r+1 live chunks on rank r."""
    b, t, h, d = q.shape
    assert t % 2 == 0, "zig-zag layout needs an even local chunk"
    c = t // 2
    idx = jax.lax.axis_index(axis_name)
    flash_engine = _flash_in_ring_ok(c, use_flash)
    if flash_engine:
        from trlx_tpu.ops.flash_attention import flash_attention, pick_block

        blk = pick_block(c)

    cqs = (idx, 2 * n_ring - 1 - idx)  # chunk ids of the local q halves
    q_halves = (q[:, :c], q[:, c:])
    perm = [(j, (j + 1) % n_ring) for j in range(n_ring)]

    o0 = jnp.zeros((b, h, c, d), jnp.float32)
    lse0 = jnp.full((b, h, c), M_INIT, jnp.float32)

    def half_pair(q_half, cq, k_half, v_half, mask_half, ck, o, lse):
        """One (q-half, k-half) attend + lse-combine, liveness-gated."""

        def live(args):
            o, lse = args
            if flash_engine:
                o_c, lse_c = flash_attention(
                    q_half, k_half, v_half, mask_half, scale=scale, causal=True,
                    window=window, offset=((ck - cq) * c).astype(jnp.float32),
                    return_lse=True, block_q=blk, block_k=blk,
                )
                o_c = o_c.astype(jnp.float32).transpose(0, 2, 1, 3)  # → [b,h,c,d]
            else:
                q_pos = cq * c + jnp.arange(c)
                k_pos = ck * c + jnp.arange(c)
                s = jnp.einsum(
                    "bqhd,bkhd->bhqk",
                    q_half.astype(jnp.float32),
                    k_half.astype(jnp.float32),
                ) * scale
                pair = (mask_half[:, None, None, :] > 0) & (
                    k_pos[None, None, None, :] <= q_pos[None, None, :, None]
                )
                if window > 0:
                    pair = pair & (
                        k_pos[None, None, None, :] > q_pos[None, None, :, None] - window
                    )
                s = jnp.where(pair, s, MASK_VAL)
                m_c = jnp.max(s, axis=-1, keepdims=True)
                p = jnp.exp(s - m_c)
                l_c = jnp.sum(p, axis=-1, keepdims=True)
                o_c = jnp.einsum("bhqk,bkhd->bhqd", p, v_half.astype(jnp.float32)) / l_c
                lse_c = (m_c + jnp.log(l_c))[..., 0]
            lse_new = jnp.logaddexp(lse, lse_c)
            w_old = jnp.exp(lse - lse_new)[..., None]
            w_new = jnp.exp(lse_c - lse_new)[..., None]
            return o * w_old + o_c * w_new, lse_new

        is_dead = ck > cq  # wholly future under causality
        if window > 0:
            is_dead = is_dead | (ck * c + c - 1 <= cq * c - window)
        return jax.lax.cond(is_dead, lambda args: args, live, (o, lse))

    def attend(k_c, v_c, mask_c, i, carrys):
        src = (idx - i) % n_ring
        cks = (src, 2 * n_ring - 1 - src)
        k_halves = (k_c[:, :c], k_c[:, c:])
        v_halves = (v_c[:, :c], v_c[:, c:])
        m_halves = (mask_c[:, :c], mask_c[:, c:])
        out = []
        for qi in range(2):
            o, lse = carrys[qi]
            for kj in range(2):
                o, lse = half_pair(
                    q_halves[qi], cqs[qi], k_halves[kj], v_halves[kj],
                    m_halves[kj], cks[kj], o, lse,
                )
            out.append((o, lse))
        return out

    def step(carry, i):
        k_c, v_c, mask_c, oa, la, ob, lb = carry
        (oa, la), (ob, lb) = attend(k_c, v_c, mask_c, i, [(oa, la), (ob, lb)])
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        mask_c = jax.lax.ppermute(mask_c, axis_name, perm)
        return (k_c, v_c, mask_c, oa, la, ob, lb), None

    carry = (k, v, kv_mask, o0, lse0, o0, lse0)
    if n_ring > 1:
        carry, _ = jax.lax.scan(step, carry, jnp.arange(n_ring - 1))
    k_c, v_c, mask_c, oa, la, ob, lb = carry
    (oa, _), (ob, _) = attend(k_c, v_c, mask_c, jnp.asarray(n_ring - 1), [(oa, la), (ob, lb)])
    out = jnp.concatenate([oa, ob], axis=2)  # [b, h, t, d]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention_sharded(q, k, v, kv_mask, *, scale: float, causal: bool = True,
                           window: int = 0, mesh=None, use_flash=None,
                           layout: str = "auto"):
    """jit-composable entry: shard_map over the full (dp, fsdp, tp, sp) mesh.

    q/k/v: GLOBAL [b, T, h, d] logical arrays (XLA reshards at the shard_map
    boundary): batch over (dp, fsdp), sequence over sp, heads over tp.

    `layout`: "auto" picks zig-zag (balanced causal work — each rank holds
    half-chunks {r, 2n−1−r}) whenever causal and T divides 2·n_ring, else the
    contiguous layout; "zigzag"/"contiguous" force. The zig-zag permutation is
    applied and inverted HERE, so callers always see natural sequence order.

    Cost note: the permutation round-trip is 5 cross-shard gathers of O(T·h·d)
    per attention call. Attention compute is O(T²·h·d/n) per rank, so the
    movement is a ~n/T fraction of the work — noise at the long sequences sp
    targets (T ≥ 8k), but measurable at short T; pass layout="contiguous" to
    opt out there (short sequences are also where the causal imbalance being
    fixed costs the least).
    """
    from jax.sharding import PartitionSpec as P

    mesh = mesh if mesh is not None else get_mesh()
    n_ring = mesh.shape[AXIS_SP]
    qkv_spec = P(DATA_AXES, AXIS_SP, AXIS_TP, None)
    mask_spec = P(DATA_AXES, AXIS_SP)

    T = q.shape[1]
    if layout == "auto":
        zig = causal and n_ring > 1 and T % (2 * n_ring) == 0
    else:
        zig = layout == "zigzag"
    if zig:
        if not causal:
            raise ValueError("zig-zag layout is a causal-balance construct; use contiguous for non-causal")
        if T % (2 * n_ring):
            raise ValueError(f"zig-zag needs T divisible by 2*n_ring, got T={T}, n_ring={n_ring}")
        zz, inv = _zigzag_indices(T, n_ring)
        body = partial(
            ring_attention_zigzag, axis_name=AXIS_SP, n_ring=n_ring, scale=scale,
            window=window, use_flash=use_flash,
        )
        out = shard_map(
            lambda q, k, v, m: body(q, k, v, m),
            mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
            out_specs=qkv_spec,
        )(
            jnp.take(q, zz, axis=1),
            jnp.take(k, zz, axis=1),
            jnp.take(v, zz, axis=1),
            jnp.take(kv_mask, zz, axis=1),
        )
        return jnp.take(out, inv, axis=1)

    body = partial(
        ring_attention, axis_name=AXIS_SP, n_ring=n_ring, scale=scale,
        causal=causal, window=window, use_flash=use_flash,
    )
    return shard_map(
        lambda q, k, v, m: body(q, k, v, m),
        mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
    )(q, k, v, kv_mask)
