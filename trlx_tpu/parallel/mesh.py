"""Device mesh construction and multi-host bootstrap.

The reference's distributed backend is NCCL hidden behind Accelerate
(reference: trlx/model/accelerate_base_model.py:31-36 — Accelerator() process
group init + torch.distributed.barrier). The TPU-native design replaces all of
it with one object: a `jax.sharding.Mesh` over four named axes

    dp    — pure data parallel (params replicated, batch sharded)
    fsdp  — data parallel with param/optimizer sharding (≡ ZeRO-3; the
            equivalent of the reference's DeepSpeed zero_stage 2/3,
            reference: configs/deepspeed_configs/default_configs.yml:2-9)
    tp    — tensor (Megatron-style) parallel over hidden/vocab dims
    sp    — sequence/context parallel (ring attention over the seq dim)

Collectives (psum/all_gather/reduce_scatter/ppermute) are emitted by XLA from
sharding annotations — there is no hand-written NCCL analogue. Axis ORDER
matters for ICI locality: the innermost (fastest-varying) mesh dims should map
to physically adjacent chips, so tp (latency-bound, every-layer collectives)
is placed innermost.
"""

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from trlx_tpu.utils import sanitize

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_TP = "tp"
AXIS_SP = "sp"
MESH_AXES = (AXIS_DP, AXIS_FSDP, AXIS_TP, AXIS_SP)
# Axes over which the *batch* dimension is sharded (fsdp is a flavor of data
# parallelism: same batch sharding, plus param sharding).
DATA_AXES = (AXIS_DP, AXIS_FSDP)

_GLOBAL_MESH: Optional[Mesh] = None


def init_distributed(coordinator_address: Optional[str] = None, num_processes: Optional[int] = None, process_id: Optional[int] = None):
    """Multi-host bootstrap over DCN.

    The analogue of Accelerate's process-group init + barrier
    (reference: trlx/model/accelerate_base_model.py:31-36). On a TPU pod,
    call with no args — jax auto-detects the coordinator from TPU metadata.
    On single-host CPU/dev environments with no multi-host signal this is a
    no-op. Safe to call twice (already-initialized is tolerated); genuine
    config errors propagate.
    """
    multi_host_signal = (
        coordinator_address is not None
        or num_processes is not None
        or "JAX_COORDINATOR_ADDRESS" in os.environ
        or os.environ.get("TPU_WORKER_HOSTNAMES", "localhost") not in ("", "localhost")
    )
    if not multi_host_signal:
        return  # single host dev environment
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        if "already" not in str(e).lower():
            raise


def resolve_mesh_shape(shape: Sequence[int], n_devices: Optional[int] = None) -> Tuple[int, ...]:
    """Resolve a mesh shape with at most one -1 ("fill remaining devices").

    e.g. (-1, 1, 1, 1) on 8 devices → (8, 1, 1, 1).
    """
    n_devices = n_devices if n_devices is not None else jax.device_count()
    shape = tuple(int(s) for s in shape)
    if shape.count(-1) > 1:
        raise ValueError(f"mesh shape can have at most one -1, got {shape}")
    fixed = int(np.prod([s for s in shape if s != -1]))
    if -1 in shape:
        if n_devices % fixed != 0:
            raise ValueError(f"{n_devices} devices not divisible by fixed mesh product {fixed}")
        shape = tuple(n_devices // fixed if s == -1 else s for s in shape)
    if int(np.prod(shape)) != n_devices:
        raise ValueError(f"mesh {shape} needs {int(np.prod(shape))} devices, have {n_devices}")
    return shape


def make_mesh(shape: Sequence[int] = (-1, 1, 1, 1), devices=None) -> Mesh:
    """Build the 4-axis (dp, fsdp, tp, sp) device mesh.

    ``devices`` defaults to all addressable+remote devices in row-major order;
    `mesh_utils.create_device_mesh` is used when possible so the tp axis rides
    ICI-adjacent chips.
    """
    if devices is None:
        devices = jax.devices()
    shape = resolve_mesh_shape(shape, len(devices))
    try:
        from jax.experimental import mesh_utils

        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, MESH_AXES)


def set_mesh(mesh: Mesh):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def peek_mesh() -> Optional[Mesh]:
    """The process-global mesh if one was created, else None (no side
    effects — unlike get_mesh, which creates a default mesh)."""
    return _GLOBAL_MESH


def get_mesh(shape: Sequence[int] = (-1, 1, 1, 1)) -> Mesh:
    """Return the process-global mesh, creating it on first use."""
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        _GLOBAL_MESH = make_mesh(shape)
    return _GLOBAL_MESH


def to_local_host(tree, mesh: Optional[Mesh] = None, batch_axes=DATA_AXES):
    """Global (possibly multi-host sharded) device arrays → THIS process's
    batch rows as host numpy.

    The device→host inverse of the put_batch direction
    (host_local_array_to_global_array): each process gets back exactly the
    rows it fed in, so rollout decode/score/store stay process-local and the
    whole path is process-count-agnostic. A plain np.asarray on a multi-host
    global array would throw on non-addressable shards. Single-process (and
    for host numpy passed through): a plain np.asarray.
    """
    # Sanitizer checkpoint: pulling a donated buffer to host is the classic
    # use-after-donate read — fail here with the donation site, not with
    # jax's anonymous "Array has been deleted" downstream.
    sanitize.check_host_read(tree, "to_local_host")

    def pull(x):
        if jax.process_count() == 1 or not isinstance(x, jax.Array):
            return np.asarray(x)
        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec

        spec = PartitionSpec(batch_axes, *([None] * (x.ndim - 1)))
        m = mesh if mesh is not None else get_mesh()
        return np.asarray(
            multihost_utils.global_array_to_host_local_array(x, m, spec)  # graftlint: disable=GL004 -- pull() only runs inside the collective_guard("to_local_host") tree_map below
        )

    if jax.process_count() > 1:
        # Reading a global array blocks until every host's shards exist — a
        # dead peer would hang this forever; the guard converts that into a
        # deadline'd CollectiveTimeout abort (resilience/distributed.py).
        from trlx_tpu.resilience.distributed import collective_guard

        with collective_guard("to_local_host"):
            return jax.tree_util.tree_map(pull, tree)
    return jax.tree_util.tree_map(pull, tree)


def allgather_host(tree):
    """Each process's host-local numpy rows → the full global rows on every
    process, concatenated along axis 0 in process order.

    The counterpart of the reference's eval-time accelerator.gather
    (reference: trlx/model/accelerate_base_model.py:149-158). Single-process:
    identity (np.asarray).
    """
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(np.asarray, tree)
    from jax.experimental import multihost_utils

    from trlx_tpu.resilience.distributed import collective_guard

    # Guarded: an allgather with a dead/wedged peer never completes — abort
    # with CollectiveTimeout after train.collective_deadline instead.
    with collective_guard("allgather_host"):
        return jax.tree_util.tree_map(
            lambda x: np.asarray(multihost_utils.process_allgather(np.asarray(x), tiled=True)),
            tree,
        )


def barrier(name: str = "trlx_tpu_barrier"):
    """Cross-host barrier ≈ the reference's torch.distributed.barrier
    (reference: trlx/model/accelerate_base_model.py:33-34). A tiny psum forces
    all hosts/devices to synchronize. Guarded by the collective deadline —
    a barrier whose peer died aborts with CollectiveTimeout, not a hang."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        from trlx_tpu.resilience.distributed import collective_guard

        with collective_guard(f"barrier:{name}"):
            multihost_utils.sync_global_devices(name)


def broadcast_host(value):
    """Rank-0's host value → every process (the guarded counterpart of a bare
    ``multihost_utils.broadcast_one_to_all``). Used for process-agreed
    decisions (e.g. "does a checkpoint exist?") that every host must answer
    identically before entering a collective code path. Single-process:
    identity."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    from trlx_tpu.resilience.distributed import collective_guard

    # Guarded: a broadcast with a dead coordinator never completes — abort
    # with CollectiveTimeout after train.collective_deadline instead.
    with collective_guard("broadcast_host"):
        return multihost_utils.broadcast_one_to_all(value)


def is_main_process() -> bool:
    """Rank-0 check for logging/checkpoint side effects
    (≈ accelerator.is_main_process, reference: trlx/model/accelerate_base_model.py:66)."""
    return jax.process_index() == 0
