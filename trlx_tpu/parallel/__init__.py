"""Distributed runtime: device mesh, sharding specs, collectives.

This package is the first-class replacement for the runtime layer the
reference delegates entirely to Accelerate/DeepSpeed/torch.distributed
(reference: trlx/model/accelerate_base_model.py:31-36,
configs/deepspeed_configs/default_configs.yml). Here it is explicit and ours:

- :mod:`trlx_tpu.parallel.mesh` — mesh construction over dp/fsdp/tp/sp axes,
  multi-host bootstrap (`jax.distributed.initialize`), barriers.
- :mod:`trlx_tpu.parallel.sharding` — partition rules for params, optimizer
  states (ZeRO ≡ fsdp axis sharding), activations, and rollout batches.
"""

from trlx_tpu.parallel.mesh import (  # noqa: F401
    AXIS_DP,
    AXIS_FSDP,
    AXIS_SP,
    AXIS_TP,
    DATA_AXES,
    barrier,
    get_mesh,
    make_mesh,
    set_mesh,
)
from trlx_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    lm_partition_rules,
    match_partition_rules,
    shard_pytree,
)
