"""Fleet roles: the persistent rollout worker and the learner's episode feed.

Two independent single-controller worlds (topology.py), three transports
(episode stream, weight broadcast, heartbeats), one coupling knob
(``method.max_staleness``). The schedule both sides enforce:

- the worker may produce stream batch ``seq`` only when the learner's
  cursor allows it (``staleness_gate_open(seq, consumed, S)`` — the SAME
  predicate the in-process RolloutProducer gates on), and only from a
  weight snapshot with publish ordinal >= ``seq - S``;
- the learner publishes the post-train weights BEFORE advancing its
  cursor, and the worker reads cursor-then-latest — so a just-opened gate
  always sees the version that opened it.

At S=0 this degenerates to the exact serial synchronous schedule (produce
n from weights n, train on n, publish n+1, ...) — which is why the
staleness-0 disaggregated run is bitwise-identical to the serial path
(tests/test_fleet_disagg.py re-proves the PR 5 contract through the
stream). At S>0 the worker runs ahead, LlamaRL-style, and every consumed
batch's realized staleness (publish ordinals elapsed since its version)
is written into the store's staleness column for the PR 9 lineage logs.

Degradation ladder (the robustness core): a learner whose episode wait
exhausts its timeout/retry/backoff budget triages the rollout role by
heartbeat — DEAD (file age), STALLED (file fresh, progress frozen), or
merely slow (keep waiting). Dead/stalled flips the feed to ``degraded``:
the /healthz fleet block and the ``fleet/degraded`` gauge flip at ENTRY
(so a scraper sees the state for the whole drain, not a final instant),
queued in-flight batches are drained at their elevated staleness, and
when the stream runs dry — or a batch exceeds the staleness cap — the
feed raises ``FleetDegradedExit``: the trainer checkpoints (the rollback
point), writes ``abort.json`` (coordinated shutdown: a stalled-but-alive
worker reads it and exits 0), and winds down cleanly instead of hanging.
"""

import os
import time
from typing import Optional

import numpy as np

from trlx_tpu.observability import numerics as obs_numerics
from trlx_tpu.pipeline.overlap import staleness_gate_open
from trlx_tpu.pipeline.ppo_pipeline import PPORolloutStorage
from trlx_tpu.resilience.checkpoint import atomic_write_json
from trlx_tpu.resilience.distributed import Heartbeat, read_heartbeats
from trlx_tpu.utils.jsonl import append_record

from .broadcast import WeightPublisher, WeightSubscriber, put_leaves
from .leases import LeaseLedger, WorkerRegistry
from .stream import (
    ElasticStreamReader,
    EpisodeStreamReader,
    EpisodeStreamTimeout,
    EpisodeStreamWriter,
)
from .topology import (
    LEARNER_HOST,
    ROLE_COLOCATED,
    ROLE_ROLLOUT,
    ROLLOUT_HOST,
    WORKER_ENV,
    FleetPaths,
    fleet_paths,
    read_jsonl_or_empty,
    role_timeouts,
)


class FleetDegradedExit(RuntimeError):
    """Coordinated fleet shutdown: the learner has drained what it can and
    must stop consuming. Carries the triage verdict for the event log."""

    def __init__(self, reason: str, triage: str = "", detail: str = ""):
        super().__init__(f"fleet degraded exit: {reason}" + (f" ({detail})" if detail else ""))
        self.reason = reason
        self.triage = triage
        self.detail = detail


def fleet_snapshot(trainer, host_leaves, version: int) -> dict:
    """Rebuild a rollout snapshot (the ``_rollout_snapshot`` contract) from
    broadcast byte-leaves: params re-viewed + device_put onto THIS world's
    shardings, the frozen ref branch deep-copied from the local state (it
    never trains, and both worlds initialize it identically from the same
    seed), and the int8 decode weights re-quantized locally when W8A16
    decode is armed. Bitwise: npz bytes → device is a pure transfer."""
    import jax
    import jax.numpy as jnp

    with trainer._dispatch_lock:
        params = put_leaves(trainer.state.params, host_leaves)
        snap = {
            "params": params,
            # Deep copy, not a reference: at S>0 the snapshot outlives train
            # steps that donate the live TrainState (same hazard
            # _rollout_snapshot documents).
            "extras": (
                None
                if trainer.state.extras is None
                else jax.tree_util.tree_map(jnp.copy, trainer.state.extras)
            ),
            "version": int(version),
        }
        if trainer._qw is not None:
            snap["qw"] = trainer._quantize_fn(snap["params"])
        if obs_numerics.enabled():
            # PR 15 tie-in: per-version quant telemetry at the handoff.
            obs_numerics.record_weight_handoff(snap, version=snap["version"])
    return snap


def _read_cursor(paths: FleetPaths) -> int:
    """The learner's consume cursor (count of consumed seqs).

    MISSING file = fresh fleet: 0 — the worker just waits at the gate
    until it lands. A PRESENT-but-unparseable file (torn write from a
    kill mid-write, torn read on a flaky shared filesystem) must NOT read
    as 0: a restarted learner would silently re-consume — and re-train
    on — every streamed batch. Fall back to the last indexed stream seq
    + 1 instead: at-most-once (skip forward over batches whose consume
    we cannot prove) rather than at-least-once (silent duplicates). The
    cursor itself is written atomically (tmp + os.replace), so the
    fallback only triggers on filesystem-level tears."""
    import json

    try:
        with open(paths.cursor, "r") as f:
            raw = f.read()
    except OSError:
        return 0
    try:
        return int(json.loads(raw)["consumed"])
    except (ValueError, KeyError, TypeError):
        # Scan EVERY stream index (an elastic fleet interleaves N of them;
        # the single-worker fleet has exactly one) and skip past the highest
        # landed unit/seq — the at-most-once verdict must cover batches a
        # peer streamed while this cursor was being torn.
        best = -1
        for index_path in paths.stream_indexes().values() or [paths.stream_index]:
            for r in read_jsonl_or_empty(index_path):
                best = max(best, int(r.get("unit", r["seq"])))
        return 1 + best


def _event(paths: FleetPaths, role: str, event: str, **fields):
    rec = {"t": time.time(), "role": role, "event": event}
    rec.update(fields)
    append_record(paths.events, rec)


# --------------------------------------------------------- rollout worker


def run_rollout_worker(trainer, orch, num_rollouts: Optional[int] = None):
    """The persistent rollout job: wait at the staleness gate, hold the
    newest eligible weights, generate one experience phase, stream it.

    Runs INSTEAD of ``learn()`` when this process's fleet role is
    ``rollout`` (trainer/api.py). Exits 0 on ``abort.json`` (coordinated
    shutdown), 117 via the collective guard if the broadcast starves past
    ``fleet_broadcast_deadline``, and abruptly (``os._exit(1)``) on the
    ``rollout_host_kill`` fault.

    With ``method.fleet_elastic`` the worker instead joins the N-worker
    lease-claiming loop (``_run_elastic_worker``)."""
    if getattr(trainer.config.method, "fleet_elastic", False):
        return _run_elastic_worker(trainer, orch, num_rollouts)
    t = trainer.config.train
    knobs = role_timeouts(t)
    paths = fleet_paths(t).ensure()
    S = trainer.max_staleness
    n_roll = int(num_rollouts or trainer.config.method.num_rollouts)
    heartbeat = Heartbeat(
        paths.heartbeats_dir, knobs["heartbeat_interval"], process_index=ROLLOUT_HOST
    )
    heartbeat.start()
    writer = EpisodeStreamWriter(paths, fault_plan=trainer.fault_plan)
    subscriber = WeightSubscriber(paths)
    _event(paths, ROLE_ROLLOUT, "worker_start", next_seq=writer.next_seq)

    def aborted() -> bool:
        return paths.read_abort() is not None

    current_ordinal = -1
    snapshot = None
    # In-flight weight updates (method.fleet_inflight_weights + the engine):
    # poll the latest pointer BETWEEN engine syncs and push a fresher
    # version into the running engine — PipelineRL-style, instead of only
    # at phase boundaries. Default off: the phase-boundary path stays
    # byte-identical to PR 16.
    inflight = bool(
        getattr(trainer.config.method, "fleet_inflight_weights", False)
    ) and bool(getattr(trainer, "rollout_engine_enabled", False))
    poll_state = {"tick": 0, "storm": 0}

    def weight_poll():
        """Once per engine sync: adopt a fresher broadcast ordinal into the
        RUNNING phase. Returns (decode variables, version) to push, or None.
        Torn snapshots (``weight_push_torn``) are rejected — keep decoding
        on the version already held; the ``version_switch_storm`` fault
        re-pushes the held latest every sync for a window, which the
        engine must coalesce (never queue)."""
        nonlocal current_ordinal, snapshot
        poll_state["tick"] += 1
        if trainer.fault_plan.fire("version_switch_storm", poll_state["tick"]):
            poll_state["storm"] = int(
                os.environ.get("TRLX_TPU_SWITCH_STORM_PUSHES", "8")
            )
        latest = subscriber.latest()
        if latest is None:
            return None
        fresh = int(latest["ordinal"]) > current_ordinal
        storm = poll_state["storm"] > 0
        if storm:
            poll_state["storm"] -= 1
        if not fresh and not storm:
            return None
        if fresh:
            leaves = subscriber.try_load(latest)
            if leaves is None:
                # Torn push: pointer flipped but the snapshot file is
                # truncated. Reject — the engine keeps the old version —
                # and retry at the next sync (the next intact ordinal wins).
                _event(
                    paths, ROLE_ROLLOUT, "weights_torn",
                    ordinal=int(latest["ordinal"]), held=current_ordinal,
                )
                return None
            snapshot = fleet_snapshot(trainer, leaves, latest["version"])
            current_ordinal = int(latest["ordinal"])
            if "kl_coef" in latest and getattr(trainer, "kl_ctl", None) is not None:
                trainer.kl_ctl.value = float(latest["kl_coef"])
            _event(
                paths, ROLE_ROLLOUT, "weights_adopted_inflight",
                ordinal=current_ordinal, version=snapshot["version"],
            )
        if snapshot is None:
            return None
        return trainer.rollout_engine_variables(snapshot), snapshot["version"]

    try:
        while not aborted():
            seq = writer.next_seq
            heartbeat.beat(step=seq, phase="fleet:gate")
            if not staleness_gate_open(seq, _read_cursor(paths), S):
                time.sleep(0.05)
                continue
            # Gate open: cursor read BEFORE the latest pointer, so the
            # version whose publish opened the gate is already visible.
            need = max(0, seq - S)
            latest = subscriber.latest()
            if latest is None or int(latest["ordinal"]) < need:
                heartbeat.beat(step=seq, phase="fleet:wait_weights")
                got = subscriber.fetch(
                    need,
                    deadline=knobs["broadcast_deadline"],
                    abort_check=aborted,
                    heartbeat=heartbeat,
                )
                if got is None:
                    break  # coordinated shutdown while waiting
                latest, leaves = got
            elif int(latest["ordinal"]) != current_ordinal:
                leaves = subscriber.try_load(latest)
                if leaves is None:
                    # Torn latest pointer at the phase boundary
                    # (weight_push_torn): reject it exactly like the
                    # in-flight poller — keep the held version when it still
                    # satisfies the gate, otherwise spin at the gate until
                    # the next intact ordinal lands (one event per torn
                    # ordinal, not per spin).
                    if poll_state.get("torn_seen") != int(latest["ordinal"]):
                        poll_state["torn_seen"] = int(latest["ordinal"])
                        _event(
                            paths, ROLE_ROLLOUT, "weights_torn",
                            ordinal=int(latest["ordinal"]), held=current_ordinal,
                        )
                    if current_ordinal < need or snapshot is None:
                        time.sleep(0.05)
                        continue
            else:
                leaves = None
            if leaves is not None:
                snapshot = fleet_snapshot(trainer, leaves, latest["version"])
                current_ordinal = int(latest["ordinal"])
                if "kl_coef" in latest and getattr(trainer, "kl_ctl", None) is not None:
                    # Track the learner's adaptive KL coefficient in
                    # lockstep with the params (it shapes rollout rewards).
                    trainer.kl_ctl.value = float(latest["kl_coef"])
                _event(
                    paths, ROLE_ROLLOUT, "weights_fetched",
                    ordinal=current_ordinal, version=snapshot["version"], seq=seq,
                )

            store = PPORolloutStorage(trainer.pad_token_id, record_staleness=True)

            def produce_stop():
                heartbeat.beat(step=seq, phase="fleet:produce")
                return aborted()

            info = orch.make_experience(
                n_roll,
                iter_count=snapshot["version"],
                store=store,
                snapshot=snapshot,
                staleness=0,  # realized staleness is stamped at consume time
                stop=produce_stop,
                weight_poll=weight_poll if inflight else None,
            )
            if aborted():
                break  # phase was cut short; drop the partial store
            heartbeat.beat(step=seq, phase="fleet:stream")
            writer.append(
                store.columns(),
                # In-flight adoption may have advanced the snapshot
                # mid-phase: the tag is the LAST version that decoded, and
                # the span aggregate carries the full per-token mix.
                weight_version=snapshot["version"],
                # Gated on the knob, not just the engine: with inflight off
                # the index record stays byte-identical to PR 16's.
                version_spans=(
                    (info or {}).get("version_spans")
                    if inflight and isinstance(info, dict)
                    else None
                ),
            )
            _event(
                paths, ROLE_ROLLOUT, "episode_streamed",
                seq=seq, version=snapshot["version"], n=len(store),
            )
            if trainer.fault_plan.fire("rollout_host_kill", seq):
                os._exit(1)  # abrupt: no cleanup, no final heartbeat
        _event(paths, ROLE_ROLLOUT, "worker_exit", reason="abort", next_seq=writer.next_seq)
    finally:
        heartbeat.stop()
        if getattr(trainer, "heartbeat", None) is not None:
            # The worker path never runs learn(), so the base trainer's own
            # heartbeat thread must be joined here instead.
            trainer.heartbeat.stop()


# --------------------------------------------------------- elastic worker


def _run_elastic_worker(trainer, orch, num_rollouts: Optional[int] = None):
    """One of N elastic rollout workers: register, then loop claim-a-unit →
    hold-eligible-weights → seek-the-unit's-prompt-shard → produce →
    stream → complete, with the lease renewed off the produce heartbeat.

    Membership dynamics handled here: ``worker_join_mid_run@N`` defers
    registration until the learner's cursor reaches N (the joiner then
    adopts the LATEST broadcast, never a historical one);
    ``TRLX_TPU_FLEET_LEAVE_AFTER=k`` makes the worker deregister cleanly
    after producing k units (releasing any held lease for instant
    reclaim); ``worker_kill_mid_lease`` / ``slow_worker_reclaim`` die or
    oversleep while HOLDING a lease, which is exactly what the peers'
    reclaim path and the learner's dedup intake must absorb."""
    t = trainer.config.train
    knobs = role_timeouts(t)
    paths = fleet_paths(t).ensure_elastic()
    S = trainer.max_staleness
    n_roll = int(num_rollouts or trainer.config.method.num_rollouts)
    plan = trainer.fault_plan
    cpu = orch.chunks_per_unit(n_roll)

    def aborted() -> bool:
        return paths.read_abort() is not None

    # Dynamic join: hold registration (and the heartbeat — an unregistered
    # worker must not look like a dead one) until the run reaches the
    # configured cursor.
    join_at = plan.pending_at("worker_join_mid_run")
    if join_at is not None:
        while _read_cursor(paths) < join_at and not aborted():
            time.sleep(0.05)
        plan.fire_at_or_after("worker_join_mid_run", join_at)
        if aborted():
            return

    registry = WorkerRegistry(paths.workers_dir)
    ledger = LeaseLedger(paths.leases_dir, ttl=knobs["lease_ttl"])
    env_worker = os.environ.get(WORKER_ENV, "")
    wid = registry.register(int(env_worker) if env_worker else None)
    heartbeat = Heartbeat(
        paths.heartbeats_dir, knobs["heartbeat_interval"],
        process_index=ROLLOUT_HOST + wid,
    )
    heartbeat.start()
    writer = EpisodeStreamWriter(paths, fault_plan=plan, worker=wid)
    subscriber = WeightSubscriber(paths)
    _event(
        paths, ROLE_ROLLOUT, "worker_registered",
        worker=wid, cursor=_read_cursor(paths),
        **({"joined_at": join_at} if join_at is not None else {}),
    )
    leave_after = int(os.environ.get("TRLX_TPU_FLEET_LEAVE_AFTER", "0") or 0)
    produced = 0
    current_ordinal = -1
    snapshot = None
    lease = None
    reason = "abort"
    try:
        # Bootstrap: hold SOME broadcast before claiming anything. A lease
        # claimed across the learner's first publish (compile + first step,
        # easily many TTLs) would expire un-renewed and spawn spurious
        # bootstrap reclaims among the very workers that are all just
        # waiting. A mid-run joiner gets the LATEST ordinal here — never a
        # historical one (broadcast.py serves the freshest >= need).
        heartbeat.beat(step=0, phase="fleet:wait_weights")
        boot = subscriber.fetch(
            0,
            deadline=knobs["broadcast_deadline"],
            abort_check=aborted,
            heartbeat=heartbeat,
        )
        if boot is not None:
            latest, leaves = boot
            snapshot = fleet_snapshot(trainer, leaves, latest["version"])
            current_ordinal = int(latest["ordinal"])
            if "kl_coef" in latest and getattr(trainer, "kl_ctl", None) is not None:
                trainer.kl_ctl.value = float(latest["kl_coef"])
            _event(
                paths, ROLE_ROLLOUT, "weights_fetched",
                ordinal=current_ordinal, version=snapshot["version"], worker=wid,
            )
        while boot is not None and not aborted():
            if leave_after and produced >= leave_after:
                reason = "left"
                break
            consumed = _read_cursor(paths)
            heartbeat.beat(step=consumed, phase="fleet:claim")
            # Lowest claimable gate-open unit: [cursor, cursor+S] are the
            # only units the staleness gate admits; done/fresh-held units
            # are skipped inside try_claim.
            lease = None
            for unit in range(consumed, consumed + S + 1):
                got = ledger.try_claim(unit, wid)
                if got is not None:
                    lease = got
                    break
            if lease is None:
                time.sleep(0.05)
                continue
            unit = lease.unit
            _event(
                paths, ROLE_ROLLOUT,
                "lease_reclaimed" if lease.gen > 0 else "lease_claimed",
                unit=unit, worker=wid, gen=lease.gen,
            )
            if plan.fire_at_or_after("worker_kill_mid_lease", unit):
                os._exit(1)  # lease held, nothing streamed: peers must reclaim
            if plan.fire_at_or_after("slow_worker_reclaim", unit):
                # Outlive the TTL mid-hold, then produce anyway: the
                # double-production the learner's dedup must suppress.
                time.sleep(float(
                    os.environ.get("TRLX_TPU_SLOW_WORKER_SECONDS", "")
                    or 3.0 * knobs["lease_ttl"]
                ))
            # Weight eligibility for this unit (same gate as single-worker).
            need = max(0, unit - S)
            latest = subscriber.latest()
            leaves = None
            if latest is None or int(latest["ordinal"]) < need:
                heartbeat.beat(step=unit, phase="fleet:wait_weights")
                got = subscriber.fetch(
                    need,
                    deadline=knobs["broadcast_deadline"],
                    abort_check=aborted,
                    heartbeat=heartbeat,
                )
                if got is None:
                    break  # coordinated shutdown while waiting
                latest, leaves = got
            elif int(latest["ordinal"]) != current_ordinal:
                leaves = subscriber.try_load(latest)
                if leaves is None and (current_ordinal < need or snapshot is None):
                    # Torn pointer and the held version is ineligible: spin
                    # until the next intact ordinal (lease stays renewed via
                    # the next loop's claim adoption).
                    ledger.renew(lease)
                    time.sleep(0.05)
                    continue
            if leaves is not None:
                snapshot = fleet_snapshot(trainer, leaves, latest["version"])
                current_ordinal = int(latest["ordinal"])
                if "kl_coef" in latest and getattr(trainer, "kl_ctl", None) is not None:
                    trainer.kl_ctl.value = float(latest["kl_coef"])
                _event(
                    paths, ROLE_ROLLOUT, "weights_fetched",
                    ordinal=current_ordinal, version=snapshot["version"],
                    unit=unit, worker=wid,
                )

            # The unit's prompt shard: every worker derives the same
            # deterministic chunk schedule, so a reclaimed unit reproduces
            # the dead owner's exact prompts.
            orch.seek_chunks(unit * cpu)
            store = PPORolloutStorage(trainer.pad_token_id, record_staleness=True)
            renew_state = {"last": time.monotonic(), "owned": True}

            def produce_stop():
                heartbeat.beat(step=unit, phase="fleet:produce")
                now = time.monotonic()
                if renew_state["owned"] and now - renew_state["last"] >= max(
                    0.2, knobs["lease_ttl"] / 3.0
                ):
                    renew_state["last"] = now
                    if ledger.renew(lease) is None:
                        # A peer reclaimed us mid-produce. Keep producing —
                        # the intake dedupes, and aborting would strand a
                        # dispatched phase — but say so once.
                        renew_state["owned"] = False
                        _event(
                            paths, ROLE_ROLLOUT, "lease_lost",
                            unit=unit, worker=wid, gen=lease.gen,
                        )
                return aborted()

            info = orch.make_experience(
                n_roll,
                iter_count=snapshot["version"],
                store=store,
                snapshot=snapshot,
                staleness=0,  # realized staleness stamped at consume time
                stop=produce_stop,
                weight_poll=None,
            )
            del info  # in-flight spans are a single-worker engine contract
            if aborted():
                break  # phase cut short; drop the partial store
            heartbeat.beat(step=unit, phase="fleet:stream")
            seq = writer.append(
                store.columns(), weight_version=snapshot["version"], unit=unit
            )
            kept = ledger.complete(lease)
            produced += 1
            _event(
                paths, ROLE_ROLLOUT, "episode_streamed",
                unit=unit, seq=seq, version=snapshot["version"], n=len(store),
                worker=wid, lease_kept=bool(kept),
            )
            lease = None
        _event(
            paths, ROLE_ROLLOUT, "worker_exit",
            reason=reason, worker=wid, produced=produced,
        )
        if reason == "left":
            _event(paths, ROLE_ROLLOUT, "worker_left", worker=wid, produced=produced)
    finally:
        if lease is not None:
            ledger.release(lease)
        registry.leave(wid)
        heartbeat.stop()
        if getattr(trainer, "heartbeat", None) is not None:
            trainer.heartbeat.stop()


# ----------------------------------------------------------- learner feed


class FleetLearnerFeed:
    """The learner's store source: one consumed stream batch per call.

    Drives the publish-before-advance schedule, stamps realized staleness,
    and owns the degradation ladder. In COLOCATED mode (fleet armed, no
    role) it also runs the worker inline at each boundary — both roles in
    one process, episodes still crossing the real npz transports, which is
    the bitwise staleness-0 parity configuration."""

    def __init__(self, trainer, orch=None):
        self.trainer = trainer
        self.orch = orch
        t = trainer.config.train
        self.role = trainer.fleet_role
        self.max_staleness = trainer.max_staleness
        self.knobs = role_timeouts(t)
        self.elastic = bool(getattr(trainer.config.method, "fleet_elastic", False))
        self.paths = (
            fleet_paths(t).ensure_elastic() if self.elastic else fleet_paths(t).ensure()
        )
        # Elastic: exactly-once unit intake across N per-worker indexes
        # (reclaim duplicates counted + suppressed); else the PR 16
        # single-stream sequential reader. Same wait/queued_from/load shape.
        self.reader = (
            ElasticStreamReader(self.paths) if self.elastic else EpisodeStreamReader(self.paths)
        )
        self._registry = WorkerRegistry(self.paths.workers_dir) if self.elastic else None
        self._ledger = (
            LeaseLedger(self.paths.leases_dir, ttl=self.knobs["lease_ttl"])
            if self.elastic
            else None
        )
        self.publisher = WeightPublisher(self.paths, fault_plan=trainer.fault_plan)
        # version -> publish ordinal, for realized-staleness stamping
        # (resume-aware: rebuilt from the log, injected entries included —
        # they consumed an ordinal even though no snapshot landed).
        self._version_ordinal = {
            int(r["version"]): int(r["ordinal"]) for r in read_jsonl_or_empty(self.paths.broadcast_log)
        }
        self.consumed = _read_cursor(self.paths)
        # Elastic resume: recover the per-stream consume marks alongside the
        # unit cursor (absent/torn cursors leave it empty — marks are
        # forensic, the unit cursor is the authority).
        self._stream_marks = {}
        if self.elastic:
            import json

            try:
                with open(self.paths.cursor, "r") as f:
                    marks = json.load(f).get("streams") or {}
                self._stream_marks = {str(k): int(v) for k, v in marks.items()}
            except (OSError, ValueError, TypeError, AttributeError):
                pass
        self.state = "ok"
        self.triage = ""
        self._abort_written = False
        self._t0 = time.monotonic()
        self.heartbeat = Heartbeat(
            self.paths.heartbeats_dir, self.knobs["heartbeat_interval"], process_index=LEARNER_HOST
        )
        self.heartbeat.start()
        # Colocated inline worker state.
        self._writer = EpisodeStreamWriter(self.paths, fault_plan=trainer.fault_plan) if self.role == ROLE_COLOCATED else None
        self._subscriber = WeightSubscriber(self.paths) if self.role == ROLE_COLOCATED else None
        self._colo_ordinal = -1
        self._colo_snapshot = None
        if self.elastic and self._writer is not None:
            # Colocated elastic: the inline producer IS worker 0 — it
            # registers, claims leases, and tags units like any peer, so
            # the fast parity tests drive the whole elastic machinery.
            self._registry.register(0)
        # Token-granularity staleness watch (in-flight weight updates): the
        # detector rides the trainer's health monitor when one is armed —
        # its state joins the health/* gauges and a CRIT escalates through
        # the shared incident hook.
        self._mixed_detector = None
        monitor = getattr(trainer, "_health", None)
        if monitor is not None:
            from trlx_tpu.observability.health import MixedVersionDetector

            self._mixed_detector = monitor.register_detector(MixedVersionDetector())
        _event(self.paths, self.role, "learner_start", consumed=self.consumed)
        self._export(staleness=0.0)

    # ------------------------------------------------------------- publish

    def _publish(self):
        tr = self.trainer
        version = int(tr.iter_count)
        # The adaptive KL coefficient travels WITH the weights: rollout
        # rewards are kl_coef-shaped, so a worker on version-n params must
        # also hold version-n's coefficient (post_epoch flushed the pending
        # KL updates just before calling consume_done).
        meta = {}
        kl_ctl = getattr(tr, "kl_ctl", None)
        if kl_ctl is not None:
            meta["kl_coef"] = float(kl_ctl.value)
        ordinal = self.publisher.publish(tr.state.params, version=version, meta=meta)
        self._version_ordinal[version] = ordinal
        if obs_numerics.enabled():
            with tr._dispatch_lock:
                obs_numerics.record_weight_quant(tr.state.params, version=version)
        _event(self.paths, self.role, "weights_published", ordinal=ordinal, version=version)
        return ordinal

    def bootstrap(self) -> PPORolloutStorage:
        """Iteration-0 fill: publish v0, then consume the first batch (the
        colocated inline worker produces it; a disaggregated worker's gate
        opens the moment the v0 pointer lands)."""
        self._publish()
        self.heartbeat.beat(step=self.trainer.iter_count, phase="fleet:bootstrap")
        return self.next_store()

    def consume_done(self):
        """One train iteration fully consumed: publish the post-train
        weights. Publish-BEFORE-advance is the ordering the staleness gate's
        visibility argument rests on (the cursor only moves in
        ``next_store`` → ``_consume``, after this)."""
        self._publish()

    # ------------------------------------------------------------- consume

    def next_store(self) -> PPORolloutStorage:
        if self.state == "degraded":
            return self._drain_one()
        if self._writer is not None:
            self._inline_produce()
        while True:
            self.heartbeat.beat(step=self.trainer.iter_count, phase="fleet:wait_episode")
            try:
                rec = self.reader.wait(
                    self.consumed,
                    timeout=self.knobs["episode_timeout"],
                    retries=self.knobs["stream_retries"],
                    backoff=self.knobs["stream_backoff"],
                )
            except EpisodeStreamTimeout:
                verdict = self._triage_rollout()
                if verdict in ("alive", "starting"):
                    # Slow but live (or still compiling): keep waiting — a
                    # straggler is not a fault.
                    _event(self.paths, self.role, "stream_slow", seq=self.consumed, triage=verdict)
                    continue
                self._enter_degraded(verdict)
                return self._drain_one()
            return self._consume(rec)

    def _consume(self, rec: dict) -> PPORolloutStorage:
        seq = int(rec["seq"])
        # Elastic records advance the cursor by WORK UNIT (the per-worker
        # seq only orders one stream); the single-worker stream's seq IS
        # its unit.
        unit = int(rec.get("unit", rec["seq"]))
        worker = int(rec.get("worker", 0))
        version = int(rec["weight_version"])
        latest_ordinal = self.publisher.next_ordinal - 1
        v_ordinal = self._version_ordinal.get(version)
        if v_ordinal is None:
            # Lineage violation: an episode tagged with a version this
            # learner never published. Surfaced loudly — the drills assert
            # the event log has none of these.
            _event(self.paths, self.role, "unknown_version", seq=seq, version=version)
            v_ordinal = latest_ordinal
        staleness = max(0, latest_ordinal - v_ordinal)
        # Token granularity (in-flight weight updates): a batch whose
        # episodes straddle version switches carries a span aggregate. The
        # cap gates on the FRESHEST span — those tokens are the batch's
        # claim to being on-policy — while the older-token mix feeds the
        # fleet/mixed_version_tokens gauge and its health detector instead
        # of tripping the cap (some mix is the point of mid-decode pushes).
        spans = rec.get("version_spans")
        mixed_tokens = 0
        total_tokens = 0
        if spans:
            span_stal = []
            for v, k in spans:
                vo = self._version_ordinal.get(int(v))
                if vo is None:
                    _event(
                        self.paths, self.role, "unknown_version",
                        seq=seq, version=int(v),
                    )
                    vo = latest_ordinal
                span_stal.append((max(0, latest_ordinal - vo), int(k)))
            freshest = min(s for s, _ in span_stal)
            staleness = freshest
            mixed_tokens = sum(k for s, k in span_stal if s > freshest)
            total_tokens = sum(k for _, k in span_stal)
        if staleness > self.max_staleness:
            self._enter_degraded(self.triage or "staleness_cap")
            raise FleetDegradedExit(
                "staleness_cap",
                triage=self.triage,
                detail=f"seq={seq} staleness={staleness} > cap={self.max_staleness}",
            )
        if self._mixed_detector is not None and total_tokens:
            self._mixed_detector.observe(
                {"mixed_tokens": mixed_tokens, "total_tokens": total_tokens}
            )
        cols = dict(self.reader.load(rec))
        n = int(rec.get("n", 0))
        cols["staleness"] = np.full((n, 1), float(staleness), dtype=np.float32)
        store = PPORolloutStorage(self.trainer.pad_token_id, record_staleness=True)
        store.push_batch(cols)
        self.consumed = unit + 1
        cursor_payload = {
            "consumed": self.consumed, "ordinal": latest_ordinal, "t": time.time(),
        }
        if self.elastic:
            # Per-stream consume marks: which seq of each worker's index the
            # chosen records have reached — the resume forensics for
            # interleaved multi-stream cursors (consumed alone is the
            # authority; units are strictly ordered).
            self._stream_marks[str(worker)] = seq + 1
            cursor_payload["streams"] = dict(self._stream_marks)
        atomic_write_json(self.paths.cursor, cursor_payload)
        _event(
            self.paths, self.role, "episode_consumed",
            seq=seq, version=version, staleness=staleness, n=n, state=self.state,
            **({"unit": unit, "worker": worker} if self.elastic else {}),
            **({"mixed_version_tokens": mixed_tokens} if spans else {}),
        )
        self._export(
            staleness=float(staleness),
            version=version,
            mixed_tokens=float(mixed_tokens) if spans else None,
            worker=worker if self.elastic else None,
        )
        return store

    # ---------------------------------------------------------- colocated

    def _inline_produce(self):
        """Colocated mode: run the worker's loop body inline until the gate
        closes — same transports, same schedule, one process. With
        ``method.fleet_elastic`` the inline producer is WORKER 0: it claims
        each unit's lease, seeks the unit's prompt shard, and tags its
        records — the fast (in-process) path through the whole elastic
        machinery, which the parity tests pin against the non-elastic
        colocated run bitwise."""
        tr = self.trainer
        cpu = (
            self.orch.chunks_per_unit(tr.config.method.num_rollouts)
            if self.elastic
            else 0
        )
        while staleness_gate_open(self._writer.next_seq, self.consumed, self.max_staleness):
            seq = self._writer.next_seq
            lease = None
            if self.elastic:
                lease = self._ledger.try_claim(seq, 0)
                if lease is None:
                    # Unit already done (produced before a learner restart):
                    # nothing to produce until consuming reopens the gate.
                    break
                _event(
                    self.paths, self.role,
                    "lease_reclaimed" if lease.gen > 0 else "lease_claimed",
                    unit=seq, worker=0, gen=lease.gen,
                )
                self.orch.seek_chunks(seq * cpu)
            latest = self._subscriber.latest()
            if latest is None or int(latest["ordinal"]) < max(0, seq - self.max_staleness):
                raise RuntimeError(
                    "colocated fleet invariant broken: gate open but no "
                    "eligible weight snapshot published"
                )
            if int(latest["ordinal"]) != self._colo_ordinal:
                leaves = self._subscriber.load(latest)
                self._colo_snapshot = fleet_snapshot(tr, leaves, latest["version"])
                self._colo_ordinal = int(latest["ordinal"])
            store = PPORolloutStorage(tr.pad_token_id, record_staleness=True)
            info = self.orch.make_experience(
                tr.config.method.num_rollouts,
                iter_count=self._colo_snapshot["version"],
                store=store,
                snapshot=self._colo_snapshot,
                staleness=0,
                stop=None,
            )
            # Same span gating as the disaggregated worker. Colocated, no
            # publish can land mid-phase (one process, publish only at the
            # boundary) — so with the knob on every record carries exactly
            # one span, which the acceptance test pins down.
            inflight = bool(
                getattr(tr.config.method, "fleet_inflight_weights", False)
            ) and bool(getattr(tr, "rollout_engine_enabled", False))
            self._writer.append(
                store.columns(),
                weight_version=self._colo_snapshot["version"],
                version_spans=(
                    (info or {}).get("version_spans")
                    if inflight and isinstance(info, dict)
                    else None
                ),
                unit=seq if self.elastic else None,
            )
            if lease is not None:
                self._ledger.complete(lease)
            _event(
                self.paths, self.role, "episode_streamed",
                seq=seq, version=self._colo_snapshot["version"], n=len(store),
                **({"unit": seq, "worker": 0} if self.elastic else {}),
            )

    # --------------------------------------------------------- degradation

    def _classify_heartbeat(self, rec) -> str:
        """dead / stalled / alive / starting from one heartbeat record —
        the same written_t-vs-progress_t distinction for every role."""
        timeout = self.knobs["heartbeat_timeout"]
        now = time.time()
        if rec is None:
            grace = max(120.0, 10.0 * timeout)
            return "starting" if time.monotonic() - self._t0 < grace else "dead"
        if now - float(rec.get("written_t", 0.0)) > timeout:
            return "dead"
        if now - float(rec.get("progress_t", 0.0)) > timeout:
            return "stalled"
        return "alive"

    def _triage_workers(self) -> dict:
        """Per-worker triage (elastic only): worker id -> {state,
        heartbeat_age, leases_held, incarnation}. A worker that wrote a
        clean ``left`` record is 'left' regardless of heartbeat age — a
        deregistered exit is not a fault. Heartbeats live at process index
        ROLLOUT_HOST + worker id."""
        recs = read_heartbeats(self.paths.heartbeats_dir)
        now = time.time()
        workers = {}
        for wid, wrec in sorted(self._registry.workers().items()):
            hb = recs.get(ROLLOUT_HOST + wid)
            if wrec.get("status") == "left":
                state = "left"
            else:
                state = self._classify_heartbeat(hb)
            workers[wid] = {
                "state": state,
                "heartbeat_age": (
                    round(now - float(hb.get("written_t", 0.0)), 3) if hb else None
                ),
                "leases_held": len(self._ledger.held_by(wid)),
                "incarnation": int(wrec.get("incarnation", 0)),
            }
        return workers

    def _triage_rollout(self) -> str:
        """Classify the rollout side from its fleet heartbeat(s): 'dead'
        (written_t stale — process gone), 'stalled' (file fresh, progress_t
        frozen — thread alive, work wedged), 'alive' (progressing), or
        'starting' (no heartbeat yet, within the startup grace).

        Elastic aggregate across the registry: ANY progressing worker keeps
        the fleet alive (a dead peer's units get reclaimed — not a fault),
        any still-compiling worker keeps it starting, a wedged-but-present
        worker reads stalled, and only an EMPTY set of live workers is dead
        — which degrades gracefully per the PR 16 contract."""
        recs = read_heartbeats(self.paths.heartbeats_dir)
        if not self.elastic:
            return self._classify_heartbeat(recs.get(ROLLOUT_HOST))
        states = [
            w["state"] for w in self._triage_workers().values() if w["state"] != "left"
        ]
        if any(s == "alive" for s in states):
            return "alive"
        if any(s == "starting" for s in states):
            return "starting"
        if any(s == "stalled" for s in states):
            return "stalled"
        if states:
            return "dead"
        # Empty registry: nobody ever joined (startup grace) or everyone
        # left cleanly and no one remains to produce.
        grace = max(120.0, 10.0 * self.knobs["heartbeat_timeout"])
        return "starting" if time.monotonic() - self._t0 < grace else "dead"

    def _enter_degraded(self, triage: str):
        if self.state == "degraded":
            return
        self.state = "degraded"
        self.triage = triage
        # Flip the health surface FIRST: every scrape during the drain (and
        # the trainer's subsequent checkpoint) sees fleet/degraded.
        self._export(staleness=None)
        _event(
            self.paths, self.role, "degraded",
            triage=triage, consumed=self.consumed,
            queued=len(self.reader.queued_from(self.consumed)),
        )

    def _drain_one(self) -> PPORolloutStorage:
        """Degraded: hand over the next queued in-flight batch (tagged with
        its now-elevated staleness), or raise when the stream is dry."""
        queued = self.reader.queued_from(self.consumed)
        if not queued:
            raise FleetDegradedExit("stream_dry", triage=self.triage)
        _event(
            self.paths, self.role, "drain",
            seq=int(queued[0]["seq"]), remaining=len(queued), triage=self.triage,
        )
        return self._consume(queued[0])

    # ------------------------------------------------------------ teardown

    def shutdown(self, reason: str = "complete"):
        """Learner-side teardown. Writes ``abort.json`` — the coordinated
        shutdown signal the worker polls — EXCEPT on preemption: a
        preempted learner resumes into the same fleet_dir, and the worker
        (live the whole time) must keep serving it."""
        if reason != "preempted" and not self._abort_written:
            atomic_write_json(
                self.paths.abort,
                {"reason": reason, "triage": self.triage, "consumed": self.consumed, "t": time.time()},
            )
            self._abort_written = True
        _event(self.paths, self.role, "learner_exit", reason=reason, consumed=self.consumed)
        self.heartbeat.stop()

    # --------------------------------------------------------- observability

    def _export(self, staleness=None, version=None, mixed_tokens=None, worker=None):
        exporter = getattr(self.trainer, "_metrics_exporter", None)
        payload = {
            "state": self.state,
            "role": self.role,
            "triage": self.triage,
            "consumed": self.consumed,
            "published": self.publisher.next_ordinal,
            "max_staleness": self.max_staleness,
        }
        if exporter is None:
            return
        gauges = {"fleet/degraded": 1.0 if self.state == "degraded" else 0.0}
        if staleness is not None:
            gauges["fleet/staleness"] = float(staleness)
        if version is not None:
            gauges["fleet/weight_version"] = float(version)
        if mixed_tokens is not None:
            # Tokens in the last consumed batch NOT produced by its freshest
            # weight version — the in-flight update mix the detector watches.
            gauges["fleet/mixed_version_tokens"] = float(mixed_tokens)
        fleet_payload = {"disaggregated": payload}
        if self.elastic:
            workers = self._triage_workers()
            gauges["fleet/episodes_deduped_total"] = float(self.reader.duplicates())
            gauges["fleet/units_reclaimed_total"] = float(
                len(self._ledger.reclaimed_units())
            )
            gauges["fleet/workers_active"] = float(
                sum(1 for w in workers.values() if w["state"] in ("alive", "starting"))
            )
            # Every fleet/* per-consume gauge carries the producing worker as
            # a label; the per-worker liveness trio is labeled by triaged id.
            if worker is not None:
                labels = {"worker": str(int(worker))}
                if staleness is not None:
                    exporter.set_gauge("fleet/staleness", float(staleness), labels)
                if version is not None:
                    exporter.set_gauge("fleet/weight_version", float(version), labels)
            state_code = {"alive": 0, "starting": 1, "stalled": 2, "dead": 3, "left": 4}
            for wid, w in workers.items():
                labels = {"worker": str(wid)}
                if w["heartbeat_age"] is not None:
                    exporter.set_gauge(
                        "fleet/worker_heartbeat_age", float(w["heartbeat_age"]), labels
                    )
                exporter.set_gauge(
                    "fleet/worker_leases_held", float(w["leases_held"]), labels
                )
                exporter.set_gauge(
                    "fleet/worker_state", float(state_code.get(w["state"], 3)), labels
                )
            fleet_payload["workers"] = {str(k): v for k, v in workers.items()}
        exporter.update(gauges)
        exporter.set_fleet(fleet_payload)
