"""Fault-tolerant episode streaming: the rollout→learner transport.

One stream batch = one finished ``make_experience`` phase, shipped as the
store's raw column dict (``PPORolloutStorage.columns()``) in a single
``.npz`` written atomically (tmp + ``os.replace``), plus one line in the
append-only ``stream.jsonl`` index::

    {"seq": 3, "file": "batch_000003.npz", "n": 64, "weight_version": 12, "t": ...}

The npz round-trip is bitwise-lossless for every column dtype (int32
tokens/masks, float32 stats), which is what lets the staleness-0
disaggregated run re-prove the PR 5 serial-parity contract THROUGH the
stream rather than around it (tests/test_fleet_disagg.py).

Reader semantics: consume strictly in ``seq`` order (the learner's train
schedule is deterministic given the stream order); each wait is wrapped in
``resilience.retry.call_with_retries`` — per-episode timeout, bounded
retries, exponential backoff — so a transient filesystem hiccup is retried
and only a persistent stall escalates to the heartbeat triage in
runner.py. Torn index tails (a writer killed mid-line) are tolerated by
``utils.jsonl.read_jsonl``.

The ``episode_stream_stall@N`` fault fires HERE, in the writer: batch N's
append sleeps instead of writing while the worker's heartbeat thread keeps
beating — fresh ``written_t``, frozen ``progress_t`` — exactly the
signature the learner's triage must classify as STALLED (not DEAD).
"""

import os
import time
from typing import Dict, Optional

import numpy as np

from trlx_tpu.resilience.retry import call_with_retries
from trlx_tpu.utils.jsonl import append_record

from .topology import FleetPaths, read_jsonl_or_empty


class EpisodeStreamTimeout(RuntimeError):
    """A stream wait exhausted its per-attempt timeout (retryable; the
    caller's retry wrapper decides when it becomes a triage event)."""


def _atomic_savez(path: str, columns: Dict[str, np.ndarray]):
    # np.savez appends ".npz" to names that lack it, so the tmp name must
    # already end in .npz for os.replace to find what savez wrote.
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    np.savez(tmp, **{k: np.asarray(v) for k, v in columns.items()})
    os.replace(tmp, path)


def load_columns(path: str) -> Dict[str, np.ndarray]:
    with np.load(path, allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


class EpisodeStreamWriter:
    """Rollout-side appender. Resume-aware: a restarted worker continues
    ``seq`` numbering from the existing index instead of clobbering it."""

    def __init__(self, paths: FleetPaths, fault_plan=None):
        self.paths = paths
        self.fault_plan = fault_plan
        records = read_jsonl_or_empty(paths.stream_index)
        self.next_seq = 1 + max((int(r["seq"]) for r in records), default=-1)

    def append(
        self,
        columns: Dict[str, np.ndarray],
        weight_version: int,
        version_spans: Optional[list] = None,
    ) -> int:
        """Write one episode batch atomically and index it. Returns seq.

        ``version_spans`` is the batch-aggregate per-token weight-version
        provenance — ``[[version, n_tokens], ...]`` summed over the batch's
        episodes (engine in-flight updates; Episode.version_spans). Omitted
        (None) for phase-boundary batches, where ``weight_version`` alone
        says everything: the index record stays byte-identical to PR 16's
        on that path."""
        seq = self.next_seq
        if self.fault_plan is not None and self.fault_plan.fire("episode_stream_stall", seq):
            # Stall INSTEAD of writing: the batch never lands, but the
            # worker process (and its heartbeat thread) stays alive.
            time.sleep(float(os.environ.get("TRLX_TPU_STREAM_STALL_SECONDS", "3600")))
        path = self.paths.episode_file(seq)
        _atomic_savez(path, columns)
        n = int(next(iter(columns.values())).shape[0]) if columns else 0
        rec = {
            "seq": seq,
            "file": os.path.basename(path),
            "n": n,
            "weight_version": int(weight_version),
            "t": time.time(),
        }
        if version_spans:
            rec["version_spans"] = [[int(v), int(k)] for v, k in version_spans]
        append_record(self.paths.stream_index, rec)
        self.next_seq = seq + 1
        return seq


class EpisodeStreamReader:
    """Learner-side sequential reader with timeout/retry/backoff waits."""

    def __init__(self, paths: FleetPaths):
        self.paths = paths

    def index(self) -> Dict[int, dict]:
        return {int(r["seq"]): r for r in read_jsonl_or_empty(self.paths.stream_index)}

    def poll(self, seq: int) -> Optional[dict]:
        return self.index().get(int(seq))

    def queued_from(self, seq: int) -> list:
        """Index records for every landed batch with seq >= the cursor — the
        degraded-drain worklist."""
        return [r for s, r in sorted(self.index().items()) if s >= int(seq)]

    def load(self, record: dict) -> Dict[str, np.ndarray]:
        return load_columns(os.path.join(self.paths.episodes_dir, record["file"]))

    def wait(
        self,
        seq: int,
        *,
        timeout: float,
        retries: int,
        backoff: float,
        poll_interval: float = 0.05,
    ) -> dict:
        """Block until batch ``seq`` lands in the index.

        Each ATTEMPT polls for up to ``timeout`` seconds then raises
        EpisodeStreamTimeout; call_with_retries re-attempts with doubling
        backoff. Exhaustion re-raises — the runner's triage takes over."""

        def attempt():
            deadline = time.monotonic() + max(0.1, float(timeout))
            while time.monotonic() < deadline:
                rec = self.poll(seq)
                if rec is not None:
                    return rec
                time.sleep(poll_interval)
            raise EpisodeStreamTimeout(
                f"episode batch seq={seq} did not land within {timeout}s "
                f"(index {self.paths.stream_index})"
            )

        return call_with_retries(
            attempt,
            retries=max(0, int(retries)),
            backoff=max(0.0, float(backoff)),
            timeout=0.0,  # the attempt bounds itself; no watchdog thread
            description=f"episode stream wait seq={seq}",
        )
