"""Fault-tolerant episode streaming: the rollout→learner transport.

One stream batch = one finished ``make_experience`` phase, shipped as the
store's raw column dict (``PPORolloutStorage.columns()``) in a single
``.npz`` written atomically (tmp + ``os.replace``), plus one line in the
append-only ``stream.jsonl`` index::

    {"seq": 3, "file": "batch_000003.npz", "n": 64, "weight_version": 12, "t": ...}

The npz round-trip is bitwise-lossless for every column dtype (int32
tokens/masks, float32 stats), which is what lets the staleness-0
disaggregated run re-prove the PR 5 serial-parity contract THROUGH the
stream rather than around it (tests/test_fleet_disagg.py).

Reader semantics: consume strictly in ``seq`` order (the learner's train
schedule is deterministic given the stream order); each wait is wrapped in
``resilience.retry.call_with_retries`` — per-episode timeout, bounded
retries, exponential backoff — so a transient filesystem hiccup is retried
and only a persistent stall escalates to the heartbeat triage in
runner.py. Torn index tails (a writer killed mid-line) are tolerated by
``utils.jsonl.read_jsonl``.

The ``episode_stream_stall@N`` fault fires HERE, in the writer: batch N's
append sleeps instead of writing while the worker's heartbeat thread keeps
beating — fresh ``written_t``, frozen ``progress_t`` — exactly the
signature the learner's triage must classify as STALLED (not DEAD).
"""

import os
import time
import zlib
from typing import Dict, List, Optional

import numpy as np

from trlx_tpu.resilience.retry import call_with_retries
from trlx_tpu.utils.jsonl import append_record

from .topology import FleetPaths, read_jsonl_or_empty


class EpisodeStreamTimeout(RuntimeError):
    """A stream wait exhausted its per-attempt timeout (retryable; the
    caller's retry wrapper decides when it becomes a triage event)."""


def episode_key(columns: Dict[str, np.ndarray]) -> str:
    """Content key for a streamed batch's PROMPT shard: crc32 over the
    query tokens+mask bytes. Two productions of the same work unit — the
    original owner's and a reclaimer's — decode the same deterministic
    prompt chunks, so they carry the SAME key even when a weight broadcast
    landed between them (responses differ, queries cannot). The elastic
    intake dedupes on (work_unit, episode_key); a key mismatch inside one
    unit means the prompt-shard schedule diverged between workers and is
    surfaced as a lineage violation, never consumed silently."""
    crc = 0
    for name in ("query_tensors", "query_mask"):
        arr = columns.get(name)
        if arr is not None:
            crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
    return f"{crc:08x}"


def _atomic_savez(path: str, columns: Dict[str, np.ndarray]):
    # np.savez appends ".npz" to names that lack it, so the tmp name must
    # already end in .npz for os.replace to find what savez wrote.
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    np.savez(tmp, **{k: np.asarray(v) for k, v in columns.items()})
    os.replace(tmp, path)


def load_columns(path: str) -> Dict[str, np.ndarray]:
    with np.load(path, allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


class EpisodeStreamWriter:
    """Rollout-side appender. Resume-aware: a restarted worker continues
    ``seq`` numbering from the existing index instead of clobbering it.

    Elastic fleets give every worker its OWN writer (``worker=k`` →
    ``stream.w<k>.jsonl`` + ``w<k>_``-prefixed npz names) so N producers
    never contend on an append; worker 0 (and the single-worker fleet)
    keeps the PR 16/17 file names byte-identically."""

    def __init__(self, paths: FleetPaths, fault_plan=None, worker: int = 0):
        self.paths = paths
        self.fault_plan = fault_plan
        self.worker = int(worker)
        self.index_path = paths.stream_index_for(self.worker)
        records = read_jsonl_or_empty(self.index_path)
        self.next_seq = 1 + max((int(r["seq"]) for r in records), default=-1)

    def append(
        self,
        columns: Dict[str, np.ndarray],
        weight_version: int,
        version_spans: Optional[list] = None,
        unit: Optional[int] = None,
    ) -> int:
        """Write one episode batch atomically and index it. Returns seq.

        ``version_spans`` is the batch-aggregate per-token weight-version
        provenance — ``[[version, n_tokens], ...]`` summed over the batch's
        episodes (engine in-flight updates; Episode.version_spans). Omitted
        (None) for phase-boundary batches, where ``weight_version`` alone
        says everything: the index record stays byte-identical to PR 16's
        on that path.

        ``unit`` (elastic fleet only) tags the record with the WORK UNIT it
        produces — the learner's exactly-once intake keys on it (plus the
        content ``episode_key``) across all per-worker indexes. None keeps
        the single-worker record shape."""
        seq = self.next_seq
        if self.fault_plan is not None and self.fault_plan.fire("episode_stream_stall", seq):
            # Stall INSTEAD of writing: the batch never lands, but the
            # worker process (and its heartbeat thread) stays alive.
            time.sleep(float(os.environ.get("TRLX_TPU_STREAM_STALL_SECONDS", "3600")))
        path = self.paths.episode_file(seq, worker=self.worker)
        _atomic_savez(path, columns)
        n = int(next(iter(columns.values())).shape[0]) if columns else 0
        rec = {
            "seq": seq,
            "file": os.path.basename(path),
            "n": n,
            "weight_version": int(weight_version),
            "t": time.time(),
        }
        if version_spans:
            rec["version_spans"] = [[int(v), int(k)] for v, k in version_spans]
        if unit is not None:
            rec["unit"] = int(unit)
            rec["worker"] = self.worker
            rec["episode_key"] = episode_key(columns)
        append_record(self.index_path, rec)
        self.next_seq = seq + 1
        return seq


class EpisodeStreamReader:
    """Learner-side sequential reader with timeout/retry/backoff waits."""

    def __init__(self, paths: FleetPaths):
        self.paths = paths

    def index(self) -> Dict[int, dict]:
        return {int(r["seq"]): r for r in read_jsonl_or_empty(self.paths.stream_index)}

    def poll(self, seq: int) -> Optional[dict]:
        return self.index().get(int(seq))

    def queued_from(self, seq: int) -> list:
        """Index records for every landed batch with seq >= the cursor — the
        degraded-drain worklist."""
        return [r for s, r in sorted(self.index().items()) if s >= int(seq)]

    def load(self, record: dict) -> Dict[str, np.ndarray]:
        return load_columns(os.path.join(self.paths.episodes_dir, record["file"]))

    def wait(
        self,
        seq: int,
        *,
        timeout: float,
        retries: int,
        backoff: float,
        poll_interval: float = 0.05,
    ) -> dict:
        """Block until batch ``seq`` lands in the index.

        Each ATTEMPT polls for up to ``timeout`` seconds then raises
        EpisodeStreamTimeout; call_with_retries re-attempts with doubling
        backoff. Exhaustion re-raises — the runner's triage takes over."""

        def attempt():
            deadline = time.monotonic() + max(0.1, float(timeout))
            while time.monotonic() < deadline:
                rec = self.poll(seq)
                if rec is not None:
                    return rec
                time.sleep(poll_interval)
            raise EpisodeStreamTimeout(
                f"episode batch seq={seq} did not land within {timeout}s "
                f"(index {self.paths.stream_index})"
            )

        return call_with_retries(
            attempt,
            retries=max(0, int(retries)),
            backoff=max(0.0, float(backoff)),
            timeout=0.0,  # the attempt bounds itself; no watchdog thread
            description=f"episode stream wait seq={seq}",
        )


class ElasticStreamReader:
    """Exactly-once learner intake over N per-worker stream indexes.

    The elastic learner consumes WORK UNITS in order (unit u = train
    iteration u; the train schedule stays deterministic no matter which
    worker produced which unit). Each scan re-globs ``stream*.jsonl`` —
    workers join mid-run — and merges every index into a per-unit record
    list. The CHOSEN record for a unit is the first to land (earliest index
    timestamp, worker id as the tiebreak); every other record for that unit
    is a duplicate from a lease reclaim racing its slow/dead original owner
    and is counted, never consumed — (work_unit, episode_key) dedup, since
    all of a unit's productions carry the prompt-shard content key. The
    same API shape as EpisodeStreamReader (wait/poll/queued_from/load), with
    units in place of seqs, so the learner feed drives either transport."""

    def __init__(self, paths: FleetPaths):
        self.paths = paths

    def indexes(self) -> Dict[int, List[dict]]:
        return {
            worker: read_jsonl_or_empty(path)
            for worker, path in sorted(self.paths.stream_indexes().items())
        }

    def by_unit(self) -> Dict[int, List[dict]]:
        """unit -> its records across all workers, landing order. Records
        without a ``unit`` field (a non-elastic writer sharing the dir)
        key on their seq — the N=1 degenerate case."""
        units: Dict[int, List[dict]] = {}
        for worker, records in self.indexes().items():
            for rec in records:
                rec = dict(rec)
                rec.setdefault("worker", worker)
                unit = int(rec.get("unit", rec["seq"]))
                rec["unit"] = unit
                units.setdefault(unit, []).append(rec)
        for recs in units.values():
            recs.sort(key=lambda r: (float(r.get("t", 0.0)), int(r["worker"])))
        return units

    def chosen(self) -> Dict[int, dict]:
        return {unit: recs[0] for unit, recs in self.by_unit().items()}

    def duplicates(self) -> int:
        """Total landed-but-not-chosen records — the monotone
        ``fleet/episodes_deduped_total`` counter (index files only append,
        so rescanning never decreases it)."""
        return sum(len(recs) - 1 for recs in self.by_unit().values())

    def max_unit(self) -> int:
        """Highest unit with any landed record, or -1 — the torn-cursor
        at-most-once fallback's scan (runner._read_cursor)."""
        return max(self.by_unit().keys(), default=-1)

    def poll(self, unit: int) -> Optional[dict]:
        return self.chosen().get(int(unit))

    def queued_from(self, unit: int) -> list:
        """Chosen records for every landed unit >= the cursor — the
        degraded-drain worklist (duplicates never drain twice)."""
        return [r for u, r in sorted(self.chosen().items()) if u >= int(unit)]

    def load(self, record: dict) -> Dict[str, np.ndarray]:
        return load_columns(os.path.join(self.paths.episodes_dir, record["file"]))

    def wait(
        self,
        unit: int,
        *,
        timeout: float,
        retries: int,
        backoff: float,
        poll_interval: float = 0.05,
    ) -> dict:
        """Block until ANY worker's record for ``unit`` lands (same
        timeout/retry/backoff contract as EpisodeStreamReader.wait)."""

        def attempt():
            deadline = time.monotonic() + max(0.1, float(timeout))
            while time.monotonic() < deadline:
                rec = self.poll(unit)
                if rec is not None:
                    return rec
                time.sleep(poll_interval)
            raise EpisodeStreamTimeout(
                f"episode work unit={unit} did not land in any stream index "
                f"within {timeout}s (root {self.paths.root})"
            )

        return call_with_retries(
            attempt,
            retries=max(0, int(retries)),
            backoff=max(0.0, float(backoff)),
            timeout=0.0,
            description=f"episode stream wait unit={unit}",
        )
