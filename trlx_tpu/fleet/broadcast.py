"""Versioned weight broadcast: the learner→rollout transport.

The learner publishes a flat-leaf snapshot of its params at every consume
boundary (and once at bootstrap)::

    weights_<ordinal>.npz          # leaves by position: leaf_000000, ...
    broadcast.jsonl  += {"ordinal": k, "version": v, "file": ..., "status": "published", "t": ...}
    weights_latest.json            # atomic pointer {ordinal, version, file}

``ordinal`` is the dense publish counter (resume-safe: a restarted learner
continues from the log length); ``version`` is the training iter_count the
snapshot was taken at — the tag every episode carries (PR 9 lineage) and
the key the per-version quant telemetry buckets by (PR 15). Leaves are
matched POSITIONALLY: both worlds build the identical model from the same
config/seed, so ``tree_flatten`` yields the same leaf order — a size
mismatch is a hard error, never a silent misload. Each leaf is stored as
its RAW BYTES (a uint8 view), not a typed array: the ``.npy`` format
round-trips builtin dtypes only, and params are frequently bfloat16
(an ml_dtypes extension type). Bytes in, bytes out — the transport is
bitwise by construction, which the staleness-0 parity test leans on.

The rollout side blocks for the version its staleness gate requires under
``collective_guard("fleet/weight_broadcast", deadline=...)`` — the fleet
has no raw collectives, but a worker starved of weights is semantically a
peer stuck in a broadcast, so it gets the same treatment: heartbeat phase
tagging, stall report, and a deadline abort with exit code 117
(``EXIT_COLLECTIVE_TIMEOUT``). The ``broadcast_timeout@N`` fault fires in
the publisher: ordinal N's snapshot is SKIPPED (logged as
``status="injected_timeout"``), so a staleness-0 worker waiting for
exactly that ordinal outlives its deadline.
"""

import json
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from trlx_tpu.resilience.checkpoint import atomic_write_json
from trlx_tpu.resilience.distributed import collective_guard
from trlx_tpu.utils.jsonl import append_record

from .topology import FleetPaths, read_jsonl_or_empty

BROADCAST_GUARD = "fleet/weight_broadcast"


def _leaf_key(i: int) -> str:
    return f"leaf_{i:06d}"


class WeightPublisher:
    """Learner-side publisher. One ``publish`` per consume boundary."""

    def __init__(self, paths: FleetPaths, fault_plan=None):
        self.paths = paths
        self.fault_plan = fault_plan
        records = read_jsonl_or_empty(paths.broadcast_log)
        # Dense resume: injected-timeout records still consumed an ordinal.
        self.next_ordinal = 1 + max((int(r["ordinal"]) for r in records), default=-1)

    def publish(self, params, version: int, meta: Optional[dict] = None) -> int:
        """Snapshot ``params`` (a device pytree) to disk and advance the
        latest pointer. Returns the ordinal it landed at.

        ``meta`` rides in the log record AND the latest pointer: small host
        scalars the rollout side must track in lockstep with the weights —
        today the adaptive KL coefficient (``kl_coef``), which shapes
        rollout rewards exactly like the params shape rollout tokens. A
        worker holding version-n params but a stale KL coefficient would
        silently break the staleness-0 parity contract."""
        import jax

        ordinal = self.next_ordinal
        self.next_ordinal = ordinal + 1
        if self.fault_plan is not None and self.fault_plan.fire("broadcast_timeout", ordinal):
            # Skip the snapshot entirely: the log records the injection (so
            # lineage checks can filter status=="published") but no file
            # lands and the latest pointer stays put.
            append_record(
                self.paths.broadcast_log,
                {"ordinal": ordinal, "version": int(version), "file": None, "status": "injected_timeout", "t": time.time()},
            )
            return ordinal
        leaves = jax.tree_util.tree_leaves(params)
        host = jax.device_get(leaves)
        path = self.paths.weight_file(ordinal)
        tmp = f"{path}.tmp.{os.getpid()}.npz"
        views = {}
        for i, h in enumerate(host):
            a = np.ascontiguousarray(np.asarray(h)).reshape(-1)
            views[_leaf_key(i)] = a.view(np.uint8)
        np.savez(tmp, **views)
        os.replace(tmp, path)
        rec = {
            "ordinal": ordinal,
            "version": int(version),
            "file": os.path.basename(path),
            "n_leaves": len(host),
            "status": "published",
            "t": time.time(),
        }
        pointer = {"ordinal": ordinal, "version": int(version), "file": rec["file"]}
        if meta:
            rec.update(meta)
            pointer.update(meta)
        append_record(self.paths.broadcast_log, rec)
        atomic_write_json(self.paths.latest_pointer, pointer)
        if self.fault_plan is not None and self.fault_plan.fire(
            "weight_push_torn", ordinal
        ):
            # Torn-push drill: the pointer ALREADY names this ordinal, but
            # the snapshot file it points at is truncated (publisher host
            # killed mid-write, full disk). Subscribers must reject the torn
            # load and keep decoding on the version they already hold.
            with open(path, "r+b") as f:
                f.truncate(max(1, os.path.getsize(path) // 2))
            append_record(
                self.paths.broadcast_log,
                {
                    "ordinal": ordinal,
                    "version": int(version),
                    "file": rec["file"],
                    "status": "injected_torn",
                    "t": time.time(),
                },
            )
        return ordinal

    def published(self) -> List[dict]:
        return [r for r in read_jsonl_or_empty(self.paths.broadcast_log) if r.get("status") == "published"]


class WeightSubscriber:
    """Rollout-side subscriber: poll the latest pointer, load host leaves."""

    def __init__(self, paths: FleetPaths):
        self.paths = paths

    def latest(self) -> Optional[dict]:
        """The latest pointer, or None. Torn-read tolerant."""
        try:
            with open(self.paths.latest_pointer, "r") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def load(self, record: dict) -> List[np.ndarray]:
        path = os.path.join(self.paths.weights_dir, record["file"])
        with np.load(path, allow_pickle=False) as z:
            return [z[k] for k in sorted(z.files)]

    def try_load(self, record: dict) -> Optional[List[np.ndarray]]:
        """``load`` that treats a torn/truncated snapshot (publisher host
        killed mid-write — the ``weight_push_torn`` drill) as not-there:
        returns None instead of raising, so an in-flight weight poller can
        keep decoding on the version it already holds and pick up the next
        intact ordinal."""
        import zipfile

        try:
            return self.load(record)
        except (OSError, ValueError, KeyError, EOFError, zipfile.BadZipFile):
            return None

    def fetch(
        self,
        min_ordinal: int,
        *,
        deadline: float,
        abort_check: Optional[Callable[[], bool]] = None,
        heartbeat=None,
        poll_interval: float = 0.05,
    ) -> Optional[Tuple[dict, List[np.ndarray]]]:
        """Block until a snapshot with ordinal >= ``min_ordinal`` is
        published, under the collective guard's deadline. Returns
        (pointer record, host leaves), or None if ``abort_check`` tripped
        first (coordinated shutdown, not a fault). Deadline exceeded =
        guard abort: exit EXIT_COLLECTIVE_TIMEOUT, never a hang."""
        with collective_guard(BROADCAST_GUARD, deadline=max(0.1, float(deadline))):
            while True:
                rec = self.latest()
                if rec is not None and int(rec["ordinal"]) >= int(min_ordinal):
                    # Torn-tolerant: a satisfying pointer whose snapshot file
                    # is truncated (weight_push_torn — publisher killed
                    # mid-write after the pointer flip) keeps us polling for
                    # the next intact ordinal instead of crashing; the guard
                    # deadline still bounds a publisher that never recovers.
                    leaves = self.try_load(rec)
                    if leaves is not None:
                        break
                if abort_check is not None and abort_check():
                    return None
                if heartbeat is not None:
                    heartbeat.beat(phase=f"collective:{BROADCAST_GUARD}")
                time.sleep(poll_interval)
        return rec, leaves


def put_leaves(template_params, host_leaves: List[np.ndarray]):
    """Map broadcast byte-leaves back onto a live param tree: positional
    unflatten against THIS world's treedef, each byte blob re-viewed with
    the reference leaf's dtype/shape and ``device_put`` with its sharding
    (so the worker's mesh layout — not the learner's — decides placement).
    Bitwise: no cast, no copy semantics beyond the host→device transfer."""
    import jax

    ref_with_path, treedef = jax.tree_util.tree_flatten_with_path(template_params)
    if len(ref_with_path) != len(host_leaves):
        raise ValueError(
            f"weight broadcast leaf-count mismatch: snapshot has "
            f"{len(host_leaves)} leaves, this world's param tree has "
            f"{len(ref_with_path)} — the jobs are not running the same model "
            "config."
        )
    put = []
    for raw, (key_path, ref) in zip(host_leaves, ref_with_path):
        dt = np.dtype(ref.dtype)
        raw = np.asarray(raw)
        if raw.nbytes != ref.size * dt.itemsize:
            # Name the first mismatched leaf BY PATH: a same-shape dtype
            # misconfig (f32 learner → bf16 rollout world) looks like a
            # byte-count skew on every leaf, and the path is what tells the
            # operator which config knob diverged.
            raise ValueError(
                f"weight broadcast leaf size mismatch at param leaf "
                f"{jax.tree_util.keystr(key_path)!r}: {raw.nbytes} bytes vs "
                f"expected {ref.size * dt.itemsize} for shape {ref.shape} "
                f"{dt} — the jobs are not running the same model config "
                "(dtype mismatch, e.g. an f32 learner streaming to a bf16 "
                "rollout world, shows up here as a per-leaf byte-count skew)."
            )
        host = raw.view(dt).reshape(ref.shape)
        put.append(jax.device_put(host, getattr(ref, "sharding", None)))
    return jax.tree_util.tree_unflatten(treedef, put)
